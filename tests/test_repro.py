"""Repro pipeline: bisection, minimization, option/C simplification
(reference pkg/repro) — hermetic via an injected Tester."""

import os
import stat
import tempfile

from syzkaller_tpu import repro
from syzkaller_tpu.ipc import ExecOpts
from syzkaller_tpu.prog import get_target
from syzkaller_tpu.prog.encoding import serialize
from syzkaller_tpu.report import Report

TARGET = get_target("linux", "amd64")

# the "bug": any program containing both mkdir and rmdir crashes
CULPRIT = """\
r0 = open(&0:0:0=".\\x00", 0x0, 0x0)
mkdir(&1:0:0="./dir\\x00", 0x1ff)
dup(r0)
rmdir(&2:0:0="./dir\\x00")
close(r0)
"""

INNOCENT = """\
getpid()
getuid()
"""


class FakeTester(repro.Tester):
    def __init__(self, c_crashes=True):
        self.runs = 0
        self.c_crashes = c_crashes

    @staticmethod
    def _is_crasher(p):
        names = [c.meta.call_name for c in p.calls]
        return "mkdir" in names and "rmdir" in names

    def test_progs(self, progs, opts, duration):
        self.runs += 1
        if any(self._is_crasher(p) for p in progs):
            return Report(title="KASAN: use-after-free in foo")
        return None

    def test_c_bin(self, bin_path, duration):
        if self.c_crashes:
            return Report(title="KASAN: use-after-free in foo")
        return None


def _log(progs):
    out = []
    for i, text in enumerate(progs):
        out.append(f"executing program {i % 2}:")
        out.append(text.strip())
        out.append("")
    return "\n".join(out)


def test_repro_single_last():
    # the last program is the crasher: single-prog extraction path
    log = _log([INNOCENT, INNOCENT, CULPRIT])
    tester = FakeTester()
    res = repro.run(log, TARGET, tester)
    assert res is not None
    assert res.title == "KASAN: use-after-free in foo"
    names = [c.meta.call_name for c in res.prog.calls]
    # minimized to just the crash-relevant calls
    assert "mkdir" in names and "rmdir" in names
    assert "dup" not in names and "close" not in names
    # fake tester crashes regardless of opts -> everything simplified away
    assert not res.opts.threaded and not res.opts.collide
    assert res.c_src is not None
    assert not res.c_opts.repeat


def test_repro_bisect():
    # crasher buried mid-log, last program innocent: bisection path
    progs = [INNOCENT] * 6 + [CULPRIT] + [INNOCENT] * 3
    tester = FakeTester()
    res = repro.run(_log(progs), TARGET, tester)
    assert res is not None
    names = [c.meta.call_name for c in res.prog.calls]
    assert "mkdir" in names and "rmdir" in names


MKDIR_ONLY = 'mkdir(&1:0:0="./dir\\x00", 0x1ff)\n'
RMDIR_ONLY = 'rmdir(&2:0:0="./dir\\x00")\n'


class SequenceTester(repro.Tester):
    """Crashes only when mkdir and rmdir appear in *different* programs —
    forces the sequence-fallback path (concatenation breaks the repro)."""

    def test_progs(self, progs, opts, duration):
        has_mk = [("mkdir" in [c.meta.call_name for c in p.calls])
                  for p in progs]
        has_rm = [("rmdir" in [c.meta.call_name for c in p.calls])
                  for p in progs]
        crosses = any(m and not r for m, r in zip(has_mk, has_rm)) and \
            any(r and not m for m, r in zip(has_mk, has_rm))
        return Report(title="sequence bug") if crosses else None

    def test_c_bin(self, bin_path, duration):
        return None


def test_repro_sequence_fallback():
    log = _log([INNOCENT, MKDIR_ONLY, INNOCENT, RMDIR_ONLY, INNOCENT])
    res = repro.run(log, TARGET, SequenceTester())
    assert res is not None
    assert res.prog is None  # no single-program reproducer exists
    assert len(res.progs) == 2  # ddmin kept exactly the crashing pair
    names = [{c.meta.call_name for c in p.calls} for p in res.progs]
    assert {"mkdir"} <= names[0] and {"rmdir"} <= names[1]


def test_repro_no_crash():
    tester = FakeTester()
    res = repro.run(_log([INNOCENT, INNOCENT]), TARGET, tester)
    assert res is None
    assert tester.runs > 0


def test_repro_no_c():
    tester = FakeTester(c_crashes=False)
    res = repro.run(_log([CULPRIT]), TARGET, tester)
    assert res is not None
    assert res.c_src is None


def test_repro_empty_log():
    assert repro.run("no programs here", TARGET, FakeTester()) is None


def test_vmtester_plumbing():
    """VMTester end-to-end over the local VM backend: a stand-in
    'python' binary prints a KASAN oops to the console; the monitor must
    recognize it and the pipeline must produce a reproducer."""
    from syzkaller_tpu.vm import LocalPool, VMConfig

    fake = tempfile.NamedTemporaryFile(
        "w", suffix=".sh", delete=False)
    fake.write("""#!/bin/sh
echo "BUG: KASAN: use-after-free in vfs_rmdir+0x100/0x200"
echo "Read of size 8 at addr ffff8800b92ca3b0 by task a.out/1234"
sleep 30
""")
    fake.close()
    os.chmod(fake.name, stat.S_IRWXU)
    try:
        from syzkaller_tpu.prog.encoding import deserialize

        pool = LocalPool(VMConfig(type="local", count=1))
        tester = repro.VMTester(pool, python=fake.name)
        rep = tester.test_progs([deserialize(TARGET, CULPRIT)],
                                ExecOpts(), duration=20.0)
        assert rep is not None
        assert rep.title == "KASAN: use-after-free Read in vfs_rmdir"
    finally:
        os.unlink(fake.name)
