"""Pseudo-syscall (syz_*) tests: stable ids, real-executor execution,
kmemleak parsing."""

import os

import pytest

from syzkaller_tpu.descriptions.compiler import PSEUDO_IDS, PSEUDO_NR_BASE
from syzkaller_tpu.prog import get_target
from syzkaller_tpu.prog.encoding import deserialize


@pytest.fixture(scope="module")
def target():
    return get_target("linux", "amd64")


def test_pseudo_ids_fixed(target):
    """Every syz_* variant's nr comes from the fixed registry (so the
    executor's switch stays in sync across description edits)."""
    for s in target.syscalls:
        if s.call_name.startswith("syz_"):
            assert s.call_name in PSEUDO_IDS, s.call_name
            assert s.nr == PSEUDO_NR_BASE + PSEUDO_IDS[s.call_name]


def test_descriptions_cover_pseudo_surface(target):
    names = {s.name for s in target.syscalls}
    for want in ["syz_open_dev$tty", "syz_open_pts", "syz_emit_ethernet",
                 "syz_extract_tcp_res", "syz_fuse_mount",
                 "syz_kvm_setup_cpu", "openat$kvm", "openat$ptmx",
                 "ioctl$KVM_CREATE_VM", "ioctl$KVM_RUN"]:
        assert want in names, want


def test_executor_runs_pts_chain(target, tmp_path):
    """openat$ptmx -> syz_open_pts through the real executor: the pts
    pseudo-call must succeed against the live /dev/ptmx."""
    if not os.path.exists("/dev/ptmx"):
        pytest.skip("no /dev/ptmx")
    from syzkaller_tpu.ipc import Env, ExecOpts

    # unlock the slave (TIOCSPTLCK 0) before opening it, as real pty
    # users (and reference-generated programs) do
    text = (
        'r0 = openat$ptmx(0xffffffffffffff9c, '
        '&0:0:0="/dev/ptmx\\x00", 0x2, 0x0)\n'
        'ioctl$TIOCSPTLCK(r0, 0x40045431, &1:0:0=0x00000000)\n'
        "syz_open_pts(r0, 0x2)\n"
    )
    p = deserialize(target, text)
    cwd = os.getcwd()
    os.chdir(tmp_path)
    try:
        with Env(target, pid=0) as env:
            _, infos, failed, hanged = env.exec(ExecOpts(), p)
    finally:
        os.chdir(cwd)
    assert not failed and not hanged
    assert [i.errno for i in infos] == [0, 0, 0]


def test_executor_open_dev_substitution(target, tmp_path):
    """syz_open_dev replaces '#' with the id digit."""
    from syzkaller_tpu.ipc import Env, ExecOpts

    # /dev/tty exists everywhere; use id substitution over /dev/tty#
    # (tty0 may not exist in a container: accept ENOENT/EACCES/EIO but
    # crucially not EFAULT/ENOSYS, which would mean broken dispatch)
    text = ('syz_open_dev$tty(&0:0:0="/dev/tty#\\x00", 0x0, 0x0)\n')
    p = deserialize(target, text)
    cwd = os.getcwd()
    os.chdir(tmp_path)
    try:
        with Env(target, pid=0) as env:
            _, infos, failed, hanged = env.exec(ExecOpts(), p)
    finally:
        os.chdir(cwd)
    assert not failed
    import errno as e

    assert infos[0].errno in (0, e.ENOENT, e.EACCES, e.EIO, e.ENXIO)


def test_executor_kvm_chain(target, tmp_path):
    """KVM setup chain: with /dev/kvm the vcpu must be runnable; without,
    the open fails cleanly (never ENOSYS from the pseudo dispatch)."""
    from syzkaller_tpu.ipc import Env, ExecOpts

    text = (
        'r0 = openat$kvm(0xffffffffffffff9c, '
        '&0:0:0="/dev/kvm\\x00", 0x2, 0x0)\n'
        "r1 = ioctl$KVM_CREATE_VM(r0, 0xae01, 0x0)\n"
        "r2 = ioctl$KVM_CREATE_VCPU(r1, 0xae41, 0x0)\n"
        'syz_kvm_setup_cpu(r1, r2, &vma 100:24, '
        '&1:0:0="f4f4f4f4", 0x4, 0x0)\n'
    )
    p = deserialize(target, text)
    cwd = os.getcwd()
    os.chdir(tmp_path)
    try:
        with Env(target, pid=0) as env:
            _, infos, failed, hanged = env.exec(ExecOpts(), p)
    finally:
        os.chdir(cwd)
    assert not failed
    import errno as e

    if os.path.exists("/dev/kvm") and os.access("/dev/kvm", os.W_OK):
        assert infos[0].errno == 0
        # some sandboxes expose a /dev/kvm node whose ioctls are stubbed
        # out (ENOTTY/ENODEV/EPERM): the open works, virtualization
        # doesn't — only a working CREATE_VM obliges the full chain
        assert infos[1].errno in (0, e.ENOTTY, e.ENODEV, e.EPERM)
        if infos[1].errno == 0:
            assert [i.errno for i in infos] == [0, 0, 0, 0]
    else:
        assert infos[0].errno in (e.ENOENT, e.EACCES, e.EPERM)
    # downstream calls see invalid fds, not a broken dispatcher
    assert all(i.errno != e.ENOSYS for i in infos)


def test_kmemleak_parse():
    from syzkaller_tpu.engine.kmemleak import parse_leaks

    data = """unreferenced object 0xffff8880111 (size 64):
  comm "syz-executor", pid 1234
  backtrace:
    [<00000000abc>] kmalloc+0x10
unreferenced object 0xffff8880222 (size 128):
  comm "kworker", pid 5
"""
    leaks = parse_leaks(data)
    assert len(leaks) == 2
    assert "0xffff8880111" in leaks[0]
    assert "kworker" in leaks[1]


def test_kmemleak_unavailable_is_quiet(tmp_path):
    from syzkaller_tpu.engine.kmemleak import Kmemleak

    k = Kmemleak(path=str(tmp_path / "nope"))
    assert not k.available
    assert k.scan() == []
