"""Fleet observability tests (ISSUE 7): the pinned /stats.json wire
shape (schema_version + engine_id — the fleet aggregator and external
scrapers depend on it), AttributionLedger.merge_state exactness (merge
of N disjoint ledgers == campaign totals; restart continuation stays
monotonic), the fleet aggregator's restart-aware counter folding and
stale/unreachable marking, the /fleet.json + /fleet endpoints, and the
two-engine chaos acceptance: one engine SIGKILL'd and ``--resume``d
mid-campaign with /fleet.json aggregates monotonic across the restart
and the merged ledger exactly equal to the sum of the engines' totals."""

import json
import os
import signal
import subprocess
import sys
import time
import urllib.request

import pytest

from syzkaller_tpu.manager.fleet import (
    FleetAggregator,
    FleetHttp,
    STATUS_ONLINE,
    STATUS_STALE,
    STATUS_UNREACHABLE,
)
from syzkaller_tpu.prog import get_target
from syzkaller_tpu.telemetry import AttributionLedger, get_registry


@pytest.fixture(scope="module")
def target():
    return get_target("linux", "amd64")


def _get_json(addr: str, path: str) -> dict:
    with urllib.request.urlopen(f"http://{addr}{path}", timeout=10) as r:
        return json.loads(r.read())


# ---- /stats.json wire shape (satellite: pinned regression test) ----


def test_stats_json_schema_pinned(tmp_path, target):
    """The EXACT top-level shape external scrapers (and manager/fleet.py)
    parse.  Adding/removing a key must bump STATS_SCHEMA_VERSION and
    update this test deliberately."""
    from syzkaller_tpu.manager import Manager, ManagerConfig
    from syzkaller_tpu.manager.html import STATS_SCHEMA_VERSION

    m = Manager(ManagerConfig(workdir=str(tmp_path),
                              analytics_interval=0), target=target)
    try:
        doc = _get_json(m.http.addr, "/stats.json")
    finally:
        m.close()
    assert set(doc) == {
        "schema_version", "engine_id", "name", "now", "interval",
        "samples", "series", "attribution", "attribution_state",
        "engines", "snapshot", "frontend"}
    assert doc["schema_version"] == STATS_SCHEMA_VERSION == 2
    # the frontend block always exists, zero-defaulted, with the exact
    # counter set the dashboard's "compiler frontend" table reads
    from syzkaller_tpu.manager.html import FRONTEND_METRICS

    assert set(doc["frontend"]) == set(FRONTEND_METRICS)
    assert all(isinstance(v, (int, float)) for v in doc["frontend"].values())
    # the manager's identity is the workdir-minted persistent id
    assert doc["engine_id"] == \
        (tmp_path / "engine_id").read_text().strip()
    assert doc["name"] == m.cfg.name
    ast = doc["attribution_state"]
    assert set(ast) == {"proc", "local", "engines"}
    assert set(ast["local"]) == {"phases", "ops"}


def test_engine_id_rides_wire_stats_and_checkpoint(tmp_path, target):
    """The engine stamps its persistent id into the wire stats (the
    manager pops + records it) and its checkpoint."""
    from syzkaller_tpu.engine import checkpoint as ckpt
    from syzkaller_tpu.engine.fuzzer import Fuzzer, FuzzerConfig
    from syzkaller_tpu.manager import Manager, ManagerConfig
    from syzkaller_tpu.manager.rpc import RemoteManager

    m = Manager(ManagerConfig(workdir=str(tmp_path / "mgr"),
                              analytics_interval=0), target=target)
    try:
        rm = RemoteManager(m.rpc.addr, name="f0")
        cfg = FuzzerConfig(mock=True, use_device=False,
                           smash_mutations=1,
                           workdir=str(tmp_path / "eng"),
                           checkpoint_interval=0)
        with Fuzzer(target, cfg, manager=rm) as f:
            f.loop(iterations=30)
            f.poll_manager()
            f.save_checkpoint()
            eid = f.engine_id
        assert eid == (tmp_path / "eng" / "engine_id").read_text().strip()
        doc = _get_json(m.http.addr, "/stats.json")
        assert doc["engines"]["f0"]["engine_id"] == eid
        # the numeric fold never saw the string id
        assert "engine_id" not in doc["snapshot"]
        st = ckpt.read_checkpoint(str(tmp_path / "eng" / "engine.ckpt"))
        assert st["engine_id"] == eid
    finally:
        m.close()


# ---- ledger merge exactness ----


def test_merge_state_of_disjoint_ledgers_is_exact():
    """Merge of N disjoint ledgers == the campaign totals, cell-exact."""
    parts = []
    for k in range(4):
        led = AttributionLedger()
        led.record_exec("mutate", (k % 5,), n=10 * (k + 1))
        led.record_new_signal("mutate", (k % 5,), 3 * (k + 1))
        for _ in range(k + 1):
            led.record_corpus_add("mutate", (k % 5,))
        led.record_exec("generate", n=k)
        parts.append(led)
    merged = AttributionLedger()
    for led in parts:
        merged.merge_state(led.state())
    want = {
        "execs": sum(led.totals()["execs"] for led in parts),
        "new_signal": sum(led.totals()["new_signal"] for led in parts),
        "corpus_adds": sum(led.totals()["corpus_adds"] for led in parts),
    }
    assert merged.totals() == want
    # per-cell exactness, not just totals
    snap = merged.snapshot()
    assert snap["phases"]["mutate"]["execs"] == sum(
        led.snapshot()["phases"]["mutate"]["execs"] for led in parts)
    for k in range(4):
        op = merged.snapshot()["operators"]
        assert op  # operator rows survived the merge


def test_merge_state_json_roundtrip_key_types():
    """Ledger states cross the RPC wire as JSON, which stringifies the
    integer operator keys — merge_state must fold them back."""
    led = AttributionLedger()
    led.record_exec("mutate", (0, 2), n=7)
    led.record_corpus_add("mutate", (2,))
    wire = json.loads(json.dumps(led.state()))
    merged = AttributionLedger()
    merged.merge_state(wire)
    assert merged.state() == led.state()


def test_load_state_restart_continuation_is_monotonic():
    """--resume semantics: a ledger restored from a checkpoint and then
    credited further never goes below the checkpointed counts."""
    led = AttributionLedger()
    led.record_exec("mutate", (1,), n=100)
    led.record_corpus_add("mutate", (1,))
    ckpt = led.state()
    restored = AttributionLedger()
    restored.load_state(json.loads(json.dumps(ckpt)))
    assert restored.totals() == led.totals()
    restored.record_exec("mutate", (1,), n=5)
    restored.record_corpus_add("mutate", (1,))
    after = restored.state()
    for table in ("phases", "ops"):
        for key, cell in ckpt[table].items():
            got = after[table][type(list(after[table])[0])(key)] \
                if after[table] else None
            assert got is not None
            assert all(b >= a for a, b in zip(cell, got))


# ---- fleet aggregator folding / health ----


def _doc(name, snapshot, engine_id="eng-x", att=None):
    return {
        "schema_version": 2, "engine_id": engine_id, "name": name,
        "now": time.time(), "interval": 0, "samples": 1, "series": {},
        "attribution": {}, "attribution_state": att,
        "engines": {}, "snapshot": snapshot, "frontend": {},
    }


def test_fleet_fold_is_monotonic_across_engine_restart():
    """The rate_points clamp on the fold: a counter that went backwards
    (engine restarted, --resume rewound to the checkpoint) contributes
    nothing until it passes its high-water mark — the fleet aggregate
    never decreases and never double-counts the replayed range."""
    feed = {"m": {"exec_total": 100, "corpus": 5}}

    fleet = FleetAggregator(["m"], interval=0,
                            fetch=lambda t: _doc("m", feed[t]))
    fleet.poll_once(now=1.0)
    assert fleet.fleet_doc(now=1.0)["counters"]["exec_total"] == 100
    feed["m"] = {"exec_total": 40, "corpus": 3}   # restart: rewound
    fleet.poll_once(now=2.0)
    assert fleet.fleet_doc(now=2.0)["counters"]["exec_total"] == 100
    feed["m"] = {"exec_total": 90, "corpus": 4}   # catching up
    fleet.poll_once(now=3.0)
    assert fleet.fleet_doc(now=3.0)["counters"]["exec_total"] == 100
    feed["m"] = {"exec_total": 130, "corpus": 6}  # past the mark
    fleet.poll_once(now=4.0)
    doc = fleet.fleet_doc(now=4.0)
    assert doc["counters"]["exec_total"] == 130
    # gauges are sum-of-latest, not folded (corpus tracked the rewind)
    assert doc["gauges"]["corpus"] == 6
    # the aggregate series stayed monotonic throughout
    vals = doc["series"]["exec_total"]["v"]
    assert vals == sorted(vals)


def test_fleet_marks_unreachable_engines_without_dropping_them():
    calls = {"n": 0}

    def fetch(t):
        if t == "dead" or (t == "flaky" and calls["n"] > 0):
            raise OSError("connection refused")
        calls["n"] += 1
        return _doc("flaky", {"exec_total": 50, "corpus": 7, "signal": 9})

    reg = get_registry()
    before = reg.snapshot().get("fleet_scrape_errors_total", 0)
    fleet = FleetAggregator(["flaky", "dead"], interval=0, fetch=fetch)
    fleet.poll_once(now=1.0)
    rows = {r["target"]: r for r in fleet.fleet_doc(now=1.0)["engines"]}
    assert rows["flaky"]["status"] == STATUS_ONLINE
    assert rows["dead"]["status"] == STATUS_UNREACHABLE  # never answered
    fleet.poll_once(now=2.0)  # flaky has ONE transient failure
    rows = {r["target"]: r for r in fleet.fleet_doc(now=2.0)["engines"]}
    # grace window: a single blip inside stale_after must not flap the
    # fleet view to unreachable
    assert rows["flaky"]["status"] == STATUS_ONLINE
    # past the staleness window with the latest attempt failing: now
    # it's honestly unreachable — but its data is retained, not dropped
    doc = fleet.fleet_doc(now=10.0)
    rows = {r["target"]: r for r in doc["engines"]}
    assert rows["flaky"]["status"] == STATUS_UNREACHABLE
    assert rows["flaky"]["last_error"]
    assert doc["gauges"]["corpus"] == 7
    assert doc["counters"]["exec_total"] == 50
    assert reg.snapshot()["fleet_scrape_errors_total"] >= before + 3
    assert doc["engines_online"] == 0


def test_fleet_stale_when_scraping_goes_quiet():
    """STALE is the no-error staleness: the last attempt succeeded but
    is old (aggregator paused) — distinct from UNREACHABLE."""
    fleet = FleetAggregator(
        ["q"], interval=0, fetch=lambda t: _doc("q", {"exec_total": 1}))
    fleet.poll_once(now=1.0)
    assert fleet.fleet_doc(now=1.5)["engines"][0]["status"] \
        == STATUS_ONLINE
    assert fleet.fleet_doc(now=50.0)["engines"][0]["status"] \
        == STATUS_STALE


def test_fleet_attribution_dedup_by_engine_and_proc():
    """An engine polled through two managers (or two managers sharing
    one process-global ledger) is merged exactly once."""
    eng_state = {"phases": {"mutate": [10, 4, 2]}, "ops": {"1": [10, 4, 2]}}
    local = {"phases": {"generate": [5, 1, 1]}, "ops": {}}
    att = {"proc": "proc-1", "local": local,
           "engines": {"f0": {"engine_id": "eng-dup",
                              "state": eng_state}}}

    fleet = FleetAggregator(
        ["a", "b"], interval=0,
        fetch=lambda t: _doc(t, {"exec_total": 1}, att=dict(att)))
    fleet.poll_once(now=1.0)
    fleet.poll_once(now=2.0)  # repeated scrapes must not re-accumulate
    merged = fleet.merged_ledger()
    assert merged.totals() == {"execs": 15, "new_signal": 5,
                               "corpus_adds": 3}
    doc = fleet.fleet_doc(now=2.0)
    assert list(doc["engine_ledgers"]) == ["eng-dup"]


def test_fleet_collapses_same_process_engines():
    """Two fuzzers sharing one engine PROCESS share one process-global
    ledger — seen through two managers under different names/ids, the
    fleet must count that ledger exactly once."""
    eng_state = {"phases": {"mutate": [8, 2, 1]}, "ops": {}}

    def fetch(t):
        att = {"proc": f"mgrproc-{t}", "local": {"phases": {}, "ops": {}},
               "engines": {f"f-{t}": {"engine_id": f"eng-{t}",
                                      "proc": "shared-engine-proc",
                                      "state": eng_state}}}
        return _doc(t, {"exec_total": 1}, engine_id=f"mgr-{t}", att=att)

    fleet = FleetAggregator(["a", "b"], interval=0, fetch=fetch)
    fleet.poll_once(now=1.0)
    assert fleet.merged_ledger().totals() == {
        "execs": 8, "new_signal": 2, "corpus_adds": 1}
    # exactly one surviving entry for the shared process
    assert len(fleet.fleet_doc(now=1.0)["engine_ledgers"]) == 1


def test_manager_keeps_one_ledger_per_engine_process(tmp_path, target):
    """The manager-side half of the same invariant: two names polling
    with the same proc token ship the same process-global ledger —
    latest name wins, the state is stored once."""
    from syzkaller_tpu.manager import Manager, ManagerConfig

    m = Manager(ManagerConfig(workdir=str(tmp_path),
                              analytics_interval=0), target=target)
    try:
        state = {"phases": {"fleetdedup": [10, 4, 2]}, "ops": {}}
        led = {"proc": "remote-proc", "engine_id": "eng-1",
               "state": state}
        m.on_poll("f0", {}, False, [], ledger=led)
        m.on_poll("f1", {}, False, [],
                  ledger={**led, "engine_id": "eng-2"})
        ast = m.attribution_state()
        assert list(ast["engines"]) == ["f1"]
        assert ast["engines"]["f1"]["proc"] == "remote-proc"
        merged = m.merged_attribution_state()
        assert merged["phases"]["fleetdedup"] == [10, 4, 2]  # not doubled
    finally:
        m.close()


def test_fleet_endpoints_render(tmp_path, target):
    """/fleet.json + the /fleet dashboard over two REAL managers."""
    from syzkaller_tpu.manager import Manager, ManagerConfig

    m1 = Manager(ManagerConfig(name="mgr-a",
                               workdir=str(tmp_path / "a"),
                               analytics_interval=0), target=target)
    m2 = Manager(ManagerConfig(name="mgr-b",
                               workdir=str(tmp_path / "b"),
                               analytics_interval=0), target=target)
    fleet = FleetAggregator([m1.http.addr, m2.http.addr], interval=0)
    http = FleetHttp(fleet)
    http.start()
    try:
        for tick in range(3):
            fleet.poll_once(now=time.time() + tick)
        doc = _get_json(http.addr, "/fleet.json")
        assert doc["schema_version"] == 1
        assert len(doc["engines"]) == 2
        assert doc["engines_online"] == 2
        assert {r["name"] for r in doc["engines"]} == {"mgr-a", "mgr-b"}
        assert all(r["engine_id"] for r in doc["engines"])
        page = urllib.request.urlopen(
            f"http://{http.addr}/fleet", timeout=10).read().decode()
        assert "fleet exec rate /s" in page and "<svg" in page
        assert "mgr-a" in page and "mgr-b" in page and "engines" in page
        # required fleet metrics really registered + live
        snap = get_registry().snapshot()
        assert "fleet_engines_online" in snap
        assert "fleet_scrape_errors_total" in snap
    finally:
        http.stop()
        m1.close()
        m2.close()


def test_required_metrics_cover_fleet_observability():
    from syzkaller_tpu.tools.check_metrics import REQUIRED_METRICS, check

    for name in ("journal_records_total", "journal_bytes_total",
                 "fleet_scrape_errors_total", "fleet_engines_online"):
        assert name in REQUIRED_METRICS
    assert check() == []  # every required name has a live registration


def test_fleet_cli_main_smoke(tmp_path, target):
    """The standalone entry point parses targets and serves /fleet.json
    (constructed directly — main()'s serve-forever loop is not a test)."""
    from syzkaller_tpu.manager import fleet as fleet_mod

    assert callable(fleet_mod.main)
    fleet = FleetAggregator(["127.0.0.1:1", "http://x/stats.json"],
                            interval=0)
    assert fleet.engines[0].url == "http://127.0.0.1:1/stats.json"
    assert fleet.engines[1].url == "http://x/stats.json"


# ---- the two-engine chaos acceptance ----


def _spawn_engine(manager_addr: str, name: str, wd: str, *extra):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.Popen(
        [sys.executable, "-m", "syzkaller_tpu.engine", "-mock",
         "-no-detect", "-manager", manager_addr, "-name", name,
         "-workdir", wd, "-checkpoint-interval", "0.2", *extra],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.PIPE)


def _engine_ledger_totals(state):
    out = {"execs": 0, "new_signal": 0, "corpus_adds": 0}
    for phase, (e, ns, ca) in (state.get("phases") or {}).items():
        out["execs"] += int(e)
        out["new_signal"] += int(ns)
        if phase != "seed":
            out["corpus_adds"] += int(ca)
    return out


@pytest.mark.chaos
def test_two_engine_chaos_kill_resume_fleet_exact(tmp_path, target):
    """The ISSUE 7 acceptance campaign: two engines (real subprocesses,
    each with its own manager), one SIGKILL'd mid-campaign and resumed
    with ``--resume``.  Pins: (1) /fleet.json folded counters monotonic
    across every scrape spanning the restart, (2) the merged attribution
    ledger exact — fleet corpus_adds == sum of both engines' new_inputs,
    (3) yield-per-operator/phase trajectory continuous across the
    restart (post-resume counts >= a pre-kill scrape that predates the
    restored checkpoint), (4) both engines' journals chain-valid."""
    from syzkaller_tpu.manager import Manager, ManagerConfig

    m1 = Manager(ManagerConfig(name="mgr-a",
                               workdir=str(tmp_path / "ma"),
                               analytics_interval=0), target=target)
    m2 = Manager(ManagerConfig(name="mgr-b",
                               workdir=str(tmp_path / "mb"),
                               analytics_interval=0), target=target)
    fleet = FleetAggregator([m1.http.addr, m2.http.addr], interval=0)
    wd_a, wd_b = str(tmp_path / "ea"), str(tmp_path / "eb")
    ck_a = os.path.join(wd_a, "engine.ckpt")
    pa = _spawn_engine(m1.rpc.addr, "eng-a", wd_a)
    pb = None
    fold_history = []

    def scrape(now=None):
        fleet.poll_once(now=now)
        doc = fleet.fleet_doc(now=now)
        fold_history.append(dict(doc["counters"]))
        return doc

    try:
        # engine B runs a clean finite campaign alongside
        pb = _spawn_engine(m2.rpc.addr, "eng-b", wd_b,
                           "-iterations", "300")
        # wait until manager A holds engine A's ledger AND a checkpoint
        deadline = time.time() + 120
        pre = None
        while time.time() < deadline:
            if pa.poll() is not None:
                pytest.fail("engine A died early: "
                            + pa.stderr.read().decode()[-2000:])
            doc = _get_json(m1.http.addr, "/stats.json")
            engs = doc["attribution_state"]["engines"]
            if engs.get("eng-a", {}).get("state") and \
                    os.path.exists(ck_a):
                pre = engs["eng-a"]["state"]
                break
            time.sleep(0.05)
        assert pre is not None, "engine A never shipped a ledger"
        scrape()
        # a checkpoint NEWER than the pre-kill scrape: the resumed
        # trajectory is then guaranteed >= `pre` cell-wise
        mtime0 = os.path.getmtime(ck_a)
        while os.path.getmtime(ck_a) <= mtime0 and \
                time.time() < deadline:
            time.sleep(0.05)
        os.kill(pa.pid, signal.SIGKILL)
        pa.wait(timeout=30)
        scrape()  # mid-outage scrape: totals must not regress
        # resume the killed engine; finite run ends with a final poll
        pa = _spawn_engine(m1.rpc.addr, "eng-a", wd_a,
                           "--resume", "-iterations", "200")
        out_a = pa.communicate(timeout=120)
        assert pa.returncode == 0, out_a[1].decode()[-2000:]
        out_b = pb.communicate(timeout=120)
        assert pb.returncode == 0, out_b[1].decode()[-2000:]
        final = scrape()
    finally:
        for p in (pa, pb):
            if p is not None and p.poll() is None:
                p.kill()
                p.wait(timeout=30)
        m1.close()
        m2.close()

    # (1) folded fleet counters monotonic across the kill + resume
    for a, b in zip(fold_history, fold_history[1:]):
        for k, v in a.items():
            assert b.get(k, 0) >= v, \
                f"fleet counter {k} regressed across restart: {v}->{b.get(k)}"

    # (2) merged ledger EXACT: fleet corpus_adds == sum of engines'
    # new_inputs (each engine's final poll shipped its final absolute
    # ledger; the manager snapshot's new_inputs came from the same poll)
    want_ni = 0
    for m in (m1, m2):
        want_ni += int(m.snapshot().get("new_inputs", 0))
    ledgers = final["engine_ledgers"]
    assert len(ledgers) == 2, f"expected 2 engines, got {list(ledgers)}"
    got = sum(_engine_ledger_totals(st)["corpus_adds"]
              for st in ledgers.values())
    assert got == want_ni > 0
    # engine identity is the workdir-persistent id for both
    ids = {open(os.path.join(wd, "engine_id")).read().strip()
           for wd in (wd_a, wd_b)}
    assert set(ledgers) == ids

    # (3) trajectory continuity: the resumed engine's final per-phase /
    # per-operator cells dominate the pre-kill scrape (which predates
    # the checkpoint the resume restored)
    eid_a = open(os.path.join(wd_a, "engine_id")).read().strip()
    post = ledgers[eid_a]
    for table in ("phases", "ops"):
        for key, cell in (pre.get(table) or {}).items():
            after = (post.get(table) or {}).get(key)
            assert after is not None, f"{table}[{key}] vanished on resume"
            assert all(b >= a for a, b in zip(cell, after)), \
                f"{table}[{key}] regressed: {cell} -> {after}"

    # (4) both journals chain-valid from the workdirs alone
    from syzkaller_tpu.telemetry import journal as J

    for wd in (wd_a, wd_b):
        records, defects = J.read_records(wd)
        assert [d for d in defects if not d.startswith("tail: ")] == []
        assert J.verify_records(records) == []
    rep = J.replay(wd_a)
    assert rep["restores"] == 1  # the --resume really replayed state
