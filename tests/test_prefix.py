"""Prefix-memoized batch execution (ISSUE 6 tentpole): LCP-tree
correctness properties, jax/host hash-mirror parity, MockEnv exact
continuation (memoized prefix + suffix == full exec, bit-identical
CallInfo signal), the per-env cache LRU bound, prefix-aware drain
accounting, and quarantine re-plan exactly-once under the chaos fault
harness."""

import time

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from syzkaller_tpu.descriptions.tables import get_tables  # noqa: E402
from syzkaller_tpu.engine.fuzzer import Fuzzer, FuzzerConfig  # noqa: E402
from syzkaller_tpu.ipc import ExecOpts, MockEnv  # noqa: E402
from syzkaller_tpu.ops import admission  # noqa: E402
from syzkaller_tpu.ops import prefix as pfx  # noqa: E402
from syzkaller_tpu.prog import get_target  # noqa: E402
from syzkaller_tpu.prog.encodingexec import serialize_for_exec  # noqa: E402
from syzkaller_tpu.prog.generation import generate  # noqa: E402
from syzkaller_tpu.telemetry import get_registry  # noqa: E402
from syzkaller_tpu.testing import faults  # noqa: E402
from syzkaller_tpu.testing.faults import FaultPlan  # noqa: E402


@pytest.fixture(scope="module")
def target():
    return get_target("linux", "amd64")


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


def _counter(name):
    m = get_registry().get(name)
    return m.value if m is not None else 0


def _mk_batch(seed=0, B=16, C=6, S=3, D=8):
    rng = np.random.default_rng(seed)
    cid = rng.integers(0, 50, size=(B, C)).astype(np.int32)
    sval = rng.integers(0, 2 ** 63, size=(B, C, S)).astype(np.uint64)
    data = rng.integers(0, 255, size=(B, C, D)).astype(np.uint8)
    return cid, sval, data


# ------------------------------------------------------------------ #
# hash + LCP mirrors


def test_call_hashes_match_admission_row_hash_per_slot():
    """The per-slot content hash IS admission.row_hash of that slot's
    triple (one hash family across the admission + prefix gates), with
    empty slots normalized to the sentinel."""
    cid, sval, data = _mk_batch(1, B=4, C=3)
    cid[1, 1] = -1
    h = pfx.call_hashes_host(cid, sval, data)
    for b in range(4):
        for c in range(3):
            want = (pfx.EMPTY_SLOT_HASH if cid[b, c] < 0 else
                    admission.row_hash_host(cid[b, c], sval[b, c],
                                            data[b, c]))
            assert int(h[b, c]) == int(want)


def test_device_host_mirror_parity():
    """jax call_hashes / prefix_hashes / sorted_lcp == the numpy
    mirrors, bit for bit."""
    import jax.numpy as jnp

    cid, sval, data = _mk_batch(2, B=12, C=5)
    cid[3:7, :2] = cid[0, :2]
    sval[3:7, :2] = sval[0, :2]
    data[3:7, :2] = data[0, :2]
    cid[5, 4] = -1
    h = pfx.call_hashes_host(cid, sval, data)
    hj = np.asarray(pfx.call_hashes(
        jnp.asarray(cid), jnp.asarray(sval), jnp.asarray(data)))
    assert (h == hj).all()
    assert (pfx.prefix_hashes_host(h)
            == np.asarray(pfx.prefix_hashes(jnp.asarray(h)))).all()
    oh, lh = pfx.sorted_lcp_host(h)
    oj, lj = (np.asarray(x) for x in pfx.sorted_lcp(jnp.asarray(h)))
    assert (oh == oj).all() and (lh == lj).all()


def test_inactive_slot_garbage_never_splits_a_group():
    """Two rows with identical active calls but different garbage in an
    empty slot's sval/data hash identically (the executed stream can't
    see the garbage, so the planner must not either)."""
    cid, sval, data = _mk_batch(3, B=2, C=4)
    cid[1] = cid[0]
    sval[1] = sval[0]
    data[1] = data[0]
    cid[:, 2] = -1
    sval[1, 2] ^= np.uint64(0xDEAD)
    data[1, 2, :] = 7
    h = pfx.call_hashes_host(cid, sval, data)
    assert (h[0] == h[1]).all()


# ------------------------------------------------------------------ #
# tree / schedule properties


def test_plan_covers_each_row_exactly_once_and_is_reachable():
    """Every grouped program is reachable as (node prefix + own
    suffix): the schedule assigns each row at most one node, nodes have
    >= 2 users and >= min_calls active prefix calls, and a member's
    first `depth` slots are byte-identical to its node carrier's."""
    cid, sval, data = _mk_batch(4, B=24, C=6)
    # group A: 6 rows sharing 3 slots; group B: 4 rows sharing 2 slots;
    # nested: group A splits at depth 4 for 3 of its rows
    for r in range(1, 6):
        cid[r, :3], sval[r, :3], data[r, :3] = \
            cid[0, :3], sval[0, :3], data[0, :3]
    for r in (3, 4, 5):
        cid[r, 3], sval[r, 3], data[r, 3] = \
            cid[2, 3], sval[2, 3], data[2, 3]
    for r in (7, 8, 9):
        cid[r, :2], sval[r, :2], data[r, :2] = \
            cid[6, :2], sval[6, :2], data[6, :2]
    plan = pfx.build_plan(cid, sval, data, min_group=2, min_calls=1)
    assert plan.nodes
    seen = [r for nd in plan.nodes for r in nd.rows]
    assert len(seen) == len(set(seen)), "a row appears in two nodes"
    assert set(plan.row_node) == set(seen)
    for row, nid in plan.row_node.items():
        nd = plan.nodes[nid]
        assert nd.n_calls >= 1
        d = nd.depth
        assert (cid[row, :d] == cid[nd.carrier, :d]).all()
        assert (sval[row, :d] == sval[nd.carrier, :d]).all()
        assert (data[row, :d] == data[nd.carrier, :d]).all()
        # n_calls is the ACTIVE-call projection of the slot depth
        assert nd.n_calls == int((cid[row, :d] >= 0).sum())
    # every node amortizes: >= 2 users (direct rows + children)
    kids = {}
    for nid, nd in enumerate(plan.nodes):
        if nd.parent >= 0:
            kids.setdefault(nd.parent, []).append(nid)
            # topological order + strictly growing prefix depth
            assert nd.parent < nid
            assert nd.n_calls > plan.nodes[nd.parent].n_calls
    for nid, nd in enumerate(plan.nodes):
        assert len(nd.rows) + len(kids.get(nid, ())) >= 2
    # the two seeded groups both scheduled
    assert {plan.row_node.get(r) for r in (0, 1, 2)} != {None}
    assert {plan.row_node.get(r) for r in (6, 7, 8, 9)} != {None}


def test_plan_respects_min_calls_and_eligible_rows():
    cid, sval, data = _mk_batch(5, B=8, C=4)
    for r in range(1, 4):
        cid[r, 0], sval[r, 0], data[r, 0] = \
            cid[0, 0], sval[0, 0], data[0, 0]
    # depth-1 sharing only: min_calls=2 must schedule nothing
    plan = pfx.build_plan(cid, sval, data, min_calls=2)
    assert not plan.nodes and not plan.row_node
    # restricting eligibility excludes rows from grouping
    plan = pfx.build_plan(cid, sval, data, rows=[0, 1], min_calls=1)
    assert set(plan.row_node) <= {0, 1}


def test_min_group_merge_cascade_resolves_stale_parents():
    """Regression: with min_group raised, a deep node can merge into an
    ancestor that LATER merges upward itself — eff_parent must follow
    the collapse chain to a node that still stands for itself, not
    return a stale link absent from the emitted plan (was a KeyError).
    Pinned with the discovered repro plus a fuzz sweep over nested
    batches and min_group values."""
    seqs = [[0, 8], [0, 1, 0, 1, 6], [0, 1, 0, 1, 0, 1, 7], [0, 5],
            [0, 1, 7], [0, 1, 0, 1, 1, 0, 1, 7], [0, 1, 0, 7],
            [0, 1, 0, 1, 1, 0, 1, 1, 5]]
    C = max(len(s) for s in seqs)
    B = len(seqs)
    cid = np.full((B, C), -1, np.int32)
    for b, s in enumerate(seqs):
        cid[b, :len(s)] = s
    sval = np.zeros((B, C, 2), np.uint64)
    data = np.zeros((B, C, 4), np.uint8)
    plan = pfx.build_plan(cid, sval, data, min_group=5, min_calls=1)
    for nd in plan.nodes:
        assert nd.parent < len(plan.nodes)
    rng = np.random.default_rng(0)
    for trial in range(50):
        Bf, Cf = 12, 8
        cidf = rng.integers(0, 3, size=(Bf, Cf)).astype(np.int32)
        svalf = np.zeros((Bf, Cf, 1), np.uint64)
        dataf = np.zeros((Bf, Cf, 1), np.uint8)
        for mg in (2, 3, 5):
            p = pfx.build_plan(cidf, svalf, dataf, min_group=mg)
            for nid, nd in enumerate(p.nodes):
                assert -1 <= nd.parent < nid


def test_identical_rows_group_at_full_depth():
    """A row that IS another row's prefix (or exact duplicate under a
    hash-collision-free plan) schedules with an empty suffix instead of
    falling out of the tree."""
    cid, sval, data = _mk_batch(6, B=4, C=4)
    cid[1], sval[1], data[1] = cid[0], sval[0], data[0]
    plan = pfx.build_plan(cid, sval, data, min_calls=1)
    assert plan.row_node.get(0) is not None
    assert plan.row_node.get(0) == plan.row_node.get(1)
    nd = plan.nodes[plan.row_node[0]]
    assert nd.depth == 4 and nd.n_calls == int((cid[0] >= 0).sum())


# ------------------------------------------------------------------ #
# MockEnv exact continuation


def _prog_stream(target, seed=3, n=8):
    p = generate(target, seed, n)
    return (serialize_for_exec(p, pid=0), [c.meta.id for c in p.calls])


def test_mockenv_splice_is_bit_identical_to_full_exec(target):
    env = MockEnv(target)
    opts = ExecOpts(collect_cover=True, collect_comps=True)
    data, cids = _prog_stream(target)
    _, full, _, _ = env.exec_raw(opts, data, cids)
    n_prefix = 3
    _, pinf, failed, hanged, hit = env.exec_prefix(
        opts, data, cids, n_prefix, prefix_hash=0xABC)
    assert not (failed or hanged or hit)
    # the prefix job executes calls 1..n only — never the prelude mmap
    # (whose page budget is a whole-program property)
    assert [i.index for i in pinf] == list(range(1, n_prefix + 1))
    _, spliced, failed, hanged, hit = env.exec_suffix(
        opts, data, cids, n_prefix, prefix_hash=0xABC)
    assert hit and not (failed or hanged)
    assert len(spliced) == len(full)
    for a, b in zip(spliced, full):
        assert (a.index, a.num, a.errno, a.executed, a.fault_injected,
                a.signal, a.cover, a.comps) == \
               (b.index, b.num, b.errno, b.executed, b.fault_injected,
                b.signal, b.cover, b.comps)
    # spliced infos are COPIES: mutating one result can't corrupt the
    # memo for the next sibling
    spliced[1].signal.append(424242)
    _, again, *_ = env.exec_suffix(opts, data, cids, n_prefix,
                                   prefix_hash=0xABC)
    assert 424242 not in again[1].signal


def test_mockenv_suffix_miss_self_heals_and_counts_saved(target):
    env = MockEnv(target)
    opts = ExecOpts()
    data, cids = _prog_stream(target, seed=5)
    before = _counter("prefix_calls_saved_total")
    _, m, _, _, hit = env.exec_suffix(opts, data, cids, 2, prefix_hash=7)
    assert not hit  # cold memo: full exec
    assert _counter("prefix_calls_saved_total") == before
    _, m2, _, _, hit = env.exec_suffix(opts, data, cids, 2, prefix_hash=7)
    assert hit  # the full exec healed the memo
    assert _counter("prefix_calls_saved_total") == before + 2
    for a, b in zip(m, m2):
        assert a.signal == b.signal and a.index == b.index


def test_mockenv_nested_prefix_continues_from_parent(target):
    env = MockEnv(target)
    opts = ExecOpts()
    data, cids = _prog_stream(target, seed=6)
    env.exec_prefix(opts, data, cids, 2, prefix_hash=100)
    before = _counter("prefix_calls_saved_total")
    _, infos, _, _, hit = env.exec_prefix(
        opts, data, cids, 4, prefix_hash=200,
        parent_hash=100, parent_calls=2)
    assert hit  # parent memo reused: only 2 marginal calls executed
    assert _counter("prefix_calls_saved_total") == before + 2
    _, full, _, _ = env.exec_raw(opts, data, cids)
    for a, b in zip(infos, full[1:5]):  # calls 1..4 (no prelude)
        assert a.signal == b.signal and a.index == b.index


def test_mockenv_prefix_cache_lru_bound(target):
    env = MockEnv(target, prefix_cache_entries=3)
    opts = ExecOpts()
    data, cids = _prog_stream(target, seed=7)
    for k in range(8):
        env.exec_prefix(opts, data, cids, 2, prefix_hash=k)
    assert len(env._prefix_cache) == 3
    # oldest evicted, newest retained
    _, _, _, _, hit = env.exec_suffix(opts, data, cids, 2, prefix_hash=0)
    assert not hit
    _, _, _, _, hit = env.exec_suffix(opts, data, cids, 2, prefix_hash=7)
    assert hit


def test_mockenv_opts_key_isolates_memo_entries(target):
    """A memo recorded without cover collection must not splice into an
    execution that wants cover (the payloads differ)."""
    env = MockEnv(target)
    data, cids = _prog_stream(target, seed=8)
    env.exec_prefix(ExecOpts(), data, cids, 2, prefix_hash=9)
    _, _, _, _, hit = env.exec_suffix(
        ExecOpts(collect_cover=True), data, cids, 2, prefix_hash=9)
    assert not hit


# ------------------------------------------------------------------ #
# engine drain integration


def mk(target, **kw):
    kw.setdefault("mock", True)
    kw.setdefault("use_device", False)
    kw.setdefault("procs", 1)
    return Fuzzer(target, FuzzerConfig(**kw))


def test_device_loop_prefix_scheduling_end_to_end(target):
    """The live mock device loop builds prefix plans, drains env-affine
    suffix jobs, and records hits + saved calls; exec accounting stays
    exactly consistent through the prefix jobs."""
    cfg = FuzzerConfig(mock=True, use_device=True, device_batch=64,
                       program_length=8, device_period=1,
                       smash_mutations=0, generate_period=1 << 30,
                       procs=3)
    h0 = _counter("prefix_cache_hits_total")
    s0 = _counter("prefix_calls_saved_total")
    with Fuzzer(target, cfg) as f:
        for i in range(24):
            f._add_corpus(generate(target, 500 + i, 8), ())
        for _ in range(200):
            f.step()
            if _counter("prefix_cache_hits_total") - h0 >= 10:
                break
        assert _counter("prefix_cache_hits_total") - h0 >= 10
        assert _counter("prefix_calls_saved_total") - s0 > 0
        assert f.stats.get("prefix_hits", 0) > 0  # wire-stat mirror
        parts = ("exec_gen", "exec_fuzz", "exec_candidate", "exec_triage",
                 "exec_minimize", "exec_smash", "exec_hints")
        assert f.stats["exec_total"] == sum(f.stats[k] for k in parts)
        # the plan span recorded
        snap = get_registry().snapshot()
        assert snap.get("span_device_prefix_plan_seconds_count", 0) > 0


def test_prefix_schedule_off_is_the_old_drain(target):
    """prefix_schedule=False never builds a plan nor touches the
    continuation API (the PR5 drain, bit for bit)."""
    cfg = FuzzerConfig(mock=True, use_device=True, device_batch=32,
                       program_length=8, device_period=1,
                       smash_mutations=0, generate_period=1 << 30,
                       procs=2, prefix_schedule=False)
    h0 = _counter("prefix_cache_hits_total")
    m0 = _counter("prefix_cache_misses_total")
    with Fuzzer(target, cfg) as f:
        for i in range(8):
            f._add_corpus(generate(target, 600 + i, 8), ())
        for _ in range(40):
            f.step()
        assert f.stats["device_batches"] > 0
    assert _counter("prefix_cache_hits_total") == h0
    assert _counter("prefix_cache_misses_total") == m0


class _ChaosContEnv:
    """Continuation-capable fake env for the re-plan chaos test: records
    which rows it executed (suffix stream byte 0 = row id), consults the
    fault plan like ipc does, and tracks a real memo so re-planned rows
    demonstrably self-heal on the surviving env."""

    supports_continuation = True

    def __init__(self, pid, delay=0.002):
        self.pid = pid
        self.delay = delay
        self.rows = []
        self.prefix_jobs = 0
        self.fails = 0
        self.memo = set()

    def _fire(self):
        if faults.should_fire(f"env.exec:{self.pid}"):
            self.fails += 1
            return True
        return False

    def exec_prefix(self, opts, data, call_ids, n_calls, prefix_hash,
                    parent_hash=None, parent_calls=0):
        time.sleep(self.delay)
        if self._fire():
            return b"", [], True, False, False
        self.prefix_jobs += 1
        self.memo.add(prefix_hash)
        return b"", [], False, False, False

    def exec_suffix(self, opts, data, call_ids, n_prefix, prefix_hash):
        time.sleep(self.delay)
        if self._fire():
            return b"", [], True, False, False
        hit = prefix_hash in self.memo
        self.memo.add(prefix_hash)
        self.rows.append(data[0])
        return b"", [], False, False, hit

    def exec_raw(self, opts, data, call_ids):
        time.sleep(self.delay)
        if self._fire():
            return b"", [], True, False
        self.rows.append(data[0])
        return b"", [], False, False

    def close(self):
        pass


class _FakePlanBatch:
    """Batch stand-in with a REAL PrefixPlan injected via a stub
    _plan_prefixes (streams carry the row id in byte 0)."""

    def __init__(self, n):
        self.streams = [bytes([r]) for r in range(n)]
        self.dropped = 0

    def __len__(self):
        return len(self.streams)

    def op_mask(self, row):
        return 1

    def src_row(self, row):
        return -1

    def src_age(self, row):
        return -1

    def call_ids(self, row):
        return [0, 1, 2, 3]

    def decode(self, row):
        return None


@pytest.mark.chaos
def test_quarantine_replan_executes_group_rows_exactly_once(target):
    """Kill one env until quarantine mid-group: its remaining suffix
    jobs re-plan onto the survivors and every ROW still executes
    exactly once; the re-planned rows miss (cold memo) then self-heal,
    and prefix jobs are never retried."""
    from syzkaller_tpu.ops.prefix import PrefixNode, PrefixPlan

    plan = PrefixPlan()
    plan.nodes.append(PrefixNode(hash=111, depth=2, n_calls=2,
                                 carrier=0, rows=list(range(0, 10))))
    plan.nodes.append(PrefixNode(hash=222, depth=2, n_calls=2,
                                 carrier=10, rows=list(range(10, 20))))
    for r in range(20):
        plan.row_node[r] = 0 if r < 10 else 1
    faults.install(FaultPlan(seed=3).fail_at("env.exec:0", 2, 3))
    with mk(target, procs=2, use_device=False,
            env_base_backoff=0.002, env_max_backoff=0.01,
            env_quarantine_threshold=2, env_probe_interval=0.01,
            drain_max_attempts=10) as f:
        f.envs = [_ChaosContEnv(i) for i in range(2)]
        f._plan_prefixes = lambda batch: plan
        before_h = _counter("prefix_cache_hits_total")
        f._run_device_batch_inner(_FakePlanBatch(20))
        rows = sorted(r for e in f.envs for r in e.rows)
        assert rows == list(range(20)), "rows lost or duplicated"
        assert sum(e.fails for e in f.envs) >= 1, "plan never fired"
        # both groups' members mostly hit their (possibly re-healed) memo
        assert _counter("prefix_cache_hits_total") > before_h


def test_dropped_rows_surface_in_wire_stats_and_supervisor(target):
    """Satellite: rows dropped after drain_max_attempts are visible in
    the wire stats (-> /stats.json, dashboard) and the supervisor's
    introspection, not just the registry counter."""
    faults.install(FaultPlan().rate("env.exec:0", 1.0)
                   .rate("env.exec:1", 1.0))
    before = _counter("drain_rows_dropped_total")
    with mk(target, procs=2, use_device=False,
            env_base_backoff=0.001, env_max_backoff=0.005,
            env_quarantine_threshold=100, env_probe_interval=0.005,
            drain_max_attempts=2) as f:
        f.envs = [_ChaosContEnv(i, delay=0.0) for i in range(2)]
        f._run_device_batch_inner(_FakePlanBatch(3))
        assert f.stats.get("drain_rows_dropped", 0) == 3
        assert f.supervisor.dropped_rows() == 3
    assert _counter("drain_rows_dropped_total") == before + 3


class _PlainEnv:
    """Fallback fake env (NO continuation support, like the real
    executor): grouped rows must drain off the shared overflow deque
    (never pinned env-affine) and reuse the memoized prefix signal via
    the engine's scanned-set."""

    supports_continuation = False

    def __init__(self, pid):
        self.pid = pid
        self.rows = []

    def exec_raw(self, opts, data, call_ids):
        time.sleep(0.002)  # force genuine worker overlap
        self.rows.append(data[0])
        return b"", [], False, False

    def close(self):
        pass


def test_fallback_env_reuses_prefix_signal_without_affinity(target):
    """Real-executor path: no prefix jobs are scheduled, every grouped
    row still executes exactly once (dynamically balanced), the first
    row of a group counts a miss (it pays the scan), and every sibling
    counts a hit via the engine-global scanned-set."""
    from syzkaller_tpu.ops.prefix import PrefixNode, PrefixPlan

    plan = PrefixPlan()
    plan.nodes.append(PrefixNode(hash=333, depth=2, n_calls=2,
                                 carrier=0, rows=list(range(8))))
    for r in range(8):
        plan.row_node[r] = 0
    h0 = _counter("prefix_cache_hits_total")
    m0 = _counter("prefix_cache_misses_total")
    with mk(target, procs=2, use_device=False) as f:
        f.envs = [_PlainEnv(i) for i in range(2)]
        f._plan_prefixes = lambda batch: plan
        f._run_device_batch_inner(_FakePlanBatch(8))
        rows = sorted(r for e in f.envs for r in e.rows)
        assert rows == list(range(8))
        # dynamic balancing preserved: both envs executed rows
        assert all(e.rows for e in f.envs)
    assert _counter("prefix_cache_misses_total") == m0 + 1
    assert _counter("prefix_cache_hits_total") == h0 + 7


def test_nested_prefix_job_skips_parent_scanned_range(target):
    """Regression: a child node's prefix contains its parent's — the
    child's prefix job must skip the range the parent's job already
    novelty-scanned, or every tree level re-enqueues duplicate
    TriageItems for it."""
    from syzkaller_tpu.ops.prefix import PrefixNode, PrefixPlan

    plan = PrefixPlan()
    plan.nodes.append(PrefixNode(hash=41, depth=2, n_calls=2,
                                 carrier=0, rows=[0, 1]))
    plan.nodes.append(PrefixNode(hash=42, depth=4, n_calls=4, parent=0,
                                 carrier=2, rows=[2, 3]))
    skips = []
    with mk(target, procs=1, use_device=False) as f:
        f.envs = [_ChaosContEnv(0, delay=0.0)]
        orig = f._scan_infos_for_triage
        f._scan_infos_for_triage = (
            lambda batch, row, infos, origin, skip_prefix_calls=0:
            skips.append(skip_prefix_calls) or
            orig(batch, row, infos, origin, skip_prefix_calls))
        batch = _FakePlanBatch(4)
        f._drain_prefix(batch, plan, 0, 0)   # parent: full scan
        f._drain_prefix(batch, plan, 1, 0)   # child: parent range skipped
    assert skips == [0, 2]


def test_plan_gate_skips_negative_savings_on_continuation_fleet(target):
    """A plan whose estimated splice savings can't repay its warm-up
    round trips is not scheduled on a continuation fleet — but a
    fallback fleet (no warm-up cost, free triage-scan reuse) keeps it."""
    from syzkaller_tpu.ops import prefix as pfx_mod
    from syzkaller_tpu.ops.prefix import PrefixNode, PrefixPlan

    losing = PrefixPlan()
    losing.nodes.append(PrefixNode(hash=1, depth=1, n_calls=1,
                                   carrier=0, rows=[0, 1]))
    losing.row_node = {0: 0, 1: 0}
    losing.calls_saved_est = 0  # 2 saved - (1 call + 1 job) = 0

    class _EncBatch(_FakePlanBatch):
        def __init__(self, n):
            super().__init__(n)
            self.batch = type("E", (), {
                "call_id": np.zeros((n, 2), np.int32),
                "slot_val": np.zeros((n, 2, 1), np.uint64),
                "data": np.zeros((n, 2, 1), np.uint8)})()

    with mk(target, procs=1, use_device=False) as f:
        import unittest.mock as um

        with um.patch.object(pfx_mod, "build_plan",
                             return_value=losing):
            assert f._plan_prefixes(_EncBatch(4)) is None  # MockEnv fleet
            f.envs = [_PlainEnv(0)]
            assert f._plan_prefixes(_EncBatch(4)) is losing  # fallback


def test_decode_failure_does_not_mark_prefix_scanned(target):
    """Regression: if the first-drained row of a group can't decode
    (codec long tail), its lost triage enqueue must NOT mark the prefix
    hash scanned — a sibling's successful decode still rescues the
    group's prefix coverage."""
    from syzkaller_tpu.ipc import CallInfo
    from syzkaller_tpu.ops.prefix import PrefixNode, PrefixPlan

    plan = PrefixPlan()
    plan.nodes.append(PrefixNode(hash=77, depth=1, n_calls=1,
                                 carrier=0, rows=[0, 1]))
    plan.row_node = {0: 0, 1: 0}

    class _SigEnv(_PlainEnv):
        def exec_raw(self, opts, data, call_ids):
            self.rows.append(data[0])
            infos = [CallInfo(index=i, num=1, errno=0, executed=True,
                              fault_injected=False,
                              signal=[424201 + i], cover=[], comps=[])
                     for i in range(len(call_ids))]
            return b"", infos, False, False

    with mk(target, procs=1, use_device=False) as f:
        f.envs = [_SigEnv(0)]
        batch = _FakePlanBatch(2)  # decode() returns None: lost triage
        assert f._drain_row(batch, 0, 0, node=plan.nodes[0])[0] == "ok"
        assert not f._prefix_seen(77), \
            "decode failure must not suppress the group's prefix scan"


def test_exec_prefix_warm_short_circuit_executes_nothing(target):
    """A recurring prefix job on an already-warm memo executes ZERO
    calls (the cross-batch steady state) and reports the full saving."""
    env = MockEnv(target)
    opts = ExecOpts()
    data, cids = _prog_stream(target, seed=9)
    env.exec_prefix(opts, data, cids, 3, prefix_hash=55)
    c0 = _counter("calls_executed_total")
    s0 = _counter("prefix_calls_saved_total")
    _, infos, failed, hanged, saved = env.exec_prefix(
        opts, data, cids, 3, prefix_hash=55)
    assert not (failed or hanged)
    assert saved == 3
    assert [i.index for i in infos] == [1, 2, 3]
    assert _counter("calls_executed_total") == c0
    assert _counter("prefix_calls_saved_total") == s0 + 3


def test_env_memo_hit_does_not_skip_unscanned_prefix(target):
    """Regression: an env-side memo hit alone must not skip the novelty
    scan — the engine scanned-set is the single scan authority (the
    carrier's scan may have failed decode, or the memo may predate the
    scanned-set LRU window).  The first sibling with a warm memo still
    draws the (one, atomic) scan duty; the second skips."""
    from syzkaller_tpu.ops.prefix import PrefixNode, PrefixPlan

    plan = PrefixPlan()
    plan.nodes.append(PrefixNode(hash=888, depth=2, n_calls=2,
                                 carrier=0, rows=[0, 1]))
    plan.row_node = {0: 0, 1: 0}
    skips = []
    with mk(target, procs=1, use_device=False) as f:
        env = _ChaosContEnv(0, delay=0.0)
        env.memo.add(888)  # warm memo, but engine never scanned it
        f.envs = [env]
        orig = f._scan_infos_for_triage
        f._scan_infos_for_triage = (
            lambda batch, row, infos, origin, skip_prefix_calls=0:
            skips.append(skip_prefix_calls) or
            orig(batch, row, infos, origin, skip_prefix_calls))
        batch = _FakePlanBatch(2)
        assert f._drain_row(batch, 0, 0, node=plan.nodes[0])[0] == "ok"
        assert f._drain_row(batch, 1, 0, node=plan.nodes[0])[0] == "ok"
    # both were memo hits, yet the first still scanned the full range
    assert skips == [0, 2]
