"""ifuzz x86 codegen tests (reference pkg/ifuzz/ifuzz_test.go strategy:
generate, decode, mutate under every mode; invariants not golden bytes)."""

import random

import pytest

from syzkaller_tpu import ifuzz
from syzkaller_tpu.ifuzz import (
    Config,
    MODE_LONG64,
    MODE_PROT16,
    MODE_PROT32,
    MODE_REAL16,
    decode,
    generate,
    mode_insns,
    mutate,
    split,
)

MODES = [MODE_LONG64, MODE_PROT32, MODE_PROT16, MODE_REAL16]


@pytest.mark.parametrize("mode", MODES)
def test_generate_nonempty_and_mode_filtered(mode):
    cfg = Config(length=20, mode=mode)
    rng = random.Random(0)
    text = generate(cfg, rng)
    assert len(text) >= 20  # at least 1 byte per instruction
    pool = mode_insns(cfg)
    assert pool
    if mode != MODE_LONG64:
        assert not any(i.name == "syscall" for i in pool)
    # unprivileged pool is strictly smaller
    assert len(mode_insns(Config(mode=mode, priv=False))) < len(pool)


@pytest.mark.parametrize("mode", MODES)
def test_encode_decode_roundtrip(mode):
    """Every single-insn encoding must decode to exactly its length."""
    cfg = Config(mode=mode)
    rng = random.Random(1)
    for insn in mode_insns(cfg):
        for _ in range(8):
            enc = ifuzz.encode_insn(insn, cfg, rng)
            ln = decode(cfg, enc)
            assert ln == len(enc), (insn.name, enc.hex(), ln)


@pytest.mark.parametrize("mode", MODES)
def test_split_covers_stream(mode):
    cfg = Config(length=15, mode=mode)
    rng = random.Random(2)
    text = generate(cfg, rng)
    chunks = split(cfg, text)
    assert b"".join(chunks) == text
    # the generator emits table encodings, so the greedy split should
    # recover instruction-sized chunks (not all 1-byte fallbacks)
    assert sum(len(c) > 1 for c in chunks) > len(chunks) // 3


def test_mutate_changes_and_stays_decodable():
    cfg = Config(length=10, mode=MODE_LONG64)
    rng = random.Random(3)
    text = generate(cfg, rng)
    seen_change = False
    for _ in range(10):
        m = mutate(cfg, text, rng)
        assert m  # never empty
        seen_change |= m != text
    assert seen_change


def test_mutate_empty_generates():
    cfg = Config(mode=MODE_LONG64)
    assert mutate(cfg, b"", random.Random(4))


def test_decode_garbage():
    cfg = Config(mode=MODE_LONG64)
    assert decode(cfg, b"\x06") == -1  # push es is illegal in long mode


def test_table_rows_export():
    tmpl, lens, ioff, isz = ifuzz.table_rows(Config(mode=MODE_LONG64))
    assert tmpl.shape[0] == len(lens) > 50
    assert tmpl.shape[1] == 16
    for i in range(len(lens)):
        assert 1 <= lens[i] <= 16
        if isz[i]:
            assert ioff[i] + isz[i] <= lens[i]


def test_device_textgen():
    jax = pytest.importorskip("jax")
    from syzkaller_tpu.ops.textgen import generate_text_batch, get_text_tables

    tt = get_text_tables(MODE_LONG64)
    key = jax.random.PRNGKey(0)
    arenas, lens = generate_text_batch(key, tt, B=16, n_insns=6, cap=128)
    assert arenas.shape == (16, 128) and lens.shape == (16,)
    import numpy as np

    lens = np.asarray(lens)
    arenas = np.asarray(arenas)
    cfg = Config(mode=MODE_LONG64)
    assert (lens > 0).all()
    # each lane's stream must split into >= 2 table-decodable insns
    ok = 0
    for b in range(16):
        chunks = split(cfg, bytes(arenas[b, :lens[b]]))
        ok += sum(len(c) > 1 for c in chunks) >= 2
    assert ok >= 12
