"""Crash parsing tests: realistic console outputs -> expected titles
(the reference's largest test surface, pkg/report/report_test.go)."""

from syzkaller_tpu.report import contains_crash, extract_guilty_file, parse

KASAN_UAF = """\
[   44.226361] ==================================================================
[   44.226845] BUG: KASAN: use-after-free in ip6_send_skb+0x2f5/0x330
[   44.227340] Read of size 8 at addr ffff8800398d4780 by task syz-executor/4447
[   44.227904]
[   44.228041] CPU: 0 PID: 4447 Comm: syz-executor Not tainted 4.11.0 #1
[   44.228588] Call Trace:
[   44.228816]  dump_stack+0x115/0x1cf
[   44.229129]  kasan_report+0x171/0x1b0
[   44.229453]  ip6_send_skb+0x2f5/0x330 net/ipv6/ip6_output.c:1720
[   44.229918]  udpv6_sendmsg+0x1dcb/0x2400
"""

GPF = """\
[   30.111] general protection fault: 0000 [#1] SMP KASAN
[   30.112] Modules linked in:
[   30.113] CPU: 1 PID: 1325 Comm: syz-executor Not tainted 4.11.0 #1
[   30.114] task: ffff880038e72f00 task.stack: ffff88003b6a0000
[   30.115] RIP: 0010:sock_sendmsg+0xb2/0x110
[   30.116] RSP: 0018:ffff88003b6a7b58 EFLAGS: 00010206
"""

TASK_HUNG = """\
[  246.6] INFO: task syz-executor:5068 blocked for more than 120 seconds.
[  246.7]       Not tainted 4.11.0 #1
[  246.8] "echo 0 > /proc/sys/kernel/hung_task_timeout_secs" disables this message.
"""

PANIC = """\
[   10.0] Kernel panic - not syncing: Fatal exception in interrupt
[   10.1] Kernel Offset: disabled
"""

WARNING_AT = """\
[   12.3] WARNING: CPU: 0 PID: 3654 at kernel/events/core.c:10336 perf_event_open+0x2d0/0x1bc0
[   12.4] Kernel panic - not syncing: panic_on_warn set ...
"""

DEADLOCK = """\
[   87.0] ======================================================
[   87.1] WARNING: possible circular locking dependency detected
[   87.2] 4.11.0 #1 Not tainted
[   87.3] ------------------------------------------------------
[   87.4] syz-executor/5068 is trying to acquire lock:
[   87.5]  (&pipe->mutex/1){+.+.+.}, at: [<ffffffff8190c049>] pipe_lock+0x59/0x70
"""

KMEMLEAK = """\
unreferenced object 0xffff88003b7cd580 (size 64):
  comm "syz-executor", pid 4821, jiffies 4294945155 (age 13.690s)
  hex dump (first 32 bytes):
    00 00 00 00 00 00 00 00 00 00 00 00 00 00 00 00  ................
  backtrace:
    [<ffffffff8152b458>] kmemleak_alloc+0x28/0x50
    [<ffffffff814f5163>] kmem_cache_alloc_trace+0x113/0x2f0
    [<ffffffff8182d0d2>] sock_alloc_inode+0x52/0x120
"""

KERNEL_BUG_AT = """\
[   55.1] kernel BUG at net/packet/af_packet.c:3651!
[   55.2] invalid opcode: 0000 [#1] SMP KASAN
"""


def test_kasan_title():
    r = parse(KASAN_UAF)
    assert r is not None
    assert r.title == "KASAN: use-after-free Read in ip6_send_skb"
    assert not r.corrupted


def test_gpf_title():
    r = parse(GPF)
    assert r.title == "general protection fault in sock_sendmsg"


def test_task_hung():
    assert parse(TASK_HUNG).title == "INFO: task hung"


def test_panic():
    assert parse(PANIC).title == \
        "kernel panic: Fatal exception in interrupt"


def test_warning_at():
    assert parse(WARNING_AT).title == "WARNING in perf_event_open"


def test_deadlock():
    assert parse(DEADLOCK).title == "possible deadlock in pipe_lock"


def test_kmemleak():
    r = parse(KMEMLEAK)
    assert r.title == "memory leak in sock_alloc_inode (size 64)"


def test_kernel_bug_at():
    assert parse(KERNEL_BUG_AT).title == \
        "kernel BUG at net/packet/af_packet.c:3651"


def test_no_crash():
    out = "[  1.0] systemd[1]: Started Session 1 of user root.\n" * 50
    assert parse(out) is None
    assert not contains_crash(out)


def test_contains_crash_hot_predicate():
    assert contains_crash(KASAN_UAF)
    assert contains_crash("x\n" * 1000 + GPF)


def test_suppressions():
    out = "[  1.0] WARNING: /etc/ssh/moduli does not exist, using fixed modulus\n"
    assert not contains_crash(out)


def test_custom_ignores():
    assert contains_crash(TASK_HUNG)
    assert not contains_crash(TASK_HUNG, ignores=[r"INFO: task .* blocked"])


def test_first_crash_wins():
    r = parse(TASK_HUNG + KASAN_UAF)
    assert r.title == "INFO: task hung"


def test_title_deduplicates():
    # same crash from two runs with different addresses/pids -> same title
    variant = KASAN_UAF.replace("4447", "9999").replace(
        "ffff8800398d4780", "ffff88003b7cd580")
    assert parse(KASAN_UAF).title == parse(variant).title


def test_guilty_file():
    r = parse(KASAN_UAF)
    assert extract_guilty_file(r.report) == "net/ipv6/ip6_output.c"
