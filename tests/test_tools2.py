"""Tests for the second wave of tool equivalents: syz-repro, syz-crush,
syz-upgrade, syz-headerparser, syz-tty, kcovtrace."""

import os
import subprocess
import sys

import pytest

from syzkaller_tpu.prog import get_target
from syzkaller_tpu.prog.encoding import deserialize, serialize
from syzkaller_tpu.prog.generation import generate


@pytest.fixture(scope="module")
def target():
    return get_target("linux", "amd64")


def test_repro_tool_mock(tmp_path, target):
    from syzkaller_tpu.tools import repro as tool

    progs = [generate(target, s, 4) for s in range(3)]
    log = "\n\n".join(serialize(p) for p in progs)
    lp = tmp_path / "crash.log"
    lp.write_text(log)
    out = tmp_path / "repro.prog"
    rc = tool.main([str(lp), "--mock", "--out", str(out),
                    "--cout", str(tmp_path / "repro.c")])
    assert rc == 0
    text = out.read_text()
    p = deserialize(target, text)
    assert p.calls  # minimized reproducer parses


def test_upgrade_tool(tmp_path, target):
    from syzkaller_tpu.tools.upgrade import upgrade_dir

    good = serialize(generate(target, 1, 4))
    (tmp_path / "good").write_text(good)
    # a program mixing known + unknown calls: unknown lines dropped
    (tmp_path / "mixed").write_text(
        "nonexistent_call$future(0x0)\nclose(0xffffffffffffffff)\n")
    (tmp_path / "garbage").write_text("!!! not a program !!!")
    stats = upgrade_dir(target, str(tmp_path))
    assert stats["dropped"] == 1
    assert not (tmp_path / "garbage").exists()
    fixed = (tmp_path / "mixed").read_text()
    assert "nonexistent_call" not in fixed and "close" in fixed
    # idempotent second run
    stats2 = upgrade_dir(target, str(tmp_path))
    assert stats2 == {"ok": 2, "fixed": 0, "dropped": 0}


def test_headerparser():
    from syzkaller_tpu.tools.headerparser import (
        emit_descriptions,
        parse_defines,
        parse_structs,
    )

    hdr = """
/* a uapi-looking header */
#define FOO_READ 0x1
#define FOO_WRITE 0x2
#define FOO_MAGIC 0xabcd

struct foo_req {
    __u32 cmd;
    __u16 flags : 4;
    __u16 pad : 12;
    __u64 addr;
    char name[32];
    void *buf;
    __u32 buf_len;
};
"""
    structs = parse_structs(hdr)
    assert len(structs) == 1
    name, fields = structs[0]
    assert name == "foo_req" and len(fields) == 7
    defines = parse_defines(hdr)
    assert defines["FOO_READ"] == "0x1"
    out = emit_descriptions(hdr)
    assert "foo_req {" in out
    assert "cmd\tint32" in out
    assert "flags\tint16:4" in out
    assert "array[int8, 32]" in out
    assert "ptr[in, TODO]" in out
    assert "foo_flags = FOO_MAGIC, FOO_READ, FOO_WRITE" in out


def test_crush_mock(tmp_path, target):
    """crush over the local VM backend with a stubbed tester module."""
    from syzkaller_tpu.tools.crush import crush
    from syzkaller_tpu.report import Report
    from syzkaller_tpu.vm import VMConfig, create

    progs = [generate(target, s, 3) for s in range(2)]
    log = "\n\n".join(serialize(p) for p in progs)

    class StubRepro:
        class VMTester:
            def __init__(self, pool, instance_indexes=(0,)):
                self.idx = instance_indexes[0]

            def test_progs(self, progs, opts, duration):
                # instance 0 "crashes", instance 1 doesn't
                if self.idx == 0:
                    return Report(title="stub crash")
                return None

    pool = create(VMConfig(type="local", count=2))
    titles = crush(target, pool, log, instances=2, duration=1.0,
                   repro_mod=StubRepro)
    assert titles == {"stub crash": 1}


def test_kcovtrace_compiles(tmp_path):
    src = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "syzkaller_tpu", "tools",
        "kcovtrace.c")
    out = tmp_path / "kcovtrace"
    subprocess.run(["gcc", "-O2", "-o", str(out), src], check=True,
                   capture_output=True)
    assert out.exists()
    # no args -> usage on stderr, exit 1 (no kcov needed for this path)
    r = subprocess.run([str(out)], capture_output=True, text=True)
    assert r.returncode == 1 and "usage" in r.stderr


def test_tty_console_config():
    """open_console on a pty: raw-mode attrs actually applied."""
    import termios

    from syzkaller_tpu.tools.tty import open_console

    master, slave = os.openpty()
    try:
        path = os.ttyname(slave)
        fd = open_console(path)
        attrs = termios.tcgetattr(fd)
        assert attrs[3] == 0  # lflag: fully raw (no echo/canon)
        assert attrs[2] & termios.CS8
        os.close(fd)
    finally:
        os.close(master)
        os.close(slave)
