"""Pallas coverage-kernel parity tests.

Run through the pallas interpreter on the CPU test backend (conftest
forces JAX_PLATFORMS=cpu); on a real TPU the same code path compiles the
kernels natively.  Semantics are checked against the exact jnp
implementations in ops/cover.py.
"""

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

from syzkaller_tpu.ops import cover, pallas_cover  # noqa: E402


def rand_bits(rng, n, l):
    return rng.integers(0, 1 << 32, size=(n, l), dtype=np.uint32)


def test_minimize_matches_jnp():
    rng = np.random.default_rng(0)
    # sparse-ish sets so the greedy pass has real structure
    bits = rand_bits(rng, 12, 256) & rand_bits(rng, 12, 256) \
        & rand_bits(rng, 12, 256)
    keep_pl = np.asarray(pallas_cover.minimize_corpus(bits))
    keep_jnp = np.asarray(cover.minimize_corpus(jnp.asarray(bits)))
    np.testing.assert_array_equal(keep_pl, keep_jnp)


def test_minimize_covers_everything():
    rng = np.random.default_rng(1)
    bits = rand_bits(rng, 10, 128) & rand_bits(rng, 10, 128)
    keep = np.asarray(pallas_cover.minimize_corpus(bits))
    all_bits = np.bitwise_or.reduce(bits, axis=0)
    kept_bits = np.bitwise_or.reduce(bits[keep], axis=0) if keep.any() \
        else np.zeros_like(all_bits)
    np.testing.assert_array_equal(kept_bits, all_bits)


def test_minimize_drops_duplicates():
    rng = np.random.default_rng(2)
    row = rand_bits(rng, 1, 128)
    bits = np.repeat(row, 5, axis=0)
    keep = np.asarray(pallas_cover.minimize_corpus(bits))
    assert keep.sum() == 1


def test_large_fallback_matches():
    """Above MAX_VMEM_WORDS the dispatcher must fall back, same
    semantics, and count the fallback."""
    rng = np.random.default_rng(5)
    bits = rand_bits(rng, 3, 64)
    from syzkaller_tpu.telemetry import get_registry

    before = get_registry().snapshot()
    old = pallas_cover.MAX_VMEM_WORDS
    try:
        pallas_cover.MAX_VMEM_WORDS = 16  # force fallback
        keep_fb = np.asarray(pallas_cover.minimize_corpus(bits))
    finally:
        pallas_cover.MAX_VMEM_WORDS = old
    keep_jnp = np.asarray(cover.minimize_corpus(jnp.asarray(bits)))
    np.testing.assert_array_equal(keep_fb, keep_jnp)
    delta = get_registry().delta(before)
    assert delta.get("pallas_cover_fallback_total", 0) >= 1


# ---- fused merge + new-signal kernel (ISSUE 8) ----


SENT = 0xFFFFFFFF


def _host(acc, sigs, update=False):
    return cover.merge_and_new_host(acc, sigs, update=update)


def _assert_all_parity(acc, sigs):
    """The pallas kernel, the XLA core, and the numpy host mirror must
    be bit-identical on (counts, mask, merged)."""
    hc, hm, hmerged = _host(acc.copy(), sigs, update=True)
    pc, pm, pmerged = pallas_cover.merge_and_new_pallas(acc, sigs)
    xc, xm, xmerged = cover._merge_and_new_xla(acc, sigs)
    for c, m, mg in ((pc, pm, pmerged), (xc, xm, xmerged)):
        np.testing.assert_array_equal(np.asarray(c), hc)
        np.testing.assert_array_equal(np.asarray(m), hm)
        np.testing.assert_array_equal(np.asarray(mg), hmerged)
    return hc


def test_fused_merge_parity_random():
    rng = np.random.default_rng(10)
    acc = rand_bits(rng, 1, 256)[0] & rand_bits(rng, 1, 256)[0]
    sigs = rand_bits(rng, 9, 13)
    sigs[rng.random(sigs.shape) < 0.25] = SENT
    _assert_all_parity(acc, sigs)


def test_fused_merge_empty_batch():
    acc = np.zeros(64, np.uint32)
    counts, mask, merged = cover.merge_and_new(
        acc, np.zeros((0, 8), np.uint32))
    assert np.asarray(counts).shape == (0,)
    assert np.asarray(mask).shape == (0,)
    np.testing.assert_array_equal(np.asarray(merged), acc)
    hc, hm, hacc = _host(acc.copy(), np.zeros((0, 8), np.uint32))
    assert hc.shape == (0,) and hm.shape == (0,)


def test_fused_merge_duplicate_rows():
    """A bit claimed by an earlier row never counts again — duplicate
    rows after the first report zero new bits (sequential-prefix
    semantics), and in-row duplicate values count once."""
    rng = np.random.default_rng(11)
    acc = np.zeros(128, np.uint32)
    row = rand_bits(rng, 1, 6)
    sigs = np.repeat(row, 4, axis=0)
    sigs = np.concatenate([sigs, np.full((1, 6), row[0, 0], np.uint32)])
    counts = _assert_all_parity(acc, sigs)
    assert counts[0] > 0
    assert not counts[1:].any()


def test_fused_merge_all_novel_and_all_known():
    rng = np.random.default_rng(12)
    sigs = rand_bits(rng, 6, 8)
    empty = np.zeros(1 << 12, np.uint32)
    counts = _assert_all_parity(empty, sigs)
    assert (counts > 0).all()  # all-novel vs an empty accumulator
    # fold them in, then the same batch is all-known
    _, _, full = _host(empty.copy(), sigs, update=True)
    counts2 = _assert_all_parity(full, sigs)
    assert not counts2.any()


def test_fused_merge_nontile_aligned_nwords():
    """L neither a multiple of 128 lanes nor a power of two exercises
    the tile padding AND the non-pow2 (nbits-1) index mask — all three
    implementations must agree bit-for-bit anyway."""
    rng = np.random.default_rng(13)
    acc = rand_bits(rng, 1, 100)[0] & rand_bits(rng, 1, 100)[0]
    sigs = rand_bits(rng, 5, 7)
    sigs[0, 3:] = SENT
    merged = _host(acc.copy(), sigs, update=True)[2]
    assert merged.shape == (100,)
    _assert_all_parity(acc, sigs)


def test_fused_merge_counts_match_sequential_scan():
    """The fused popcount-delta verdicts equal folding the rows one at
    a time with signal_new/signal_add — the exactness claim that lets
    the engine replace its sequential scan."""
    rng = np.random.default_rng(14)
    acc = np.zeros(256, np.uint32)
    sigs = rand_bits(rng, 10, 5)
    counts, mask, merged = _host(acc.copy(), sigs, update=True)
    bits = jnp.asarray(np.zeros(256, np.uint32))
    seq_mask = []
    for row in sigs:
        seq_mask.append(bool(cover.signal_new(bits, jnp.asarray(row))))
        bits = cover.signal_add(bits, jnp.asarray(row))
    assert list(mask) == seq_mask
    np.testing.assert_array_equal(np.asarray(bits), merged)


def test_signal_stats_retired():
    """The dead dense-input kernel is GONE (ISSUE 8 satellite): the
    fused entry is its wired replacement."""
    assert not hasattr(pallas_cover, "signal_stats")


# ---- measured-crossover dispatch ----


def test_dispatch_probe_measures_once_and_caches(monkeypatch):
    """Off the interpreter, the first dispatch per (op, size-bucket)
    times BOTH paths (after a warm-up each) and caches the winner; a
    losing pallas path counts the fallback metric on every dispatch."""
    from syzkaller_tpu.telemetry import get_registry

    monkeypatch.setattr(pallas_cover, "_INTERPRET", False)
    monkeypatch.setattr(pallas_cover, "_platform", lambda: "tpu")
    pallas_cover.crossover_reset()
    calls = {"pallas": 0, "xla": 0}

    def slow_pallas():
        calls["pallas"] += 1
        import time as _t

        _t.sleep(0.01)
        return "pallas"

    def fast_xla():
        calls["xla"] += 1
        return "xla"

    before = get_registry().snapshot()
    out = pallas_cover.dispatch("t", 64, 4, slow_pallas, fast_xla)
    assert out == "xla"  # probe measured pallas slower
    assert calls == {"pallas": 2, "xla": 2}  # warm-up + timed, each
    out = pallas_cover.dispatch("t", 64, 4, slow_pallas, fast_xla)
    assert out == "xla" and calls["pallas"] == 2  # cached: no re-probe
    delta = get_registry().delta(before)
    assert delta.get("pallas_cover_fallback_total", 0) == 2
    pallas_cover.crossover_reset()


def test_dispatch_interpret_always_pallas(monkeypatch):
    """Under the test interpreter the kernel path always runs — the
    interpreter exists to exercise kernel logic, not to win races."""
    assert pallas_cover._INTERPRET
    pallas_cover.crossover_reset()
    out = pallas_cover.dispatch("t", 64, 4, lambda: "pallas",
                                lambda: "xla")
    assert out == "pallas"
