"""Pallas coverage-kernel parity tests.

Run through the pallas interpreter on the CPU test backend (conftest
forces JAX_PLATFORMS=cpu); on a real TPU the same code path compiles the
kernels natively.  Semantics are checked against the exact jnp
implementations in ops/cover.py.
"""

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

from syzkaller_tpu.ops import cover, pallas_cover  # noqa: E402


def rand_bits(rng, n, l):
    return rng.integers(0, 1 << 32, size=(n, l), dtype=np.uint32)


def test_minimize_matches_jnp():
    rng = np.random.default_rng(0)
    # sparse-ish sets so the greedy pass has real structure
    bits = rand_bits(rng, 12, 256) & rand_bits(rng, 12, 256) \
        & rand_bits(rng, 12, 256)
    keep_pl = np.asarray(pallas_cover.minimize_corpus(bits))
    keep_jnp = np.asarray(cover.minimize_corpus(jnp.asarray(bits)))
    np.testing.assert_array_equal(keep_pl, keep_jnp)


def test_minimize_covers_everything():
    rng = np.random.default_rng(1)
    bits = rand_bits(rng, 10, 128) & rand_bits(rng, 10, 128)
    keep = np.asarray(pallas_cover.minimize_corpus(bits))
    all_bits = np.bitwise_or.reduce(bits, axis=0)
    kept_bits = np.bitwise_or.reduce(bits[keep], axis=0) if keep.any() \
        else np.zeros_like(all_bits)
    np.testing.assert_array_equal(kept_bits, all_bits)


def test_minimize_drops_duplicates():
    rng = np.random.default_rng(2)
    row = rand_bits(rng, 1, 128)
    bits = np.repeat(row, 5, axis=0)
    keep = np.asarray(pallas_cover.minimize_corpus(bits))
    assert keep.sum() == 1


def test_signal_stats_matches_jnp():
    rng = np.random.default_rng(3)
    acc = rand_bits(rng, 1, 384)[0] & rand_bits(rng, 1, 384)[0]
    progs = rand_bits(rng, 7, 384) & rand_bits(rng, 7, 384)
    counts, merged = pallas_cover.signal_stats(acc, progs)
    counts, merged = np.asarray(counts), np.asarray(merged)
    exp_fresh = progs & ~acc[None, :]
    exp_counts = np.array(
        [bin(int.from_bytes(r.tobytes(), "little")).count("1")
         for r in exp_fresh])
    np.testing.assert_array_equal(counts, exp_counts)
    np.testing.assert_array_equal(
        merged, acc | np.bitwise_or.reduce(progs, axis=0))


def test_signal_stats_nonaligned_length():
    """L not a multiple of 1024 exercises the tile padding path."""
    rng = np.random.default_rng(4)
    acc = rand_bits(rng, 1, 100)[0]
    progs = rand_bits(rng, 3, 100)
    counts, merged = pallas_cover.signal_stats(acc, progs)
    assert merged.shape == (100,)
    exp_fresh = progs & ~acc[None, :]
    exp_counts = np.array(
        [bin(int.from_bytes(r.tobytes(), "little")).count("1")
         for r in exp_fresh])
    np.testing.assert_array_equal(np.asarray(counts), exp_counts)


def test_large_fallback_matches():
    """Above MAX_VMEM_WORDS the wrapper must fall back, same semantics."""
    rng = np.random.default_rng(5)
    bits = rand_bits(rng, 3, 64)
    old = pallas_cover.MAX_VMEM_WORDS
    try:
        pallas_cover.MAX_VMEM_WORDS = 16  # force fallback
        keep_fb = np.asarray(pallas_cover.minimize_corpus(bits))
    finally:
        pallas_cover.MAX_VMEM_WORDS = old
    keep_jnp = np.asarray(cover.minimize_corpus(jnp.asarray(bits)))
    np.testing.assert_array_equal(keep_fb, keep_jnp)
