"""Whole-corpus liveness check against the real kernel.

Every linux/amd64 syscall variant is executed once through the real C++
executor; a call answered with ENOSYS means its syscall number is wrong
(bad __NR_* const, broken pseudo-call dispatch) — precisely the class of
corpus bug nothing else catches, since generation/serialization tests
never reach the kernel.  Any other errno (EBADF/EINVAL/EPERM/...) is a
legitimate answer for type-correct-but-unresourced arguments.

Slow-ish (one pass over ~900 variants, batched); marked for the tail of
the suite via its filename ordering.
"""

import errno
import os

import pytest

from syzkaller_tpu.ipc import Env, ExecOpts
from syzkaller_tpu.prog import get_target
from syzkaller_tpu.prog.generation import RandGen
from syzkaller_tpu.prog.analysis import analyze
from syzkaller_tpu.prog.prog import Prog

# Calls that legitimately block (the parent kills the child on timeout and
# the batch's remaining calls go unexecuted) or that reconfigure the host
# (VT switching) are exercised elsewhere; skip them here so the sweep
# stays fast and self-contained.
SKIP = {
    "pause", "waitid", "wait4", "rt_sigtimedwait", "epoll_pwait",
    "epoll_wait", "ppoll", "pselect6", "select", "poll", "read", "readv",
    "recvfrom", "recvmsg", "accept", "accept4", "msgrcv", "semop",
    "semtimedop", "flock", "fcntl", "ioctl$VT_WAITACTIVE",
    "ioctl$VT_ACTIVATE", "ioctl$NBD_DO_IT", "io_getevents", "syz_mmap",
    "ioctl$KDMKTONE", "ioctl$KIOCSOUND", "ioctl$TIOCSTI",
}


def test_every_variant_reaches_the_kernel(tmp_path):
    target = get_target("linux", "amd64")
    rng = RandGen(target, seed=1234)
    cwd = os.getcwd()
    os.chdir(tmp_path)
    enosys = []
    executed = 0
    try:
        with Env(target, pid=0) as env:
            batch = []
            metas = [m for m in target.syscalls
                     if m.name not in SKIP and m.call_name not in SKIP]
            for i, meta in enumerate(metas):
                batch.append(meta)
                if len(batch) < 8 and i != len(metas) - 1:
                    continue
                p = Prog(target)
                s = analyze(None, p, None)
                names = []
                for m in batch:
                    for c in rng.generate_particular_call(s, m):
                        p.calls.append(c)
                        names.append(c.meta.name)
                batch = []
                opts = ExecOpts(timeout_ms=3000)
                _, infos, failed, hanged = env.exec(opts, p)
                if failed or hanged:
                    continue  # a mid-batch blocking call; NRs still fine
                for c, info in zip(p.calls, infos):
                    executed += 1
                    if info.errno == errno.ENOSYS and \
                            not c.meta.call_name.startswith("syz_"):
                        enosys.append(c.meta.name)
    finally:
        os.chdir(cwd)
    # A handful of surfaces may genuinely be compiled out of this test
    # kernel; wrong NRs would show up as a broad scatter, so bound the
    # count rather than requiring zero.
    assert executed > 400, f"too few calls executed ({executed})"
    assert len(enosys) <= 12, f"ENOSYS from: {sorted(set(enosys))}"
