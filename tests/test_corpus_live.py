"""Whole-corpus liveness check against the real kernel.

Every linux/amd64 syscall variant is executed once through the real C++
executor; a call answered with ENOSYS means its syscall number is wrong
(bad __NR_* const, broken pseudo-call dispatch) — precisely the class of
corpus bug nothing else catches, since generation/serialization tests
never reach the kernel.  Any other errno (EBADF/EINVAL/EPERM/...) is a
legitimate answer for type-correct-but-unresourced arguments.

Slow-ish (one pass over ~900 variants, batched); marked for the tail of
the suite via its filename ordering.
"""

import ctypes
import errno
import functools
import os

import pytest

from syzkaller_tpu.ipc import Env, ExecOpts
from syzkaller_tpu.prog import get_target
from syzkaller_tpu.prog.generation import RandGen
from syzkaller_tpu.prog.analysis import analyze
from syzkaller_tpu.prog.prog import Prog

# Calls that legitimately block (the parent kills the child on timeout and
# the batch's remaining calls go unexecuted) or that reconfigure the host
# (VT switching) are exercised elsewhere; skip them here so the sweep
# stays fast and self-contained.
SKIP = {
    "pause", "waitid", "wait4", "rt_sigtimedwait", "epoll_pwait",
    "epoll_wait", "ppoll", "pselect6", "select", "poll", "read", "readv",
    "recvfrom", "recvmsg", "accept", "accept4", "msgrcv", "semop",
    "semtimedop", "flock", "fcntl", "ioctl$VT_WAITACTIVE",
    "ioctl$VT_ACTIVATE", "ioctl$NBD_DO_IT", "io_getevents", "syz_mmap",
    "ioctl$KDMKTONE", "ioctl$KIOCSOUND", "ioctl$TIOCSTI",
}


@functools.lru_cache(maxsize=None)
def _kernel_lacks_nr(nr: int) -> bool:
    """True iff the running kernel answers ENOSYS for raw syscall(nr) —
    sandboxed/partial-syscall-table hosts (gVisor-style: the kernel
    reports e.g. 4.4 but implements a curated subset, ENOSYS-ing even
    ancient calls like uselib/ustat) genuinely lack the call, and the
    sweep cannot validate an NR the kernel refuses to dispatch.  Only
    NRs the executor already saw ENOSYS for are probed, so the raw call
    never reaches argument handling."""
    try:
        libc = ctypes.CDLL(None, use_errno=True)
        ret = libc.syscall(ctypes.c_long(nr), 0, 0, 0, 0, 0, 0)
        return ret == -1 and ctypes.get_errno() == errno.ENOSYS
    except Exception:
        return False  # probe unavailable: keep the case as a failure


# The per-NR skip path is only trusted when the host demonstrably
# curates its syscall table: probing the ENOSYS'd NR from our own tables
# can't distinguish "host lacks it" from "our NR is wrong", and single
# sentinel syscalls are unreliable (uselib/sysfs are config-gated out of
# modern mainline kernels).  Breadth is the tell instead — a sandboxed
# partial table (gVisor-style) lacks dozens of distinct swept syscalls,
# while a wrong-NR regression in our tables touches a few and a full
# kernel's config gates (mq_*, keyctl, ...) stay within the existing
# <=12 bound.  Below this many probe-confirmed-missing distinct kernel
# NRs (not description variants — many variants share one NR), every
# ENOSYS stays a hard failure — the wrong-NR scatter guard keeps its
# teeth on real kernels.
_PARTIAL_TABLE_MIN_NRS = 16


def test_every_variant_reaches_the_kernel(tmp_path):
    target = get_target("linux", "amd64")
    rng = RandGen(target, seed=1234)
    nr_by_name = {m.name: m.nr for m in target.syscalls}
    cwd = os.getcwd()
    os.chdir(tmp_path)
    enosys = []
    executed = 0
    try:
        with Env(target, pid=0) as env:
            batch = []
            metas = [m for m in target.syscalls
                     if m.name not in SKIP and m.call_name not in SKIP]
            for i, meta in enumerate(metas):
                batch.append(meta)
                if len(batch) < 8 and i != len(metas) - 1:
                    continue
                p = Prog(target)
                s = analyze(None, p, None)
                names = []
                for m in batch:
                    for c in rng.generate_particular_call(s, m):
                        p.calls.append(c)
                        names.append(c.meta.name)
                batch = []
                opts = ExecOpts(timeout_ms=3000)
                _, infos, failed, hanged = env.exec(opts, p)
                if failed or hanged:
                    continue  # a mid-batch blocking call; NRs still fine
                for c, info in zip(p.calls, infos):
                    executed += 1
                    if info.errno == errno.ENOSYS and \
                            not c.meta.call_name.startswith("syz_"):
                        enosys.append(c.meta.name)
    finally:
        os.chdir(cwd)
    # A handful of surfaces may genuinely be compiled out of this test
    # kernel; wrong NRs would show up as a broad scatter, so bound the
    # count rather than requiring zero.  Cases whose syscall the host
    # kernel itself refuses with ENOSYS (partial syscall table) are
    # skipped rather than failed: on such hosts the sweep cannot tell a
    # wrong NR from a missing syscall, and the remaining supported calls
    # still validate the corpus.
    assert executed > 400, f"too few calls executed ({executed})"
    probed_missing = sorted({n for n in set(enosys)
                             if _kernel_lacks_nr(nr_by_name[n])})
    missing_nrs = {nr_by_name[n] for n in probed_missing}
    unsupported = probed_missing \
        if len(missing_nrs) >= _PARTIAL_TABLE_MIN_NRS else []
    unexplained = sorted(set(enosys) - set(unsupported))
    assert len(unexplained) <= 12, (
        f"ENOSYS from syscalls the host kernel implements: {unexplained} "
        f"(plus {len(unsupported)} skipped as host-unsupported)")
    if unsupported:
        pytest.skip(
            f"host kernel lacks {len(unsupported)} swept syscalls "
            f"(partial syscall table, e.g. {unsupported[:6]}); "
            f"{executed} calls on supported syscalls all reached the "
            f"kernel")
