"""Hub federation tests.

Covers what the reference exercises in syz-hub/state/state_test.go plus the
hermetic two-manager exchange the reference never had (SURVEY.md §4 calls
this gap out explicitly).
"""

import pytest

from syzkaller_tpu.hub import (
    Hub,
    HubClient,
    HubConfig,
    HubState,
    MAX_SYNC_RECORDS,
)
from syzkaller_tpu.manager import (
    Manager,
    ManagerConfig,
    PHASE_TRIAGED_CORPUS,
)
from syzkaller_tpu.manager.rpc import RpcError
from syzkaller_tpu.prog import get_target
from syzkaller_tpu.prog.encoding import serialize
from syzkaller_tpu.prog.generation import generate


@pytest.fixture(scope="module")
def target():
    return get_target("linux", "amd64")


@pytest.fixture(scope="module")
def progs(target):
    return [serialize(generate(target, seed, 4)) for seed in range(30)]


# --------------------------------------------------------------------- #
# HubState semantics


def test_state_connect_sync_roundtrip(tmp_path, progs):
    st = HubState(str(tmp_path))
    st.connect("a", fresh=True, calls=["open", "close", "read", "write",
                                       "mmap", "dup3", "socket"],
               corpus=[])
    st.connect("b", fresh=True, calls=["open", "close", "read", "write",
                                       "mmap", "dup3", "socket"],
               corpus=[])
    text = "open(&0:0:0=\"./file0\\x00\", 0x0, 0x0)\n"
    got, more = st.sync("a", add=[text], del_=[])
    assert got == [] and more == 0  # own input never comes back
    got, more = st.sync("b", add=[], del_=[])
    assert got == [text] and more == 0
    # second sync: no repeats
    got, more = st.sync("b", add=[], del_=[])
    assert got == []
    st.close()


def test_state_call_filtering(tmp_path):
    st = HubState(str(tmp_path))
    st.connect("a", fresh=True, calls=["open", "exotic_call"], corpus=[])
    st.connect("b", fresh=True, calls=["open"], corpus=[])
    st.sync("a", add=["exotic_call(0x0)\n", "open(0x0, 0x0, 0x0)\n"],
            del_=[])
    got, _ = st.sync("b", add=[], del_=[])
    # b doesn't support exotic_call -> only the open program crosses
    assert got == ["open(0x0, 0x0, 0x0)\n"]
    st.close()


def test_state_unconnected_rejected(tmp_path):
    st = HubState(str(tmp_path))
    with pytest.raises(RuntimeError):
        st.sync("ghost", add=[], del_=[])
    st.close()


def test_state_more_backpressure(tmp_path):
    st = HubState(str(tmp_path))
    st.connect("a", fresh=True, calls=["open"], corpus=[])
    st.connect("b", fresh=True, calls=["open"], corpus=[])
    n = MAX_SYNC_RECORDS + 50
    for i in range(n):
        # distinct single-call programs; one sync per add gives distinct seqs
        st.sync("a", add=[f"open(0x{i:x}, 0x0, 0x0)\n"], del_=[])
    got1, more1 = st.sync("b", add=[], del_=[])
    # pages are exactly MAX records when seqs are unique (group rounding
    # only extends through ties of the last included seq)
    assert len(got1) == MAX_SYNC_RECORDS
    assert more1 == n - len(got1)
    got2, more2 = st.sync("b", add=[], del_=[])
    assert len(got2) == more1 and more2 == 0
    assert len(set(got1) | set(got2)) == n
    st.close()


def test_state_delete_and_purge(tmp_path):
    st = HubState(str(tmp_path))
    st.connect("a", fresh=True, calls=["open"], corpus=[])
    text = "open(0x0, 0x0, 0x0)\n"
    st.sync("a", add=[text], del_=[])
    from syzkaller_tpu.utils.hash import hash_str

    sig = hash_str(text.encode())
    st.sync("a", add=[], del_=[sig])
    # no manager mirrors the program anymore -> purged from the hub corpus
    assert sig not in st.corpus
    st.close()


def test_state_persistence(tmp_path, progs):
    st = HubState(str(tmp_path))
    st.connect("a", fresh=True, calls=["open"], corpus=[])
    st.connect("b", fresh=True, calls=["open"], corpus=[])
    st.sync("a", add=["open(0x1, 0x0, 0x0)\n"], del_=[])
    st.close()
    # reload from disk: b (not fresh) must not re-receive what it already got
    st2 = HubState(str(tmp_path))
    st2.connect("b", fresh=False, calls=["open"], corpus=[])
    got, _ = st2.sync("b", add=[], del_=[])
    assert got == ["open(0x1, 0x0, 0x0)\n"]
    st2.close()
    st3 = HubState(str(tmp_path))
    st3.connect("b", fresh=False, calls=["open"], corpus=[])
    got, _ = st3.sync("b", add=[], del_=[])
    assert got == []
    st3.close()


def test_repro_exchange(tmp_path):
    st = HubState(str(tmp_path))
    st.connect("a", fresh=True, calls=["open"], corpus=[])
    st.connect("b", fresh=True, calls=["open"], corpus=[])
    st.add_repro("a", "open(0x0, 0x0, 0x0)\n")
    # originator never gets its own repro back
    assert st.pending_repro("a") is None
    assert st.pending_repro("b") == "open(0x0, 0x0, 0x0)\n"
    assert st.pending_repro("b") is None  # delivered once
    st.close()


# --------------------------------------------------------------------- #
# RPC service: auth + two managers federating end-to-end


def test_hub_rpc_auth(tmp_path):
    hub = Hub(HubConfig(workdir=str(tmp_path), clients={"mgr": "secret"}))
    hub.start()
    try:
        bad = HubClient(hub.addr, "mgr", "wrong")
        with pytest.raises(RpcError):
            bad.connect(fresh=True, calls=[], corpus=[])
        bad.close()
        ok = HubClient(hub.addr, "mgr", "secret")
        ok.connect(fresh=True, calls=["open"], corpus=[])
        progs, more, repros = ok.sync(add=["open(0x0, 0x0, 0x0)\n"])
        assert progs == [] and more == 0 and repros == []
        ok.close()
    finally:
        hub.stop()


def test_two_managers_federate(tmp_path, target, progs):
    hub = Hub(HubConfig(workdir=str(tmp_path / "hub"),
                        clients={"mgr-a": "ka", "mgr-b": "kb"}))
    hub.start()
    ma = mb = None
    try:
        ma = Manager(ManagerConfig(
            name="mgr-a", workdir=str(tmp_path / "a"),
            hub_addr=hub.addr, hub_key="ka"), target=target)
        mb = Manager(ManagerConfig(
            name="mgr-b", workdir=str(tmp_path / "b"),
            hub_addr=hub.addr, hub_key="kb"), target=target)
        # seed manager a's corpus as a fuzzer would (via new_input)
        for t in progs[:5]:
            ma.on_new_input("fuzz0", t, 0, [1, 2], [])
        ma.phase = PHASE_TRIAGED_CORPUS
        mb.phase = PHASE_TRIAGED_CORPUS
        assert ma.hub_sync_once() == 0
        got = mb.hub_sync_once()
        assert got == 5
        assert set(mb.candidates) == set(progs[:5])
        # b contributes one more; a receives exactly the delta
        mb.on_new_input("fuzz0", progs[10], 0, [3], [])
        assert mb.hub_sync_once() == 0
        assert ma.hub_sync_once() == 1
        assert progs[10] in ma.candidates
        assert ma.stats.get("hub_recv") == 1
    finally:
        if ma:
            ma.close()
        if mb:
            mb.close()
        hub.stop()
