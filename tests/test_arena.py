"""Device-resident corpus arena tests (ISSUE 3 tentpole): append/sample
parity with the old stack-and-put path, ring eviction bounds, arena_*
gauges, and the guard that the launch path stages no O(B) host batch."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from syzkaller_tpu.descriptions.tables import get_tables  # noqa: E402
from syzkaller_tpu.engine.fuzzer import Fuzzer, FuzzerConfig  # noqa: E402
from syzkaller_tpu.ops.arena import CorpusArena  # noqa: E402
from syzkaller_tpu.prog import get_target  # noqa: E402
from syzkaller_tpu.prog.generation import generate  # noqa: E402
from syzkaller_tpu.prog.tensor import (  # noqa: E402
    ProgBatch,
    TensorFormat,
    encode_prog,
)
from syzkaller_tpu.telemetry.metrics import Registry  # noqa: E402


@pytest.fixture(scope="module")
def env():
    target = get_target("linux", "amd64")
    tables = get_tables(target)
    fmt = TensorFormat.for_tables(tables, max_calls=8)
    return target, tables, fmt


def _encode_rows(target, tables, fmt, n, seed=0):
    """n encoded (cid, sval, data) triples, skipping codec long-tail."""
    rows = []
    while len(rows) < n:
        p = generate(target, seed, 6)
        seed += 1
        b = ProgBatch.empty(fmt, 1)
        try:
            encode_prog(tables, fmt, p, b, 0)
        except Exception:
            continue
        rows.append((b.call_id[0].copy(), b.slot_val[0].copy(),
                     b.data[0].copy()))
    return rows


def test_append_gather_matches_stack_and_put(env):
    """Round-trip parity: sampling the arena on device equals the old
    host np.stack-then-device_put path bit-for-bit."""
    target, tables, fmt = env
    rows = _encode_rows(target, tables, fmt, 12)
    arena = CorpusArena(16, fmt, registry=Registry())
    for cid, sval, data in rows:
        arena.append(cid, sval, data)
    assert arena.size == 12 and arena.evictions == 0

    idx = arena.sample_indices(np.random.default_rng(5), 32)
    assert idx is not None and idx.dtype == np.int32
    assert int(idx.max()) < 12 and int(idx.min()) >= 0
    g_cid, g_sval, g_data = (np.asarray(x) for x in arena.gather(idx))
    np.testing.assert_array_equal(
        g_cid, np.stack([rows[i][0] for i in idx]))
    np.testing.assert_array_equal(
        g_sval, np.stack([rows[i][1] for i in idx]))
    np.testing.assert_array_equal(
        g_data, np.stack([rows[i][2] for i in idx]))


def test_ring_eviction_bounds_capacity(env):
    """Long campaigns stay bounded: the ring overwrites the oldest rows
    and the eviction counter records every overwrite."""
    target, tables, fmt = env
    rows = _encode_rows(target, tables, fmt, 10)
    reg = Registry()
    arena = CorpusArena(4, fmt, registry=reg)
    for cid, sval, data in rows:
        arena.append(cid, sval, data)
    assert arena.size == 4
    assert arena.evictions == 6
    assert arena.cursor == 10 % 4
    assert reg.snapshot()["arena_evictions_total"] == 6
    # appends 0..9 land on slots 0,1,2,3,0,1,2,3,0,1 — the ring holds the
    # newest four, in wrap order
    a_cid, a_sval, a_data = (np.asarray(x) for x in arena.tensors())
    for slot, ridx in {0: 8, 1: 9, 2: 6, 3: 7}.items():
        np.testing.assert_array_equal(a_cid[slot], rows[ridx][0])
        np.testing.assert_array_equal(a_sval[slot], rows[ridx][1])
        np.testing.assert_array_equal(a_data[slot], rows[ridx][2])


def test_arena_gauges(env):
    target, tables, fmt = env
    reg = Registry()
    arena = CorpusArena(8, fmt, registry=reg)
    rows = _encode_rows(target, tables, fmt, 2)
    for cid, sval, data in rows:
        arena.append(cid, sval, data)
    snap = reg.snapshot()
    assert snap["arena_occupancy"] == pytest.approx(2 / 8)
    assert snap["arena_resident_bytes"] == arena.resident_bytes() > 0
    assert snap["arena_evictions_total"] == 0
    assert arena.sample_indices(np.random.default_rng(0), 4) is not None
    # an empty arena refuses to sample
    empty = CorpusArena(8, fmt, registry=Registry())
    assert empty.sample_indices(np.random.default_rng(0), 4) is None


def test_weighted_eviction_protects_credited_rows(env):
    """ISSUE 5: eviction prefers the lowest-yield row over FIFO — a
    credited seed survives a full ring while the uncredited one of the
    same age is overwritten, and the divergence is counted."""
    target, tables, fmt = env
    rows = _encode_rows(target, tables, fmt, 6)
    reg = Registry()
    arena = CorpusArena(4, fmt, registry=reg)
    for cid, sval, data in rows[:4]:
        arena.append(cid, sval, data)
    arena.credit(0, 10.0)  # the OLDEST row earns yield
    arena.append(*rows[4])
    # FIFO would have evicted row 0; weighted eviction spares it and
    # takes the lowest-yield oldest survivor (row 1) instead
    a_cid, _, _ = (np.asarray(x) for x in arena.tensors())
    np.testing.assert_array_equal(a_cid[0], rows[0][0])
    np.testing.assert_array_equal(a_cid[1], rows[4][0])
    assert arena.evictions == 1
    assert arena.weighted_evictions == 1
    assert reg.snapshot()["arena_weighted_evictions_total"] == 1
    # the credited row keeps being protected: the next eviction takes
    # the oldest zero-yield row (row 2) and still counts as a weighted
    # divergence because FIFO would again have picked row 0 (pure-FIFO
    # degradation with NO credit anywhere is pinned by
    # test_ring_eviction_bounds_capacity above)
    arena.append(*rows[5])
    a_cid, _, _ = (np.asarray(x) for x in arena.tensors())
    np.testing.assert_array_equal(a_cid[2], rows[5][0])
    assert arena.evictions == 2
    assert arena.weighted_evictions == 2


def test_weighted_sampling_prefers_credited_rows(env):
    """sample_indices draws from the cumulative-weight table: a heavily
    credited row dominates the draw, and the host weight mirror matches
    the device weight tensor bit-for-bit."""
    target, tables, fmt = env
    arena = CorpusArena(8, fmt, registry=Registry())
    for cid, sval, data in _encode_rows(target, tables, fmt, 4):
        arena.append(cid, sval, data)
    arena.credit(3, 1000.0)
    w = arena.host_weights()
    np.testing.assert_array_equal(w, np.asarray(arena.weights_tensor()))
    np.testing.assert_array_equal(w[:4], [1, 1, 1, 1001])
    assert (w[4:] == 0).all()  # dead rows can never be drawn
    idx = arena.sample_indices(np.random.default_rng(1), 400)
    assert idx is not None and idx.min() >= 0 and idx.max() < 4
    assert (idx == 3).mean() > 0.9
    # credit on a dead/out-of-range row is ignored, not an error
    arena.credit(7, 5.0)
    arena.credit(-1, 5.0)
    arena.credit(99, 5.0)
    np.testing.assert_array_equal(arena.host_weights(), w)


def test_credit_stamp_guards_eviction_races(env):
    """A credit carrying the age stamp of a row that was evicted and
    rewritten since the sample is DROPPED — yield earned by a dead seed
    never inflates the unrelated program now living in its slot."""
    target, tables, fmt = env
    rows = _encode_rows(target, tables, fmt, 3)
    arena = CorpusArena(2, fmt, registry=Registry())
    arena.append(*rows[0])
    arena.append(*rows[1])
    stamp0 = int(arena.age_stamps([0])[0])
    arena.append(*rows[2])  # evicts + rewrites row 0 (FIFO, no credit)
    arena.credit(0, 5.0, stamp=stamp0)  # stale stamp: dropped
    assert arena.yields[0] == 0.0
    arena.credit(0, 5.0, stamp=int(arena.age_stamps([0])[0]))
    assert arena.yields[0] == 5.0
    arena.credit(1, 3.0)  # stampless credit stays accepted (host paths)
    assert arena.yields[1] == 3.0


def test_weight_cap_bounds_starvation(env):
    from syzkaller_tpu.ops.arena import WEIGHT_CAP

    target, tables, fmt = env
    arena = CorpusArena(4, fmt, registry=Registry())
    arena.append(*_encode_rows(target, tables, fmt, 1)[0])
    arena.credit(0, 1e12)
    assert arena.host_weights()[0] == WEIGHT_CAP + 1
    np.testing.assert_array_equal(arena.host_weights(),
                                  np.asarray(arena.weights_tensor()))


def test_arena_restore_roundtrips_yield_state(env):
    """Checkpoint/resume restores yield scores bit-identically and
    re-projects the device weight tensor from them."""
    target, tables, fmt = env
    rows = _encode_rows(target, tables, fmt, 3)
    src = CorpusArena(4, fmt, registry=Registry())
    for cid, sval, data in rows:
        src.append(cid, sval, data)
    src.credit(1, 3.7)
    dst = CorpusArena(4, fmt, registry=Registry())
    dst.restore(*(np.asarray(x) for x in src.tensors()),
                size=src.size, cursor=src.cursor,
                evictions=src.evictions,
                weighted_evictions=src.weighted_evictions,
                yields=src.yields, ages=src.ages, seq=src._seq)
    np.testing.assert_array_equal(dst.yields, src.yields)
    np.testing.assert_array_equal(dst.ages, src.ages)
    np.testing.assert_array_equal(dst.host_weights(), src.host_weights())
    np.testing.assert_array_equal(np.asarray(dst.weights_tensor()),
                                  np.asarray(src.weights_tensor()))
    assert dst.weighted_evictions == src.weighted_evictions
    # appends continue with fresh sequence stamps after the restore
    dst.append(*rows[0])
    assert dst.ages[dst.cursor - 1] >= src._seq


def test_launch_path_has_no_host_stack(env, monkeypatch):
    """Guard (ISSUE 3 acceptance): the steady-state launch path is an
    O(B) device-side gather — no per-row host np.stack staging, and no
    encoded-corpus host list to re-stack from."""
    target, _, _ = env
    cfg = FuzzerConfig(mock=True, use_device=True, device_batch=8,
                       program_length=8, smash_mutations=1,
                       device_period=2, arena_capacity=32)
    with Fuzzer(target, cfg) as f:
        assert f._device is not None
        # the host-side encoded-corpus list is gone entirely
        assert not hasattr(f._device, "_corpus_encoded")
        for _ in range(200):
            f.step()
            if f._device.arena.size >= 1 and len(f.corpus) >= 1:
                break
        assert f._device.arena.size >= 1

        def boom(*a, **k):
            raise AssertionError(
                "np.stack on the launch path — O(B) host staging is back")

        monkeypatch.setattr(np, "stack", boom)
        before = f.stats["device_batches"]
        for _ in range(400):
            f.step()
            if f.stats["device_batches"] > before:
                break
        assert f.stats["device_batches"] > before


def test_yield_age_decay_unpins_jackpot_rows(env):
    """ISSUE 6 satellite (ROADMAP carried item): geometric age-decay of
    yield scores — applied on the admission-Bloom reset cadence — lets
    a fresher earner overtake an early-campaign jackpot row instead of
    the jackpot pinning the weighted sampler forever."""
    target, tables, fmt = env
    rows = _encode_rows(target, tables, fmt, 3)
    reg = Registry()
    arena = CorpusArena(4, fmt, registry=reg)
    for cid, sval, data in rows:
        arena.append(cid, sval, data)
    arena.credit(0, 100.0)  # early jackpot
    arena.credit(1, 10.0)   # steady fresher earner
    w = arena.host_weights()
    assert w[0] > w[1]
    for _ in range(3):
        arena.decay_yields(0.5)
    # decay is geometric and uniform: ordering is preserved...
    w = arena.host_weights()
    assert w[0] > w[1] > w[2]
    assert arena.yields[0] == pytest.approx(12.5)
    # ...so the jackpot only stays ahead while its lead outruns the
    # decay: a fresh credit smaller than the ORIGINAL jackpot now
    # flips the ordering (12.5 decayed vs 1.25 + 15)
    arena.credit(1, 15.0)
    w = arena.host_weights()
    assert w[1] > w[0]
    # the device weight tensor re-projected in lockstep with the host
    # mirror, and live/dead row structure survived
    np.testing.assert_array_equal(np.asarray(arena.weights),
                                  arena.host_weights())
    assert int(np.asarray(arena.weights)[3]) == 0  # dead row stays 0
    assert reg.snapshot()["arena_yield_decays_total"] == 3
    # guard band: factor 1.0 (a no-op pin) and junk are refused
    before = arena.yields.copy()
    arena.decay_yields(1.0)
    arena.decay_yields(-3.0)
    np.testing.assert_array_equal(arena.yields, before)
    assert reg.snapshot()["arena_yield_decays_total"] == 3


def test_engine_bloom_reset_triggers_yield_decay(env, monkeypatch):
    """The decay rides the existing occupancy-triggered Bloom reset in
    _DevicePipeline.candidates (one cadence, one knob)."""
    target, tables, fmt = env
    cfg = FuzzerConfig(mock=True, use_device=True, device_batch=8,
                       program_length=6, device_period=1,
                       smash_mutations=0, arena_yield_decay=0.25)
    with Fuzzer(target, cfg) as f:
        assert f._device is not None
        f._add_corpus(generate(target, 11, 4), ())
        arena = f._device.arena
        arena.credit(0, 40.0)
        # force the occupancy trigger: report the filter saturated
        f._device._bloom_bits = 1  # any popcount crosses the threshold
        y0 = float(arena.yields[0])
        decays0 = arena._c_yield_decays.value
        for _ in range(8):
            f.step()
            if arena._c_yield_decays.value > decays0:
                break
        assert arena._c_yield_decays.value > decays0
        assert float(arena.yields[0]) < y0
