"""VM-fleet scheduler tests: the full distributed stack, hermetically.

manager (RPC+HTTP) <- vmLoop -> local-backend "VM" -> real syz-fuzzer
subprocess -> real C++ executor.  The reference has no hermetic test of
this path (SURVEY.md §4 calls the gap out); the local VM backend closes
it.
"""

import os
import time

import pytest

from syzkaller_tpu.manager import Manager, ManagerConfig
from syzkaller_tpu.manager.vmloop import VMLoop, VMLoopConfig
from syzkaller_tpu.prog import get_target
from syzkaller_tpu.vm import VMConfig


@pytest.fixture(scope="module")
def target():
    return get_target("linux", "amd64")


def _wait(cond, timeout=90.0, period=0.5):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(period)
    return False


def test_end_to_end_local_fleet(tmp_path, target):
    """Boot a 1-instance local fleet; the real fuzzer subprocess must
    connect over RPC, execute via the real executor, and feed inputs
    back into the manager corpus."""
    mgr = Manager(ManagerConfig(
        workdir=str(tmp_path), vm=VMConfig(type="local", count=1)),
        target=target)
    loop = VMLoop(mgr, VMLoopConfig(procs=1))
    loop.start()
    try:
        assert _wait(lambda: mgr.connected_fuzzers), \
            "fuzzer never connected over RPC"
        assert _wait(lambda: len(mgr.corpus) >= 3), \
            f"corpus never grew (stats: {mgr.snapshot()})"
        snap = mgr.snapshot()
        assert snap["corpus"] >= 3
        assert snap.get("manager_new_inputs", 0) >= 3
    finally:
        loop.stop()
        loop.join()
        mgr.close()


def test_crash_detection_and_repro_scheduling(tmp_path, target,
                                              monkeypatch):
    """An instance whose console prints an oops must produce a saved
    crash and a queued+executed repro job."""
    mgr = Manager(ManagerConfig(
        workdir=str(tmp_path), vm=VMConfig(type="local", count=1)),
        target=target)

    crash_script = (
        "echo 'executing program 0:'; "
        "echo 'close(0xffffffffffffffff)'; "
        "echo ''; "
        "echo 'BUG: KASAN: use-after-free in fake_func+0x1/0x2'; "
        "echo 'Read of size 8 at addr ffff8801'; "
        "sleep 30")
    monkeypatch.setattr(VMLoop, "_fuzzer_cmd",
                        lambda self, addr="": crash_script.replace("{name}", "x"))

    # the repro tester would re-run programs in a VM; stub it to always
    # "reproduce" so the pipeline completes deterministically
    from syzkaller_tpu import repro as repro_mod
    from syzkaller_tpu.report import Report

    class StubTester:
        def __init__(self, *a, **k):
            pass

        def test_progs(self, progs, opts, duration):
            if any(p.calls for p in progs):
                return Report(title="KASAN: use-after-free in fake_func")
            return None

        def test_c_bin(self, bin_path, duration):
            return None

    monkeypatch.setattr(repro_mod, "VMTester", StubTester)

    loop = VMLoop(mgr, VMLoopConfig())
    loop.start()
    try:
        assert _wait(lambda: loop.crashes >= 1), "crash never detected"
        assert _wait(lambda: loop.repros_done >= 1), \
            f"repro never completed (stats {mgr.snapshot()})"
        title = "KASAN: use-after-free Read in fake_func"
        assert title in mgr.crashes
        from syzkaller_tpu.utils.hash import hash_str

        d = os.path.join(mgr.crashdir, hash_str(title.encode())[:16])
        assert os.path.exists(os.path.join(d, "repro.prog"))
        assert not mgr.need_repro(title)  # satisfied by the saved repro
    finally:
        loop.stop()
        loop.join()
        mgr.close()


def test_no_output_pseudo_crash(tmp_path, target, monkeypatch):
    """Silent instances produce the 'no output' pseudo-crash."""
    mgr = Manager(ManagerConfig(
        workdir=str(tmp_path), vm=VMConfig(type="local", count=1)),
        target=target)
    monkeypatch.setattr(VMLoop, "_fuzzer_cmd",
                        lambda self, addr="": "sleep 300")
    loop = VMLoop(mgr, VMLoopConfig())
    # tighten the silence threshold for the test
    orig = loop._run_instance

    def fast_run(idx):
        inst = loop.pool.create(idx)
        try:
            from syzkaller_tpu.vm import monitor_execution

            merger, proc = inst.run("sleep 300", timeout=60.0)
            res = monitor_execution(merger, proc, timeout=60.0,
                                    no_output_timeout=2.0,
                                    stop=loop.stop_ev)
            if res.no_output:
                from syzkaller_tpu.report import Report

                mgr.save_crash(Report(title="no output from test machine"),
                               res.output, idx)
                loop.crashes += 1
        finally:
            inst.close()

    monkeypatch.setattr(loop, "_run_instance", fast_run)
    loop.start()
    try:
        assert _wait(lambda: "no output from test machine" in mgr.crashes,
                     timeout=30.0)
    finally:
        loop.stop()
        loop.join()
        mgr.close()


def test_isolated_backend_target_parsing(monkeypatch):
    """isolated pool: target list parsing + per-index assignment (no
    actual ssh: the setup command is stubbed)."""
    import syzkaller_tpu.vm as vm_mod
    from syzkaller_tpu.vm import IsolatedInstance, VMConfig, create

    monkeypatch.setattr(IsolatedInstance, "_run_ssh",
                        lambda self, cmd, check=True: None)
    # no ssh binary in the test environment: skip the readiness probe
    monkeypatch.setattr(vm_mod, "_wait_ssh",
                        lambda target, port, key, what, timeout=0: None)
    pool = create(VMConfig(type="isolated",
                           targets=["root@h1", "fuzz@h2:2222"]))
    assert pool.count == 2
    i0 = pool.create(0)
    assert (i0.target, i0.ssh_port) == ("root@h1", 22)
    i1 = pool.create(1)
    assert (i1.target, i1.ssh_port) == ("fuzz@h2", 2222)
    # ssh argv shape
    base = i1._ssh_base()
    assert base[0] == "ssh" and "-p" in base and "2222" in base
    assert base[-1] == "fuzz@h2"
    i0.close()
    i1.close()


def test_manager_cli_config(tmp_path):
    """syz-manager CLI: strict config load rejects unknown fields."""
    import json
    import pytest as _pytest

    from syzkaller_tpu.manager import ManagerConfig
    from syzkaller_tpu.utils.config import load_file

    good = tmp_path / "good.json"
    good.write_text(json.dumps({
        "name": "m0", "workdir": str(tmp_path / "wd"),
        "vm": {"type": "local", "count": 2}}))
    cfg = load_file(ManagerConfig, str(good))
    assert cfg.vm.count == 2 and cfg.name == "m0"
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"name": "m0", "no_such_field": 1}))
    with _pytest.raises(Exception):
        load_file(ManagerConfig, str(bad))
