"""csource: prog → C reproducer generation, build, and execution; plus
prog/parse.py log extraction (reference pkg/csource + prog/parse.go)."""

import os
import subprocess

import pytest

from syzkaller_tpu import csource
from syzkaller_tpu.prog import get_target
from syzkaller_tpu.prog.encoding import deserialize, serialize
from syzkaller_tpu.prog.generation import RandGen, generate
from syzkaller_tpu.prog.parse import parse_log


TARGET = get_target("linux", "amd64")

SIMPLE = """\
r0 = open(&0:0:0=".\\x00", 0x0, 0x0)
read(r0, &1:0:0=zero(0x40), 0x40)
close(r0)
"""


def _prog(text=SIMPLE):
    return deserialize(TARGET, text)


def test_write_basic():
    src = csource.write(_prog(), csource.Options(handle_segv=False,
                                                 use_tmp_dir=False))
    assert "syscall(" in src
    assert "int main(void)" in src
    assert "mmap((void*)0x20000000" in src
    # result dataflow: read/close use open's fd via r[]
    assert "r[" in src


@pytest.mark.parametrize("opts", [
    csource.Options(),
    csource.Options(threaded=True),
    csource.Options(threaded=True, collide=True),
    csource.Options(repeat=False, procs=2),
    csource.Options(sandbox="none"),
    csource.Options(sandbox="setuid"),
    csource.Options(fault=True, fault_call=1, fault_nth=3),
    csource.Options(handle_segv=False, use_tmp_dir=False),
])
def test_option_matrix_compiles(opts):
    src = csource.write(_prog(), opts)
    bin_path = csource.build(src)
    try:
        assert os.path.exists(bin_path)
    finally:
        os.unlink(bin_path)


def test_reproducer_runs():
    # non-repeat, non-threaded reproducer of open(".")/read/close must
    # run to completion with exit status 0
    opts = csource.Options(use_tmp_dir=False, handle_segv=True)
    src = csource.write(_prog(), opts)
    bin_path = csource.build(src)
    try:
        r = subprocess.run([bin_path], timeout=30, capture_output=True)
        assert r.returncode == 0, r.stderr
    finally:
        os.unlink(bin_path)


def test_threaded_reproducer_runs():
    opts = csource.Options(threaded=True, collide=True, use_tmp_dir=False)
    src = csource.write(_prog(), opts)
    bin_path = csource.build(src)
    try:
        # the threaded runner's per-call completion waits are wall-clock
        # (reference executor.h:268) and this box has one core: retry once
        # if a parallel test starved the first run
        for attempt in range(2):
            r = subprocess.run([bin_path], timeout=60, capture_output=True)
            if r.returncode == 0:
                break
        assert r.returncode == 0, r.stderr
    finally:
        os.unlink(bin_path)


def test_random_progs_compile():
    rng = RandGen(TARGET, seed=7)
    for i in range(10):
        p = generate(TARGET, rng, 6)
        src = csource.write(p, csource.Options())
        bin_path = csource.build(src)
        os.unlink(bin_path)


def test_parse_log_roundtrip():
    p = _prog()
    text = serialize(p)
    log = (
        "2026/07/29 10:00:00 [0] booting\n"
        "2026/07/29 10:00:01 [0] executing program 3:\n"
        f"{text}\n"
        "some unrelated line\n"
        "2026/07/29 10:00:02 [0] executing program 1 "
        "(fault-call:2 fault-nth:5):\n"
        f"{text}"
    )
    entries = parse_log(TARGET, log)
    assert len(entries) == 2
    assert entries[0].proc == 3
    assert not entries[0].fault
    assert serialize(entries[0].p) == text
    assert entries[1].proc == 1
    assert entries[1].fault
    assert entries[1].fault_call == 2
    assert entries[1].fault_nth == 5


def test_parse_log_truncated():
    p = _prog()
    text = serialize(p)
    # crash truncates the last program mid-line: parser keeps the prefix
    log = "executing program 0:\n" + text[: text.rfind("close") + 3]
    entries = parse_log(TARGET, log)
    assert len(entries) == 1
    assert len(entries[0].p.calls) >= 1


def test_c_string_hex_digit_after_escape():
    # "\x04B" in C is ONE byte (0x4b, greedy hex escape); the emitter must
    # use 3-digit octal so a following hex-digit char stays a separate byte.
    data = bytes([0x04]) + b"B" + bytes([0xFF]) + b"7" + b'"\\'
    lit = csource._c_string(data)
    assert "\\x" not in lit
    assert lit == '"\\004B\\3777\\"\\\\"'
    # round-trip through an actual C compiler
    src = ("#include <string.h>\n#include <stdio.h>\n"
           "int main() {\n"
           f"  const char s[] = {lit};\n"
           f"  if (sizeof(s) - 1 != {len(data)}) return 1;\n"
           f"  if (memcmp(s, \"\\004B\\377\\067\\042\\134\", {len(data)})) "
           "return 2;\n"
           "  puts(\"OK\"); return 0;\n}\n")
    import subprocess, tempfile
    with tempfile.TemporaryDirectory() as d:
        c = os.path.join(d, "t.c")
        with open(c, "w") as f:
            f.write(src)
        exe = os.path.join(d, "t")
        subprocess.check_call(["cc", "-o", exe, c])
        out = subprocess.run([exe], capture_output=True, text=True)
        assert out.returncode == 0 and out.stdout.strip() == "OK"
