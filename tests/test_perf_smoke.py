"""Perf smoke (CI satellite, ISSUE 3): a pipelined device round on the
CPU backend must reuse its steady-state buffers — the donated signal
bitset and the resident corpus arena update in place, so the set of live
device arrays does not grow across rounds.  Fast enough for tier-1 (not
marked slow): a regression here means every launch leaks a buffer, which
is exactly what the arena + donation work removed."""

import gc

import pytest

jax = pytest.importorskip("jax")

from syzkaller_tpu.engine.fuzzer import Fuzzer, FuzzerConfig  # noqa: E402
from syzkaller_tpu.prog import get_target  # noqa: E402


def test_steady_state_live_device_buffers_flat():
    target = get_target("linux", "amd64")
    cfg = FuzzerConfig(mock=True, use_device=True, device_batch=8,
                       program_length=8, smash_mutations=1,
                       device_period=2, arena_capacity=64)
    with Fuzzer(target, cfg) as f:
        if f._device is None:
            pytest.skip("jax device pipeline unavailable")

        def run_until(nbatches, budget=800):
            # stop right after a batch is consumed so both measurement
            # points sit at the same phase of the double-buffered loop
            for _ in range(budget):
                f.step()
                if f.stats["device_batches"] >= nbatches:
                    return True
            return False

        assert run_until(3), "pipeline never produced 3 batches"
        gc.collect()
        before = len(jax.live_arrays())
        assert run_until(6), "pipeline stalled mid-test"
        gc.collect()
        after = len(jax.live_arrays())
        assert after <= before, (
            f"live device arrays grew across steady-state rounds "
            f"({before} -> {after}): donated signal buffer or arena "
            f"tensors are being reallocated per launch")


def test_signal_buffer_donated_in_place():
    """The engine's sharded-step signal bitset is donated: after a launch
    the previous buffer is consumed (deleted), not left to accumulate."""
    target = get_target("linux", "amd64")
    cfg = FuzzerConfig(mock=True, use_device=True, device_batch=8,
                       program_length=8, smash_mutations=1,
                       device_period=2, arena_capacity=64)
    with Fuzzer(target, cfg) as f:
        if f._device is None:
            pytest.skip("jax device pipeline unavailable")
        for _ in range(200):
            f.step()
            if f._device.arena.size:
                break
        assert f._device.arena.size
        sig_before = f._device._sig_shard
        assert f._device._launch() is not None
        assert f._device._sig_shard is not sig_before
        assert sig_before.is_deleted(), \
            "signal bitset was copied, not donated"
