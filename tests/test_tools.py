"""Tools suite (reference tools/): syz-db, syz-prog2c, syz-mutate,
syz-stress, syz-benchcmp, syz-fmt, syz-symbolize equivalents."""

import json
import os
import random
import tempfile

import pytest

from syzkaller_tpu.db import DB
from syzkaller_tpu.prog import get_target
from syzkaller_tpu.prog.encoding import deserialize, serialize
from syzkaller_tpu.prog.generation import generate
from syzkaller_tpu.tools import benchcmp, dbtool, fmt, mutate, prog2c, stress
from syzkaller_tpu.utils.hash import hash_str

TARGET = get_target("linux", "amd64")


def _progs(n, seed=0):
    return [generate(TARGET, seed * 100 + i, 8) for i in range(n)]


class TestDbTool:
    def test_pack_unpack_roundtrip(self, tmp_path):
        src = tmp_path / "src"
        src.mkdir()
        texts = sorted(serialize(p) for p in _progs(5))
        for i, t in enumerate(texts):
            (src / f"prog{i}").write_text(t)
        db_path = str(tmp_path / "corpus.db")
        assert dbtool.pack(TARGET, str(src), db_path) == len(set(texts))

        dst = tmp_path / "dst"
        n = dbtool.unpack(db_path, str(dst))
        assert n == len(set(texts))
        got = sorted((dst / f).read_text() for f in os.listdir(dst))
        assert got == sorted(set(texts))
        # keys are the manager's sha1 keying
        for f in os.listdir(dst):
            assert hash_str((dst / f).read_bytes()) == f

    def test_pack_skips_garbage(self, tmp_path, capsys):
        src = tmp_path / "src"
        src.mkdir()
        (src / "good").write_text(serialize(_progs(1)[0]))
        (src / "bad").write_text("not_a_syscall(1, 2)\n")
        assert dbtool.pack(TARGET, str(src), str(tmp_path / "c.db")) == 1

    def test_merge(self, tmp_path):
        texts = [serialize(p) for p in _progs(6)]
        a, b, dst = (str(tmp_path / x) for x in ("a.db", "b.db", "dst.db"))
        with DB.open(a) as db:
            for t in texts[:4]:
                db.save(hash_str(t.encode()).encode(), t.encode())
            db.flush()
        with DB.open(b) as db:
            for t in texts[2:]:
                db.save(hash_str(t.encode()).encode(), t.encode())
            db.flush()
        dbtool.merge(dst, [a, b])
        with DB.open(dst) as db:
            assert len(db) == len(set(texts))

    def test_cli_list(self, tmp_path, capsys):
        db_path = str(tmp_path / "c.db")
        t = serialize(_progs(1)[0])
        with DB.open(db_path) as db:
            db.save(b"k1", t.encode())
            db.flush()
        assert dbtool.main(["list", db_path]) == 0
        assert "k1" in capsys.readouterr().out


class TestProg2C:
    def test_emits_compilable_looking_c(self, tmp_path, capsys):
        p = _progs(1)[0]
        f = tmp_path / "p.prog"
        f.write_text(serialize(p))
        assert prog2c.main([str(f)]) == 0
        out = capsys.readouterr().out
        assert "int main" in out
        assert "syscall" in out

    def test_threaded_option(self, tmp_path, capsys):
        f = tmp_path / "p.prog"
        f.write_text(serialize(_progs(1)[0]))
        assert prog2c.main([str(f), "-threaded"]) == 0
        assert "pthread" in capsys.readouterr().out


class TestMutateTool:
    def test_mutates_given_prog(self, tmp_path, capsys):
        p = _progs(1)[0]
        f = tmp_path / "p.prog"
        f.write_text(serialize(p))
        assert mutate.main([str(f), "-seed", "7"]) == 0
        out = capsys.readouterr().out
        deserialize(TARGET, out)  # output parses back

    def test_seed_determinism(self, tmp_path, capsys):
        f = tmp_path / "p.prog"
        f.write_text(serialize(_progs(1)[0]))
        outs = []
        for _ in range(2):
            mutate.main([str(f), "-seed", "3"])
            outs.append(capsys.readouterr().out)
        assert outs[0] == outs[1]

    def test_loop_bench(self, tmp_path, capsys):
        f = tmp_path / "p.prog"
        f.write_text(serialize(_progs(1)[0]))
        assert mutate.main([str(f), "-seed", "1", "-loop", "20"]) == 0
        assert "progs/sec" in capsys.readouterr().err


class TestStress:
    def test_mock_stress_run(self, tmp_path, capsys):
        db_path = str(tmp_path / "c.db")
        with DB.open(db_path) as db:
            for p in _progs(3):
                t = serialize(p)
                db.save(hash_str(t.encode()).encode(), t.encode())
            db.flush()
        rc = stress.main(["-mock", "-corpus", db_path, "-procs", "2",
                          "-executed", "25", "-seed", "1"])
        assert rc == 0
        out = capsys.readouterr().out
        assert "executed" in out


class TestBenchcmp:
    def test_render_html(self, tmp_path):
        files = []
        for run in range(2):
            path = tmp_path / f"bench{run}.json"
            with open(path, "w") as f:
                for minute in range(5):
                    f.write(json.dumps({
                        "ts": 1000 + 60 * minute,
                        "signal": 100 * (minute + run),
                        "corpus": 10 * minute,
                        "exec_total": 1000 * minute,
                        "crash_types": run,
                    }) + "\n")
            files.append(str(path))
        html = benchcmp.render(files)
        assert html.count("<svg") == 4
        assert "signal" in html and "crash_types" in html
        out = str(tmp_path / "out.html")
        assert benchcmp.main(files + ["-o", out]) == 0
        assert os.path.exists(out)


class TestFmt:
    SRC = ("resource fd[int32]: -1\n"
           "open(file   ptr[in, filename],flags flags[oflags]) fd\n"
           "oflags=0x1,0x2,OTHER\n"
           "point {\n"
           "  x  int32\n"
           "  y  int64\n"
           "}\n")

    def test_format_idempotent(self, tmp_path):
        f = tmp_path / "d.txt"
        f.write_text(self.SRC)
        first = fmt.main([str(f)])
        assert first == 0
        once = f.read_text()
        assert "resource fd[int32]: -1" in once
        fmt.main([str(f)])
        assert f.read_text() == once

    def test_string_escapes_roundtrip(self, tmp_path):
        from syzkaller_tpu.descriptions.format import format_description
        from syzkaller_tpu.descriptions.parser import parse
        src = 'open(file ptr[in, string["a\\"b\\n"]]) fd\n'
        once = format_description(parse(src))
        assert format_description(parse(once)) == once

    def test_write_refuses_corruption(self, tmp_path, monkeypatch):
        # format_file must never overwrite a file with unparsable output
        import syzkaller_tpu.descriptions.format as dfmt
        f = tmp_path / "d.txt"
        f.write_text(self.SRC)
        monkeypatch.setattr(dfmt, "format_description",
                            lambda d: '"""broken')
        with pytest.raises(Exception):
            dfmt.format_file(str(f), write=True)
        assert f.read_text() == self.SRC

    def test_formatted_still_compiles(self, tmp_path):
        from syzkaller_tpu.descriptions.parser import parse
        f = tmp_path / "d.txt"
        f.write_text(self.SRC)
        fmt.main([str(f)])
        desc = parse(f.read_text(), str(f))
        names = {type(n).__name__ for n in desc.nodes}
        assert "CallDef" in names and "StructDef" in names


class TestJournalcat:
    """journalcat (ISSUE 7 satellite): decode/filter a campaign journal
    and verify the CRC/seq chain end-to-end — wired into test_tools like
    check_metrics so the tool keeps decoding what the engine writes."""

    def _make_journal(self, tmp_path):
        from syzkaller_tpu.telemetry.journal import CampaignJournal

        j = CampaignJournal(str(tmp_path / "journal.jsonl"),
                            engine_id="eng-t")
        j.emit("campaign_start", procs=2)
        j.emit("corpus_add", phase="mutate", ops=[1], row=3, h="ab" * 8)
        j.emit("signal", n=4, phase="mutate", ops=[1])
        j.emit("env_restart", env=1, failures=1)
        j.emit("corpus_add", phase="seed", h="cd" * 8)
        j.emit("campaign_end", execs=10, new_inputs=1)
        j.close()
        return str(tmp_path)

    def test_dump_and_verify(self, tmp_path, capsys):
        from syzkaller_tpu.tools import journalcat

        wd = self._make_journal(tmp_path)
        assert journalcat.main([wd]) == 0
        out = capsys.readouterr().out.strip().splitlines()
        assert len(out) == 6
        assert json.loads(out[0])["ev"] == "campaign_start"
        assert journalcat.main([wd, "--verify"]) == 0
        assert "0 problem(s)" in capsys.readouterr().out

    def test_filters(self, tmp_path, capsys):
        from syzkaller_tpu.tools import journalcat

        wd = self._make_journal(tmp_path)
        assert journalcat.main([wd, "--type", "corpus_add"]) == 0
        out = capsys.readouterr().out.strip().splitlines()
        assert len(out) == 2
        assert all(json.loads(l)["ev"] == "corpus_add" for l in out)
        assert journalcat.main(
            [wd, "--type", "corpus_add", "--phase", "mutate"]) == 0
        out = capsys.readouterr().out.strip().splitlines()
        assert len(out) == 1 and json.loads(out[0])["row"] == 3
        assert journalcat.main([wd, "--env", "1"]) == 0
        out = capsys.readouterr().out.strip().splitlines()
        assert len(out) == 1
        assert json.loads(out[0])["ev"] == "env_restart"

    def test_replay_summary(self, tmp_path, capsys):
        from syzkaller_tpu.tools import journalcat

        wd = self._make_journal(tmp_path)
        assert journalcat.main([wd, "--replay"]) == 0
        doc = json.loads(capsys.readouterr().out.strip())
        assert doc["new_inputs_total"] == 1   # seed add excluded
        assert doc["corpus_total"] == 2
        assert doc["signal_total"] == 4
        assert doc["engines"] == ["eng-t"]

    def test_corruption_fails_truncation_tolerated(self, tmp_path,
                                                   capsys):
        from syzkaller_tpu.tools import journalcat

        wd = self._make_journal(tmp_path)
        path = tmp_path / "journal.jsonl"
        blob = bytearray(path.read_bytes())
        idx = blob.index(b'"failures":1')
        blob[idx + 11:idx + 12] = b"7"  # mid-file flip: real corruption
        path.write_bytes(bytes(blob))
        assert journalcat.main([wd, "--verify"]) == 1
        assert "crc mismatch" in capsys.readouterr().err
        # a truncated FINAL record is the tolerated SIGKILL artifact
        wd2 = tmp_path / "ok"
        wd2.mkdir()
        self._make_journal(wd2)
        p2 = wd2 / "journal.jsonl"
        p2.write_bytes(p2.read_bytes()[:-15])
        assert journalcat.main([str(wd2), "--verify"]) == 0
        assert "tolerated crash artifact" in capsys.readouterr().err

    def test_missing_journal_is_usage_error(self, tmp_path, capsys):
        from syzkaller_tpu.tools import journalcat

        assert journalcat.main([str(tmp_path / "nope")]) == 2
        assert "no journal" in capsys.readouterr().err
