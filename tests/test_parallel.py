"""Sharded fuzz-step tests on the virtual 8-device CPU mesh (conftest sets
xla_force_host_platform_device_count=8)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
if len(jax.devices()) < 8:
    pytest.skip("needs the virtual 8-device mesh", allow_module_level=True)

import jax.numpy as jnp  # noqa: E402

from syzkaller_tpu.descriptions.tables import get_tables  # noqa: E402
from syzkaller_tpu.ops.dtables import build_device_tables  # noqa: E402
from syzkaller_tpu.parallel import collective, mesh as pmesh  # noqa: E402
from syzkaller_tpu.prog import get_target  # noqa: E402
from syzkaller_tpu.prog.tensor import (  # noqa: E402
    ProgBatch,
    TensorFormat,
    decode_batch,
)

NBITS = 1 << 14


@pytest.fixture(scope="module")
def env():
    target = get_target("linux", "amd64")
    tables = get_tables(target)
    fmt = TensorFormat.for_tables(tables, max_calls=8)
    dt = build_device_tables(tables, fmt)
    m = pmesh.make_mesh()  # 4x2 over the 8 virtual devices
    return target, tables, fmt, dt, m


def test_mesh_shape(env):
    *_, m = env
    assert m.devices.size == 8
    assert m.axis_names == (pmesh.AXIS_FUZZ, pmesh.AXIS_COVER)


def test_or_all_reduce():
    m = pmesh.make_mesh()
    n = m.devices.shape[0]
    x = jnp.arange(n * 4, dtype=jnp.uint32).reshape(n, 4)

    out = jax.jit(pmesh.shard_map(
        lambda v: collective.or_all_reduce(v, pmesh.AXIS_FUZZ),
        mesh=m,
        in_specs=jax.sharding.PartitionSpec(pmesh.AXIS_FUZZ),
        out_specs=jax.sharding.PartitionSpec(pmesh.AXIS_FUZZ)))(x)
    expect = np.bitwise_or.reduce(np.asarray(x).reshape(n, 1, 4), axis=0)
    np.testing.assert_array_equal(np.asarray(out)[:1], expect)


def test_sharded_fuzz_step(env):
    target, tables, fmt, dt, m = env
    B, C = 16, fmt.max_calls
    gen = pmesh.make_generate_step(m, dt, C=C)
    key = jax.random.PRNGKey(7)
    cid, sval, data = gen(key, jnp.zeros((B,), jnp.int32))
    # the step donates its batch + signal inputs (in-place update on the
    # double-buffered loop): keep host copies for the re-fold below
    cid0, sval0, data0 = (np.asarray(x).copy() for x in (cid, sval, data))

    step, shardings = pmesh.make_fuzz_step(m, dt)
    sig = jax.device_put(jnp.zeros(NBITS // 32, jnp.uint32),
                         shardings["signal"])
    cid2, sval2, data2, sig2, fresh, opm = step(key, cid, sval, data, sig)

    # shapes preserved, signal set grew, first step sees fresh signal
    assert cid2.shape == (B, C)
    assert sig2.shape == sig.shape
    assert int(jnp.sum(jax.lax.population_count(sig2))) > 0
    assert bool(jnp.any(fresh))
    # every lane carries operator provenance (>= rounds bits set is not
    # guaranteed — the same op can hit twice — but no lane is untouched,
    # and only the five known operator bits appear)
    assert opm.shape == (B,)
    assert bool(jnp.all(opm > 0))
    assert bool(jnp.all((opm >> 5) == 0))

    # every mutated lane still decodes to a valid executable program
    batch = ProgBatch(np.asarray(cid2), np.asarray(sval2), np.asarray(data2))
    for p in decode_batch(tables, fmt, batch):
        p.validate()

    # donation: the first call consumed its batch/signal inputs in place
    for donated in (cid, sval, data, sig):
        assert donated.is_deleted()

    # running the same batch again: no fresh signal (set is saturated
    # w.r.t. these fingerprints) unless mutation changed programs -- so
    # instead re-fold the *same* signals via a second identical step with
    # mutation disabled is not exposed; check determinism of fold instead:
    sig2_host = np.asarray(sig2).copy()  # sig2 is donated next call
    _, _, _, sig3, fresh3, _ = step(key, jnp.asarray(cid0),
                                    jnp.asarray(sval0), jnp.asarray(data0),
                                    sig2)
    np.testing.assert_array_equal(np.asarray(sig3),
                                  sig2_host | np.asarray(sig3))


def test_arena_fuzz_step(env):
    """The arena-sampling sharded step: the corpus + weight table stay
    resident and replicated, row selection is the on-device
    yield-weighted draw (NOTHING per-row crosses per launch), the batch
    materializes via jnp.take, admission (in-batch dedup + sharded Bloom
    filter) gates the mutants, and the signal/Bloom bitsets are donated
    while the arena tensors are NOT (they persist across launches)."""
    target, tables, fmt, dt, m = env
    B, C = 16, fmt.max_calls
    gen = pmesh.make_generate_step(m, dt, C=C)
    key = jax.random.PRNGKey(11)
    cap = 8
    a_cid, a_sval, a_data = gen(key, jnp.zeros((cap,), jnp.int32))
    repl = jax.sharding.NamedSharding(m, jax.sharding.PartitionSpec())
    a_cid, a_sval, a_data = (
        jax.device_put(x, repl) for x in (a_cid, a_sval, a_data))
    weights = jax.device_put(jnp.ones((cap,), jnp.uint32), repl)

    step, shardings = pmesh.make_arena_fuzz_step(m, dt, batch=B)
    assert "arena" in shardings and "bloom" in shardings
    sig = jax.device_put(jnp.zeros(NBITS // 32, jnp.uint32),
                         shardings["signal"])
    bloom = jax.device_put(jnp.zeros(NBITS // 32, jnp.uint32),
                           shardings["bloom"])
    idx, cid, sval, data, sig2, bloom2, fresh, admit, opm, pop = step(
        key, a_cid, a_sval, a_data, weights, sig, bloom)
    assert idx.shape == (B,)
    assert 0 <= int(jnp.min(idx)) and int(jnp.max(idx)) < cap
    assert cid.shape == (B, C)
    assert sval.shape == (B, C, dt.max_slots)
    assert opm.shape == (B,) and bool(jnp.all(opm > 0))
    assert int(jnp.sum(jax.lax.population_count(sig2))) > 0
    assert bool(jnp.any(fresh))
    # admission folded every row's probes into the Bloom filter, and the
    # reported popcount matches the updated filter
    assert admit.shape == (B,) and bool(jnp.any(admit))
    assert int(pop) == int(jnp.sum(jax.lax.population_count(
        jnp.asarray(bloom2)))) > 0
    # signal + bloom donated, arena + weights persist for the next launch
    assert sig.is_deleted()
    assert bloom.is_deleted()
    for persistent in (a_cid, a_sval, a_data, weights):
        assert not persistent.is_deleted()
    # mutated lanes gathered from the arena still decode + validate
    batch = ProgBatch(np.asarray(cid), np.asarray(sval), np.asarray(data))
    for p in decode_batch(tables, fmt, batch):
        p.validate()
    # and the step is re-launchable against the updated signal state
    out = step(key, a_cid, a_sval, a_data, weights, sig2, bloom2)
    jax.block_until_ready(out)


def test_arena_step_outputs_replicated_over_cover(env):
    """The batch outputs are declared replicated over the cover axis, so
    every cover replica of a fuzz shard must hold IDENTICAL data — the
    key is folded with the fuzz index only.  A cover-index fold would
    make each replica draw/mutate different programs while the sharded
    signal/Bloom folds record each replica's own phantoms (and
    check_rep=False would silence it, replica 0 silently winning)."""
    target, tables, fmt, dt, m = env
    assert m.devices.shape[1] > 1, "needs a real cover axis"
    B = 16
    gen = pmesh.make_generate_step(m, dt, C=fmt.max_calls)
    key = jax.random.PRNGKey(13)
    cap = 8
    a_cid, a_sval, a_data = gen(key, jnp.zeros((cap,), jnp.int32))
    repl = jax.sharding.NamedSharding(m, jax.sharding.PartitionSpec())
    a_cid, a_sval, a_data = (
        jax.device_put(x, repl) for x in (a_cid, a_sval, a_data))
    weights = jax.device_put(jnp.ones((cap,), jnp.uint32), repl)
    step, shardings = pmesh.make_arena_fuzz_step(m, dt, batch=B)
    sig = jax.device_put(jnp.zeros(NBITS // 32, jnp.uint32),
                         shardings["signal"])
    bloom = jax.device_put(jnp.zeros(NBITS // 32, jnp.uint32),
                           shardings["bloom"])
    out = step(key, a_cid, a_sval, a_data, weights, sig, bloom)
    idx, cid, sval, data, _sig, _bloom, fresh, admit, opm, _pop = out
    for arr in (idx, cid, fresh, admit, opm):
        by_slice = {}
        for sh in arr.addressable_shards:
            by_slice.setdefault(str(sh.index), []).append(
                np.asarray(sh.data))
        assert by_slice and all(len(v) > 1 for v in by_slice.values()), \
            "expected multiple cover replicas per fuzz shard"
        for replicas in by_slice.values():
            for r in replicas[1:]:
                np.testing.assert_array_equal(replicas[0], r)


def test_arena_fuzz_step_weighted_draw_concentrates(env):
    """The on-device weighted sampler honors the weight table: with all
    weight on one arena row, every lane draws that row."""
    target, tables, fmt, dt, m = env
    B = 16
    gen = pmesh.make_generate_step(m, dt, C=fmt.max_calls)
    key = jax.random.PRNGKey(5)
    cap = 8
    a_cid, a_sval, a_data = gen(key, jnp.zeros((cap,), jnp.int32))
    repl = jax.sharding.NamedSharding(m, jax.sharding.PartitionSpec())
    a_cid, a_sval, a_data = (
        jax.device_put(x, repl) for x in (a_cid, a_sval, a_data))
    w = jnp.zeros((cap,), jnp.uint32).at[3].set(7)
    weights = jax.device_put(w, repl)
    step, shardings = pmesh.make_arena_fuzz_step(m, dt, batch=B)
    sig = jax.device_put(jnp.zeros(NBITS // 32, jnp.uint32),
                         shardings["signal"])
    bloom = jax.device_put(jnp.zeros(NBITS // 32, jnp.uint32),
                           shardings["bloom"])
    idx, *_ = step(key, a_cid, a_sval, a_data, weights, sig, bloom)
    np.testing.assert_array_equal(np.asarray(idx), np.full(B, 3))


def _arena_inputs(env, key, cap=8, B=16, weights=None):
    """Replicated arena row tensors + weight table for an arena step."""
    target, tables, fmt, dt, m = env
    gen = pmesh.make_generate_step(m, dt, C=fmt.max_calls)
    a_cid, a_sval, a_data = gen(key, jnp.zeros((cap,), jnp.int32))
    repl = jax.sharding.NamedSharding(m, jax.sharding.PartitionSpec())
    a_cid, a_sval, a_data = (
        jax.device_put(x, repl) for x in (a_cid, a_sval, a_data))
    if weights is None:
        weights = (jnp.arange(cap, dtype=jnp.uint32) % 3) + 1
    weights = jax.device_put(jnp.asarray(weights, jnp.uint32), repl)
    return a_cid, a_sval, a_data, weights


ARENA_OUT_NAMES = ("idx", "cid", "sval", "data", "sig", "bloom", "fresh",
                   "admit", "op_mask", "pop")


def test_arena_step_explicit_parity_with_shard_map(env):
    """PINNED: the explicit-sharding (global-view jit) arena step is
    BIT-IDENTICAL to the shard_map step on the 4x2 mesh — per-shard PRNG
    streams (collective.per_shard_keys vs fold_in(key, axis_index)),
    the yield-weighted draw, mutation, the in-batch+Bloom admission
    gate, the gated signal fold, and operator provenance all match, and
    across two CHAINED launches so the carried sig/bloom state is
    covered too.  This is the contract that let the engine switch to
    explicit shardings without a behavior change."""
    target, tables, fmt, dt, m = env
    B = 16
    a_cid, a_sval, a_data, weights = _arena_inputs(
        env, jax.random.PRNGKey(23))
    outs = {}
    for impl in ("explicit", "shard_map"):
        step, shardings = pmesh.make_arena_fuzz_step(
            m, dt, batch=B, donate=False, impl=impl)
        sig = jax.device_put(jnp.zeros(NBITS // 32, jnp.uint32),
                             shardings["signal"])
        bloom = jax.device_put(jnp.zeros(NBITS // 32, jnp.uint32),
                               shardings["bloom"])
        first = step(jax.random.PRNGKey(31), a_cid, a_sval, a_data,
                     weights, sig, bloom)
        second = step(jax.random.PRNGKey(37), a_cid, a_sval, a_data,
                      weights, first[4], first[5])
        outs[impl] = [np.asarray(x) for x in (*first, *second)]
    for i, (a, b) in enumerate(zip(outs["explicit"], outs["shard_map"])):
        np.testing.assert_array_equal(
            a, b, err_msg=f"launch {i // 10} output "
                          f"{ARENA_OUT_NAMES[i % 10]} diverged")


def test_fuzz_step_explicit_parity_with_shard_map(env):
    """Same pin for the non-arena fuzz step: mutate-in-place + signal
    fold, all six outputs bit-identical across the two impls."""
    target, tables, fmt, dt, m = env
    B = 16
    gen = pmesh.make_generate_step(m, dt, C=fmt.max_calls)
    cid, sval, data = gen(jax.random.PRNGKey(41),
                          jnp.zeros((B,), jnp.int32))
    cid, sval, data = (np.asarray(x).copy() for x in (cid, sval, data))
    outs = {}
    for impl in ("explicit", "shard_map"):
        step, shardings = pmesh.make_fuzz_step(
            m, dt, donate=False, impl=impl)
        batch = tuple(jax.device_put(jnp.asarray(x), shardings["batch"])
                      for x in (cid, sval, data))
        sig = jax.device_put(jnp.zeros(NBITS // 32, jnp.uint32),
                             shardings["signal"])
        out = step(jax.random.PRNGKey(43), *batch, sig)
        outs[impl] = [np.asarray(x) for x in out]
    names = ("cid", "sval", "data", "sig", "fresh", "op_mask")
    for name, a, b in zip(names, outs["explicit"], outs["shard_map"]):
        np.testing.assert_array_equal(
            a, b, err_msg=f"fuzz-step output {name} diverged")


def test_arena_step_sharded_weights_parity(env):
    """shard_weights=True (the real row-sharded weight table the engine
    uses when capacity divides the fuzz axis) must not change a single
    bit vs the replicated table — the global cumsum is the same sum."""
    target, tables, fmt, dt, m = env
    B = 16
    a_cid, a_sval, a_data, weights = _arena_inputs(
        env, jax.random.PRNGKey(47))
    outs = {}
    for shard_weights in (False, True):
        step, shardings = pmesh.make_arena_fuzz_step(
            m, dt, batch=B, donate=False, shard_weights=shard_weights)
        w = jax.device_put(jnp.asarray(np.asarray(weights)),
                           shardings["weights"])
        sig = jax.device_put(jnp.zeros(NBITS // 32, jnp.uint32),
                             shardings["signal"])
        bloom = jax.device_put(jnp.zeros(NBITS // 32, jnp.uint32),
                               shardings["bloom"])
        out = step(jax.random.PRNGKey(53), a_cid, a_sval, a_data, w,
                   sig, bloom)
        outs[shard_weights] = [np.asarray(x) for x in out]
    for name, a, b in zip(ARENA_OUT_NAMES, outs[False], outs[True]):
        np.testing.assert_array_equal(
            a, b, err_msg=f"sharded-weights output {name} diverged")


def test_fingerprints_mask_dead_calls(env):
    target, tables, fmt, dt, m = env
    cid = jnp.array([1, 2, -1, -1], jnp.int32)
    sval = jnp.zeros((4, dt.max_slots), jnp.uint64)
    sig = pmesh.call_fingerprints(cid, sval)
    assert int(sig[2]) == 0xFFFFFFFF and int(sig[3]) == 0xFFFFFFFF
    assert int(sig[0]) != 0xFFFFFFFF
