"""Sharded fuzz-step tests on the virtual 8-device CPU mesh (conftest sets
xla_force_host_platform_device_count=8)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
if len(jax.devices()) < 8:
    pytest.skip("needs the virtual 8-device mesh", allow_module_level=True)

import jax.numpy as jnp  # noqa: E402

from syzkaller_tpu.descriptions.tables import get_tables  # noqa: E402
from syzkaller_tpu.ops.dtables import build_device_tables  # noqa: E402
from syzkaller_tpu.parallel import collective, mesh as pmesh  # noqa: E402
from syzkaller_tpu.prog import get_target  # noqa: E402
from syzkaller_tpu.prog.tensor import (  # noqa: E402
    ProgBatch,
    TensorFormat,
    decode_batch,
)

NBITS = 1 << 14


@pytest.fixture(scope="module")
def env():
    target = get_target("linux", "amd64")
    tables = get_tables(target)
    fmt = TensorFormat.for_tables(tables, max_calls=8)
    dt = build_device_tables(tables, fmt)
    m = pmesh.make_mesh()  # 4x2 over the 8 virtual devices
    return target, tables, fmt, dt, m


def test_mesh_shape(env):
    *_, m = env
    assert m.devices.size == 8
    assert m.axis_names == (pmesh.AXIS_FUZZ, pmesh.AXIS_COVER)


def test_or_all_reduce():
    m = pmesh.make_mesh()
    n = m.devices.shape[0]
    x = jnp.arange(n * 4, dtype=jnp.uint32).reshape(n, 4)

    out = jax.jit(pmesh.shard_map(
        lambda v: collective.or_all_reduce(v, pmesh.AXIS_FUZZ),
        mesh=m,
        in_specs=jax.sharding.PartitionSpec(pmesh.AXIS_FUZZ),
        out_specs=jax.sharding.PartitionSpec(pmesh.AXIS_FUZZ)))(x)
    expect = np.bitwise_or.reduce(np.asarray(x).reshape(n, 1, 4), axis=0)
    np.testing.assert_array_equal(np.asarray(out)[:1], expect)


def test_sharded_fuzz_step(env):
    target, tables, fmt, dt, m = env
    B, C = 16, fmt.max_calls
    gen = pmesh.make_generate_step(m, dt, C=C)
    key = jax.random.PRNGKey(7)
    cid, sval, data = gen(key, jnp.zeros((B,), jnp.int32))
    # the step donates its batch + signal inputs (in-place update on the
    # double-buffered loop): keep host copies for the re-fold below
    cid0, sval0, data0 = (np.asarray(x).copy() for x in (cid, sval, data))

    step, shardings = pmesh.make_fuzz_step(m, dt)
    sig = jax.device_put(jnp.zeros(NBITS // 32, jnp.uint32),
                         shardings["signal"])
    cid2, sval2, data2, sig2, fresh, opm = step(key, cid, sval, data, sig)

    # shapes preserved, signal set grew, first step sees fresh signal
    assert cid2.shape == (B, C)
    assert sig2.shape == sig.shape
    assert int(jnp.sum(jax.lax.population_count(sig2))) > 0
    assert bool(jnp.any(fresh))
    # every lane carries operator provenance (>= rounds bits set is not
    # guaranteed — the same op can hit twice — but no lane is untouched,
    # and only the five known operator bits appear)
    assert opm.shape == (B,)
    assert bool(jnp.all(opm > 0))
    assert bool(jnp.all((opm >> 5) == 0))

    # every mutated lane still decodes to a valid executable program
    batch = ProgBatch(np.asarray(cid2), np.asarray(sval2), np.asarray(data2))
    for p in decode_batch(tables, fmt, batch):
        p.validate()

    # donation: the first call consumed its batch/signal inputs in place
    for donated in (cid, sval, data, sig):
        assert donated.is_deleted()

    # running the same batch again: no fresh signal (set is saturated
    # w.r.t. these fingerprints) unless mutation changed programs -- so
    # instead re-fold the *same* signals via a second identical step with
    # mutation disabled is not exposed; check determinism of fold instead:
    sig2_host = np.asarray(sig2).copy()  # sig2 is donated next call
    _, _, _, sig3, fresh3, _ = step(key, jnp.asarray(cid0),
                                    jnp.asarray(sval0), jnp.asarray(data0),
                                    sig2)
    np.testing.assert_array_equal(np.asarray(sig3),
                                  sig2_host | np.asarray(sig3))


def test_arena_fuzz_step(env):
    """The arena-sampling sharded step: the corpus + weight table stay
    resident and replicated, row selection is the on-device
    yield-weighted draw (NOTHING per-row crosses per launch), the batch
    materializes via jnp.take, admission (in-batch dedup + sharded Bloom
    filter) gates the mutants, and the signal/Bloom bitsets are donated
    while the arena tensors are NOT (they persist across launches)."""
    target, tables, fmt, dt, m = env
    B, C = 16, fmt.max_calls
    gen = pmesh.make_generate_step(m, dt, C=C)
    key = jax.random.PRNGKey(11)
    cap = 8
    a_cid, a_sval, a_data = gen(key, jnp.zeros((cap,), jnp.int32))
    repl = jax.sharding.NamedSharding(m, jax.sharding.PartitionSpec())
    a_cid, a_sval, a_data = (
        jax.device_put(x, repl) for x in (a_cid, a_sval, a_data))
    weights = jax.device_put(jnp.ones((cap,), jnp.uint32), repl)

    step, shardings = pmesh.make_arena_fuzz_step(m, dt, batch=B)
    assert "arena" in shardings and "bloom" in shardings
    sig = jax.device_put(jnp.zeros(NBITS // 32, jnp.uint32),
                         shardings["signal"])
    bloom = jax.device_put(jnp.zeros(NBITS // 32, jnp.uint32),
                           shardings["bloom"])
    idx, cid, sval, data, sig2, bloom2, fresh, admit, opm, pop = step(
        key, a_cid, a_sval, a_data, weights, sig, bloom)
    assert idx.shape == (B,)
    assert 0 <= int(jnp.min(idx)) and int(jnp.max(idx)) < cap
    assert cid.shape == (B, C)
    assert sval.shape == (B, C, dt.max_slots)
    assert opm.shape == (B,) and bool(jnp.all(opm > 0))
    assert int(jnp.sum(jax.lax.population_count(sig2))) > 0
    assert bool(jnp.any(fresh))
    # admission folded every row's probes into the Bloom filter, and the
    # reported popcount matches the updated filter
    assert admit.shape == (B,) and bool(jnp.any(admit))
    assert int(pop) == int(jnp.sum(jax.lax.population_count(
        jnp.asarray(bloom2)))) > 0
    # signal + bloom donated, arena + weights persist for the next launch
    assert sig.is_deleted()
    assert bloom.is_deleted()
    for persistent in (a_cid, a_sval, a_data, weights):
        assert not persistent.is_deleted()
    # mutated lanes gathered from the arena still decode + validate
    batch = ProgBatch(np.asarray(cid), np.asarray(sval), np.asarray(data))
    for p in decode_batch(tables, fmt, batch):
        p.validate()
    # and the step is re-launchable against the updated signal state
    out = step(key, a_cid, a_sval, a_data, weights, sig2, bloom2)
    jax.block_until_ready(out)


def test_arena_step_outputs_replicated_over_cover(env):
    """The batch outputs are declared replicated over the cover axis, so
    every cover replica of a fuzz shard must hold IDENTICAL data — the
    key is folded with the fuzz index only.  A cover-index fold would
    make each replica draw/mutate different programs while the sharded
    signal/Bloom folds record each replica's own phantoms (and
    check_rep=False would silence it, replica 0 silently winning)."""
    target, tables, fmt, dt, m = env
    assert m.devices.shape[1] > 1, "needs a real cover axis"
    B = 16
    gen = pmesh.make_generate_step(m, dt, C=fmt.max_calls)
    key = jax.random.PRNGKey(13)
    cap = 8
    a_cid, a_sval, a_data = gen(key, jnp.zeros((cap,), jnp.int32))
    repl = jax.sharding.NamedSharding(m, jax.sharding.PartitionSpec())
    a_cid, a_sval, a_data = (
        jax.device_put(x, repl) for x in (a_cid, a_sval, a_data))
    weights = jax.device_put(jnp.ones((cap,), jnp.uint32), repl)
    step, shardings = pmesh.make_arena_fuzz_step(m, dt, batch=B)
    sig = jax.device_put(jnp.zeros(NBITS // 32, jnp.uint32),
                         shardings["signal"])
    bloom = jax.device_put(jnp.zeros(NBITS // 32, jnp.uint32),
                           shardings["bloom"])
    out = step(key, a_cid, a_sval, a_data, weights, sig, bloom)
    idx, cid, sval, data, _sig, _bloom, fresh, admit, opm, _pop = out
    for arr in (idx, cid, fresh, admit, opm):
        by_slice = {}
        for sh in arr.addressable_shards:
            by_slice.setdefault(str(sh.index), []).append(
                np.asarray(sh.data))
        assert by_slice and all(len(v) > 1 for v in by_slice.values()), \
            "expected multiple cover replicas per fuzz shard"
        for replicas in by_slice.values():
            for r in replicas[1:]:
                np.testing.assert_array_equal(replicas[0], r)


def test_arena_fuzz_step_weighted_draw_concentrates(env):
    """The on-device weighted sampler honors the weight table: with all
    weight on one arena row, every lane draws that row."""
    target, tables, fmt, dt, m = env
    B = 16
    gen = pmesh.make_generate_step(m, dt, C=fmt.max_calls)
    key = jax.random.PRNGKey(5)
    cap = 8
    a_cid, a_sval, a_data = gen(key, jnp.zeros((cap,), jnp.int32))
    repl = jax.sharding.NamedSharding(m, jax.sharding.PartitionSpec())
    a_cid, a_sval, a_data = (
        jax.device_put(x, repl) for x in (a_cid, a_sval, a_data))
    w = jnp.zeros((cap,), jnp.uint32).at[3].set(7)
    weights = jax.device_put(w, repl)
    step, shardings = pmesh.make_arena_fuzz_step(m, dt, batch=B)
    sig = jax.device_put(jnp.zeros(NBITS // 32, jnp.uint32),
                         shardings["signal"])
    bloom = jax.device_put(jnp.zeros(NBITS // 32, jnp.uint32),
                           shardings["bloom"])
    idx, *_ = step(key, a_cid, a_sval, a_data, weights, sig, bloom)
    np.testing.assert_array_equal(np.asarray(idx), np.full(B, 3))


def test_fingerprints_mask_dead_calls(env):
    target, tables, fmt, dt, m = env
    cid = jnp.array([1, 2, -1, -1], jnp.int32)
    sval = jnp.zeros((4, dt.max_slots), jnp.uint64)
    sig = pmesh.call_fingerprints(cid, sval)
    assert int(sig[2]) == 0xFFFFFFFF and int(sig[3]) == 0xFFFFFFFF
    assert int(sig[0]) != 0xFFFFFFFF
