"""Tensor codec tests: template correspondence and round-trips."""

import numpy as np
import pytest

from syzkaller_tpu.descriptions.tables import get_tables
from syzkaller_tpu.prog import get_target
from syzkaller_tpu.prog.encoding import serialize
from syzkaller_tpu.prog.encodingexec import serialize_for_exec
from syzkaller_tpu.prog.generation import generate
from syzkaller_tpu.prog.prio import build_choice_table, calculate_priorities
from syzkaller_tpu.prog.tensor import (
    ProgBatch,
    TensorFormat,
    decode_batch,
    decode_prog,
    encode_batch,
    encode_prog,
    template_arg,
    walk_slots,
)


@pytest.fixture(scope="module")
def target():
    return get_target("linux", "amd64")


@pytest.fixture(scope="module")
def tables(target):
    return get_tables(target)


@pytest.fixture(scope="module")
def fmt(tables):
    return TensorFormat.for_tables(tables)


def test_walk_matches_tables(target, tables):
    """The python walker must reproduce the compiled template slot kinds for
    every syscall — this pins the codec to the tables."""
    for meta in target.syscalls:
        args = [template_arg(t) for t in meta.args]
        walked = [k for _a, k in walk_slots(args)]
        off = int(tables.call_slot_off[meta.id])
        cnt = int(tables.call_slot_cnt[meta.id])
        expected = tables.slot_kind[off:off + cnt].tolist()
        assert walked == expected, (
            f"{meta.name}: walker kinds {walked} != table {expected}")


def test_decode_empty(tables, fmt):
    b = ProgBatch.empty(fmt, 2)
    p = decode_prog(tables, fmt, b, 0)
    assert p.calls == []


def test_decode_template_programs(target, tables, fmt):
    """Decoding a batch with just call ids set must give valid programs."""
    rng = np.random.RandomState(0)
    b = ProgBatch.empty(fmt, 8)
    for i in range(8):
        n = rng.randint(1, fmt.max_calls)
        b.call_id[i, :n] = rng.randint(0, tables.n_calls, n)
    for p in decode_batch(tables, fmt, b):
        p.validate()
        serialize(p)
        serialize_for_exec(p)


def test_roundtrip_host_programs(target, tables, fmt):
    """encode(host prog) -> decode -> must be valid and preserve the call
    sequence (modulo mmap normalization and template-shape projection)."""
    ct = build_choice_table(target, calculate_priorities(target, []))
    for seed in range(20):
        p = generate(target, seed, 10, ct)
        b = encode_prog(tables, fmt, p)
        q = decode_prog(tables, fmt, b, 0)
        q.validate()
        serialize_for_exec(q)
        mmap = target.mmap_syscall
        orig = [c.meta.name for c in p.calls if c.meta is not mmap]
        got = [c.meta.name for c in q.calls if c.meta is not mmap]
        assert got == orig[: fmt.max_calls]


def test_encode_decode_encode_stable(target, tables, fmt):
    """decode -> encode must be a fixed point on the tensor form."""
    ct = build_choice_table(target, calculate_priorities(target, []))
    for seed in range(10):
        p = generate(target, seed, 8, ct)
        b1 = encode_prog(tables, fmt, p)
        q = decode_prog(tables, fmt, b1, 0)
        b2 = encode_prog(tables, fmt, q)
        assert np.array_equal(b1.call_id, b2.call_id)
        assert np.array_equal(b1.slot_val, b2.slot_val), (
            serialize(q),
            np.argwhere(b1.slot_val != b2.slot_val)[:5],
        )
        assert np.array_equal(b1.data, b2.data)


def test_refs_preserved(target, tables, fmt):
    """Cross-call fd dataflow survives the tensor round-trip."""
    from syzkaller_tpu.prog.encoding import deserialize

    text = ('r0 = open(&0:0:1="./f0\\x00", 0x0, 0x0)\n'
            'read(r0, &1:0:1=zero(0x10), 0x10)\n'
            'close(r0)\n')
    p = deserialize(target, text)
    b = encode_prog(tables, fmt, p)
    q = decode_prog(tables, fmt, b, 0)
    calls = [c for c in q.calls if c.meta is not target.mmap_syscall]
    read_fd = calls[1].args[0]
    close_fd = calls[2].args[0]
    assert read_fd.res is calls[0].ret
    assert close_fd.res is calls[0].ret
