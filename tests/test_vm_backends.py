"""kvm (lkvm) and odroid backend tests — hermetic: lkvm is faked with a
shell script, ssh probes are stubbed, the serial console is a FIFO-less
plain file.  Mirrors the registration/argv checks the isolated/adb
backends get in test_vmloop.py."""

import os
import stat
import time

import pytest

import syzkaller_tpu.vm as vm_mod
from syzkaller_tpu.vm import VMConfig, create


FAKE_LKVM = """#!/bin/sh
# fake lkvm: prints its argv, then idles like a booted VM
echo "fake-lkvm $@"
exec sleep 300
"""


@pytest.fixture
def fake_lkvm(tmp_path):
    p = tmp_path / "lkvm"
    p.write_text(FAKE_LKVM)
    p.chmod(p.stat().st_mode | stat.S_IEXEC)
    return str(p)


def test_kvm_backend_boot_run_close(tmp_path, fake_lkvm):
    kernel = tmp_path / "bzImage"
    kernel.write_bytes(b"\x00")
    cfg = VMConfig(type="kvm", count=2, workdir=str(tmp_path),
                   kernel=str(kernel), lkvm_bin=fake_lkvm,
                   cpu=1, mem_mb=128)
    pool = create(cfg)
    assert pool.count == 2
    inst = pool.create(0)
    try:
        # sandbox prepared with the guest init contract
        sandbox = os.path.join(str(tmp_path), "kvm-sandbox-0")
        assert os.path.exists(os.path.join(sandbox, "init.sh"))
        # console shows the lkvm invocation with the 9p share
        deadline = time.time() + 10
        while time.time() < deadline and b"--9p" not in inst.merger.output():
            time.sleep(0.1)
        out = inst.merger.output()
        assert b"fake-lkvm" in out and b"--9p" in out
        # copy drops into the sandbox, guest path under /host
        src = tmp_path / "payload"
        src.write_text("hi")
        gpath = inst.copy(str(src))
        assert gpath == "/host/payload"
        assert (tmp_path / "kvm-sandbox-0" / "payload").exists()
        # manager address rides lkvm's user-network gateway
        assert inst.forward(7788) == "192.168.33.1:7788"
        # run(): command file appears; simulate guest completing it
        merger, handle = inst.run("echo done-marker", timeout=5)
        cmdfile = os.path.join(sandbox, "command")
        deadline = time.time() + 5
        while time.time() < deadline and not os.path.exists(cmdfile):
            time.sleep(0.05)
        assert os.path.exists(cmdfile)
        with open(os.path.join(sandbox, "output"), "w") as f:
            f.write("guest-output-line\n")
        with open(os.path.join(sandbox, "done"), "w") as f:
            f.write("0\n")
        deadline = time.time() + 10
        while time.time() < deadline and handle.poll() is None:
            time.sleep(0.1)
        assert handle.poll() is not None
    finally:
        inst.close()


def test_kvm_backend_boot_failure(tmp_path):
    bad = tmp_path / "lkvm"
    bad.write_text("#!/bin/sh\necho broken; exit 1\n")
    bad.chmod(0o755)
    kernel = tmp_path / "bzImage"
    kernel.write_bytes(b"\x00")
    cfg = VMConfig(type="kvm", count=1, workdir=str(tmp_path),
                   kernel=str(kernel), lkvm_bin=str(bad))
    pool = create(cfg)
    with pytest.raises(RuntimeError, match="lkvm exited"):
        pool.create(0)


def test_odroid_backend(tmp_path, monkeypatch):
    from syzkaller_tpu.vm.odroid import OdroidInstance

    console = tmp_path / "ttyUSB0"
    console.write_text("board console line\n")
    monkeypatch.setattr(vm_mod, "_wait_ssh",
                        lambda *a, **k: None)
    import syzkaller_tpu.vm.odroid as od
    monkeypatch.setattr(od, "_wait_ssh", lambda *a, **k: None)
    monkeypatch.setattr(OdroidInstance, "_ssh",
                        lambda self, cmd, check=True: None)
    cfg = VMConfig(type="odroid", targets=["root@10.0.0.7"],
                   console=str(console),
                   power_cycle="true")
    pool = create(cfg)
    assert pool.count == 1
    inst = pool.create(0)
    try:
        assert (inst.target, inst.ssh_port) == ("root@10.0.0.7", 22)
        # console stream lands in the merger
        deadline = time.time() + 5
        while time.time() < deadline and \
                b"board console" not in inst.merger.output():
            time.sleep(0.05)
        assert b"board console line" in inst.merger.output()
        # repair shells out to the configured power-cycle command
        monkeypatch.setattr(od.time, "sleep", lambda s: None)
        inst.repair()  # "true" exits 0; would raise on failure
        cfg_nocycle = VMConfig(type="odroid", targets=["root@x"],
                               power_cycle="")
        inst2 = OdroidInstance.__new__(OdroidInstance)
        inst2.cfg = cfg_nocycle
        with pytest.raises(RuntimeError, match="power_cycle"):
            inst2.repair()
    finally:
        inst.close()
