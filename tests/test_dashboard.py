"""Dashboard service + dashapi client tests (reference dashboard/app
crash-ingestion semantics: dedup by title, needRepro, bug lifecycle)."""

import urllib.request

import pytest

from syzkaller_tpu.dashboard import (
    Dashboard,
    DashApi,
    REPRO_LEVEL_C,
)


@pytest.fixture()
def dash(tmp_path):
    d = Dashboard(str(tmp_path), keys={"mgr": "k"})
    d.start()
    yield d
    d.stop()


@pytest.fixture()
def api(dash):
    return DashApi(dash.addr, "mgr", "k")


def test_auth(dash):
    bad = DashApi(dash.addr, "mgr", "wrong")
    with pytest.raises(Exception):
        bad.report_crash({"title": "x"})


def test_crash_dedup_by_title(api, dash):
    for i in range(5):
        r = api.report_crash({
            "namespace": "ns", "manager": "mgr",
            "title": "KASAN: use-after-free in foo",
            "log": f"log {i}", "report": "trace"})
    bugs = dash.db.bugs("ns")
    assert len(bugs) == 1
    assert bugs[0]["num_crashes"] == 5
    assert r["need_repro"] is True
    crashes = dash.db.bug_crashes(bugs[0]["id"])
    assert len(crashes) == 5
    assert crashes[0]["log"].startswith("log")


def test_need_repro_lifecycle(api, dash):
    title = "WARNING in bar"
    api.report_crash({"namespace": "ns", "title": title, "log": "l"})
    assert api.need_repro("ns", title)
    # C repro arrives -> no more repro wanted
    api.report_crash({"namespace": "ns", "title": title, "log": "l",
                      "repro_c": "int main() {}"})
    assert not api.need_repro("ns", title)
    bugs = dash.db.bugs("ns")
    assert bugs[0]["repro_level"] == REPRO_LEVEL_C
    # unknown bug: no repro wanted
    assert not api.need_repro("ns", "no such bug")


def test_bug_status_updates_and_reopen(api, dash):
    title = "BUG: unable to handle kernel paging request in baz"
    api.report_crash({"namespace": "ns", "title": title, "log": "l"})
    assert api.update_bug("ns", title, "fixed")
    assert dash.db.bugs("ns", "fixed")
    # crash comes back after the fix -> bug reopens (regression handling)
    api.report_crash({"namespace": "ns", "title": title, "log": "l"})
    assert dash.db.bugs("ns", "open")
    assert not api.update_bug("ns", "missing title", "fixed")
    with pytest.raises(Exception):
        api.update_bug("ns", title, "bogus-status")


def test_build_upload_and_html(api, dash):
    api.upload_build({"id": "b1", "namespace": "ns", "manager": "mgr",
                      "os": "linux", "arch": "amd64",
                      "kernel_commit": "deadbeef"})
    api.report_crash({"namespace": "ns", "title": "t", "log": "l",
                      "build_id": "b1"})
    page = urllib.request.urlopen(
        f"http://{dash.addr}/", timeout=10).read().decode()
    assert "t" in page and "bugs" in page
    bug_id = dash.db.bugs("ns")[0]["id"]
    detail = urllib.request.urlopen(
        f"http://{dash.addr}/bug?id={bug_id}", timeout=10).read().decode()
    assert "crash @" in detail


def test_manager_reports_to_dashboard(dash, tmp_path):
    from syzkaller_tpu.manager import Manager, ManagerConfig
    from syzkaller_tpu.prog import get_target

    m = Manager(ManagerConfig(
        name="ns", workdir=str(tmp_path / "wd"),
        dashboard_addr=dash.addr, dashboard_client="mgr",
        dashboard_key="k"), target=get_target("linux", "amd64"))
    try:
        class R:
            title = "KASAN: slab-out-of-bounds in qux"
            report = "trace"
            maintainers = ["a@k.org"]

        m.save_crash(R(), b"console log", 0)
        bugs = dash.db.bugs("ns")
        assert len(bugs) == 1 and bugs[0]["title"] == R.title
        assert m.need_repro(R.title)  # dashboard-driven decision
    finally:
        m.close()


def test_save_repro_and_local_need_repro(tmp_path):
    """Without a dashboard the repro.prog file gates need_repro."""
    from syzkaller_tpu.manager import Manager, ManagerConfig
    from syzkaller_tpu.prog import get_target

    m = Manager(ManagerConfig(workdir=str(tmp_path / "wd")),
                target=get_target("linux", "amd64"))
    try:
        title = "WARNING in quux"
        assert m.need_repro(title)
        d = m.save_repro(title, "close(0xffffffffffffffff)\n",
                         "int main() { return 0; }")
        import os

        assert os.path.exists(os.path.join(d, "repro.prog"))
        assert os.path.exists(os.path.join(d, "repro.cprog"))
        assert not m.need_repro(title)
    finally:
        m.close()
