"""Table-driven crash-report parsing tests.

The reference's largest test surface (pkg/report/report_test.go, 1459 LoC
of real console outputs -> expected titles).  Each case below is a
realistic kernel console fragment (written to match the formats kernels
actually print, old and modern) with the canonical title the parser must
produce.
"""

import pytest

from syzkaller_tpu.report import contains_crash, extract_guilty_file, parse

CASES = [
    # --- KASAN, classic and modern ---
    ("""[   45.128563] ==================================================================
[   45.129342] BUG: KASAN: use-after-free in ip6_send_skb+0x2f5/0x330
[   45.130001] Read of size 8 at addr ffff8801c9bb6b18 by task syz-executor/4297
[   45.130812]
[   45.131001] CPU: 1 PID: 4297 Comm: syz-executor Not tainted 4.14.0 #5
[   45.131819] Call Trace:
[   45.132142]  dump_stack+0x194/0x257
[   45.132562]  print_address_description+0x73/0x250
[   45.133112]  kasan_report+0x25b/0x340
[   45.133598]  ip6_send_skb+0x2f5/0x330 net/ipv6/ip6_output.c:1688
""", "KASAN: use-after-free Read in ip6_send_skb"),
    ("""[  100.001000] BUG: KASAN: slab-out-of-bounds in memcpy+0x1d/0x40
[  100.002000] Write of size 4096 at addr ffff88006c9ee200
""", "KASAN: slab-out-of-bounds Write in memcpy"),
    ("""[   12.000000] BUG: KASAN: double-free or invalid-free in kfree+0x10/0x20
""", "KASAN: double-free or invalid-free in kfree"),
    ("""[   12.000000] BUG: KASAN: stack-out-of-bounds on address ffff880039a81bd8
[   12.000100] Read of size 8 by task syz-executor/6778
""", "KASAN: stack-out-of-bounds Read of size 8"),
    # --- KCSAN / KMSAN ---
    ("""[   33.100000] BUG: KCSAN: data-race in tcp_poll+0x1f0/0x500
""", "KCSAN: data-race in tcp_poll"),
    ("""[   33.100000] BUG: KMSAN: uninit-value in udp_sendmsg+0x123/0x999
""", "KMSAN: uninit-value in udp_sendmsg"),
    # --- page faults, old and modern ---
    ("""[   10.000000] BUG: unable to handle kernel paging request at ffffc90001b4a008
[   10.000100] IP: skb_release_data+0x258/0x470
[   10.000200] PGD 1c9ad8067
""", "BUG: unable to handle kernel paging request in skb_release_data"),
    ("""[   10.000000] BUG: unable to handle page fault for address: ffffed1021e509ff
[   10.000100] #PF: supervisor read access in kernel mode
[   10.000200] RIP: 0010:ext4_find_extent+0x2e6/0x480
""", "BUG: unable to handle kernel paging request in ext4_find_extent"),
    ("""[   10.000000] BUG: kernel NULL pointer dereference, address: 0000000000000028
[   10.000200] RIP: 0010:vfs_rename+0x101/0x300
""", "BUG: unable to handle kernel NULL pointer dereference in vfs_rename"),
    # --- misc BUG variants ---
    ("""[   20.000000] BUG: sleeping function called from invalid context at mm/slab.h:421
""", "BUG: sleeping function called from invalid context at mm/slab.h:421"),
    ("""[   20.000000] BUG: workqueue lockup - pool cpus=0 node=0
""", "BUG: workqueue lockup"),
    ("""[   20.000000] BUG: scheduling while atomic: syz-executor/12/0x00000002
""", "BUG: scheduling while atomic"),
    ("""[   20.000000] BUG: corrupted list in netlink_update_socket+0x100/0x200
""", "BUG: corrupted list in netlink_update_socket"),
    ("""[   20.000000] BUG: spinlock lockup suspected on CPU#0, syz-executor/123
""", "BUG: spinlock lockup suspected"),
    ("""[   20.000000] BUG: Bad page state in process syz-executor  pfn:1bc05
""", "BUG: Bad page state"),
    ("""[   20.000000] BUG: stack guard page was hit at ffffb46a (stack is f0f0)
[   20.000100] RIP: 0010:do_overflow+0x2f/0x40
""", "BUG: stack guard page was hit in do_overflow"),
    # --- WARNING ---
    ("""[   30.000000] WARNING: CPU: 1 PID: 100 at net/core/dev.c:2444 skb_warn_bad_offload+0x2bc/0x600
""", "WARNING in skb_warn_bad_offload"),
    ("""[   30.000000] ======================================================
[   30.000100] WARNING: possible circular locking dependency detected
[   30.000200] 4.14.0 #5 Not tainted
[   30.000300] ------------------------------------------------------
[   30.000400] syz-executor/5623 is trying to acquire lock:
[   30.000500]  (sk_lock-AF_INET6){+.+.}, at: [<ffffffff84100fa0>] ip6_mroute_setsockopt+0x190/0x1800
""", "possible deadlock in ip6_mroute_setsockopt"),
    ("""[   30.000000] WARNING: suspicious RCU usage
[   30.000100] 4.14.0 #5 Not tainted
[   30.000200] -----------------------------
[   30.000300] net/ipv4/tcp_input.c:123 suspicious rcu_dereference_check() usage!
""", "suspicious RCU usage at net/ipv4/tcp_input.c:123"),
    # --- INFO ---
    ("""[   40.000000] INFO: rcu_sched detected stalls on CPUs/tasks:
[   40.000100] 	0-...: (1 GPs behind) idle=a8a
""", "INFO: rcu detected stall"),
    ("""[   40.000000] INFO: rcu_preempt self-detected stall on CPU
[   40.000100] 	0-...: (20999 ticks this GP)
[   40.000200] RIP: 0010:csd_lock_wait+0x30/0x40
""", "INFO: rcu detected stall in csd_lock_wait"),
    ("""[   40.000000] INFO: task syz-executor:5068 blocked for more than 120 seconds.
[   40.000100]       Not tainted 4.14.0 #5
""", "INFO: task hung"),
    # --- faults with RIP ---
    ("""[   50.000000] general protection fault: 0000 [#1] SMP KASAN
[   50.000100] Modules linked in:
[   50.000200] RIP: 0010:__list_del_entry_valid+0x7e/0x150
""", "general protection fault in __list_del_entry_valid"),
    ("""[   50.000000] general protection fault, probably for non-canonical address 0xdffffc0000000003
[   50.000100] KASAN: null-ptr-deref in range [0x18-0x1f]
[   50.000200] RIP: 0010:crypto_shash_alg+0x18/0x30
""", "general protection fault in crypto_shash_alg"),
    ("""[   50.000000] divide error: 0000 [#1] SMP KASAN
[   50.000100] RIP: 0010:tcp_select_window+0x56f/0x7a0
""", "divide error in tcp_select_window"),
    ("""[   50.000000] invalid opcode: 0000 [#1] SMP
[   50.000100] RIP: 0010:jbd2_journal_stop+0x5b0/0x640
""", "invalid opcode in jbd2_journal_stop"),
    ("""[   50.000000] double fault: 0000 [#1] SMP
[   50.000100] RIP: 0010:page_fault+0x11/0x30
""", "double fault in page_fault"),
    ("""[   50.000000] stack segment: 0000 [#1] SMP KASAN
[   50.000100] RIP: 0010:__radix_tree_lookup+0xd2/0x230
""", "stack segment fault in __radix_tree_lookup"),
    # --- lockups / panics / kernel BUG ---
    ("""[   60.000000] watchdog: BUG: soft lockup - CPU#0 stuck for 22s! [syz-executor:123]
[   60.000100] RIP: 0010:smp_call_function_single+0x11a/0x170
""", "BUG: soft lockup in smp_call_function_single"),
    ("""[   60.000000] Kernel panic - not syncing: Attempted to kill init! exitcode=0x00000009
""", "kernel panic: Attempted to kill init!"),
    ("""[   60.000000] kernel BUG at fs/ext4/inode.c:2711!
""", "kernel BUG at fs/ext4/inode.c:2711"),
    ("""[   60.000000] Kernel panic - not syncing: stack-protector: Kernel stack is corrupted in: ffffffff81aa1f42
""", "kernel panic: stack-protector: Kernel stack is corrupted in: ffffffff81aa1f42"),
    # --- leaks / UBSAN / netdev ---
    ("""[   70.000000] UBSAN: Undefined behaviour in net/ipv4/tcp_output.c:223:14
""", "UBSAN: Undefined behaviour in net/ipv4/tcp_output.c:223:14"),
    ("""[   70.000000] unregister_netdevice: waiting for lo to become free. Usage count = 2
""", "unregister_netdevice: waiting for DEV to become free"),
]


@pytest.mark.parametrize("output,title", CASES,
                         ids=[t[:40] for _, t in CASES])
def test_title_extraction(output, title):
    assert contains_crash(output)
    rep = parse(output)
    assert rep is not None
    assert rep.title == title
    assert not rep.corrupted


def test_no_crash_in_clean_boot():
    out = """[    0.000000] Linux version 5.15.0
[    1.000000] systemd[1]: Detected virtualization kvm.
[    2.000000] EXT4-fs (sda1): mounted filesystem
executing program 0:
mmap(&vma 0:1, 0x1000, 0x3, 0x32, 0xffffffffffffffff, 0x0)
"""
    assert not contains_crash(out)
    assert parse(out) is None


def test_suppressions():
    assert not contains_crash(
        "[1.0] WARNING: /etc/ssh/moduli does not exist, using fixed modulus\n")
    assert not contains_crash("[1.0] INFO: lockdep is turned off\n")
    assert not contains_crash(
        "[1.0] INFO: NMI handler perf_event took too long to run\n")
    # user-supplied ignores
    out = "[1.0] WARNING: CPU: 0 PID: 1 at kernel/x.c:1 foo+0x1/0x2\n"
    assert contains_crash(out)
    assert not contains_crash(out, ignores=[r"WARNING: .* at kernel/x"])


def test_first_crash_wins():
    out = """[1.0] BUG: KASAN: use-after-free in aaa_first+0x1/0x2
[1.1] Read of size 8 at addr ffff8801
[2.0] general protection fault: 0000 [#1]
[2.1] RIP: 0010:bbb_second+0x1/0x2
"""
    rep = parse(out)
    assert rep.title == "KASAN: use-after-free Read in aaa_first"


def test_guilty_file_skips_generic_frames():
    report = """BUG: KASAN: use-after-free in ip6_dst_store
Call Trace:
 dump_stack+0x194/0x257 lib/dump_stack.c:52
 kasan_report+0x25b/0x340 mm/kasan/report.c:409
 ip6_dst_store+0x1f/0x2d0 include/net/ip6_fib.h:176
 tcp_v6_connect+0x10a9/0x1f20 net/ipv6/tcp_ipv6.c:295
"""
    assert extract_guilty_file(report) == "net/ipv6/tcp_ipv6.c"


def test_corrupted_report_flag():
    # header present but no format can extract a sane title
    out = "[1.0] unreferenced object\n"
    rep = parse(out)
    assert rep is not None
    # generic fallback fires; title is the header-ish first line
    assert rep.title


def test_console_prefix_variants():
    # raw, timestamped, and loglevel-prefixed forms all parse the same
    for prefix in ("", "[    5.123456] ", "<4>[    5.123456] "):
        out = (f"{prefix}BUG: KASAN: use-after-free in foo_bar+0x1/0x2\n"
               f"{prefix}Read of size 8 at addr ffff8801\n")
        rep = parse(out)
        assert rep.title == "KASAN: use-after-free Read in foo_bar", prefix


def test_userspace_gpf_trap_not_a_crash():
    """show_unhandled_signals traps lines are userspace, not kernel bugs."""
    out = ("[1.0] traps: syz-executor[4297] general protection fault "
           "ip:7f3a8c1 sp:7ffd2 error:0 in libc-2.27.so[7f3a8+1c0000]\n")
    assert not contains_crash(out)


def test_rip_scan_bounded_by_next_crash():
    """A RIP-less lockup must not steal the next crash's RIP line."""
    out = """[1.0] watchdog: BUG: soft lockup - CPU#0 stuck for 22s! [syz:1]
[1.1] CPU: 0 PID: 1 Comm: syz
[2.0] general protection fault: 0000 [#1] SMP
[2.1] RIP: 0010:totally_unrelated_func+0x1/0x2
"""
    rep = parse(out)
    assert rep.title == "BUG: soft lockup"
    # and the report slice stops before the second crash
    assert "totally_unrelated_func" not in rep.report
