"""Campaign-analytics tests (ISSUE 2): ring-buffer series invariants
(capacity bound, origin preservation, monotonic timestamps, stride
doubling), the registry sampler, the phase/operator attribution ledger
(totals exactly equal the engine's corpus additions), the manager's
/stats.json and /dashboard endpoints after a short mock campaign, and
the metric-namespace linter that keeps registry names coherent."""

import json
import time
import urllib.request

import pytest

from syzkaller_tpu.telemetry import (
    AttributionLedger,
    Provenance,
    RegistrySampler,
    Series,
    TimeSeriesStore,
    get_ledger,
    get_registry,
    ops_from_mask,
    rate_points,
)
from syzkaller_tpu.telemetry.attribution import (
    OP_INSERT,
    OP_NAMES,
    OP_SPLICE,
    OP_VALUE,
    PHASE_GENERATE,
    PHASE_MUTATE,
)
from syzkaller_tpu.telemetry.metrics import Registry


# ---- ring-buffer series ----


def test_series_capacity_and_downsample_invariants():
    cap = 16
    s = Series("m", capacity=cap)
    n = 500
    for i in range(n):
        s.append(float(i), float(i * 10))
        # invariant: the bound holds after EVERY append, not just at the end
        assert len(s) <= cap
    # the campaign origin is never dropped
    assert s.ts[0] == 0.0 and s.vals[0] == 0.0
    # the newest point always survives (it was just appended)
    assert s.ts[-1] == float(n - 1)
    # timestamps strictly increasing
    assert all(a < b for a, b in zip(s.ts, s.ts[1:]))
    # stride is 2**k after k in-place downsamples
    assert s.stride & (s.stride - 1) == 0 and s.stride > 1
    # values were stored exactly as sampled (no averaging)
    assert all(v == t * 10 for t, v in s.points())


def test_series_rejects_nonmonotonic_time():
    s = Series("m", capacity=8)
    s.append(10.0, 1.0)
    s.append(10.0, 2.0)  # duplicate tick: dropped
    s.append(9.0, 3.0)   # clock went backwards: dropped
    s.append(11.0, 4.0)
    assert s.points() == [(10.0, 1.0), (11.0, 4.0)]


def test_series_minimum_capacity():
    with pytest.raises(ValueError):
        Series("m", capacity=2)


def test_store_snapshot_roundtrip():
    st = TimeSeriesStore(capacity=8)
    st.record_snapshot(1.0, {"a": 1, "b": 10})
    st.record_snapshot(2.0, {"a": 2, "b": 20})
    st.record("c", 3.0, 30)
    assert st.names() == ["a", "b", "c"]
    doc = json.loads(json.dumps(st.to_dict()))
    assert doc["a"]["t"] == [1.0, 2.0]
    assert doc["b"]["v"] == [10, 20]
    assert doc["c"]["stride"] == 1


def test_rate_points_clamps_counter_restart():
    ts = [0.0, 10.0, 20.0, 30.0]
    vals = [0.0, 100.0, 5.0, 15.0]  # counter restarted between t=10, t=20
    pts = rate_points(ts, vals)
    assert pts == [(10.0, 10.0), (20.0, 0.0), (30.0, 1.0)]


def test_registry_sampler_manual_ticks():
    reg = Registry()
    reg.counter("exec_total").inc(5)
    extra_calls = []

    def extra():
        extra_calls.append(1)
        if len(extra_calls) > 1:
            raise RuntimeError("dying manager")  # must not kill the tick
        return {"manager_corpus": 7}

    smp = RegistrySampler(registry=reg, interval=0, extra=extra)
    smp.sample(now=1.0)
    reg.counter("exec_total").inc(3)
    smp.sample(now=2.0)
    assert smp.samples_taken == 2
    doc = smp.store.to_dict()
    assert doc["exec_total"]["v"] == [5, 8]
    assert doc["manager_corpus"]["v"] == [7]  # second tick's extra() died
    assert len(extra_calls) == 2


def test_registry_sampler_thread_lifecycle():
    reg = Registry()
    reg.gauge("g").set(1)
    smp = RegistrySampler(registry=reg, interval=0.01)
    smp.start()
    smp.start()  # idempotent
    deadline = time.time() + 5.0
    while smp.samples_taken < 3 and time.time() < deadline:
        time.sleep(0.01)
    smp.stop()
    taken = smp.samples_taken
    assert taken >= 3
    time.sleep(0.05)
    assert smp.samples_taken == taken  # really stopped


# ---- attribution ledger ----


def test_ops_from_mask():
    assert ops_from_mask(0) == ()
    assert ops_from_mask(0b10101) == (0, 2, 4)
    assert ops_from_mask(0b11111) == (0, 1, 2, 3, 4)


def test_operator_index_space_is_shared():
    """The host mutator imports its OP_* indices from the attribution
    module, and the device mix enumerates exactly the same index space —
    a reorder in any copy would silently miscredit provenance."""
    from syzkaller_tpu.prog import mutation as host_mut
    from syzkaller_tpu.telemetry import attribution as att

    assert (host_mut.OP_SPLICE, host_mut.OP_INSERT, host_mut.OP_VALUE,
            host_mut.OP_DATA, host_mut.OP_REMOVE) == tuple(range(5))
    assert host_mut.OP_SPLICE is att.OP_SPLICE
    ops_mut = pytest.importorskip("syzkaller_tpu.ops.mutation")
    assert [op for op, _w in ops_mut._OP_MIX] \
        == list(range(len(att.OP_NAMES)))


def test_provenance_dedupes_operators():
    p = Provenance(PHASE_MUTATE, [OP_VALUE, OP_VALUE, OP_INSERT, OP_VALUE])
    assert p.ops == (OP_VALUE, OP_INSERT)
    assert "mutate" in repr(p) and "value" in repr(p)


def test_ledger_accounting():
    led = AttributionLedger()
    led.record_exec(PHASE_MUTATE, (OP_SPLICE, OP_VALUE), n=10)
    led.record_exec(PHASE_GENERATE)
    led.record_new_signal(PHASE_MUTATE, (OP_SPLICE, OP_VALUE), 4)
    led.record_new_signal(PHASE_MUTATE, (OP_SPLICE,), 0)  # no-op
    led.record_corpus_add(PHASE_MUTATE, (OP_SPLICE, OP_VALUE))
    snap = led.snapshot()
    # phase totals are exact
    assert snap["phases"]["mutate"] == {
        "execs": 10, "new_signal": 4, "corpus_adds": 1,
        "adds_per_kexec": 100.0, "signal_per_kexec": 400.0}
    assert snap["phases"]["generate"]["execs"] == 1
    # per-operator rows each credit the full event
    for op in ("splice", "value"):
        assert snap["operators"][op]["execs"] == 10
        assert snap["operators"][op]["corpus_adds"] == 1
    assert led.totals() == {"execs": 11, "new_signal": 4, "corpus_adds": 1}
    led.reset()
    assert led.totals() == {"execs": 0, "new_signal": 0, "corpus_adds": 0}
    json.dumps(snap)


def test_ledger_totals_match_mock_campaign():
    """Acceptance: after a short mock campaign the ledger's phase-summed
    totals exactly equal the engine's own counters — every exec and every
    corpus addition is credited to exactly one phase."""
    from syzkaller_tpu.engine.fuzzer import Fuzzer, FuzzerConfig
    from syzkaller_tpu.prog import get_target

    led = get_ledger()
    before = led.totals()
    target = get_target("linux", "amd64")
    cfg = FuzzerConfig(mock=True, use_device=False, smash_mutations=2)
    with Fuzzer(target, cfg) as f:
        f.loop(iterations=80)
        execs, adds = f.stats["exec_total"], f.stats["new_inputs"]
    after = led.totals()
    assert after["execs"] - before["execs"] == execs
    assert after["corpus_adds"] - before["corpus_adds"] == adds > 0
    snap = led.snapshot()
    # mutation yield was attributed to concrete operators
    assert set(snap["operators"]) <= set(OP_NAMES)
    assert sum(c["execs"] for c in snap["operators"].values()) > 0


def test_seed_corpus_credits_seed_phase():
    """Connect-time corpus imports land in the ledger's seed row (no
    exec paid, no new_inputs bump), so seed volume is auditable next to
    earned yield; duplicates are not double-credited."""
    from syzkaller_tpu.engine.fuzzer import Fuzzer, FuzzerConfig
    from syzkaller_tpu.prog import get_target
    from syzkaller_tpu.prog.encoding import serialize
    from syzkaller_tpu.prog.generation import generate

    target = get_target("linux", "amd64")
    led = get_ledger()

    def seed_adds():
        return led.snapshot()["phases"].get(
            "seed", {"corpus_adds": 0})["corpus_adds"]

    before = seed_adds()
    cfg = FuzzerConfig(mock=True, use_device=False)
    with Fuzzer(target, cfg) as f:
        text = serialize(generate(target, 7, 5))
        new_inputs = f.stats["new_inputs"]
        f._add_corpus_text(text)
        f._add_corpus_text(text)  # duplicate: dropped by the corpus hash
        assert f.stats["new_inputs"] == new_inputs
    assert seed_adds() - before == 1


# ---- manager endpoints ----


def _get(mgr, path: str) -> bytes:
    with urllib.request.urlopen(f"http://{mgr.http.addr}{path}",
                                timeout=10) as r:
        return r.read()


@pytest.fixture()
def campaign_manager(tmp_path):
    """A manager over a registry/ledger already populated by a short mock
    campaign, its sampler unstarted (interval<=0) so tests drive ticks
    deterministically."""
    from syzkaller_tpu.engine.fuzzer import Fuzzer, FuzzerConfig
    from syzkaller_tpu.manager import Manager, ManagerConfig
    from syzkaller_tpu.prog import get_target

    target = get_target("linux", "amd64")
    cfg = FuzzerConfig(mock=True, use_device=False, smash_mutations=2)
    with Fuzzer(target, cfg) as f:
        f.loop(iterations=60)
    m = Manager(ManagerConfig(workdir=str(tmp_path),
                              analytics_interval=0),
                target=target)
    try:
        now = time.time()
        m.sampler.sample(now=now)
        get_registry().counter("exec_total").inc(17)
        m.sampler.sample(now=now + 5)
        yield m
    finally:
        m.close()


def test_stats_json_roundtrip(campaign_manager):
    m = campaign_manager
    doc = json.loads(_get(m, "/stats.json"))
    assert {"now", "interval", "samples", "series", "attribution",
            "snapshot"} <= set(doc)
    assert doc["samples"] == 2
    series = doc["series"]["exec_total"]
    assert len(series["t"]) == 2 and series["stride"] == 1
    assert series["v"][1] - series["v"][0] >= 17
    # the manager's own trajectory rides the extra() callback
    assert "manager_corpus" in doc["series"]
    # nonzero attribution after the mock campaign (acceptance criterion)
    att = doc["attribution"]
    assert sum(c["corpus_adds"] for c in att["phases"].values()) > 0
    assert att["operators"]  # per-operator rows populated


def test_dashboard_page_renders(campaign_manager):
    m = campaign_manager
    page = _get(m, "/dashboard").decode()
    # sparkline panels with real polylines (>=2 samples were taken)
    assert "<svg" in page and "<polyline" in page
    assert "signal growth" in page and "exec rate /s" in page
    # admission-rate sparkline panel + yield-per-exec stat (ISSUE 5)
    assert "admission rate /s" in page
    assert "admission &amp; yield" in page
    assert "execs_per_new_input" in page
    # attribution tables
    assert "per-operator yield" in page and "per-phase yield" in page
    for op in ("splice", "insert", "value"):
        assert op in page
    # linked from the summary page
    root = _get(m, "/").decode()
    assert "/dashboard" in root and "/stats.json" in root


def test_stats_json_empty_manager(tmp_path):
    """A manager with no samples yet still serves valid JSON."""
    from syzkaller_tpu.manager import Manager, ManagerConfig
    from syzkaller_tpu.prog import get_target

    m = Manager(ManagerConfig(workdir=str(tmp_path),
                              analytics_interval=0),
                target=get_target("linux", "amd64"))
    try:
        doc = json.loads(_get(m, "/stats.json"))
        assert doc["samples"] == 0 and doc["series"] == {}
        page = _get(m, "/dashboard").decode()
        assert "no data yet" in page
    finally:
        m.close()


# ---- metric-namespace linter (CI satellite) ----


def test_metric_namespace_is_coherent():
    from syzkaller_tpu.tools.check_metrics import check, collect_registrations

    regs = collect_registrations()
    # sanity: the walker actually sees the known registration sites
    names = {r.name for r in regs}
    assert {"exec_total", "corpus_size",
            "device_batch_occupancy"} <= names
    # the arena + drain families (ISSUE 3) are registered and documented
    assert {"arena_occupancy", "arena_evictions_total",
            "arena_resident_bytes", "device_drain_env_occupancy"} <= names
    # the admission + weighted-scheduling family (ISSUE 5)
    assert {"candidates_deduped_total", "candidates_admitted_total",
            "admission_bloom_occupancy",
            "arena_weighted_evictions_total"} <= names
    assert check() == []


def test_check_metrics_required_metrics(tmp_path):
    """The linter fails when a REQUIRED metric (the arena_* family and
    the drain gauge) loses its registration — a refactor must not drop
    them silently."""
    from syzkaller_tpu.tools.check_metrics import (
        REQUIRED_METRICS,
        check,
        main,
    )

    assert "arena_occupancy" in REQUIRED_METRICS
    assert "arena_evictions_total" in REQUIRED_METRICS
    assert "arena_resident_bytes" in REQUIRED_METRICS
    # a tree without the arena registrations fails the required check...
    stub = tmp_path / "stub.py"
    stub.write_text("reg.counter('other_total', help='x')\n")
    problems = check(str(tmp_path), required=("arena_occupancy",
                                              "device_drain_*"))
    assert any("arena_occupancy" in p for p in problems)
    assert any("device_drain_*" in p for p in problems)
    assert main([str(tmp_path), "--require", "arena_occupancy"]) == 1
    # ...and explicit roots without `required` stay exempt (fixtures)
    assert check(str(tmp_path)) == []
    # the real package satisfies the full required set
    assert check() == []


def test_check_metrics_flags_bad_names(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text(
        "reg.counter('CamelCase')\n"
        "reg.gauge('undocumented_gauge')\n"
        "reg.histogram('ok_hist', help='documented')\n"
        "reg.counter('fleet_' + k)  # dynamic: exempt\n")
    from syzkaller_tpu.tools.check_metrics import check, main

    problems = check(str(tmp_path))
    assert any("CamelCase" in p and "snake_case" in p for p in problems)
    assert any("undocumented_gauge" in p and "help=" in p
               for p in problems)
    assert not any("ok_hist" in p for p in problems)
    assert main([str(tmp_path)]) == 1
