"""Manager HTTP UI tests (reference endpoint set html.go:30-39)."""

import json
import urllib.request

import pytest

from syzkaller_tpu.manager import Manager, ManagerConfig
from syzkaller_tpu.prog import get_target
from syzkaller_tpu.prog.encoding import serialize
from syzkaller_tpu.prog.generation import generate


@pytest.fixture(scope="module")
def target():
    return get_target("linux", "amd64")


@pytest.fixture()
def mgr(tmp_path, target):
    m = Manager(ManagerConfig(workdir=str(tmp_path)), target=target)
    yield m
    m.close()


def _get(mgr, path: str) -> bytes:
    with urllib.request.urlopen(f"http://{mgr.http.addr}{path}",
                                timeout=10) as r:
        return r.read()


def test_summary_and_stats(mgr, target):
    page = _get(mgr, "/").decode()
    assert mgr.cfg.name in page
    assert "corpus" in page and "cover" in page
    snap = json.loads(_get(mgr, "/stats"))
    assert snap["corpus"] == 0 and "uptime_s" in snap


def test_corpus_pages(mgr, target):
    text = serialize(generate(target, 1, 4))
    mgr.on_new_input("f0", text, 0, [1, 2, 3], [0xFFFF1000, 0xFFFF2000])
    page = _get(mgr, "/corpus").decode()
    assert "corpus (1)" in page
    sig = next(iter(mgr.corpus))
    assert _get(mgr, f"/corpus?sig={sig}").decode() == text


def test_cover_pages(mgr, target):
    mgr.on_new_input("f0", serialize(generate(target, 2, 4)), 0,
                     [9], [0xFFFF1000, 0xFFFF2010, 0xABC0000])
    raw = _get(mgr, "/rawcover").decode().splitlines()
    assert "0xffff1000" in raw and len(raw) == 3
    page = _get(mgr, "/cover").decode()
    assert "3 PCs" in page  # raw-region fallback (no kernel_obj)


def test_crash_pages(mgr):
    class R:
        title = "KASAN: use-after-free in foo"
        report = "stack trace here"

    mgr.save_crash(R(), b"console output", 0)
    page = _get(mgr, "/").decode()
    assert "KASAN: use-after-free in foo" in page
    crash = _get(
        mgr, "/crash?title=KASAN:%20use-after-free%20in%20foo").decode()
    assert "console output" in crash and "stack trace here" in crash


def test_prio_page(mgr, target):
    for seed in range(3):
        mgr.on_new_input("f0", serialize(generate(target, seed, 4)), 0,
                         [seed], [])
    page = _get(mgr, "/prio").decode()
    assert "priorities" in page


def test_404(mgr):
    with pytest.raises(urllib.error.HTTPError):
        _get(mgr, "/nope")
