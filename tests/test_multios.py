"""Multi-OS target tests: freebsd, fuchsia, windows.

The reference registers four OS description corpora (sys/{linux,freebsd,
fuchsia,windows}; reference sys/freebsd/init.go:10-25,
sys/fuchsia/init.go:10-29, sys/windows/init.go:10-24).  These tests check
that each bundled non-linux target compiles, generates, mutates, minimizes,
and round-trips both serialization formats, mirroring the seeded-random
property tests the reference runs against linux (prog/mutation_test.go).
"""

import random

import pytest

from syzkaller_tpu.prog import get_target
from syzkaller_tpu.prog.encoding import deserialize, serialize
from syzkaller_tpu.prog.encodingexec import serialize_for_exec
from syzkaller_tpu.prog.generation import generate
from syzkaller_tpu.prog.mutation import minimize, mutate
from syzkaller_tpu.prog.prio import build_choice_table, calculate_priorities

OSES = ["freebsd", "fuchsia", "windows"]


@pytest.fixture(scope="module", params=OSES)
def target(request):
    return get_target(request.param, "amd64")


def test_target_builds(target):
    assert len(target.syscalls) > 50
    assert target.mmap_syscall is not None
    assert target.make_mmap is not None


def test_generate_roundtrip(target):
    for seed in range(20):
        p = generate(target, seed, 10, None)
        text = serialize(p)
        p2 = deserialize(target, text)
        assert serialize(p2) == text
        assert serialize_for_exec(p2, 0)


def test_mutate_changes_program(target):
    changed = 0
    for seed in range(20):
        p = generate(target, seed, 8, None)
        before = serialize(p)
        mutate(p, seed + 10_000, ncalls=12, ct=None, corpus=[])
        if serialize(p) != before:
            changed += 1
    # The reference asserts every mutation changes the program
    # (prog/mutation_test.go:13-30); allow rare no-ops for robustness.
    assert changed >= 15


def test_minimize(target):
    p = generate(target, 7, 10, None)
    ncalls = len(p.calls)
    target_call = ncalls - 1

    p2, idx = minimize(p, target_call, lambda q, i: True, crash=False)
    # Everything removable should be gone except the target call chain.
    assert 1 <= len(p2.calls) <= ncalls
    assert 0 <= idx < len(p2.calls)


def test_choice_table(target):
    corpus = [generate(target, s, 8, None) for s in range(5)]
    prios = calculate_priorities(target, corpus)
    ct = build_choice_table(target, prios, None)
    rng = random.Random(3)
    for _ in range(50):
        idx = ct.choose(rng, rng.randrange(len(target.syscalls)))
        assert 0 <= idx < len(target.syscalls)


def test_linux_arm64():
    """linux/arm64: asm-generic syscall numbering, no legacy traps."""
    t = get_target("linux", "arm64")
    a = get_target("linux", "amd64")
    nr = {s.name: s.nr for s in t.syscalls}
    # asm-generic numbers (arch/arm64 includes asm-generic/unistd.h).
    assert nr["openat"] == 56
    assert nr["mmap"] == 222
    assert nr["read"] == 63
    # Legacy calls without an arm64 trap must be absent, not mis-numbered.
    assert "open" not in nr and "pipe" not in nr and "poll" not in nr
    # arm64 inherits arm's fcntl overrides — different from amd64's.
    assert t.consts["O_DIRECTORY"] == 0o40000
    assert t.consts["O_DIRECT"] == 0o200000
    assert a.consts["O_DIRECTORY"] != t.consts["O_DIRECTORY"]
    # 32-bit-only traps must not leak into the 64-bit table.
    assert "__NR_clock_gettime64" not in t.consts
    assert "__NR_futex_time64" not in t.consts
    for seed in range(10):
        p = generate(t, seed, 8, None)
        text = serialize(p)
        assert serialize(deserialize(t, text)) == text
        assert serialize_for_exec(p, 0)


def test_cross_os_isolation():
    """Targets must not leak state across OSes (distinct registries)."""
    a = get_target("freebsd", "amd64")
    b = get_target("windows", "amd64")
    assert a is not b
    assert {s.name for s in a.syscalls}.isdisjoint(
        {s.name for s in b.syscalls} - {"mmap"})
