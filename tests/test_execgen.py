"""Parity of the fast tensor->exec emitter against the decode path.

The contract (prog/execgen.py): whenever every DATA slot's length value
is >= its cap, ExecGen.emit_row must be byte-identical to
serialize_for_exec(decode_prog(row)) — the template instantiation the two
paths share.  Rows containing sanitize-special calls return None.
"""

import numpy as np
import pytest

from syzkaller_tpu.descriptions.tables import SK_DATA, get_tables
from syzkaller_tpu.prog import get_target
from syzkaller_tpu.prog.encodingexec import serialize_for_exec
from syzkaller_tpu.prog.execgen import ExecGen
from syzkaller_tpu.prog.generation import generate
from syzkaller_tpu.prog.tensor import (
    ProgBatch,
    TensorFormat,
    decode_prog,
    encode_prog,
)


@pytest.fixture(scope="module")
def ctx():
    t = get_target("linux", "amd64")
    tb = get_tables(t)
    fmt = TensorFormat.for_tables(tb, max_calls=12)
    return t, tb, fmt


def _pin_data_caps(tb, batch):
    """Force every DATA slot's length >= cap so decode instantiates the
    template shape (n = min(v, cap) = cap)."""
    for r in range(batch.batch):
        for c in range(batch.call_id.shape[1]):
            cid = int(batch.call_id[r, c])
            if cid < 0:
                continue
            off = int(tb.call_slot_off[cid])
            cnt = int(tb.call_slot_cnt[cid])
            lim = min(cnt, batch.slot_val.shape[2])
            kinds = tb.slot_kind[off:off + lim]
            for si in np.nonzero(kinds == SK_DATA)[0]:
                batch.slot_val[r, c, si] = np.uint64(1 << 32)


def _assert_parity(t, tb, fmt, batch, pid=0):
    gen = ExecGen(tb, fmt)
    checked = skipped = 0
    for r in range(batch.batch):
        fast = gen.emit_row(batch, r, pid)
        if fast is None:
            assert _template_failed(gen, batch, r), \
                f"row {r}: unexpected fallback"
            skipped += 1
            continue
        p = decode_prog(tb, fmt, batch, r)
        ref = serialize_for_exec(p, pid)
        if fast != ref:
            fw = np.frombuffer(fast, dtype=np.uint64)
            rw = np.frombuffer(ref, dtype=np.uint64)
            d = next((i for i in range(min(len(fw), len(rw)))
                      if fw[i] != rw[i]), None)
            names = [t.syscalls[int(c)].name
                     for c in batch.call_id[r] if int(c) >= 0]
            raise AssertionError(
                f"row {r} {names}: lens {len(fw)}/{len(rw)}, first diff at "
                f"word {d}: {hex(int(fw[d])) if d is not None else '-'} vs "
                f"{hex(int(rw[d])) if d is not None else '-'}")
        checked += 1
    return checked, skipped


def _template_failed(gen, batch, r):
    for c in range(batch.call_id.shape[1]):
        cid = int(batch.call_id[r, c])
        if cid >= 0 and gen._tmpl.get(cid, "x") is None:
            return True
    return False


def test_parity_generated_programs(ctx):
    t, tb, fmt = ctx
    progs = [generate(t, s, 10, None) for s in range(80)]
    batch = ProgBatch.empty(fmt, len(progs))
    for i, p in enumerate(progs):
        encode_prog(tb, fmt, p, batch, i)
    _pin_data_caps(tb, batch)
    checked, skipped = _assert_parity(t, tb, fmt, batch)
    assert checked >= batch.batch // 2, (checked, skipped)


def test_parity_random_tensors(ctx):
    """Fuzz the emitter itself: arbitrary slot values and arena bytes must
    keep byte-parity (both paths clamp identically)."""
    t, tb, fmt = ctx
    rng = np.random.default_rng(11)
    B = 48
    batch = ProgBatch.empty(fmt, B)
    ncalls = len(t.syscalls)
    batch.call_id[:] = rng.integers(-1, ncalls, size=batch.call_id.shape,
                                    dtype=np.int64).astype(np.int32)
    batch.slot_val[:] = rng.integers(0, 1 << 63,
                                     size=batch.slot_val.shape,
                                     dtype=np.int64).astype(np.uint64)
    # sprinkle REF_NONE and small ref indices
    mask = rng.random(batch.slot_val.shape) < 0.3
    batch.slot_val[mask] = np.uint64((1 << 64) - 1)
    small = rng.random(batch.slot_val.shape) < 0.2
    batch.slot_val[small] = rng.integers(
        0, 12, size=batch.slot_val.shape, dtype=np.int64
    ).astype(np.uint64)[small]
    batch.data[:] = rng.integers(0, 256, size=batch.data.shape,
                                 dtype=np.int64).astype(np.uint8)
    _pin_data_caps(tb, batch)
    checked, skipped = _assert_parity(t, tb, fmt, batch)
    assert checked > 0


def test_parity_nonzero_pid(ctx):
    t, tb, fmt = ctx
    progs = [generate(t, 1000 + s, 8, None) for s in range(24)]
    batch = ProgBatch.empty(fmt, len(progs))
    for i, p in enumerate(progs):
        encode_prog(tb, fmt, p, batch, i)
    _pin_data_caps(tb, batch)
    checked, _ = _assert_parity(t, tb, fmt, batch, pid=3)
    assert checked > 0


def test_sanitize_calls_emit_with_parity(ctx):
    """mmap/mremap/exit rows vectorize the linux sanitize_call rewrites
    (MAP_FIXED OR-in, MREMAP_FIXED, exit-status 67/68 remap) instead of
    falling back — byte parity must still hold."""
    t, tb, fmt = ctx
    batch = ProgBatch.empty(fmt, 3)
    batch.call_id[0, 0] = t.syscall_map["mmap"].id
    batch.call_id[1, 0] = t.syscall_map["exit"].id
    batch.slot_val[1, 0, 0] = np.uint64(67)  # reserved executor status
    batch.call_id[2, 0] = t.syscall_map["exit_group"].id
    batch.slot_val[2, 0, 0] = np.uint64(196)  # 196 % 128 == 68
    _pin_data_caps(tb, batch)
    checked, skipped = _assert_parity(t, tb, fmt, batch)
    assert checked == 3 and skipped == 0


def test_empty_row(ctx):
    t, tb, fmt = ctx
    gen = ExecGen(tb, fmt)
    batch = ProgBatch.empty(fmt, 1)
    fast = gen.emit_row(batch, 0)
    p = decode_prog(tb, fmt, batch, 0)
    assert fast == serialize_for_exec(p, 0)
