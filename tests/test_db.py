"""Corpus DB tests (reference pkg/db semantics: persistence, deletes,
compaction, torn-tail recovery)."""

import os

from syzkaller_tpu.db import DB
from syzkaller_tpu.utils.hash import hash_bytes, hash_str


def test_roundtrip(tmp_path):
    path = str(tmp_path / "corpus.db")
    with DB.open(path) as db:
        db.save(b"k1", b"v1" * 100)
        db.save(b"k2", b"")
        db.flush()
    with DB.open(path) as db:
        assert db.get(b"k1") == b"v1" * 100
        assert db.get(b"k2") == b""
        assert len(db) == 2


def test_delete_persists(tmp_path):
    path = str(tmp_path / "corpus.db")
    with DB.open(path) as db:
        db.save(b"a", b"1")
        db.save(b"b", b"2")
        db.delete(b"a")
        db.flush()
    with DB.open(path) as db:
        assert b"a" not in db
        assert db.get(b"b") == b"2"


def test_overwrite_latest_wins(tmp_path):
    path = str(tmp_path / "corpus.db")
    with DB.open(path) as db:
        for i in range(10):
            db.save(b"k", f"v{i}".encode())
        db.flush()
    with DB.open(path) as db:
        assert db.get(b"k") == b"v9"


def test_compaction_shrinks(tmp_path):
    path = str(tmp_path / "corpus.db")
    with DB.open(path) as db:
        for i in range(100):
            db.save(b"key", b"x" * 50)  # 99 dead records
        db.flush()
    big = os.path.getsize(path)
    with DB.open(path) as db:  # open triggers compaction (dead > live)
        assert db.get(b"key") == b"x" * 50
    assert os.path.getsize(path) < big / 4


def test_torn_tail_recovery(tmp_path):
    path = str(tmp_path / "corpus.db")
    with DB.open(path) as db:
        db.save(b"good", b"data")
        db.flush()
    # simulate a crash mid-append
    with open(path, "ab") as f:
        f.write(b"\x00\x10\x00\x00garbage-partial-record")
    with DB.open(path) as db:
        assert db.get(b"good") == b"data"
        db.save(b"more", b"after-recovery")
        db.flush()
    # note: recovery writes continue after the torn bytes; a compact on the
    # next open (or explicit) drops them
    with DB.open(path) as db2:
        db2.compact()
    with DB.open(path) as db3:
        assert db3.get(b"good") == b"data"


def test_hash_sig():
    a = hash_bytes(b"prog1")
    b = hash_bytes(b"prog1")
    c = hash_bytes(b"prog2")
    assert a == b and hash(a) == hash(b)
    assert a != c
    assert str(a) == hash_str(b"prog1")
    assert len(str(a)) == 40
