"""Seeded randomized property tests over the program model.

Mirrors the reference's prog test strategy (reference:
/root/reference/prog/mutation_test.go:13-47, encoding_test.go,
encodingexec_test.go): generate N random programs against the real linux
target and check invariants — clone identity, mutation changes serialization,
serialize/deserialize round-trips, exec-serialization decodes.
"""

import random

import pytest

from syzkaller_tpu.prog import get_target
from syzkaller_tpu.prog.encoding import deserialize, serialize
from syzkaller_tpu.prog.encodingexec import decode_exec, serialize_for_exec
from syzkaller_tpu.prog.generation import RandGen, generate
from syzkaller_tpu.prog.mutation import minimize, mutate
from syzkaller_tpu.prog.prio import build_choice_table, calculate_priorities

ITERS = 30
NCALLS = 12


@pytest.fixture(scope="module")
def target():
    return get_target("linux", "amd64")


@pytest.fixture(scope="module")
def ct(target):
    prios = calculate_priorities(target, [])
    return build_choice_table(target, prios)


def test_generate_valid(target, ct):
    for seed in range(ITERS):
        p = generate(target, seed, NCALLS, ct)
        assert 0 < len(p.calls) <= NCALLS
        p.validate()


def test_clone_identity(target, ct):
    for seed in range(ITERS):
        p = generate(target, seed, NCALLS, ct)
        q = p.clone()
        q.validate()
        assert serialize(p) == serialize(q)


def test_mutate_changes_program(target, ct):
    changed = 0
    for seed in range(ITERS):
        p = generate(target, seed, NCALLS, ct)
        s0 = serialize(p)
        q = p.clone()
        mutate(q, RandGen(target, seed=seed + 10_000), NCALLS, ct, [p])
        q.validate()
        if serialize(q) != s0:
            changed += 1
        # original must be untouched by mutating the clone
        assert serialize(p) == s0
    assert changed >= ITERS * 3 // 4


def test_serialize_roundtrip(target, ct):
    for seed in range(ITERS):
        p = generate(target, seed, NCALLS, ct)
        text = serialize(p)
        q = deserialize(target, text)
        q.validate()
        assert serialize(q) == text


def test_exec_serialization_decodes(target, ct):
    for seed in range(ITERS):
        p = generate(target, seed, NCALLS, ct)
        data = serialize_for_exec(p, pid=0)
        instrs = decode_exec(data)
        ncalls = sum(1 for i in instrs if i["op"] == "call")
        assert ncalls == len(p.calls)
        for ins in instrs:
            if ins["op"] == "call":
                assert 0 <= ins["id"] < len(target.syscalls)
                meta = target.syscalls[ins["id"]]
                assert len(ins["args"]) == len(meta.args)


def test_exec_result_refs_in_bounds(target, ct):
    """ExecArgResult indices must reference earlier instructions."""
    for seed in range(ITERS):
        p = generate(target, seed, NCALLS, ct)
        instrs = decode_exec(serialize_for_exec(p))
        seen = 0
        for ins in instrs:
            if ins["op"] == "call":
                for a in ins["args"]:
                    if a["kind"] == "result":
                        assert a["index"] < seen + len(ins["args"])
            seen += 1


def test_minimize_removes_calls(target, ct):
    rng = random.Random(1)
    for seed in range(10):
        p = generate(target, seed, NCALLS, ct)
        if len(p.calls) < 2:
            continue
        keep = p.calls[-1].meta.name
        # predicate: program still contains the last call's syscall
        q, ci = minimize(
            p, len(p.calls) - 1,
            lambda pp, ii: ii >= 0 and ii < len(pp.calls)
            and pp.calls[ii].meta.name == keep)
        q.validate()
        assert q.calls[ci].meta.name == keep
        assert len(q.calls) <= len(p.calls)


def test_mutate_respects_ncalls(target, ct):
    for seed in range(10):
        p = generate(target, seed, 6, ct)
        corpus = [generate(target, 1000 + seed, 6, ct)]
        for step in range(5):
            mutate(p, RandGen(target, seed=seed * 100 + step), 10, ct, corpus)
        p.validate()
        # ncalls is a soft cap (ctor-sequence insertion and mmap synthesis
        # for pointer args can overshoot, as in the reference); it must stay
        # bounded
        assert len(p.calls) <= 4 * 10


def test_deterministic_generation(target, ct):
    a = serialize(generate(target, 42, NCALLS, ct))
    b = serialize(generate(target, 42, NCALLS, ct))
    assert a == b
