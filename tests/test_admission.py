"""Device-side candidate admission tests (ISSUE 5 tentpole): row-hash
parity across jit/vmap/host, in-batch dedup correctness (identical rows
-> exactly one admitted), the Bloom filter's false-positive bound at
target occupancy + decay reset, and the launch-path guard that admission
and weighted sampling add no per-row host work."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

import jax.numpy as jnp  # noqa: E402

from syzkaller_tpu.descriptions.tables import get_tables  # noqa: E402
from syzkaller_tpu.engine.fuzzer import Fuzzer, FuzzerConfig  # noqa: E402
from syzkaller_tpu.ops import admission as adm  # noqa: E402
from syzkaller_tpu.ops import cover  # noqa: E402
from syzkaller_tpu.ops.arena import CorpusArena  # noqa: E402
from syzkaller_tpu.prog import get_target  # noqa: E402
from syzkaller_tpu.prog.generation import generate  # noqa: E402
from syzkaller_tpu.prog.tensor import (  # noqa: E402
    ProgBatch,
    TensorFormat,
    encode_prog,
)
from syzkaller_tpu.telemetry import get_registry  # noqa: E402
from syzkaller_tpu.telemetry.metrics import Registry  # noqa: E402


@pytest.fixture(scope="module")
def env():
    target = get_target("linux", "amd64")
    tables = get_tables(target)
    fmt = TensorFormat.for_tables(tables, max_calls=8)
    return target, tables, fmt


def _encode_rows(target, tables, fmt, n, seed=0):
    rows = []
    while len(rows) < n:
        p = generate(target, seed, 6)
        seed += 1
        b = ProgBatch.empty(fmt, 1)
        try:
            encode_prog(tables, fmt, p, b, 0)
        except Exception:
            continue
        rows.append((b.call_id[0].copy(), b.slot_val[0].copy(),
                     b.data[0].copy()))
    return rows


# --------------------------------------------------------------------- #
# row hash


def test_row_hash_parity_device_jit_vmap_host(env):
    """The same encoded row hashes identically on every path: eager
    device, jitted, vmapped over a batch axis, and the numpy host
    reference — the admission verdict must not depend on where the hash
    was computed."""
    target, tables, fmt = env
    rows = _encode_rows(target, tables, fmt, 6)
    cids = np.stack([r[0] for r in rows])
    svals = np.stack([r[1] for r in rows])
    datas = np.stack([r[2] for r in rows])
    vmapped = np.asarray(jax.vmap(adm.row_hash)(cids, svals, datas))
    jitted = jax.jit(adm.row_hash)
    for k, (cid, sval, data) in enumerate(rows):
        h_host = adm.row_hash_host(cid, sval, data)
        assert int(adm.row_hash(cid, sval, data)) == h_host
        assert int(jitted(cid, sval, data)) == h_host
        assert int(vmapped[k]) == h_host
    # distinct encoded programs hash distinctly (64-bit: a collision in
    # 6 rows means the fold is broken, not unlucky)
    assert len({int(h) for h in vmapped}) == len(rows)


def test_row_hash_is_position_sensitive(env):
    """Permuting call slots or nudging one data byte changes the hash —
    the fold keys every word by its position."""
    target, tables, fmt = env
    (cid, sval, data), = _encode_rows(target, tables, fmt, 1)
    h0 = adm.row_hash_host(cid, sval, data)
    perm = np.roll(cid, 1)
    assert adm.row_hash_host(perm, sval, data) != h0
    data2 = data.copy()
    data2[0, 0] ^= 1
    assert adm.row_hash_host(cid, sval, data2) != h0


# --------------------------------------------------------------------- #
# in-batch dedup


def test_inbatch_dedup_identical_rows_admit_exactly_one():
    rng = np.random.default_rng(0)
    uniq = rng.integers(0, 1 << 63, size=8, dtype=np.uint64)
    hashes = np.concatenate([uniq, uniq[:4], uniq[:1]])  # dups galore
    first = np.asarray(adm.inbatch_first_mask(jnp.asarray(hashes)))
    # exactly one keeper per distinct hash, and it is a real occurrence
    for h in np.unique(hashes):
        keepers = first & (hashes == h)
        assert keepers.sum() == 1
    bloom = adm.make_bloom(1 << 12)
    admit, bloom = adm.admit_mask(bloom, jnp.asarray(hashes))
    admit = np.asarray(admit)
    assert admit.sum() == len(uniq)
    # the whole batch is now remembered: nothing re-admits
    admit2, bloom = adm.admit_mask(bloom, jnp.asarray(hashes))
    assert not np.asarray(admit2).any()


def test_step_admits_exactly_one_of_identical_rows(env):
    """End-to-end dedup correctness through the sharded fuzz step: with
    mutation disabled (rounds=0) and all sampling weight on one arena
    row, every lane gathers the SAME program — admission must pass
    exactly one, and zero on the next launch (Bloom remembers)."""
    from syzkaller_tpu.ops.dtables import build_device_tables
    from syzkaller_tpu.parallel import mesh as pmesh

    target, tables, fmt = env
    dt = build_device_tables(tables, fmt)
    m = pmesh.make_mesh()
    n_fuzz = m.devices.shape[0]
    B = 4 * n_fuzz
    rows = _encode_rows(target, tables, fmt, 2)
    arena = CorpusArena(4, fmt, registry=Registry())
    for cid, sval, data in rows:
        arena.append(cid, sval, data)
    weights = jnp.zeros((4,), jnp.uint32).at[1].set(1)

    step, shardings = pmesh.make_arena_fuzz_step(m, dt, batch=B, rounds=0)
    nwords = max((1 << 12) // 32, 32 * m.devices.shape[1])
    sig = jax.device_put(jnp.zeros(nwords, jnp.uint32),
                         shardings["signal"])
    bloom = jax.device_put(jnp.zeros(nwords, jnp.uint32),
                           shardings["bloom"])
    key = jax.random.PRNGKey(3)
    a_cid, a_sval, a_data = arena.tensors()
    idx, cid, sval, data, sig, bloom, fresh, admit, opm, pop = step(
        key, a_cid, a_sval, a_data, weights, sig, bloom)
    np.testing.assert_array_equal(np.asarray(idx), np.full(B, 1))
    # rounds=0: the gathered rows really are bit-identical
    assert len({adm.row_hash_host(c, s, d) for c, s, d in zip(
        np.asarray(cid), np.asarray(sval), np.asarray(data))}) == 1
    assert int(np.asarray(admit).sum()) == 1
    # relaunch: the hash is in the Bloom filter now — zero admitted
    idx2, *_rest = out2 = step(
        jax.random.PRNGKey(4), a_cid, a_sval, a_data, weights, sig, bloom)
    admit2 = out2[7]
    assert int(np.asarray(admit2).sum()) == 0


# --------------------------------------------------------------------- #
# Bloom filter


def test_bloom_false_positive_rate_bounded_at_target_occupancy():
    """Fill the filter to ~50% bit occupancy (the default decay
    threshold), then probe fresh hashes: the false-positive rate must
    stay near the k-probe theory value occupancy**k (~6% at 0.5 with
    k=4) — the admission filter may cost occasional skipped novelty,
    never wholesale blindness."""
    rng = np.random.default_rng(7)
    nbits = 1 << 14
    bloom = adm.make_bloom(nbits)
    occ = 0.0
    while occ < 0.5:
        hs = rng.integers(0, 1 << 63, size=256, dtype=np.uint64)
        bloom = adm.bloom_add(bloom, jnp.asarray(hs))
        occ = float(adm.bloom_occupancy(bloom))
    assert occ < 0.56  # the 256-chunk granularity cannot overshoot far
    fresh = rng.integers(0, 1 << 63, size=4000, dtype=np.uint64)
    fp = float(np.asarray(
        adm.bloom_test(bloom, jnp.asarray(fresh))).mean())
    assert fp < 0.15, f"false-positive rate {fp:.3f} way above theory"
    # and everything actually added still tests positive (no false
    # negatives by construction)
    assert bool(np.asarray(adm.bloom_test(bloom, jnp.asarray(hs))).all())


def test_bloom_probes_reuse_cover_bitset_machinery():
    """The probes are plain u32 signals: bitset_add/bitset_test from
    ops/cover.py are the storage layer, no parallel implementation."""
    h = jnp.asarray([0x1234_5678_9ABC_DEF0], jnp.uint64)
    probes = adm.bloom_probes(h)
    assert probes.shape == (1, adm.BLOOM_PROBES)
    bits = cover.bitset_add(cover.make_bitset(1 << 10),
                            probes.reshape(-1))
    assert bool(np.asarray(cover.bitset_test(bits, probes)).all())
    assert bool(np.asarray(adm.bloom_test(bits, h)).all())


def test_engine_bloom_decay_resets_filter(env):
    """A tiny filter with a low decay threshold must hit the reset path
    during a short campaign (counted, occupancy gauge falls back)."""
    target, _, _ = env
    reg = get_registry()
    before = (reg.get("admission_bloom_resets_total").value
              if reg.get("admission_bloom_resets_total") else 0)
    cfg = FuzzerConfig(mock=True, use_device=True, device_batch=8,
                       program_length=8, smash_mutations=1,
                       device_period=2, arena_capacity=16,
                       admission_bloom_bits=1 << 11,
                       admission_bloom_decay=0.01)
    with Fuzzer(target, cfg) as f:
        if f._device is None:
            pytest.skip("jax device pipeline unavailable")
        for _ in range(400):
            f.step()
            if reg.get("admission_bloom_resets_total").value > before:
                break
        assert reg.get("admission_bloom_resets_total").value > before


# --------------------------------------------------------------------- #
# launch-path guard (ISSUE 5 acceptance)


def test_launch_path_no_per_row_host_work(env, monkeypatch):
    """Admission + weighted sampling run entirely on device in the
    steady state: the launch path performs no host row hashing, no
    host-side weighted sampling or weight normalization, and no O(B)
    host batch staging (same style as the PR 3 no-np.stack guard)."""
    target, _, _ = env
    cfg = FuzzerConfig(mock=True, use_device=True, device_batch=8,
                       program_length=8, smash_mutations=1,
                       device_period=2, arena_capacity=32)
    with Fuzzer(target, cfg) as f:
        if f._device is None:
            pytest.skip("jax device pipeline unavailable")
        for _ in range(200):
            f.step()
            if f._device.arena.size >= 1 and \
                    f.stats["device_batches"] >= 1:
                break
        assert f._device.arena.size >= 1

        def boom(what):
            def _b(*a, **k):
                raise AssertionError(f"{what} on the launch path")
            return _b

        monkeypatch.setattr(adm, "row_hash_host",
                            boom("host row hashing"))
        monkeypatch.setattr(CorpusArena, "sample_indices",
                            boom("host-side weighted sampling"))
        monkeypatch.setattr(CorpusArena, "host_weights",
                            boom("host weight normalization"))
        monkeypatch.setattr(np, "stack", boom("np.stack host staging"))
        before = f.stats["device_batches"]
        for _ in range(400):
            f.step()
            if f.stats["device_batches"] > before:
                break
        assert f.stats["device_batches"] > before
