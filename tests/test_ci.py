"""CI orchestration tests (reference syz-ci two-build latest/current
semantics, broken-head resilience, restart-on-update)."""

import os
import sys

import pytest

from syzkaller_tpu.ci import (
    CI,
    BuildInfo,
    CIManager,
    CIManagerConfig,
    Updater,
    executor_build_steps,
)


class FakeSource:
    """Injectable poll/build: version bumps on demand, optionally broken."""

    def __init__(self):
        self.version = "v1"
        self.broken = False
        self.builds = 0

    def poll(self):
        return self.version

    def build(self, tag, outdir):
        self.builds += 1
        if self.broken:
            raise RuntimeError("compile error")
        with open(os.path.join(outdir, "artifact"), "w") as f:
            f.write(tag)


def test_updater_latest_current(tmp_path):
    src = FakeSource()
    up = Updater(str(tmp_path), src.poll, src.build)
    assert up.poll_and_build() is True
    assert BuildInfo.load(up.latest).tag == "v1"
    # same version: no rebuild
    assert up.poll_and_build() is False
    assert src.builds == 1
    # use_latest copies into current
    assert up.use_latest().tag == "v1"
    assert open(os.path.join(up.current, "artifact")).read() == "v1"
    # version moves: rebuild + promote
    src.version = "v2"
    assert up.poll_and_build() is True
    assert up.use_latest().tag == "v2"


def test_broken_head_keeps_last_known_good(tmp_path):
    src = FakeSource()
    up = Updater(str(tmp_path), src.poll, src.build)
    up.poll_and_build()
    src.version = "v2"
    src.broken = True
    assert up.poll_and_build() is False
    assert up.build_failures == 1
    # latest still the good v1 build; current still usable
    assert BuildInfo.load(up.latest).tag == "v1"
    assert up.use_latest().tag == "v1"
    # head fixed: recovers
    src.broken = False
    assert up.poll_and_build() is True
    assert BuildInfo.load(up.latest).tag == "v2"


def test_failed_test_step_blocks_promotion(tmp_path):
    src = FakeSource()

    def bad_test(d):
        raise RuntimeError("selftest failed")

    up = Updater(str(tmp_path), src.poll, src.build, test=bad_test)
    assert up.poll_and_build() is False
    assert BuildInfo.load(up.latest) is None
    assert up.use_latest() is None


def test_ci_manager_restart_on_update(tmp_path):
    src = FakeSource()
    up = Updater(str(tmp_path / "build"), src.poll, src.build)
    # managed process: sleeps forever; uses {current} to prove expansion
    mgr = CIManager(str(tmp_path / "m1"), CIManagerConfig(
        name="m1",
        argv=[sys.executable, "-c",
              "import sys, time; open(sys.argv[1]).close(); "
              "time.sleep(60)", "{current}/artifact"]), up)
    ci = CI(up, [mgr], poll_period=0.1)
    try:
        r = ci.run_once()
        assert r == {"updated": 1, "started": 1}
        pid1 = mgr.proc.pid
        assert mgr.proc.poll() is None
        # no change: process left alone
        assert ci.run_once() == {"updated": 0, "started": 0}
        assert mgr.proc.pid == pid1
        # update: restart with the new build
        src.version = "v2"
        r = ci.run_once()
        assert r["updated"] == 1
        assert mgr.proc.pid != pid1
        # process death: next cycle resurrects it
        mgr.proc.kill()
        mgr.proc.wait()
        assert ci.run_once() == {"updated": 0, "started": 1}
        assert mgr.proc.poll() is None
    finally:
        ci.stop()


def test_executor_build_steps(tmp_path):
    import pathlib

    repo = str(pathlib.Path(__file__).resolve().parent.parent)
    poll, build, test = executor_build_steps(repo)
    up = Updater(str(tmp_path), poll, build, test)
    assert up.poll_and_build() is True
    exe = os.path.join(up.latest, "syz-executor")
    assert os.path.isfile(exe) and os.access(exe, os.X_OK)
    # second poll: mtime fingerprint unchanged -> no rebuild
    assert up.poll_and_build() is False
