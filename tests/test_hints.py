"""Hints tests: table-driven CompMap -> expected mutants (the reference's
prog/hints_test.go:1-338 strategy) plus host<->device parity."""

import random

import pytest

from syzkaller_tpu.prog import get_target
from syzkaller_tpu.prog.encoding import deserialize, serialize
from syzkaller_tpu.prog.hints import (
    CompMap,
    mutate_with_hints,
    shrink_expand,
)


@pytest.fixture(scope="module")
def target():
    return get_target("linux", "amd64")


# ---- shrink_expand table (semantics from reference hints.go:120-178) ----

def se(v, pairs):
    return shrink_expand(v, CompMap.from_pairs(pairs))


def test_trivial_match():
    # direct 64-bit match: replace whole value
    assert se(0xDEAD, [(0xDEAD, 0xCAFE)]) == {0xCAFE}


def test_shrink_u8():
    # f(u16 0x1234): kernel compares (u8)0x34 vs 0xab -> splice low byte
    assert se(0x1234, [(0x34, 0xAB)]) == {0x12AB}


def test_shrink_u16():
    assert se(0xABCD1234, [(0x1234, 0x5678)]) == {0xABCD5678}


def test_shrink_rejects_wide_comparand():
    # comparand wider than the cast width: no valid code does this
    assert se(0x1234, [(0x34, 0xDEADBEEF)]) == set()


def test_expand_sign_extension():
    # f(i8 -1): kernel compares 0xff..ff vs 0xff..fe -> splice to -2
    v = 0xFF
    comps = [(0xFFFFFFFFFFFFFFFF, 0xFFFFFFFFFFFFFFFE)]
    assert 0xFE in se(v, comps)


def test_sign_extended_comparand_accepted():
    # comparand with all-ones high bits fits (it is the sign extension)
    assert se(0x1234, [(0x34, 0xFFFFFFFFFFFFFFFE)]) == {0x12FE}


def test_special_ints_excluded():
    # 0 and 0xff are special ints the generator already tries unprompted
    assert se(0x1234, [(0x1234, 0)]) == set()
    assert se(0x1234, [(0x34, 0xFF)]) == set()


def test_no_self_replacement():
    assert se(0x1234, [(0x1234, 0x1234)]) == set()


def test_multiple_comparands():
    got = se(0x10, [(0x10, 0x21), (0x10, 0x33)])
    assert got == {0x21, 0x33}


# ---- mutate_with_hints over real programs ----

def test_hint_mutants_const_arg(target):
    p = deserialize(target, "alarm(0x1234)\n")
    comps = [CompMap.from_pairs([(0x1234, 0x4444), (0x34, 0xAB)])]
    mutants = []
    n = mutate_with_hints(p, comps, lambda q: mutants.append(q))
    assert n == len(mutants) == 2
    vals = sorted(m.calls[0].args[0].val for m in mutants)
    assert vals == [0x12AB, 0x4444]
    # original untouched
    assert p.calls[0].args[0].val == 0x1234
    for m in mutants:
        serialize(m)  # must remain serializable


def test_hint_mutants_data_arg(target):
    # write(fd, ptr[data "abcd"], len): data byte scan should splice
    p = deserialize(
        target, 'write(0xffffffffffffffff, &0:0:0="abcd1234", 0x4)\n')
    arg = p.calls[0].args[1].res
    assert arg.data == b"abcd1234"
    # the u64 read at byte offset 2 of the buffer
    base = int.from_bytes(b"cd1234", "little")  # zero-padded to 8
    comps = [CompMap.from_pairs([(base, 0x6666)])]
    mutants = []
    mutate_with_hints(p, comps, lambda q: mutants.append(q))
    assert len(mutants) == 1
    new_data = mutants[0].calls[0].args[1].res.data
    assert new_data != arg.data
    assert new_data[:2] == b"ab"  # splice at offset 2 leaves prefix


def test_mmap_calls_skipped(target):
    p = deserialize(
        target, "mmap(&vma 0:1, 0x1000, 0x3, 0x32, 0xffffffffffffffff, 0x0)\n")
    comps = [CompMap.from_pairs([(0x1000, 0x2000)])]
    n = mutate_with_hints(p, comps, lambda q: None)
    assert n == 0


# ---- host <-> device parity ----

def test_device_parity_random():
    jnp = pytest.importorskip("jax.numpy")
    import numpy as np

    from syzkaller_tpu.ops.hints import hint_matrix, unique_replacers
    from syzkaller_tpu.prog.generation import SPECIAL_INTS

    rng = random.Random(0)
    M, N = 24, 64
    vals = [rng.getrandbits(rng.choice([8, 16, 32, 64])) for _ in range(M)]
    pairs = []
    for _ in range(N):
        if pairs and rng.random() < 0.5:
            # derive ops from value casts so there are real matches
            v = rng.choice(vals)
            w = rng.choice([8, 16, 32, 64])
            mask = (1 << w) - 1
            op = v & mask
            if v & (1 << (w - 1)) and rng.random() < 0.5:
                op = (v | ~mask) & 0xFFFFFFFFFFFFFFFF
            pairs.append((op, rng.getrandbits(rng.choice([8, 16, 64]))))
        else:
            pairs.append((rng.getrandbits(64), rng.getrandbits(64)))

    comps = CompMap.from_pairs(pairs)
    expected = [shrink_expand(v, comps) for v in vals]

    ok, rep = hint_matrix(
        np.array(vals, np.uint64),
        np.array([a for a, _ in pairs], np.uint64),
        np.array([b for _, b in pairs], np.uint64),
        np.array([v & 0xFFFFFFFFFFFFFFFF for v in SPECIAL_INTS], np.uint64))
    out, mask = unique_replacers(ok, rep, max_out=64)
    for i in range(M):
        got = set(int(x) for x, m in zip(out[i], mask[i]) if m)
        assert got == expected[i], (i, hex(vals[i]))
