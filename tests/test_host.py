"""Supported-syscall detection tests (reference pkg/host/host_linux.go)."""

import os

import pytest

from syzkaller_tpu import host
from syzkaller_tpu.prog import get_target


@pytest.fixture(scope="module")
def target():
    return get_target("linux", "amd64")


def _fake_kallsyms(names):
    return b"".join(b"ffffffff81000000 T sys_%s\n" % n.encode()
                    for n in names)


def test_kallsyms_probe(target):
    ks = _fake_kallsyms(["open", "close", "read"])
    by_name = {m.name: m for m in target.syscalls}
    assert host.is_supported(ks, by_name["open"])
    assert host.is_supported(ks, by_name["close"])
    assert not host.is_supported(ks, by_name["mmap"])
    # variants share the base call's symbol
    assert host.is_supported(ks, by_name["open$dir"])


def test_empty_kallsyms_means_all(target):
    by_name = {m.name: m for m in target.syscalls}
    assert host.is_supported(b"", by_name["mmap"])


def test_modern_symbol_prefix(target):
    by_name = {m.name: m for m in target.syscalls}
    ks = b"ffffffff81000000 T __x64_sys_mmap\n"
    assert host.is_supported(ks, by_name["mmap"])


def test_socket_probe(target):
    by_name = {m.name: m for m in target.syscalls}
    # AF_UNIX and AF_INET exist everywhere this test runs
    assert host.is_supported(b"", by_name["socket$unix"])
    assert host.is_supported(b"", by_name["socket$tcp"])


def test_live_detection_sane(target):
    """On the live machine a healthy majority of the corpus must probe as
    supported, and the ctor closure must keep resource chains intact."""
    supported = host.detect_supported_syscalls(target)
    n_ok = sum(supported.values())
    assert n_ok > len(target.syscalls) // 2
    ids = host.build_call_list(target)
    assert ids
    names = {target.syscalls[i].name for i in ids}
    # closure property: every enabled resource consumer has a ctor enabled
    if "close" in names:
        assert any(n.startswith("open") or n.startswith("socket")
                   or n == "dup" for n in names)


def test_transitive_pruning(target):
    """A consumer whose only ctor is unsupported gets pruned."""
    # enable only close (consumes fd) with no fd producer
    by_name = {m.name: m for m in target.syscalls}
    ids = host.build_call_list(
        target, enabled=[by_name["close"].id],
        kallsyms=_fake_kallsyms(["close"]))
    assert by_name["close"].id not in ids
