"""Description pipeline tests: parser, compiler, target invariants.

Mirrors the reference's compiler-test strategy (fixture descriptions +
structural checks; reference: /root/reference/pkg/compiler/compiler_test.go)
but asserts on the live Target instead of golden generated files.
"""

import pytest

from syzkaller_tpu.descriptions.compiler import Compiler, compile_description
from syzkaller_tpu.descriptions.parser import ParseError, parse
from syzkaller_tpu.prog import (
    BufferKind, BufferType, ConstType, Dir, FlagsType, IntType, LenType,
    PtrType, ResourceType, StructType, UnionType,
)
from syzkaller_tpu.prog.target import get_target


def test_parse_basic():
    d = parse(
        """
# comment
resource fd[int32]: -1, AT_FDCWD
open_flags = O_A, O_B, 0x4
names = "a", "bb"
foo(a fd, b ptr[in, bar], c flags[open_flags]) fd
bar {
\tf1\tint32
\tf2\tint8:3
\tf3\tint8:5
}
baz [
\topt1\tint64
\topt2\tarray[int8, 8]
]
"""
    )
    kinds = [type(n).__name__ for n in d.nodes]
    assert kinds == ["ResourceDef", "FlagsDef", "StrFlagsDef", "CallDef",
                     "StructDef", "StructDef"]


def test_parse_error():
    with pytest.raises(ParseError):
        parse("foo(a b c)")


def _mini_target():
    d = parse(
        """
resource fd[int32]: -1

open(file ptr[in, filename], flags flags[oflags]) fd
close(fd fd)
read(fd fd, buf buffer[out], n len[buf])
use_s(s ptr[in, s_t])

s_t {
\ta\tint32
\tb\tint8
\tc\tint64
\td\tint16be
\te\tarray[int8, 3]
}

oflags = O_X, O_Y
"""
    )
    return compile_description(
        d, {"__NR_open": 2, "__NR_close": 3, "__NR_read": 0,
            "__NR_use_s": 99, "O_X": 1, "O_Y": 2})


def test_compile_mini():
    t = _mini_target()
    assert [c.name for c in t.syscalls] == ["open", "close", "read", "use_s"]
    o = t.syscall_map["open"]
    assert isinstance(o.args[0], PtrType)
    assert isinstance(o.args[0].elem, BufferType)
    assert o.args[0].elem.kind == BufferKind.FILENAME
    assert isinstance(o.ret, ResourceType)
    assert o.ret.dir == Dir.OUT
    r = t.syscall_map["read"]
    assert isinstance(r.args[2], LenType) and r.args[2].buf == "buf"
    # layout: a(4) b(1) pad(1) d-align... a=0,b=4,pad,e...
    s = t.syscall_map["use_s"].args[0].elem
    assert isinstance(s, StructType)
    sizes = [(f.field_name, f.size) for f in s.fields]
    # a:4 b:1 pad:3 c:8 d:2 e:3 pad:3 -> 24 total, align 8
    assert s.size == 24, sizes


def test_linux_target_loads():
    t = get_target("linux", "amd64")
    assert len(t.syscalls) > 150
    assert "open" in t.syscall_map
    assert t.syscall_map["open"].nr == 2
    assert t.mmap_syscall is not None
    # every resource has at least one ctor or is a root (uid/gid via getuid)
    assert t.resource_ctors["fd"], "fd must have constructors"
    # all calls remain enabled under transitive closure
    assert len(t.transitively_enabled_calls(t.syscalls)) == len(t.syscalls)


def test_linux_resource_compat():
    t = get_target("linux", "amd64")
    assert t.is_compatible_resource("fd", "sock")
    assert t.is_compatible_resource("sock", "fd")  # imprecise direction
    assert not t.is_compatible_resource("sock_tcp", "sock_udp")


def test_mmap_hook():
    t = get_target("linux", "amd64")
    c = t.make_mmap(3, 2)
    assert c.meta.name == "mmap"
    assert c.args[0].page_index == 3 and c.args[0].pages_num == 2
    start, npages, mapped = t.analyze_mmap(c)
    assert (start, npages, mapped) == (3, 2, True)


def test_sanitize_mmap_forces_fixed():
    t = get_target("linux", "amd64")
    c = t.make_mmap(0, 1)
    c.args[3].val = 0
    t.sanitize_call(c)
    assert c.args[3].val & t.consts["MAP_FIXED"]


# --------------------------------------------------------------------- #
# Bundled linux corpus: breadth + extraction pipeline


def test_linux_corpus_breadth():
    """The bundled sys/linux-equivalent corpus covers the major subsystems."""
    target = get_target("linux", "amd64")
    names = {s.name for s in target.syscalls}
    assert len(target.syscalls) >= 350
    for expected in [
        # fs / fd
        "open", "openat", "close", "splice", "epoll_ctl$add", "memfd_create",
        # sockets incl. v6/netlink/packet
        "socket$tcp", "socket$udp6", "socket$netlink", "bind$packet",
        "sendto$netlink", "setsockopt$inet_tcp_int",
        # sysv ipc + mqueue
        "msgsnd", "semop", "shmat", "mq_timedsend",
        # signals / process
        "rt_sigaction", "tgkill", "wait4", "exit_group",
        # keys, bpf, perf, ptrace, aio, ns
        "add_key", "keyctl$search", "bpf$MAP_CREATE", "perf_event_open",
        "ptrace$setopts", "io_submit", "unshare", "capset", "seccomp$set_mode_strict",
    ]:
        assert expected in names, f"missing {expected}"
    # every syscall got a real number (pseudo-calls are in the high range)
    for s in target.syscalls:
        assert s.nr >= 0


def test_linux_corpus_generates():
    """Generation exercises the new subsystems without validation errors."""
    from syzkaller_tpu.prog.generation import generate
    from syzkaller_tpu.prog.prio import build_choice_table, calculate_priorities

    target = get_target("linux", "amd64")
    ct = build_choice_table(target, calculate_priorities(target, []))
    seen = set()
    for seed in range(30):
        p = generate(target, seed, 12, ct)
        p.validate()
        seen.update(c.meta.call_name for c in p.calls)
    # a healthy choice table should spread across many distinct syscalls
    assert len(seen) >= 40


def test_extract_collect_idents():
    """The syz-extract-equivalent ident collector: consts vs fields vs types."""
    from syzkaller_tpu.descriptions.extract import collect_idents

    d = parse(
        """
include <linux/foo.h>
foo_flags = FOO_A, FOO_B
foo(a const[FOO_C], b ptr[in, bar], n len[b]) fd
bar {
\tf1\tflags[foo_flags, int32]
\tsz\tbytesize[parent, int32]
}
"""
    )
    consts, calls, includes = collect_idents(d)
    assert includes == ["linux/foo.h"]
    assert calls == {"foo"}
    assert {"FOO_A", "FOO_B", "FOO_C"} <= consts
    # field names / keywords / local defs must not leak into the probe set
    assert "parent" not in consts and "b" not in consts
    assert "foo_flags" not in consts and "int32" not in consts


def test_extract_consts_live(tmp_path):
    """End-to-end extraction against the real system headers."""
    from syzkaller_tpu.descriptions.extract import extract_consts

    vals, unresolved = extract_consts(
        {"O_RDONLY", "O_CREAT", "SIGKILL", "NOT_A_REAL_CONST_XYZ"},
        includes=[])
    assert vals["O_RDONLY"] == 0
    assert vals["SIGKILL"] == 9
    assert "NOT_A_REAL_CONST_XYZ" in unresolved
