"""Fuzzer engine tests: the hermetic end-to-end loop against MockEnv, and
(when the toolchain allows) the real executor."""

import shutil
import threading
import time

import pytest

from syzkaller_tpu.engine.fuzzer import Fuzzer, FuzzerConfig, ManagerConn
from syzkaller_tpu.engine.queue import (
    CandidateItem,
    SmashItem,
    TriageItem,
    WorkQueue,
)
from syzkaller_tpu.prog import get_target
from syzkaller_tpu.prog.encoding import deserialize, serialize
from syzkaller_tpu.prog.generation import generate


@pytest.fixture(scope="module")
def target():
    return get_target("linux", "amd64")


def mk(target, **kw) -> Fuzzer:
    kw.setdefault("mock", True)
    kw.setdefault("use_device", False)
    kw.setdefault("smash_mutations", 3)
    return Fuzzer(target, FuzzerConfig(**kw))


def test_queue_priority_order(target):
    q = WorkQueue()
    p = generate(target, 0, 3)
    q.push_smash(SmashItem(p))
    q.push_triage(TriageItem(p, 0, [1]))
    q.push_triage(TriageItem(p, 0, [2], from_candidate=True))
    q.push_candidate(CandidateItem(p))
    kinds = []
    while (item := q.pop()) is not None:
        kinds.append((type(item).__name__,
                      getattr(item, "from_candidate", None)))
    assert kinds == [("TriageItem", True), ("CandidateItem", None),
                     ("TriageItem", False), ("SmashItem", None)]


def test_loop_grows_corpus(target):
    with mk(target) as f:
        f.loop(iterations=50)
        assert f.stats["exec_total"] >= 50
        assert len(f.corpus) > 0          # mock signal must triage inputs
        assert f.stats["new_inputs"] == len(f.corpus)
        assert len(f.max_signal) > 0
        assert f.corpus_signal <= f.max_signal


def test_triage_minimizes(target):
    with mk(target) as f:
        # execute one program; triage queue fills from novel signal
        p = generate(target, 3, 6)
        f.execute(p)
        item = f.queue.pop()
        assert isinstance(item, TriageItem)
        before = len(item.prog.calls)
        f.triage(item)
        assert len(f.corpus) >= 1
        # minimization can only shrink
        assert all(len(q.calls) <= before for q in f.corpus)


def test_signal_dedup_no_retriage(target):
    with mk(target) as f:
        p = deserialize(target, "r0 = getpid()\n")
        f.execute(p)
        while (item := f.queue.pop()) is not None:
            if isinstance(item, TriageItem):
                f.triage(item)
        execs = f.stats["exec_total"]
        # same program again: no new signal, no new triage work
        f.execute(p)
        assert f.queue.pop() is None
        assert f.stats["exec_total"] == execs + 1


def test_candidates_from_manager(target):
    class Mgr(ManagerConn):
        def connect(self):
            c = super().connect()
            c["candidates"] = ["r0 = getpid()\n"]
            return c

        def __init__(self):
            self.inputs = []

        def new_input(self, text, ci, sig, cover):
            self.inputs.append(text)

    mgr = Mgr()
    f = Fuzzer(target, FuzzerConfig(mock=True, use_device=False,
                                    smash_mutations=2), manager=mgr)
    with f:
        f.loop(iterations=10)
        assert f.stats["exec_candidate"] >= 1
        assert mgr.inputs  # triaged input reported back


def test_stats_flow(target):
    with mk(target) as f:
        f.loop(iterations=30)
        f.poll_manager()
        assert not f.new_signal  # cleared after poll


@pytest.mark.skipif(shutil.which("g++") is None, reason="no toolchain")
def test_real_executor_loop(target):
    with mk(target, mock=False, smash_mutations=2) as f:
        f.loop(iterations=12)
        assert f.stats["exec_total"] >= 12
        # synthetic executor signal also grows a corpus
        assert len(f.corpus) > 0


def test_device_pipeline(target):
    jax = pytest.importorskip("jax")
    cfg = FuzzerConfig(mock=True, use_device=True, device_batch=8,
                       program_length=8, smash_mutations=2)
    with Fuzzer(target, cfg) as f:
        assert f._device is not None
        # run until the queue drains and the double-buffered device path
        # has produced at least one decoded batch
        for _ in range(600):
            f.step()
            if f.stats["device_candidates"]:
                break
        assert f.stats["device_batches"] >= 1
        assert f.stats["device_candidates"] > 0


def test_device_hints_join_in_smash(target):
    """With a device present, smash's hint seeds go through the batched
    ops/hints join (one XLA kernel per call) and the resulting mutants
    execute — the BASELINE config[3] path, live in the engine."""
    pytest.importorskip("jax")
    cfg = FuzzerConfig(mock=True, use_device=True, collect_comps=True,
                       device_batch=8, program_length=6,
                       smash_mutations=1, device_period=1000)
    with Fuzzer(target, cfg) as f:
        for _ in range(400):
            f.step()
            if f.stats.get("hints_device_joins", 0) > 0 and \
                    f.stats.get("exec_hints", 0) > 1:
                break
        assert f.stats.get("hints_device_joins", 0) > 0
        # joins produced actual executed mutants (beyond the seed exec)
        assert f.stats["exec_hints"] > f.stats["hints_device_joins"]


class _CountingEnv:
    """Fake executor env for the drain fan-out: counts exec_raw calls,
    detects concurrent entry (per-env serialization must hold), and
    sleeps long enough that the pool provably overlaps workers."""

    def __init__(self):
        self.execs = 0
        self.overlapped = False
        self._busy = threading.Lock()

    def exec_raw(self, opts, data, call_ids):
        if not self._busy.acquire(blocking=False):
            self.overlapped = True
            raise AssertionError("concurrent exec_raw on one env")
        try:
            time.sleep(0.002)
            self.execs += 1
            return b"", [], False, False
        finally:
            self._busy.release()

    def close(self):
        pass


class _FakeBatch:
    """Minimal _DeviceBatch stand-in: every row is a raw stream."""

    def __init__(self, n):
        self.streams = [b"\x00"] * n
        self.dropped = 0

    def __len__(self):
        return len(self.streams)

    def op_mask(self, row):
        return 1

    def src_row(self, row):
        return -1  # no arena sampling provenance

    def src_age(self, row):
        return -1

    def call_ids(self, row):
        return [0, 1]  # prelude mmap + one live call: row executes

    def decode(self, row):
        return None


def test_parallel_drain_fans_out_across_envs(target):
    """One device batch drains across ALL envs: rows are dynamically
    balanced over one worker per env, per-env serialization holds, and
    every stat lands exactly once through the locked helper."""
    with mk(target, procs=4) as f:
        envs = [_CountingEnv() for _ in range(4)]
        f.envs = envs
        before_fuzz = f.stats["exec_fuzz"]
        before_total = f.stats["exec_total"]
        f._run_device_batch_inner(_FakeBatch(40))
        assert sum(e.execs for e in envs) == 40
        assert not any(e.overlapped for e in envs)
        # dynamic row-pull with a 2ms exec: every worker gets rows
        assert sum(1 for e in envs if e.execs) >= 3
        assert f.stats["exec_fuzz"] == before_fuzz + 40
        assert f.stats["exec_total"] == before_total + 40
        occ = f.metrics.get("device_drain_env_occupancy")
        assert occ is not None and occ.value >= 0.75


def test_parallel_drain_single_env_inline(target):
    """procs=1 drains inline (no pool), same accounting."""
    with mk(target) as f:
        env = _CountingEnv()
        f.envs = [env]
        f._run_device_batch_inner(_FakeBatch(5))
        assert env.execs == 5
        assert f.stats["exec_fuzz"] == 5
        assert f._drain_pool is None  # never built for one env


def test_device_drain_multiproc_integration(target):
    """End-to-end: the device pipeline's batches drain across a 3-env
    mock fleet and the exec stat ledger stays exactly consistent (every
    exec recorded once despite the worker threads)."""
    pytest.importorskip("jax")
    cfg = FuzzerConfig(mock=True, use_device=True, device_batch=16,
                       program_length=8, smash_mutations=1,
                       device_period=4, procs=3)
    with Fuzzer(target, cfg) as f:
        for _ in range(600):
            f.step()
            if f.stats["device_candidates"] >= 16:
                break
        assert f.stats["device_candidates"] >= 16
        parts = ("exec_gen", "exec_fuzz", "exec_candidate", "exec_triage",
                 "exec_minimize", "exec_smash", "exec_hints")
        assert f.stats["exec_total"] == sum(f.stats[k] for k in parts)


def test_batch_call_ids_vectorized_parity(target):
    """The batch-vectorized call_ids equals the per-row walk it
    replaced (prelude mmap + live calls, in slot order)."""
    pytest.importorskip("jax")
    cfg = FuzzerConfig(mock=True, use_device=True, device_batch=8,
                       program_length=8, smash_mutations=1,
                       device_period=2)
    with Fuzzer(target, cfg) as f:
        batch = None
        for _ in range(200):
            f.step()
            if f.corpus:
                batch = f._device.candidates(f.corpus)
                if batch is not None and len(batch):
                    break
        assert batch is not None and len(batch)
        mm = target.mmap_syscall.id
        for row in range(len(batch)):
            expect = [mm] + [int(c) for c in batch.batch.call_id[row]
                             if int(c) >= 0]
            assert batch.call_ids(row) == expect


def test_device_pipeline_runs_sharded_mesh_step(target):
    """The production pipeline runs the SHARDED fuzz step over the whole
    visible mesh (8 virtual devices under conftest), not a single-device
    path, and the device-side fresh mask gates stale candidates."""
    pytest.importorskip("jax")
    import jax

    cfg = FuzzerConfig(mock=True, use_device=True, device_batch=16,
                       program_length=8, smash_mutations=1,
                       device_period=4)
    with Fuzzer(target, cfg) as f:
        assert f._device is not None
        dev = f._device
        assert dev.mesh.devices.size == len(jax.devices())
        assert dev.n_fuzz * dev.n_cover == dev.mesh.devices.size
        assert dev.B % dev.n_fuzz == 0
        # the sharded proxy bitset lives on the cover axis
        assert dev._sig_shard.shape[0] % dev.n_cover == 0
        for _ in range(400):
            f.step()
            if f.stats.get("device_batches", 0) >= 3:
                break
        assert f.stats["device_batches"] >= 3
        # after a few batches the proxy set has content: freshness gating
        # is live (dropped counter exists, even if zero early on)
        assert "device_dropped_stale" in f.stats
        import numpy as np

        bits = int(np.asarray(
            jax.device_get(dev._sig_shard), dtype=np.uint32).sum())
        assert bits != 0, "sharded proxy signal set never folded"


def test_device_raw_path_feeds_triage(target):
    """Device candidates execute as raw exec streams (no Prog trees) and
    rows with new signal are lazily decoded into triage items that the
    regular loop then turns into corpus entries."""
    pytest.importorskip("jax")
    cfg = FuzzerConfig(mock=True, use_device=True, device_batch=16,
                       program_length=8, smash_mutations=1,
                       device_period=4)
    with Fuzzer(target, cfg) as f:
        assert f._device is not None
        corpus_before = None
        for _ in range(800):
            f.step()
            if f.stats["device_candidates"] and corpus_before is None:
                corpus_before = len(f.corpus)
            if corpus_before is not None and \
                    f.stats["exec_triage"] > 0 and \
                    len(f.corpus) > corpus_before:
                break
        assert f.stats["device_candidates"] > 0
        # raw streams were emitted (the emit path, not the fallback)
        batch = f._device.candidates(f.corpus)
        assert batch is not None
        raws = [s for s in batch.streams if s is not None]
        assert raws, "no raw streams emitted — fast path inactive"
        # raw stream + call_ids round-trip through the mock env (pick a
        # row that still has calls — mutation can empty a program, whose
        # stream is a legal EOF-only bytes object)
        from syzkaller_tpu.ipc import ExecOpts

        row = next(r for r, s in enumerate(batch.streams)
                   if s is not None and len(batch.call_ids(r)) > 1)
        _, infos, failed, hanged = f.envs[0].exec_raw(
            ExecOpts(), batch.streams[row], batch.call_ids(row))
        assert not failed and not hanged
        assert infos and infos[0].executed
