"""Fuzzer engine tests: the hermetic end-to-end loop against MockEnv, and
(when the toolchain allows) the real executor."""

import shutil
import threading
import time

import pytest

from syzkaller_tpu.engine.fuzzer import Fuzzer, FuzzerConfig, ManagerConn
from syzkaller_tpu.engine.queue import (
    CandidateItem,
    SmashItem,
    TriageItem,
    WorkQueue,
)
from syzkaller_tpu.prog import get_target
from syzkaller_tpu.prog.encoding import deserialize, serialize
from syzkaller_tpu.prog.generation import generate


@pytest.fixture(scope="module")
def target():
    return get_target("linux", "amd64")


def mk(target, **kw) -> Fuzzer:
    kw.setdefault("mock", True)
    kw.setdefault("use_device", False)
    kw.setdefault("smash_mutations", 3)
    return Fuzzer(target, FuzzerConfig(**kw))


def test_queue_priority_order(target):
    q = WorkQueue()
    p = generate(target, 0, 3)
    q.push_smash(SmashItem(p))
    q.push_triage(TriageItem(p, 0, [1]))
    q.push_triage(TriageItem(p, 0, [2], from_candidate=True))
    q.push_candidate(CandidateItem(p))
    kinds = []
    while (item := q.pop()) is not None:
        kinds.append((type(item).__name__,
                      getattr(item, "from_candidate", None)))
    assert kinds == [("TriageItem", True), ("CandidateItem", None),
                     ("TriageItem", False), ("SmashItem", None)]


def test_loop_grows_corpus(target):
    with mk(target) as f:
        f.loop(iterations=50)
        assert f.stats["exec_total"] >= 50
        assert len(f.corpus) > 0          # mock signal must triage inputs
        assert f.stats["new_inputs"] == len(f.corpus)
        assert len(f.max_signal) > 0
        assert f.corpus_signal <= f.max_signal


def test_triage_minimizes(target):
    with mk(target) as f:
        # execute one program; triage queue fills from novel signal
        p = generate(target, 3, 6)
        f.execute(p)
        item = f.queue.pop()
        assert isinstance(item, TriageItem)
        before = len(item.prog.calls)
        f.triage(item)
        assert len(f.corpus) >= 1
        # minimization can only shrink
        assert all(len(q.calls) <= before for q in f.corpus)


def test_signal_dedup_no_retriage(target):
    with mk(target) as f:
        p = deserialize(target, "r0 = getpid()\n")
        f.execute(p)
        while (item := f.queue.pop()) is not None:
            if isinstance(item, TriageItem):
                f.triage(item)
        execs = f.stats["exec_total"]
        # same program again: no new signal, no new triage work
        f.execute(p)
        assert f.queue.pop() is None
        assert f.stats["exec_total"] == execs + 1


def test_candidates_from_manager(target):
    class Mgr(ManagerConn):
        def connect(self):
            c = super().connect()
            c["candidates"] = ["r0 = getpid()\n"]
            return c

        def __init__(self):
            self.inputs = []

        def new_input(self, text, ci, sig, cover):
            self.inputs.append(text)

    mgr = Mgr()
    f = Fuzzer(target, FuzzerConfig(mock=True, use_device=False,
                                    smash_mutations=2), manager=mgr)
    with f:
        f.loop(iterations=10)
        assert f.stats["exec_candidate"] >= 1
        assert mgr.inputs  # triaged input reported back


def test_stats_flow(target):
    with mk(target) as f:
        f.loop(iterations=30)
        f.poll_manager()
        assert not f.new_signal  # cleared after poll


@pytest.mark.skipif(shutil.which("g++") is None, reason="no toolchain")
def test_real_executor_loop(target):
    with mk(target, mock=False, smash_mutations=2) as f:
        f.loop(iterations=12)
        assert f.stats["exec_total"] >= 12
        # synthetic executor signal also grows a corpus
        assert len(f.corpus) > 0


def test_device_pipeline(target):
    jax = pytest.importorskip("jax")
    cfg = FuzzerConfig(mock=True, use_device=True, device_batch=8,
                       program_length=8, smash_mutations=2)
    with Fuzzer(target, cfg) as f:
        assert f._device is not None
        # run until the queue drains and the double-buffered device path
        # has produced at least one decoded batch
        for _ in range(600):
            f.step()
            if f.stats["device_candidates"]:
                break
        assert f.stats["device_batches"] >= 1
        assert f.stats["device_candidates"] > 0


def test_device_hints_join_in_smash(target):
    """With a device present, smash's hint seeds go through the batched
    ops/hints join (one XLA kernel per call) and the resulting mutants
    execute — the BASELINE config[3] path, live in the engine."""
    pytest.importorskip("jax")
    cfg = FuzzerConfig(mock=True, use_device=True, collect_comps=True,
                       device_batch=8, program_length=6,
                       smash_mutations=1, device_period=1000)
    with Fuzzer(target, cfg) as f:
        for _ in range(400):
            f.step()
            if f.stats.get("hints_device_joins", 0) > 0 and \
                    f.stats.get("exec_hints", 0) > 1:
                break
        assert f.stats.get("hints_device_joins", 0) > 0
        # joins produced actual executed mutants (beyond the seed exec)
        assert f.stats["exec_hints"] > f.stats["hints_device_joins"]


class _CountingEnv:
    """Fake executor env for the drain fan-out: counts exec_raw calls,
    detects concurrent entry (per-env serialization must hold), and
    sleeps long enough that the pool provably overlaps workers."""

    def __init__(self):
        self.execs = 0
        self.overlapped = False
        self._busy = threading.Lock()

    def exec_raw(self, opts, data, call_ids):
        if not self._busy.acquire(blocking=False):
            self.overlapped = True
            raise AssertionError("concurrent exec_raw on one env")
        try:
            time.sleep(0.002)
            self.execs += 1
            return b"", [], False, False
        finally:
            self._busy.release()

    def close(self):
        pass


class _FakeBatch:
    """Minimal _DeviceBatch stand-in: every row is a raw stream."""

    def __init__(self, n):
        self.streams = [b"\x00"] * n
        self.dropped = 0

    def __len__(self):
        return len(self.streams)

    def op_mask(self, row):
        return 1

    def src_row(self, row):
        return -1  # no arena sampling provenance

    def src_age(self, row):
        return -1

    def call_ids(self, row):
        return [0, 1]  # prelude mmap + one live call: row executes

    def decode(self, row):
        return None


def test_parallel_drain_fans_out_across_envs(target):
    """One device batch drains across ALL envs: rows are dynamically
    balanced over one worker per env, per-env serialization holds, and
    every stat lands exactly once through the locked helper."""
    with mk(target, procs=4) as f:
        envs = [_CountingEnv() for _ in range(4)]
        f.envs = envs
        before_fuzz = f.stats["exec_fuzz"]
        before_total = f.stats["exec_total"]
        f._run_device_batch_inner(_FakeBatch(40))
        assert sum(e.execs for e in envs) == 40
        assert not any(e.overlapped for e in envs)
        # dynamic row-pull with a 2ms exec: every worker gets rows
        assert sum(1 for e in envs if e.execs) >= 3
        assert f.stats["exec_fuzz"] == before_fuzz + 40
        assert f.stats["exec_total"] == before_total + 40
        occ = f.metrics.get("device_drain_env_occupancy")
        assert occ is not None and occ.value >= 0.75


def test_parallel_drain_single_env_inline(target):
    """procs=1 drains inline (no pool), same accounting."""
    with mk(target) as f:
        env = _CountingEnv()
        f.envs = [env]
        f._run_device_batch_inner(_FakeBatch(5))
        assert env.execs == 5
        assert f.stats["exec_fuzz"] == 5
        assert f._drain_pool is None  # never built for one env


def test_device_drain_multiproc_integration(target):
    """End-to-end: the device pipeline's batches drain across a 3-env
    mock fleet and the exec stat ledger stays exactly consistent (every
    exec recorded once despite the worker threads)."""
    pytest.importorskip("jax")
    cfg = FuzzerConfig(mock=True, use_device=True, device_batch=16,
                       program_length=8, smash_mutations=1,
                       device_period=4, procs=3)
    with Fuzzer(target, cfg) as f:
        for _ in range(600):
            f.step()
            if f.stats["device_candidates"] >= 16:
                break
        assert f.stats["device_candidates"] >= 16
        parts = ("exec_gen", "exec_fuzz", "exec_candidate", "exec_triage",
                 "exec_minimize", "exec_smash", "exec_hints")
        assert f.stats["exec_total"] == sum(f.stats[k] for k in parts)


def test_batch_call_ids_vectorized_parity(target):
    """The batch-vectorized call_ids equals the per-row walk it
    replaced (prelude mmap + live calls, in slot order)."""
    pytest.importorskip("jax")
    cfg = FuzzerConfig(mock=True, use_device=True, device_batch=8,
                       program_length=8, smash_mutations=1,
                       device_period=2)
    with Fuzzer(target, cfg) as f:
        batch = None
        for _ in range(200):
            f.step()
            if f.corpus:
                batch = f._device.candidates(f.corpus)
                if batch is not None and len(batch):
                    break
        assert batch is not None and len(batch)
        mm = target.mmap_syscall.id
        for row in range(len(batch)):
            expect = [mm] + [int(c) for c in batch.batch.call_id[row]
                             if int(c) >= 0]
            assert batch.call_ids(row) == expect


def test_device_pipeline_runs_sharded_mesh_step(target):
    """The production pipeline runs the SHARDED fuzz step over the whole
    visible mesh (8 virtual devices under conftest), not a single-device
    path, and the device-side fresh mask gates stale candidates."""
    pytest.importorskip("jax")
    import jax

    cfg = FuzzerConfig(mock=True, use_device=True, device_batch=16,
                       program_length=8, smash_mutations=1,
                       device_period=4)
    with Fuzzer(target, cfg) as f:
        assert f._device is not None
        dev = f._device
        assert dev.mesh.devices.size == len(jax.devices())
        assert dev.n_fuzz * dev.n_cover == dev.mesh.devices.size
        assert dev.B % dev.n_fuzz == 0
        # the sharded proxy bitset lives on the cover axis
        assert dev._sig_shard.shape[0] % dev.n_cover == 0
        for _ in range(400):
            f.step()
            if f.stats.get("device_batches", 0) >= 3:
                break
        assert f.stats["device_batches"] >= 3
        # after a few batches the proxy set has content: freshness gating
        # is live (dropped counter exists, even if zero early on)
        assert "device_dropped_stale" in f.stats
        import numpy as np

        bits = int(np.asarray(
            jax.device_get(dev._sig_shard), dtype=np.uint32).sum())
        assert bits != 0, "sharded proxy signal set never folded"


def test_device_raw_path_feeds_triage(target):
    """Device candidates execute as raw exec streams (no Prog trees) and
    rows with new signal are lazily decoded into triage items that the
    regular loop then turns into corpus entries."""
    pytest.importorskip("jax")
    cfg = FuzzerConfig(mock=True, use_device=True, device_batch=16,
                       program_length=8, smash_mutations=1,
                       device_period=4)
    with Fuzzer(target, cfg) as f:
        assert f._device is not None
        corpus_before = None
        for _ in range(800):
            f.step()
            if f.stats["device_candidates"] and corpus_before is None:
                corpus_before = len(f.corpus)
            if corpus_before is not None and \
                    f.stats["exec_triage"] > 0 and \
                    len(f.corpus) > corpus_before:
                break
        assert f.stats["device_candidates"] > 0
        # raw streams were emitted (the emit path, not the fallback)
        batch = f._device.candidates(f.corpus)
        assert batch is not None
        raws = [s for s in batch.streams if s is not None]
        assert raws, "no raw streams emitted — fast path inactive"
        # raw stream + call_ids round-trip through the mock env (pick a
        # row that still has calls — mutation can empty a program, whose
        # stream is a legal EOF-only bytes object)
        from syzkaller_tpu.ipc import ExecOpts

        row = next(r for r, s in enumerate(batch.streams)
                   if s is not None and len(batch.call_ids(r)) > 1)
        _, infos, failed, hanged = f.envs[0].exec_raw(
            ExecOpts(), batch.streams[row], batch.call_ids(row))
        assert not failed and not hanged
        assert infos and infos[0].executed


# ---- batched-bisection triage minimize (ISSUE 8) ----


def _drain_all(f):
    while any(v for v in f.queue.depths().values()):
        f.step()


def _seed_and_drain(target, bisect, procs, seed=7, n_progs=5, length=8):
    cfg = FuzzerConfig(mock=True, use_device=False, procs=procs,
                       program_length=length, smash_mutations=0,
                       minimize_bisect=bisect)
    with Fuzzer(target, cfg, seed=seed) as f:
        for i in range(n_progs):
            f.execute(generate(target, 500 + i, length), "exec_fuzz")
        _drain_all(f)
        return sorted(serialize(p) for p in f.corpus), dict(f.stats)


def test_bisect_minimize_byte_identical_to_sequential(target):
    """ACCEPTANCE (ISSUE 8): the batched-bisection scheduler produces
    the exact same minimized programs (and exec totals) as the
    sequential one-probe-per-round-trip path on a seeded corpus — the
    per-item minimize ladder is the same code, only the execution
    schedule changes."""
    c_seq, s_seq = _seed_and_drain(target, bisect=False, procs=1)
    c_bis, s_bis = _seed_and_drain(target, bisect=True, procs=1)
    assert c_seq == c_bis
    assert s_seq["exec_total"] == s_bis["exec_total"]
    assert s_seq["new_inputs"] == s_bis["new_inputs"]
    # the win surface: probes collapsed into far fewer serial rounds
    probes = s_bis["exec_triage"] + s_bis["exec_minimize"]
    assert 0 < s_bis["minimize_rounds"] < probes
    assert s_bis["minimize_batch_execs"] <= probes
    assert "minimize_rounds" not in s_seq


def test_bisect_minimize_multi_env_fleet(target):
    """At procs=4 the rounds fan across the fleet with each item pinned
    to a home env; the minimized corpus still matches the sequential
    path (MockEnv signal is a pure per-call function, so an internally
    consistent env assignment preserves every verdict)."""
    c_seq, _s = _seed_and_drain(target, bisect=False, procs=4)
    c_bis, s_bis = _seed_and_drain(target, bisect=True, procs=4)
    assert c_seq == c_bis
    assert s_bis["minimize_rounds"] > 0


def test_bisect_rounds_batch_probes(target):
    """One round carries one probe from every still-active item: with
    N items queued, rounds < total probes and the per-round batch size
    starts at N."""
    from syzkaller_tpu.engine.fuzzer import _BisectRounds

    cfg = FuzzerConfig(mock=True, use_device=False, procs=2,
                       program_length=6, smash_mutations=0)
    with Fuzzer(target, cfg) as f:
        items = []
        for i in range(3):
            f.execute(generate(target, 700 + i, 6), "exec_fuzz")
        while (item := f.queue.pop()) is not None:
            if isinstance(item, TriageItem):
                items.append(item)
        assert len(items) >= 2
        items = items[:3]
        outs = _BisectRounds(f, items).run()
        assert len(outs) == len(items)
        assert any(o is not None for o in outs)
        rounds = f.stats["minimize_rounds"]
        execs = f.stats["minimize_batch_execs"]
        assert rounds < execs  # batching happened
        # every item is pinned to a home env for its whole ladder
        assert f.stats["exec_triage"] >= len(items) * f.cfg.triage_reruns


def test_step_pops_triage_batch(target):
    """step() drains the whole triage class into one batched call when
    minimize_bisect is on, and one item at a time when off."""
    cfg = FuzzerConfig(mock=True, use_device=False, procs=1,
                       program_length=6, smash_mutations=0,
                       minimize_bisect=True, minimize_batch=8)
    with Fuzzer(target, cfg) as f:
        for i in range(3):
            f.execute(generate(target, 800 + i, 6), "exec_fuzz")
        depth = f.queue.depths()["triage"]
        assert depth >= 2
        f.step()  # one step consumes the whole class (<= minimize_batch)
        assert f.queue.depths()["triage"] == max(depth - 8, 0)


# ---- fused triage novelty screen (ISSUE 8) ----


def test_scan_infos_fused_screen(target):
    """The drain's novelty scan screens calls through the max-signal
    bitset image: known signal enqueues nothing, novel signal still
    triages, and within one execution a later call whose novelty is
    fully claimed by an earlier call defers to it (first-claim)."""
    from syzkaller_tpu.ipc import CallInfo

    class _FakeBatch:
        def __init__(self, prog):
            self.prog = prog

        def decode(self, row):
            return self.prog

    cfg = FuzzerConfig(mock=True, use_device=True, procs=1,
                       smash_mutations=0)
    with Fuzzer(target, cfg) as f:
        if f._tri_bits is None:
            pytest.skip("no device pipeline (jax unavailable)")
        p = generate(target, 42, 4)
        infos = [
            CallInfo(index=0, num=0, errno=0, executed=True,
                     fault_injected=False, signal=[11111, 22222],
                     cover=[], comps=[]),
            CallInfo(index=1, num=0, errno=0, executed=True,
                     fault_injected=False, signal=[11111],
                     cover=[], comps=[]),
        ]
        from syzkaller_tpu.telemetry import Provenance

        origin = Provenance("mutate")
        ok = f._scan_infos_for_triage(_FakeBatch(p), 0, infos, origin)
        assert ok
        items = []
        while (it := f.queue.pop()) is not None:
            items.append(it)
        triaged = [i for i in items if isinstance(i, TriageItem)]
        # call 0 claims both PCs; call 1's novelty is fully claimed
        assert [t.call_index for t in triaged] == [0]
        # once the signal is in max_signal (screen noted), nothing new
        f._note_signal([11111, 22222])
        ok = f._scan_infos_for_triage(_FakeBatch(p), 0, infos, origin)
        assert ok
        assert f.queue.pop() is None


def test_screen_mirrors_max_signal_superset(target):
    """Every max_signal growth site must set the member's screen bit —
    the soundness invariant (clear bit => definitely new)."""
    import numpy as np

    cfg = FuzzerConfig(mock=True, use_device=True, procs=1,
                       smash_mutations=0)
    with Fuzzer(target, cfg) as f:
        if f._tri_bits is None:
            pytest.skip("no device pipeline (jax unavailable)")
        f._note_signal([12345, 67890])
        nbits = f._tri_bits.shape[0] * 32
        for s in f.max_signal:
            pos = s & (nbits - 1)
            assert (f._tri_bits[pos >> 5] >> (pos & 31)) & 1


def test_screen_never_drops_sent_wrapping_signal(target):
    """A signal value that wraps to the SENT sentinel (0xFFFFFFFF) is
    invisible to the packed screen — such calls must take the exact
    path, not be silently screened out."""
    from syzkaller_tpu.ipc import CallInfo
    from syzkaller_tpu.telemetry import Provenance

    class _FakeBatch:
        def __init__(self, prog):
            self.prog = prog

        def decode(self, row):
            return self.prog

    cfg = FuzzerConfig(mock=True, use_device=True, procs=1,
                       smash_mutations=0)
    with Fuzzer(target, cfg) as f:
        if f._tri_bits is None:
            pytest.skip("no device pipeline (jax unavailable)")
        p = generate(target, 43, 4)
        infos = [
            CallInfo(index=0, num=0, errno=0, executed=True,
                     fault_injected=False, signal=[0xFFFFFFFF],
                     cover=[], comps=[]),
            CallInfo(index=1, num=0, errno=0, executed=True,
                     fault_injected=False, signal=[0xFFFFFFFF],
                     cover=[], comps=[]),
        ]
        f._scan_infos_for_triage(_FakeBatch(p), 0, infos,
                                 Provenance("mutate"))
        items = []
        while (it := f.queue.pop()) is not None:
            if isinstance(it, TriageItem):
                items.append(it)
        # both calls carry the unscreenable value and max_signal does
        # not contain it: the exact diff must have triaged BOTH (the
        # screen may not first-claim what it cannot see)
        assert [t.call_index for t in items] == [0, 1]
