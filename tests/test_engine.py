"""Fuzzer engine tests: the hermetic end-to-end loop against MockEnv, and
(when the toolchain allows) the real executor."""

import shutil

import pytest

from syzkaller_tpu.engine.fuzzer import Fuzzer, FuzzerConfig, ManagerConn
from syzkaller_tpu.engine.queue import (
    CandidateItem,
    SmashItem,
    TriageItem,
    WorkQueue,
)
from syzkaller_tpu.prog import get_target
from syzkaller_tpu.prog.encoding import deserialize, serialize
from syzkaller_tpu.prog.generation import generate


@pytest.fixture(scope="module")
def target():
    return get_target("linux", "amd64")


def mk(target, **kw) -> Fuzzer:
    kw.setdefault("mock", True)
    kw.setdefault("use_device", False)
    kw.setdefault("smash_mutations", 3)
    return Fuzzer(target, FuzzerConfig(**kw))


def test_queue_priority_order(target):
    q = WorkQueue()
    p = generate(target, 0, 3)
    q.push_smash(SmashItem(p))
    q.push_triage(TriageItem(p, 0, [1]))
    q.push_triage(TriageItem(p, 0, [2], from_candidate=True))
    q.push_candidate(CandidateItem(p))
    kinds = []
    while (item := q.pop()) is not None:
        kinds.append((type(item).__name__,
                      getattr(item, "from_candidate", None)))
    assert kinds == [("TriageItem", True), ("CandidateItem", None),
                     ("TriageItem", False), ("SmashItem", None)]


def test_loop_grows_corpus(target):
    with mk(target) as f:
        f.loop(iterations=50)
        assert f.stats["exec_total"] >= 50
        assert len(f.corpus) > 0          # mock signal must triage inputs
        assert f.stats["new_inputs"] == len(f.corpus)
        assert len(f.max_signal) > 0
        assert f.corpus_signal <= f.max_signal


def test_triage_minimizes(target):
    with mk(target) as f:
        # execute one program; triage queue fills from novel signal
        p = generate(target, 3, 6)
        f.execute(p)
        item = f.queue.pop()
        assert isinstance(item, TriageItem)
        before = len(item.prog.calls)
        f.triage(item)
        assert len(f.corpus) >= 1
        # minimization can only shrink
        assert all(len(q.calls) <= before for q in f.corpus)


def test_signal_dedup_no_retriage(target):
    with mk(target) as f:
        p = deserialize(target, "r0 = getpid()\n")
        f.execute(p)
        while (item := f.queue.pop()) is not None:
            if isinstance(item, TriageItem):
                f.triage(item)
        execs = f.stats["exec_total"]
        # same program again: no new signal, no new triage work
        f.execute(p)
        assert f.queue.pop() is None
        assert f.stats["exec_total"] == execs + 1


def test_candidates_from_manager(target):
    class Mgr(ManagerConn):
        def connect(self):
            c = super().connect()
            c["candidates"] = ["r0 = getpid()\n"]
            return c

        def __init__(self):
            self.inputs = []

        def new_input(self, text, ci, sig, cover):
            self.inputs.append(text)

    mgr = Mgr()
    f = Fuzzer(target, FuzzerConfig(mock=True, use_device=False,
                                    smash_mutations=2), manager=mgr)
    with f:
        f.loop(iterations=10)
        assert f.stats["exec_candidate"] >= 1
        assert mgr.inputs  # triaged input reported back


def test_stats_flow(target):
    with mk(target) as f:
        f.loop(iterations=30)
        f.poll_manager()
        assert not f.new_signal  # cleared after poll


@pytest.mark.skipif(shutil.which("g++") is None, reason="no toolchain")
def test_real_executor_loop(target):
    with mk(target, mock=False, smash_mutations=2) as f:
        f.loop(iterations=12)
        assert f.stats["exec_total"] >= 12
        # synthetic executor signal also grows a corpus
        assert len(f.corpus) > 0


def test_device_pipeline(target):
    jax = pytest.importorskip("jax")
    cfg = FuzzerConfig(mock=True, use_device=True, device_batch=8,
                       program_length=8, smash_mutations=2)
    with Fuzzer(target, cfg) as f:
        assert f._device is not None
        # run until the queue drains and the double-buffered device path
        # has produced at least one decoded batch
        for _ in range(600):
            f.step()
            if f.stats["device_candidates"]:
                break
        assert f.stats["device_batches"] >= 1
        assert f.stats["device_candidates"] > 0


def test_device_hints_join_in_smash(target):
    """With a device present, smash's hint seeds go through the batched
    ops/hints join (one XLA kernel per call) and the resulting mutants
    execute — the BASELINE config[3] path, live in the engine."""
    pytest.importorskip("jax")
    cfg = FuzzerConfig(mock=True, use_device=True, collect_comps=True,
                       device_batch=8, program_length=6,
                       smash_mutations=1, device_period=1000)
    with Fuzzer(target, cfg) as f:
        for _ in range(400):
            f.step()
            if f.stats.get("hints_device_joins", 0) > 0 and \
                    f.stats.get("exec_hints", 0) > 1:
                break
        assert f.stats.get("hints_device_joins", 0) > 0
        # joins produced actual executed mutants (beyond the seed exec)
        assert f.stats["exec_hints"] > f.stats["hints_device_joins"]


def test_device_pipeline_runs_sharded_mesh_step(target):
    """The production pipeline runs the SHARDED fuzz step over the whole
    visible mesh (8 virtual devices under conftest), not a single-device
    path, and the device-side fresh mask gates stale candidates."""
    pytest.importorskip("jax")
    import jax

    cfg = FuzzerConfig(mock=True, use_device=True, device_batch=16,
                       program_length=8, smash_mutations=1,
                       device_period=4)
    with Fuzzer(target, cfg) as f:
        assert f._device is not None
        dev = f._device
        assert dev.mesh.devices.size == len(jax.devices())
        assert dev.n_fuzz * dev.n_cover == dev.mesh.devices.size
        assert dev.B % dev.n_fuzz == 0
        # the sharded proxy bitset lives on the cover axis
        assert dev._sig_shard.shape[0] % dev.n_cover == 0
        for _ in range(400):
            f.step()
            if f.stats.get("device_batches", 0) >= 3:
                break
        assert f.stats["device_batches"] >= 3
        # after a few batches the proxy set has content: freshness gating
        # is live (dropped counter exists, even if zero early on)
        assert "device_dropped_stale" in f.stats
        import numpy as np

        bits = int(np.asarray(
            jax.device_get(dev._sig_shard), dtype=np.uint32).sum())
        assert bits != 0, "sharded proxy signal set never folded"


def test_device_raw_path_feeds_triage(target):
    """Device candidates execute as raw exec streams (no Prog trees) and
    rows with new signal are lazily decoded into triage items that the
    regular loop then turns into corpus entries."""
    pytest.importorskip("jax")
    cfg = FuzzerConfig(mock=True, use_device=True, device_batch=16,
                       program_length=8, smash_mutations=1,
                       device_period=4)
    with Fuzzer(target, cfg) as f:
        assert f._device is not None
        corpus_before = None
        for _ in range(800):
            f.step()
            if f.stats["device_candidates"] and corpus_before is None:
                corpus_before = len(f.corpus)
            if corpus_before is not None and \
                    f.stats["exec_triage"] > 0 and \
                    len(f.corpus) > corpus_before:
                break
        assert f.stats["device_candidates"] > 0
        # raw streams were emitted (the emit path, not the fallback)
        batch = f._device.candidates(f.corpus)
        assert batch is not None
        raws = [s for s in batch.streams if s is not None]
        assert raws, "no raw streams emitted — fast path inactive"
        # raw stream + call_ids round-trip through the mock env (pick a
        # row that still has calls — mutation can empty a program, whose
        # stream is a legal EOF-only bytes object)
        from syzkaller_tpu.ipc import ExecOpts

        row = next(r for r, s in enumerate(batch.streams)
                   if s is not None and len(batch.call_ids(r)) > 1)
        _, infos, failed, hanged = f.envs[0].exec_raw(
            ExecOpts(), batch.streams[row], batch.call_ids(row))
        assert not failed and not hanged
        assert infos and infos[0].executed
