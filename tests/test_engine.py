"""Fuzzer engine tests: the hermetic end-to-end loop against MockEnv, and
(when the toolchain allows) the real executor."""

import shutil

import pytest

from syzkaller_tpu.engine.fuzzer import Fuzzer, FuzzerConfig, ManagerConn
from syzkaller_tpu.engine.queue import (
    CandidateItem,
    SmashItem,
    TriageItem,
    WorkQueue,
)
from syzkaller_tpu.prog import get_target
from syzkaller_tpu.prog.encoding import deserialize, serialize
from syzkaller_tpu.prog.generation import generate


@pytest.fixture(scope="module")
def target():
    return get_target("linux", "amd64")


def mk(target, **kw) -> Fuzzer:
    kw.setdefault("mock", True)
    kw.setdefault("use_device", False)
    kw.setdefault("smash_mutations", 3)
    return Fuzzer(target, FuzzerConfig(**kw))


def test_queue_priority_order(target):
    q = WorkQueue()
    p = generate(target, 0, 3)
    q.push_smash(SmashItem(p))
    q.push_triage(TriageItem(p, 0, [1]))
    q.push_triage(TriageItem(p, 0, [2], from_candidate=True))
    q.push_candidate(CandidateItem(p))
    kinds = []
    while (item := q.pop()) is not None:
        kinds.append((type(item).__name__,
                      getattr(item, "from_candidate", None)))
    assert kinds == [("TriageItem", True), ("CandidateItem", None),
                     ("TriageItem", False), ("SmashItem", None)]


def test_loop_grows_corpus(target):
    with mk(target) as f:
        f.loop(iterations=50)
        assert f.stats["exec_total"] >= 50
        assert len(f.corpus) > 0          # mock signal must triage inputs
        assert f.stats["new_inputs"] == len(f.corpus)
        assert len(f.max_signal) > 0
        assert f.corpus_signal <= f.max_signal


def test_triage_minimizes(target):
    with mk(target) as f:
        # execute one program; triage queue fills from novel signal
        p = generate(target, 3, 6)
        f.execute(p)
        item = f.queue.pop()
        assert isinstance(item, TriageItem)
        before = len(item.prog.calls)
        f.triage(item)
        assert len(f.corpus) >= 1
        # minimization can only shrink
        assert all(len(q.calls) <= before for q in f.corpus)


def test_signal_dedup_no_retriage(target):
    with mk(target) as f:
        p = deserialize(target, "r0 = getpid()\n")
        f.execute(p)
        while (item := f.queue.pop()) is not None:
            if isinstance(item, TriageItem):
                f.triage(item)
        execs = f.stats["exec_total"]
        # same program again: no new signal, no new triage work
        f.execute(p)
        assert f.queue.pop() is None
        assert f.stats["exec_total"] == execs + 1


def test_candidates_from_manager(target):
    class Mgr(ManagerConn):
        def connect(self):
            c = super().connect()
            c["candidates"] = ["r0 = getpid()\n"]
            return c

        def __init__(self):
            self.inputs = []

        def new_input(self, text, ci, sig, cover):
            self.inputs.append(text)

    mgr = Mgr()
    f = Fuzzer(target, FuzzerConfig(mock=True, use_device=False,
                                    smash_mutations=2), manager=mgr)
    with f:
        f.loop(iterations=10)
        assert f.stats["exec_candidate"] >= 1
        assert mgr.inputs  # triaged input reported back


def test_stats_flow(target):
    with mk(target) as f:
        f.loop(iterations=30)
        f.poll_manager()
        assert not f.new_signal  # cleared after poll


@pytest.mark.skipif(shutil.which("g++") is None, reason="no toolchain")
def test_real_executor_loop(target):
    with mk(target, mock=False, smash_mutations=2) as f:
        f.loop(iterations=12)
        assert f.stats["exec_total"] >= 12
        # synthetic executor signal also grows a corpus
        assert len(f.corpus) > 0


def test_device_pipeline(target):
    jax = pytest.importorskip("jax")
    cfg = FuzzerConfig(mock=True, use_device=True, device_batch=8,
                       program_length=8, smash_mutations=2)
    with Fuzzer(target, cfg) as f:
        assert f._device is not None
        # run until the queue drains and the double-buffered device path
        # has produced at least one decoded batch
        for _ in range(600):
            f.step()
            if f.stats["device_candidates"]:
                break
        assert f.stats["device_batches"] >= 1
        assert f.stats["device_candidates"] > 0
