"""Coverage-engine parity tests: device exact-set ops vs the direct python
reimplementation of reference pkg/cover semantics; bitset properties."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from syzkaller_tpu.ops import cover  # noqa: E402


def _rand_sets(rng, n=64):
    a = rng.choice(200, size=rng.integers(0, n), replace=False)
    b = rng.choice(200, size=rng.integers(0, n), replace=False)
    return a, b


@pytest.mark.parametrize("seed", range(5))
def test_exact_ops_parity(seed):
    rng = np.random.default_rng(seed)
    a, b = _rand_sets(rng)
    pa, pb = cover.pad_set(a, 128), cover.pad_set(b, 128)
    ca, cb = cover.canonicalize(pa), cover.canonicalize(pb)

    def unpad(x):
        x = np.asarray(x)
        return [int(v) for v in x if v != 0xFFFFFFFF]

    assert unpad(ca) == cover.py_canonicalize(a)
    assert unpad(cover.union(ca, cb)) == cover.py_union(a, b)
    assert unpad(cover.intersection(ca, cb)) == cover.py_intersection(a, b)
    assert unpad(cover.difference(ca, cb)) == cover.py_difference(a, b)
    assert unpad(cover.symmetric_difference(ca, cb)) == \
        cover.py_symmetric_difference(a, b)
    assert bool(cover.has_difference(ca, cb)) == cover.py_has_difference(a, b)


def test_bitset_roundtrip():
    bs = cover.make_bitset(1 << 16)
    sigs = np.array([1, 5, 77, 1 << 15, 0xDEAD], dtype=np.uint32)
    bs = cover.bitset_add(bs, sigs)
    assert bool(cover.bitset_test(bs, np.uint32(5)))
    assert not bool(cover.bitset_test(bs, np.uint32(6)))
    assert int(cover.bitset_count(bs)) == 5
    # adding again is idempotent
    bs2 = cover.bitset_add(bs, sigs)
    assert int(cover.bitset_count(bs2)) == 5


def test_signal_new_batch():
    bs = cover.make_bitset(1 << 16)
    bs = cover.signal_add(bs, np.array([10, 20, 30], dtype=np.uint32))
    batch = np.array(
        [[10, 20, 0xFFFFFFFF], [10, 99, 0xFFFFFFFF],
         [0xFFFFFFFF, 0xFFFFFFFF, 0xFFFFFFFF]], dtype=np.uint32)
    new = cover.signal_new(bs, batch)
    assert new.tolist() == [False, True, False]
    mask = cover.signal_diff_mask(bs, batch)
    assert mask[1].tolist() == [False, True, False]


def test_minimize_matches_python():
    rng = np.random.default_rng(7)
    corpus = [rng.choice(500, size=rng.integers(1, 40), replace=False)
              for _ in range(20)]
    bits = np.stack([
        np.asarray(cover.bitset_add(cover.make_bitset(1 << 12),
                                    c.astype(np.uint32)))
        for c in corpus])
    keep = cover.minimize_corpus(bits)
    kept = [i for i in range(20) if keep[i]]
    # the greedy device cover must cover everything the python cover does
    py_kept = cover.py_minimize([list(c) for c in corpus])
    union_dev = set()
    for i in kept:
        union_dev |= set(int(v) & ((1 << 12) - 1) for v in corpus[i])
    union_all = set()
    for c in corpus:
        union_all |= set(int(v) & ((1 << 12) - 1) for v in c)
    assert union_dev == union_all
    assert len(kept) <= len(py_kept) + 3  # same order of magnitude


def test_rng_samplers():
    from syzkaller_tpu.ops import rng as r
    import jax

    key = jax.random.PRNGKey(0)
    v = r.rand_int(key, (1000,))
    assert v.dtype == np.uint64
    # magnitude bias: most values small
    small = np.sum(np.asarray(v) < 4096)
    assert small > 300
    b = r.biased_rand(key, 10, 5, (2000,))
    counts = np.bincount(np.asarray(b), minlength=10)
    assert counts[9] > counts[0]  # biased toward n-1
    f = r.sample_flags(key, np.int32(0), np.int32(3),
                       np.array([1, 2, 4], dtype=np.uint64), (500,))
    assert np.all(np.asarray(f) >= 0)
    cs = np.array([0, 10, 10, 30], dtype=np.int64)
    idx = [int(r.choose_weighted(jax.random.PRNGKey(i), cs))
           for i in range(50)]
    assert 0 not in idx  # zero-weight first entry never chosen
    assert 1 in idx and 3 in idx


def test_exact_ops_batched():
    """Exact-set ops must accept leading batch dimensions (the per-program
    PC-set use case)."""
    rng = np.random.default_rng(3)
    A = np.stack([cover.pad_set(rng.choice(100, 20, replace=False), 32)
                  for _ in range(4)])
    Bm = np.stack([cover.pad_set(rng.choice(100, 20, replace=False), 32)
                   for _ in range(4)])
    hd = cover.has_difference(A, Bm)
    assert hd.shape == (4,)
    for i in range(4):
        ai = [int(v) for v in A[i] if v != 0xFFFFFFFF]
        bi = [int(v) for v in Bm[i] if v != 0xFFFFFFFF]
        assert bool(hd[i]) == cover.py_has_difference(ai, bi)
    assert cover.set_size(cover.union(A, Bm)).shape == (4,)


# ---- fused merge + new-signal entry (ISSUE 8) ----


def _py_sequential_fold(acc, sigs):
    """Direct python reimplementation of the merge_and_new contract:
    fold the rows one at a time into an exact bit-position set."""
    nbits = acc.shape[0] * 32
    covered = set()
    for w in range(acc.shape[0]):
        v = int(acc[w])
        b = 0
        while v:
            if v & 1:
                covered.add(w * 32 + b)
            v >>= 1
            b += 1
    counts = []
    for row in sigs:
        fresh = set()
        for v in row:
            v = int(v)
            if v == 0xFFFFFFFF:
                continue
            p = v & (nbits - 1)
            if p not in covered:
                fresh.add(p)
        covered |= fresh
        counts.append(len(fresh))
    merged = np.zeros_like(acc)
    for p in covered:
        merged[p >> 5] |= np.uint32(1) << np.uint32(p & 31)
    return counts, merged


@pytest.mark.parametrize("seed", range(3))
def test_merge_and_new_matches_python_reference(seed):
    rng = np.random.default_rng(seed)
    acc = rng.integers(0, 1 << 32, size=64, dtype=np.uint32) & \
        rng.integers(0, 1 << 32, size=64, dtype=np.uint32)
    sigs = rng.integers(0, 1 << 32, size=(8, 6), dtype=np.uint32)
    sigs[rng.random(sigs.shape) < 0.2] = 0xFFFFFFFF
    ref_counts, ref_merged = _py_sequential_fold(acc, sigs)
    counts, mask, merged = cover.merge_and_new_host(
        acc.copy(), sigs, update=True)
    assert list(counts) == ref_counts
    assert list(mask) == [c > 0 for c in ref_counts]
    np.testing.assert_array_equal(merged, ref_merged)
    jc, jm, jmerged = cover.merge_and_new(acc, sigs)
    assert list(np.asarray(jc)) == ref_counts
    np.testing.assert_array_equal(np.asarray(jmerged), ref_merged)


def test_merge_and_new_host_update_semantics():
    """update=True mutates the accumulator IN PLACE and returns it;
    update=False performs no fold and returns the input untouched."""
    acc = np.zeros(32, np.uint32)
    sigs = np.array([[3, 70]], dtype=np.uint32)
    counts, mask, out = cover.merge_and_new_host(acc, sigs)
    assert out is acc and not acc.any()          # screen mode: no fold
    assert counts[0] == 2 and mask[0]
    counts, mask, out = cover.merge_and_new_host(acc, sigs, update=True)
    assert out is acc and acc.any()              # folded in place
    counts2, _, _ = cover.merge_and_new_host(acc, sigs)
    assert counts2[0] == 0                       # now known


def test_merge_and_new_jit_callable():
    """The entry is safe under jit (the XLA core traces)."""
    acc = np.zeros(64, np.uint32)
    sigs = np.array([[1, 2], [1, 0xFFFFFFFF]], dtype=np.uint32)
    jitted = jax.jit(cover.merge_and_new)
    counts, mask, merged = jitted(acc, sigs)
    # row 1's only real signal is claimed by row 0 (sequential-prefix)
    assert list(np.asarray(counts)) == [2, 0]
    hc, _, hmerged = cover.merge_and_new_host(acc.copy(), sigs,
                                              update=True)
    np.testing.assert_array_equal(np.asarray(merged), hmerged)


def test_bitset_add_host_matches_device_add():
    values = [5, 1 << 20, 0xFFFFFFFF, 123456789, 5]
    host = np.zeros(1 << 10, np.uint32)
    cover.bitset_add_host(host, values)
    dev = cover.bitset_add(cover.make_bitset(32 << 10),
                           np.asarray(values, np.uint32))
    np.testing.assert_array_equal(host, np.asarray(dev))


def test_bitset_word_helpers_roundtrip():
    """The shared word-level core (also used by the mesh folds): OR'd
    positions test back as set, invalid lanes are no-ops."""
    import jax.numpy as jnp

    bits = jnp.zeros(16, jnp.uint32)
    word = jnp.array([0, 3, 3, 0], jnp.int32)
    bit = jnp.array([1, 5, 9, 1], jnp.uint32)
    valid = jnp.array([True, True, False, True])
    bits = cover.bitset_or_words(bits, word, bit, valid)
    hit = cover.bitset_test_words(bits, word, bit)
    assert list(np.asarray(hit)) == [True, True, False, True]
    assert int(np.asarray(bits)[3]) == 1 << 5


def test_merge_and_new_host_strategies_identical(monkeypatch):
    """The sort-free claim-table strategy (big batch, small table) and
    the stable-sort strategy must be bit-identical — same counts, same
    folded accumulator."""
    rng = np.random.default_rng(9)
    acc = rng.integers(0, 1 << 32, size=1 << 10, dtype=np.uint32) & \
        rng.integers(0, 1 << 32, size=1 << 10, dtype=np.uint32)
    sigs = rng.integers(0, 1 << 32, size=(40, 16), dtype=np.uint32)
    sigs[rng.random(sigs.shape) < 0.2] = 0xFFFFFFFF
    sigs[1] = sigs[0]
    a1, a2 = acc.copy(), acc.copy()
    monkeypatch.setattr(cover, "CLAIM_TABLE_MIN_ELEMS", 0)
    c1, m1, _ = cover.merge_and_new_host(a1, sigs, update=True)
    monkeypatch.setattr(cover, "CLAIM_TABLE_MIN_ELEMS", 1 << 60)
    c2, m2, _ = cover.merge_and_new_host(a2, sigs, update=True)
    np.testing.assert_array_equal(c1, c2)
    np.testing.assert_array_equal(a1, a2)
