"""Coverage-engine parity tests: device exact-set ops vs the direct python
reimplementation of reference pkg/cover semantics; bitset properties."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from syzkaller_tpu.ops import cover  # noqa: E402


def _rand_sets(rng, n=64):
    a = rng.choice(200, size=rng.integers(0, n), replace=False)
    b = rng.choice(200, size=rng.integers(0, n), replace=False)
    return a, b


@pytest.mark.parametrize("seed", range(5))
def test_exact_ops_parity(seed):
    rng = np.random.default_rng(seed)
    a, b = _rand_sets(rng)
    pa, pb = cover.pad_set(a, 128), cover.pad_set(b, 128)
    ca, cb = cover.canonicalize(pa), cover.canonicalize(pb)

    def unpad(x):
        x = np.asarray(x)
        return [int(v) for v in x if v != 0xFFFFFFFF]

    assert unpad(ca) == cover.py_canonicalize(a)
    assert unpad(cover.union(ca, cb)) == cover.py_union(a, b)
    assert unpad(cover.intersection(ca, cb)) == cover.py_intersection(a, b)
    assert unpad(cover.difference(ca, cb)) == cover.py_difference(a, b)
    assert unpad(cover.symmetric_difference(ca, cb)) == \
        cover.py_symmetric_difference(a, b)
    assert bool(cover.has_difference(ca, cb)) == cover.py_has_difference(a, b)


def test_bitset_roundtrip():
    bs = cover.make_bitset(1 << 16)
    sigs = np.array([1, 5, 77, 1 << 15, 0xDEAD], dtype=np.uint32)
    bs = cover.bitset_add(bs, sigs)
    assert bool(cover.bitset_test(bs, np.uint32(5)))
    assert not bool(cover.bitset_test(bs, np.uint32(6)))
    assert int(cover.bitset_count(bs)) == 5
    # adding again is idempotent
    bs2 = cover.bitset_add(bs, sigs)
    assert int(cover.bitset_count(bs2)) == 5


def test_signal_new_batch():
    bs = cover.make_bitset(1 << 16)
    bs = cover.signal_add(bs, np.array([10, 20, 30], dtype=np.uint32))
    batch = np.array(
        [[10, 20, 0xFFFFFFFF], [10, 99, 0xFFFFFFFF],
         [0xFFFFFFFF, 0xFFFFFFFF, 0xFFFFFFFF]], dtype=np.uint32)
    new = cover.signal_new(bs, batch)
    assert new.tolist() == [False, True, False]
    mask = cover.signal_diff_mask(bs, batch)
    assert mask[1].tolist() == [False, True, False]


def test_minimize_matches_python():
    rng = np.random.default_rng(7)
    corpus = [rng.choice(500, size=rng.integers(1, 40), replace=False)
              for _ in range(20)]
    bits = np.stack([
        np.asarray(cover.bitset_add(cover.make_bitset(1 << 12),
                                    c.astype(np.uint32)))
        for c in corpus])
    keep = cover.minimize_corpus(bits)
    kept = [i for i in range(20) if keep[i]]
    # the greedy device cover must cover everything the python cover does
    py_kept = cover.py_minimize([list(c) for c in corpus])
    union_dev = set()
    for i in kept:
        union_dev |= set(int(v) & ((1 << 12) - 1) for v in corpus[i])
    union_all = set()
    for c in corpus:
        union_all |= set(int(v) & ((1 << 12) - 1) for v in c)
    assert union_dev == union_all
    assert len(kept) <= len(py_kept) + 3  # same order of magnitude


def test_rng_samplers():
    from syzkaller_tpu.ops import rng as r
    import jax

    key = jax.random.PRNGKey(0)
    v = r.rand_int(key, (1000,))
    assert v.dtype == np.uint64
    # magnitude bias: most values small
    small = np.sum(np.asarray(v) < 4096)
    assert small > 300
    b = r.biased_rand(key, 10, 5, (2000,))
    counts = np.bincount(np.asarray(b), minlength=10)
    assert counts[9] > counts[0]  # biased toward n-1
    f = r.sample_flags(key, np.int32(0), np.int32(3),
                       np.array([1, 2, 4], dtype=np.uint64), (500,))
    assert np.all(np.asarray(f) >= 0)
    cs = np.array([0, 10, 10, 30], dtype=np.int64)
    idx = [int(r.choose_weighted(jax.random.PRNGKey(i), cs))
           for i in range(50)]
    assert 0 not in idx  # zero-weight first entry never chosen
    assert 1 in idx and 3 in idx


def test_exact_ops_batched():
    """Exact-set ops must accept leading batch dimensions (the per-program
    PC-set use case)."""
    rng = np.random.default_rng(3)
    A = np.stack([cover.pad_set(rng.choice(100, 20, replace=False), 32)
                  for _ in range(4)])
    Bm = np.stack([cover.pad_set(rng.choice(100, 20, replace=False), 32)
                   for _ in range(4)])
    hd = cover.has_difference(A, Bm)
    assert hd.shape == (4,)
    for i in range(4):
        ai = [int(v) for v in A[i] if v != 0xFFFFFFFF]
        bi = [int(v) for v in Bm[i] if v != 0xFFFFFFFF]
        assert bool(hd[i]) == cover.py_has_difference(ai, bi)
    assert cover.set_size(cover.union(A, Bm)).shape == (4,)
