"""Durable campaign journal tests (ISSUE 7): writer invariants (seq +
CRC chain, rotation bound, tail recovery across reopen), reader
tolerance of the SIGKILL artifact (at most one truncated trailing
record), replay parity (event-sourced corpus/signal totals bit-exact
against the live engine's counters and the sampler's final points), and
the clean-exit flush (terminal ``campaign_end`` record)."""

import os
import signal
import subprocess
import sys
import time

import pytest

from syzkaller_tpu.prog import get_target
from syzkaller_tpu.telemetry import get_registry
from syzkaller_tpu.telemetry import journal as J


@pytest.fixture(scope="module")
def target():
    return get_target("linux", "amd64")


# ---- engine identity ----


def test_mint_engine_id_is_persistent_per_workdir(tmp_path):
    wd = str(tmp_path / "wd")
    a = J.mint_engine_id(wd)
    assert a.startswith("eng-")
    # same workdir: same identity, every time (restart == same trajectory)
    assert J.mint_engine_id(wd) == a
    assert (tmp_path / "wd" / "engine_id").read_text().strip() == a
    # different workdir: different engine
    assert J.mint_engine_id(str(tmp_path / "other")) != a
    # no workdir: ephemeral, unique
    assert J.mint_engine_id() != J.mint_engine_id()


# ---- writer / chain invariants ----


def test_journal_seq_crc_chain_roundtrip(tmp_path):
    path = str(tmp_path / "journal.jsonl")
    j = J.CampaignJournal(path, engine_id="eng-t")
    for i in range(20):
        rec = j.emit("tick", i=i)
        assert rec["seq"] == i
    j.close()
    records, defects = J.read_records(path)
    assert defects == []
    assert [r["seq"] for r in records] == list(range(20))
    assert all(r["eng"] == "eng-t" for r in records)
    assert J.verify_records(records) == []
    # the chain actually links: each pc is the previous crc
    for prev, cur in zip(records, records[1:]):
        assert cur["pc"] == prev["crc"]
    assert records[0]["pc"] == ""


def test_journal_detects_tamper(tmp_path):
    path = str(tmp_path / "journal.jsonl")
    j = J.CampaignJournal(path, engine_id="e")
    for i in range(10):
        j.emit("tick", i=i)
    j.close()
    blob = bytearray(open(path, "rb").read())
    # flip one byte inside a mid-file record's payload (a digit of "i")
    idx = blob.index(b'"i":3')
    blob[idx + 4:idx + 5] = b"9"
    open(path, "wb").write(bytes(blob))
    problems = J.verify_records(J.read_records(path)[0])
    assert any("crc mismatch" in p for p in problems)


def test_journal_rotation_bounds_disk_and_keeps_chain(tmp_path):
    path = str(tmp_path / "journal.jsonl")
    j = J.CampaignJournal(path, engine_id="e", max_bytes=4096, segments=3)
    for i in range(400):
        j.emit("tick", i=i, pad="x" * 64)
    j.close()
    segs = J.journal_segments(path)
    assert 1 <= len(segs) <= 3
    total = sum(os.path.getsize(s) for s in segs)
    assert total <= 3 * (4096 + (200))  # bound: segments * (max + 1 line)
    records, defects = J.read_records(path)
    assert defects == []
    # seq strictly consecutive across the surviving segments; the
    # dropped prefix only costs history, never chain validity
    seqs = [r["seq"] for r in records]
    assert seqs == list(range(seqs[0], seqs[0] + len(seqs)))
    assert seqs[0] > 0  # rotation really dropped the oldest segment
    assert J.verify_records(records) == []
    assert get_registry().snapshot()["journal_rotations_total"] >= 1


def test_journal_reopen_continues_chain(tmp_path):
    """A resumed engine reopens the same journal: seq and the crc chain
    continue from the last durable record — replay sees ONE campaign."""
    path = str(tmp_path / "journal.jsonl")
    j = J.CampaignJournal(path, engine_id="e")
    for i in range(5):
        j.emit("tick", i=i)
    j.close()
    j2 = J.CampaignJournal(path, engine_id="e")
    j2.emit("tick", i=5)
    j2.close()
    records, defects = J.read_records(path)
    assert defects == []
    assert [r["seq"] for r in records] == list(range(6))
    assert J.verify_records(records) == []


def test_journal_truncated_tail_is_tolerated(tmp_path):
    """The durability contract: a SIGKILL loses at most the record being
    written — readers keep everything before a truncated final line and
    tag the artifact ``tail:`` instead of treating it as corruption."""
    path = str(tmp_path / "journal.jsonl")
    j = J.CampaignJournal(path, engine_id="e")
    for i in range(8):
        j.emit("tick", i=i)
    j.close()
    blob = open(path, "rb").read()
    open(path, "wb").write(blob[:-20])  # mid-record truncation
    records, defects = J.read_records(path)
    assert len(records) == 7
    assert len(defects) == 1 and defects[0].startswith("tail: ")
    assert J.verify_records(records) == []


def test_journal_reopen_after_truncated_tail_heals(tmp_path):
    """Resuming over a SIGKILL-truncated journal must TRUNCATE the
    partial trailing line before appending — otherwise the next record
    fuses with it into one undecodable mid-file line, losing a record
    and turning the tolerated tail artifact into permanent corruption."""
    path = str(tmp_path / "journal.jsonl")
    j = J.CampaignJournal(path, engine_id="e")
    for i in range(8):
        j.emit("tick", i=i)
    j.close()
    blob = open(path, "rb").read()
    open(path, "wb").write(blob[:-20])  # SIGKILL artifact: partial tail
    j2 = J.CampaignJournal(path, engine_id="e")
    rec = j2.emit("tick", i=99)
    j2.close()
    records, defects = J.read_records(path)
    assert defects == []                    # the partial line is healed
    assert J.verify_records(records) == []  # chain valid end-to-end
    # 7 surviving originals + the post-restart record, seq continuous
    assert [r["seq"] for r in records] == list(range(8))
    assert rec["seq"] == 7 and records[-1]["i"] == 99


def test_journal_write_failure_is_counted_not_raised(tmp_path):
    # parent directory missing: every write fails (chmod tricks don't
    # bite under root, which is how the suite runs)
    j = J.CampaignJournal(str(tmp_path / "gone" / "journal.jsonl"),
                          engine_id="e")
    before = get_registry().snapshot().get("errors_total", 0)
    assert j.emit("tick") is None  # swallowed, not raised
    assert get_registry().snapshot()["errors_total"] == before + 1
    assert j.records_written == 0
    j.close()


def test_failed_engine_init_releases_global_hook(tmp_path, target):
    """A Fuzzer whose __init__ dies after creating its journal (manager
    down) must not leave the process-global journal hook pointing at the
    orphaned journal — the next engine could never install its own."""
    from syzkaller_tpu.engine.fuzzer import Fuzzer, FuzzerConfig

    class BoomManager:
        def connect(self):
            raise RuntimeError("manager down")

    assert J.get_journal() is None
    with pytest.raises(RuntimeError, match="manager down"):
        Fuzzer(target, FuzzerConfig(mock=True, use_device=False,
                                    workdir=str(tmp_path)),
               manager=BoomManager())
    assert J.get_journal() is None


def test_global_journal_hook(tmp_path):
    path = str(tmp_path / "journal.jsonl")
    j = J.CampaignJournal(path, engine_id="e")
    assert J.get_journal() is None or J.get_journal() is not j
    J.journal_emit("ignored")  # no-op without an installed journal
    J.install(j)
    try:
        J.journal_emit("hooked", x=1)
    finally:
        J.install(None)
        j.close()
    records, _ = J.read_records(path)
    assert [r["ev"] for r in records] == ["hooked"]


# ---- replay: the trajectory from the workdir alone ----


def test_replay_reconstructs_campaign_bit_exact(tmp_path, target):
    """Acceptance: after a mock campaign, ``replay`` over the workdir
    alone (no live process) rebuilds the corpus/signal trajectory —
    event-sourced totals equal the engine's own counters exactly, and
    the replayed series' final points match what the live sampler saw."""
    from syzkaller_tpu.engine.fuzzer import Fuzzer, FuzzerConfig
    from syzkaller_tpu.telemetry import RegistrySampler

    reg = get_registry()
    before = reg.snapshot()
    sampler = RegistrySampler(interval=0)
    cfg = FuzzerConfig(mock=True, use_device=False, smash_mutations=2,
                       workdir=str(tmp_path), checkpoint_interval=0)
    with Fuzzer(target, cfg) as f:
        for burst in range(4):
            f.loop(iterations=30)
            sampler.sample(now=float(burst + 1))
        execs, ni = f.stats["exec_total"], f.stats["new_inputs"]
        f.save_checkpoint()
    assert ni > 0, "campaign found nothing to replay"

    rep = J.replay(str(tmp_path))
    assert rep["defects"] == []
    # event-sourced counters are bit-exact
    assert rep["new_inputs_total"] == ni
    assert rep["signal_total"] == \
        reg.snapshot()["new_signal_total"] - before.get("new_signal_total", 0)
    # replayed series vs the live sampler's series: same final
    # cumulative value (the sampler stores absolute counter samples;
    # the replay accumulates the same events)
    sampled = sampler.store.to_dict()["new_inputs_total"]
    replay_final = rep["series"]["new_inputs"][-1][1]
    assert replay_final == ni
    assert sampled["v"][-1] - before.get("new_inputs_total", 0) == ni
    # trajectory is monotonic (cumulative event-sourced series)
    for name in ("corpus", "new_inputs", "signal"):
        vals = [v for _, v in rep["series"][name]]
        assert vals == sorted(vals)
    # yield attribution rebuilt per phase: replayed corpus_adds match
    # the engine's exactly (triage-confirmed adds all journaled)
    assert sum(c["corpus_adds"] for p, c in
               rep["attribution"]["phases"].items() if p != "seed") == ni
    # the checkpoint stamped an exec point (checkpoint-granular series)
    assert rep["series"]["execs"][-1][1] == execs
    # the terminal record is the campaign_end flush
    records, _ = J.read_records(str(tmp_path))
    assert records[-1]["ev"] == "campaign_end"
    assert records[-1]["execs"] == execs


def test_supervision_events_reach_journal(tmp_path, target):
    """Env restarts and quarantine transitions — state the registry only
    counts — land in the journal with the env index attached."""
    from syzkaller_tpu.engine.fuzzer import Fuzzer, FuzzerConfig
    from syzkaller_tpu.testing import faults
    from syzkaller_tpu.testing.faults import FaultPlan

    faults.install(FaultPlan()
                   .fail_at("env.exec:1", 1, 2, 3, 4))
    cfg = FuzzerConfig(mock=True, use_device=False, procs=2,
                       smash_mutations=1, workdir=str(tmp_path),
                       checkpoint_interval=0, env_base_backoff=0.002,
                       env_max_backoff=0.01, env_probe_interval=0.01,
                       env_quarantine_threshold=2)
    try:
        with Fuzzer(target, cfg) as f:
            f.loop(iterations=200)
    finally:
        faults.clear()
    rep = J.replay(str(tmp_path))
    records, _ = J.read_records(str(tmp_path))
    restarts = [r for r in records if r["ev"] == "env_restart"]
    if restarts:  # the fault only fires when the drain fed env 1
        assert all(r["env"] == 1 for r in restarts)
        assert rep["events"]["env_restart"] == len(restarts)


@pytest.mark.chaos
def test_sigkill_loses_at_most_one_record(tmp_path, target):
    """The durability bound, pinned by an actual SIGKILL: run the engine
    CLI in a subprocess, kill it mid-campaign, and verify the journal —
    every complete record intact, chain valid, at most one truncated
    trailing record (the tolerated ``tail:`` artifact)."""
    wd = str(tmp_path / "wd")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    p = subprocess.Popen(
        [sys.executable, "-m", "syzkaller_tpu.engine", "-mock",
         "-no-detect", "-workdir", wd, "-checkpoint-interval", "0.2"],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.PIPE)
    try:
        path = os.path.join(wd, "journal.jsonl")
        deadline = time.time() + 60
        # wait until the campaign has journaled real progress
        while time.time() < deadline:
            if p.poll() is not None:
                pytest.fail("engine died early: "
                            + p.stderr.read().decode()[-2000:])
            if os.path.exists(path) and os.path.getsize(path) > 4096:
                break
            time.sleep(0.02)
        else:
            pytest.fail("engine never journaled progress")
        os.kill(p.pid, signal.SIGKILL)
        p.wait(timeout=30)
    finally:
        if p.poll() is None:
            p.kill()
            p.wait(timeout=30)
    records, defects = J.read_records(wd)
    assert len(records) > 0
    # at most the in-flight record was lost, and only as a tail artifact
    assert len(defects) <= 1
    assert all(d.startswith("tail: ") for d in defects)
    assert J.verify_records(records) == []
    # a SIGKILL'd campaign has no terminal record — that is the point
    assert records[-1]["ev"] != "campaign_end"


def test_clean_exit_flushes_terminal_record(tmp_path):
    """Flush-on-exit satellite: the engine CLI's clean-exit path ends
    the journal with a fsync'd ``campaign_end`` (after the final
    checkpoint), so a clean shutdown is distinguishable from a crash."""
    wd = str(tmp_path / "wd")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, "-m", "syzkaller_tpu.engine", "-mock",
         "-no-detect", "-workdir", wd, "-iterations", "40"],
        env=env, capture_output=True, timeout=120)
    assert r.returncode == 0, r.stderr.decode()[-2000:]
    records, defects = J.read_records(wd)
    assert defects == []
    assert J.verify_records(records) == []
    assert records[-1]["ev"] == "campaign_end"
    evs = [r["ev"] for r in records]
    assert evs[0] == "campaign_start"
    assert "checkpoint_save" in evs  # the final forced checkpoint
