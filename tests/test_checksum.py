"""Checksum dependency tests (reference prog/checksum.go semantics):
csum-typed fields yield exec instructions whose chunks the executor sums
with the ones'-complement internet checksum after copyins land.
"""

import random

from syzkaller_tpu.prog import get_target
from syzkaller_tpu.prog.checksum import (
    CHUNK_CONST,
    CHUNK_DATA,
    calc_checksums,
    ip_checksum,
)
from syzkaller_tpu.prog.encoding import deserialize, serialize
from syzkaller_tpu.prog.encodingexec import decode_exec, serialize_for_exec
from syzkaller_tpu.prog.generation import generate
from syzkaller_tpu.prog.mutation import mutate


def target():
    return get_target("linux", "amd64")


def _emit_prog(variant):
    t = target()
    from syzkaller_tpu.prog.generation import RandGen

    meta = t.syscall_map[variant]
    r = RandGen(t, seed=5)
    from syzkaller_tpu.prog.analysis import analyze
    from syzkaller_tpu.prog.prog import Prog

    p = Prog(t)
    s = analyze(None, p, None)
    calls = r.generate_particular_call(s, meta)
    for c in calls:
        p.calls.append(c)
    return p


def test_ipv4_header_csum_instruction():
    p = _emit_prog("syz_emit_ethernet$ipv4_tcp")
    data = serialize_for_exec(p, 0)
    instrs = decode_exec(data)
    csums = [i for i in instrs
             if i["op"] == "copyin" and i["arg"]["kind"] == "csum"]
    # One inet header csum + one tcp pseudo csum.
    assert len(csums) == 2
    inet = [c for c in csums if len(c["arg"]["chunks"]) == 1]
    pseudo = [c for c in csums if len(c["arg"]["chunks"]) == 5]
    assert len(inet) == 1 and len(pseudo) == 1
    # The header csum must cover exactly the 20-byte IPv4 header — not the
    # payload — or the kernel's ip_rcv drops every injected frame.
    assert inet[0]["arg"]["chunks"][0]["size"] == 20
    # Pseudo chunks: src_ip, dst_ip, proto const, length const, payload.
    kinds = [ch["kind"] for ch in pseudo[0]["arg"]["chunks"]]
    assert kinds == [CHUNK_DATA, CHUNK_DATA, CHUNK_CONST, CHUNK_CONST,
                     CHUNK_DATA]
    proto = pseudo[0]["arg"]["chunks"][2]["value"]
    assert proto == 6  # IPPROTO_TCP


def test_ipv6_pseudo_uses_4byte_consts():
    # IPv6 pseudo headers carry 32-bit length/next-header words
    # (reference prog/checksum.go composePseudoCsumIPv6) — the 2-byte form
    # would silently truncate payloads >= 64KiB.
    p = _emit_prog("syz_emit_ethernet$ipv6_tcp")
    instrs = decode_exec(serialize_for_exec(p, 0))
    csums = [i for i in instrs
             if i["op"] == "copyin" and i["arg"]["kind"] == "csum"]
    pseudo = [c for c in csums if len(c["arg"]["chunks"]) == 5]
    assert len(pseudo) == 1
    consts = [ch for ch in pseudo[0]["arg"]["chunks"]
              if ch["kind"] == CHUNK_CONST]
    assert [ch["size"] for ch in consts] == [4, 4]
    assert consts[0]["value"] == 6  # IPPROTO_TCP


def test_udp_pseudo_proto():
    p = _emit_prog("syz_emit_ethernet$ipv4_udp")
    instrs = decode_exec(serialize_for_exec(p, 0))
    csums = [i for i in instrs
             if i["op"] == "copyin" and i["arg"]["kind"] == "csum"]
    pseudo = [c for c in csums if len(c["arg"]["chunks"]) == 5]
    assert pseudo and pseudo[0]["arg"]["chunks"][2]["value"] == 0x11


def test_ip_checksum_reference_values():
    # RFC 1071 worked example.
    data = bytes([0x00, 0x01, 0xf2, 0x03, 0xf4, 0xf5, 0xf6, 0xf7])
    assert ip_checksum(data) == (~0xddf2) & 0xFFFF
    # Checksum of a block including its own correct checksum verifies to 0.
    c = ip_checksum(data)
    whole = data + bytes([c >> 8, c & 0xFF])
    assert ip_checksum(whole) == 0
    # Odd length pads with zero.
    assert ip_checksum(b"\x01") == (~0x0100) & 0xFFFF


def test_calc_checksums_degrades_gracefully():
    """Mutants that break the packet shape must not crash serialization."""
    t = target()
    rng = random.Random(0)
    for seed in range(30):
        p = generate(t, seed, 6, None)
        mutate(p, seed, ncalls=8, ct=None, corpus=[])
        serialize_for_exec(p, 0)  # must not raise


def test_vnet_roundtrip():
    t = target()
    for variant in ["syz_emit_ethernet$arp", "syz_emit_ethernet$ipv6_udp",
                    "syz_emit_ethernet$ipv4_icmp"]:
        p = _emit_prog(variant)
        text = serialize(p)
        p2 = deserialize(t, text)
        assert serialize(p2) == text
