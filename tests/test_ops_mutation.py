"""Device mutation/generation kernel tests: every produced tensor must
decode into a valid, executable host program, and the op mix must actually
change programs."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from syzkaller_tpu.descriptions.tables import get_tables  # noqa: E402
from syzkaller_tpu.ops.dtables import build_device_tables  # noqa: E402
from syzkaller_tpu.ops import mutation as M  # noqa: E402
from syzkaller_tpu.prog import get_target  # noqa: E402
from syzkaller_tpu.prog.encoding import serialize  # noqa: E402
from syzkaller_tpu.prog.encodingexec import serialize_for_exec  # noqa: E402
from syzkaller_tpu.prog.generation import generate  # noqa: E402
from syzkaller_tpu.prog.prio import (  # noqa: E402
    build_choice_table,
    calculate_priorities,
)
from syzkaller_tpu.prog.tensor import (  # noqa: E402
    ProgBatch,
    TensorFormat,
    decode_batch,
    encode_batch,
)

B = 16


@pytest.fixture(scope="module")
def env():
    target = get_target("linux", "amd64")
    tables = get_tables(target)
    fmt = TensorFormat.for_tables(tables, max_calls=8)
    dt = build_device_tables(tables, fmt)
    return target, tables, fmt, dt


def _decode_all(env, cid, sval, data):
    target, tables, fmt, dt = env
    batch = ProgBatch(np.asarray(cid), np.asarray(sval), np.asarray(data))
    progs = decode_batch(tables, fmt, batch)
    for p in progs:
        p.validate()
        serialize(p)
        serialize_for_exec(p)
    return progs


def test_generate_batch_decodes(env):
    target, tables, fmt, dt = env
    cid, sval, data = M.generate_batch(
        jax.random.PRNGKey(0), dt, B=B, C=fmt.max_calls)
    progs = _decode_all(env, cid, sval, data)
    sizes = [len(p.calls) for p in progs]
    assert max(sizes) > 2
    names = {c.meta.name for p in progs for c in p.calls}
    assert len(names) > 10  # syscall diversity


def test_generated_refs_resolve(env):
    """Generated programs must wire resource inputs to earlier producers
    when available (fd dataflow on device)."""
    target, tables, fmt, dt = env
    cid, sval, data = M.generate_batch(
        jax.random.PRNGKey(3), dt, B=64, C=fmt.max_calls)
    progs = _decode_all(env, cid, sval, data)
    from syzkaller_tpu.prog.prog import ResultArg, foreach_arg
    linked = [0]

    def count(p):
        for c in p.calls:
            def chk(a, _b):
                if isinstance(a, ResultArg) and a.res is not None:
                    linked[0] += 1
            foreach_arg(c, chk)

    for p in progs:
        count(p)
    assert linked[0] > 10, "device generation should produce real dataflow"


def test_mutate_batch_changes_and_decodes(env):
    target, tables, fmt, dt = env
    ct = build_choice_table(target, calculate_priorities(target, []))
    host = [generate(target, s, 6, ct) for s in range(B)]
    b0 = encode_batch(tables, fmt, host)
    cid, sval, data = M.mutate_batch(
        jax.random.PRNGKey(1), dt,
        b0.call_id, b0.slot_val, b0.data, rounds=3)
    progs = _decode_all(env, cid, sval, data)
    changed = sum(
        1 for i in range(B)
        if not (np.array_equal(np.asarray(cid)[i], b0.call_id[i])
                and np.array_equal(np.asarray(sval)[i], b0.slot_val[i])
                and np.array_equal(np.asarray(data)[i], b0.data[i])))
    assert changed >= B * 3 // 4


def test_refs_stay_in_bounds_after_mutation(env):
    """After many mutation rounds every REF slot either is REF_NONE or
    points at an earlier live call."""
    target, tables, fmt, dt = env
    from syzkaller_tpu.descriptions.tables import SK_REF
    from syzkaller_tpu.prog.tensor import REF_NONE

    cid, sval, data = M.generate_batch(
        jax.random.PRNGKey(7), dt, B=B, C=fmt.max_calls)
    for r in range(4):
        cid, sval, data = M.mutate_batch(
            jax.random.PRNGKey(100 + r), dt, cid, sval, data, rounds=2)
    cid_np, sval_np = np.asarray(cid), np.asarray(sval)
    for b in range(B):
        for c in range(fmt.max_calls):
            if cid_np[b, c] < 0:
                continue
            o = int(tables.call_slot_off[cid_np[b, c]])
            cnt = min(int(tables.call_slot_cnt[cid_np[b, c]]), fmt.max_slots)
            for s in range(cnt):
                if int(tables.slot_kind[o + s]) != SK_REF:
                    continue
                v = int(sval_np[b, c, s])
                if v == REF_NONE:
                    continue
                assert v < c, f"ref at ({b},{c},{s}) -> {v} not earlier"
                assert cid_np[b, v] >= 0, "ref to dead call"


def test_splice_keeps_live_prefix_contiguous(env):
    """Regression: splice with a donor whose live-call count is smaller
    than the splice point must not leave dead-call holes mid-program
    (REF values are row indices; decode assumes a contiguous live
    prefix)."""
    import jax.numpy as jnp
    from syzkaller_tpu.ops.mutation import splice

    target, tables, fmt, dt = env
    C, S, D = fmt.max_calls, dt.max_slots, dt.arena
    own_cid = jnp.array([65] + [-1] * (C - 1), jnp.int32)
    donor_cid = jnp.array([134] + [-1] * (C - 1), jnp.int32)
    zeros_s = jnp.zeros((C, S), jnp.uint64)
    zeros_d = jnp.zeros((C, D), jnp.uint8)
    for seed in range(32):
        cid, _, _ = splice(jax.random.PRNGKey(seed), dt,
                           (own_cid, zeros_s, zeros_d),
                           (donor_cid, zeros_s, zeros_d))
        cid = np.asarray(cid)
        nlive = int((cid >= 0).sum())
        assert (cid[:nlive] >= 0).all() and (cid[nlive:] == -1).all(), cid

    # empty donor: splice is a no-op
    empty = jnp.full((C,), -1, jnp.int32)
    cid, _, _ = splice(jax.random.PRNGKey(0), dt,
                       (own_cid, zeros_s, zeros_d),
                       (empty, zeros_s, zeros_d))
    np.testing.assert_array_equal(np.asarray(cid), np.asarray(own_cid))


def test_mutate_batch_live_prefix_invariant(env):
    """All mutation ops combined must preserve the contiguous-live-prefix
    invariant across many rounds."""
    target, tables, fmt, dt = env
    cid, sval, data = M.generate_batch(
        jax.random.PRNGKey(3), dt, B=B, C=fmt.max_calls)
    for r in range(4):
        cid, sval, data = M.mutate_batch(
            jax.random.PRNGKey(100 + r), dt, cid, sval, data)
    carr = np.asarray(cid)
    for rowv in carr:
        nlive = int((rowv >= 0).sum())
        assert (rowv[:nlive] >= 0).all() and (rowv[nlive:] == -1).all(), rowv


def test_stratified_mutation_decodes(env):
    """mutate_rows_stratified (the bench/mesh hot path) keeps tensor
    invariants: decodable programs, in-range call ids, real change."""
    target, tables, fmt, dt = env
    cid, sval, data = M.generate_batch(
        jax.random.PRNGKey(5), dt, B=B, C=fmt.max_calls)
    ncid, nsval, ndata = jax.jit(
        lambda k, a, b, c: M.mutate_rows_stratified(k, dt, a, b, c, 2)
    )(jax.random.PRNGKey(7), cid, sval, data)
    _decode_all(env, ncid, nsval, ndata)
    ncid_np = np.asarray(ncid)
    assert ((ncid_np >= -1) & (ncid_np < dt.n_calls)).all()
    assert not (np.array_equal(ncid_np, np.asarray(cid))
                and np.array_equal(np.asarray(nsval), np.asarray(sval)))
