"""special_structs generators (reference sys/linux/init.go:12-60,214-280):
timespec/timeval must come out of the arch generator — zero/small-delta/
far-future values or a chained clock_gettime — never random struct bytes.
"""

from syzkaller_tpu.prog import get_target
from syzkaller_tpu.prog.encoding import deserialize, serialize
from syzkaller_tpu.prog.generation import RandGen, generate
from syzkaller_tpu.prog.mutation import mutate
from syzkaller_tpu.prog.types import Dir
from syzkaller_tpu.prog.prog import (
    GroupArg,
    ResultArg,
    foreach_arg,
    foreach_subarg,
)


def target():
    return get_target("linux", "amd64")


def test_special_structs_registered():
    t = target()
    assert set(t.special_structs) == {"timespec", "timeval"}


def _collect_time_structs(p):
    found = []
    for c in p.calls:
        def visit(a, _b=None):
            if isinstance(a, GroupArg) and \
                    getattr(a.typ, "name", None) in ("timespec", "timeval"):
                found.append((c, a))
        foreach_arg(c, lambda a, b: foreach_subarg(a, visit))
    return found


def test_generator_fires_for_nanosleep():
    t = target()
    meta = t.syscall_map["nanosleep"]
    saw_gettime = saw_small = 0
    for seed in range(60):
        from syzkaller_tpu.prog.analysis import analyze
        from syzkaller_tpu.prog.prog import Prog

        r = RandGen(t, seed=seed)
        p = Prog(t)
        s = analyze(None, p, None)
        for c in r.generate_particular_call(s, meta):
            p.calls.append(c)
        p.validate()
        structs = _collect_time_structs(p)
        # nanosleep(req ptr[in, timespec], rem ptr[out, ...]): the IN one
        # must be generator-made, i.e. all fields are ResultArgs.
        in_structs = [a for c, a in structs if a.typ.dir != Dir.OUT]
        assert in_structs
        for a in in_structs:
            assert all(isinstance(f, ResultArg) for f in a.inner), \
                "timespec fields must come from the special generator"
            sec, nsec = a.inner
            if sec.res is not None or nsec.res is not None:
                saw_gettime += 1
                # absolute few-ms-ahead: nsec chains with an op_add
                assert nsec.op_add in (10_000_000, 30_000_000)
            elif nsec.val in (10_000_000, 30_000_000):
                saw_small += 1
            else:
                assert (sec.val, nsec.val) in ((0, 0), (2 * 10**9, 0))
        if any(c.meta.call_name == "clock_gettime" for c in p.calls):
            assert saw_gettime
    # All four branches are probabilistic; over 60 seeds the two
    # interesting ones must each fire.
    assert saw_gettime > 0 and saw_small > 0


def test_timeval_uses_usec_scale():
    t = target()
    saw = 0
    for seed in range(80):
        from syzkaller_tpu.prog.analysis import analyze
        from syzkaller_tpu.prog.prog import Prog

        r = RandGen(t, seed=seed)
        p = Prog(t)
        s = analyze(None, p, None)
        arg, calls = t.special_structs["timeval"](
            r, s, _timeval_type(t), None)
        sec, usec = arg.inner
        if usec.res is not None:
            assert usec.op_div == 1000
            assert usec.op_add in (10_000, 30_000)
            saw += 1
        elif usec.val:
            assert usec.val in (10_000, 30_000)
    assert saw > 0


def _timeval_type(t):
    # find the timeval StructType via a call that takes ptr[in/out, timeval]
    meta = t.syscall_map["gettimeofday"]
    return meta.args[0].elem


def test_round_trip_with_gettime_chain():
    t = target()
    for seed in range(40):
        p = generate(t, seed, 8, None)
        if not any(c.meta.call_name == "clock_gettime" for c in p.calls):
            continue
        text = serialize(p)
        q = deserialize(t, text)
        assert serialize(q) == text
        q.validate()


def test_mutation_of_deserialized_struct_keeps_res_links():
    """Corpus programs arrive via deserialize (ConstArg fields); when
    mutation regenerates the special struct, the res links and the chained
    clock_gettime must survive (whole-struct replace_arg)."""
    t = target()
    meta = t.syscall_map["nanosleep"]
    saw_chain = 0
    for seed in range(120):
        from syzkaller_tpu.prog.analysis import analyze
        from syzkaller_tpu.prog.prog import Prog

        r = RandGen(t, seed=seed)
        p = Prog(t)
        s = analyze(None, p, None)
        for c in r.generate_particular_call(s, meta):
            p.calls.append(c)
        q = deserialize(t, serialize(p))
        mutate(q, seed, ncalls=10, ct=None, corpus=[])
        q.validate()
        for c in q.calls:
            if c.meta.call_name != "clock_gettime":
                continue
            # every clock_gettime present must be referenced by some
            # ResultArg (no dead chains)
            used = []
            for cc in q.calls:
                def vis(a, _b=None):
                    if isinstance(a, ResultArg) and a.res is not None:
                        used.append(a)
                foreach_arg(cc, lambda a, b: foreach_subarg(a, vis))
            if used:
                saw_chain += 1
    assert saw_chain > 0


def test_long_mutation_run_survives_nested_time_structs():
    """Regression: timespec/timeval generated INSIDE non-special structs
    (itimerval, itimerspec) leave ResultArg int fields the mutator may
    later target individually — replace_arg must accept the resulting
    ResultArg -> ConstArg scalar replacement (found by bench at ~840
    mutations)."""
    t = target()
    r = RandGen(t, seed=0)
    progs = [generate(t, i, 16) for i in range(16)]
    for n in range(1200):
        p = progs[n % len(progs)].clone()
        mutate(p, r, 16, corpus=progs)
        if n % 200 == 0:
            p.validate()


def test_mutation_keeps_generator_invariant():
    t = target()
    corpus = []
    for seed in range(20):
        p = generate(t, seed, 6, None)
        mutate(p, seed + 1000, ncalls=8, ct=None, corpus=corpus)
        p.validate()
        for c, a in _collect_time_structs(p):
            if a.typ.dir == Dir.OUT:
                continue
            # after mutation the struct is either untouched or regenerated —
            # always all-ResultArg fields, never raw const garbage
            assert all(isinstance(f, ResultArg) for f in a.inner)
        corpus.append(p)
