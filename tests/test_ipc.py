"""IPC round-trip tests against the real compiled C++ executor.

Mirrors the reference's pkg/ipc/ipc_test.go:23-50 strategy (build executor,
round-trip generated programs through Env.Exec) — but runs hermetically:
no KCOV in containers, so the executor's synthetic-signal fallback provides
deterministic coverage.
"""

import shutil

import pytest

from syzkaller_tpu.ipc import Env, ExecOpts, Gate, MockEnv
from syzkaller_tpu.prog import get_target
from syzkaller_tpu.prog.encoding import deserialize
from syzkaller_tpu.prog.generation import generate

pytestmark = pytest.mark.skipif(shutil.which("g++") is None,
                                reason="no C++ toolchain")


@pytest.fixture(scope="module")
def target():
    return get_target("linux", "amd64")


@pytest.fixture(scope="module")
def env(target):
    with Env(target, pid=0) as e:
        yield e


def test_exec_simple(target, env):
    p = deserialize(target, "r0 = getpid()\n")
    _, infos, failed, hanged = env.exec(ExecOpts(), p)
    assert not failed and not hanged
    assert len(infos) == 1
    info = infos[0]
    assert info.index == 0
    assert info.num == p.calls[0].meta.id
    assert info.executed
    assert info.errno == 0
    assert len(info.signal) > 0  # synthetic fallback signal


def test_result_arg_dataflow(target, env):
    # r0 flows from getgid() into setresgid; as any uid this must succeed
    # (setting gids to the current gid), proving the executor resolved the
    # ExecArgResult instruction-index reference.
    p = deserialize(target, "r0 = getgid()\nsetresgid(r0, r0, r0)\n")
    _, infos, failed, hanged = env.exec(ExecOpts(), p)
    assert not failed and not hanged
    assert len(infos) == 2
    assert infos[1].errno == 0


def test_errno_reported(target, env):
    # close of a known-bad fd must report EBADF(9)
    p = deserialize(target, "close(0xffffff9c)\n")
    _, infos, _, _ = env.exec(ExecOpts(), p)
    assert len(infos) == 1
    assert infos[0].errno == 9


def test_generated_progs_roundtrip(target, env):
    completed = 0
    for seed in range(20):
        p = generate(target, seed, 8)
        _, infos, failed, hanged = env.exec(ExecOpts(), p)
        # A blocking call (pause, blocking read, ...) legitimately hangs
        # the child, which the parent kills on timeout — that's a normal
        # program outcome, not an executor failure.
        assert not failed, f"seed {seed}"
        if hanged:
            continue
        completed += 1
        assert len(infos) == len(p.calls)
        for i, info in enumerate(infos):
            assert info.index == i
            assert info.num == p.calls[i].meta.id
    # Blocking calls are rare; an executor that hangs on everything is
    # broken, not tolerant.
    assert completed >= 15


def test_threaded_and_collide(target, env):
    p = generate(target, 7, 6)
    _, infos, failed, hanged = env.exec(
        ExecOpts(threaded=True, collide=True), p)
    assert not failed and not hanged
    # threaded mode may drop hung calls, but these are all benign
    assert len(infos) >= 1


def test_signal_determinism(target, env):
    p = deserialize(target, "r0 = getpid()\n")
    _, a, _, _ = env.exec(ExecOpts(), p)
    _, b, _, _ = env.exec(ExecOpts(), p)
    assert a[0].signal == b[0].signal


def test_cover_collection(target, env):
    p = deserialize(target, "r0 = getpid()\n")
    _, infos, _, _ = env.exec(ExecOpts(collect_cover=True), p)
    assert len(infos[0].cover) > 0


def test_executor_respawns_after_kill(target):
    with Env(target, pid=3) as e:
        p = deserialize(target, "r0 = getpid()\n")
        _, infos, failed, _ = e.exec(ExecOpts(), p)
        assert not failed
        e._proc.kill()
        e._proc.wait()
        _, infos, failed, _ = e.exec(ExecOpts(), p)
        # first exec after a kill either fails (reported) or respawns clean;
        # the one after that must succeed
        if failed:
            _, infos, failed, _ = e.exec(ExecOpts(), p)
        assert not failed
        assert len(infos) == 1


def test_exec_opts_fault_flags():
    f = ExecOpts(fault_call=3, fault_nth=7).flags()
    assert f & (1 << 6)
    assert (f >> 32) & 0xFFFF == 3
    assert (f >> 48) & 0xFFFF == 7


def test_mock_env_matches_env_api(target):
    p = generate(target, 1, 5)
    with MockEnv(target) as m:
        _, infos, failed, hanged = m.exec(ExecOpts(), p)
    assert not failed and not hanged
    assert len(infos) == len(p.calls)
    _, infos2, _, _ = MockEnv(target).exec(ExecOpts(), p)
    assert [i.signal for i in infos] == [i.signal for i in infos2]


def test_gate_window():
    import threading

    hooks = []
    g = Gate(2, hook=lambda: hooks.append(g._retired))
    t0 = g.enter()
    t1 = g.enter()
    # window full: a third entry must block until ticket 0 retires
    entered = threading.Event()

    def third():
        t = g.enter()
        entered.set()
        g.leave(t)

    th = threading.Thread(target=third)
    th.start()
    assert not entered.wait(0.1), "section size+0 started before section 0 ended"
    g.leave(t1)  # out of order: ticket 0 still running, nothing retires
    assert not entered.wait(0.1), "out-of-order leave released the window"
    g.leave(t0)  # tickets 0+1 retire together -> hook fires once, window opens
    assert entered.wait(1)
    th.join()
    assert hooks == [2]
