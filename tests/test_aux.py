"""Fleet-aux package tests: email workflow, KD decoder, kernel build
helper, adb/gce backend registration."""

import os
import struct
import subprocess
import sys

import pytest


# --------------------------------------------------------------------- #
# email (reference pkg/email)


def test_email_parse_command_and_bugid():
    from syzkaller_tpu.utils.email import parse

    raw = """From: Dev Eloper <dev@kernel.org>
To: syzbot+abcd1234@syzkaller.example.com, lkml@vger.kernel.org
Cc: maintainer@kernel.org
Subject: Re: [syzbot] KASAN: use-after-free in foo
Message-ID: <msg123@mail>

Thanks for the report.

#syz fix: net: fix refcount leak in foo
"""
    em = parse(raw, own_emails=("syzbot@syzkaller.example.com",))
    assert em.bug_id == "abcd1234"
    assert em.from_addr == "dev@kernel.org"
    assert em.command == "fix"
    assert em.command_args == "net: fix refcount leak in foo"
    assert "lkml@vger.kernel.org" in em.cc
    assert "maintainer@kernel.org" in em.cc
    assert "syzbot" not in " ".join(em.cc)
    assert em.message_id == "<msg123@mail>"


def test_email_addr_context_roundtrip():
    from syzkaller_tpu.utils.email import (
        add_addr_context,
        remove_addr_context,
    )

    a = add_addr_context("bot@example.com", "bug42")
    assert a == "bot+bug42@example.com"
    assert remove_addr_context(a) == ("bot@example.com", "bug42")
    assert remove_addr_context("x@y.z") == ("x@y.z", "")


def test_email_merge_and_reply():
    from syzkaller_tpu.utils.email import form_reply, merge_email_lists

    merged = merge_email_lists(
        ["A <a@x.com>", "b@y.com"], ["a@x.com", "c@z.com"])
    assert merged == ["a@x.com", "b@y.com", "c@z.com"]
    rep = form_reply("original line 1\nline 2", "my answer")
    assert rep.startswith("my answer\n\n> original line 1\n> line 2")


def test_email_multipart_body():
    from syzkaller_tpu.utils.email import parse

    raw = (
        "From: a@b.c\n"
        "Subject: t\n"
        'Content-Type: multipart/alternative; boundary="BBB"\n'
        "\n--BBB\n"
        "Content-Type: text/html\n\n<b>nope</b>\n"
        "--BBB\n"
        "Content-Type: text/plain\n\n#syz invalid\n"
        "--BBB--\n")
    em = parse(raw)
    assert em.command == "invalid"


# --------------------------------------------------------------------- #
# KD decoder (reference pkg/kd)


def _kd_packet(typ, payload):
    hdr = struct.pack("<4sHHII", b"0000", typ, len(payload), 1, 0)
    return hdr + payload


def test_kd_state_change_decodes():
    from syzkaller_tpu.utils import kd

    prefix = struct.pack("<IHHIQQ", 3, 0, 1, 2, 0xCAFE, 0xFFFF800000001234)
    exc = struct.pack("<IIQQII15QI", 0xC0000005, 0, 0, 0xDEAD, 1, 0,
                      *([0] * 15), 1)
    stream = b"garbage" + _kd_packet(kd.TYPE_STATE_CHANGE64, prefix + exc)
    start, size, decoded = kd.decode(stream)
    assert start == len(b"garbage")
    assert size == len(stream) - start
    text = decoded.decode()
    assert "BUG: first chance exception 0xc0000005" in text
    assert "pc 0xffff800000001234" in text and "addr 0xdead" in text


def test_kd_non_exception_packet_skipped():
    from syzkaller_tpu.utils import kd

    stream = _kd_packet(2, b"\x00" * 8)
    start, size, decoded = kd.decode(stream)
    assert (start, size, decoded) == (0, len(stream), b"")


def test_kd_incomplete_waits():
    from syzkaller_tpu.utils import kd

    full = _kd_packet(kd.TYPE_STATE_CHANGE64, b"\x00" * 300)
    start, size, _ = kd.decode(full[:10])
    assert size == 0  # incomplete: caller should retry with more data


# --------------------------------------------------------------------- #
# kernel build helper (reference pkg/kernel)


def test_kernel_build_drives_make(tmp_path):
    """Build against a fake kernel tree whose `make` records invocations."""
    from syzkaller_tpu.ci import kernel

    kdir = tmp_path / "linux"
    (kdir / "arch/x86/boot").mkdir(parents=True)
    bindir = tmp_path / "bin"
    bindir.mkdir()
    make = bindir / "make"
    make.write_text(f"""#!/bin/sh
echo "$@" >> {kdir}/make.log
if [ "$1" = bzImage ]; then touch {kdir}/arch/x86/boot/bzImage; fi
""")
    make.chmod(0o755)
    cfgfile = tmp_path / "kcfg"
    cfgfile.write_text("CONFIG_KASAN=y\n")
    old_path = os.environ["PATH"]
    os.environ["PATH"] = f"{bindir}:{old_path}"
    try:
        bz = kernel.build(str(kdir), str(cfgfile), compiler="gcc-13")
    finally:
        os.environ["PATH"] = old_path
    assert os.path.exists(bz)
    log = (kdir / "make.log").read_text()
    assert "olddefconfig" in log
    assert "CC=gcc-13" in log
    assert (kdir / ".config").read_text() == "CONFIG_KASAN=y\n"


def test_kernel_build_failure_raises(tmp_path):
    from syzkaller_tpu.ci import kernel

    kdir = tmp_path / "linux"
    kdir.mkdir()
    bindir = tmp_path / "bin"
    bindir.mkdir()
    (bindir / "make").write_text("#!/bin/sh\necho boom >&2; exit 2\n")
    (bindir / "make").chmod(0o755)
    cfgfile = tmp_path / "kcfg"
    cfgfile.write_text("")
    old_path = os.environ["PATH"]
    os.environ["PATH"] = f"{bindir}:{old_path}"
    try:
        with pytest.raises(kernel.KernelBuildError, match="boom"):
            kernel.build(str(kdir), str(cfgfile))
    finally:
        os.environ["PATH"] = old_path


# --------------------------------------------------------------------- #
# backend registration


def test_lazy_backend_registration():
    from syzkaller_tpu.vm import VMConfig, create

    # adb registers lazily on first use; gce too (both will fail to CREATE
    # instances without hardware/cloud, but the pool must resolve)
    pool = create(VMConfig(type="adb", targets=["SERIAL1", "SERIAL2"]))
    assert pool.count == 2
    pool2 = create(VMConfig(type="gce", image="img", count=3))
    assert pool2.count == 3
    with pytest.raises(ValueError, match="unknown VM type"):
        create(VMConfig(type="nonexistent"))
