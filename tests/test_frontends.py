"""Frontend registry + hlo compiler-fuzzing frontend tests (ISSUE 16).

Pins the headline claims of the frontends subsystem:

  - refactor guard: the default ``syscall`` frontend path through the
    registry is behaviorally identical to the pre-registry engine (same
    env types, and two seeded MockEnv campaigns — default config vs
    explicit ``frontend="syscall"`` — produce bit-identical corpus and
    signal);
  - the hlo target compiles through the UNCHANGED table/tensor codec
    stack (slot templates, fixed-width rows, encode/decode round trips);
  - the in-process differential executor: deterministic coverage,
    structural compile-cache hits, seeded miscompare/exception bugs
    reported as crash-PCs through the existing paths, and bug triggers
    that require op AND pass so minimization provably keeps both;
  - CLI: unknown ``--frontend`` dies at parse time with the registry's
    name list (exit 2);
  - e2e: a short CPU campaign on the stock device pipeline finds,
    triages, and journals every seeded differential bug with coverage
    growing and admission deduping — arena/admission/supervision/journal
    code paths unchanged, asserted via the existing metrics.
"""

import json
import subprocess
import sys
import time

import numpy as np
import pytest

from syzkaller_tpu import frontends
from syzkaller_tpu.descriptions.tables import get_tables
from syzkaller_tpu.engine.fuzzer import Fuzzer, FuzzerConfig, ManagerConn
from syzkaller_tpu.frontends.hlo import bugs as hbugs
from syzkaller_tpu.frontends.hlo.executor import HloEnv, _pc
from syzkaller_tpu.ipc import ExecOpts, MockEnv
from syzkaller_tpu.prog import get_target
from syzkaller_tpu.prog import prog as pm
from syzkaller_tpu.prog.encoding import serialize
from syzkaller_tpu.prog.encodingexec import serialize_for_exec
from syzkaller_tpu.prog.generation import generate
from syzkaller_tpu.prog.mutation import minimize
from syzkaller_tpu.prog.prog import Prog
from syzkaller_tpu.prog.tensor import TensorFormat, decode_prog, encode_prog
from syzkaller_tpu.telemetry import get_registry


@pytest.fixture(scope="module")
def hlo_target():
    return frontends.get("hlo").make_target()


@pytest.fixture(autouse=True)
def _no_bug_plan():
    hbugs.clear()
    yield
    hbugs.clear()


def _result_arg(typ, producer_call):
    a = pm.ResultArg(typ, res=producer_call.ret, val=0)
    producer_call.ret.uses.add(a)
    return a


def _call(meta, *args):
    return pm.Call(meta=meta, args=list(args),
                   ret=pm.ReturnArg(meta.ret) if meta.ret else None)


def _trigger_prog(t, op_name: str, pass_name: str, junk: int = 0) -> Prog:
    """const leaf -> trigger op -> pass marker, plus optional junk calls
    (independent iota/neg chains and an extra pass) minimization must be
    able to drop."""
    const = t.syscall_map["hlo_const"]
    op = t.syscall_map[op_name]
    leaf = _call(const, pm.ConstArg(const.args[0], 0),
                 pm.ConstArg(const.args[1], 3), pm.ConstArg(const.args[2], 7))
    args = []
    for at in op.args:
        if at.name == "hlo_tensor":
            args.append(_result_arg(at, leaf))
        else:
            args.append(pm.ConstArg(at, 1))
    trig = _call(op, *args)
    calls = [leaf, trig, _call(t.syscall_map[pass_name])]
    for j in range(junk):
        iota = t.syscall_map["hlo_iota"]
        neg = t.syscall_map["hlo_neg"]
        jleaf = _call(iota, pm.ConstArg(iota.args[0], j % 3),
                      pm.ConstArg(iota.args[1], (j + 1) % 8))
        calls.append(jleaf)
        calls.append(_call(neg, _result_arg(neg.args[0], jleaf)))
    if junk:
        calls.append(_call(t.syscall_map["hlo_pass_dce"]))
    return Prog(target=t, calls=calls)


# ---- registry + CLI ---------------------------------------------------


def test_registry_names_and_lookup():
    assert set(frontends.names()) >= {"syscall", "hlo"}
    assert frontends.get("syscall").name == "syscall"
    with pytest.raises(KeyError) as ei:
        frontends.get("nope")
    # the error carries the full name list (the CLI quotes it)
    assert "syscall" in str(ei.value) and "hlo" in str(ei.value)


def test_cli_rejects_unknown_frontend():
    """Unknown --frontend must die at argument-parse time with exit 2
    and the registry's name list — not an AttributeError at first
    batch."""
    r = subprocess.run(
        [sys.executable, "-m", "syzkaller_tpu.engine",
         "--frontend", "bogus", "-mock", "-iterations", "1"],
        capture_output=True, text=True, timeout=120)
    assert r.returncode == 2
    assert "unknown frontend 'bogus'" in r.stderr
    assert "syscall" in r.stderr and "hlo" in r.stderr


def test_unknown_frontend_config_raises_before_envs():
    t = get_target("linux", "amd64")
    with pytest.raises(KeyError):
        Fuzzer(t, FuzzerConfig(mock=True, use_device=False,
                               frontend="bogus"))


# ---- refactor guard: syscall-frontend parity --------------------------


def _mock_campaign(explicit_frontend: bool, seed: int = 9):
    t = get_target("linux", "amd64")
    kw = {"frontend": "syscall"} if explicit_frontend else {}
    cfg = FuzzerConfig(mock=True, use_device=False, procs=2,
                       program_length=8, prefix_cache_entries=64, **kw)
    f = Fuzzer(t, cfg, seed=seed)
    for _ in range(60):
        f.step()
    out = (sorted(serialize(p) for p in f.corpus),
           set(f.max_signal),
           [type(e).__name__ for e in f.envs],
           [e.prefix_cache_entries for e in f.envs])
    f.close()
    return out


def test_syscall_frontend_parity_with_default_path():
    """The registry indirection must be invisible: a seeded MockEnv
    campaign through the default config and one explicitly selecting
    frontend="syscall" produce identical corpus, signal, and envs."""
    corpus_a, sig_a, envs_a, pce_a = _mock_campaign(False)
    corpus_b, sig_b, envs_b, pce_b = _mock_campaign(True)
    assert envs_a == envs_b == ["MockEnv", "MockEnv"]
    assert pce_a == pce_b == [64, 64]  # cfg plumbing reaches the env
    assert corpus_a == corpus_b
    assert sig_a == sig_b
    assert len(corpus_a) > 0


def test_syscall_frontend_env_construction_matches_pre_refactor():
    """make_env replicates the historical loop verbatim: MockEnv under
    cfg.mock with the configured prefix cache bound."""
    t = get_target("linux", "amd64")
    fe = frontends.get("syscall")
    cfg = FuzzerConfig(mock=True, prefix_cache_entries=17)
    env = fe.make_env(t, 3, cfg)
    assert isinstance(env, MockEnv)
    assert env.pid == 3 and env.prefix_cache_entries == 17


# ---- hlo target through the unchanged codec stack ---------------------


def test_hlo_target_builds_and_compiles_tables(hlo_target):
    t = hlo_target
    assert t.os == "hlo" and t.arch == "xla"
    assert t.mmap_syscall is not None
    assert t.mmap_syscall.name == "hlo_setup"
    assert "hlo_tensor" in t.resource_map
    # pass markers present and distinct ops
    names = {c.name for c in t.syscalls}
    assert {"hlo_dot", "hlo_pass_fold", "hlo_pass_cse"} <= names
    tables = get_tables(t)
    assert tables.n_calls == len(t.syscalls)
    # every tensor op can be constructed: the resource has ctors
    assert t.resource_ctors["hlo_tensor"]


def test_hlo_generate_and_serialize_roundtrip(hlo_target):
    from syzkaller_tpu.prog.encoding import deserialize

    for seed in range(10):
        p = generate(hlo_target, seed, 10, None)
        text = serialize(p)
        p2 = deserialize(hlo_target, text)
        assert serialize(p2) == text
        assert serialize_for_exec(p2, 0)


def test_hlo_tensor_row_roundtrip(hlo_target):
    """hlo programs ride the SAME fixed-width row encoding: encode ->
    decode preserves the op sequence (pass markers included), and
    decode -> encode is a fixed point."""
    t = hlo_target
    tables = get_tables(t)
    fmt = TensorFormat.for_tables(tables)
    for seed in range(10):
        p = generate(t, seed, 10, None)
        b = encode_prog(tables, fmt, p)
        q = decode_prog(tables, fmt, b, 0)
        q.validate()
        mmap = t.mmap_syscall
        orig = [c.meta.name for c in p.calls if c.meta is not mmap]
        got = [c.meta.name for c in q.calls if c.meta is not mmap]
        assert got == orig[: fmt.max_calls]
        b2 = encode_prog(tables, fmt, q)
        assert np.array_equal(b.call_id, b2.call_id)
        assert np.array_equal(b.slot_val, b2.slot_val)


# ---- the differential executor ---------------------------------------


def test_hlo_executor_coverage_deterministic(hlo_target):
    """Per-call coverage is a pure function of the instruction stream:
    two envs, repeated execs — identical signal, clean exits."""
    env1 = HloEnv(hlo_target, pid=0)
    env2 = HloEnv(hlo_target, pid=1)
    for seed in range(6):
        p = generate(hlo_target, seed, 8, None)
        _, i1, f1, h1 = env1.exec(ExecOpts(), p)
        _, i2, f2, h2 = env2.exec(ExecOpts(), p)
        _, i3, _, _ = env1.exec(ExecOpts(), p)
        assert not f1 and not h1 and not f2 and not h2
        assert [c.signal for c in i1] == [c.signal for c in i2]
        assert [c.signal for c in i1] == [c.signal for c in i3]
        assert len(i1) == len(p.calls)


def test_hlo_compile_cache_hits_on_same_structure(hlo_target):
    env = HloEnv(hlo_target, pid=0)
    reg = get_registry()
    p = _trigger_prog(hlo_target, "hlo_add", "hlo_pass_cse")
    env.exec(ExecOpts(), p)
    before = reg.snapshot()
    env.exec(ExecOpts(), p)
    after = reg.snapshot()
    assert after["frontend_compile_cache_hits_total"] \
        == before["frontend_compile_cache_hits_total"] + 1
    assert after["frontend_compiles_total"] \
        == before["frontend_compiles_total"]


def test_seeded_miscompare_reported_as_crash_signal(hlo_target):
    """A seeded miscompare surfaces as errno + a distinctive crash PC on
    the TRIGGER call, with failed=False so the engine's triage scans it
    instead of discarding the program."""
    plan = hbugs.BugPlan(bugs=(
        hbugs.SeededBug(name="x", op="hlo_dot",
                        pass_name="hlo_pass_fold"),))
    hbugs.install(plan)
    env = HloEnv(hlo_target, pid=0)
    reg = get_registry()
    before = reg.snapshot().get("frontend_miscompares_total", 0)

    p = _trigger_prog(hlo_target, "hlo_dot", "hlo_pass_fold")
    _, infos, failed, hanged = env.exec(ExecOpts(), p)
    assert not failed and not hanged
    assert infos[1].errno == 5  # the hlo_dot call
    assert _pc("bug", "hlo-seeded-x") in infos[1].signal
    assert plan.fired_names() == {"x"}
    assert reg.snapshot()["frontend_miscompares_total"] == before + 1

    # content-determinism: reruns (triage) reproduce it identically
    _, infos2, _, _ = env.exec(ExecOpts(), p)
    assert infos2[1].errno == 5
    assert _pc("bug", "hlo-seeded-x") in infos2[1].signal


def test_seeded_bug_requires_both_op_and_pass(hlo_target):
    plan = hbugs.BugPlan(bugs=(
        hbugs.SeededBug(name="x", op="hlo_dot",
                        pass_name="hlo_pass_fold"),))
    hbugs.install(plan)
    env = HloEnv(hlo_target, pid=0)
    # op without the pass: no fire
    p = _trigger_prog(hlo_target, "hlo_dot", "hlo_pass_cse")
    _, infos, _, _ = env.exec(ExecOpts(), p)
    assert all(i.errno == 0 for i in infos)
    # pass without the op: no fire
    p = _trigger_prog(hlo_target, "hlo_add", "hlo_pass_fold")
    _, infos, _, _ = env.exec(ExecOpts(), p)
    assert all(i.errno == 0 for i in infos)
    assert plan.fired() == []


def test_seeded_exception_and_timeout_kinds(hlo_target):
    plan = hbugs.BugPlan(bugs=(
        hbugs.SeededBug(name="boom", op="hlo_neg", kind="exception"),
        hbugs.SeededBug(name="hang", op="hlo_abs", kind="timeout"),))
    hbugs.install(plan)
    env = HloEnv(hlo_target, pid=0)
    reg = get_registry()
    b = reg.snapshot()

    p = _trigger_prog(hlo_target, "hlo_neg", "hlo_pass_fuse")
    _, infos, failed, _ = env.exec(ExecOpts(), p)
    assert not failed and infos[1].errno == 5
    p = _trigger_prog(hlo_target, "hlo_abs", "hlo_pass_fuse")
    _, infos, failed, _ = env.exec(ExecOpts(), p)
    assert not failed and infos[1].errno == 5

    a = reg.snapshot()
    assert a["frontend_exceptions_total"] == b.get(
        "frontend_exceptions_total", 0) + 1
    assert a["frontend_exec_timeouts_total"] == b.get(
        "frontend_exec_timeouts_total", 0) + 1
    assert plan.fired_names() == {"boom", "hang"}


def test_hlo_env_death_site_keeps_supervision_contract(hlo_target):
    """The testing/faults.py env.exec:<pid> site works unchanged: the
    env reports failed like a crashed executor and counts a restart —
    the drain supervisor path needs no frontend-specific code."""
    from syzkaller_tpu.testing import faults

    faults.install(faults.FaultPlan().fail_at("env.exec:0", 1))
    try:
        env = HloEnv(hlo_target, pid=0)
        p = _trigger_prog(hlo_target, "hlo_add", "hlo_pass_cse")
        _, infos, failed, hanged = env.exec(ExecOpts(), p)
        assert failed and not hanged and infos == []
        assert env.restarts == 1
        _, infos, failed, _ = env.exec(ExecOpts(), p)
        assert not failed and len(infos) == len(p.calls)
    finally:
        faults.clear()


def test_minimize_shrinks_ops_and_pass_list(hlo_target):
    """The acceptance property: minimization against a seeded (op, pass)
    bug drops the junk op chains AND the junk pass markers but must keep
    both the trigger op and its required pass — the joint IR+pass row
    minimizes through the stock call-removal ladder."""
    plan = hbugs.BugPlan(bugs=(
        hbugs.SeededBug(name="x", op="hlo_dot",
                        pass_name="hlo_pass_fold"),))
    hbugs.install(plan)
    env = HloEnv(hlo_target, pid=0)
    t = hlo_target

    p = _trigger_prog(t, "hlo_dot", "hlo_pass_fold", junk=3)
    names0 = [c.meta.name for c in p.calls]
    assert "hlo_pass_dce" in names0 and names0.count("hlo_neg") == 3
    crash_pc = _pc("bug", "hlo-seeded-x")
    trig_idx = names0.index("hlo_dot")

    def pred(p1, ci):
        _, infos, failed, hanged = env.exec(ExecOpts(), p1)
        if failed or hanged or not (0 <= ci < len(infos)):
            return False
        return crash_pc in infos[ci].signal

    assert pred(p, trig_idx)  # the bug reproduces before minimizing
    p2, idx = minimize(p, trig_idx, pred)
    names = [c.meta.name for c in p2.calls]
    assert len(p2.calls) < len(p.calls)
    assert p2.calls[idx].meta.name == "hlo_dot"
    assert "hlo_pass_fold" in names      # the required pass survives
    assert "hlo_pass_dce" not in names   # the junk pass is gone
    assert "hlo_neg" not in names        # the junk op chains are gone


# ---- e2e: seeded bugs through the stock engine ------------------------


@pytest.mark.hlo
def test_hlo_e2e_campaign_finds_triages_journals_seeded_bugs(tmp_path):
    """A short CPU campaign on the UNCHANGED device pipeline: all seeded
    differential bugs found (crash PCs triaged into max_signal, crash
    records journaled), coverage growing and admission deduping across
    batches — asserted via the existing metrics only."""
    fe = frontends.get("hlo")
    t = fe.make_target()
    plan = hbugs.default_plan()
    hbugs.install(plan)
    reg = get_registry()
    before = reg.snapshot()

    # seed corpus: near-trigger programs (trigger op + pass + junk), the
    # role of syzkaller's seed corpus — the campaign still has to
    # execute, triage, minimize, and journal them through the stock
    # paths, and mutation explores around them
    seeds = [serialize(_trigger_prog(t, b.op, b.pass_name or
                                     "hlo_pass_fuse", junk=2))
             for b in plan.bugs]

    class SeedConn(ManagerConn):
        def connect(self):
            d = super().connect()
            d["candidates"] = seeds
            return d

    cfg = FuzzerConfig(frontend="hlo", use_device=True, device_batch=8,
                       device_period=4, procs=1, program_length=6,
                       smash_mutations=1, triage_reruns=2,
                       workdir=str(tmp_path))
    f = Fuzzer(t, cfg, manager=SeedConn(), seed=3)
    want = {f"hlo-seeded-{b.name}" for b in plan.bugs}
    want_pcs = {_pc("bug", title) for title in want}
    sig_first_batch = None
    deadline = time.time() + 150
    try:
        while time.time() < deadline:
            f.step()
            if sig_first_batch is None and f.stats["device_batches"]:
                sig_first_batch = len(f.max_signal)
            if want_pcs <= f.max_signal and f.stats["device_batches"] > 1:
                break

        # found: every seeded bug fired and its crash PC was triaged
        # into the campaign's signal
        assert plan.fired_names() == {b.name for b in plan.bugs}
        assert want_pcs <= f.max_signal
        assert f.stats["new_inputs"] > 0 and f.stats["exec_triage"] > 0

        # journaled through the existing crash path
        records = [json.loads(line) for line in
                   (tmp_path / "journal.jsonl").read_text().splitlines()]
        crash_titles = {r["title"] for r in records if r["ev"] == "crash"}
        assert want <= crash_titles

        # stock machinery moved, no forks: admission dedup + device
        # batches + journal volume via the existing metrics; env
        # supervision untouched (no restarts in a healthy campaign)
        after = reg.snapshot()
        assert after["candidates_admitted_total"] > \
            before.get("candidates_admitted_total", 0)
        assert after["candidates_deduped_total"] >= \
            before.get("candidates_deduped_total", 0)
        assert after["journal_records_total"] > \
            before.get("journal_records_total", 0)
        assert f.stats["device_batches"] > 1
        assert all(e.restarts == 0 for e in f.envs)
        # coverage kept growing after the first device batch
        assert sig_first_batch is not None
        assert len(f.max_signal) > sig_first_batch
    finally:
        f.close()


@pytest.mark.hlo
@pytest.mark.slow
def test_hlo_organic_campaign_soak():
    """Fully organic (no seed corpus): random generation + device
    mutation alone find every seeded differential bug."""
    fe = frontends.get("hlo")
    t = fe.make_target()
    plan = hbugs.default_plan()
    hbugs.install(plan)
    cfg = FuzzerConfig(frontend="hlo", use_device=True, device_batch=8,
                       device_period=4, procs=1, program_length=8,
                       smash_mutations=2, triage_reruns=2)
    f = Fuzzer(t, cfg, seed=5)
    deadline = time.time() + 600
    try:
        while time.time() < deadline:
            f.step()
            if len(plan.fired_names()) == len(plan.bugs):
                break
        assert plan.fired_names() == {b.name for b in plan.bugs}
    finally:
        f.close()
