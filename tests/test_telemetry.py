"""Telemetry subsystem tests (ISSUE 1): registry semantics, thread
safety, span nesting, Prometheus exposition, the manager /metrics and
/trace endpoints, and the end-to-end MockEnv fuzzer instrumentation
including the compile/dispatch split on the device fuzz step and the
<5% overhead bound on the mock engine loop."""

import json
import threading
import time
import urllib.request

import pytest

from syzkaller_tpu.telemetry import (
    Registry,
    Tracer,
    get_registry,
    get_tracer,
    set_spans_enabled,
    telemetry_dump,
)
from syzkaller_tpu.telemetry.metrics import DEFAULT_BUCKETS


@pytest.fixture()
def reg():
    return Registry()


@pytest.fixture()
def tracer(reg):
    return Tracer(registry=reg)


# ---- metric semantics ----


def test_counter_semantics(reg):
    c = reg.counter("c", help="h")
    assert c.value == 0
    c.inc()
    c.inc(41)
    assert c.value == 42
    assert reg.counter("c") is c  # get-or-create returns the same object


def test_gauge_semantics(reg):
    g = reg.gauge("g")
    g.set(7)
    g.inc(3)
    g.dec()
    assert g.value == 9
    backing = [1, 2, 3]
    g.set_fn(lambda: len(backing))
    assert g.value == 3
    backing.append(4)
    assert g.value == 4  # callback-backed reads are live
    g.set(5)             # explicit set clears the callback
    assert g.value == 5


def test_histogram_semantics(reg):
    h = reg.histogram("h", buckets=(0.1, 1.0, 10.0))
    for v in (0.05, 0.5, 0.5, 5.0, 50.0):
        h.observe(v)
    assert h.count == 5
    assert h.sum == pytest.approx(56.05)
    cum = h.cumulative()
    assert cum == [(0.1, 1), (1.0, 3), (10.0, 4), (float("inf"), 5)]


def test_histogram_bucket_edge_is_le(reg):
    # Prometheus buckets are `le`: an observation equal to a bound lands
    # in that bound's bucket
    h = reg.histogram("edge", buckets=(1.0, 2.0))
    h.observe(1.0)
    assert h.cumulative()[0] == (1.0, 1)


def test_type_conflict_raises(reg):
    reg.counter("x")
    with pytest.raises(TypeError):
        reg.gauge("x")


def test_snapshot_and_delta(reg):
    reg.counter("c").inc(10)
    reg.gauge("g").set(3)
    reg.histogram("h", buckets=(1.0,)).observe(0.5)
    snap = reg.snapshot()
    assert snap == {"c": 10, "g": 3, "h_count": 1, "h_sum": 0.5}
    reg.counter("c").inc(5)
    reg.gauge("g").set(99)
    d = reg.delta(snap)
    assert d["c"] == 5          # counters diff
    assert d["g"] == 99         # gauges pass through as-is
    assert d["h_count"] == 0


# ---- thread safety ----


def test_concurrent_bumps_are_exact(reg):
    c = reg.counter("tc")
    h = reg.histogram("th")
    n_threads, n_iter = 8, 5000

    def work():
        for _ in range(n_iter):
            c.inc()
            h.observe(0.001)

    threads = [threading.Thread(target=work) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == n_threads * n_iter
    assert h.count == n_threads * n_iter


# ---- spans ----


def test_span_nesting_and_order(tracer):
    with tracer.span("outer"):
        with tracer.span("inner"):
            pass
    events = tracer.events()
    # inner finishes first; depth reflects nesting
    assert [(e[0], e[4]) for e in events] == [("inner", 1), ("outer", 0)]
    # each span also feeds a latency histogram in the registry
    assert tracer._reg().get("span_outer_seconds").count == 1
    assert tracer._reg().get("span_inner_seconds").count == 1


def test_span_optout(tracer, reg):
    reg.spans_enabled = False
    with tracer.span("off"):
        pass
    assert tracer.events() == []
    reg.spans_enabled = True
    with tracer.span("on"):
        pass
    assert tracer.span_names() == ["on"]


def test_span_ring_bound(reg):
    tr = Tracer(registry=reg, max_events=4)
    for i in range(10):
        with tr.span(f"s{i}"):
            pass
    assert len(tr.events()) == 4
    assert tr.events()[-1][0] == "s9"


def test_timed_single_sink(tracer, reg):
    """timed() feeds the explicit histogram exactly once and records a
    trace event — no duplicate auto-named span_* histogram."""
    h = reg.histogram("phase_latency_seconds")
    with tracer.timed("fuzzer.phase", h):
        pass
    assert h.count == 1
    assert tracer.span_names() == ["fuzzer.phase"]
    assert reg.get("span_fuzzer_phase_seconds") is None
    # spans off: the histogram still observes (wire stats stay on), the
    # trace buffer does not grow
    reg.spans_enabled = False
    with tracer.timed("fuzzer.phase", h):
        pass
    assert h.count == 2
    assert len(tracer.events()) == 1


def test_gauge_clear_fn_only_detaches_own(reg):
    g = reg.gauge("cg")
    f1, f2 = (lambda: 1), (lambda: 2)
    g.set_fn(f1)
    g.clear_fn(f2)    # not the bound fn: no-op
    assert g.value == 1
    g.set_fn(f2)
    g.clear_fn(f1)    # stale owner must not clobber the newer binding
    assert g.value == 2
    g.clear_fn(f2)
    assert g.value == 0


def test_fuzzer_close_detaches_gauges():
    from syzkaller_tpu.engine.fuzzer import Fuzzer, FuzzerConfig
    from syzkaller_tpu.prog import get_target

    target = get_target("linux", "amd64")
    g = get_registry().gauge("fuzzer_corpus_size")
    cfg = FuzzerConfig(mock=True, use_device=False, smash_mutations=2)
    with Fuzzer(target, cfg) as f:
        f.loop(iterations=40)
        assert g.value == len(f.corpus) > 0
    assert g.value == 0  # close() detached the callback


def test_tracer_survives_registry_reset():
    """After Registry.reset() the tracer drops its stale histogram cache
    so span_* metrics reappear in the live registry."""
    reg = Registry()
    tr = Tracer(registry=reg)
    with tr.span("p"):
        pass
    assert reg.get("span_p_seconds").count == 1
    reg.reset()
    with tr.span("p"):
        pass
    assert reg.get("span_p_seconds").count == 1  # fresh, live histogram


def test_chrome_trace_document(tracer):
    with tracer.span("phase.a"):
        time.sleep(0.001)
    doc = tracer.chrome_trace()
    assert doc["displayTimeUnit"] == "ms"
    (ev,) = doc["traceEvents"]
    assert ev["name"] == "phase.a" and ev["ph"] == "X"
    assert ev["dur"] >= 1000  # microseconds
    json.dumps(doc)  # must be JSON-serializable as-is


# ---- Prometheus text exposition ----


def test_prometheus_text_format(reg):
    reg.counter("exec_total", help="programs executed").inc(3)
    reg.gauge("corpus_size").set(17)
    reg.histogram("lat", buckets=(0.5, 1.0)).observe(0.7)
    text = reg.prometheus_text()
    assert "# HELP exec_total programs executed" in text
    assert "# TYPE exec_total counter" in text
    assert "exec_total 3" in text
    assert "# TYPE corpus_size gauge" in text
    assert "corpus_size 17" in text
    assert "# TYPE lat histogram" in text
    assert 'lat_bucket{le="0.5"} 0' in text
    assert 'lat_bucket{le="1"} 1' in text
    assert 'lat_bucket{le="+Inf"} 1' in text
    assert "lat_sum 0.7" in text
    assert "lat_count 1" in text
    assert text.endswith("\n")


# ---- manager endpoints ----


def _get(mgr, path: str) -> bytes:
    with urllib.request.urlopen(f"http://{mgr.http.addr}{path}",
                                timeout=10) as r:
        return r.read()


def test_manager_metrics_and_trace_endpoints(tmp_path):
    from syzkaller_tpu.manager import Manager, ManagerConfig
    from syzkaller_tpu.prog import get_target

    target = get_target("linux", "amd64")
    m = Manager(ManagerConfig(workdir=str(tmp_path)), target=target)
    try:
        m._bump("exec_total", 2)
        text = _get(m, "/metrics").decode()
        # acceptance: one counter, one gauge, one histogram
        assert "# TYPE exec_total counter" in text
        assert "# TYPE corpus_size gauge" in text
        assert "# TYPE device_batch_latency_seconds histogram" in text
        assert 'device_batch_latency_seconds_bucket{le="+Inf"}' in text
        doc = json.loads(_get(m, "/trace"))
        assert "traceEvents" in doc
        # the summary page links the telemetry endpoints
        page = _get(m, "/").decode()
        assert "/metrics" in page and "/trace" in page
    finally:
        m.close()


def test_metrics_content_type_and_exposition_parses(tmp_path):
    """Regression (ISSUE 2 satellite): /metrics must declare the
    Prometheus exposition media type ``text/plain; version=0.0.4`` —
    scrapers content-negotiate on it — and every line of the body must
    be a well-formed exposition line (# HELP / # TYPE / sample)."""
    import re

    from syzkaller_tpu.manager import Manager, ManagerConfig
    from syzkaller_tpu.prog import get_target

    m = Manager(ManagerConfig(workdir=str(tmp_path)),
                target=get_target("linux", "amd64"))
    try:
        m._bump("exec_total", 1)
        with urllib.request.urlopen(
                f"http://{m.http.addr}/metrics", timeout=10) as r:
            assert r.headers.get("Content-Type") \
                == "text/plain; version=0.0.4"
            text = r.read().decode()
    finally:
        m.close()

    sample = re.compile(
        r"^[a-zA-Z_][a-zA-Z0-9_]*(\{[^{}]*\})? \S+$")
    seen_types = 0
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# HELP ") or line.startswith("# TYPE "):
            seen_types += line.startswith("# TYPE ")
            assert len(line.split(None, 3)) >= 4 or \
                line.startswith("# TYPE "), line
            continue
        assert sample.match(line), f"malformed exposition line: {line!r}"
        value = line.rsplit(" ", 1)[1]
        assert value in ("+Inf", "-Inf", "NaN") or float(value) is not None
    assert seen_types > 0


def test_manager_stats_dual_write(tmp_path):
    """_bump dual-writes: the historic per-manager `stats` dict shape
    and snapshot() stay per-instance (RPC wire compat, several managers
    per process), while the registry carries the process-wide total."""
    from syzkaller_tpu.manager import Manager, ManagerConfig
    from syzkaller_tpu.prog import get_target

    m = Manager(ManagerConfig(workdir=str(tmp_path)),
                target=get_target("linux", "amd64"))
    try:
        before = int(get_registry().counter("hub_recv").value)
        m._bump("hub_recv", 3)
        assert m.stats["hub_recv"] == 3
        assert m.snapshot()["hub_recv"] == 3
        assert int(get_registry().counter("hub_recv").value) == before + 3
    finally:
        m.close()


def test_fleet_stats_reach_registry(tmp_path):
    """Remote fuzzers' absolute stat snapshots (arriving via poll) fold
    into fleet_-prefixed registry counters as deltas, so /metrics covers
    the RPC topology where fuzzers don't share the process."""
    from syzkaller_tpu.manager import Manager, ManagerConfig
    from syzkaller_tpu.prog import get_target

    m = Manager(ManagerConfig(workdir=str(tmp_path)),
                target=get_target("linux", "amd64"))
    try:
        before = int(get_registry().counter("fleet_exec_total").value)
        m.on_poll("f0", {"exec_total": 100}, False, [])
        m.on_poll("f0", {"exec_total": 250}, False, [])
        m.on_poll("f1", {"exec_total": 40}, False, [])
        m.on_poll("f0", {"exec_total": 250}, False, [])  # no progress
        m.on_poll("f1", {"exec_total": 5}, False, [])    # f1 restarted
        assert int(get_registry().counter("fleet_exec_total").value) \
            == before + 295
        # the per-fuzzer absolute snapshots still sum in /stats
        # (f0 at 250, f1 restarted at 5)
        assert m.snapshot()["exec_total"] == 255
    finally:
        m.close()


# ---- end-to-end: mock fuzzer populates the registry ----


def test_mock_fuzzer_populates_registry():
    from syzkaller_tpu.engine.fuzzer import Fuzzer, FuzzerConfig
    from syzkaller_tpu.prog import get_target

    target = get_target("linux", "amd64")
    reg = get_registry()
    snap = reg.snapshot()
    cfg = FuzzerConfig(mock=True, use_device=False, smash_mutations=2)
    with Fuzzer(target, cfg) as f:
        f.loop(iterations=60)
        execs = f.stats["exec_total"]
    d = reg.delta(snap)
    assert d["exec_total"] >= execs >= 60
    assert d["ipc_exec_latency_seconds_count"] >= 60
    assert d["triage_latency_seconds_count"] > 0


def test_device_fuzz_step_compile_dispatch_spans():
    """Acceptance: a hermetic MockEnv run with the device pipeline yields
    a Chrome trace with distinct compile and dispatch spans for the
    device fuzz step, and a populated device-batch histogram."""
    pytest.importorskip("jax")
    from syzkaller_tpu.engine.fuzzer import Fuzzer, FuzzerConfig
    from syzkaller_tpu.prog import get_target

    target = get_target("linux", "amd64")
    reg = get_registry()
    tr = get_tracer()
    tr.reset()
    snap = reg.snapshot()
    cfg = FuzzerConfig(mock=True, use_device=True, device_batch=8,
                       program_length=8, smash_mutations=2,
                       device_period=2)
    with Fuzzer(target, cfg) as f:
        for _ in range(2000):
            f.step()
            if f.stats["device_batches"] >= 3:
                break
        assert f.stats["device_batches"] >= 3
    names = tr.span_names()
    assert "device.fuzz_step.compile" in names
    assert "device.fuzz_step.dispatch" in names
    doc = tr.chrome_trace()
    traced = {e["name"] for e in doc["traceEvents"]}
    assert {"device.fuzz_step.compile",
            "device.fuzz_step.dispatch"} <= traced
    d = reg.delta(snap)
    assert d["device_batch_latency_seconds_count"] >= 3
    assert d["device_batches_total"] >= 3


def test_telemetry_dump_document():
    doc = telemetry_dump()
    assert set(doc) == {"metrics", "trace", "attribution"}
    assert "traceEvents" in doc["trace"]
    assert set(doc["attribution"]) == {"phases", "operators"}
    json.dumps(doc)


# ---- bench JSON line schema (ISSUE 2 satellite) ----


def test_bench_json_line_schema(monkeypatch, capsys):
    """bench.py's one-line JSON result carries per-config
    ``span_*_seconds`` deltas (the ROADMAP open item) plus a whole-run
    telemetry delta.  The heavy bench bodies are stubbed; the schema —
    which is what BENCH_r* consumers parse — is asserted on the real
    main()."""
    import bench

    monkeypatch.setattr(bench, "_ensure_backend", lambda: "stub")
    monkeypatch.setattr(bench, "bench_device_mutate",
                        lambda dt, C=16: 1000.0)
    monkeypatch.setattr(bench, "bench_host_mutate", lambda target: 10.0)
    monkeypatch.setattr(
        bench, "bench_cover_merge_sweep",
        lambda: {f"nbits{b.bit_length() - 1}_t{t // 1000}k": {
            "device": 20.0, "device_measured_traces": min(t, 10_000),
            "host": 2.0, "fused": 200.0}
            for b in bench.COVER_SWEEP_NBITS
            for t in bench.COVER_SWEEP_TRACES})
    monkeypatch.setattr(
        bench, "bench_minimize_bisect",
        lambda target: {
            "sequential": {"items": 4, "execs": 100,
                           "execs_per_item": 25.0, "wall_s": 1.0,
                           "wall_per_item_s": 0.25, "rounds": None,
                           "serial_roundtrips_per_item": 25.0,
                           "new_inputs": 4},
            "batched": {"items": 4, "execs": 100,
                        "execs_per_item": 25.0, "wall_s": 0.5,
                        "wall_per_item_s": 0.125, "rounds": 30,
                        "serial_roundtrips_per_item": 7.5,
                        "new_inputs": 4},
            "minimized_equal": True})
    monkeypatch.setattr(bench, "bench_hints", lambda: (30.0, 3.0))
    # e2e-style configs return (rate, execs, new_inputs, efficiency)
    # per side so the JSON line can report execs-per-new-input (yield
    # efficiency) and calls-per-exec (prefix memoization)
    dev_eff = {"calls_executed_per_exec": 2.5, "prefix_hit_rate": 0.5,
               "prefix_calls_saved": 10, "journal_records": 12}
    monkeypatch.setattr(bench, "bench_e2e",
                        lambda target: ((40.0, 400, 4, dev_eff),
                                        (4.0, 40, 2, {}), "mock"))
    # compiler-frontend e2e (ISSUE 16): the real body is import-guarded
    # so pre-frontends engines report nulls through the same harness
    monkeypatch.setattr(
        bench, "bench_hlo_e2e",
        lambda: {"execs_per_sec": 25.0, "execs": 250, "new_inputs": 5,
                 "compile_cache_hit_rate": 0.5, "miscompares_found": 1,
                 "exceptions_found": 1, "timeouts_found": 0,
                 "bugs_fired": ["fold-dot-miscompare"], "seeded": 3})
    monkeypatch.setattr(
        bench, "bench_prefix_sweep",
        lambda target: {f"len{n}": {
            "off": {"execs_per_sec": 2.0, "batches": 3,
                    "calls_executed_per_exec": 4.0},
            "on": {"execs_per_sec": 3.0, "batches": 3,
                   "calls_executed_per_exec": 2.4,
                   "prefix_hit_rate": 0.8},
            "calls_reduction": 0.4}
            for n in bench.PREFIX_SWEEP_LENGTHS})
    monkeypatch.setattr(
        bench, "bench_arena_sweep",
        lambda target: {str(c): {"execs_per_sec": 1.0, "new_inputs": 1,
                                 "execs_per_new_input": 1.0,
                                 "arena_occupancy": 0.5,
                                 "arena_evictions_total": 0,
                                 "arena_weighted_evictions_total": 0}
                        for c in bench.ARENA_SWEEP_CAPACITIES})
    monkeypatch.setattr(bench, "bench_hub", lambda: 50.0)
    # async pipelined device step sweep (ISSUE 18): per-(batch, depth)
    # cells; a pre-pipeline harness nulls the depth!=1 cells
    monkeypatch.setattr(
        bench, "bench_pipeline_depth_sweep",
        lambda target: {
            "has_pipeline_depth": True,
            **{f"b{b}_d{d}": {
                "execs_per_sec": 10.0 * d, "new_inputs": 2,
                "execs_per_new_input": 5.0, "stall_rate": 0.0,
                "stalls": 0, "overlap_ratio": 1.0 + d,
                "inflight_end": d}
               for b in bench.PIPELINE_SWEEP_BATCHES
               for d in bench.PIPELINE_SWEEP_DEPTHS}})

    bench.main([])
    line = capsys.readouterr().out.strip().splitlines()[-1]
    doc = json.loads(line)

    assert {"metric", "value", "unit", "vs_baseline", "device",
            "configs", "telemetry"} <= set(doc)
    assert doc["vs_baseline"] == pytest.approx(100.0)
    e2e = doc["configs"]["e2e_triage"]
    assert e2e["execs_per_new_input"] == {"device": 100.0, "host": 20.0}
    assert e2e["new_inputs"] == {"device": 4, "host": 2}
    # executed-call efficiency (prefix memoization) rides the e2e line
    # getattr-tolerantly: the host side reports an (empty) dict too
    assert e2e["efficiency"]["device"]["calls_executed_per_exec"] == 2.5
    # journal volume rides the e2e efficiency block (ISSUE 7: the
    # durability layer's cost is visible in BENCH deltas)
    assert e2e["efficiency"]["device"]["journal_records"] == 12
    assert e2e["efficiency"]["host"] == {}
    # compiler-frontend config rides the same line (ISSUE 16)
    hlo = doc["configs"]["hlo_e2e"]
    assert hlo["compile_cache_hit_rate"] == 0.5
    assert hlo["miscompares_found"] == 1 and hlo["seeded"] == 3
    sweep = doc["configs"]["arena_sweep"]
    for cap in bench.ARENA_SWEEP_CAPACITIES:
        assert "execs_per_new_input" in sweep[str(cap)]
    psweep = doc["configs"]["prefix_depth_sweep"]
    for n in bench.PREFIX_SWEEP_LENGTHS:
        assert "calls_reduction" in psweep[f"len{n}"]
    # pipeline_depth_sweep: every (batch, depth) cell carries the
    # throughput AND the honesty numbers (stall rate, span overlap)
    plsweep = doc["configs"]["pipeline_depth_sweep"]
    assert plsweep["has_pipeline_depth"] is True
    for b in bench.PIPELINE_SWEEP_BATCHES:
        for d in bench.PIPELINE_SWEEP_DEPTHS:
            cell = plsweep[f"b{b}_d{d}"]
            assert {"execs_per_sec", "stall_rate",
                    "overlap_ratio"} <= set(cell)
    # cover_merge_sweep: every (nbits, traces) cell carries all three
    # paths (fused may be None on a pre-ISSUE 8 engine — not here)
    csweep = doc["configs"]["cover_merge_sweep"]
    for b in bench.COVER_SWEEP_NBITS:
        for t in bench.COVER_SWEEP_TRACES:
            cell = csweep[f"nbits{b.bit_length() - 1}_t{t // 1000}k"]
            assert {"device", "host", "fused",
                    "device_measured_traces"} <= set(cell)
    mb = doc["configs"]["minimize_bisect"]
    assert mb["minimized_equal"] is True
    assert mb["batched"]["serial_roundtrips_per_item"] < \
        mb["sequential"]["serial_roundtrips_per_item"]
    for name in ("mutate", "cover_merge_sweep", "minimize_bisect",
                 "hints_100k", "e2e_triage", "hlo_e2e", "arena_sweep",
                 "hub_sync", "prefix_depth_sweep",
                 "pipeline_depth_sweep"):
        cfg = doc["configs"][name]
        assert "error" not in cfg
        spans = cfg["spans"]
        assert isinstance(spans, dict)
        # every config body ran under a bench.<name> span, so its own
        # per-phase delta is always present...
        assert spans[f"span_bench_{name}_seconds_count"] >= 1
        # ...and the namespace is exclusively span_* with numeric values
        for k, v in spans.items():
            assert k.startswith("span_") and isinstance(v, (int, float))
    assert any(k.startswith("span_bench_") for k in doc["telemetry"])


# ---- overhead bound ----


def test_overhead_under_5_percent(tmp_path):
    """The per-step telemetry work (the counter incs, histogram observes,
    one span, and the attribution-ledger exec credit a mock-engine step
    pays) must cost <5% of a measured mock-engine step — measured with
    the ISSUE 2 campaign sampler ticking in the background AND the
    ISSUE 7 campaign journal enabled (a workdir is configured, so every
    corpus add / new-signal acceptance pays a real journal write inside
    the measured loop), since that is how a live campaign runs.
    Measured as cost ratios rather than two full loop timings: the box
    is a single shared core and loop-vs-loop wall-clock comparisons
    flap far more than the bound being asserted."""
    from syzkaller_tpu.engine.fuzzer import Fuzzer, FuzzerConfig
    from syzkaller_tpu.prog import get_target
    from syzkaller_tpu.telemetry import AttributionLedger, RegistrySampler

    target = get_target("linux", "amd64")
    cfg = FuzzerConfig(mock=True, use_device=False, smash_mutations=2,
                       workdir=str(tmp_path), checkpoint_interval=0)
    sampler = RegistrySampler(interval=0.05)
    sampler.start()
    try:
        with Fuzzer(target, cfg) as f:
            f.loop(iterations=50)  # warm caches
            n = 300
            t0 = time.perf_counter()
            f.loop(iterations=n)
            per_step = (time.perf_counter() - t0) / n
            journaled = f._journal.records_written
    finally:
        sampler.stop()
    assert sampler.samples_taken > 0  # sampling really was live
    assert journaled > 0  # the journal really was in the measured loop

    reg = Registry()
    tr = Tracer(registry=reg)
    led = AttributionLedger()
    c1, c2 = reg.counter("a"), reg.counter("b")
    h1, h2, h3 = (reg.histogram(x) for x in ("x", "y", "z"))
    m = 20000
    t0 = time.perf_counter()
    for _ in range(m):
        # upper bound of one engine step's telemetry: 2 counter incs,
        # 3 histogram observes, 1 recorded span, 1 ledger exec credit
        c1.inc()
        c2.inc()
        h1.observe(0.001)
        h2.observe(0.001)
        h3.observe(0.001)
        led.record_exec("mutate", (1, 2))
        with tr.span("s"):
            pass
    per_bundle = (time.perf_counter() - t0) / m
    assert per_bundle < 0.05 * per_step, (
        f"telemetry bundle {per_bundle * 1e6:.1f}us vs "
        f"step {per_step * 1e6:.1f}us")


def test_set_spans_enabled_global_toggle():
    tr = get_tracer()
    tr.reset()
    set_spans_enabled(False)
    try:
        with tr.span("never"):
            pass
        assert tr.events() == []
    finally:
        set_spans_enabled(True)
