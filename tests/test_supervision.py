"""Campaign supervision tests (ISSUE 4): checkpoint/resume round-trips,
the executor-env supervisor (backoff, quarantine, probes, watchdog), the
device degradation ladder, RPC retry/reconnect, and the seeded
fault-injection chaos harness that drives them all."""

import io
import os
import subprocess
import threading
import time

import pytest

from syzkaller_tpu.engine import checkpoint as ckpt
from syzkaller_tpu.engine.fuzzer import Fuzzer, FuzzerConfig
from syzkaller_tpu.engine.supervisor import EnvSupervisor
from syzkaller_tpu.prog import get_target
from syzkaller_tpu.telemetry import get_registry
from syzkaller_tpu.testing import faults
from syzkaller_tpu.testing.faults import FaultPlan, InjectedFault


@pytest.fixture(scope="module")
def target():
    return get_target("linux", "amd64")


@pytest.fixture(autouse=True)
def _no_leftover_faults():
    faults.clear()
    yield
    faults.clear()


def _counter(name):
    m = get_registry().get(name)
    return m.value if m is not None else 0


def mk(target, **kw) -> Fuzzer:
    kw.setdefault("mock", True)
    kw.setdefault("use_device", False)
    kw.setdefault("smash_mutations", 2)
    return Fuzzer(target, FuzzerConfig(**kw))


# --------------------------------------------------------------------- #
# fault harness


def test_fault_plan_schedule_and_determinism():
    p1 = FaultPlan(seed=42, rates={"x": 0.5}).fail_at("a", 2, 4)
    p2 = FaultPlan(seed=42, rates={"x": 0.5}).fail_at("a", 2, 4)
    seq1 = [p1.should_fire("a") for _ in range(5)]
    assert seq1 == [False, True, False, True, False]
    rand1 = [p1.should_fire("x") for _ in range(50)]
    [p2.should_fire("a") for _ in range(5)]
    rand2 = [p2.should_fire("x") for _ in range(50)]
    assert rand1 == rand2  # seeded: same plan replays identically
    assert any(rand1) and not all(rand1)
    assert ("a", 2) in p1.fired() and ("a", 4) in p1.fired()


def test_fault_hooks_noop_without_plan():
    assert faults.active() is None
    assert not faults.should_fire("anything")
    faults.fire("anything")  # must not raise
    faults.install(FaultPlan().fail_at("site", 1))
    with pytest.raises(InjectedFault):
        faults.fire("site")
    faults.fire("site")  # occurrence 2: not scheduled


def test_mock_env_honors_injected_death(target):
    from syzkaller_tpu.ipc import ExecOpts, MockEnv
    from syzkaller_tpu.prog.generation import generate

    env = MockEnv(target, pid=7)
    p = generate(target, 1, 3)
    faults.install(FaultPlan().fail_at("env.exec:7", 1))
    _, infos, failed, _ = env.exec(ExecOpts(), p)
    assert failed and not infos
    _, infos, failed, _ = env.exec(ExecOpts(), p)  # next exec recovers
    assert not failed and infos


# --------------------------------------------------------------------- #
# checkpoint format


def test_checkpoint_roundtrip_bit_identical(tmp_path):
    np = pytest.importorskip("numpy")
    path = str(tmp_path / "a.ckpt")
    arr = np.arange(4096, dtype=np.uint32) * np.uint32(2654435761)
    state = {"bits": arr, "n": 7, "s": "x", "nested": {"k": [1, 2, 3]}}
    n = ckpt.write_checkpoint(path, state)
    assert n > 0 and not os.path.exists(path + ".tmp")
    got = ckpt.read_checkpoint(path)
    assert got["n"] == 7 and got["nested"] == {"k": [1, 2, 3]}
    assert got["bits"].dtype == arr.dtype
    assert np.array_equal(got["bits"], arr)


def test_checkpoint_rejects_corruption(tmp_path):
    path = str(tmp_path / "a.ckpt")
    ckpt.write_checkpoint(path, {"v": list(range(100))})
    blob = bytearray(open(path, "rb").read())
    for mutate, name in (
            (lambda b: b[:10], "truncated header"),
            (lambda b: b"NOTMAGIC!!" + bytes(b[10:]), "bad magic"),
            (lambda b: bytes(b[:-5]), "truncated payload"),
            (lambda b: bytes(b[:40]) + bytes([b[40] ^ 0xFF])
             + bytes(b[41:]), "flipped byte"),
    ):
        bad = str(tmp_path / "bad.ckpt")
        open(bad, "wb").write(bytes(mutate(blob)))
        with pytest.raises(ckpt.CheckpointError):
            ckpt.read_checkpoint(bad)
    with pytest.raises(ckpt.CheckpointError):
        ckpt.read_checkpoint(str(tmp_path / "missing.ckpt"))


def test_checkpoint_rejects_wrong_version(tmp_path):
    path = str(tmp_path / "a.ckpt")
    ckpt.write_checkpoint(path, {})
    blob = bytearray(open(path, "rb").read())
    blob[len(ckpt.MAGIC)] = 99  # version field LSB
    open(path, "wb").write(bytes(blob))
    with pytest.raises(ckpt.CheckpointError, match="version"):
        ckpt.read_checkpoint(path)


# --------------------------------------------------------------------- #
# supervisor state machine


def test_supervisor_backoff_and_quarantine():
    clock = [0.0]
    sup = EnvSupervisor(2, quarantine_threshold=3, base_backoff=0.1,
                        max_backoff=1.0, probe_interval=5.0, seed=1,
                        time_fn=lambda: clock[0])
    assert sup.acquire(0)
    sup.record_failure(0)
    b1 = sup.last_backoff(0)
    assert 0.05 <= b1 <= 0.15  # jittered [0.5, 1.5) x base
    assert not sup.acquire(0)  # inside the backoff window
    clock[0] += b1 + 0.001
    assert sup.acquire(0)      # window elapsed
    sup.record_failure(0)
    assert sup.last_backoff(0) >= b1 * 0.5 * 2 * 0.5  # exponential-ish
    sup.record_failure(0)      # third consecutive -> quarantine
    assert sup.is_quarantined(0)
    assert sup.quarantined_count() == 1
    assert get_registry().get("env_quarantined").value == 1
    assert sup.usable_elsewhere(0)       # env 1 survives
    assert not sup.usable_elsewhere(1)   # env 0 is out
    # quarantined: no rows except one probe per probe_interval
    clock[0] += 100.0
    assert sup.acquire(0)      # the probe
    assert not sup.acquire(0)  # not a second one
    sup.record_success(0)      # probe succeeded -> back in service
    assert not sup.is_quarantined(0)
    assert sup.failures(0) == 0
    assert get_registry().get("env_quarantined").value == 0
    sup.close()


def test_supervisor_backoff_is_capped():
    clock = [0.0]
    sup = EnvSupervisor(1, base_backoff=0.1, max_backoff=0.5,
                        quarantine_threshold=100, seed=3,
                        time_fn=lambda: clock[0])
    for _ in range(12):
        sup.record_failure(0)
    assert sup.last_backoff(0) <= 0.5 * 1.5
    sup.close()


@pytest.mark.chaos
def test_watchdog_interrupts_wedged_env():
    class _Wedged:
        def __init__(self):
            self._evt = threading.Event()
            self.interrupted = False

        def interrupt(self):
            self.interrupted = True
            self._evt.set()

    before = _counter("env_watchdog_trips_total")
    sup = EnvSupervisor(1, watchdog_seconds=0.05)
    env = _Wedged()
    t0 = time.monotonic()
    with sup.guard(0, env):
        assert env._evt.wait(3.0), "watchdog never fired"
    assert env.interrupted
    assert time.monotonic() - t0 < 1.0
    assert _counter("env_watchdog_trips_total") == before + 1
    sup.close()


def test_watchdog_disabled_guard_is_noop():
    sup = EnvSupervisor(1, watchdog_seconds=0.0)
    with sup.guard(0, object()):
        pass
    assert sup._monitor is None  # no thread was ever started
    sup.close()


# --------------------------------------------------------------------- #
# supervised drain fan-out


class _FakeBatch:
    """Minimal _DeviceBatch stand-in with per-row-identifiable streams."""

    def __init__(self, n):
        self.streams = [bytes([i]) for i in range(n)]
        self.dropped = 0

    def __len__(self):
        return len(self.streams)

    def op_mask(self, row):
        return 1

    def src_row(self, row):
        return -1  # no arena sampling provenance

    def src_age(self, row):
        return -1

    def call_ids(self, row):
        return [0, 1]

    def decode(self, row):
        return None


class _ChaosEnv:
    """Fake executor env that consults the fault plan like ipc does and
    records which rows it executed (stream byte 0 = row id)."""

    def __init__(self, pid, delay=0.002):
        self.pid = pid
        self.delay = delay
        self.rows = []
        self.fails = 0

    def exec_raw(self, opts, data, call_ids):
        time.sleep(self.delay)  # force genuine worker overlap
        if faults.should_fire(f"env.exec:{self.pid}"):
            self.fails += 1
            return b"", [], True, False
        self.rows.append(data[0])
        return b"", [], False, False

    def close(self):
        pass


@pytest.mark.chaos
def test_drain_reshards_rows_exactly_once_after_env_kills(target):
    """Kill 2 of 4 envs mid-batch: the supervised drain re-shards their
    failed rows across the survivors and every row still executes
    exactly once (the ISSUE 4 acceptance invariant)."""
    plan = (FaultPlan(seed=1)
            .fail_at("env.exec:1", 2, 3)
            .fail_at("env.exec:2", 1, 2))
    faults.install(plan)
    before_restarts = _counter("env_restarts_total")
    with mk(target, procs=4, env_base_backoff=0.005,
            env_max_backoff=0.02, env_quarantine_threshold=2,
            env_probe_interval=0.02) as f:
        f.envs = [_ChaosEnv(i) for i in range(4)]
        before_fuzz = f.stats["exec_fuzz"]
        f._run_device_batch_inner(_FakeBatch(40))
        rows = sorted(r for e in f.envs for r in e.rows)
        assert rows == list(range(40)), "rows lost or duplicated"
        fails = sum(e.fails for e in f.envs)
        assert fails >= 1, "fault plan never fired"
        # only a row's FIRST failure charges the env (repeat failures
        # indict the program, not the env)
        restarts = _counter("env_restarts_total") - before_restarts
        assert 1 <= restarts <= fails
        # every attempt that reached the executor is accounted
        assert f.stats["exec_fuzz"] == before_fuzz + 40 + fails


@pytest.mark.chaos
def test_drain_survives_full_fleet_quarantine(target):
    """Every env fails until quarantined, then recovers: un-quarantine
    probes must still drain the batch (the last worker never leaves)."""
    # both envs fail their first 2 execs -> both quarantine (threshold 2)
    plan = (FaultPlan(seed=2)
            .fail_at("env.exec:0", 1, 2)
            .fail_at("env.exec:1", 1, 2))
    faults.install(plan)
    with mk(target, procs=2, env_base_backoff=0.002,
            env_max_backoff=0.01, env_quarantine_threshold=2,
            env_probe_interval=0.01, drain_max_attempts=10) as f:
        f.envs = [_ChaosEnv(i, delay=0.001) for i in range(2)]
        f._run_device_batch_inner(_FakeBatch(6))
        rows = sorted(r for e in f.envs for r in e.rows)
        assert rows == list(range(6))
        # probes brought at least one env back
        assert f.supervisor.quarantined_count() < 2


def test_drain_drops_row_after_max_attempts(target):
    """A row that fails on every env is dropped (counted), not retried
    forever."""
    plan = FaultPlan().rate("env.exec:0", 1.0).rate("env.exec:1", 1.0)
    faults.install(plan)
    before = _counter("drain_rows_dropped_total")
    with mk(target, procs=2, env_base_backoff=0.001,
            env_max_backoff=0.005, env_quarantine_threshold=100,
            env_probe_interval=0.005, drain_max_attempts=2) as f:
        f.envs = [_ChaosEnv(i, delay=0.0) for i in range(2)]
        f._run_device_batch_inner(_FakeBatch(3))
        assert all(not e.rows for e in f.envs)
    assert _counter("drain_rows_dropped_total") == before + 3


# --------------------------------------------------------------------- #
# RPC supervision


def test_poll_manager_survives_injected_rpc_failure(target):
    faults.install(FaultPlan().fail_at("rpc.poll", 1))
    before = _counter("errors_rpc_poll_total")
    with mk(target) as f:
        f.loop(iterations=20)
        assert f.new_signal, "test needs un-synced signal"
        kept = set(f.new_signal)
        f.poll_manager()  # injected failure: logged + counted, not fatal
        assert _counter("errors_rpc_poll_total") == before + 1
        assert f.new_signal == kept, "new_signal lost on a failed sync"
        f.poll_manager()  # next poll succeeds and clears
        assert not f.new_signal


def test_new_input_reports_retained_while_manager_down(target):
    """A manager outage during triage must not kill the campaign; the
    missed new_input reports are retained and re-sent once a poll
    succeeds."""
    from syzkaller_tpu.engine.fuzzer import ManagerConn

    class FlakyMgr(ManagerConn):
        def __init__(self):
            self.inputs = []
            self.down = True

        def new_input(self, text, ci, sig, cover):
            if self.down:
                raise OSError("manager down")
            self.inputs.append(text)

    mgr = FlakyMgr()
    f = Fuzzer(target, FuzzerConfig(mock=True, use_device=False,
                                    smash_mutations=1), manager=mgr)
    with f:
        f.loop(iterations=30)  # triage lands inputs, reports all fail
        assert f.corpus, "test needs corpus additions"
        assert not mgr.inputs
        assert f._pending_new_inputs
        mgr.down = False
        f.poll_manager()  # manager back: backlog drains
        assert len(mgr.inputs) == len(f.corpus)
        assert not f._pending_new_inputs


def test_drain_program_failure_does_not_charge_env(target):
    """STATUS_FAILED from a LIVE executor (call records present) is a
    program property: the row is consumed without charging the env or
    re-sharding."""
    from syzkaller_tpu.ipc import CallInfo

    class _FailingProgEnv:
        def __init__(self, pid):
            self.pid = pid
            self.execs = 0

        def exec_raw(self, opts, data, call_ids):
            self.execs += 1
            infos = [CallInfo(index=0, num=0, errno=1, executed=True,
                              fault_injected=False)]
            return b"", infos, True, False  # failed, but env replied

        def close(self):
            pass

    before = _counter("env_restarts_total")
    with mk(target, procs=2) as f:
        f.envs = [_FailingProgEnv(i) for i in range(2)]
        f._run_device_batch_inner(_FakeBatch(6))
        assert sum(e.execs for e in f.envs) == 6  # no re-shard retries
        assert f.supervisor.failures(0) == 0
        assert f.supervisor.failures(1) == 0
    assert _counter("env_restarts_total") == before


@pytest.mark.chaos
def test_remote_manager_reconnects_after_manager_restart():
    """Transport failure -> jittered retry, fresh socket, and a replayed
    connect (the restarted manager lost our registration)."""
    from syzkaller_tpu.manager.rpc import RemoteManager, RpcServer

    class H:
        def __init__(self):
            self.connects = []
            self.polls = 0

        def connect(self, name):
            self.connects.append(name)
            return {"ok": 1}

        def poll(self, name, stats, need_candidates, new_signal=()):
            self.polls += 1
            return {"new_inputs": []}

    h1 = H()
    s1 = RpcServer(h1, port=0)
    s1.start()
    _, port = s1.addr.rsplit(":", 1)
    rm = RemoteManager(s1.addr, name="f0", base_backoff=0.01,
                       max_backoff=0.05)
    rm.connect()
    assert h1.connects == ["f0"]
    before_rc = _counter("rpc_reconnects_total")
    s1.stop()
    h2 = H()
    s2 = RpcServer(h2, port=int(port))
    s2.start()
    try:
        rm.client._sock.close()  # the restart killed the old connection
        assert rm.poll({}, need_candidates=False) == {"new_inputs": []}
        assert h2.connects == ["f0"], "restart-aware re-register missing"
        assert h2.polls == 1
        assert _counter("rpc_reconnects_total") == before_rc + 1
    finally:
        rm.close()
        s2.stop()


def test_remote_manager_injected_fault_is_retried():
    from syzkaller_tpu.manager.rpc import RemoteManager, RpcServer

    class H:
        def poll(self, name, stats, need_candidates, new_signal=()):
            return {"new_inputs": []}

        def connect(self, name):
            return {}

    s = RpcServer(H(), port=0)
    s.start()
    rm = RemoteManager(s.addr, name="x", base_backoff=0.005,
                       max_backoff=0.01)
    faults.install(FaultPlan().fail_at("rpc.transport.poll", 1))
    before = _counter("rpc_retries_total")
    try:
        assert rm.poll({}, need_candidates=False) == {"new_inputs": []}
        assert _counter("rpc_retries_total") == before + 1
    finally:
        rm.close()
        s.stop()


# --------------------------------------------------------------------- #
# ipc close escalation


class _WedgedProc:
    """Popen stand-in that ignores the graceful quit until killed."""

    def __init__(self):
        self.killed = False
        self.stdin = io.BytesIO()
        self.stdout = None
        self.waits = []

    def poll(self):
        return -9 if self.killed else None

    def wait(self, timeout=None):
        self.waits.append(timeout)
        if not self.killed:
            raise subprocess.TimeoutExpired("executor", timeout)
        return -9

    def kill(self):
        self.killed = True


class _Closeable:
    def close(self):
        pass


def test_env_close_escalates_to_kill(tmp_path):
    from syzkaller_tpu.ipc import Env

    env = Env.__new__(Env)  # no toolchain in CI: skip __init__/build
    env._proc = proc = _WedgedProc()
    env._in_mm = env._out_mm = env._in_f = env._out_f = _Closeable()
    env.workdir = str(tmp_path / "envdir")
    os.makedirs(env.workdir)
    before = _counter("env_kill_escalations_total")
    env.close()
    assert proc.killed, "wedged executor was not SIGKILLed"
    assert len(proc.waits) >= 2, "no re-wait after kill: zombie leaks"
    assert _counter("env_kill_escalations_total") == before + 1
    assert env._proc is None


# --------------------------------------------------------------------- #
# engine checkpoint/resume


def test_fuzzer_checkpoint_roundtrip_host_only(tmp_path, target):
    from syzkaller_tpu.prog.encoding import serialize

    cfg = dict(workdir=str(tmp_path), checkpoint_interval=0)
    with mk(target, **cfg) as f:
        f.loop(iterations=40)
        f.save_checkpoint()
        want_stats = dict(f.stats)
        want_corpus = sorted(serialize(p) for p in f.corpus)
        want_sig = set(f.max_signal)
        want_depths = f.queue.depths()
        want_draw = f.rng.rng.random()
    with mk(target, resume=True, **cfg) as g:
        assert dict(g.stats) == want_stats
        assert sorted(serialize(p) for p in g.corpus) == want_corpus
        assert g.max_signal == want_sig
        assert g.queue.depths() == want_depths
        # the RNG stream continues exactly where the dead engine stopped
        assert g.rng.rng.random() == want_draw
        g.loop(iterations=10)  # and the engine still fuzzes


def test_fuzzer_checkpoint_age_and_metrics(tmp_path, target):
    before_w = _counter("checkpoint_writes_total")
    with mk(target, workdir=str(tmp_path), checkpoint_interval=0) as f:
        f.loop(iterations=5)
        f.save_checkpoint()
        age = get_registry().get("checkpoint_age_seconds").value
        assert 0 <= age < 60
        assert _counter("checkpoint_writes_total") == before_w + 1
        assert get_registry().get("checkpoint_write_seconds").count >= 1


def test_fuzzer_rejects_corrupt_checkpoint_and_starts_fresh(
        tmp_path, target):
    cfg = dict(workdir=str(tmp_path), checkpoint_interval=0)
    with mk(target, **cfg) as f:
        f.loop(iterations=30)
        f.save_checkpoint()
        assert f.corpus
    path = str(tmp_path / "engine.ckpt")
    blob = bytearray(open(path, "rb").read())
    blob[len(blob) // 2] ^= 0xFF  # one flipped payload byte
    open(path, "wb").write(bytes(blob))
    before = _counter("checkpoint_rejected_total")
    with mk(target, resume=True, **cfg) as g:
        # clean rejection: fresh state, no crash, campaign runs
        assert _counter("checkpoint_rejected_total") == before + 1
        assert not g.corpus
        g.loop(iterations=20)
        assert g.stats["exec_total"] >= 20


@pytest.mark.chaos
def test_chaos_campaign_survives_and_resumes_bit_identical(
        tmp_path, target):
    """The ISSUE 4 acceptance scenario: a seeded FaultPlan kills 2 of 4
    envs mid-campaign, fails one RPC sync, and poisons one device step —
    the campaign completes, then a kill + --resume run restores a
    bit-identical max-signal bitset and arena occupancy."""
    pytest.importorskip("jax")
    np = pytest.importorskip("numpy")

    plan = (FaultPlan(seed=7)
            .fail_at("env.exec:1", 3)
            .fail_at("env.exec:2", 4)
            .fail_at("rpc.poll", 1)
            .fail_at("device.step", 2))
    faults.install(plan)
    rpc_before = _counter("errors_rpc_poll_total")
    cfg = dict(mock=True, use_device=True, procs=4, device_batch=16,
               device_period=4, smash_mutations=1, program_length=8,
               workdir=str(tmp_path), checkpoint_interval=0,
               env_base_backoff=0.005, env_max_backoff=0.02,
               env_probe_interval=0.02)
    with Fuzzer(target, FuzzerConfig(**cfg), seed=3) as f:
        for _ in range(400):
            f.step()
            if f.stats.get("device_candidates", 0) >= 16:
                break
        assert f.stats["device_candidates"] >= 16
        f.poll_manager()  # the injected sync failure
        assert _counter("errors_rpc_poll_total") == rpc_before + 1
        f.poll_manager()  # and the campaign syncs fine afterwards
        # the poisoned device step was retried, not fatal
        assert ("device.step", 2) in plan.fired()
        assert not f._device.degraded
        # exec ledger stayed exactly consistent through the chaos
        parts = ("exec_gen", "exec_fuzz", "exec_candidate", "exec_triage",
                 "exec_minimize", "exec_smash", "exec_hints")
        assert f.stats["exec_total"] == sum(f.stats[k] for k in parts)
        f.save_checkpoint()
        want_bits = f._max_bits.copy()
        want_sig = np.asarray(f._device._sig_shard).copy()
        want_bloom = np.asarray(f._device._bloom).copy()
        want_yields = f._device.arena.yields.copy()
        want_arena = [np.asarray(x).copy()
                      for x in f._device.arena.tensors()]
        want_occ = (f._device.arena.size, f._device.arena.cursor)
    faults.clear()

    # the "kill": the process state is gone; --resume restores it
    with Fuzzer(target, FuzzerConfig(**{**cfg, "resume": True}),
                seed=999) as g:
        assert np.array_equal(g._max_bits, want_bits)
        assert np.array_equal(np.asarray(g._device._sig_shard), want_sig)
        # the admission Bloom filter and the arena yield scores restore
        # bit-identically (ISSUE 5 acceptance)
        assert np.array_equal(np.asarray(g._device._bloom), want_bloom)
        assert np.array_equal(g._device.arena.yields, want_yields)
        got_arena = [np.asarray(x) for x in g._device.arena.tensors()]
        for a, b in zip(got_arena, want_arena):
            assert np.array_equal(a, b)
        assert (g._device.arena.size, g._device.arena.cursor) == want_occ
        g.loop(iterations=10)  # resumed campaign keeps fuzzing


def test_checkpoint_restores_inflight_device_batch(tmp_path, target):
    """The double-buffered pipeline always has one launched-but-not-yet-
    consumed batch in flight; the checkpoint must carry it so resume
    continues with the EXACT staged candidates instead of re-mutating a
    batch of work (closes the PR 4 ROADMAP open item)."""
    pytest.importorskip("jax")
    np = pytest.importorskip("numpy")

    cfg = dict(mock=True, use_device=True, device_batch=8,
               device_period=2, smash_mutations=1, program_length=8,
               workdir=str(tmp_path), checkpoint_interval=0)
    with mk(target, **cfg) as f:
        for _ in range(300):
            f.step()
            if f._device is not None and f._device._pending is not None:
                break
        assert f._device._pending is not None, "no batch ever in flight"
        f.save_checkpoint()
        want = [np.asarray(x).copy() for x in f._device._pending]
    with mk(target, resume=True, **cfg) as g:
        assert g._device._pending is not None, \
            "in-flight batch discarded on resume"
        got = [np.asarray(x) for x in g._device._pending]
        assert len(got) == len(want)
        for a, b in zip(got, want):
            assert np.array_equal(a, b), "staged batch diverged on resume"
        # and the resumed pipeline consumes it as a normal batch
        before = g.stats["device_batches"]
        for _ in range(400):
            g.step()
            if g.stats["device_batches"] > before or \
                    g.stats.get("device_dropped_stale", 0) > 0 or \
                    g.stats.get("device_deduped", 0) > 0:
                break
        assert (g.stats["device_batches"] > before
                or g.stats.get("device_dropped_stale", 0) > 0
                or g.stats.get("device_deduped", 0) > 0), \
            "restored in-flight batch was never consumed"


@pytest.mark.chaos
def test_checkpoint_restores_depth3_inflight_ring(tmp_path, target):
    """ALL k in-flight batches ride the checkpoint: with
    pipeline_depth=3 a kill + resume restores EVERY staged slot
    bit-identically and in launch order — exact staged-candidate
    continuity, not just the oldest slot the old double buffer
    carried."""
    pytest.importorskip("jax")
    np = pytest.importorskip("numpy")

    cfg = dict(mock=True, use_device=True, device_batch=8,
               device_period=2, pipeline_depth=3, smash_mutations=1,
               program_length=8, workdir=str(tmp_path),
               checkpoint_interval=0)
    with mk(target, **cfg) as f:
        for _ in range(600):
            f.step()
            if f._device is not None and len(f._device._inflight) >= 3:
                break
        assert len(f._device._inflight) == 3, "ring never filled to depth"
        g_inflight = get_registry().get("device_pipeline_inflight")
        assert g_inflight is not None and g_inflight.value == 3
        f.save_checkpoint()
        want = [[np.asarray(x).copy() for x in slot.outs]
                for slot in f._device._inflight]
        want_ages = [slot.ages.copy() for slot in f._device._inflight]
    with mk(target, resume=True, **cfg) as g:
        slots = list(g._device._inflight)
        assert len(slots) == 3, "in-flight ring lost slots on resume"
        for i, slot in enumerate(slots):
            for a, b in zip(slot.outs, want[i]):
                assert np.array_equal(np.asarray(a), b), \
                    f"slot {i} staged batch diverged on resume"
            assert np.array_equal(slot.ages, want_ages[i]), \
                f"slot {i} age-stamp snapshot diverged on resume"
        # the resumed pipeline drains the restored slots as its next
        # batches (host arrays always test ready: oldest-first order)
        before = g.stats["device_batches"]
        for _ in range(400):
            g.step()
            if g.stats["device_batches"] > before or \
                    g.stats.get("device_dropped_stale", 0) > 0 or \
                    g.stats.get("device_deduped", 0) > 0:
                break
        assert (g.stats["device_batches"] > before
                or g.stats.get("device_dropped_stale", 0) > 0
                or g.stats.get("device_deduped", 0) > 0), \
            "restored in-flight slots were never consumed"


def test_restore_accepts_legacy_single_pending_checkpoint(target):
    """Pre-pipeline checkpoints staged at most ONE batch under
    "pending"/"pending_ages"; the depth-k ring restore must accept them
    as a one-slot ring."""
    pytest.importorskip("jax")
    np = pytest.importorskip("numpy")

    cfg = FuzzerConfig(mock=True, use_device=True, device_batch=8,
                       program_length=8, smash_mutations=1,
                       device_period=2)
    with Fuzzer(target, cfg) as f:
        for _ in range(400):
            f.step()
            if f._device._pending is not None:
                break
        assert f._device._pending is not None
        st = f._device.checkpoint_state()
        slot = st.pop("inflight")[0]
        st["pending"] = slot["outs"]
        st["pending_ages"] = slot["ages"]
        f._device.restore_state(st)
        assert len(f._device._inflight) == 1
        for a, b in zip(f._device._inflight[0].outs, slot["outs"]):
            assert np.array_equal(np.asarray(a), b)
        assert np.array_equal(f._device._inflight[0].ages, slot["ages"])


@pytest.mark.chaos
@pytest.mark.slow
def test_soak_kill_resume_cycles_under_random_faults(tmp_path, target):
    """Long-soak variant (excluded from tier-1): repeated kill/resume
    cycles under a random-rate FaultPlan — signal state must be
    monotone across every restart, the in-flight device batch must
    survive each kill bit-identically (batch continuity: resume never
    re-mutates staged work), and the engine must never crash."""
    pytest.importorskip("jax")
    np = pytest.importorskip("numpy")

    cfg = dict(mock=True, use_device=True, procs=3, device_batch=8,
               device_period=4, smash_mutations=1, program_length=8,
               workdir=str(tmp_path), checkpoint_interval=0,
               env_base_backoff=0.002, env_max_backoff=0.01,
               env_probe_interval=0.01)
    prev_bits = None
    prev_pending = None
    pending_checked = 0
    for cycle in range(5):
        faults.install(FaultPlan(seed=cycle, rates={
            "env.exec:0": 0.02, "env.exec:1": 0.02, "env.exec:2": 0.02,
            "rpc.poll": 0.2, "device.step": 0.01}))
        with Fuzzer(target, FuzzerConfig(
                **{**cfg, "resume": cycle > 0}), seed=cycle) as f:
            if prev_bits is not None:
                assert np.array_equal(f._max_bits, prev_bits), \
                    f"cycle {cycle}: resumed bitset diverged"
            if prev_pending is not None and f._device is not None:
                assert f._device._pending is not None, \
                    f"cycle {cycle}: in-flight batch lost on resume"
                for a, b in zip(f._device._pending, prev_pending):
                    assert np.array_equal(np.asarray(a), b), \
                        f"cycle {cycle}: in-flight batch re-mutated"
                pending_checked += 1
            f.loop(iterations=120)
            f.poll_manager()
            f.save_checkpoint()
            prev_bits = f._max_bits.copy()
            prev_pending = None
            if f._device is not None and not f._device.degraded and \
                    f._device._pending is not None:
                prev_pending = [np.asarray(x).copy()
                                for x in f._device._pending]
            popcount = int(sum(int(x).bit_count() for x in prev_bits))
        faults.clear()
    assert popcount > 0, "soak never accumulated signal"
    assert pending_checked > 0, \
        "soak never exercised in-flight batch continuity"


# --------------------------------------------------------------------- #
# device degradation ladder


@pytest.mark.chaos
def test_device_step_poison_is_retried(target):
    pytest.importorskip("jax")
    faults.install(FaultPlan().fail_at("device.step", 1))
    before = _counter("device_step_retries_total")
    cfg = FuzzerConfig(mock=True, use_device=True, device_batch=8,
                       program_length=8, smash_mutations=1,
                       device_period=4)
    with Fuzzer(target, cfg) as f:
        for _ in range(400):
            f.step()
            if f.stats.get("device_candidates", 0) >= 8:
                break
        assert f.stats["device_candidates"] >= 8
        assert not f._device.degraded
    assert _counter("device_step_retries_total") == before + 1


@pytest.mark.chaos
def test_depth2_step_poison_preserves_staged_slots(target):
    """Regression for the depth>1 healing bug: a poisoned launch while
    other batches are already staged must be retried by the per-slot
    ladder WITHOUT losing the earlier in-flight slots or degrading the
    pipeline.  Occurrence 3 of device.step is a refill launch — by then
    at least one healthy batch is staged in the ring."""
    pytest.importorskip("jax")
    plan = FaultPlan().fail_at("device.step", 3)
    faults.install(plan)
    before = _counter("device_step_retries_total")
    cfg = FuzzerConfig(mock=True, use_device=True, device_batch=8,
                       program_length=8, smash_mutations=1,
                       device_period=4, pipeline_depth=2)
    with Fuzzer(target, cfg) as f:
        for _ in range(400):
            f.step()
            if f.stats.get("device_candidates", 0) >= 8:
                break
        assert ("device.step", 3) in plan.fired(), "poison never fired"
        assert f.stats["device_candidates"] >= 8, \
            "staged batches lost after mid-flight poison"
        assert not f._device.degraded
        assert len(f._device._inflight) > 0
    assert _counter("device_step_retries_total") == before + 1


@pytest.mark.chaos
def test_heal_inflight_drops_only_poisoned_slots(target):
    """A mid-flight device failure can kill buffers belonging to ANY
    staged slot, not just the newest launch's: _heal_inflight must walk
    every slot, drop the ones whose outputs died (their drain would
    raise), and keep the healthy ones — then the campaign continues."""
    jax = pytest.importorskip("jax")
    cfg = FuzzerConfig(mock=True, use_device=True, device_batch=8,
                       program_length=8, smash_mutations=1,
                       device_period=2, pipeline_depth=3)
    with Fuzzer(target, cfg) as f:
        for _ in range(600):
            f.step()
            if len(f._device._inflight) >= 3:
                break
        dev = f._device
        assert len(dev._inflight) == 3
        victim = dev._inflight[1]
        for x in victim.outs:
            if isinstance(x, jax.Array):
                x.delete()
        survivors = [dev._inflight[0], dev._inflight[2]]
        dev._heal_inflight()
        assert victim not in dev._inflight, "poisoned slot kept"
        assert list(dev._inflight) == survivors, "healthy slot dropped"
        # consume + refill still work: the campaign continues
        before = f.stats["device_batches"]
        for _ in range(400):
            f.step()
            if f.stats["device_batches"] > before or \
                    f.stats.get("device_dropped_stale", 0) > 0 or \
                    f.stats.get("device_deduped", 0) > 0:
                break
        assert not dev.degraded


@pytest.mark.chaos
def test_device_ladder_degrades_to_host_path(target):
    pytest.importorskip("jax")
    cfg = FuzzerConfig(mock=True, use_device=True, device_batch=8,
                       program_length=8, smash_mutations=1,
                       device_period=4)
    before = _counter("device_degraded_total")
    with Fuzzer(target, cfg) as f:
        f.loop(iterations=10)  # warm up, grow a corpus
        faults.install(FaultPlan(rates={"device.step": 1.0}))
        for _ in range(60):
            f.step()
            if f._device.degraded:
                break
        assert f._device.degraded, "ladder never exhausted"
        assert _counter("device_degraded_total") == before + 1
        assert _counter("device_step_recompiles_total") >= 1
        faults.clear()
        # host mutation path carries the campaign on
        before_exec = f.stats["exec_total"]
        f.loop(iterations=30)
        assert f.stats["exec_total"] >= before_exec + 30


# --------------------------------------------------------------------- #
# namespace / satellite wiring


def test_required_metrics_cover_supervision():
    from syzkaller_tpu.tools.check_metrics import REQUIRED_METRICS, check

    for name in ("env_restarts_total", "env_quarantined",
                 "env_watchdog_trips_total", "env_kill_escalations_total",
                 "checkpoint_write_seconds", "checkpoint_age_seconds",
                 "rpc_errors_total", "rpc_retries_total",
                 "device_degraded_total", "errors_total"):
        assert name in REQUIRED_METRICS
    assert check() == []  # every required name has a live registration


def test_count_error_counts_and_splits_by_site():
    from syzkaller_tpu.telemetry import count_error

    before_total = _counter("errors_total")
    before_site = _counter("errors_test_site_total")
    count_error("test_site", ValueError("boom"))
    assert _counter("errors_total") == before_total + 1
    assert _counter("errors_test_site_total") == before_site + 1
