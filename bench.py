"""Headline benchmark: batched device mutation throughput (programs/sec).

Mirrors BASELINE.json config[0] (`tools/syz-mutate` in a loop = raw
single-proc mutation throughput; reference tool at
/root/reference/tools/syz-mutate/mutate.go).  The CPU baseline is measured
in-process: the host-side tree mutator (syzkaller_tpu/prog/mutation.py, the
reimplementation of prog/mutation.go semantics) run single-threaded on this
machine — the Go reference cannot be built here (no Go toolchain in the
image), so `vs_baseline` is device-vs-host-CPU on identical program
distributions.

The whole timed region is ONE dispatch: `iters` mutation rounds run inside
a single jitted lax.scan (stratified op assignment), so per-call dispatch latency (0.4s round-trip on
the axon TPU tunnel) and compile time are excluded from the steady-state
number, the same way the reference's bench loop excludes process startup.

Prints ONE json line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import sys
import time


def bench_device(dt, B=16384, C=16, iters=20):
    # B=16k measured best on v5e-1 (+15% over 4k; 32k exceeds HBM with
    # the per-lane byte arenas)
    import jax

    from syzkaller_tpu.ops import mutation as dmut

    key = jax.random.PRNGKey(0)

    @jax.jit
    def chain(key, cid, sval, data):
        def one(carry, _):
            key, cid, sval, data = carry
            key, k = jax.random.split(key)
            cid, sval, data = dmut.mutate_rows_stratified(k, dt, cid, sval, data, 2)
            return (key, cid, sval, data), None

        (key, cid, sval, data), _ = jax.lax.scan(
            one, (key, cid, sval, data), None, length=iters)
        return cid, sval, data

    import numpy as np

    def sync(arrs):
        # block_until_ready on the axon tunnel intermittently returns
        # before the computation lands (experimental plugin); a tiny
        # device->host transfer is an unconditional barrier
        jax.block_until_ready(arrs)
        np.asarray(arrs[0][:1])

    cid, sval, data = dmut.generate_batch(key, dt, B=B, C=C)
    sync((cid,))
    # warmup dispatch compiles the chain
    out = chain(key, cid, sval, data)
    sync(out)

    # best-of-3: the axon tunnel adds occasional multi-second stalls that
    # would otherwise make single-shot numbers flap by ~10x
    best = 0.0
    for rep in range(3):
        t0 = time.perf_counter()
        out = chain(jax.random.fold_in(key, rep + 1), *out)
        sync(out)
        dt_s = time.perf_counter() - t0
        best = max(best, B * iters / dt_s)
    return best


def bench_host_cpu(target, n=300, ncalls=16):
    """Single-proc host-CPU mutation baseline (syz-mutate-in-a-loop)."""
    from syzkaller_tpu.prog.generation import RandGen, generate
    from syzkaller_tpu.prog.mutation import mutate

    rng = RandGen(target, seed=0)
    progs = [generate(target, i, ncalls) for i in range(32)]
    t0 = time.perf_counter()
    for i in range(n):
        p = progs[i % len(progs)].clone()
        mutate(p, rng, ncalls, corpus=progs)
    return n / (time.perf_counter() - t0)


def main():
    from syzkaller_tpu.descriptions.tables import get_tables
    from syzkaller_tpu.ops.dtables import build_device_tables
    from syzkaller_tpu.prog import get_target
    from syzkaller_tpu.prog.tensor import TensorFormat

    target = get_target("linux", "amd64")
    tables = get_tables(target)
    fmt = TensorFormat.for_tables(tables, max_calls=16)
    dt = build_device_tables(tables, fmt)

    dev = bench_device(dt, C=fmt.max_calls)
    host = bench_host_cpu(target)

    print(json.dumps({
        "metric": "mutation_throughput",
        "value": round(dev, 1),
        "unit": "progs/sec",
        "vs_baseline": round(dev / host, 2),
    }))


if __name__ == "__main__":
    sys.exit(main())
