"""Benchmark harness: every BASELINE.md config, one JSON line.

Configs (BASELINE.json "configs"):
  mutate          — batched device mutation vs single-proc host mutation
                    (reference tools/syz-mutate in a loop)
  cover_merge_sweep — new-signal dedup over recorded traces (reference
                    pkg/cover merge) at nbits {2^20, 2^22, 2^26} x
                    traces {1k, 10k, 100k}: the per-trace sequential
                    scan fold ("device", the pre-ISSUE 8 path), the
                    python-set host reference, and the fused
                    merge_and_new entry (ops/cover, ISSUE 8) in every
                    cell — tolerant nulls where the engine predates the
                    fused entry, so the SAME harness runs pre+post
  minimize_bisect — triage minimize round-trip economy: the serial
                    one-exec-per-probe path vs batched-bisection
                    rounds at equal item set, reporting execs and
                    wall-clock per minimized item and whether both
                    modes minimized to identical programs
  e2e_triage      — the full engine loop: device candidate factory ->
                    exec -> signal fold -> triage, vs the host-only loop
                    (reference syz-manager+VMs triage progs/sec).  Uses
                    the real C++ executor when it builds on this machine,
                    the hermetic MockEnv otherwise ("executor" key says
                    which).
  hints_100k      — comparison-hint matching over 100k cmp traces
                    (reference prog/hints.go)
  hub_sync        — corpus delta exchange between managers
                    (reference syz-hub; host-path: the DCN tier)
  arena_sweep     — the e2e loop at arena capacities {256, 1024, 4096}:
                    arena occupancy / evictions vs corpus yield per
                    capacity (the ROADMAP arena_capacity-tuning item)
  prefix_depth_sweep — the e2e device loop over seed-program length
                    (the shared-prefix depth axis) x prefix scheduling
                    {off, on} at EQUAL env count: executed calls per
                    batch/exec, prefix hit rate, and the off->on call
                    reduction (the prefix-memoized execution claim)
  pipeline_depth_sweep — the e2e device loop over pipeline_depth
                    {1, 2, 4} x device_batch {256, 512} at equal env
                    count: execs/sec, stall rate, and the device.step /
                    batch_drain span-overlap ratio per cell (depth 1 =
                    the old lockstep double buffer, the in-harness
                    baseline every deeper cell is judged against)

The e2e-style configs report execs-per-new-input (yield efficiency)
next to execs/sec: admission/scheduling wins show up as fewer wasted
host executions per corpus addition even when the raw exec rate is
unchanged.

Honesty notes, also emitted in the JSON:
  - the "host" baselines are THIS REPO'S single-threaded Python
    reimplementations on one core of this box, NOT the Go reference
    (unbuildable here: no Go toolchain).  A Go mutator is plausibly
    50-500x the Python one, so vs_baseline OVERSTATES the win over real
    syzkaller by that factor; the absolute device numbers are the
    portable result.
  - host rates are the median of 5 runs of >= 2s each (the box is a
    single shared core; earlier min-of-1 runs flapped 30x).
  - the timed device region is whole batched dispatches with a
    device->host transfer as the barrier (block_until_ready on the axon
    tunnel intermittently returns early).

Prints ONE json line: {"metric", "value", "unit", "vs_baseline",
"configs", "baseline_note"}.
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import tempfile
import time


def _ensure_backend() -> str:
    """Probe the jax backend before any heavy work; when the TPU/axon
    runtime fails to initialize (BENCH_r05 tail: ``RuntimeError: Unable to
    initialize backend 'axon'``) fall back to CPU so the round reports a
    JSON line instead of crashing with rc=1.  Returns the platform name,
    or "cpu-fallback" when the fallback kicked in."""
    import jax

    try:
        return jax.devices()[0].platform
    except RuntimeError:
        os.environ["JAX_PLATFORMS"] = "cpu"
        try:
            jax.clear_backends()
        except Exception:
            pass
        jax.config.update("jax_platforms", "cpu")
        jax.devices()  # raises if even CPU is unavailable — that IS fatal
        return "cpu-fallback"


def _sync(arrs):
    import jax
    import numpy as np

    jax.block_until_ready(arrs)
    first = arrs[0] if isinstance(arrs, (tuple, list)) else arrs
    np.asarray(first)[:1]


def _median_rate(fn, reps: int = 5, min_seconds: float = 2.0):
    """Median of `reps` timed runs; fn(seconds) -> units done."""
    rates = []
    for _ in range(reps):
        t0 = time.perf_counter()
        n = fn(min_seconds)
        rates.append(n / (time.perf_counter() - t0))
    return statistics.median(rates)


def span_deltas(reg, before):
    """Per-phase ``span_*_seconds`` histogram deltas (``_sum``/``_count``)
    of registry snapshot ``before`` vs now — the ROADMAP open item: each
    BENCH config carries its compile vs dispatch vs transfer breakdown so
    perf PRs are judged on where the time went, not just headline rates.
    Zero deltas are dropped to keep the one-line JSON one line."""
    return {k: v for k, v in telemetry_delta(reg, before).items()
            if k.startswith("span_")}


def telemetry_delta(reg, before):
    """Whole-run registry snapshot delta for the JSON line's `telemetry`
    key.  Exact-zero entries are dropped; gauges pass through as their
    end-of-run point-in-time values (Registry.delta semantics), so
    run-dependent gauge readings do appear and diff between rounds —
    compare rounds on the counter/histogram ``_sum``/``_count`` keys."""
    return {k: (round(v, 6) if isinstance(v, float) else v)
            for k, v in reg.delta(before).items() if v}


# ------------------------------------------------------------------ #
# config[0]: mutation throughput


def bench_device_mutate(dt, B=16384, C=16, iters=20):
    # B=16k measured best on v5e-1 (+15% over 4k; 32k exceeds HBM with
    # the per-lane byte arenas)
    import jax

    from syzkaller_tpu.ops import mutation as dmut

    key = jax.random.PRNGKey(0)

    @jax.jit
    def chain(key, cid, sval, data):
        def one(carry, _):
            key, cid, sval, data = carry
            key, k = jax.random.split(key)
            cid, sval, data = dmut.mutate_rows_stratified(
                k, dt, cid, sval, data, 2)
            return (key, cid, sval, data), None

        (key, cid, sval, data), _ = jax.lax.scan(
            one, (key, cid, sval, data), None, length=iters)
        return cid, sval, data

    cid, sval, data = dmut.generate_batch(key, dt, B=B, C=C)
    _sync((cid,))
    out = chain(key, cid, sval, data)  # warmup/compile
    _sync(out)
    best = 0.0
    for rep in range(3):
        t0 = time.perf_counter()
        out = chain(jax.random.fold_in(key, rep + 1), *out)
        _sync(out)
        best = max(best, B * iters / (time.perf_counter() - t0))
    return best


def bench_host_mutate(target, ncalls=16):
    from syzkaller_tpu.prog.generation import RandGen, generate
    from syzkaller_tpu.prog.mutation import mutate

    rng = RandGen(target, seed=0)
    progs = [generate(target, i, ncalls) for i in range(32)]

    def run(seconds):
        n = 0
        t_end = time.perf_counter() + seconds
        while time.perf_counter() < t_end:
            p = progs[n % len(progs)].clone()
            mutate(p, rng, ncalls, corpus=progs)
            n += 1
        return n

    return _median_rate(run)


# ------------------------------------------------------------------ #
# config[1]: cover merge sweep (ISSUE 8 — fused merge+new vs the scan)

COVER_SWEEP_NBITS = (1 << 20, 1 << 22, 1 << 26)
COVER_SWEEP_TRACES = (1_000, 10_000, 100_000)


def _gen_traces(n_traces, pcs=64, seed=7):
    """Synthetic KCOV-shaped traces: a shared hot set (kernel entry
    paths) + a novel tail — the same generator every round has used."""
    import numpy as np

    rng = np.random.default_rng(seed)
    hot = rng.integers(0, 1 << 18, size=1 << 12, dtype=np.uint32)
    return np.where(
        rng.random((n_traces, pcs)) < 0.8,
        hot[rng.integers(0, hot.size, size=(n_traces, pcs))],
        rng.integers(0, 1 << 30, size=(n_traces, pcs)).astype(np.uint32))


def bench_cover_merge_sweep():
    """traces/sec of the new-signal dedup at every (nbits, traces)
    design point, three ways per cell:

      device — the sequential per-trace scan fold (signal_new +
               signal_add under lax.scan): the pre-ISSUE 8 device path,
               measured on a capped trace window (the [nwords] carry
               makes 100k-step scans at 2^26 bits minutes-slow; the
               scan is per-trace linear, so the rate transfers —
               ``device_measured_traces`` records the honesty).
      host   — python sets (pkg/cover SignalNew/SignalAdd), the same
               2000-trace estimator every round has used.
      fused  — ops/cover.merge_and_new: ONE batched fold of the whole
               trace set (sequential-prefix popcount-delta verdicts +
               merged accumulator in one pass).  None when the engine
               predates the fused entry (the pre harness), so the SAME
               harness runs both sides.
    """
    import jax
    import jax.numpy as jnp

    from syzkaller_tpu.ops import cover

    merge = getattr(cover, "merge_and_new", None)
    out = {}
    for n_traces in COVER_SWEEP_TRACES:
        traces = _gen_traces(n_traces)

        # host reference, measured once per trace set (independent of
        # nbits — exact sets don't hash into a table)
        def host_run(seconds):
            done = 0
            t_end = time.perf_counter() + seconds
            while time.perf_counter() < t_end:
                max_sig = set()
                for row in traces[:2000]:
                    s = set(row.tolist())
                    if not s <= max_sig:
                        max_sig |= s
                done += 2000
            return done

        host = _median_rate(host_run, reps=3)

        for nbits in COVER_SWEEP_NBITS:
            cell = {}
            # --- device: the old sequential scan fold ---
            cap = min(n_traces, 2_000 if nbits >= 1 << 26 else 10_000)
            reps = 1 if nbits >= 1 << 26 else 3

            @jax.jit
            def fold_all(bits, ts):
                def step(bits, t):
                    fresh = cover.signal_new(bits, t)
                    bits = cover.signal_add(bits, t)
                    return bits, fresh

                bits, fresh = jax.lax.scan(step, bits, ts)
                return bits, jnp.sum(fresh)

            ts = jnp.asarray(traces[:cap])
            _sync(fold_all(cover.make_bitset(nbits), ts))  # warm/compile
            best = 0.0
            for _ in range(reps):
                t0 = time.perf_counter()
                _sync(fold_all(cover.make_bitset(nbits), ts))
                best = max(best, cap / (time.perf_counter() - t0))
            cell["device"] = round(best, 1)
            cell["device_measured_traces"] = cap
            cell["host"] = round(host, 1)
            # --- fused: one merge_and_new pass over the FULL set,
            # called the way the engine calls it (host-resident
            # accumulator + trace batch — the dispatcher picks the
            # best fused implementation for this platform) ---
            if merge is None:
                cell["fused"] = None
            else:
                import numpy as np

                def acc0():
                    return np.zeros(nbits // 32, np.uint32)

                _sync(merge(acc0(), traces))  # warm/compile
                fbest = 0.0
                for _ in range(2):
                    t0 = time.perf_counter()
                    _sync(merge(acc0(), traces))
                    fbest = max(fbest,
                                n_traces / (time.perf_counter() - t0))
                cell["fused"] = round(fbest, 1)
            out[f"nbits{nbits.bit_length() - 1}_t{n_traces // 1000}k"] = \
                cell
    return out


# ------------------------------------------------------------------ #
# config: batched-bisection minimize round-trip economy (ISSUE 8)


def bench_minimize_bisect(target, n_progs=4, length=8):
    """Equal triage workload through the sequential one-exec-per-probe
    path and the batched-bisection round scheduler (MockEnv fleet, 4
    envs): execs and wall-clock per minimized item, the serial
    round-trip count each mode pays, and whether both modes minimized
    to byte-identical programs.  getattr/field-tolerant: a pre engine
    without the ``minimize_bisect`` knob reports a null batched cell so
    the SAME harness runs pre+post."""
    import dataclasses

    from syzkaller_tpu.engine.fuzzer import Fuzzer, FuzzerConfig
    from syzkaller_tpu.prog.encoding import serialize
    from syzkaller_tpu.prog.generation import generate
    from syzkaller_tpu.telemetry import get_registry

    has_knob = "minimize_bisect" in {
        fld.name for fld in dataclasses.fields(FuzzerConfig)}
    reg = get_registry()

    def run(batched):
        kw = {"minimize_bisect": batched} if has_knob else {}
        cfg = FuzzerConfig(mock=True, use_device=False, procs=4,
                           program_length=length, smash_mutations=0,
                           generate_period=1 << 30, **kw)
        with Fuzzer(target, cfg, seed=11) as f:
            for i in range(n_progs):
                f.execute(generate(target, 900 + i, length), "exec_fuzz")
            items = f.queue.depths()["triage"]
            before = reg.snapshot()
            n0 = f.stats["exec_total"]
            t0 = time.perf_counter()
            while any(v for v in f.queue.depths().values()):
                f.step()
            wall = time.perf_counter() - t0
            delta = reg.delta(before)
            probe_execs = f.stats["exec_total"] - n0
            rounds = delta.get("minimize_bisect_rounds_total", 0)
            items = max(items, 1)
            return {
                "items": items,
                "execs": probe_execs,
                "execs_per_item": round(probe_execs / items, 1),
                "wall_s": round(wall, 3),
                "wall_per_item_s": round(wall / items, 4),
                # the serial-round-trip axis: every probe is its own
                # round trip sequentially; a round is one trip batched
                "rounds": rounds or None,
                "serial_roundtrips_per_item": round(
                    (rounds if rounds else probe_execs) / items, 1),
                "new_inputs": f.stats["new_inputs"],
            }, sorted(serialize(p) for p in f.corpus)

    seq, corpus_seq = run(batched=False)
    if has_knob:
        bis, corpus_bis = run(batched=True)
        equal = corpus_seq == corpus_bis
    else:
        bis, equal = None, None
    return {"sequential": seq, "batched": bis,
            "minimized_equal": equal}


# ------------------------------------------------------------------ #
# config[2]: end-to-end triage loop


E2E_DEVICE_PROCS = 4  # executor envs the device-pipeline drain fans over


def _timed_loop(f, seconds: float, reg=None, warmup: int = 30):
    """Run a warmed Fuzzer for `seconds`; returns (execs/sec, execs,
    new_inputs, registry delta of the timed window) so callers can
    report execs-per-new-input and executed-call efficiency next to
    the raw rate.  The delta is {} without a registry."""
    f.loop(iterations=warmup)  # warm up (compiles, first corpus entries)
    before = reg.snapshot() if reg is not None else None
    n0 = f.stats["exec_total"]
    ni0 = f.stats["new_inputs"]
    t0 = time.perf_counter()
    f.loop(duration=seconds)
    dt = time.perf_counter() - t0
    execs = f.stats["exec_total"] - n0
    delta = reg.delta(before) if reg is not None else {}
    return execs / dt, execs, f.stats["new_inputs"] - ni0, delta


def _exec_efficiency(delta, execs, batches=0):
    """Executed-call efficiency of one timed window from a registry
    delta: calls-per-exec (the prefix-memoization win surface) and the
    prefix cache hit rate.  getattr/.get-tolerant by design — engines
    predating calls_executed_total / prefix_* (the PR6-pre harness
    runs) report None here, so the SAME harness runs pre+post."""
    calls = delta.get("calls_executed_total", 0)
    hits = delta.get("prefix_cache_hits_total", 0)
    misses = delta.get("prefix_cache_misses_total", 0)
    out = {
        "calls_executed_per_exec": (round(calls / max(execs, 1), 2)
                                    if calls else None),
        "prefix_hit_rate": (round(hits / max(hits + misses, 1), 3)
                            if (hits or misses) else None),
        "prefix_calls_saved": delta.get("prefix_calls_saved_total", 0),
        # campaign-journal volume of the timed window (0 when the
        # config runs without a workdir/journal): the durability layer's
        # cost must be visible in BENCH deltas, not assumed free
        "journal_records": delta.get("journal_records_total", 0),
    }
    if batches:
        out["calls_per_batch"] = (round(calls / batches, 1)
                                  if calls else None)
    return out


def bench_e2e(target, seconds=18.0):
    from syzkaller_tpu.engine.fuzzer import Fuzzer, FuzzerConfig
    from syzkaller_tpu.telemetry import get_registry

    reg = get_registry()

    def run(use_device: bool, mock: bool):
        # the device pipeline drains batches across an executor fleet
        # (ISSUE 3 fan-out); the host-only loop stays the 1-proc
        # single-threaded reference baseline.  A per-run workdir keeps
        # the campaign journal LIVE so its cost (and record volume)
        # shows in the e2e numbers instead of being benched away
        cfg = FuzzerConfig(
            mock=mock, use_device=use_device, device_batch=256,
            program_length=16, device_period=2, smash_mutations=4,
            procs=E2E_DEVICE_PROCS if use_device else 1,
            workdir=tempfile.mkdtemp(
                prefix=f"syztpu-e2e-{'dev' if use_device else 'host'}-"))
        with Fuzzer(target, cfg) as f:
            rate, execs, ni, delta = _timed_loop(f, seconds, reg)
            eff = _exec_efficiency(delta, execs)
            if use_device:
                # the pipelined-step honesty numbers ride the device
                # cell (null-tolerant on pre engines)
                eff = {**eff, **_pipeline_overlap(delta)}
            return rate, execs, ni, eff

    cwd = os.getcwd()
    work = tempfile.mkdtemp(prefix="syztpu-bench-")
    os.chdir(work)
    try:
        try:
            dev = run(use_device=True, mock=False)
            host = run(use_device=False, mock=False)
            executor = "real"
        except Exception:
            dev = run(use_device=True, mock=True)
            host = run(use_device=False, mock=True)
            executor = "mock"
    finally:
        os.chdir(cwd)
    return dev, host, executor


# ------------------------------------------------------------------ #
# config[5]: arena capacity sweep (ROADMAP arena_capacity tuning)

ARENA_SWEEP_CAPACITIES = (256, 1024, 4096)


def bench_arena_sweep(target, seconds=6.0):
    """The e2e device loop at each arena capacity, hermetic MockEnv fleet
    (the sweep compares arena policies against themselves, not executor
    speed): occupancy / evictions vs corpus yield per capacity.  Reads
    the weighted-eviction counter via getattr so the same harness runs
    against engines with and without weighted eviction."""
    from syzkaller_tpu.engine.fuzzer import Fuzzer, FuzzerConfig

    out = {}
    for cap in ARENA_SWEEP_CAPACITIES:
        cfg = FuzzerConfig(
            mock=True, use_device=True, device_batch=256,
            program_length=16, device_period=2, smash_mutations=4,
            procs=E2E_DEVICE_PROCS, arena_capacity=cap)
        with Fuzzer(target, cfg) as f:
            from syzkaller_tpu.telemetry import get_registry

            rate, execs, new_inputs, delta = _timed_loop(
                f, seconds, get_registry())
            arena = f._device.arena if f._device is not None else None
            eff = _exec_efficiency(delta, execs)
            out[str(cap)] = {
                "execs_per_sec": round(rate, 1),
                "new_inputs": new_inputs,
                "execs_per_new_input": round(execs / max(new_inputs, 1), 1),
                "calls_executed_per_exec": eff["calls_executed_per_exec"],
                "prefix_hit_rate": eff["prefix_hit_rate"],
                "arena_occupancy": (round(arena.size / arena.capacity, 4)
                                    if arena is not None else None),
                "arena_evictions_total": (arena.evictions
                                          if arena is not None else None),
                "arena_weighted_evictions_total": (
                    getattr(arena, "weighted_evictions", 0)
                    if arena is not None else None),
            }
    return out


# ------------------------------------------------------------------ #
# config: async pipelined device step sweep (ISSUE 18)

PIPELINE_SWEEP_DEPTHS = (1, 2, 4)
PIPELINE_SWEEP_BATCHES = (256, 512)


def _pipeline_overlap(delta):
    """Pipeline honesty numbers of one timed window, null-tolerant for
    engines predating the pipelined step's telemetry (the pre harness).

      stall_rate    — consumes that blocked on an incomplete transfer
                      over all consumes (device.fuzz_step.sync count).
      overlap_ratio — sum of per-slot device.step spans (launch ->
                      consume, OVERLAPPING at depth>=2) over the drain's
                      elapsed device.batch_drain time; > 1 means the
                      device was mutating while the host drained — the
                      pipelining claim, measured, not asserted.
    """
    syncs = delta.get("span_device_fuzz_step_sync_seconds_count", 0)
    stalls = delta.get("device_pipeline_stalls_total", 0)
    step_sum = delta.get("span_device_step_seconds_sum", 0.0)
    drain_sum = delta.get("span_device_batch_drain_seconds_sum", 0.0)
    return {
        "stall_rate": (round(stalls / syncs, 3) if syncs else None),
        "stalls": stalls if syncs else None,
        "overlap_ratio": (round(step_sum / drain_sum, 3)
                          if (step_sum and drain_sum) else None),
        "inflight_end": delta.get("device_pipeline_inflight") or None,
    }


def bench_pipeline_depth_sweep(target, seconds=6.0):
    """The e2e device loop over pipeline_depth {1, 2, 4} x device_batch
    {256, 512} at EQUAL env count, hermetic MockEnv fleet (the sweep
    compares the launch ring against itself, not executor speed):
    execs/sec, stall rate, and the span-overlap ratio per cell.  Depth 1
    is the old lockstep double buffer — the in-harness baseline every
    deeper cell is judged against.  Config construction is
    dataclasses-tolerant so the SAME harness runs pre+post: a pre-PR
    engine has no pipeline_depth knob (only its lockstep pipeline runs,
    reported as the d1 cells; deeper cells are null)."""
    import dataclasses

    from syzkaller_tpu.engine.fuzzer import Fuzzer, FuzzerConfig
    from syzkaller_tpu.telemetry import get_registry

    reg = get_registry()
    has_knob = "pipeline_depth" in {
        fld.name for fld in dataclasses.fields(FuzzerConfig)}
    out = {"has_pipeline_depth": has_knob}
    for batch in PIPELINE_SWEEP_BATCHES:
        for depth in PIPELINE_SWEEP_DEPTHS:
            cell_name = f"b{batch}_d{depth}"
            if not has_knob and depth != 1:
                out[cell_name] = None  # pre harness: lockstep only
                continue
            kw = {"pipeline_depth": depth} if has_knob else {}
            cfg = FuzzerConfig(
                mock=True, use_device=True, device_batch=batch,
                program_length=16, device_period=2, smash_mutations=4,
                procs=E2E_DEVICE_PROCS, **kw)
            with Fuzzer(target, cfg) as f:
                rate, execs, ni, delta = _timed_loop(f, seconds, reg)
            out[cell_name] = {
                "execs_per_sec": round(rate, 1),
                "new_inputs": ni,
                "execs_per_new_input": round(execs / max(ni, 1), 1),
                **_pipeline_overlap(delta),
            }
    return out


# ------------------------------------------------------------------ #
# config[6]: prefix-memoized execution sweep (the PR6 claim surface)

PREFIX_SWEEP_LENGTHS = (4, 8, 16)


def bench_prefix_sweep(target, seconds=8.0):
    """The e2e device loop seeded with programs of each length (the
    shared-prefix depth axis — splice/insert/value mutants of longer
    seeds share deeper call prefixes) x prefix scheduling {off, on} at
    EQUAL env count, hermetic MockEnv fleet (the sweep compares the
    scheduler against itself, not executor speed).  device_batch=512:
    bigger batches mean more mutants per arena seed, so groups are
    deeper and warm-ups amortize further — the design point the
    memoization targets.  Reports executed calls per batch/exec, the
    prefix cache hit rate, and the off->on call reduction.  Config
    construction and counter reads are tolerance-guarded so the SAME
    harness runs pre+post: a pre-PR engine has no prefix_schedule knob
    (the "on" cell is null) and no calls_executed_total (efficiency
    cells are null)."""
    import dataclasses

    from syzkaller_tpu.engine.fuzzer import Fuzzer, FuzzerConfig
    from syzkaller_tpu.prog.generation import generate
    from syzkaller_tpu.telemetry import get_registry

    reg = get_registry()
    has_prefix = "prefix_schedule" in {
        fld.name for fld in dataclasses.fields(FuzzerConfig)}
    out = {}
    for length in PREFIX_SWEEP_LENGTHS:
        row = {}
        for mode in ("off", "on"):
            if mode == "on" and not has_prefix:
                row[mode] = None  # pre harness: nothing to switch on
                continue
            kw = {"prefix_schedule": mode == "on"} if has_prefix else {}
            cfg = FuzzerConfig(
                mock=True, use_device=True, device_batch=512,
                program_length=length, device_period=1,
                smash_mutations=0, generate_period=1 << 30,
                procs=E2E_DEVICE_PROCS, **kw)
            with Fuzzer(target, cfg) as f:
                # controlled corpus: the depth axis must come from the
                # seeds, not from what triage minimized a run into
                for i in range(32):
                    f._add_corpus(generate(target, 1000 + i, length), ())
                rate, execs, _ni, delta = _timed_loop(
                    f, seconds, reg, warmup=10)
                batches = delta.get("device_batches_total", 0)
                eff = _exec_efficiency(delta, execs, batches=batches)
                row[mode] = {"execs_per_sec": round(rate, 1),
                             "batches": batches, **eff}
        off, on = row.get("off"), row.get("on")
        if off and on and off.get("calls_executed_per_exec") and \
                on.get("calls_executed_per_exec"):
            row["calls_reduction"] = round(
                1 - on["calls_executed_per_exec"] /
                off["calls_executed_per_exec"], 3)
        out[f"len{length}"] = row
    return out


# ------------------------------------------------------------------ #
# config[3]: hints over 100k cmp traces


def bench_hints(n_sites=512, n_comps=100_000, chunk=64):
    import jax
    import numpy as np

    from syzkaller_tpu.ops import hints as dhints
    from syzkaller_tpu.prog.generation import SPECIAL_INTS
    from syzkaller_tpu.prog.hints import CompMap, shrink_expand

    U64 = (1 << 64) - 1
    rng = np.random.default_rng(3)
    vals = rng.integers(0, 1 << 32, size=n_sites, dtype=np.uint64)
    # half the comps hit real site values (with casts), half are noise
    ops = np.where(rng.random(n_comps) < 0.5,
                   vals[rng.integers(0, n_sites, size=n_comps)]
                   & np.uint64(0xFFFF),
                   rng.integers(0, 1 << 32, size=n_comps,
                                dtype=np.uint64))
    cargs = rng.integers(0, 1 << 16, size=n_comps, dtype=np.uint64)
    special = np.asarray([v & U64 for v in SPECIAL_INTS], np.uint64)

    join = jax.jit(lambda v, o, c: dhints.unique_replacers(
        *dhints.hint_matrix(v, o, c, special), max_out=16))
    outs = [join(vals[i:i + chunk], ops, cargs)
            for i in range(0, n_sites, chunk)]  # warmup/compile
    _sync(outs[-1])
    best = 0.0
    for _ in range(3):
        t0 = time.perf_counter()
        outs = [join(vals[i:i + chunk], ops, cargs)
                for i in range(0, n_sites, chunk)]
        _sync(outs[-1])
        best = max(best, n_sites * n_comps / (time.perf_counter() - t0))

    def host_run(_seconds):
        m = CompMap()
        for a, b in zip(ops.tolist(), cargs.tolist()):
            m.add(a, b)
        for v in vals.tolist():
            shrink_expand(v, m)
        return n_sites * n_comps

    host = _median_rate(host_run, reps=3, min_seconds=0)
    return best, host


# ------------------------------------------------------------------ #
# config[4]: hub corpus exchange


def bench_hub(n_progs=4000):
    from syzkaller_tpu.hub import HubState

    progs = [f"r{i} = open(&0:0:0=\"./f{i}\\x00\", 0x0, 0x0)\n"
             f"write(r{i}, &1:0:0=\"6162\", 0x2)\n"
             for i in range(n_progs)]

    def run(_seconds):
        d = tempfile.mkdtemp(prefix="syztpu-hub-")
        hub = HubState(d)
        hub.connect("mgr-a", True, ["open", "write"], progs[: n_progs // 2])
        hub.connect("mgr-b", True, ["open", "write"], [])
        hub.sync("mgr-a", progs[n_progs // 2:], [])
        got, more = hub.sync("mgr-b", [], [])
        while more:
            extra, more = hub.sync("mgr-b", [], [])
            got += extra
        assert len(got) > 0
        return n_progs + len(got)

    return _median_rate(run, reps=3, min_seconds=0)


# ------------------------------------------------------------------ #
# config: hlo compiler-frontend e2e (ISSUE 16)


def bench_hlo_e2e(seconds=10.0):
    """The hlo frontend's end-to-end loop: generate/mutate op programs,
    compile+run them under pass settings, differentially check against
    the numpy reference, and chase the seeded differential bugs.
    Reports execs/sec, the structural compile-cache hit rate, and
    miscompares found vs seeded.  Import-guarded so the SAME harness
    runs on engines predating the frontends package (pre rounds report
    nulls)."""
    try:
        from syzkaller_tpu import frontends
        from syzkaller_tpu.frontends.hlo import bugs as hbugs
    except ImportError:
        return {"execs_per_sec": None, "compile_cache_hit_rate": None,
                "miscompares_found": None, "seeded": None}
    from syzkaller_tpu.engine.fuzzer import Fuzzer, FuzzerConfig
    from syzkaller_tpu.telemetry import get_registry

    reg = get_registry()
    plan = hbugs.default_plan()
    hbugs.install(plan)
    try:
        t = frontends.get("hlo").make_target()
        cfg = FuzzerConfig(frontend="hlo", use_device=False, procs=1,
                           program_length=8, smash_mutations=4)
        with Fuzzer(t, cfg) as f:
            rate, execs, ni, delta = _timed_loop(f, seconds, reg,
                                                 warmup=5)
        fc = delta.get("frontend_compiles_total", 0)
        fh = delta.get("frontend_compile_cache_hits_total", 0)
        return {
            "execs_per_sec": round(rate, 1),
            "execs": execs,
            "new_inputs": ni,
            "compile_cache_hit_rate": (round(fh / (fh + fc), 3)
                                       if (fh or fc) else None),
            "miscompares_found": delta.get(
                "frontend_miscompares_total", 0),
            "exceptions_found": delta.get("frontend_exceptions_total", 0),
            "timeouts_found": delta.get(
                "frontend_exec_timeouts_total", 0),
            "bugs_fired": sorted(plan.fired_names()),
            "seeded": len(plan.bugs),
        }
    finally:
        hbugs.clear()


def main(argv=None):
    ap = argparse.ArgumentParser(prog="bench")
    ap.add_argument("--telemetry-out", default="",
                    help="dump the telemetry document (metrics snapshot + "
                    "Chrome trace) to this JSON file after the run, so "
                    "BENCH rounds carry per-phase breakdowns")
    args = ap.parse_args(argv)

    from syzkaller_tpu.descriptions.tables import get_tables
    from syzkaller_tpu.ops.dtables import build_device_tables
    from syzkaller_tpu.prog import get_target
    from syzkaller_tpu.prog.tensor import TensorFormat
    from syzkaller_tpu.telemetry import get_registry, span

    device = _ensure_backend()
    target = get_target("linux", "amd64")
    tables = get_tables(target)
    fmt = TensorFormat.for_tables(tables, max_calls=16)
    dt = build_device_tables(tables, fmt)

    reg = get_registry()
    run_snap = reg.snapshot()
    configs = {}

    def run_config(name, fn):
        """One benchmark config: the result dict plus the per-phase
        span_*_seconds deltas it produced (each config body runs under a
        bench.<name> span; the e2e config additionally emits the engine's
        own compile/dispatch/triage spans)."""
        before = reg.snapshot()
        try:
            with span(f"bench.{name}"):
                configs[name] = fn()
        except Exception as e:  # noqa: BLE001 — record, don't kill the line
            configs[name] = {"error": str(e)[:200]}
        configs[name]["spans"] = span_deltas(reg, before)

    dev_host = {}

    def _mutate():
        dev_host["dev_mut"] = bench_device_mutate(dt, C=fmt.max_calls)
        dev_host["host_mut"] = bench_host_mutate(target)
        return {"device": round(dev_host["dev_mut"], 1),
                "host": round(dev_host["host_mut"], 1),
                "unit": "progs/sec"}

    run_config("mutate", _mutate)
    if "error" in configs["mutate"]:
        raise RuntimeError(
            f"mutate (the headline config) failed: "
            f"{configs['mutate']['error']}")
    dev_mut, host_mut = dev_host["dev_mut"], dev_host["host_mut"]

    def _cover():
        res = bench_cover_merge_sweep()
        res["unit"] = "traces/sec per (nbits, traces) cell"
        return res

    run_config("cover_merge_sweep", _cover)

    def _minimize():
        res = bench_minimize_bisect(target)
        res["unit"] = "per-minimized-item execs / round-trips"
        return res

    run_config("minimize_bisect", _minimize)

    def _hints():
        dev_hint, host_hint = bench_hints()
        return {"device": round(dev_hint, 1), "host": round(host_hint, 1),
                "unit": "site*comps/sec"}

    run_config("hints_100k", _hints)

    def _e2e():
        dev, host, executor = bench_e2e(target)
        (dev_rate, dev_execs, dev_ni, dev_eff) = dev
        (host_rate, host_execs, host_ni, host_eff) = host
        return {"device_pipeline": round(dev_rate, 1),
                "host_only": round(host_rate, 1),
                "unit": "execs/sec", "executor": executor,
                "device_procs": E2E_DEVICE_PROCS,
                # yield efficiency: admission/scheduling wins are visible
                # here even when the raw exec rate is unchanged
                "new_inputs": {"device": dev_ni, "host": host_ni},
                "execs_per_new_input": {
                    "device": round(dev_execs / max(dev_ni, 1), 1),
                    "host": round(host_execs / max(host_ni, 1), 1)},
                # executed-call efficiency (prefix memoization): nulls
                # when the engine predates the counters (pre harness)
                "efficiency": {"device": dev_eff, "host": host_eff}}

    run_config("e2e_triage", _e2e)

    def _hlo_e2e():
        res = bench_hlo_e2e()
        res["unit"] = "execs/sec (compiler-frontend differential loop)"
        return res

    run_config("hlo_e2e", _hlo_e2e)

    def _arena_sweep():
        res = bench_arena_sweep(target)
        res["unit"] = "per-capacity e2e yield"
        return res

    run_config("arena_sweep", _arena_sweep)

    def _prefix_sweep():
        res = bench_prefix_sweep(target)
        res["unit"] = "per-depth calls/exec, prefix off vs on"
        return res

    run_config("prefix_depth_sweep", _prefix_sweep)

    def _pipeline_sweep():
        res = bench_pipeline_depth_sweep(target)
        res["unit"] = "per-(batch, depth) execs/sec + stall/overlap"
        return res

    run_config("pipeline_depth_sweep", _pipeline_sweep)

    run_config("hub_sync", lambda: {
        "host": round(bench_hub(), 1), "unit": "progs/sec"})

    print(json.dumps({
        "metric": "mutation_throughput",
        "value": round(dev_mut, 1),
        "unit": "progs/sec",
        "vs_baseline": round(dev_mut / host_mut, 2),
        "device": device,
        "configs": configs,
        "telemetry": telemetry_delta(reg, run_snap),
        "baseline_note": (
            "host = this repo's single-threaded Python reimplementation "
            "on one shared core, NOT the Go reference (no Go toolchain "
            "here); a Go mutator is plausibly 50-500x the Python one, so "
            "vs_baseline overstates the win over real syzkaller by that "
            "factor. Host rates are median-of-5 runs of >=2s."),
    }))

    # after the JSON line: a bad dump path must not cost the round its
    # number of record
    if args.telemetry_out:
        from syzkaller_tpu.telemetry import telemetry_dump_to

        err = telemetry_dump_to(args.telemetry_out)
        if err:
            print(f"telemetry dump failed: {err}", file=sys.stderr)


if __name__ == "__main__":
    sys.exit(main())
