"""Dashboard: bug-tracking web service + client API.

Role parity with reference /root/reference/dashboard — the AppEngine app
(dashboard/app/entities.go:27-98 Build/Bug/Crash entities, crash ingestion
with dedup-by-title, needRepro decisions) and the dashapi client
(dashboard/dashapi/dashapi.go: UploadBuild/ReportCrash/NeedRepro/
ReportFailedRepro/ReportRepro/LogError) — redesigned as a self-hosted
sqlite-backed HTTP JSON service instead of an AppEngine datastore app.

Crash payloads (log/report/reproducers) are stored gzip-compressed, the
same way the reference's Text entities are (entities.go:96-...).
"""

from __future__ import annotations

import gzip
import html as _html
import http.server
import json
import os
import sqlite3
import threading
import time
import urllib.parse
import urllib.request
from dataclasses import dataclass, field
from typing import Dict, List, Optional

MAX_CRASHES_PER_BUG = 100  # mirror of the manager's per-bug log cap
REPRO_LEVEL_NONE = 0
REPRO_LEVEL_SYZ = 1
REPRO_LEVEL_C = 2

_SCHEMA = """
CREATE TABLE IF NOT EXISTS builds (
    id TEXT PRIMARY KEY, namespace TEXT, manager TEXT, os TEXT, arch TEXT,
    kernel_commit TEXT, kernel_config TEXT, time REAL
);
CREATE TABLE IF NOT EXISTS bugs (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    namespace TEXT, title TEXT, status TEXT DEFAULT 'open',
    num_crashes INTEGER DEFAULT 0, repro_level INTEGER DEFAULT 0,
    first_time REAL, last_time REAL,
    UNIQUE(namespace, title)
);
CREATE TABLE IF NOT EXISTS crashes (
    id INTEGER PRIMARY KEY AUTOINCREMENT,
    bug_id INTEGER, manager TEXT, build_id TEXT, time REAL,
    log BLOB, report BLOB, repro_syz BLOB, repro_c BLOB,
    maintainers TEXT
);
CREATE INDEX IF NOT EXISTS crashes_bug ON crashes(bug_id);
"""


def _z(text: Optional[str]) -> Optional[bytes]:
    return gzip.compress(text.encode()) if text else None


def _unz(blob: Optional[bytes]) -> str:
    return gzip.decompress(blob).decode("utf-8", "replace") if blob else ""


class DashboardDB:
    """sqlite store; one connection per thread via TLS."""

    def __init__(self, path: str):
        self.path = path
        self._tls = threading.local()
        with self._conn() as c:
            c.executescript(_SCHEMA)

    def _conn(self) -> sqlite3.Connection:
        c = getattr(self._tls, "conn", None)
        if c is None:
            c = sqlite3.connect(self.path)
            c.row_factory = sqlite3.Row
            self._tls.conn = c
        return c

    # ---- builds ----

    def upload_build(self, b: Dict) -> None:
        with self._conn() as c:
            c.execute(
                "INSERT OR REPLACE INTO builds VALUES (?,?,?,?,?,?,?,?)",
                (b["id"], b.get("namespace", ""), b.get("manager", ""),
                 b.get("os", ""), b.get("arch", ""),
                 b.get("kernel_commit", ""), b.get("kernel_config", ""),
                 time.time()))

    # ---- crash ingestion (reference app handler: dedup by title) ----

    def report_crash(self, crash: Dict) -> Dict:
        ns = crash.get("namespace", "")
        title = crash.get("title", "corrupted report")
        now = time.time()
        with self._conn() as c:
            row = c.execute(
                "SELECT * FROM bugs WHERE namespace=? AND title=?",
                (ns, title)).fetchone()
            if row is None:
                cur = c.execute(
                    "INSERT INTO bugs(namespace, title, num_crashes, "
                    "first_time, last_time) VALUES (?,?,0,?,?)",
                    (ns, title, now, now))
                bug_id = cur.lastrowid
                n_crashes = 0
                repro_level = 0
                status = "open"
            else:
                bug_id = row["id"]
                n_crashes = row["num_crashes"]
                repro_level = row["repro_level"]
                status = row["status"]
            new_level = REPRO_LEVEL_C if crash.get("repro_c") else (
                REPRO_LEVEL_SYZ if crash.get("repro_syz") else 0)
            c.execute(
                "UPDATE bugs SET num_crashes=num_crashes+1, last_time=?, "
                "repro_level=MAX(repro_level, ?), status=CASE WHEN "
                "status='fixed' THEN 'open' ELSE status END WHERE id=?",
                (now, new_level, bug_id))
            # store the crash payload unless the bug already has plenty
            # and this one adds nothing new (entities-cap analogue)
            if n_crashes < MAX_CRASHES_PER_BUG or new_level > repro_level:
                c.execute(
                    "INSERT INTO crashes(bug_id, manager, build_id, time,"
                    " log, report, repro_syz, repro_c, maintainers)"
                    " VALUES (?,?,?,?,?,?,?,?,?)",
                    (bug_id, crash.get("manager", ""),
                     crash.get("build_id", ""), now,
                     _z(crash.get("log")), _z(crash.get("report")),
                     _z(crash.get("repro_syz")), _z(crash.get("repro_c")),
                     json.dumps(crash.get("maintainers", []))))
            # needRepro: open bug without a C repro yet, still young
            need_repro = (status == "open"
                          and max(repro_level, new_level) < REPRO_LEVEL_C)
        return {"bug_id": bug_id, "need_repro": need_repro}

    def need_repro(self, ns: str, title: str) -> bool:
        with self._conn() as c:
            row = c.execute(
                "SELECT status, repro_level FROM bugs WHERE namespace=? "
                "AND title=?", (ns, title)).fetchone()
        if row is None:
            return False
        return row["status"] == "open" and \
            row["repro_level"] < REPRO_LEVEL_C

    def update_bug(self, ns: str, title: str, status: str) -> bool:
        if status not in ("open", "fixed", "invalid", "dup"):
            raise ValueError(f"bad status {status!r}")
        with self._conn() as c:
            cur = c.execute(
                "UPDATE bugs SET status=? WHERE namespace=? AND title=?",
                (status, ns, title))
            return cur.rowcount > 0

    # ---- queries ----

    def bugs(self, ns: str = "", status: str = "") -> List[Dict]:
        q = "SELECT * FROM bugs WHERE 1=1"
        args: List = []
        if ns:
            q += " AND namespace=?"
            args.append(ns)
        if status:
            q += " AND status=?"
            args.append(status)
        q += " ORDER BY num_crashes DESC"
        with self._conn() as c:
            return [dict(r) for r in c.execute(q, args).fetchall()]

    def bug_crashes(self, bug_id: int) -> List[Dict]:
        with self._conn() as c:
            rows = c.execute(
                "SELECT * FROM crashes WHERE bug_id=? ORDER BY time DESC",
                (bug_id,)).fetchall()
        out = []
        for r in rows:
            d = dict(r)
            for k in ("log", "report", "repro_syz", "repro_c"):
                d[k] = _unz(d[k])
            out.append(d)
        return out


class Dashboard:
    """HTTP JSON API + minimal HTML bug browser."""

    def __init__(self, workdir: str, host: str = "127.0.0.1", port: int = 0,
                 keys: Optional[Dict[str, str]] = None):
        os.makedirs(workdir, exist_ok=True)
        self.db = DashboardDB(os.path.join(workdir, "dashboard.db"))
        self.keys = keys or {}
        dash = self

        class _Handler(http.server.BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _reply(self, code: int, obj, ctype="application/json"):
                body = obj if isinstance(obj, bytes) else \
                    json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self) -> None:
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(n) or b"{}")
                    if dash.keys:
                        key = dash.keys.get(req.get("client", ""))
                        if key is None or key != req.get("key", ""):
                            self._reply(403, {"error": "unauthorized"})
                            return
                    method = self.path.lstrip("/")
                    fn = {
                        "api/upload_build": dash._api_upload_build,
                        "api/report_crash": dash._api_report_crash,
                        "api/need_repro": dash._api_need_repro,
                        "api/failed_repro": dash._api_failed_repro,
                        "api/update_bug": dash._api_update_bug,
                        "api/log_error": dash._api_log_error,
                    }.get(method)
                    if fn is None:
                        self._reply(404, {"error": f"no method {method}"})
                        return
                    self._reply(200, fn(req))
                except Exception as e:
                    try:
                        self._reply(500, {"error": str(e)})
                    except Exception:
                        pass

            def do_GET(self) -> None:
                try:
                    url = urllib.parse.urlparse(self.path)
                    q = dict(urllib.parse.parse_qsl(url.query))
                    if url.path == "/":
                        self._reply(200, dash._html_bugs(q), "text/html")
                    elif url.path == "/bug":
                        self._reply(200, dash._html_bug(q), "text/html")
                    elif url.path == "/api/bugs":
                        self._reply(200, dash.db.bugs(
                            q.get("ns", ""), q.get("status", "")))
                    else:
                        self.send_error(404)
                except Exception as e:
                    try:
                        self.send_error(500, str(e))
                    except Exception:
                        pass

        class _Server(http.server.ThreadingHTTPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._server = _Server((host, port), _Handler)
        self.addr = "%s:%d" % self._server.server_address
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True)
        self.errors: List[Dict] = []

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()

    # ---- API methods ----

    def _api_upload_build(self, req):
        self.db.upload_build(req["build"])
        return {}

    def _api_report_crash(self, req):
        return self.db.report_crash(req["crash"])

    def _api_need_repro(self, req):
        return {"need_repro": self.db.need_repro(
            req.get("namespace", ""), req["title"])}

    def _api_failed_repro(self, req):
        # recorded only for stats; a failed repro does not close the want
        return {}

    def _api_update_bug(self, req):
        return {"ok": self.db.update_bug(
            req.get("namespace", ""), req["title"], req["status"])}

    def _api_log_error(self, req):
        self.errors.append(req)
        del self.errors[:-1000]
        return {}

    # ---- HTML ----

    def _html_bugs(self, q) -> bytes:
        rows = []
        for b in self.db.bugs(q.get("ns", ""), q.get("status", "")):
            rows.append(
                f'<tr><td><a href="/bug?id={b["id"]}">'
                f'{_html.escape(b["title"])}</a></td>'
                f'<td>{b["status"]}</td><td>{b["num_crashes"]}</td>'
                f'<td>{b["repro_level"]}</td></tr>')
        return (
            "<html><body><h1>bugs</h1><table border=1>"
            "<tr><th>title</th><th>status</th><th>crashes</th>"
            "<th>repro</th></tr>" + "".join(rows)
            + "</table></body></html>").encode()

    def _html_bug(self, q) -> bytes:
        bug_id = int(q.get("id", 0))
        crashes = self.db.bug_crashes(bug_id)
        parts = [f"<h1>bug {bug_id}</h1>"]
        for cr in crashes[:10]:
            parts.append(f"<h3>crash @ {cr['time']}</h3>")
            for k in ("report", "repro_c", "repro_syz", "log"):
                if cr[k]:
                    parts.append(
                        f"<h4>{k}</h4><pre>"
                        f"{_html.escape(cr[k][:1 << 16])}</pre>")
        return ("<html><body>" + "".join(parts) + "</body></html>").encode()


class DashApi:
    """Client API (reference dashboard/dashapi/dashapi.go)."""

    def __init__(self, addr: str, client: str = "", key: str = ""):
        self.addr = addr
        self.client = client
        self.key = key

    def _query(self, method: str, **req):
        req.update({"client": self.client, "key": self.key})
        data = json.dumps(req).encode()
        r = urllib.request.Request(
            f"http://{self.addr}/api/{method}", data=data,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(r, timeout=30) as resp:
            out = json.loads(resp.read())
        if isinstance(out, dict) and out.get("error"):
            raise RuntimeError(out["error"])
        return out

    def upload_build(self, build: Dict) -> None:
        self._query("upload_build", build=build)

    def report_crash(self, crash: Dict) -> Dict:
        return self._query("report_crash", crash=crash)

    def need_repro(self, namespace: str, title: str) -> bool:
        return self._query("need_repro", namespace=namespace,
                           title=title)["need_repro"]

    def report_failed_repro(self, namespace: str, title: str) -> None:
        self._query("failed_repro", namespace=namespace, title=title)

    def update_bug(self, namespace: str, title: str, status: str) -> bool:
        return self._query("update_bug", namespace=namespace, title=title,
                           status=status)["ok"]

    def log_error(self, name: str, msg: str) -> None:
        try:
            self._query("log_error", name=name, msg=msg)
        except Exception:
            pass  # error logging must never take the caller down
