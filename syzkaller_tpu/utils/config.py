"""Strict JSON config loading (reference /root/reference/pkg/config/
config.go: unknown fields are rejected so typos fail loudly)."""

from __future__ import annotations

import dataclasses
import json
import typing
from typing import Any, Dict, Type, TypeVar

T = TypeVar("T")


class ConfigError(ValueError):
    pass


def load_dict(cls: Type[T], data: Dict[str, Any]) -> T:
    """Instantiate dataclass `cls` from `data`, recursing into dataclass
    fields, rejecting unknown keys."""
    if not dataclasses.is_dataclass(cls):
        raise ConfigError(f"{cls} is not a dataclass")
    fields = {f.name: f for f in dataclasses.fields(cls)}
    unknown = set(data) - set(fields)
    if unknown:
        raise ConfigError(f"unknown config fields: {sorted(unknown)} "
                          f"(known: {sorted(fields)})")
    hints = typing.get_type_hints(cls)  # resolves string annotations
    kwargs = {}
    for name, value in data.items():
        ftype = hints.get(name)
        if (isinstance(ftype, type) and dataclasses.is_dataclass(ftype)
                and isinstance(value, dict)):
            kwargs[name] = load_dict(ftype, value)
        else:
            kwargs[name] = value
    return cls(**kwargs)


def load_file(cls: Type[T], path: str) -> T:
    with open(path) as f:
        try:
            data = json.load(f)
        except json.JSONDecodeError as e:
            raise ConfigError(f"{path}: {e}") from e
    return load_dict(cls, data)
