"""Minimal Windows KD (kernel debugger) protocol decoder.

Role parity with reference /root/reference/pkg/kd/kd.go:32-100: scan a
serial byte stream for KD data packets ('0000' leader), and rewrite
STATE_CHANGE64 exception notifications into BUG: lines the crash-report
parser can pick up — how Windows targets surface crashes without a
console oops.  Original implementation against the public protocol
layout (windbgkd.h): 16-byte packet header (leader u32, type u16,
byte-count u16, id u32, checksum u32) followed by the payload.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import Optional, Tuple

DATA_LEADER = b"0000"          # 0x30303030
TYPE_STATE_CHANGE64 = 7

_HDR = struct.Struct("<4sHHII")
# stateChange64 prefix: state u32, proc_level u16, proc u16, nproc u32,
# thread u64, pc u64, then exception64: code u32, flags u32, record u64,
# address u64, num_params u32, unused u32, params[15] u64, first_chance u32
_STATE_PREFIX = struct.Struct("<IHHIQQ")
_EXCEPTION = struct.Struct("<IIQQII15QI")
_STATE_CHANGE_MIN = _STATE_PREFIX.size + _EXCEPTION.size


@dataclass
class Exception64:
    code: int
    flags: int
    address: int
    first_chance: bool
    pc: int
    processor: int


def decode(data: bytes) -> Tuple[int, int, bytes]:
    """(start, size, decoded): scan for one packet at/after `start`.

    size==0 means incomplete — retry with more data from `start`.
    `decoded` is a synthesized crash line for exception packets, else
    empty (reference Decode kd.go:32-65 semantics)."""
    if len(data) < len(DATA_LEADER):
        return 0, 0, b""
    start = data.find(DATA_LEADER)
    if start == -1:
        # keep a tail that could begin a leader next read
        return max(0, len(data) - len(DATA_LEADER) - 1), 0, b""
    if len(data) - start < _HDR.size:
        return start, 0, b""
    _leader, typ, count, _pid, _csum = _HDR.unpack_from(data, start)
    if len(data) - start < _HDR.size + count:
        return start, 0, b""
    size = _HDR.size + count
    if typ != TYPE_STATE_CHANGE64 or count < _STATE_CHANGE_MIN:
        return start, size, b""
    exc = parse_state_change(data[start + _HDR.size:start + size])
    if exc is None:
        return start, size, b""
    chance = "first" if exc.first_chance else "second"
    line = (f"\n\nBUG: {chance} chance exception 0x{exc.code:x} "
            f"at pc 0x{exc.pc:x} addr 0x{exc.address:x} "
            f"(cpu {exc.processor})\n\n")
    return start, size, line.encode()


def parse_state_change(payload: bytes) -> Optional[Exception64]:
    if len(payload) < _STATE_CHANGE_MIN:
        return None
    _state, _lvl, proc, _n, _thread, pc = _STATE_PREFIX.unpack_from(
        payload, 0)
    fields = _EXCEPTION.unpack_from(payload, _STATE_PREFIX.size)
    code, flags, _record, address = fields[0], fields[1], fields[2], \
        fields[3]
    first_chance = fields[-1]
    return Exception64(code=code, flags=flags, address=address,
                       first_chance=bool(first_chance), pc=pc,
                       processor=proc)
