"""Email parsing/reply for the dashboard reporting workflow.

Role parity with reference /root/reference/pkg/email (parser.go:20-226,
reply.go:12-50): parse incoming bug-report replies (sender, subject,
message-id, body, `#syz` commands, address contexts for bug-id routing),
merge CC lists, and form quoted replies.  Built on the stdlib email
package rather than hand-rolling MIME.
"""

from __future__ import annotations

import email
import email.utils
import re
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

# commands understood in reply bodies (reference extractCommand):
#   #syz fix: commit title
#   #syz dup: other bug title
#   #syz invalid / #syz undup / #syz upstream / #syz test: repo branch
COMMAND_RE = re.compile(r"^#syz(?:bot)?[ \t]+([a-z\-]+):?[ \t]*(.*)$",
                        re.M)


@dataclass
class Email:
    bug_id: str = ""
    message_id: str = ""
    from_addr: str = ""
    cc: List[str] = field(default_factory=list)
    subject: str = ""
    body: str = ""
    command: str = ""
    command_args: str = ""


def add_addr_context(addr: str, context: str) -> str:
    """user@host -> user+context@host (reference AddAddrContext): the
    context routes a reply back to the bug it concerns."""
    if "@" not in addr:
        raise ValueError(f"bad email address {addr!r}")
    user, host = addr.rsplit("@", 1)
    return f"{user}+{context}@{host}"


def remove_addr_context(addr: str) -> Tuple[str, str]:
    """Inverse of add_addr_context; returns (bare_addr, context)."""
    if "@" not in addr:
        raise ValueError(f"bad email address {addr!r}")
    user, host = addr.rsplit("@", 1)
    if "+" not in user:
        return addr, ""
    bare, context = user.split("+", 1)
    return f"{bare}@{host}", context


def parse(raw: str, own_emails: Tuple[str, ...] = ()) -> Email:
    """Parse a raw RFC-2822 message (reference Parse, parser.go:37-118)."""
    msg = email.message_from_string(raw)
    out = Email()
    out.message_id = (msg.get("Message-ID") or "").strip()
    out.subject = " ".join((msg.get("Subject") or "").split())
    from_addrs = email.utils.getaddresses([msg.get("From") or ""])
    if from_addrs:
        out.from_addr = from_addrs[0][1]

    own_bare = set()
    for own in own_emails:
        bare, _ = remove_addr_context(own) if "@" in own else (own, "")
        own_bare.add(bare.lower())

    cc: List[str] = []
    for hdr in ("To", "Cc", "From"):
        for _name, addr in email.utils.getaddresses([msg.get(hdr) or ""]):
            if not addr:
                continue
            bare, context = remove_addr_context(addr)
            if bare.lower() in own_bare:
                # one of OUR addresses: its +context names the bug
                if context and not out.bug_id:
                    out.bug_id = context
                continue
            if bare.lower() not in (c.lower() for c in cc):
                cc.append(bare)
    out.cc = sorted(cc)

    out.body = _extract_body(msg)
    m = COMMAND_RE.search(out.body)
    if m:
        out.command = m.group(1)
        out.command_args = m.group(2).strip()
    return out


def _extract_body(msg) -> str:
    if msg.is_multipart():
        for part in msg.walk():
            if part.get_content_type() == "text/plain":
                payload = part.get_payload(decode=True)
                if payload is not None:
                    return payload.decode(
                        part.get_content_charset() or "utf-8", "replace")
        return ""
    payload = msg.get_payload(decode=True)
    if payload is None:
        return str(msg.get_payload())
    return payload.decode(msg.get_content_charset() or "utf-8", "replace")


def merge_email_lists(*lists: List[str]) -> List[str]:
    """Dedup + canonicalize + sort (reference MergeEmailLists)."""
    seen = {}
    for lst in lists:
        for addr in lst:
            _name, bare = email.utils.parseaddr(addr)
            if bare and bare.lower() not in seen:
                seen[bare.lower()] = bare
    return sorted(seen.values())


def form_reply(original_body: str, reply: str) -> str:
    """Quote the original and prepend the reply after the first quoted
    line (reference FormReply: reply goes above the quote)."""
    quoted = "\n".join("> " + ln for ln in original_body.splitlines())
    return f"{reply.rstrip()}\n\n{quoted}\n"
