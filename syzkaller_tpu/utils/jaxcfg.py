"""JAX configuration shared by all device modules.

Program words are u64 and coverage signal is u32; every module that touches
jax must call ensure_x64() before building arrays so 64-bit integer lanes are
enabled process-wide (on TPU, XLA lowers u64 bitwise ops to u32 pairs — fine
for the bitset/mutation workloads here).
"""

from __future__ import annotations

_done = False


def ensure_x64() -> None:
    global _done
    if _done:
        return
    import jax

    jax.config.update("jax_enable_x64", True)
    _done = True
