"""JAX configuration shared by all device modules.

Program words are u64 and coverage signal is u32; every module that touches
jax must call ensure_x64() before building arrays so 64-bit integer lanes are
enabled process-wide (on TPU, XLA lowers u64 bitwise ops to u32 pairs — fine
for the bitset/mutation workloads here).
"""

from __future__ import annotations

_done = False


def ensure_x64() -> None:
    global _done
    if _done:
        return
    import jax

    jax.config.update("jax_enable_x64", True)
    _enable_compile_cache(jax)
    _done = True


def x64_context(enabled: bool = True):
    """Version-tolerant `with x64 on/off` scope.

    Some jax releases expose ``jax.enable_x64`` as a context manager;
    others keep it in ``jax.experimental``.  The pallas kernels trace with
    x64 off (mosaic rejects the weak-int64 scalars x64 mode introduces)
    while the rest of the device code runs with it on — every scoped
    toggle must route through here."""
    import jax

    ctx = getattr(jax, "enable_x64", None)
    if ctx is None:
        from jax.experimental import enable_x64 as ctx
    return ctx(enabled)


def _enable_compile_cache(jax) -> None:
    """Persistent XLA compilation cache.

    Dev/CI hosts for this project can be single-core (the axon TPU tunnel
    box), where LLVM codegen of the u64-heavy kernels costs minutes; the
    disk cache makes every compile after the first instant.  Opt out with
    SYZ_TPU_NO_COMPILE_CACHE=1."""
    import os

    if os.environ.get("SYZ_TPU_NO_COMPILE_CACHE"):
        return
    cache = os.environ.get("SYZ_TPU_COMPILE_CACHE") or os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))), ".jax_cache")
    try:
        os.makedirs(cache, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except Exception:  # cache is an optimization, never a requirement
        pass
