"""Leveled logging with an in-memory ring cache for crash-time dumps.

Capability parity with reference /root/reference/pkg/log (Logf levels,
EnableLogCaching, CachedLogOutput): when caching is enabled the last N
lines are retained so a crash bundle can include recent fuzzer activity —
in particular the `executing program` records that pkg/repro parses.
"""

from __future__ import annotations

import sys
import threading
import time
from collections import deque
from typing import Deque, Optional

_lock = threading.Lock()
_level = 0
_cache: Optional[Deque[str]] = None
_cache_max_mem = 0
_stream = sys.stderr


def set_verbosity(level: int) -> None:
    global _level
    _level = level


def enable_log_caching(max_lines: int = 100000,
                       max_mem: int = 8 << 20) -> None:
    global _cache, _cache_max_mem
    with _lock:
        _cache = deque(maxlen=max_lines)
        _cache_max_mem = max_mem


def cached_log_output() -> str:
    with _lock:
        if _cache is None:
            return ""
        out, total = [], 0
        for line in reversed(_cache):
            total += len(line)
            if _cache_max_mem and total > _cache_max_mem:
                break
            out.append(line)
        return "".join(reversed(out))


def logf(level: int, fmt: str, *args) -> None:
    msg = (fmt % args) if args else fmt
    line = "%s [%d] %s\n" % (
        time.strftime("%Y/%m/%d %H:%M:%S"), level, msg)
    with _lock:
        if _cache is not None:
            _cache.append(line)
    if level <= _level:
        _stream.write(line)
        _stream.flush()
