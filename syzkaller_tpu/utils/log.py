"""Leveled logging with an in-memory ring cache for crash-time dumps.

Capability parity with reference /root/reference/pkg/log (Logf levels,
EnableLogCaching, CachedLogOutput): when caching is enabled the last N
lines are retained so a crash bundle can include recent fuzzer activity —
in particular the `executing program` records that pkg/repro parses.
"""

from __future__ import annotations

import sys
import threading
import time
from collections import deque
from typing import Deque, Optional

_lock = threading.Lock()
_level = 0
_cache: Optional[Deque[str]] = None
_cache_mem = 0       # bytes currently retained
_cache_max_mem = 0   # eviction bound, enforced at insert time
_stream = sys.stderr


def set_verbosity(level: int) -> None:
    global _level
    _level = level


def enable_log_caching(max_lines: int = 100000,
                       max_mem: int = 8 << 20) -> None:
    global _cache, _cache_max_mem, _cache_mem
    with _lock:
        _cache = deque(maxlen=max_lines)
        _cache_max_mem = max_mem
        _cache_mem = 0


def cached_log_output() -> str:
    with _lock:
        if _cache is None:
            return ""
        return "".join(_cache)


def logf(level: int, fmt: str, *args) -> None:
    global _cache_mem
    msg = (fmt % args) if args else fmt
    line = "%s [%d] %s\n" % (
        time.strftime("%Y/%m/%d %H:%M:%S"), level, msg)
    with _lock:
        if _cache is not None:
            if len(_cache) == _cache.maxlen:
                _cache_mem -= len(_cache[0])  # about to be auto-evicted
            _cache.append(line)
            _cache_mem += len(line)
            while _cache_mem > _cache_max_mem and len(_cache) > 1:
                _cache_mem -= len(_cache.popleft())
    if level <= _level:
        _stream.write(line)
        _stream.flush()
