"""Program identity hashing (reference /root/reference/pkg/hash/hash.go:
SHA1-based Sig with string form used for corpus keys and crash dedup)."""

from __future__ import annotations

import hashlib


class Sig:
    __slots__ = ("digest",)

    def __init__(self, digest: bytes):
        self.digest = digest

    def __str__(self) -> str:
        return self.digest.hex()

    def __eq__(self, other) -> bool:
        return isinstance(other, Sig) and self.digest == other.digest

    def __hash__(self) -> int:
        return hash(self.digest)


def hash_bytes(data: bytes) -> Sig:
    return Sig(hashlib.sha1(data).digest())


def hash_str(data: bytes) -> str:
    return hashlib.sha1(data).hexdigest()
