"""Automated crash reproduction.

Capability parity with reference /root/reference/pkg/repro/repro.go:63-552:
given a crash log, recover the programs executed before the crash
(prog/parse), find the crashing subset by bisection over trailing
programs, minimize the program with a crash predicate (prog.minimize),
simplify execution options, then extract a standalone C reproducer and
simplify its option matrix.

The reference tests hypotheses by rebooting VMs and running syz-execprog
inside them; here the testing surface is the `Tester` interface so the
pipeline itself is hermetic — `VMTester` provides the real
boot-VM/run-execprog/watch-console path, and tests inject a predicate.
"""

from __future__ import annotations

import os
import shlex
import subprocess
import sys
import tempfile
import time
from dataclasses import dataclass, field, replace
from typing import Callable, List, Optional, Sequence

from .. import csource
from ..ipc import ExecOpts
from ..prog.encoding import serialize
from ..prog.mutation import minimize
from ..prog.parse import parse_log
from ..prog.prog import Prog
from ..report import Report
from ..utils.log import logf

# How many trailing log programs bisection starts from (the crash cause is
# almost always recent; reference repro.go caps similarly).
MAX_BISECT_PROGS = 20


@dataclass
class Stats:
    extract_time: float = 0.0
    minimize_time: float = 0.0
    simplify_prog_time: float = 0.0
    extract_c_time: float = 0.0
    simplify_c_time: float = 0.0
    exec_runs: int = 0


@dataclass
class Result:
    # the single-program reproducer, or None when only the multi-program
    # sequence in `progs` reproduces the crash
    prog: Optional[Prog]
    opts: ExecOpts
    progs: List[Prog] = field(default_factory=list)  # what actually crashed
    c_src: Optional[str] = None  # C reproducer source, if extraction worked
    c_opts: Optional[csource.Options] = None
    duration: float = 0.0
    stats: Stats = field(default_factory=Stats)
    title: str = ""


class Tester:
    """Crash-hypothesis testing surface: run programs with options, report
    whether the target crashed (and with what title)."""

    def test_progs(self, progs: Sequence[Prog], opts: ExecOpts,
                   duration: float) -> Optional[Report]:
        raise NotImplementedError

    def test_c_bin(self, bin_path: str, duration: float) -> Optional[Report]:
        raise NotImplementedError


class VMTester(Tester):
    """Boots instances from a vm.Pool, replays programs via the execprog
    tool, and watches the console for oops output (the reference's
    testProgs path, repro.go:506-552)."""

    def __init__(self, pool, instance_indexes: Sequence[int] = (0,),
                 ignores: Optional[List[str]] = None,
                 python: str = sys.executable):
        self.pool = pool
        self.indexes = list(instance_indexes)
        self.ignores = ignores or []
        self.python = python

    def test_progs(self, progs, opts, duration):
        from ..vm import monitor_execution

        inst = self.pool.create(self.indexes[0])
        try:
            fd, path = tempfile.mkstemp(suffix=".prog")
            with os.fdopen(fd, "w") as f:
                f.write("\n\n".join(serialize(p).strip() for p in progs)
                        + "\n")
            guest = inst.copy(path)
            os.unlink(path)
            flags = ""
            if opts.threaded:
                flags += " -threaded"
            if opts.collide:
                flags += " -collide"
            if opts.fault_call >= 0:
                flags += (f" -fault-call {opts.fault_call}"
                          f" -fault-nth {opts.fault_nth}")
            cmd = (f"{shlex.quote(self.python)} -m "
                   f"syzkaller_tpu.tools.execprog -repeat 0{flags} "
                   f"{shlex.quote(guest)}")
            merger, proc = inst.run(cmd, timeout=duration)
            res = monitor_execution(merger, proc, timeout=duration,
                                    no_output_timeout=duration,
                                    ignores=self.ignores)
            return res.report
        finally:
            inst.close()

    def test_c_bin(self, bin_path, duration):
        from ..vm import monitor_execution

        inst = self.pool.create(self.indexes[0])
        try:
            guest = inst.copy(bin_path)
            merger, proc = inst.run(guest, timeout=duration)
            res = monitor_execution(merger, proc, timeout=duration,
                                    no_output_timeout=duration,
                                    ignores=self.ignores)
            return res.report
        finally:
            inst.close()


def run(crash_log: str, target, tester: Tester,
        test_duration: float = 30.0) -> Optional[Result]:
    """The full pipeline. Returns None when the crash does not reproduce
    from the logged programs."""
    t_start = time.time()
    stats = Stats()
    entries = parse_log(target, crash_log)
    if not entries:
        logf(1, "repro: no programs parsed from the crash log")
        return None
    logf(1, "repro: %d programs in log", len(entries))

    def crashed(progs: Sequence[Prog], opts: ExecOpts) -> Optional[Report]:
        stats.exec_runs += 1
        return tester.test_progs(progs, opts, test_duration)

    # default exec opts mirror the fuzzer's (threaded repro first, like
    # the reference, which simplifies away later)
    opts = ExecOpts(threaded=True, collide=True)
    for e in entries:
        if e.fault:
            opts.fault_call = e.fault_call
            opts.fault_nth = e.fault_nth
            break

    # --- phase 1: which program(s) crash? ---
    t0 = time.time()
    progs, rep = _extract(entries, opts, crashed)
    stats.extract_time = time.time() - t0
    if not progs:
        logf(1, "repro: crash did not reproduce from logged programs")
        return None
    title = rep.title if rep else ""

    # Multi-program reproducers are folded into one program by
    # concatenation when possible (the common case after bisection is a
    # single program anyway).
    p = progs[-1] if len(progs) == 1 else _concat(target, progs)
    check = _single_pred(crashed, opts)
    if len(progs) > 1 and not check(p):
        # concatenation broke it: fall back to the last program alone,
        # else give up on a single-program reproducer and return the
        # crashing sequence itself (progs), unminimized
        if check(progs[-1]):
            p = progs[-1]
        else:
            return Result(prog=None, progs=progs, opts=opts, title=title,
                          stats=stats, duration=time.time() - t_start)

    # --- phase 2: minimize the program ---
    t0 = time.time()
    p, _ = minimize(p, -1, lambda q, _ci: check(q), crash=True)
    stats.minimize_time = time.time() - t0

    # --- phase 3: simplify exec options ---
    t0 = time.time()
    for simplify in _PROG_SIMPLIFIES:
        cand = simplify(opts)
        if cand is None:
            continue
        if crashed([p], cand):
            opts = cand
    stats.simplify_prog_time = time.time() - t0

    result = Result(prog=p, progs=[p], opts=opts, title=title, stats=stats)

    # --- phase 4: C reproducer ---
    t0 = time.time()
    copts = csource.Options(
        threaded=opts.threaded, collide=opts.collide, repeat=True,
        fault=opts.fault_call >= 0, fault_call=opts.fault_call,
        fault_nth=opts.fault_nth, sandbox="none")
    src = _test_c(p, copts, tester, test_duration, stats)
    stats.extract_c_time = time.time() - t0
    if src is not None:
        # --- phase 5: simplify C options ---
        t0 = time.time()
        for simplify in _C_SIMPLIFIES:
            cand = simplify(copts)
            if cand is None:
                continue
            src2 = _test_c(p, cand, tester, test_duration, stats)
            if src2 is not None:
                copts, src = cand, src2
        stats.simplify_c_time = time.time() - t0
        result.c_src = src
        result.c_opts = copts

    result.duration = time.time() - t_start
    return result


def _single_pred(crashed, opts) -> Callable[[Prog], bool]:
    return lambda p: crashed([p], opts) is not None


def _extract(entries, opts, crashed):
    """extractProgSingle then extractProgBisect (repro.go:290-400):
    last program alone, then delta-debug the trailing window."""
    last = entries[-1].p
    rep = crashed([last], opts)
    if rep is not None:
        return [last], rep
    progs = [e.p for e in entries[-MAX_BISECT_PROGS:]]
    if len(progs) > 1:
        rep = crashed(progs, opts)
        if rep is None:
            return [], None
        progs, rep = _ddmin(progs, opts, crashed, rep)
        return progs, rep
    return [], None


def _ddmin(progs, opts, crashed, rep):
    """Greedy delta-debugging over the program list: try dropping halves,
    then quarters, ... until 1-minimal."""
    n = 2
    while len(progs) >= 2:
        chunk = max(1, len(progs) // n)
        shrunk = False
        i = 0
        while i < len(progs):
            cand = progs[:i] + progs[i + chunk:]
            if cand:
                r = crashed(cand, opts)
                if r is not None:
                    progs, rep = cand, r
                    shrunk = True
                    continue  # same i now points at the next chunk
            i += chunk
        if not shrunk:
            if chunk == 1:
                break
            n *= 2
    return progs, rep


def _concat(target, progs):
    p = Prog(target, [])
    for q in progs:
        p.calls.extend(q.clone().calls)
    return p


def _test_c(p, copts, tester, duration, stats) -> Optional[str]:
    try:
        src = csource.write(p, copts)
        bin_path = csource.build(src)
    except (subprocess.CalledProcessError, OSError, ValueError):
        return None  # e.g. no compiler on this host: skip the C phase
    try:
        stats.exec_runs += 1
        rep = tester.test_c_bin(bin_path, duration)
        return src if rep is not None else None
    finally:
        os.unlink(bin_path)


# Option-simplification ladders (reference simplifyProg repro.go:426-456
# and simplifyC:474-...): each returns a simpler candidate or None.

def _drop_collide(o: ExecOpts) -> Optional[ExecOpts]:
    return replace(o, collide=False) if o.collide else None


def _drop_threaded(o: ExecOpts) -> Optional[ExecOpts]:
    if not o.threaded or o.collide:
        return None
    return replace(o, threaded=False)


def _drop_fault(o: ExecOpts) -> Optional[ExecOpts]:
    if o.fault_call < 0:
        return None
    return replace(o, fault_call=-1, fault_nth=0)


_PROG_SIMPLIFIES = [_drop_collide, _drop_threaded, _drop_fault]


def _c_drop_collide(o: csource.Options) -> Optional[csource.Options]:
    return replace(o, collide=False) if o.collide else None


def _c_drop_threaded(o: csource.Options) -> Optional[csource.Options]:
    if not o.threaded or o.collide:
        return None
    return replace(o, threaded=False)


def _c_drop_repeat(o: csource.Options) -> Optional[csource.Options]:
    return replace(o, repeat=False) if o.repeat else None


def _c_drop_fault(o: csource.Options) -> Optional[csource.Options]:
    if not o.fault:
        return None
    return replace(o, fault=False, fault_call=-1, fault_nth=0)


_C_SIMPLIFIES = [_c_drop_collide, _c_drop_threaded, _c_drop_repeat,
                 _c_drop_fault]
