"""Supported-syscall detection on the live machine.

Role parity with reference /root/reference/pkg/host/host_linux.go:19-160:
the primary strategy is the /proc/kallsyms symbol probe (` T sys_<name>`,
the most reliable of the three strategies the reference enumerates); socket
variants are probed by actually creating a socket of that family, open
variants by opening their constant filename, and syz_* pseudo-calls by
checking the device/feature they need.  The result feeds
`Target.transitively_enabled_calls` so calls whose input resources have no
supported constructor are disabled too (reference syz-fuzzer/fuzzer.go:
430-465 buildCallList).
"""

from __future__ import annotations

import errno
import os
import socket
from typing import Dict, Iterable, Optional, Set

from ..prog.types import BufferKind, BufferType, ConstType, PtrType, Syscall


def _read_kallsyms(path: str = "/proc/kallsyms") -> bytes:
    try:
        with open(path, "rb") as f:
            return f.read()
    except OSError:
        return b""


def _string_const(typ) -> Optional[str]:
    """The single constant value of a ptr[in, string["..."]] arg."""
    if not isinstance(typ, PtrType):
        return None
    s = typ.elem
    if not isinstance(s, BufferType) or s.kind != BufferKind.STRING \
            or len(s.values) != 1:
        return None
    v = s.values[0]
    return v[:-1] if v.endswith("\x00") else v


def _supported_socket(meta: Syscall) -> bool:
    """Create a socket of the declared family (host_linux.go:112-123)."""
    af = meta.args[0]
    if not isinstance(af, ConstType):
        return True
    try:
        s = socket.socket(af.val, socket.SOCK_DGRAM, 0)
        s.close()
        return True
    except OSError as e:
        if e.errno in (errno.ENOSYS, errno.EAFNOSUPPORT):
            return False
        # EPERM/EPROTONOSUPPORT etc.: family exists, kernel said no for
        # other reasons — the reference treats these as supported
        return True


def _supported_open(meta: Syscall, fname_arg: int) -> bool:
    fname = _string_const(meta.args[fname_arg])
    if fname is None:
        return True
    try:
        fd = os.open(fname, os.O_RDONLY)
        os.close(fd)
        return True
    except OSError:
        return False


def _supported_syz(meta: Syscall) -> bool:
    """Pseudo-syscalls: check the kernel feature they wrap
    (host_linux.go:59-110)."""
    cn = meta.call_name
    if cn == "syz_test":
        return False
    if cn == "syz_open_dev":
        fname = _string_const(meta.args[0]) if meta.args else None
        if fname is None:
            return True
        if os.getuid() != 0:
            return False
        if "#" not in fname:
            return os.path.exists(fname)
        # substitute one '#' at a time over 0-9 (host_linux.go:77-98);
        # a device present only at index 5-9 must still enable the call
        def check(dev: str) -> bool:
            i = dev.find("#")
            if i < 0:
                return os.path.exists(dev)
            return any(check(dev[:i] + str(d) + dev[i + 1:])
                       for d in range(10))

        return check(fname)
    if cn == "syz_open_pts":
        return os.path.exists("/dev/ptmx")
    if cn == "syz_kvm_setup_cpu":
        return os.path.exists("/dev/kvm")
    if cn in ("syz_emit_ethernet", "syz_extract_tcp_res"):
        return os.path.exists("/dev/net/tun")
    if cn in ("syz_fuse_mount", "syz_fusectl_mount"):
        return os.path.exists("/dev/fuse")
    return True


def is_supported(kallsyms: bytes, meta: Syscall) -> bool:
    if meta.call_name.startswith("syz_"):
        return _supported_syz(meta)
    if meta.name.startswith("socket$"):
        return _supported_socket(meta)
    if meta.name.startswith("open$"):
        return _supported_open(meta, 0)
    if meta.name.startswith("openat$"):
        return _supported_open(meta, 1)
    if not kallsyms:
        return True  # no CONFIG_KALLSYMS: assume everything, like the ref
    for prefix in (b" T sys_", b" T __x64_sys_", b" T __arm64_sys_",
                   b" W sys_", b" T ksys_"):
        if prefix + meta.call_name.encode() + b"\n" in kallsyms:
            return True
    return False


def detect_supported_syscalls(target,
                              kallsyms: Optional[bytes] = None
                              ) -> Dict[int, bool]:
    """syscall id -> supported on this machine (host_linux.go:19-38)."""
    if kallsyms is None:
        kallsyms = _read_kallsyms()
    return {meta.id: is_supported(kallsyms, meta)
            for meta in target.syscalls}


def build_call_list(target, enabled: Optional[Iterable[int]] = None,
                    kallsyms: Optional[bytes] = None) -> Set[int]:
    """Supported ∩ enabled, closed under resource-constructor
    reachability (fuzzer.go:430-465).  Returns syscall ids."""
    supported = detect_supported_syscalls(target, kallsyms)
    ids = {i for i, ok in supported.items() if ok}
    if enabled is not None:
        ids &= set(enabled)
    metas = [target.syscalls[i] for i in sorted(ids)]
    return {c.id for c in target.transitively_enabled_calls(metas)}
