"""Prefix-tree batch execution planning: run each shared mutant prefix
ONCE, pay only for suffixes.

BENCH_PR5 left the campaign host-execution-bound: the device mutates
~33k programs/sec while the CPU fleet executes ~70/sec at ~25ms per
exec.  Splice/insert/value mutants drawn from the same arena rows share
long common call prefixes *by construction*, so the fleet re-executes
the same prefix hundreds of times per batch.  This module is the next
memoization move after ops/admission.py, grounded in the same
literature ("Toward Speeding up Mutation Analysis by Memoizing
Expensive Methods", arXiv:2102.11559 — memoize the expensive shared
computation, verify with a cheap fingerprint; "Faster Mutation Analysis
via Equivalence Modulo States", arXiv:1702.06689 — mutants whose
observable state after the shared prefix is identical need not re-run
it): over the staged, admission-compacted encoded batch, build a
radix/prefix tree of longest-common call prefixes and emit an execution
schedule of one *prefix job* per tree node plus per-program *suffix
jobs* keyed by parent node (ipc exec_prefix/exec_suffix).

Three layers, mirroring the admission module's device/host split:

  - ``call_hashes`` / ``prefix_hashes`` — [B, C] per-call-slot content
    hashes (``admission.row_hash`` applied per call slot, empty slots
    normalized to a sentinel so inactive-slot garbage never splits a
    group) and the FNV-chained cumulative prefix hashes.  jax versions
    are single fused elementwise kernels; ``*_host`` are the
    bit-identical numpy mirrors (parity-pinned by tests/test_prefix.py).
  - ``sorted_lcp`` — vectorized longest-common-prefix discovery in the
    ``admission.inbatch_first_mask`` style: lexicographic sort of the
    hash rows (repeated stable argsort on device, ``np.lexsort`` on
    host) + per-position equality cumulative-product between adjacent
    sorted rows.
  - ``build_plan`` — host-side lcp-interval tree construction (the
    classic suffix-array stack algorithm) over the sorted hashes,
    pruned to nodes that actually pay for themselves (>= 2 users,
    >= ``min_calls`` marginal active calls), emitting a ``PrefixPlan``:
    every eligible program is reachable as (deepest tree node's prefix)
    + (its own suffix), and the schedule covers each row exactly once.

Depth is measured in call SLOTS during tree construction (the encoded
[B, C] layout) but exported in ACTIVE CALLS (``PrefixNode.n_calls``)
because the ipc continuation protocol counts executed call
instructions; the slot->call projection is the running count of
``cid >= 0`` slots inside the shared prefix, identical across a node's
members by construction (the hash covers ``cid``).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from . import ensure_x64  # noqa: F401

import numpy as np

from .admission import (
    FNV64_OFFSET,
    FNV64_PRIME,
    _SALT_CID,
    _SALT_DATA,
    _SALT_SVAL,
)

U64 = np.uint64

# sentinel hash for an empty call slot (cid < 0): inactive slots carry
# mutation garbage in sval/data that never reaches the emitted exec
# stream, so hashing it would split groups whose executed prefixes are
# identical.  Any fixed odd constant works; this is splitmix64's gamma.
EMPTY_SLOT_HASH = 0x9E3779B97F4A7C15


# ---- per-slot content hashes (device + bit-identical host mirror) ----


def call_hashes(cid, sval, data):
    """[B, C] i32, [B, C, S] u64, [B, C, D] u8 -> [B, C] u64: one
    content hash per call slot, equal to ``admission.row_hash`` applied
    to that slot's (cid, sval, data) triple (parity-pinned), with empty
    slots (cid < 0) normalized to ``EMPTY_SLOT_HASH``.  All ops are
    elementwise + one xor reduction per field — the jitted form is a
    single fused kernel over the batch."""
    import jax.numpy as jnp

    from .admission import _mix

    JU64 = jnp.uint64
    cid = jnp.asarray(cid)
    h = jnp.full(cid.shape, JU64(FNV64_OFFSET), JU64)
    for x, salt in ((cid, _SALT_CID), (sval, _SALT_SVAL),
                    (data, _SALT_DATA)):
        x = jnp.asarray(x).astype(JU64).reshape(cid.shape + (-1,))
        idx = jnp.arange(x.shape[-1], dtype=JU64)
        w = _mix(x ^ _mix(idx + JU64(salt)))
        folded = jax_xor_reduce(w)
        h = _mix((h * JU64(FNV64_PRIME)) ^ folded)
    return jnp.where(cid < 0, JU64(EMPTY_SLOT_HASH), h)


def jax_xor_reduce(w):
    """XOR-fold the last axis (jnp.bitwise_xor.reduce is unavailable
    inside jit on some backends; lax.reduce is)."""
    import jax.numpy as jnp
    from jax import lax

    return lax.reduce(w, jnp.uint64(0), lax.bitwise_xor, (w.ndim - 1,))


def call_hashes_host(cid, sval, data) -> np.ndarray:
    """Bit-identical numpy mirror of ``call_hashes`` (the engine's plan
    path runs here — the compacted batch is already host numpy; the
    device version exists for future in-step planning and parity)."""
    from .admission import _mix_host

    cid = np.asarray(cid)
    with np.errstate(over="ignore"):
        h = np.full(cid.shape, U64(FNV64_OFFSET), U64)
        for x, salt in ((cid, _SALT_CID), (sval, _SALT_SVAL),
                        (data, _SALT_DATA)):
            x = np.asarray(x).astype(U64).reshape(cid.shape + (-1,))
            idx = np.arange(x.shape[-1], dtype=U64)
            w = _mix_host(x ^ _mix_host(idx + U64(salt)))
            folded = (np.bitwise_xor.reduce(w, axis=-1) if w.shape[-1]
                      else np.zeros(cid.shape, U64))
            h = _mix_host((h * U64(FNV64_PRIME)) ^ folded)
        return np.where(cid < 0, U64(EMPTY_SLOT_HASH), h)


# ---- chained prefix hashes ----


def prefix_hashes(h):
    """[B, C] u64 slot hashes -> [B, C] u64 chained prefix hashes:
    ``p[c] = mix((p[c-1] * FNV_PRIME) ^ h[c])`` with ``p[-1]`` the FNV
    offset — ``p[b, c]`` identifies the entire slot prefix 0..c, so two
    rows share an executed prefix iff their chained hashes match."""
    import jax.numpy as jnp

    from .admission import _mix

    JU64 = jnp.uint64
    h = jnp.asarray(h, JU64)
    cols = []
    p = jnp.full(h.shape[:-1], JU64(FNV64_OFFSET), JU64)
    for c in range(h.shape[-1]):
        p = _mix((p * JU64(FNV64_PRIME)) ^ h[..., c])
        cols.append(p)
    return jnp.stack(cols, axis=-1)


def prefix_hashes_host(h) -> np.ndarray:
    """Bit-identical numpy mirror of ``prefix_hashes``."""
    from .admission import _mix_host

    h = np.asarray(h, U64)
    out = np.empty_like(h)
    with np.errstate(over="ignore"):
        p = np.full(h.shape[:-1], U64(FNV64_OFFSET), U64)
        for c in range(h.shape[-1]):
            p = _mix_host((p * U64(FNV64_PRIME)) ^ h[..., c])
            out[..., c] = p
    return out


# ---- sort + adjacent-LCP (the inbatch_first_mask style) ----


def sorted_lcp(h):
    """[B, C] u64 -> (order [B] i32, lcp [B] i32) on device:
    lexicographic row order via repeated stable argsorts (last slot
    first — the vectorized radix idiom) and, per adjacent sorted pair,
    the longest common prefix length in slots (cumulative product of
    per-position equality).  ``lcp[0]`` is 0 by convention."""
    import jax.numpy as jnp

    h = jnp.asarray(h)
    B, C = h.shape
    order = jnp.arange(B)
    for c in range(C - 1, -1, -1):
        order = order[jnp.argsort(h[order, c], stable=True)]
    hs = h[order]
    eq = (hs[1:] == hs[:-1]).astype(jnp.int32)
    lcp = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32),
         jnp.sum(jnp.cumprod(eq, axis=1), axis=1, dtype=jnp.int32)])
    return order.astype(jnp.int32), lcp


def sorted_lcp_host(h):
    """Bit-identical numpy mirror of ``sorted_lcp`` (np.lexsort keys
    are last-significant-first, hence the reversed column order)."""
    h = np.asarray(h, U64)
    B, C = h.shape
    if B == 0:
        return (np.zeros(0, np.int32), np.zeros(0, np.int32))
    order = np.lexsort(tuple(h[:, c] for c in range(C - 1, -1, -1)))
    hs = h[order]
    eq = (hs[1:] == hs[:-1]).astype(np.int32)
    lcp = np.concatenate(
        [np.zeros(1, np.int32),
         np.sum(np.cumprod(eq, axis=1), axis=1, dtype=np.int32)])
    return order.astype(np.int32), lcp


# ---- lcp-interval tree -> execution schedule ----


@dataclass
class PrefixNode:
    """One shared-prefix tree node: ``n_calls`` active calls (the ipc
    continuation unit), identified by the chained ``hash`` of its
    ``depth`` slots.  ``parent`` indexes ``PrefixPlan.nodes`` (-1 for a
    root); a node's prefix job continues from the parent's cached
    prefix, paying only the marginal ``n_calls - parent.n_calls``
    calls.  ``carrier`` is the batch row whose stream the prefix job
    executes (any subtree member — they share the prefix by
    construction)."""

    hash: int
    depth: int              # shared prefix length in call SLOTS
    n_calls: int            # shared prefix length in ACTIVE calls
    parent: int = -1
    carrier: int = -1
    rows: List[int] = field(default_factory=list)  # direct members


@dataclass
class PrefixPlan:
    """The batch execution schedule: ``nodes`` topologically ordered
    (every parent precedes its children), ``row_node[row]`` the node
    whose prefix the row's suffix job continues from (rows absent from
    the dict run as ordinary full executions).  ``calls_saved_est`` is
    the scheduling-time estimate: per grouped row its node's prefix
    calls, minus each node's own (marginal) prefix-job cost."""

    nodes: List[PrefixNode] = field(default_factory=list)
    row_node: Dict[int, int] = field(default_factory=dict)
    calls_saved_est: int = 0

    def __bool__(self) -> bool:
        return bool(self.nodes)


def build_plan(cid, sval, data, rows: Optional[Sequence[int]] = None,
               min_group: int = 2, min_calls: int = 1) -> PrefixPlan:
    """Build the prefix-tree schedule over an encoded batch (host path:
    the admission-compacted batch is numpy by the time the drain plans).

    ``rows`` restricts planning to eligible batch rows (the engine
    passes the rows whose exec streams emitted — decode-fallback rows
    can't continue).  Guarantees, pinned by tests/test_prefix.py:

      - laminar tree: every node's member set nests inside its parent's;
      - coverage: each eligible row appears in ``row_node`` at most
        once, and every ``row_node`` target exists with
        ``n_calls >= min_calls`` and >= ``min_group`` total users;
      - reachability: a row's program == its node's prefix (``n_calls``
        active calls) + its own suffix, byte-equal on the hashed fields.
    """
    cid = np.asarray(cid)
    B = cid.shape[0]
    min_group = max(int(min_group), 2)
    min_calls = max(int(min_calls), 1)
    rows = np.asarray(range(B) if rows is None else rows, np.int64)
    if rows.size < min_group:
        return PrefixPlan()
    h = call_hashes_host(cid[rows], np.asarray(sval)[rows],
                         np.asarray(data)[rows])
    ph = prefix_hashes_host(h)
    # active-call running count per slot prefix: n_calls of a depth-d
    # node is active[row, d-1] for any member
    active = np.cumsum(cid[rows] >= 0, axis=1)
    order, lcp = sorted_lcp_host(h)
    n = order.size

    # classic lcp-interval stack sweep (the suffix-array tree
    # construction): emits every maximal interval of sorted rows
    # sharing a prefix depth greater than its surroundings, children
    # before parents (post-order); lcp[n] = 0 is the flush sentinel
    raw: List[tuple] = []  # (depth_slots, left, right) over sorted idx
    stack: List[tuple] = [(0, 0)]  # (depth, left boundary)
    for i in range(1, n + 1):
        cur = int(lcp[i]) if i < n else 0
        lb = i - 1
        while cur < stack[-1][0]:
            d, left = stack.pop()
            raw.append((d, left, i))
            lb = left
        if cur > stack[-1][0]:
            stack.append((cur, lb))
    if not raw:
        return PrefixPlan()

    # parent links: post-order emission of a laminar family means a
    # node's parent is the first later-emitted interval containing it
    parent = [-1] * len(raw)
    for k, (d, l, r) in enumerate(raw):
        for j in range(k + 1, len(raw)):
            _dj, lj, rj = raw[j]
            if lj <= l and r <= rj:
                parent[k] = j
                break

    n_calls_of = [int(active[order[l], d - 1]) if d > 0 else 0
                  for d, l, r in raw]

    # collapse redundant nodes (shallow -> deep, so parents resolve
    # first): below min_calls -> unscheduled; no marginal ACTIVE call
    # over the effective parent -> the parent IS this prefix.  eff[k]
    # is k itself (survives), another node (collapsed into it), or -1.
    eff = [-1] * len(raw)
    by_depth = sorted(range(len(raw)), key=lambda q: raw[q][0])

    def eff_parent(k: int) -> int:
        """Nearest surviving ancestor through raw parent links and
        collapse targets.  Collapse chains are FOLLOWED, not returned:
        a node this one merged into may itself have merged upward later
        (the min_group cascade), so only a node with eff[p] == p — one
        that still stands for itself — is a valid answer."""
        p = parent[k]
        while p >= 0:
            if eff[p] == p:
                return p
            p = eff[p] if eff[p] >= 0 else parent[p]
        return -1

    for k in by_depth:
        pe = eff_parent(k)
        if n_calls_of[k] < min_calls:
            eff[k] = -1
        elif pe >= 0 and n_calls_of[k] == n_calls_of[pe]:
            eff[k] = pe
        else:
            eff[k] = k

    # per sorted position: deepest surviving node covering it
    pos_node = np.full(n, -1, np.int64)
    for k in by_depth:
        if eff[k] == k:
            _d, l, r = raw[k]
            pos_node[l:r] = k
    direct: Dict[int, List[int]] = {}
    for pos in range(n):
        k = int(pos_node[pos])
        if k >= 0:
            direct.setdefault(k, []).append(pos)
    child_nodes: Dict[int, List[int]] = {}
    for k in by_depth:
        if eff[k] == k:
            p = eff_parent(k)
            if p >= 0:
                child_nodes.setdefault(p, []).append(k)

    # users = direct rows + surviving child nodes; a node with fewer
    # than min_group users can't amortize its prefix job -> merge into
    # its parent (deepest first, so merges cascade upward and parents
    # see their final user counts when their turn comes)
    for k in reversed(by_depth):
        if eff[k] != k:
            continue
        users = len(direct.get(k, ())) + len(child_nodes.get(k, ()))
        if users >= min_group:
            continue
        p = eff_parent(k)
        eff[k] = p  # -1 at a root: members become ungrouped
        if p >= 0:
            direct.setdefault(p, []).extend(direct.pop(k, ()))
            child_nodes.setdefault(p, []).extend(child_nodes.pop(k, ()))
        else:
            direct.pop(k, None)
            child_nodes.pop(k, None)

    final = [k for k in by_depth if eff[k] == k]
    if not final:
        return PrefixPlan()

    # emit parents-first (shallow -> deep is a topological order for a
    # laminar family); the carrier is the node's first sorted member —
    # every interval member shares the node's prefix by construction
    node_id = {k: i for i, k in enumerate(final)}
    plan = PrefixPlan()
    for k in final:
        d, l, _r = raw[k]
        p = eff_parent(k)
        plan.nodes.append(PrefixNode(
            hash=int(ph[order[l], d - 1]), depth=d,
            n_calls=n_calls_of[k],
            parent=node_id[p] if p >= 0 else -1,
            carrier=int(rows[order[l]])))
    for k in final:
        nid = node_id[k]
        for pos in direct.get(k, ()):
            row = int(rows[order[pos]])
            plan.nodes[nid].rows.append(row)
            plan.row_node[row] = nid

    # warm-up cost per node = its MARGINAL calls (prefix jobs continue
    # from the parent memo and never execute the prelude — see
    # ipc.MockEnv.exec_prefix); savings = memoized calls per suffix job
    saved = sum(plan.nodes[nid].n_calls for nid in plan.row_node.values())
    cost = sum(nd.n_calls - (plan.nodes[nd.parent].n_calls
                             if nd.parent >= 0 else 0)
               for nd in plan.nodes)
    plan.calls_saved_est = saved - cost
    return plan
