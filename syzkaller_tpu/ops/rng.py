"""Vectorized random-value samplers (threefry lanes).

Device counterparts of the reference's scalar randGen (reference:
/root/reference/prog/rand.go:59-154): the magnitude-biased interesting-int
distribution, the special-values table, quadratic biased choice, and flag
combination sampling — all as shape-polymorphic jax functions suitable for
vmap over thousands of program lanes.

The category chains below reproduce the reference's nested nOutOf(...)
conditionals as single uniform draws with cumulative thresholds (a chain
of conditional n/m branches over disjoint remainders is one categorical).
"""

from __future__ import annotations

from . import ensure_x64  # noqa: F401  (x64 side effect)

import jax
import jax.numpy as jnp

SPECIAL_INTS = jnp.array(
    [0, 1, 31, 32, 63, 64, 127, 128, 129, 255, 256, 257, 511, 512,
     1023, 1024, 1025, 2047, 2048, 4095, 4096,
     (1 << 15) - 1, 1 << 15, (1 << 15) + 1,
     (1 << 16) - 1, 1 << 16, (1 << 16) + 1,
     (1 << 31) - 1, 1 << 31, (1 << 31) + 1,
     (1 << 32) - 1, 1 << 32, (1 << 32) + 1],
    dtype=jnp.uint64,
)


def rand_u64(key, shape=()):
    return jax.random.bits(key, shape, dtype=jnp.uint64)


def rand_int(key, shape=()):
    """Magnitude-biased interesting integers (rand.go:69-93)."""
    k1, k2, k3, k4, k5 = jax.random.split(key, 5)
    v = rand_u64(k1, shape)
    cat = jax.random.randint(k2, shape, 0, 182)
    special = SPECIAL_INTS[jax.random.randint(
        k3, shape, 0, SPECIAL_INTS.shape[0])]
    v = jnp.select(
        [cat < 100, cat < 150, cat < 160, cat < 170, cat < 180],
        [v % 10, special, v % 256, v % (4 << 10), v % (64 << 10)],
        v % (1 << 31),
    )
    cat2 = jax.random.randint(k4, shape, 0, 107)
    shift = jax.random.randint(k5, shape, 0, 63).astype(jnp.uint64)
    v = jnp.select(
        [cat2 < 100, cat2 < 105],
        [v, (-v.astype(jnp.int64)).astype(jnp.uint64)],
        v << shift,
    )
    return v


def rand_range_int(key, lo, hi, shape=()):
    """Uniform in [lo, hi] with a 1/100 escape to rand_int (rand.go:95-100)."""
    k1, k2, k3 = jax.random.split(key, 3)
    lo = jnp.asarray(lo, jnp.uint64)
    hi = jnp.asarray(hi, jnp.uint64)
    raw = rand_u64(k1, shape)
    span = hi - lo + 1  # wraps to 0 for the full u64 range
    u = jnp.where(span == 0, raw, raw % jnp.maximum(span, 1) + lo)
    esc = jax.random.randint(k2, shape, 0, 100) == 0
    return jnp.where(esc, rand_int(k3, shape), u)


def biased_rand(key, n, k, shape=()):
    """Quadratic bias toward n-1: P(n-1) = k * P(0) (rand.go:104-109)."""
    nf = jnp.asarray(n, jnp.float32)
    kf = jnp.asarray(k, jnp.float32)
    rf = nf * (kf / 2 + 1) * jax.random.uniform(key, shape)
    bf = (-1 + jnp.sqrt(1 + 2 * kf * rf / nf)) * nf / kf
    return jnp.clip(bf.astype(jnp.int32), 0, jnp.asarray(n, jnp.int32) - 1)


def sample_flags(key, flags_off, flags_cnt, pool, shape=()):
    """Flag-combination sampler (rand.go:140-154): usually OR of a geometric
    number of set members, sometimes a single member, zero, or garbage.

    flags_off/flags_cnt may be arrays broadcastable to `shape` (each lane can
    sample from a different flag set out of the shared pool)."""
    k1, k2, k3, k4 = jax.random.split(key, 4)
    cnt = jnp.maximum(jnp.asarray(flags_cnt), 1)
    off = jnp.asarray(flags_off)
    # 4 candidate members; member j included with prob 2^-j (geometric OR)
    idx = jax.random.randint(k2, shape + (4,), 0, 1 << 30) % cnt[..., None]
    vals = pool[off[..., None] + idx]
    include = jax.random.uniform(k3, shape + (4,)) < jnp.array(
        [1.0, 0.5, 0.25, 0.125])
    ored = jnp.where(include, vals, 0).reshape(shape + (4,))
    ored = jnp.bitwise_or.reduce(ored, axis=-1)
    single = vals[..., 0]
    cat = jax.random.randint(k1, shape, 0, 111)
    garbage = rand_u64(k4, shape)
    return jnp.select(
        [cat < 90, cat < 100, cat < 110],
        [ored, single, jnp.zeros_like(garbage)],
        garbage,
    )


def choose_weighted(key, cumsum_row):
    """Sample an index from an int cumulative-weight row (prio.go:231-247:
    uniform in [0, total) then binary search)."""
    total = cumsum_row[-1]
    x = jax.random.randint(key, (), 0, jnp.maximum(total, 1),
                           dtype=cumsum_row.dtype)
    return jnp.searchsorted(cumsum_row, x, side="right").astype(jnp.int32)


def pick_masked(key, mask):
    """Uniformly pick an index where mask is true (-1 if none)."""
    u = jax.random.uniform(key, mask.shape)
    score = jnp.where(mask, u, -1.0)
    idx = jnp.argmax(score)
    return jnp.where(jnp.any(mask), idx.astype(jnp.int32), -1)
