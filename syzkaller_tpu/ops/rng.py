"""Vectorized random-value samplers (threefry lanes).

Device counterparts of the reference's scalar randGen (reference:
/root/reference/prog/rand.go:59-154): the magnitude-biased interesting-int
distribution, the special-values table, quadratic biased choice, and flag
combination sampling — all as shape-polymorphic jax functions suitable for
vmap over thousands of program lanes.

The category chains below reproduce the reference's nested nOutOf(...)
conditionals as single uniform draws with cumulative thresholds (a chain
of conditional n/m branches over disjoint remainders is one categorical).

Compile-cost note: every `jax.random.*` call expands a full threefry hash
into the HLO, which is expensive to codegen (minutes on single-core dev
hosts).  Each sampler therefore draws ONE pooled `bits` tensor with a
trailing lane axis and derives all of its sub-draws from pool words with
cheap arithmetic — one hash per sampler instead of one per draw.
"""

from __future__ import annotations

from . import ensure_x64  # noqa: F401  (x64 side effect)

import jax
import jax.numpy as jnp

SPECIAL_INTS = jnp.array(
    [0, 1, 31, 32, 63, 64, 127, 128, 129, 255, 256, 257, 511, 512,
     1023, 1024, 1025, 2047, 2048, 4095, 4096,
     (1 << 15) - 1, 1 << 15, (1 << 15) + 1,
     (1 << 16) - 1, 1 << 16, (1 << 16) + 1,
     (1 << 31) - 1, 1 << 31, (1 << 31) + 1,
     (1 << 32) - 1, 1 << 32, (1 << 32) + 1],
    dtype=jnp.uint64,
)

U64 = jnp.uint64


def randpool(key, shape=(), n=1):
    """One threefry expansion yielding n u64 words per lane: [*shape, n]."""
    return jax.random.bits(key, tuple(shape) + (n,), dtype=jnp.uint64)


def _mod(w, n):
    """Uniform-ish int in [0, n) from a pool word."""
    return (w % U64(n)).astype(jnp.int32)


def _unit(w):
    """Uniform float in [0, 1) from a pool word's top 24 bits."""
    return (w >> U64(40)).astype(jnp.float32) * jnp.float32(2.0 ** -24)


def rand_u64(key, shape=()):
    return jax.random.bits(key, shape, dtype=jnp.uint64)


def rand_int_from(pool):
    """Magnitude-biased interesting integers (rand.go:69-93) from a
    >=5-word pool slice [..., 5]."""
    v = pool[..., 0]
    cat = _mod(pool[..., 1], 182)
    special = SPECIAL_INTS[_mod(pool[..., 2], SPECIAL_INTS.shape[0])]
    v = jnp.select(
        [cat < 100, cat < 150, cat < 160, cat < 170, cat < 180],
        [v % U64(10), special, v % U64(256), v % U64(4 << 10),
         v % U64(64 << 10)],
        v % U64(1 << 31),
    )
    cat2 = _mod(pool[..., 3], 107)
    shift = _mod(pool[..., 4], 63).astype(U64)
    return jnp.select(
        [cat2 < 100, cat2 < 105],
        [v, (-v.astype(jnp.int64)).astype(U64)],
        v << shift,
    )


RAND_INT_WORDS = 5


def rand_int(key, shape=()):
    return rand_int_from(randpool(key, shape, RAND_INT_WORDS))


def rand_range_int_from(pool, lo, hi):
    """Uniform in [lo, hi] with a 1/100 escape to rand_int (rand.go:95-100)
    from a >=7-word pool slice."""
    lo = jnp.asarray(lo, U64)
    hi = jnp.asarray(hi, U64)
    raw = pool[..., 0]
    span = hi - lo + U64(1)  # wraps to 0 for the full u64 range
    u = jnp.where(span == U64(0), raw, raw % jnp.maximum(span, U64(1)) + lo)
    esc = _mod(pool[..., 1], 100) == 0
    return jnp.where(esc, rand_int_from(pool[..., 2:7]), u)


RAND_RANGE_WORDS = 7


def rand_range_int(key, lo, hi, shape=()):
    return rand_range_int_from(randpool(key, shape, RAND_RANGE_WORDS), lo, hi)


def biased_rand_from(word, n, k):
    """Quadratic bias toward n-1: P(n-1) = k * P(0) (rand.go:104-109)."""
    nf = jnp.asarray(n, jnp.float32)
    kf = jnp.asarray(k, jnp.float32)
    rf = nf * (kf / 2 + 1) * _unit(word)
    bf = (-1 + jnp.sqrt(1 + 2 * kf * rf / nf)) * nf / kf
    return jnp.clip(bf.astype(jnp.int32), 0, jnp.asarray(n, jnp.int32) - 1)


def biased_rand(key, n, k, shape=()):
    return biased_rand_from(randpool(key, shape, 1)[..., 0], n, k)


def sample_flags_from(pool, flags_off, flags_cnt, flag_pool):
    """Flag-combination sampler (rand.go:140-154) from a >=10-word pool
    slice: usually OR of a geometric number of set members, sometimes a
    single member, zero, or garbage.

    flags_off/flags_cnt may be arrays broadcastable to the pool's leading
    shape (each lane samples from its own flag set in the shared pool)."""
    cnt = jnp.maximum(jnp.asarray(flags_cnt), 1).astype(U64)
    off = jnp.asarray(flags_off)
    # 4 candidate members; member j included with prob 2^-j (geometric OR)
    idx = (pool[..., 0:4] % cnt[..., None]).astype(jnp.int32)
    vals = flag_pool[off[..., None] + idx]
    thresh = jnp.array([256, 128, 64, 32], dtype=U64)
    include = (pool[..., 4:8] & U64(0xFF)) < thresh
    ored = jnp.where(include, vals, U64(0))
    ored = jnp.bitwise_or.reduce(ored, axis=-1)
    single = vals[..., 0]
    cat = _mod(pool[..., 8], 111)
    garbage = pool[..., 9]
    return jnp.select(
        [cat < 90, cat < 100, cat < 110],
        [ored, single, jnp.zeros_like(garbage)],
        garbage,
    )


SAMPLE_FLAGS_WORDS = 10


def sample_flags(key, flags_off, flags_cnt, pool, shape=()):
    return sample_flags_from(randpool(key, shape, SAMPLE_FLAGS_WORDS),
                             flags_off, flags_cnt, pool)


def choose_weighted_from(word, cumsum_row):
    """Sample an index from an int cumulative-weight row (prio.go:231-247:
    uniform in [0, total) then binary search)."""
    total = jnp.maximum(cumsum_row[-1], 1).astype(U64)
    x = (word % total).astype(cumsum_row.dtype)
    return jnp.searchsorted(cumsum_row, x, side="right").astype(jnp.int32)


def choose_weighted(key, cumsum_row):
    return choose_weighted_from(randpool(key, (), 1)[..., 0], cumsum_row)


def pick_masked_from(pool, mask):
    """Uniformly pick an index where mask is true (-1 if none) from a pool
    [..., mask.shape[-1]] of u64 words."""
    score = jnp.where(mask, pool, U64(0))
    idx = jnp.argmax(score, axis=-1)
    return jnp.where(jnp.any(mask, axis=-1), idx.astype(jnp.int32), -1)


def pick_masked(key, mask):
    return pick_masked_from(randpool(key, mask.shape[:-1],
                                     mask.shape[-1]), mask)
