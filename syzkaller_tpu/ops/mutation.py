"""Batched device mutation + generation over program tensors.

The TPU replacement for the reference's per-program tree mutator
(reference: /root/reference/prog/mutation.go:12-250) and generator
(prog/generation.go, prog/rand.go:440-476): one vmapped kernel applies a
weighted mix of
  - corpus splice        (donor program prefix, reference 1/100)
  - call insertion       (tail-biased position, choice-table weighted
                          syscall, template defaults + sampled values,
                          resource refs resolved to the latest compatible
                          producing call)
  - value mutation       (+-delta / bitflip / type-directed resample)
  - data mutation        (byte ops + length changes inside the call arena)
  - call removal         (with REF index remapping)
to every program lane in parallel. Slot semantics (which slots are values /
refs / data) come from the dense device tables; nothing walks a tree.

LEN slots are not maintained on device: the host decode path recomputes
them (assign_sizes_call) before execution, mirroring the reference's
assignSizesCall-after-mutation.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

from . import ensure_x64  # noqa: F401

import jax
import jax.numpy as jnp

from ..descriptions.tables import (
    SK_DATA,
    SK_REF,
    SK_VALUE,
    TK_FLAGS,
    TK_INT,
    TK_PROC,
)
from ..prog.tensor import REF_NONE
from .dtables import DeviceTables
from .rng import (
    RAND_INT_WORDS,
    RAND_RANGE_WORDS,
    SAMPLE_FLAGS_WORDS,
    biased_rand_from,
    choose_weighted_from,
    pick_masked_from,
    rand_int_from,
    rand_range_int_from,
    randpool,
    sample_flags_from,
)

U64 = jnp.uint64
Row = Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]  # cid [C], sval [C,S], data [C,D]

REF_NONE_U = U64(REF_NONE)


def _safe(cid):
    return jnp.maximum(cid, 0)


def _live(cid):
    return cid >= 0


def _slot_index_mask(dt: DeviceTables, cid):
    """[C, S] mask of slots that exist for each live call."""
    S = dt.max_slots
    scnt = dt.slot_cnt[_safe(cid)]
    return _live(cid)[:, None] & (jnp.arange(S)[None, :] < scnt[:, None])


# ---------------------------------------------------------------------- #
# value mutation


def value_mutate(key, dt: DeviceTables, row: Row) -> Row:
    cid, sval, data = row
    CS = cid.shape[0] * dt.max_slots
    # one threefry expansion covers the pick lanes + every scalar sub-draw
    pool = randpool(key, (), CS + 4 + RAND_RANGE_WORDS + SAMPLE_FLAGS_WORDS)
    w = pool[CS:]
    sc = _safe(cid)
    kind = dt.slot_kind[sc]
    tk = dt.slot_tkind[sc]
    mutable = _slot_index_mask(dt, cid) & (kind == SK_VALUE) & (
        (tk == TK_INT) | (tk == TK_FLAGS) | (tk == TK_PROC))
    flat = pick_masked_from(pool[:CS], mutable.reshape(-1))
    ok = flat >= 0
    flat_s = jnp.maximum(flat, 0)
    c, s = flat_s // dt.max_slots, flat_s % dt.max_slots

    cur = sval[c, s]
    size = dt.slot_size[sc][c, s]
    bits = jnp.maximum(size * 8, 1).astype(U64)
    vmask = jnp.where(size >= 8, U64(0xFFFFFFFFFFFFFFFF),
                      (U64(1) << bits) - U64(1))

    delta = (w[0] % U64(4)) + U64(1)
    bit = w[1] % bits
    this_tk = tk[c, s]
    lo, hi = dt.slot_lo[sc][c, s], dt.slot_hi[sc][c, s]
    rr = w[4:4 + RAND_RANGE_WORDS]
    resample_int = jnp.where(lo < hi, rand_range_int_from(rr, lo, hi),
                             rand_int_from(rr[2:2 + RAND_INT_WORDS]))
    resample_flags = sample_flags_from(
        w[4 + RAND_RANGE_WORDS:4 + RAND_RANGE_WORDS + SAMPLE_FLAGS_WORDS],
        dt.slot_flags_off[sc][c, s],
        dt.slot_flags_cnt[sc][c, s], dt.flags_pool)
    resample_proc = w[2] % jnp.maximum(hi, U64(1))
    resample = jnp.select(
        [this_tk == TK_FLAGS, this_tk == TK_PROC],
        [resample_flags, resample_proc], resample_int)

    op = (w[3] % U64(4)).astype(jnp.int32)
    nv = jnp.select(
        [op == 0, op == 1, op == 2],
        [cur + delta, cur - delta, cur ^ (U64(1) << bit)],
        resample) & vmask
    sval = sval.at[c, s].set(jnp.where(ok, nv, cur))
    return cid, sval, data


# ---------------------------------------------------------------------- #
# data (byte-arena) mutation


def data_mutate(key, dt: DeviceTables, row: Row) -> Row:
    cid, sval, data = row
    CS = cid.shape[0] * dt.max_slots
    pool = randpool(key, (), CS + 7 + RAND_INT_WORDS)
    w = pool[CS:]
    sc = _safe(cid)
    kind = dt.slot_kind[sc]
    mutable = _slot_index_mask(dt, cid) & (kind == SK_DATA)
    flat = pick_masked_from(pool[:CS], mutable.reshape(-1))
    ok = flat >= 0
    flat_s = jnp.maximum(flat, 0)
    c, s = flat_s // dt.max_slots, flat_s % dt.max_slots

    aoff = dt.slot_arena_off[sc][c, s]
    cap = dt.slot_size[sc][c, s]
    lo = dt.slot_lo[sc][c, s].astype(jnp.int32)
    ln = jnp.minimum(sval[c, s].astype(jnp.int32), cap)

    op = (w[0] % U64(6)).astype(jnp.int32)
    pos = aoff + (w[1] % jnp.maximum(ln, 1).astype(U64)).astype(jnp.int32)
    pos = jnp.clip(pos, 0, dt.arena - 1)
    cur_byte = data[c, pos].astype(jnp.int32)
    rb = (w[2] % U64(256)).astype(jnp.int32)
    interesting = (rand_int_from(w[7:7 + RAND_INT_WORDS]) &
                   U64(0xFF)).astype(jnp.int32)
    delta = ((w[3] % U64(71)).astype(jnp.int32) - 35) | 1
    new_byte = jnp.select(
        [op == 0, op == 1, op == 2, op == 3],
        [rb,
         cur_byte ^ (1 << (w[4] % U64(8)).astype(jnp.int32)),
         interesting,
         (cur_byte + delta) & 0xFF],
        cur_byte) & 0xFF
    byte_ok = ok & (op < 4) & (ln > 0) & (aoff >= 0)
    data = data.at[c, pos].set(
        jnp.where(byte_ok, new_byte, cur_byte).astype(jnp.uint8))

    grow = jnp.minimum(ln + 1 + (w[5] % U64(8)).astype(jnp.int32), cap)
    shrink = jnp.maximum(ln - 1 - (w[6] % U64(8)).astype(jnp.int32), lo)
    new_len = jnp.select([op == 4, op == 5], [grow, shrink], ln)
    new_len = jnp.clip(new_len, jnp.minimum(lo, cap), cap)
    sval = sval.at[c, s].set(
        jnp.where(ok, new_len.astype(U64), sval[c, s]))
    return cid, sval, data


# ---------------------------------------------------------------------- #
# call removal


def _fix_refs_after_remove(dt, cid, sval, removed):
    sc = _safe(cid)
    is_ref = (dt.slot_kind[sc] == SK_REF) & _slot_index_mask(dt, cid)
    v = sval
    removed_u = removed.astype(U64)
    v2 = jnp.where(v == removed_u, REF_NONE_U,
                   jnp.where((v != REF_NONE_U) & (v > removed_u),
                             v - U64(1), v))
    return jnp.where(is_ref, v2, v)


def remove_call(key, dt: DeviceTables, row: Row) -> Row:
    cid, sval, data = row
    C = cid.shape[0]
    nlive = jnp.sum(_live(cid))
    ok = nlive > 0
    c = (randpool(key, (), 1)[0] %
         jnp.maximum(nlive, 1).astype(U64)).astype(jnp.int32)
    idxs = jnp.where(jnp.arange(C) >= c, jnp.arange(C) + 1, jnp.arange(C))
    idxs = jnp.minimum(idxs, C - 1)
    new_cid = jnp.where(jnp.arange(C) == C - 1, -1, cid[idxs])
    new_sval = sval[idxs]
    new_data = data[idxs]
    new_sval = _fix_refs_after_remove(dt, new_cid, new_sval, c)
    return (jnp.where(ok, new_cid, cid),
            jnp.where(ok, new_sval, sval),
            jnp.where(ok, new_data, data))


# ---------------------------------------------------------------------- #
# call insertion (also the generation primitive)


def _new_call_row(key, dt: DeviceTables, new_id, cid, pos):
    """Template defaults + sampled values + resolved refs for one new call."""
    sval = _sample_values(key, dt, new_id)
    arena = dt.default_arena[new_id]
    kind = dt.slot_kind[new_id]

    # resolve resource refs: latest earlier live call producing a
    # compatible kind
    C = cid.shape[0]
    want = dt.slot_res_kind[new_id]                      # [S]
    prod = dt.produces_compat[_safe(cid)]                # [C, R]
    avail = prod[:, jnp.maximum(want, 0)] > 0            # [C, S]
    earlier = (_live(cid) & (jnp.arange(C) < pos))[:, None]
    cand = jnp.where(avail & earlier, jnp.arange(C)[:, None], -1)
    latest = cand.max(axis=0)                            # [S]
    ref_val = jnp.where(latest >= 0, latest.astype(U64), REF_NONE_U)
    sval = jnp.where((kind == SK_REF) & (want >= 0), ref_val, sval)
    return sval, arena


def insert_call(key, dt: DeviceTables, row: Row, pos=None, new_id=None) -> Row:
    cid, sval, data = row
    C = cid.shape[0]
    kw, krow = jax.random.split(key)
    w = randpool(kw, (), 4)
    nlive = jnp.sum(_live(cid))
    ok = nlive < C
    if pos is None:
        pos = biased_rand_from(w[0], nlive + 1, 5)
    pos = jnp.asarray(pos, jnp.int32)

    if new_id is None:
        # bias toward a random existing call's row of the choice table
        bias_idx = (w[1] % jnp.maximum(nlive, 1).astype(U64)).astype(jnp.int32)
        bias_call = cid[jnp.minimum(bias_idx, C - 1)]
        have_bias = (nlive > 0) & (bias_call >= 0)
        row_w = dt.choice_run[_safe(bias_call)]
        weighted = choose_weighted_from(w[2], row_w)
        uniform = choose_weighted_from(w[3], dt.enabled_run)
        new_id = jnp.where(have_bias & (row_w[-1] > 0), weighted, uniform)
    new_id = jnp.asarray(new_id, jnp.int32)

    new_sval_row, new_data_row = _new_call_row(krow, dt, new_id, cid, pos)

    # shift right at pos
    ar = jnp.arange(C)
    src = jnp.maximum(ar - 1, 0)
    shifted_cid = jnp.where(ar > pos, cid[src], cid)
    shifted_cid = jnp.where(ar == pos, new_id, shifted_cid)
    shifted_sval = jnp.where((ar > pos)[:, None], sval[src], sval)
    shifted_sval = jnp.where((ar == pos)[:, None], new_sval_row, shifted_sval)
    shifted_data = jnp.where((ar > pos)[:, None], data[src], data)
    shifted_data = jnp.where((ar == pos)[:, None], new_data_row, shifted_data)

    # refs pointing at calls >= pos move up by one (dropped off the end ->
    # REF_NONE); the new call's own refs were built post-shift already
    sc = _safe(shifted_cid)
    is_ref = (dt.slot_kind[sc] == SK_REF) & _slot_index_mask(dt, shifted_cid)
    is_new_row = (ar == pos)[:, None]
    v = shifted_sval
    moved = jnp.where((v != REF_NONE_U) & (v >= pos.astype(U64)),
                      v + U64(1), v)
    moved = jnp.where(moved >= U64(C), REF_NONE_U, moved)
    fixed = jnp.where(is_ref & ~is_new_row, moved, v)
    shifted_sval = fixed

    return (jnp.where(ok, shifted_cid, cid),
            jnp.where(ok, shifted_sval, sval),
            jnp.where(ok, shifted_data, data))


# ---------------------------------------------------------------------- #
# corpus splice


def splice(key, dt: DeviceTables, row: Row, donor: Row) -> Row:
    cid, sval, data = row
    dcid, dsval, ddata = donor
    C = cid.shape[0]
    # clamp the spliced prefix to the donor's live-call count so the result
    # keeps the contiguous-live-prefix invariant REF decoding relies on
    dlive = jnp.sum(_live(dcid))
    k = jnp.minimum(1 + (randpool(key, (), 1)[0] %
                         U64(max(C // 2, 1))).astype(jnp.int32), dlive)
    ar = jnp.arange(C)
    take_donor = ar < k
    src_own = jnp.maximum(ar - k, 0)
    new_cid = jnp.where(take_donor, dcid, cid[src_own])
    new_sval = jnp.where(take_donor[:, None], dsval, sval[src_own])
    new_data = jnp.where(take_donor[:, None], ddata, data[src_own])

    # donor refs into beyond-prefix calls are dangling; own refs shift by k
    sc = _safe(new_cid)
    is_ref = (dt.slot_kind[sc] == SK_REF) & _slot_index_mask(dt, new_cid)
    v = new_sval
    donor_v = jnp.where((v != REF_NONE_U) & (v >= k.astype(U64)),
                        REF_NONE_U, v)
    own_v = jnp.where(v != REF_NONE_U, v + k.astype(U64), v)
    own_v = jnp.where(own_v >= U64(C), REF_NONE_U, own_v)
    fixed = jnp.where(take_donor[:, None], donor_v, own_v)
    new_sval = jnp.where(is_ref, fixed, new_sval)
    ok = dlive > 0
    return (jnp.where(ok, new_cid, cid),
            jnp.where(ok, new_sval, sval),
            jnp.where(ok, new_data, data))


# ---------------------------------------------------------------------- #
# top-level mutate / generate


def mutate_program(key, dt: DeviceTables, row: Row, donor: Row,
                   rounds: int = 2) -> Row:
    """Apply `rounds` weighted mutation ops to one program lane."""

    def one(i, carry):
        row, key = carry
        key, kop, kapply = jax.random.split(key, 3)
        # weights ~ reference mix: splice 1, insert 44, value 35, data 10,
        # remove 10 (out of 100)
        r = (randpool(kop, (), 1)[0] % U64(100)).astype(jnp.int32)
        op = jnp.select([r < 1, r < 45, r < 80, r < 90],
                        [0, 1, 2, 3], 4)
        row = jax.lax.switch(
            op,
            [lambda a: splice(kapply, dt, a, donor),
             lambda a: insert_call(kapply, dt, a),
             lambda a: value_mutate(kapply, dt, a),
             lambda a: data_mutate(kapply, dt, a),
             lambda a: remove_call(kapply, dt, a)],
            row)
        return row, key

    row, _ = jax.lax.fori_loop(0, rounds, one, (row, key))
    return row


def mutate_rows(key, dt: DeviceTables, call_id, slot_val, data,
                rounds: int = 2):
    """Unjitted vmapped batch mutation with iid per-lane op choice —
    the semantic reference implementation, kept for tests and for
    comparing against mutate_rows_stratified (the production hot path in
    mutate_batch / parallel/mesh.py / bench.py)."""
    B = call_id.shape[0]
    keys = jax.random.split(key, B)
    donor = (jnp.roll(call_id, 1, axis=0),
             jnp.roll(slot_val, 1, axis=0),
             jnp.roll(data, 1, axis=0))

    def per(key, cid, sval, dat, dcid, dsval, ddat):
        return mutate_program(key, dt, (cid, sval, dat),
                              (dcid, dsval, ddat), rounds)

    return jax.vmap(per)(keys, call_id, slot_val, data, *donor)


# Stratified variant: under vmap, lax.switch lowers to select and EVERY
# lane pays for ALL five op bodies.  Here each op gets a static slice of
# the batch (sizes ~ the reference's op mix) and a fresh random lane
# permutation each round mixes programs across ops — stratified rather
# than iid op assignment, with each op body running on only its share of
# the batch.  The op indices are the attribution ledger's operator index
# space — imported, not redefined, so a reorder there cannot silently
# miscredit device-lane provenance (the host mutator imports them the
# same way in prog/mutation.py).
from ..telemetry.attribution import (  # noqa: E402
    OP_DATA,
    OP_INSERT,
    OP_REMOVE,
    OP_SPLICE,
    OP_VALUE,
)

_OP_MIX = (  # (op, weight%)
    (OP_SPLICE, 1),
    (OP_INSERT, 44),
    (OP_VALUE, 35),
    (OP_DATA, 10),
    (OP_REMOVE, 10),
)


def _op_slices(B: int):
    """Largest-remainder allocation; every op keeps >=1 lane when the
    batch allows (small shards must not silently lose splicing)."""
    total = sum(w for _, w in _OP_MIX)
    raw = [(B * w) / total for _, w in _OP_MIX]
    sizes = [int(r) for r in raw]
    if B >= len(_OP_MIX):
        for i in range(len(sizes)):
            if sizes[i] == 0:
                sizes[i] = 1
    # settle the remainder on the ops with the largest fractional parts
    while sum(sizes) > B:
        sizes[max(range(len(sizes)), key=lambda i: sizes[i])] -= 1
    rema = sorted(range(len(sizes)), key=lambda i: raw[i] - int(raw[i]),
                  reverse=True)
    j = 0
    while sum(sizes) < B:
        sizes[rema[j % len(rema)]] += 1
        j += 1
    out = []
    off = 0
    for n in sizes:
        out.append((off, n))
        off += n
    return out


def mutate_rows_stratified(key, dt: DeviceTables, call_id, slot_val,
                           data, rounds: int = 2):
    cid, sval, dat, _ = mutate_rows_stratified_traced(
        key, dt, call_id, slot_val, data, rounds)
    return cid, sval, dat


def mutate_rows_stratified_traced(key, dt: DeviceTables, call_id, slot_val,
                                  data, rounds: int = 2):
    """Stratified batch mutation that also returns per-lane provenance:
    an extra ``op_mask`` [B] uint32 output with bit i set iff operator i
    (the _OP_MIX index order: splice / insert / value / data / remove)
    touched that lane in any round.  The mask permutes with its lane, so
    the engine's attribution ledger can credit the operators that
    produced each candidate (telemetry.attribution.ops_from_mask)."""
    B = call_id.shape[0]

    ops = {
        OP_SPLICE: lambda k, row, dn: splice(k, dt, row, dn),
        OP_INSERT: lambda k, row, dn: insert_call(k, dt, row),
        OP_VALUE: lambda k, row, dn: value_mutate(k, dt, row),
        OP_DATA: lambda k, row, dn: data_mutate(k, dt, row),
        OP_REMOVE: lambda k, row, dn: remove_call(k, dt, row),
    }
    slices = _op_slices(B)

    def one_round(carry, rkey):
        cid, sval, dat, opm = carry
        kperm, kops = jax.random.split(rkey)
        perm = jax.random.permutation(kperm, B)
        cid, sval, dat, opm = cid[perm], sval[perm], dat[perm], opm[perm]
        donor = (jnp.roll(cid, 1, axis=0), jnp.roll(sval, 1, axis=0),
                 jnp.roll(dat, 1, axis=0))
        outs = []
        bits = []
        for (op_i, _w), (off, n), kop in zip(
                _OP_MIX, slices, jax.random.split(kops, len(ops))):
            if n == 0:
                continue
            sl = slice(off, off + n)
            keys = jax.random.split(kop, n)
            out = jax.vmap(ops[op_i])(
                keys, (cid[sl], sval[sl], dat[sl]),
                (donor[0][sl], donor[1][sl], donor[2][sl]))
            outs.append(out)
            bits.append(opm[sl] | jnp.uint32(1 << op_i))
        cid = jnp.concatenate([o[0] for o in outs])
        sval = jnp.concatenate([o[1] for o in outs])
        dat = jnp.concatenate([o[2] for o in outs])
        opm = jnp.concatenate(bits)
        return (cid, sval, dat, opm), None

    (cid, sval, dat, opm), _ = jax.lax.scan(
        one_round, (call_id, slot_val, data, jnp.zeros(B, jnp.uint32)),
        jax.random.split(key, rounds))
    return cid, sval, dat, opm


@partial(jax.jit, static_argnames=("rounds",))
def mutate_batch(key, dt: DeviceTables, call_id, slot_val, data,
                 rounds: int = 2):
    # stratified assignment beats per-lane switch under vmap (every lane
    # would otherwise execute all five op bodies)
    return mutate_rows_stratified(key, dt, call_id, slot_val, data,
                                  rounds)


def _sample_values(key, dt: DeviceTables, ids):
    """Sampled slot values for calls `ids` (any leading shape + [S])."""
    shape = ids.shape + (dt.max_slots,)
    pool = randpool(key, shape, RAND_RANGE_WORDS + SAMPLE_FLAGS_WORDS + 1)
    tk = dt.slot_tkind[ids]
    lo, hi = dt.slot_lo[ids], dt.slot_hi[ids]
    rr = pool[..., :RAND_RANGE_WORDS]
    ints = jnp.where(lo < hi, rand_range_int_from(rr, lo, hi),
                     rand_int_from(rr[..., 2:2 + RAND_INT_WORDS]))
    flags = sample_flags_from(
        pool[..., RAND_RANGE_WORDS:RAND_RANGE_WORDS + SAMPLE_FLAGS_WORDS],
        dt.slot_flags_off[ids], dt.slot_flags_cnt[ids], dt.flags_pool)
    procs = pool[..., -1] % jnp.maximum(hi, U64(1))
    sampled = jnp.select([tk == TK_FLAGS, tk == TK_PROC], [flags, procs],
                         ints)
    size = dt.slot_size[ids]
    bits = jnp.maximum(size * 8, 1).astype(U64)
    vmask = jnp.where(size >= 8, U64(0xFFFFFFFFFFFFFFFF),
                      (U64(1) << bits) - U64(1))
    is_value = (dt.slot_kind[ids] == SK_VALUE) & (
        (tk == TK_INT) | (tk == TK_FLAGS) | (tk == TK_PROC))
    return jnp.where(is_value, sampled & vmask, dt.default_slot_val[ids])


def generate_program(key, dt: DeviceTables, C: int, ncalls) -> Row:
    """One program: sequential choice-table id chain + vectorized rows.

    Call ids follow the reference's biased walk (each call chosen from the
    previous call's priority row); values are template defaults + sampled;
    resource refs point at the most recent earlier compatible producer."""
    kid, ku, kv = jax.random.split(key, 3)

    # --- id chain: scan over C (pool drawn once outside the scan) ---
    def id_step(prev_id, w):
        row = dt.choice_run[_safe(prev_id)]
        weighted = choose_weighted_from(w[0], row)
        uniform = choose_weighted_from(w[1], dt.enabled_run)  # enabled only
        nid = jnp.where((prev_id >= 0) & (row[-1] > 0), weighted,
                        uniform).astype(jnp.int32)
        return nid, nid

    _, ids = jax.lax.scan(id_step, jnp.int32(-1), randpool(kid, (C,), 2))
    ids = jnp.asarray(ids, jnp.int32)
    cid = jnp.where(jnp.arange(C) < ncalls, ids, -1)
    sids = _safe(cid)

    # --- values ---
    sval = _sample_values(kv, dt, sids)
    data = dt.default_arena[sids]

    # --- resource refs: last earlier producer per kind (running max) ---
    prod = (dt.produces_compat[sids] > 0) & _live(cid)[:, None]  # [C, R]
    idx = jnp.where(prod, jnp.arange(C)[:, None], -1)
    # last_before[c, k] = max_{j < c} idx[j, k]
    run_max = jax.lax.associative_scan(jnp.maximum, idx, axis=0)
    last_before = jnp.concatenate(
        [jnp.full((1, idx.shape[1]), -1, idx.dtype), run_max[:-1]], axis=0)
    want = dt.slot_res_kind[sids]                       # [C, S]
    ref = jnp.take_along_axis(last_before, jnp.maximum(want, 0),
                              axis=1)                   # [C, S]
    ref_val = jnp.where(ref >= 0, ref.astype(U64), REF_NONE_U)
    is_ref = (dt.slot_kind[sids] == SK_REF) & (want >= 0)
    sval = jnp.where(is_ref, ref_val, sval)

    sval = jnp.where(_live(cid)[:, None], sval, U64(0))
    data = jnp.where(_live(cid)[:, None], data, jnp.uint8(0))
    return cid, sval, data


def generate_rows(key, dt: DeviceTables, *, B: int, C: int):
    """Unjitted batched generation body (shared with parallel/mesh.py)."""
    kn, kg = jax.random.split(key)
    ncalls = 1 + (randpool(kn, (B,), 1)[..., 0] % U64(C)).astype(jnp.int32)
    keys = jax.random.split(kg, B)
    return jax.vmap(lambda k, n: generate_program(k, dt, C, n))(keys, ncalls)


@partial(jax.jit, static_argnames=("B", "C"))
def generate_batch(key, dt: DeviceTables, *, B: int, C: int):
    return generate_rows(key, dt, B=B, C=C)
