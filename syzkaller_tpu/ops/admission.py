"""Device-side candidate admission: hash, dedup, and Bloom-filter the
mutated batch BEFORE the host pays an executor round-trip.

BENCH_PR3_post put 64% of the e2e wall time in the executor drain at
~25ms per host exec, while the device dispatch is milliseconds — yet
program dedup happened only *after* execution, on the host at
triage-add time (``hash_str(serialize(p))``).  Every duplicate or no-op
mutation the TPU emitted still burned a full round-trip.  This module is
the memoization move from the mutation-analysis literature ("Toward
Speeding up Mutation Analysis by Memoizing Expensive Methods",
arXiv:2102.11559; "Faster Mutation Analysis via Equivalence Modulo
States", arXiv:1702.06689) applied at the host↔device boundary: identify
redundant candidates with device arithmetic, so CPU envs only ever
execute novel ones.

Three pieces, all jit/vmap-friendly:

  - ``row_hash`` — a 64-bit FNV/xor-fold hash over one encoded program
    row ``(cid, sval, data)``: each field's words are avalanche-mixed
    against their position (so permutations change the hash), xor-folded
    to one word, and FNV-chained across fields.  ``row_hash_host`` is
    the bit-identical numpy reference (parity-pinned by tests).
  - ``inbatch_first_mask`` — in-batch duplicate masking via
    sort-and-compare over the gathered ``[B]`` hash vector: exactly one
    row per distinct hash keeps True.
  - ``bloom_probes`` / ``bloom_test`` / ``bloom_add`` — a device-resident
    recent-hash Bloom bitset reusing the ``ops/cover.py`` packed-bitset
    machinery (``bitset_test`` / ``bitset_add``); ``k`` probe positions
    per hash via the Kirsch–Mitzenmacher double-hash ``lo + i*hi``.  The
    filter decays by periodic reset (the engine zeroes it past a target
    occupancy), trading a bounded false-positive rate — a fresh
    candidate occasionally skipped, never a lost *corpus* entry, since
    exact dedup still runs at triage-add — for O(1) memory.

The sharded (word-range over the ``cover`` axis) counterpart of the
Bloom test/update lives in ``parallel/mesh.fold_admission``, next to the
signal-bitset collectives it mirrors.
"""

from __future__ import annotations

from . import ensure_x64  # noqa: F401

import jax
import jax.numpy as jnp
import numpy as np

from . import cover

U32 = jnp.uint32
U64 = jnp.uint64

# splitmix64 finalizer constants (same family as mesh.call_fingerprints)
_M1 = 0xBF58476D1CE4E5B9
_M2 = 0x94D049BB133111EB
FNV64_OFFSET = 0xCBF29CE484222325
FNV64_PRIME = 0x100000001B3

# per-field domain-separation salts (field order must not commute)
_SALT_CID = 0x9E3779B97F4A7C15
_SALT_SVAL = 0xC2B2AE3D27D4EB4F
_SALT_DATA = 0x165667B19E3779F9

BLOOM_PROBES = 4  # k hash functions; FP rate ~ occupancy**k
DEFAULT_BLOOM_BITS = 1 << 20  # 128 KiB of device memory


def _mix(h):
    """splitmix64 avalanche (device)."""
    h = (h ^ (h >> 30)) * U64(_M1)
    h = (h ^ (h >> 27)) * U64(_M2)
    return h ^ (h >> 31)


def row_hash(cid, sval, data):
    """64-bit hash of ONE encoded program row: cid [C] i32, sval [C, S]
    u64, data [C, D] u8 -> u64 scalar.  vmap over the batch axis; every
    op is elementwise + one xor reduction, so the vmapped form is a
    single fused kernel, not a per-row scan."""

    def fold(h, x, salt):
        x = jnp.asarray(x).astype(U64).reshape(-1)
        idx = jnp.arange(x.shape[0], dtype=U64)
        w = _mix(x ^ _mix(idx + U64(salt)))
        folded = jnp.bitwise_xor.reduce(w)
        return _mix((h * U64(FNV64_PRIME)) ^ folded)

    h = U64(FNV64_OFFSET)
    h = fold(h, cid, _SALT_CID)
    h = fold(h, sval, _SALT_SVAL)
    h = fold(h, data, _SALT_DATA)
    return h


def _mix_host(h):
    h = (h ^ (h >> np.uint64(30))) * np.uint64(_M1)
    h = (h ^ (h >> np.uint64(27))) * np.uint64(_M2)
    return h ^ (h >> np.uint64(31))


def row_hash_host(cid, sval, data) -> int:
    """Bit-identical numpy reference of ``row_hash`` (parity tests; NOT
    on the launch path — the guard test patches it to prove that)."""
    with np.errstate(over="ignore"):
        h = np.uint64(FNV64_OFFSET)
        for x, salt in ((cid, _SALT_CID), (sval, _SALT_SVAL),
                        (data, _SALT_DATA)):
            x = np.asarray(x).astype(np.uint64).reshape(-1)
            idx = np.arange(x.shape[0], dtype=np.uint64)
            w = _mix_host(x ^ _mix_host(idx + np.uint64(salt)))
            folded = np.bitwise_xor.reduce(w) if w.size else np.uint64(0)
            h = _mix_host((h * np.uint64(FNV64_PRIME)) ^ folded)
        return int(h)


def inbatch_first_mask(hashes):
    """[B] u64 -> [B] bool: True on exactly one row per distinct hash
    (sort-and-compare; jnp sorts are stable, so the keeper is the first
    occurrence in batch order)."""
    h = jnp.asarray(hashes, U64)
    order = jnp.argsort(h)
    s = h[order]
    dup = jnp.concatenate(
        [jnp.zeros((1,), bool), s[1:] == s[:-1]])
    return jnp.zeros(h.shape, bool).at[order].set(~dup)


def bloom_probes(hashes, k: int = BLOOM_PROBES):
    """[...] u64 -> [..., k] u32 probe signals (Kirsch–Mitzenmacher
    ``lo + i*hi`` with hi forced odd).  The probes feed the same packed
    bitset ops the signal sets use — ``cover.bitset_test/add`` eagerly,
    ``mesh.fold_admission`` inside the sharded step."""
    h = jnp.asarray(hashes, U64)
    lo = (h & U64(0xFFFFFFFF)).astype(U32)
    hi = ((h >> U64(32)).astype(U32)) | U32(1)
    i = jnp.arange(k, dtype=U32)
    return lo[..., None] + i * hi[..., None]


def bloom_test(bitset, hashes, k: int = BLOOM_PROBES):
    """Which hashes are (probably) already in the filter?  True only when
    ALL k probe bits are set — the classic Bloom membership test."""
    hit = cover.bitset_test(bitset, bloom_probes(hashes, k))
    return jnp.all(hit, axis=-1)


def bloom_add(bitset, hashes, k: int = BLOOM_PROBES):
    """Scatter all k probe bits of every hash into the filter."""
    return cover.bitset_add(bitset, bloom_probes(hashes, k).reshape(-1))


def bloom_occupancy(bitset) -> jnp.ndarray:
    """Fraction of filter bits set (drives the decay/reset policy and the
    ``admission_bloom_occupancy`` gauge)."""
    nbits = bitset.shape[-1] * 32
    return cover.bitset_count(bitset).astype(jnp.float32) / nbits


def make_bloom(nbits: int = DEFAULT_BLOOM_BITS):
    """Fresh all-zero Bloom bitset ([nbits/32] u32, power-of-two bits —
    the same layout constraint as the signal bitsets)."""
    nbits = 1 << (int(nbits) - 1).bit_length()
    return cover.make_bitset(nbits)


def admit_mask(bloom, hashes, k: int = BLOOM_PROBES):
    """Eager single-device admission: (admit [B] bool, new bloom).
    A row is admitted iff it is the first of its hash in this batch AND
    its hash is not (probably) in the recent-hash filter.  ALL hashes are
    then added — a rejected duplicate must stay remembered.  The sharded
    launch path computes the same thing inside the fuzz step via
    ``mesh.fold_admission``; this entry is for tests and host tooling."""
    first = inbatch_first_mask(hashes)
    seen = bloom_test(bloom, hashes, k)
    return first & ~seen, bloom_add(bloom, hashes, k)
