"""Device-resident corpus arena: the encoded corpus lives on the chips.

Before this module the host kept the encoded corpus as a Python list of
per-program numpy triples and, on every device launch, re-``np.stack``-ed
a [B, ...] batch out of it and ``device_put`` the result — an O(B) host
walk plus a full-batch H2D transfer per launch, exactly on the boundary
the paper optimizes (mutation/new-signal testing on the TPU, only syscall
execution on the CPU fleet).  The arena replaces that with preallocated
device tensors

    cid  [cap, C]     int32    syscall id per call slot (-1 = empty)
    sval [cap, C, S]  uint64   template slot values
    data [cap, C, D]  uint8    per-call copyin arena image
    w    [cap]        uint32   per-row sampling weight (yield-derived)

appended to by a jitted donated single-row ``.at[row].set`` (the only
per-add transfer is the one encoded program) and sampled *inside* the
sharded fuzz step (parallel/mesh.make_arena_fuzz_step) — the steady-state
launch ships no per-row host data at all.  This is the memoization move
from "Toward Speeding up Mutation Analysis by Memoizing Expensive
Methods": encode once, stay resident.

Scheduling is YIELD-WEIGHTED (ISSUE 5): every row carries a yield score
fed back from the attribution ledger (new-signal PCs and corpus adds its
sampled candidates earned).  Sampling draws from a cumulative-weight
table — ``ops/rng.choose_weighted_from``, the prio.go:231-247 shape — on
device inside the fuzz step (the host mirror here serves tests/tools),
and eviction prefers the LOWEST-yield row over plain FIFO: the victim is
the lexicographic (yield, age) minimum, so with no credit information it
degrades to exactly the old FIFO ring, and with credit it protects
proven seeds from being thrashed (``arena_weighted_evictions_total``
counts the evictions where the policy actually diverged from FIFO).
Occupancy / evictions / resident bytes stay exported as the ``arena_*``
gauge family (tools/check_metrics.py requires them registered).
"""

from __future__ import annotations

import threading
import weakref
from typing import Optional, Tuple

from . import ensure_x64  # noqa: F401

import jax
import jax.numpy as jnp
import numpy as np

from ..telemetry import get_registry

# weight formula shared by the host mirror and the device tensor:
# w = 1 + min(round(yield), WEIGHT_CAP) for live rows, 0 for dead rows.
# The +1 keeps every live row reachable; the cap bounds how hard a
# jackpot seed can starve the rest of the ring.
WEIGHT_CAP = (1 << 10) - 1


def project_weights(yields, size: int) -> np.ndarray:
    """THE yield->weight projection (one definition: the host mirror,
    the single-row device writes, and checkpoint restore must all agree
    bit-for-bit): w = 1 + min(round(yield), WEIGHT_CAP) for live rows,
    0 for dead rows."""
    w = np.minimum(np.floor(np.asarray(yields, np.float64) + 0.5),
                   WEIGHT_CAP).astype(np.uint32) + 1
    w[size:] = 0
    return w


def _append_row(a_cid, a_sval, a_data, a_w, row, cid, sval, data, w):
    """Jitted single-row write; the arena tensors are donated so XLA
    updates them in place instead of copying [cap, ...] per append."""
    return (a_cid.at[row].set(cid),
            a_sval.at[row].set(sval),
            a_data.at[row].set(data),
            a_w.at[row].set(w))


def _set_weight(a_w, row, w):
    """Jitted donated single-row weight update (yield credit)."""
    return a_w.at[row].set(w)


class CorpusArena:
    """Ring of encoded corpus programs resident on device.

    Concurrency contract: ``append`` serializes writers under one lock,
    and because it DONATES the previous tensors (the in-place update is
    the point), the pre-append triple is consumed — a reader must not
    cache ``tensors()`` results across an append.  ``gather`` therefore
    dispatches its take under the lock.  The engine complies by
    construction: appends, launches, and yield credits all happen on the
    scheduling thread (drain workers never touch the arena), and a
    launch already enqueued holds runtime-level buffer references, so an
    append cannot invalidate in-flight device work.
    """

    def __init__(self, capacity: int, fmt, sharding=None,
                 weights_sharding=None, registry=None):
        cap = int(capacity)
        if cap <= 0:
            raise ValueError(f"arena capacity must be positive, got {cap}")
        self.capacity = cap
        self.size = 0          # rows holding a real program
        self.cursor = 0        # next row to write (ring)
        self.evictions = 0     # overwrites of live rows
        self.weighted_evictions = 0  # overwrites where policy != FIFO
        # host-side yield bookkeeping: the eviction policy and the
        # sampling-weight formula read these; the device weight tensor
        # is the [cap] u32 projection kept in sync by append/credit
        self.yields = np.zeros(cap, np.float64)
        self.ages = np.zeros(cap, np.int64)   # append sequence stamp
        self._seq = 0
        cid = jnp.full((cap, fmt.max_calls), -1, jnp.int32)
        sval = jnp.zeros((cap, fmt.max_calls, fmt.max_slots), jnp.uint64)
        data = jnp.zeros((cap, fmt.max_calls, fmt.arena), jnp.uint8)
        w = jnp.zeros((cap,), jnp.uint32)
        # shard-aware placement: the row tensors and the weight table may
        # carry DIFFERENT shardings — under the explicit-sharding step
        # the [cap] u32 weight table shards over the ``fuzz`` axis while
        # the gathered row tensors stay replicated (parallel/mesh.
        # make_arena_fuzz_step's shardings dict is the source of truth)
        self._w_sharding = (weights_sharding if weights_sharding
                            is not None else sharding)
        if sharding is not None:
            cid, sval, data = (jax.device_put(x, sharding)
                               for x in (cid, sval, data))
        if self._w_sharding is not None:
            w = jax.device_put(w, self._w_sharding)
        self.cid, self.sval, self.data = cid, sval, data
        self.weights = w
        self._sharding = sharding
        self._lock = threading.Lock()
        self._append_fn = jax.jit(_append_row, donate_argnums=(0, 1, 2, 3))
        self._set_w_fn = jax.jit(_set_weight, donate_argnums=(0,))

        reg = registry or get_registry()
        self._c_evictions = reg.counter(
            "arena_evictions_total",
            help="corpus-arena ring overwrites of live rows")
        self._c_weighted_evictions = reg.counter(
            "arena_weighted_evictions_total",
            help="arena evictions where the lowest-yield victim differed "
                 "from the FIFO (oldest-row) choice")
        self._c_yield_decays = reg.counter(
            "arena_yield_decays_total",
            help="geometric age-decays applied to the arena's yield "
                 "scores (on the admission-Bloom reset cadence)")
        ref = weakref.ref(self)
        self._gauge_fns = [
            (reg.gauge(
                "arena_occupancy",
                help="fraction of corpus-arena rows holding a program"),
             lambda: (a.size / a.capacity)
             if (a := ref()) is not None else 0.0),
            (reg.gauge(
                "arena_resident_bytes",
                help="bytes of device-resident encoded corpus tensors"),
             lambda: a.resident_bytes() if (a := ref()) is not None else 0),
        ]
        for g, fn in self._gauge_fns:
            g.set_fn(fn)

    # ---- lifecycle ----

    def close(self) -> None:
        for g, fn in getattr(self, "_gauge_fns", ()):
            g.clear_fn(fn)

    def resident_bytes(self) -> int:
        return sum(int(getattr(x, "nbytes", 0))
                   for x in (self.cid, self.sval, self.data, self.weights))

    def __len__(self) -> int:
        return self.size

    # ---- weights ----

    def _row_weight(self, y: float) -> int:
        """Scalar form of ``project_weights`` for a LIVE row (the jitted
        single-row device writes)."""
        return int(project_weights(np.asarray([y]), 1)[0])

    def host_weights(self) -> np.ndarray:
        """[cap] u32 host mirror of the device weight tensor (tests +
        host-side sampling; the launch path never calls this)."""
        with self._lock:
            return project_weights(self.yields, self.size)

    # ---- writes ----

    def _next_row(self) -> int:
        """Pick the write slot (lock held): free slots first, then the
        lexicographic (yield, age) minimum — lowest-yield victim, FIFO
        among ties, so an uncredited arena evicts exactly like the old
        ring while credited seeds survive."""
        if self.size < self.capacity:
            row = self.cursor
            self.cursor = (self.cursor + 1) % self.capacity
            self.size += 1
            return row
        victim = int(np.lexsort((self.ages, self.yields))[0])
        if victim != int(np.argmin(self.ages)):
            self.weighted_evictions += 1
            self._c_weighted_evictions.inc()
        self.evictions += 1
        self._c_evictions.inc()
        self.cursor = (victim + 1) % self.capacity
        return victim

    def append(self, cid_row, sval_row, data_row) -> int:
        """Write one encoded program into the chosen slot; returns the
        row index.  The H2D payload is the single row (+ its unit
        weight); the [cap, ...] tensors update in place (donated)."""
        with self._lock:
            row = self._next_row()
            self.yields[row] = 0.0
            self.ages[row] = self._seq
            self._seq += 1
            (self.cid, self.sval, self.data,
             self.weights) = self._append_fn(
                self.cid, self.sval, self.data, self.weights, row,
                jnp.asarray(np.asarray(cid_row), jnp.int32),
                jnp.asarray(np.asarray(sval_row), jnp.uint64),
                jnp.asarray(np.asarray(data_row), jnp.uint8),
                jnp.uint32(self._row_weight(0.0)))
            return row

    def age_stamps(self, rows) -> np.ndarray:
        """Append-sequence stamps of the given rows.  Credit guards must
        capture stamps at SAMPLE/LAUNCH time (the engine snapshots
        ``ages`` as each batch launches) — a consume-time read would
        return the stamp of whatever program has since overwritten the
        row, letting misattributed credit pass ``credit``'s guard."""
        rows = np.asarray(rows, np.int64)
        with self._lock:
            return self.ages[rows].copy()

    def credit(self, row: int, amount: float, stamp: int = -1) -> None:
        """Credit yield back to a sampled source row (attribution-ledger
        feedback: new-signal PCs / corpus adds its candidates earned).
        ``stamp`` (an ``age_stamps`` value) guards against eviction
        races: if the row was overwritten since the candidate was
        sampled, the credit is dropped rather than misattributed.
        Updates the host score and pushes the single projected weight to
        the device tensor (donated in-place write — no full-[cap]
        re-upload, no launch-path work)."""
        row = int(row)
        if amount <= 0 or not (0 <= row < self.capacity):
            return
        with self._lock:
            if row >= self.size:
                return  # row not live (stale provenance)
            if stamp >= 0 and int(self.ages[row]) != int(stamp):
                return  # row evicted+rewritten since the sample
            self.yields[row] += float(amount)
            self.weights = self._set_w_fn(
                self.weights, row,
                jnp.uint32(self._row_weight(self.yields[row])))

    def decay_yields(self, factor: float) -> None:
        """Geometric age-decay of every row's yield score (satellite of
        the prefix-memoization PR; ROADMAP carried-over item): called on
        the engine's occupancy-triggered admission-Bloom reset cadence,
        so an early-campaign jackpot row's score halves away unless the
        row keeps earning — without decay it pins the weighted sampler
        (and survives eviction) forever on stale credit.  One full
        [cap] weight re-projection is uploaded per decay; the cadence
        is Bloom resets (minutes), not launches, so this is off the hot
        path."""
        factor = float(factor)
        if not 0.0 <= factor < 1.0:
            return  # 1.0 (or junk) would be a no-op pin: skip
        with self._lock:
            if self.size == 0:
                return
            self.yields *= factor
            w = jnp.asarray(project_weights(self.yields, self.size))
            if self._w_sharding is not None:
                w = jax.device_put(w, self._w_sharding)
            self.weights = w
            self._c_yield_decays.inc()

    def restore(self, cid, sval, data, *, size: int, cursor: int,
                evictions: int = 0, yields=None, ages=None, seq: int = 0,
                weighted_evictions: int = 0) -> None:
        """Replace the ring wholesale from a checkpoint (engine resume).
        Shapes must match the configured capacity/format — the caller
        validates before any state mutates (Fuzzer._apply_checkpoint).
        Yield scores restore bit-identically; the device weight tensor is
        re-projected from them (deterministic)."""
        cid = jnp.asarray(np.asarray(cid), jnp.int32)
        sval = jnp.asarray(np.asarray(sval), jnp.uint64)
        data = jnp.asarray(np.asarray(data), jnp.uint8)
        for name, got, want in (("cid", cid, self.cid),
                                ("sval", sval, self.sval),
                                ("data", data, self.data)):
            if got.shape != want.shape:
                raise ValueError(
                    f"arena restore {name} shape {got.shape} != "
                    f"{want.shape}")
        new_yields = (np.asarray(yields, np.float64).copy()
                      if yields is not None
                      else np.zeros(self.capacity, np.float64))
        new_ages = (np.asarray(ages, np.int64).copy()
                    if ages is not None
                    else np.zeros(self.capacity, np.int64))
        if new_yields.shape != (self.capacity,) or \
                new_ages.shape != (self.capacity,):
            raise ValueError(
                f"arena restore yields/ages shape {new_yields.shape}/"
                f"{new_ages.shape} != ({self.capacity},)")
        size = min(max(int(size), 0), self.capacity)
        w = jnp.asarray(project_weights(new_yields, size))
        if self._sharding is not None:
            cid, sval, data = (jax.device_put(x, self._sharding)
                               for x in (cid, sval, data))
        if self._w_sharding is not None:
            w = jax.device_put(w, self._w_sharding)
        with self._lock:
            self.cid, self.sval, self.data = cid, sval, data
            self.weights = w
            self.size = size
            self.cursor = int(cursor) % self.capacity
            self.evictions = int(evictions)
            self.weighted_evictions = int(weighted_evictions)
            self.yields = new_yields
            self.ages = new_ages
            self._seq = max(int(seq), int(new_ages.max()) + 1 if size else 0)

    # ---- reads ----

    def tensors(self) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
        """The live (cid, sval, data) triple.  Use immediately: a later
        ``append`` donates (consumes) these buffers — see the class
        concurrency contract."""
        with self._lock:
            return self.cid, self.sval, self.data

    def weights_tensor(self) -> jnp.ndarray:
        """The live [cap] u32 device weight vector the sharded fuzz step
        cumsums for on-device weighted sampling.  Same use-immediately
        contract as ``tensors()``."""
        with self._lock:
            return self.weights

    def gather(self, idx) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
        """Device-side row gather (tests + host tooling; the production
        launch path gathers inside the sharded fuzz step instead).
        Dispatched under the lock so a concurrent append cannot donate
        the triple out from under the take."""
        idx = jnp.asarray(np.asarray(idx), jnp.int32)
        with self._lock:
            return (jnp.take(self.cid, idx, axis=0),
                    jnp.take(self.sval, idx, axis=0),
                    jnp.take(self.data, idx, axis=0))

    def sample_indices(self, rng: np.random.Generator, n: int,
                       ) -> Optional[np.ndarray]:
        """Yield-weighted row indices over the live region ([n] int32):
        a host cumulative-weight draw mirroring the on-device sampler
        (ops/rng.choose_weighted_from semantics — uniform in [0, total)
        then binary search, prio.go:231-247).  None while the arena is
        empty.  Host tooling/fallback only: the steady-state launch
        samples on device inside the fuzz step."""
        with self._lock:
            size = self.size
            w = project_weights(self.yields, size)
        if size == 0:
            return None
        cw = np.cumsum(w[:size], dtype=np.uint64)
        draws = rng.integers(0, int(cw[-1]), size=n)
        return np.searchsorted(
            cw, np.asarray(draws, np.uint64), side="right").astype(np.int32)
