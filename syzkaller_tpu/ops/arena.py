"""Device-resident corpus arena: the encoded corpus lives on the chips.

Before this module the host kept the encoded corpus as a Python list of
per-program numpy triples and, on every device launch, re-``np.stack``-ed
a [B, ...] batch out of it and ``device_put`` the result — an O(B) host
walk plus a full-batch H2D transfer per launch, exactly on the boundary
the paper optimizes (mutation/new-signal testing on the TPU, only syscall
execution on the CPU fleet).  The arena replaces that with preallocated
device tensors

    cid  [cap, C]     int32    syscall id per call slot (-1 = empty)
    sval [cap, C, S]  uint64   template slot values
    data [cap, C, D]  uint8    per-call copyin arena image

appended to by a jitted donated single-row ``.at[row].set`` (the only
per-add transfer is the one encoded program) and sampled *inside* the
sharded fuzz step with ``jnp.take`` (parallel/mesh.make_arena_fuzz_step)
— so the only per-launch H2D transfer is the [B] int32 selection-index
vector.  This is the memoization move from "Toward Speeding up Mutation
Analysis by Memoizing Expensive Methods": encode once, stay resident.

Eviction is a ring (FIFO overwrite): once ``size == capacity`` the cursor
wraps and the oldest encoded program is overwritten, so week-long
campaigns stay memory-bounded.  Occupancy / evictions / resident bytes
are exported as the ``arena_*`` gauge family (tools/check_metrics.py
requires them to stay registered).
"""

from __future__ import annotations

import threading
import weakref
from typing import Optional, Tuple

from . import ensure_x64  # noqa: F401

import jax
import jax.numpy as jnp
import numpy as np

from ..telemetry import get_registry


def _append_row(a_cid, a_sval, a_data, row, cid, sval, data):
    """Jitted single-row write; the arena tensors are donated so XLA
    updates them in place instead of copying [cap, ...] per append."""
    return (a_cid.at[row].set(cid),
            a_sval.at[row].set(sval),
            a_data.at[row].set(data))


class CorpusArena:
    """Ring of encoded corpus programs resident on device.

    Concurrency contract: ``append`` serializes writers under one lock,
    and because it DONATES the previous tensors (the in-place update is
    the point), the pre-append triple is consumed — a reader must not
    cache ``tensors()`` results across an append.  ``gather`` therefore
    dispatches its take under the lock.  The engine complies by
    construction: appends and launches both happen on the scheduling
    thread (drain workers never touch the arena), and a launch already
    enqueued holds runtime-level buffer references, so an append cannot
    invalidate in-flight device work.
    """

    def __init__(self, capacity: int, fmt, sharding=None,
                 registry=None):
        cap = int(capacity)
        if cap <= 0:
            raise ValueError(f"arena capacity must be positive, got {cap}")
        self.capacity = cap
        self.size = 0          # rows holding a real program
        self.cursor = 0        # next row to write (ring)
        self.evictions = 0     # overwrites of live rows
        cid = jnp.full((cap, fmt.max_calls), -1, jnp.int32)
        sval = jnp.zeros((cap, fmt.max_calls, fmt.max_slots), jnp.uint64)
        data = jnp.zeros((cap, fmt.max_calls, fmt.arena), jnp.uint8)
        if sharding is not None:
            cid, sval, data = (jax.device_put(x, sharding)
                               for x in (cid, sval, data))
        self.cid, self.sval, self.data = cid, sval, data
        self._sharding = sharding
        self._lock = threading.Lock()
        self._append_fn = jax.jit(_append_row, donate_argnums=(0, 1, 2))

        reg = registry or get_registry()
        self._c_evictions = reg.counter(
            "arena_evictions_total",
            help="corpus-arena ring overwrites of live rows")
        ref = weakref.ref(self)
        self._gauge_fns = [
            (reg.gauge(
                "arena_occupancy",
                help="fraction of corpus-arena rows holding a program"),
             lambda: (a.size / a.capacity)
             if (a := ref()) is not None else 0.0),
            (reg.gauge(
                "arena_resident_bytes",
                help="bytes of device-resident encoded corpus tensors"),
             lambda: a.resident_bytes() if (a := ref()) is not None else 0),
        ]
        for g, fn in self._gauge_fns:
            g.set_fn(fn)

    # ---- lifecycle ----

    def close(self) -> None:
        for g, fn in getattr(self, "_gauge_fns", ()):
            g.clear_fn(fn)

    def resident_bytes(self) -> int:
        return sum(int(getattr(x, "nbytes", 0))
                   for x in (self.cid, self.sval, self.data))

    def __len__(self) -> int:
        return self.size

    # ---- writes ----

    def append(self, cid_row, sval_row, data_row) -> int:
        """Write one encoded program into the next ring slot; returns the
        row index.  The H2D payload is the single row, the [cap, ...]
        tensors update in place (donated)."""
        with self._lock:
            row = self.cursor
            self.cursor = (self.cursor + 1) % self.capacity
            if self.size == self.capacity:
                self.evictions += 1
                self._c_evictions.inc()
            else:
                self.size += 1
            self.cid, self.sval, self.data = self._append_fn(
                self.cid, self.sval, self.data, row,
                jnp.asarray(np.asarray(cid_row), jnp.int32),
                jnp.asarray(np.asarray(sval_row), jnp.uint64),
                jnp.asarray(np.asarray(data_row), jnp.uint8))
            return row

    def restore(self, cid, sval, data, *, size: int, cursor: int,
                evictions: int = 0) -> None:
        """Replace the ring wholesale from a checkpoint (engine resume).
        Shapes must match the configured capacity/format — the caller
        validates before any state mutates (Fuzzer._apply_checkpoint)."""
        cid = jnp.asarray(np.asarray(cid), jnp.int32)
        sval = jnp.asarray(np.asarray(sval), jnp.uint64)
        data = jnp.asarray(np.asarray(data), jnp.uint8)
        for name, got, want in (("cid", cid, self.cid),
                                ("sval", sval, self.sval),
                                ("data", data, self.data)):
            if got.shape != want.shape:
                raise ValueError(
                    f"arena restore {name} shape {got.shape} != "
                    f"{want.shape}")
        if self._sharding is not None:
            cid, sval, data = (jax.device_put(x, self._sharding)
                               for x in (cid, sval, data))
        with self._lock:
            self.cid, self.sval, self.data = cid, sval, data
            self.size = min(max(int(size), 0), self.capacity)
            self.cursor = int(cursor) % self.capacity
            self.evictions = int(evictions)

    # ---- reads ----

    def tensors(self) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
        """The live (cid, sval, data) triple.  Use immediately: a later
        ``append`` donates (consumes) these buffers — see the class
        concurrency contract."""
        with self._lock:
            return self.cid, self.sval, self.data

    def gather(self, idx) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
        """Device-side row gather (tests + host tooling; the production
        launch path gathers inside the sharded fuzz step instead).
        Dispatched under the lock so a concurrent append cannot donate
        the triple out from under the take."""
        idx = jnp.asarray(np.asarray(idx), jnp.int32)
        with self._lock:
            return (jnp.take(self.cid, idx, axis=0),
                    jnp.take(self.sval, idx, axis=0),
                    jnp.take(self.data, idx, axis=0))

    def sample_indices(self, rng: np.random.Generator, n: int,
                       ) -> Optional[np.ndarray]:
        """Uniform row indices over the live region ([B] int32 — the only
        per-launch H2D transfer); None while the arena is empty."""
        with self._lock:
            size = self.size
        if size == 0:
            return None
        return np.asarray(rng.integers(0, size, size=n), np.int32)
