"""Device-resident table views: dense [n_calls, S] gathers of the slot
templates plus precomputed default program images.

The ragged slot templates from descriptions/tables.py are densified so that
`call_id` alone indexes every per-slot property — the shape the vmapped
mutation/generation kernels need (one gather per property instead of a tree
walk; reference equivalent is the generated Go type graph walked per arg).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np

from . import ensure_x64  # noqa: F401

import jax.numpy as jnp

from ..descriptions.tables import (
    SK_DATA,
    SK_REF,
    SK_VALUE,
    SK_VMA,
    TK_BUF_BLOB,
    TK_BUF_FILE,
    TK_BUF_STR,
    CompiledTables,
)
from ..prog.tensor import REF_NONE, TensorFormat

DEFAULT_FILENAME = b"./file0\x00"


@dataclass
class DeviceTables:
    """Registered as a jax pytree: array fields are leaves, the four size
    fields are static metadata (so jitted kernels specialize on shapes)."""

    n_calls: int
    n_res: int
    max_slots: int
    arena: int

    # dense per-(call, slot) properties
    slot_kind: jnp.ndarray      # [N, S] i32 (-1 pad)
    slot_tkind: jnp.ndarray     # [N, S] i32 type kind (-1 pad)
    slot_size: jnp.ndarray      # [N, S] i32 byte width / data cap
    slot_lo: jnp.ndarray        # [N, S] u64
    slot_hi: jnp.ndarray        # [N, S] u64
    slot_flags_off: jnp.ndarray
    slot_flags_cnt: jnp.ndarray
    slot_res_kind: jnp.ndarray  # [N, S] i32
    slot_arena_off: jnp.ndarray  # [N, S] i32 (-1 if not a data slot)
    slot_cnt: jnp.ndarray       # [N] i32

    # defaults for insertion/generation
    default_slot_val: jnp.ndarray  # [N, S] u64
    default_arena: jnp.ndarray     # [N, D] u8

    flags_pool: jnp.ndarray        # [F] u64
    produces_compat: jnp.ndarray   # [N, R] u8: call produces kind compatible
    needs: jnp.ndarray             # [N, R] u8
    choice_run: jnp.ndarray        # [N, N] i64 cumulative weights
    enabled: jnp.ndarray           # [N] bool
    enabled_run: jnp.ndarray       # [N] i64 cumsum of enabled (uniform pick)

    str_data: jnp.ndarray          # [NS, cap] u8
    str_len: jnp.ndarray           # [NS] i32


import jax

jax.tree_util.register_dataclass(
    DeviceTables,
    data_fields=[
        "slot_kind", "slot_tkind", "slot_size", "slot_lo", "slot_hi",
        "slot_flags_off", "slot_flags_cnt", "slot_res_kind",
        "slot_arena_off", "slot_cnt", "default_slot_val", "default_arena",
        "flags_pool", "produces_compat", "needs", "choice_run", "enabled",
        "enabled_run", "str_data", "str_len",
    ],
    meta_fields=["n_calls", "n_res", "max_slots", "arena"],
)


# memo for the default build (no prio/enabled override): every Fuzzer
# construction over the same target asks for the same tables, and the
# per-slot Python loop plus host->device uploads cost real time.
# Returning the *same* DeviceTables object also lets downstream
# identity-keyed caches (parallel/mesh._ARENA_STEP_CACHE) hit.  ct is
# pinned in the value so a recycled id can never alias a dead table set.
_DT_CACHE: dict = {}


def build_device_tables(ct: CompiledTables, fmt: TensorFormat,
                        prios: Optional[np.ndarray] = None,
                        enabled_mask: Optional[np.ndarray] = None
                        ) -> DeviceTables:
    if prios is None and enabled_mask is None:
        key = (id(ct), fmt.max_calls, fmt.max_slots, fmt.arena)
        hit = _DT_CACHE.get(key)
        if hit is not None and hit[0] is ct:
            return hit[1]
        dt = _build_device_tables(ct, fmt, None, None)
        _DT_CACHE[key] = (ct, dt)
        return dt
    return _build_device_tables(ct, fmt, prios, enabled_mask)


def _build_device_tables(ct: CompiledTables, fmt: TensorFormat,
                         prios: Optional[np.ndarray] = None,
                         enabled_mask: Optional[np.ndarray] = None
                         ) -> DeviceTables:
    n, S, D = ct.n_calls, fmt.max_slots, fmt.arena
    R = max(ct.n_res_kinds, 1)

    kind = np.full((n, S), -1, dtype=np.int32)
    tkind = np.full((n, S), -1, dtype=np.int32)
    size = np.zeros((n, S), dtype=np.int32)
    lo = np.zeros((n, S), dtype=np.uint64)
    hi = np.zeros((n, S), dtype=np.uint64)
    foff = np.zeros((n, S), dtype=np.int32)
    fcnt = np.zeros((n, S), dtype=np.int32)
    resk = np.full((n, S), -1, dtype=np.int32)
    aoff = np.full((n, S), -1, dtype=np.int32)
    dval = np.zeros((n, S), dtype=np.uint64)
    darena = np.zeros((n, D), dtype=np.uint8)

    for ci in range(n):
        o = int(ct.call_slot_off[ci])
        cnt = min(int(ct.call_slot_cnt[ci]), S)
        bo = int(ct.call_block_off[ci])
        for si in range(cnt):
            g = o + si
            ti = int(ct.slot_type[g])
            sk = int(ct.slot_kind[g])
            kind[ci, si] = sk
            tkind[ci, si] = int(ct.type_kind[ti])
            size[ci, si] = int(ct.slot_size[g])
            lo[ci, si] = ct.type_lo[ti]
            hi[ci, si] = ct.type_hi[ti]
            foff[ci, si] = int(ct.type_flags_off[ti])
            fcnt[ci, si] = int(ct.type_flags_cnt[ti])
            resk[ci, si] = int(ct.slot_res_kind[g])
            blk = int(ct.slot_block[g])
            if sk == SK_DATA and blk >= 0:
                aoff[ci, si] = int(ct.block_addr[bo + blk]) + \
                    int(ct.slot_offset[g])

            # defaults
            if sk == SK_VALUE:
                dval[ci, si] = ct.slot_default[g]
            elif sk == SK_REF:
                dval[ci, si] = np.uint64(REF_NONE)
            elif sk == SK_VMA:
                dval[ci, si] = max(1, int(ct.slot_default[g]))
            elif sk == SK_DATA:
                tk = int(ct.type_kind[ti])
                payload = b""
                if tk == TK_BUF_STR and int(ct.slot_str_cnt[g]) > 0:
                    so = int(ct.slot_str_off[g])
                    ln = int(ct.str_len[so])
                    payload = bytes(ct.str_data[so, :ln].tobytes())
                elif tk == TK_BUF_FILE:
                    payload = DEFAULT_FILENAME
                elif tk == TK_BUF_BLOB:
                    payload = b"\x00" * min(int(ct.type_lo[ti]),
                                            size[ci, si])
                payload = payload[: size[ci, si]]
                dval[ci, si] = len(payload)
                a = aoff[ci, si]
                if a >= 0 and payload:
                    end = min(a + len(payload), D)
                    darena[ci, a:end] = np.frombuffer(
                        payload[: end - a], dtype=np.uint8)

    # produces_compat[call, want_kind]: call yields a resource usable as want
    produces = ct.call_res_out.astype(np.uint8)  # [N, R]
    compat = ct.res_compat.astype(np.uint8)      # [dst, src]
    produces_compat = (produces @ compat.T > 0).astype(np.uint8) \
        if ct.n_res_kinds else np.zeros((n, R), dtype=np.uint8)

    if prios is None:
        prios = ct.prio_static
    if enabled_mask is None:
        enabled_mask = np.ones(n, dtype=bool)
    weights = (prios * 1000).astype(np.int64) * enabled_mask[None, :]
    run = np.cumsum(weights, axis=1)

    return DeviceTables(
        n_calls=n, n_res=R, max_slots=S, arena=D,
        slot_kind=jnp.asarray(kind),
        slot_tkind=jnp.asarray(tkind),
        slot_size=jnp.asarray(size),
        slot_lo=jnp.asarray(lo),
        slot_hi=jnp.asarray(hi),
        slot_flags_off=jnp.asarray(foff),
        slot_flags_cnt=jnp.asarray(fcnt),
        slot_res_kind=jnp.asarray(resk),
        slot_arena_off=jnp.asarray(aoff),
        slot_cnt=jnp.asarray(np.minimum(ct.call_slot_cnt, S)),
        default_slot_val=jnp.asarray(dval),
        default_arena=jnp.asarray(darena),
        flags_pool=jnp.asarray(ct.flags_pool),
        produces_compat=jnp.asarray(produces_compat),
        needs=jnp.asarray(
            ct.call_res_in.astype(np.uint8) if ct.n_res_kinds
            else np.zeros((n, R), dtype=np.uint8)),
        choice_run=jnp.asarray(run),
        enabled=jnp.asarray(enabled_mask),
        enabled_run=jnp.asarray(
            np.cumsum(enabled_mask.astype(np.int64))),
        str_data=jnp.asarray(ct.str_data),
        str_len=jnp.asarray(ct.str_len),
    )
