"""Vectorized x86 text-arg generation on device.

Device counterpart of ifuzz.generate (reference pkg/ifuzz generates text
args one relocation at a time on the host): each batch lane assembles a
short instruction stream by sampling template rows from the exported
ifuzz table (ifuzz.table_rows) and scattering them into a byte arena,
randomizing the immediate windows.  One jit, [B] programs per dispatch —
this is how `text[x86_64]` args get filled when the TPU mutation pipeline
produces candidates, without bouncing back to the host.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

from . import ensure_x64  # noqa: F401

import jax
import jax.numpy as jnp
import numpy as np

from .rng import randpool

U64 = jnp.uint64


class TextTables:
    """Device-resident ifuzz template table for one mode."""

    def __init__(self, mode: int = 0, max_len: int = 16):
        from .. import ifuzz

        cfg = ifuzz.Config(mode=mode)
        tmpl, lens, ioff, isz = ifuzz.table_rows(cfg, max_len=max_len)
        self.n = tmpl.shape[0]
        self.max_len = max_len
        self.templates = jnp.asarray(tmpl)           # [N, L] u8
        self.lengths = jnp.asarray(lens)             # [N]
        self.imm_off = jnp.asarray(ioff)             # [N]
        self.imm_size = jnp.asarray(isz)             # [N]


def _gen_one(pool, tt: TextTables, n_insns: int, cap: int):
    """One lane: scatter n_insns sampled templates into a [cap] arena.
    pool: [n_insns, 2] u64 words (pick, imm)."""
    picks = (pool[:, 0] % U64(tt.n)).astype(jnp.int32)      # [K]
    imms = pool[:, 1]                                        # [K]
    lens = tt.lengths[picks]                                 # [K]
    starts = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(lens)[:-1]])  # [K]

    rows = tt.templates[picks]                               # [K, L]
    # randomize each row's imm window from its pool word
    off = tt.imm_off[picks][:, None]                         # [K, 1]
    isz = tt.imm_size[picks][:, None]
    lane = jnp.arange(tt.max_len)[None, :]
    byte_idx = lane - off
    in_imm = (isz > 0) & (byte_idx >= 0) & (byte_idx < isz)
    imm_bytes = ((imms[:, None] >> (byte_idx.clip(0, 7) * 8).astype(U64))
                 & U64(0xFF)).astype(jnp.uint8)
    rows = jnp.where(in_imm, imm_bytes, rows)

    # scatter rows into the arena at their cumulative offsets
    flat_pos = (starts[:, None] + lane).reshape(-1)          # [K*L]
    valid = (lane < lens[:, None]).reshape(-1)
    flat_pos = jnp.where(valid, flat_pos, cap)  # out-of-range = dropped
    arena = jnp.zeros((cap + 1,), jnp.uint8)
    arena = arena.at[flat_pos].set(rows.reshape(-1))
    total = jnp.minimum(jnp.sum(lens), cap)
    return arena[:cap], total


@partial(jax.jit, static_argnames=("tt", "n_insns", "cap", "B"))
def generate_text_batch(key, tt: TextTables, *, B: int, n_insns: int = 8,
                        cap: int = 128) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """[B, cap] u8 instruction streams + [B] lengths."""
    pool = randpool(key, (B, n_insns), 2)
    return jax.vmap(lambda p: _gen_one(p, tt, n_insns, cap))(pool)


_tt_cache = {}


def get_text_tables(mode: int = 0, max_len: int = 16) -> TextTables:
    k = (mode, max_len)
    if k not in _tt_cache:
        _tt_cache[k] = TextTables(mode, max_len)
    return _tt_cache[k]
