"""Pallas TPU kernels for the coverage-bitset hot paths.

Two ops from ops/cover.py dominate the signal path (reference pkg/cover:
greedy corpus Minimize, cover.go:119-146, and the SignalNew/SignalAdd hot
path, cover.go:104-182):

- ``minimize_corpus``: a data-dependent sequential pass — program i is kept
  iff it covers a bit not covered by the programs kept before it.  The XLA
  version is a lax.scan whose [L]-word carry round-trips HBM every step.
  Here the carry ("covered") lives in a VMEM scratch buffer that persists
  across the sequential TPU grid, so each step reads one program's bits
  from HBM and nothing else.

- ``merge_and_new_pallas``: the FUSED cover merge + new-signal test
  (ISSUE 8).  One pass over the batch's sparse signal rows: the
  accumulator bitset is copied into VMEM once, each row's signals are
  test-and-set against it scalar-wise (per-row popcount-delta novelty
  counts fall out of the test), and the merged accumulator is emitted at
  the end — no per-row [rows, nwords] dense bitsets, no second sweep.
  This replaces the retired ``signal_stats`` kernel, which required the
  caller to materialize a dense [rows, nwords] bitset per program and
  round-tripped the accumulator through HBM per stage; the engine's
  signal fold never called it (ISSUE 8 satellite: wire or retire — the
  fused entry is the wired replacement, cover.merge_and_new).

The kernels view the [L]-word bitset as [R, 128] u32 tiles (VPU lane
width; R padded to the 8-sublane int32 tile).  They require the full
bitset to fit in VMEM (≤ MAX_VMEM_WORDS per buffer).

Dispatch is a MEASURED crossover, not a size guess: the first eager call
per (op, size-bucket) times the pallas kernel against the exact XLA
implementation (after a warm-up call each, so compile time doesn't vote)
and caches the winner for the process — ``dispatch()``.  Every dispatch
that does NOT take the pallas path (off-TPU, over-size, or probe-lost)
counts ``pallas_cover_fallback_total`` so silent host fallback is
visible on /dashboard.
"""

from __future__ import annotations

import os
import time

import jax
import jax.numpy as jnp
import numpy as np

from . import ensure_x64  # noqa: F401
from ..utils.jaxcfg import x64_context
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

U32 = jnp.uint32
LANES = 128
SUBLANES = 8  # int32/uint32 min tile is (8, 128)

# One bitset buffer must fit comfortably in VMEM (~16 MB/core) alongside
# a same-sized block of program bits: cap at 4 MB = 1M words = 32 Mbit.
MAX_VMEM_WORDS = 1 << 20
# Per-program scalars (hit flags / new-bit counts) live in one full-array
# SMEM block written at program_id; SMEM is small, so cap the batch.
MAX_SMEM_ROWS = 4096
# The fused merge kernel reads each row's sparse signals from an SMEM
# block scalar-by-scalar; cap the per-row lane count the same way.
MAX_SMEM_LANES = 4096


def _tile(bits):
    """[..., L] u32 -> [..., R, 128] with R a multiple of 8."""
    l = bits.shape[-1]
    r = -(-l // LANES)
    r_pad = -(-r // SUBLANES) * SUBLANES
    pad = r_pad * LANES - l
    if pad:
        bits = jnp.concatenate(
            [bits, jnp.zeros(bits.shape[:-1] + (pad,), U32)], axis=-1)
    return bits.reshape(bits.shape[:-1] + (r_pad, LANES)), l


# The test suite sets SYZTPU_PALLAS_INTERPRET=1 (conftest.py) to run these
# kernels through the pallas interpreter on its CPU backend — covering the
# kernel logic without a chip.  Off TPU *without* that flag, production
# dispatch falls back to the exact jnp implementations (the interpreter is
# a per-step Python emulation, far slower than the XLA scan).
_INTERPRET = os.environ.get("SYZTPU_PALLAS_INTERPRET", "") == "1"


# ---------------------------------------------------------------------- #
# measured-crossover dispatch (replaces the old _use_pallas size guess)

# (op, log2-bucketed nwords, log2-bucketed nrows) -> use pallas?  One
# probe per bucket per process: both paths run once to warm (compile),
# once timed, and the winner is cached.  crossover_reset() clears it
# (tests, or after a driver restart changes kernel perf).
_CROSSOVER: dict = {}

_FALLBACKS = None


def _fallback_counter():
    global _FALLBACKS
    if _FALLBACKS is None:
        from ..telemetry import get_registry

        _FALLBACKS = get_registry().counter(
            "pallas_cover_fallback_total",
            help="cover-kernel dispatches that fell back off the pallas "
                 "path (off-TPU, bitset over VMEM budget, or the "
                 "measured crossover chose XLA)")
    return _FALLBACKS


def crossover_reset() -> None:
    """Drop the per-process measured-crossover cache (test hook)."""
    _CROSSOVER.clear()


def _platform() -> str:
    return jax.devices()[0].platform


def _eligible(nwords: int, nrows: int, lanes: int = 0) -> bool:
    """Hard constraints only (VMEM/SMEM budgets + a TPU or the
    interpreter); which path is FASTER is the probe's question."""
    if nwords > MAX_VMEM_WORDS or nrows > MAX_SMEM_ROWS \
            or lanes > MAX_SMEM_LANES:
        return False
    return _platform() == "tpu" or _INTERPRET


def dispatch(op: str, nwords: int, nrows: int, pallas_fn, xla_fn,
             lanes: int = 0):
    """Run ``pallas_fn`` or ``xla_fn`` (both thunks returning the same
    bit-identical result) — pallas when eligible AND measured faster.

    Under the test interpreter the pallas path always runs (the
    interpreter exists to exercise kernel logic, not to win races).  On
    a TPU the first call per (op, size-bucket) warms and times both
    paths and caches the winner for the process.  Every non-pallas
    dispatch counts ``pallas_cover_fallback_total``."""
    if not _eligible(nwords, nrows, lanes):
        _fallback_counter().inc()
        return xla_fn()
    if _INTERPRET:
        return pallas_fn()
    # lanes is a cost axis of its own (the merge kernel's per-row loop
    # is linear in it while the XLA sort is in n*s) — a winner measured
    # at s=8 must not get locked in for s=4096
    key = (op, max(int(nwords), 1).bit_length(),
           max(int(nrows), 1).bit_length(),
           max(int(lanes), 1).bit_length())
    use = _CROSSOVER.get(key)
    if use is None:
        # one-shot measured crossover: warm both (compile), time both,
        # keep the winner.  The probe's own work isn't wasted — the
        # timed pallas result is returned when it wins.
        jax.block_until_ready(pallas_fn())
        jax.block_until_ready(xla_fn())
        t0 = time.perf_counter()
        out_p = jax.block_until_ready(pallas_fn())
        tp = time.perf_counter() - t0
        t0 = time.perf_counter()
        out_x = jax.block_until_ready(xla_fn())
        tx = time.perf_counter() - t0
        _CROSSOVER[key] = use = tp <= tx
        if not use:
            _fallback_counter().inc()
        return out_p if use else out_x
    if not use:
        _fallback_counter().inc()
        return xla_fn()
    return pallas_fn()


# ---------------------------------------------------------------------- #
# greedy corpus minimize

def _minimize_kernel(bits_ref, hit_ref, covered_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        covered_ref[:] = jnp.zeros_like(covered_ref)

    bits = bits_ref[0]
    fresh = bits & ~covered_ref[:]
    # stay strictly 32-bit signed: mosaic implements neither unsigned
    # reductions nor jnp.any's bool path under jax_enable_x64
    nz = jnp.sum(jax.lax.convert_element_type(fresh != U32(0), jnp.int32),
                 dtype=jnp.int32)
    hit = nz > 0
    hit_ref[i] = jax.lax.convert_element_type(hit, jnp.int32)

    @pl.when(hit)
    def _():
        covered_ref[:] = covered_ref[:] | bits


def _minimize_pallas(tiles):
    n, r, _ = tiles.shape
    # the kernels are strictly 32-bit; trace them with x64 off, since the
    # mosaic lowering rejects the weak-int64 scalars x64 mode introduces
    with x64_context(False):
        return pl.pallas_call(
        _minimize_kernel,
        grid=(n,),
        in_specs=[pl.BlockSpec((1, r, LANES), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((n,), lambda i: (0,),
                               memory_space=pltpu.SMEM),
        out_shape=jax.ShapeDtypeStruct((n,), jnp.int32),
            scratch_shapes=[pltpu.VMEM((r, LANES), U32)],
            interpret=_INTERPRET,
        )(tiles)


def _minimize_pallas_entry(program_bits, sizes=None):
    """Pallas-only path; caller has already checked eligibility."""
    from . import cover as _cover

    program_bits = jnp.asarray(program_bits, U32)
    n = program_bits.shape[0]
    if sizes is None:
        sizes = jax.vmap(_cover.bitset_count)(program_bits)
    order = jnp.argsort(-sizes)
    tiles, _ = _tile(program_bits[order])
    hits = _minimize_pallas(tiles)
    return jnp.zeros(n, dtype=bool).at[order].set(hits.astype(bool))


def minimize_corpus(program_bits, sizes=None):
    """Greedy set-cover keep-mask over per-program packed bitsets.

    Drop-in for ops.cover.minimize_corpus ([N, L] u32 -> [N] bool) with
    identical semantics; dispatches to the pallas kernel through the
    measured-crossover probe, else to the exact XLA scan.
    ops.cover.minimize_corpus is the production entry point and routes
    here on TPU."""
    from . import cover as _cover

    program_bits = jnp.asarray(program_bits, U32)
    n, l = program_bits.shape
    return dispatch(
        "minimize", l, n,
        lambda: _minimize_pallas_entry(program_bits, sizes),
        lambda: _cover._minimize_corpus_xla(program_bits, sizes))


# ---------------------------------------------------------------------- #
# fused cover merge + new-signal test (ISSUE 8 tentpole)

def _merge_kernel(mask, sig_ref, acc_ref, count_ref, merged_ref):
    """One grid step per signal row: test-and-set this row's sparse
    signal positions against the VMEM-resident accumulator.  The
    novelty count is the popcount delta — each scalar test that finds
    its bit clear adds one — and in-row duplicates count once because
    the bit is set the instant it is first seen.  The accumulator is
    copied from the input ONCE (step 0) and emitted as the merged
    output; no per-row dense bitset ever exists."""
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        merged_ref[:] = acc_ref[:]

    s = sig_ref.shape[1]

    def body(j, count):
        v = sig_ref[0, j]
        valid = v != U32(0xFFFFFFFF)
        pos = v & U32(mask)
        word = pos >> U32(5)
        r = jax.lax.convert_element_type(word >> U32(7), jnp.int32)
        c = jax.lax.convert_element_type(word & U32(127), jnp.int32)
        bit = pos & U32(31)
        cur = merged_ref[r, c]
        m = U32(1) << bit
        new = valid & ((cur & m) == U32(0))

        @pl.when(valid)
        def _():
            merged_ref[r, c] = cur | m

        return count + jax.lax.convert_element_type(new, jnp.int32)

    count_ref[i] = jax.lax.fori_loop(0, s, body, jnp.int32(0))


def _merge_pallas(acc_tiles, sigs, nbits: int):
    from functools import partial

    n, s = sigs.shape
    r = acc_tiles.shape[0]
    with x64_context(False):
        counts, merged = pl.pallas_call(
            partial(_merge_kernel, nbits - 1),
            grid=(n,),
            in_specs=[
                pl.BlockSpec((1, s), lambda i: (i, 0),
                             memory_space=pltpu.SMEM),
                pl.BlockSpec((r, LANES), lambda i: (0, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=[
                pl.BlockSpec((n,), lambda i: (0,),
                             memory_space=pltpu.SMEM),
                pl.BlockSpec((r, LANES), lambda i: (0, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((n,), jnp.int32),
                jax.ShapeDtypeStruct((r, LANES), U32),
            ],
            interpret=_INTERPRET,
        )(sigs, acc_tiles)
    return counts, merged


def merge_and_new_pallas(acc_bits, sigs):
    """Pallas-only fused merge + new-signal test; same contract as
    ops.cover.merge_and_new (which is the dispatching entry point).
    Caller has already checked eligibility and non-empty shapes."""
    acc_bits = jnp.asarray(acc_bits, U32)
    sigs = jnp.asarray(sigs, U32)
    l = acc_bits.shape[-1]
    acc_tiles, _ = _tile(acc_bits)
    counts, merged_tiles = _merge_pallas(acc_tiles, sigs, nbits=l * 32)
    return counts, counts > 0, merged_tiles.reshape(-1)[:l]
