"""Pallas TPU kernels for the coverage-bitset hot paths.

Two ops from ops/cover.py dominate the triage loop (reference pkg/cover:
greedy corpus Minimize, cover.go:119-146, and the SignalNew/SignalAdd hot
path, cover.go:104-182):

- ``minimize_corpus``: a data-dependent sequential pass — program i is kept
  iff it covers a bit not covered by the programs kept before it.  The XLA
  version is a lax.scan whose [L]-word carry round-trips HBM every step.
  Here the carry ("covered") lives in a VMEM scratch buffer that persists
  across the sequential TPU grid, so each step reads one program's bits
  from HBM and nothing else.

- ``signal_stats``: fold a batch of per-program bitsets into the
  accumulated set and count each program's new bits in the same pass —
  one HBM read of the batch instead of XLA's separate popcount/OR sweeps.

Both kernels view the [L]-word bitset as [R, 128] u32 tiles (VPU lane
width; R padded to the 8-sublane int32 tile).  They require the full
bitset to fit in VMEM (≤ MAX_VMEM_WORDS per buffer) — the wrappers fall
back to the exact jnp implementations above that size or off-TPU, and
run the same kernel in interpreter mode under tests (conftest forces
JAX_PLATFORMS=cpu).
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np

from . import ensure_x64  # noqa: F401
from ..utils.jaxcfg import x64_context
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

U32 = jnp.uint32
LANES = 128
SUBLANES = 8  # int32/uint32 min tile is (8, 128)

# One bitset buffer must fit comfortably in VMEM (~16 MB/core) alongside
# a same-sized block of program bits: cap at 4 MB = 1M words = 32 Mbit.
MAX_VMEM_WORDS = 1 << 20
# Per-program scalars (hit flags / new-bit counts) live in one full-array
# SMEM block written at program_id; SMEM is small, so cap the batch.
MAX_SMEM_ROWS = 4096


def _tile(bits):
    """[..., L] u32 -> [..., R, 128] with R a multiple of 8."""
    l = bits.shape[-1]
    r = -(-l // LANES)
    r_pad = -(-r // SUBLANES) * SUBLANES
    pad = r_pad * LANES - l
    if pad:
        bits = jnp.concatenate(
            [bits, jnp.zeros(bits.shape[:-1] + (pad,), U32)], axis=-1)
    return bits.reshape(bits.shape[:-1] + (r_pad, LANES)), l


# The test suite sets SYZTPU_PALLAS_INTERPRET=1 (conftest.py) to run these
# kernels through the pallas interpreter on its CPU backend — covering the
# kernel logic without a chip.  Off TPU *without* that flag, production
# dispatch falls back to the exact jnp implementations (the interpreter is
# a per-step Python emulation, far slower than the XLA scan).
_INTERPRET = os.environ.get("SYZTPU_PALLAS_INTERPRET", "") == "1"


def _use_pallas(nwords: int, nrows: int) -> bool:
    if nwords > MAX_VMEM_WORDS or nrows > MAX_SMEM_ROWS:
        return False
    return jax.devices()[0].platform == "tpu" or _INTERPRET


def _minimize_kernel(bits_ref, hit_ref, covered_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        covered_ref[:] = jnp.zeros_like(covered_ref)

    bits = bits_ref[0]
    fresh = bits & ~covered_ref[:]
    # stay strictly 32-bit signed: mosaic implements neither unsigned
    # reductions nor jnp.any's bool path under jax_enable_x64
    nz = jnp.sum(jax.lax.convert_element_type(fresh != U32(0), jnp.int32),
                 dtype=jnp.int32)
    hit = nz > 0
    hit_ref[i] = jax.lax.convert_element_type(hit, jnp.int32)

    @pl.when(hit)
    def _():
        covered_ref[:] = covered_ref[:] | bits


def _minimize_pallas(tiles):
    n, r, _ = tiles.shape
    # the kernels are strictly 32-bit; trace them with x64 off, since the
    # mosaic lowering rejects the weak-int64 scalars x64 mode introduces
    with x64_context(False):
        return pl.pallas_call(
        _minimize_kernel,
        grid=(n,),
        in_specs=[pl.BlockSpec((1, r, LANES), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((n,), lambda i: (0,),
                               memory_space=pltpu.SMEM),
        out_shape=jax.ShapeDtypeStruct((n,), jnp.int32),
            scratch_shapes=[pltpu.VMEM((r, LANES), U32)],
            interpret=_INTERPRET,
        )(tiles)


def _minimize_pallas_entry(program_bits, sizes=None):
    """Pallas-only path; caller has already checked _use_pallas."""
    from . import cover as _cover

    program_bits = jnp.asarray(program_bits, U32)
    n = program_bits.shape[0]
    if sizes is None:
        sizes = jax.vmap(_cover.bitset_count)(program_bits)
    order = jnp.argsort(-sizes)
    tiles, _ = _tile(program_bits[order])
    hits = _minimize_pallas(tiles)
    return jnp.zeros(n, dtype=bool).at[order].set(hits.astype(bool))


def minimize_corpus(program_bits, sizes=None):
    """Greedy set-cover keep-mask over per-program packed bitsets.

    Drop-in for ops.cover.minimize_corpus ([N, L] u32 -> [N] bool) with
    identical semantics; dispatches to the pallas kernel when the bitset
    fits VMEM, else to the exact XLA scan.  ops.cover.minimize_corpus is
    the production entry point and routes here on TPU."""
    from . import cover as _cover

    program_bits = jnp.asarray(program_bits, U32)
    n, l = program_bits.shape
    if not _use_pallas(l, n):
        return _cover._minimize_corpus_xla(program_bits, sizes)
    return _minimize_pallas_entry(program_bits, sizes)


def _stats_kernel(acc_ref, bits_ref, count_ref, merged_ref):
    i = pl.program_id(0)

    bits = bits_ref[0]
    fresh = bits & ~acc_ref[:]
    pops = jax.lax.convert_element_type(
        jax.lax.population_count(fresh), jnp.int32)
    count_ref[i] = jnp.sum(pops, dtype=jnp.int32)

    @pl.when(i == 0)
    def _():
        merged_ref[:] = acc_ref[:]

    merged_ref[:] = merged_ref[:] | bits


def _stats_pallas(acc_tiles, tiles):
    n, r, _ = tiles.shape
    with x64_context(False):
        counts, merged = pl.pallas_call(
        _stats_kernel,
        grid=(n,),
        in_specs=[
            pl.BlockSpec((r, LANES), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, r, LANES), lambda i: (i, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((n,), lambda i: (0,),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((r, LANES), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n,), jnp.int32),
            jax.ShapeDtypeStruct((r, LANES), U32),
        ],
            interpret=_INTERPRET,
        )(acc_tiles, tiles)
    return counts, merged


def signal_stats(acc_bits, program_bits):
    """One-pass fold + new-bit counting.

    acc_bits: [L] u32 accumulated max-signal bitset.
    program_bits: [N, L] u32 per-program signal bitsets.
    Returns (new_counts [N] int32 — bits of each program absent from
    acc_bits — and merged [L] u32 = acc | OR(programs))."""
    from . import cover as _cover

    acc_bits = jnp.asarray(acc_bits, U32)
    program_bits = jnp.asarray(program_bits, U32)
    n, l = program_bits.shape
    if not _use_pallas(l, n):
        fresh = program_bits & ~acc_bits[None, :]
        counts = jax.vmap(_cover.bitset_count)(fresh).astype(jnp.int32)
        merged = acc_bits | jax.lax.reduce(
            program_bits, np.uint32(0), jax.lax.bitwise_or, (0,))
        return counts, merged
    acc_tiles, _ = _tile(acc_bits)
    tiles, _ = _tile(program_bits)
    counts, merged_tiles = _stats_pallas(acc_tiles, tiles)
    return counts, merged_tiles.reshape(-1)[:l]
