"""Batched comparison-hint matching on device.

The host semantics live in prog/hints.py (reference
/root/reference/prog/hints.go). Here the whole workflow is a data-parallel
join, which is exactly what the TPU is good at: the executor reports
hundreds of thousands of comparison pairs per smash batch, and every
(argument value, comparison) pair is tested at once:

    variants:  each arg value expands to its 7 cast variants
               (u8/u16/u32 truncations, their sign-extensions, u64)
    join:      variants [M, 7] == comp ops [N]  ->  [M, 7, N] mask
               (broadcast compare; XLA fuses the whole thing into one
               elementwise kernel, no host loop over comparisons)
    splice:    matched comparand low bits replace the arg's low bits

Output is a dense (mask, replacer) matrix the host turns into hint mutants
(top-K per site), or the engine applies directly to tensor programs.
"""

from __future__ import annotations

from . import ensure_x64  # noqa: F401

import jax
import jax.numpy as jnp
import numpy as np

U64 = jnp.uint64

# (width, signed-extend) pairs: u8/u16/u32 zero + sign, then full u64
_WIDTHS = (8, 16, 32)
NUM_VARIANTS = 2 * len(_WIDTHS) + 1


def cast_variants(vals):
    """[...,] u64 -> ([..., 7] variant values, [7] widths).

    Variant k for k<3: zero-truncation to 8/16/32 bits; k in 3..5: the
    sign-extended form (only meaningful when the sign bit of that width is
    set — emitted as the truncation otherwise, which dedups downstream);
    k=6: the value itself (prog/hints.py shrink_expand, reference
    hints.go:150-160)."""
    v = jnp.asarray(vals, U64)
    outs = []
    widths = []
    for w in _WIDTHS:
        mask = U64((1 << w) - 1)
        outs.append(v & mask)
        widths.append(w)
    for w in _WIDTHS:
        mask = U64((1 << w) - 1)
        sign = (v >> U64(w - 1)) & U64(1)
        ext = v | ~mask
        outs.append(jnp.where(sign == 1, ext, v & mask))
        widths.append(w)
    outs.append(v)
    widths.append(64)
    return jnp.stack(outs, axis=-1), np.asarray(widths, np.uint32)


def hint_matrix(arg_vals, comp_ops, comp_args, special_ints):
    """The batched join.

    arg_vals:  [M] u64   argument values observed in the program
    comp_ops:  [N] u64   comparison first operands (what the kernel saw)
    comp_args: [N] u64   comparison second operands (what it compared to)
    special_ints: [S] u64 values to skip (generator already tries them)

    Returns (ok [M, 7, N] bool, replacer [M, 7, N] u64): for every
    (site, cast variant, comparison) the spliced replacement value and
    whether it is a valid hint (operand matched, comparand fits the cast
    width, not special, actually changes the value)."""
    av = jnp.asarray(arg_vals, U64)
    ops = jnp.asarray(comp_ops, U64)
    cargs = jnp.asarray(comp_args, U64)
    special = jnp.asarray(special_ints, U64)

    variants, widths = cast_variants(av)          # [M, 7]
    wmask = (jnp.where(
        jnp.asarray(widths) == 64,
        jnp.full((), 0xFFFFFFFFFFFFFFFF, U64),
        (U64(1) << jnp.asarray(widths, U64)) - U64(1)))  # [7]

    m = variants[:, :, None] == ops[None, None, :]          # [M,7,N] matched
    hi = cargs[None, None, :] & ~wmask[None, :, None]
    fits = (hi == 0) | (hi == (~wmask[None, :, None]))      # comparand fits
    low = cargs[None, None, :] & wmask[None, :, None]
    is_special = jnp.any(low[..., None] == special[None, None, None, :],
                         axis=-1)
    replacer = (av[:, None, None] & ~wmask[None, :, None]) | low
    ok = m & fits & ~is_special & (replacer != av[:, None, None])
    return ok, replacer


def unique_replacers(ok, replacer, max_out: int):
    """Flatten per-site hints to a padded [M, max_out] u64 with validity
    mask, deduplicating within each site. Sites produce hints in comp-table
    order; overflow beyond max_out is dropped (mirrors the reference's
    implicit cap via set iteration)."""
    M = ok.shape[0]
    flat_ok = ok.reshape(M, -1)
    flat_rep = replacer.reshape(M, -1)

    sentinel = jnp.full((), 0xFFFFFFFFFFFFFFFF, U64)

    def per_site(okr, repr_):
        # sort invalid lanes (mapped to the sentinel) to the end, dedup
        # consecutive equals, then scatter-compact the survivors.  A genuine
        # replacer of ~0 is indistinguishable from the sentinel, but ~0 is a
        # special int and already filtered by hint_matrix.
        key = jnp.sort(jnp.where(okr, repr_, sentinel))
        dup = jnp.concatenate([jnp.zeros((1,), bool), key[1:] == key[:-1]])
        valid = (key != sentinel) & ~dup
        pos = jnp.cumsum(valid) - 1
        idx = jnp.where(valid & (pos < max_out), pos, max_out)  # oob -> drop
        out = jnp.zeros((max_out,), U64).at[idx].set(key, mode="drop")
        n = jnp.minimum(jnp.sum(valid), max_out)
        return out, jnp.arange(max_out) < n

    return jax.vmap(per_site)(flat_ok, flat_rep)
