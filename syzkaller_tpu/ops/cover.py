"""Coverage engine: exact sorted-set algebra + packed-bitset hot path.

Device counterpart of the reference's pkg/cover (reference:
/root/reference/pkg/cover/cover.go): Canonicalize / Union / Intersection /
Difference / SymmetricDifference / HasDifference over sorted u32 PC sets,
SignalNew/Diff/Add against the accumulated max-signal, and greedy set-cover
corpus minimization.

Two representations:
  - exact sets: fixed-width sorted u32 arrays padded with SENT (0xffffffff),
    semantics-identical to the reference (parity-tested against a direct
    python reimplementation);
  - packed bitsets: [nbits/32] u32 lanes indexed by the low bits of the
    signal hash. Signal values are already avalanche-mixed by the executor
    (edge sig = pc ^ hash(prev)), so low bits are uniform. The fuzzer hot
    path (is-there-new-signal over thousands of programs) is a gather over
    the global bitset; merges are scatter-ORs and cross-chip union is a
    bitwise-OR all-reduce (see parallel/collective.py).
"""

from __future__ import annotations

from . import ensure_x64  # noqa: F401

import jax
import jax.numpy as jnp
import numpy as np

SENT = jnp.uint32(0xFFFFFFFF)
U32 = jnp.uint32


# ---------------------------------------------------------------------- #
# Exact sorted-set representation


def canonicalize(x, out_size: int | None = None):
    """Sort + dedup + pad with SENT."""
    x = jnp.asarray(x, U32)
    n = out_size or x.shape[-1]
    s = jnp.sort(x, axis=-1)
    dup = jnp.concatenate(
        [jnp.zeros(s.shape[:-1] + (1,), bool), s[..., 1:] == s[..., :-1]],
        axis=-1)
    s = jnp.where(dup, SENT, s)
    s = jnp.sort(s, axis=-1)
    if n != s.shape[-1]:
        pad = jnp.full(s.shape[:-1] + (max(n - s.shape[-1], 0),), SENT, U32)
        s = jnp.concatenate([s, pad], axis=-1)[..., :n]
    return s


def _member(sorted_set, values):
    """For each value: is it present in the canonical set? Supports leading
    batch dimensions on either side (searchsorted needs a 1-D haystack, so
    batched sets are vmapped)."""

    def one(ss, v):
        idx = jnp.minimum(jnp.searchsorted(ss, v), ss.shape[-1] - 1)
        return (ss[idx] == v) & (v != SENT)

    if sorted_set.ndim == 1:
        return one(sorted_set, values)
    lead = sorted_set.shape[:-1]
    ssf = sorted_set.reshape((-1, sorted_set.shape[-1]))
    vf = jnp.broadcast_to(
        values, lead + values.shape[-1:]).reshape((-1, values.shape[-1]))
    return jax.vmap(one)(ssf, vf).reshape(lead + values.shape[-1:])


def union(a, b):
    out = jnp.concatenate([a, b], axis=-1)
    return canonicalize(out)


def intersection(a, b):
    keep = _member(b, a)
    return canonicalize(jnp.where(keep, a, SENT))


def difference(a, b):
    keep = ~_member(b, a) & (a != SENT)
    return canonicalize(jnp.where(keep, a, SENT))


def symmetric_difference(a, b):
    da = jnp.where(~_member(b, a) & (a != SENT), a, SENT)
    db = jnp.where(~_member(a, b) & (b != SENT), b, SENT)
    return canonicalize(jnp.concatenate([da, db], axis=-1))


def has_difference(a, b):
    """True if a has coverage not present in b (the fuzzer hot path,
    cover.go:104-117)."""
    return jnp.any(~_member(b, a) & (a != SENT), axis=-1)


def set_size(a):
    return jnp.sum(a != SENT, axis=-1)


# ---------------------------------------------------------------------- #
# Packed bitsets

DEFAULT_BITS = 1 << 26  # 64 Mbit = 8 MB per set


def make_bitset(nbits: int = DEFAULT_BITS):
    return jnp.zeros(nbits // 32, dtype=U32)


def _index(bitset, sigs):
    nbits = bitset.shape[-1] * 32
    h = jnp.asarray(sigs, U32) & U32(nbits - 1)
    return h >> 5, (h & U32(31)).astype(U32)


def bitset_test(bitset, sigs):
    """Gather: which signals are already present? (masked for SENT)"""
    word, bit = _index(bitset, sigs)
    hit = (bitset[word] >> bit) & U32(1)
    return (hit == 1) & (jnp.asarray(sigs, U32) != SENT)


def bitset_add(bitset, sigs):
    """Scatter-OR signals into the set (SENT lanes are no-ops)."""
    word, bit = _index(bitset, sigs)
    mask = jnp.where(jnp.asarray(sigs, U32) == SENT, U32(0),
                     U32(1) << bit)
    # scatter with bitwise-or accumulation over duplicate words
    return jnp.bitwise_or.at(bitset, word, mask, inplace=False)


def bitset_count(bitset):
    return jnp.sum(jax.lax.population_count(bitset))


def bitset_or(a, b):
    return a | b


# ---- word-level core (shared by the host entries, the fused XLA/pallas
# merge, and the sharded mesh folds in parallel/mesh.py — ONE canonical
# test/scatter so a mapping change can never fork the semantics) ----


def bitset_test_words(bitset, word, bit):
    """Gather-test pre-localized (word, bit) positions: True where the
    bit is already set.  Callers mask invalid lanes themselves (their
    ``word`` must still be in range — conventionally 0)."""
    return ((bitset[word] >> bit) & U32(1)) == 1


def bitset_or_words(bitset, word, bit, valid):
    """Scatter-OR pre-localized (word, bit) positions into the set;
    lanes with ``valid`` False are no-ops (their word index must still
    be in range)."""
    mask = jnp.where(valid, U32(1) << bit, U32(0))
    return jnp.bitwise_or.at(bitset, word, mask, inplace=False)


def signal_new(max_signal_bits, sigs):
    """Per batch row: any signal not yet in the accumulated set?
    sigs: [..., S] u32 padded with SENT."""
    fresh = ~bitset_test(max_signal_bits, sigs) & \
        (jnp.asarray(sigs, U32) != SENT)
    return jnp.any(fresh, axis=-1)


def signal_diff_mask(max_signal_bits, sigs):
    """Boolean mask of the signals that are new."""
    return ~bitset_test(max_signal_bits, sigs) & \
        (jnp.asarray(sigs, U32) != SENT)


def signal_add(max_signal_bits, sigs):
    return bitset_add(max_signal_bits, jnp.asarray(sigs, U32).reshape(-1))


# ---------------------------------------------------------------------- #
# Fused cover merge + new-signal testing (ISSUE 8).
#
# merge_and_new folds a BATCH of sparse per-program signal rows into the
# accumulated bitset in one pass and reports, per row, how many distinct
# bit positions the row introduced (the popcount delta) — the exact
# semantics of scanning the rows sequentially with signal_new/signal_add
# (cover.go:160-182 in a loop), without the [rows]-step sequential scan
# or any [rows, nwords] dense materialization.  Counts are
# SEQUENTIAL-PREFIX: a bit claimed by an earlier row in the batch (or
# already in the accumulator) is not counted again, and in-row duplicate
# values count once.  SENT lanes are padding (no-ops), matching
# bitset_add/bitset_test.
#
# Three bit-identical implementations share these semantics:
#   - merge_and_new_host: the numpy mirror (the engine's max-signal
#     mirror fold and triage novelty screen run here — the accumulator
#     lives in host memory);
#   - _merge_and_new_xla: jit-safe sort-based XLA (safe under jit; the
#     off-TPU production path);
#   - pallas_cover.merge_and_new_pallas: the fused TPU kernel (VMEM-
#     resident accumulator, one HBM read of the batch).
# The eager entry dispatches pallas vs XLA through the measured-crossover
# probe (pallas_cover.dispatch); under jit it is always the XLA core.


_FUSED_COUNTER = None


def _fused_counter():
    global _FUSED_COUNTER
    if _FUSED_COUNTER is None:
        from ..telemetry import get_registry

        _FUSED_COUNTER = get_registry().counter(
            "cover_merge_fused_total",
            help="fused cover merge + new-signal passes (host mirror, "
                 "XLA, or pallas kernel)")
    return _FUSED_COUNTER


def merge_and_new(acc_bits, sigs):
    """Fused batch fold: ``acc_bits`` [L] u32 packed bitset, ``sigs``
    [N, S] u32 signal values padded with SENT.  Returns
    ``(new_counts [N] i32, new_mask [N] bool, merged [L] u32)`` where
    ``new_counts[i]`` is the number of distinct bit positions row i set
    first (sequential-prefix popcount delta) and ``merged`` is
    ``acc | OR(rows)``.  Jit-callable (XLA core under a trace); the
    eager path dispatches to the fused pallas kernel through the
    measured-crossover probe, and eager HOST inputs (numpy on a box
    with no eligible pallas path) run the numpy mirror directly — the
    same algebra without a device round-trip."""
    if isinstance(acc_bits, jax.core.Tracer) or \
            isinstance(sigs, jax.core.Tracer):
        return _merge_and_new_xla(acc_bits, sigs)
    from . import pallas_cover

    host_in = isinstance(acc_bits, np.ndarray) and \
        isinstance(sigs, np.ndarray)
    acc_bits = jnp.asarray(acc_bits, U32) if not host_in else acc_bits
    sigs = jnp.asarray(sigs, U32) if not host_in else \
        np.asarray(sigs, np.uint32)
    n, s = sigs.shape
    if n == 0 or s == 0:
        return (jnp.zeros((n,), jnp.int32), jnp.zeros((n,), bool),
                acc_bits)
    if host_in and not pallas_cover._eligible(acc_bits.shape[-1], n,
                                              lanes=s):
        # host-resident inputs off the accelerator: the numpy mirror IS
        # the fused implementation (it's what the engine's fold and
        # screen run); a jnp round-trip here would only add dispatch
        # overhead on top of the same algebra
        pallas_cover._fallback_counter().inc()
        return merge_and_new_host(
            np.array(acc_bits, dtype=np.uint32), sigs, update=True)
    _fused_counter().inc()
    return pallas_cover.dispatch(
        "merge", acc_bits.shape[-1], n,
        lambda: pallas_cover.merge_and_new_pallas(acc_bits, sigs),
        lambda: _merge_and_new_xla(acc_bits, sigs),
        lanes=s)


def _merge_and_new_xla(acc_bits, sigs):
    """Exact XLA implementation (safe under jit; pallas fallback).
    Sequential-prefix counts come from a sort by (bit position, row):
    the first valid occurrence of each position is charged to its row
    iff the accumulator doesn't already hold it — O(NS log NS) batched
    ops instead of an N-step sequential scan."""
    U64 = jnp.uint64
    acc = jnp.asarray(acc_bits, U32)
    sigs = jnp.asarray(sigs, U32)
    n, s = sigs.shape
    if n == 0 or s == 0:
        return jnp.zeros((n,), jnp.int32), jnp.zeros((n,), bool), acc
    nbits = acc.shape[-1] * 32
    flat = sigs.reshape(-1)
    valid = flat != SENT
    pos = flat & U32(nbits - 1)
    rows = jnp.repeat(jnp.arange(n, dtype=U32), s)
    key = jnp.where(valid,
                    (pos.astype(U64) << U64(32)) | rows.astype(U64),
                    U64(0xFFFFFFFFFFFFFFFF))
    skey = jnp.sort(key)
    svalid = skey != U64(0xFFFFFFFFFFFFFFFF)
    spos = (skey >> U64(32)).astype(U32)
    srow = (skey & U64(0xFFFFFFFF)).astype(jnp.int32)
    first = jnp.concatenate(
        [jnp.ones((1,), bool), spos[1:] != spos[:-1]])
    word = jnp.where(svalid, (spos >> 5).astype(jnp.int32), 0)
    known = bitset_test_words(acc, word, spos & U32(31))
    newbit = svalid & first & ~known
    counts = jax.ops.segment_sum(
        newbit.astype(jnp.int32), jnp.where(svalid, srow, n),
        num_segments=n + 1)[:n]
    merged = bitset_add(acc, flat)
    return counts, counts > 0, merged


# claim-table strategy ceiling: below this the first-occurrence dedup
# uses an O(nbits) scratch table (no sort at all) — the big-batch fast
# path; above it (the engine's 2^26 mirrors) the scratch would be
# hundreds of MB, so the sort path runs (its batches are small)
CLAIM_TABLE_MAX_BITS = 1 << 24
# the claim table only pays for itself on big batches; tiny scans
# (the drain's per-execution novelty screen) stay on the sort path
CLAIM_TABLE_MIN_ELEMS = 1 << 12


def merge_and_new_host(acc, sigs, update=False):
    """Bit-identical numpy mirror of ``merge_and_new`` over a HOST
    accumulator.  ``update=True`` ORs the new bits into ``acc`` IN
    PLACE and returns it (the engine's 8 MB max-signal mirror must not
    copy per batch); ``update=False`` performs NO fold — the returned
    accumulator is the input object untouched (the triage novelty
    screen only wants the verdicts).

    Two internal strategies, identical results: a sort by bit position
    (stable, so the first occurrence keeps the lowest row), or — for
    big batches over small-enough tables — a sort-FREE claim pass: an
    uninitialized [nbits] scratch is fancy-stored in reverse flat
    order, so each position ends up claimed by its first occurrence,
    and the merged fold packs a bool plane instead of a scatter-OR."""
    acc = np.asarray(acc)
    sigs = np.asarray(sigs, dtype=np.uint32)
    n = sigs.shape[0]
    counts = np.zeros(n, dtype=np.int32)
    _fused_counter().inc()
    if not (n and sigs.shape[1]):
        return counts, counts > 0, acc
    nbits = acc.shape[-1] * 32
    flat = sigs.reshape(-1)
    keep = flat != np.uint32(0xFFFFFFFF)
    if nbits <= CLAIM_TABLE_MAX_BITS and \
            flat.size >= CLAIM_TABLE_MIN_ELEMS:
        fidx = np.nonzero(keep)[0].astype(np.int64)
        pos = (flat[fidx] & np.uint32(nbits - 1)).astype(np.int64)
        # np.empty is deliberate: every position read back below was
        # written by the reversed store (last write wins => the FIRST
        # flat occurrence claims the position); untouched garbage is
        # never read
        claim = np.empty(nbits, dtype=np.int64)
        claim[pos[::-1]] = fidx[::-1]
        firstf = claim[pos] == fidx
        pf = pos[firstf]
        rowsf = fidx[firstf] // sigs.shape[1]
        known = ((acc[pf >> 5] >> (pf & 31).astype(np.uint32))
                 & np.uint32(1)).astype(bool)
        counts += np.bincount(rowsf[~known],
                              minlength=n).astype(np.int32)
        if update and pf.size:
            plane = np.zeros(nbits, dtype=bool)
            plane[pf] = True
            acc |= np.packbits(plane, bitorder="little").view(np.uint32)
        return counts, counts > 0, acc
    pos = (flat & np.uint32(nbits - 1))[keep].astype(np.int64)
    rows = np.repeat(np.arange(n, dtype=np.int64),
                     sigs.shape[1])[keep]
    # stable single-key sort: rows already ascend in flat order, so
    # the first element of each equal-position run has the lowest row
    order = np.argsort(pos, kind="stable")
    ps, rs = pos[order], rows[order]
    first = np.ones(ps.size, dtype=bool)
    first[1:] = ps[1:] != ps[:-1]
    word = ps >> 5
    bit = (ps & 31).astype(np.uint32)
    known = ((acc[word] >> bit) & np.uint32(1)).astype(bool)
    newbit = first & ~known
    np.add.at(counts, rs[newbit], 1)
    if update:
        np.bitwise_or.at(acc, word[first],
                         np.uint32(1) << bit[first])
    return counts, counts > 0, acc


def bitset_add_host(bits, values) -> None:
    """In-place host scatter-OR of signal VALUES into a numpy packed
    bitset (the numpy twin of ``bitset_add``; values wrap to u32, exact
    SENT is a no-op like the device ops)."""
    v = np.asarray(list(values), dtype=np.uint64) if not \
        isinstance(values, np.ndarray) else values.astype(np.uint64)
    if v.size == 0:
        return
    vv = (v & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    vv = vv[vv != np.uint32(0xFFFFFFFF)]
    if vv.size == 0:
        return
    nbits = bits.shape[-1] * 32
    pos = (vv & np.uint32(nbits - 1)).astype(np.int64)
    np.bitwise_or.at(bits, pos >> 5,
                     np.uint32(1) << (pos & 31).astype(np.uint32))


# ---------------------------------------------------------------------- #
# Corpus minimization: greedy set cover (cover.go:119-146), device version
# over per-program bitsets.


def minimize_corpus(program_bits, sizes=None):
    """program_bits: [N, L] u32 packed coverage per program.
    Returns keep mask [N] bool — the greedy cover: programs in decreasing
    coverage-size order, kept iff they add an uncovered bit.

    Dispatches to the pallas kernel (ops/pallas_cover.py) through the
    measured-crossover probe when the bitset fits VMEM; this function is
    the exact XLA-scan semantics both share.  Call _minimize_corpus_xla
    directly from inside jit (the pallas wrapper is eager).  The eager
    entry is span-timed (``cover.minimize``) — corpus minimization is a
    triage-ladder phase the manager graphs."""
    if not isinstance(program_bits, jax.core.Tracer):
        from . import pallas_cover
        from ..telemetry import get_tracer

        pb = jnp.asarray(program_bits, U32)
        # block inside the span (jax dispatch is async; an enqueue-only
        # timing reads near-zero regardless of corpus size) — but only
        # when spans are on: the barrier is the span's cost, not the
        # caller's
        tracer = get_tracer()
        with tracer.span("cover.minimize"):
            out = pallas_cover.dispatch(
                "minimize", pb.shape[-1], pb.shape[0],
                lambda: pallas_cover._minimize_pallas_entry(pb, sizes),
                lambda: _minimize_corpus_xla(program_bits, sizes))
            if tracer.enabled:
                jax.block_until_ready(out)
        return out
    return _minimize_corpus_xla(program_bits, sizes)


def _minimize_corpus_xla(program_bits, sizes=None):
    """Exact XLA implementation (safe under jit; pallas fallback)."""
    program_bits = jnp.asarray(program_bits)
    n = program_bits.shape[0]
    if sizes is None:
        sizes = jax.vmap(bitset_count)(program_bits)
    order = jnp.argsort(-sizes)

    def step(covered, i):
        bits = program_bits[i]
        newbits = bits & ~covered
        hit = jnp.any(newbits != 0)
        covered = jnp.where(hit, covered | bits, covered)
        return covered, hit

    covered0 = jnp.zeros_like(program_bits[0])
    _, hits = jax.lax.scan(step, covered0, order)
    keep = jnp.zeros(n, dtype=bool).at[order].set(hits)
    return keep


# ---------------------------------------------------------------------- #
# Host-side exact reference (used by parity tests and host corpus records)


def py_canonicalize(cov):
    return sorted(set(int(x) for x in cov))


def py_union(a, b):
    return sorted(set(a) | set(b))


def py_intersection(a, b):
    return sorted(set(a) & set(b))


def py_difference(a, b):
    return sorted(set(a) - set(b))


def py_symmetric_difference(a, b):
    return sorted(set(a) ^ set(b))


def py_has_difference(a, b):
    return bool(set(a) - set(b))


def py_minimize(corpus):
    """Greedy set cover over exact sets; returns kept indices
    (cover.go:119-146 semantics: larger covers first, keep if any new)."""
    order = sorted(range(len(corpus)), key=lambda i: -len(corpus[i]))
    covered: set = set()
    keep = []
    for i in order:
        cov = set(corpus[i])
        if cov - covered:
            keep.append(i)
            covered |= cov
    return sorted(keep)


def pad_set(values, size: int) -> np.ndarray:
    """Host helper: exact set -> padded sorted u32 array."""
    vals = sorted(set(int(v) & 0xFFFFFFFF for v in values))[:size]
    out = np.full(size, 0xFFFFFFFF, dtype=np.uint32)
    out[: len(vals)] = np.array(vals, dtype=np.uint32)
    return out
