"""Coverage engine: exact sorted-set algebra + packed-bitset hot path.

Device counterpart of the reference's pkg/cover (reference:
/root/reference/pkg/cover/cover.go): Canonicalize / Union / Intersection /
Difference / SymmetricDifference / HasDifference over sorted u32 PC sets,
SignalNew/Diff/Add against the accumulated max-signal, and greedy set-cover
corpus minimization.

Two representations:
  - exact sets: fixed-width sorted u32 arrays padded with SENT (0xffffffff),
    semantics-identical to the reference (parity-tested against a direct
    python reimplementation);
  - packed bitsets: [nbits/32] u32 lanes indexed by the low bits of the
    signal hash. Signal values are already avalanche-mixed by the executor
    (edge sig = pc ^ hash(prev)), so low bits are uniform. The fuzzer hot
    path (is-there-new-signal over thousands of programs) is a gather over
    the global bitset; merges are scatter-ORs and cross-chip union is a
    bitwise-OR all-reduce (see parallel/collective.py).
"""

from __future__ import annotations

from . import ensure_x64  # noqa: F401

import jax
import jax.numpy as jnp
import numpy as np

SENT = jnp.uint32(0xFFFFFFFF)
U32 = jnp.uint32


# ---------------------------------------------------------------------- #
# Exact sorted-set representation


def canonicalize(x, out_size: int | None = None):
    """Sort + dedup + pad with SENT."""
    x = jnp.asarray(x, U32)
    n = out_size or x.shape[-1]
    s = jnp.sort(x, axis=-1)
    dup = jnp.concatenate(
        [jnp.zeros(s.shape[:-1] + (1,), bool), s[..., 1:] == s[..., :-1]],
        axis=-1)
    s = jnp.where(dup, SENT, s)
    s = jnp.sort(s, axis=-1)
    if n != s.shape[-1]:
        pad = jnp.full(s.shape[:-1] + (max(n - s.shape[-1], 0),), SENT, U32)
        s = jnp.concatenate([s, pad], axis=-1)[..., :n]
    return s


def _member(sorted_set, values):
    """For each value: is it present in the canonical set? Supports leading
    batch dimensions on either side (searchsorted needs a 1-D haystack, so
    batched sets are vmapped)."""

    def one(ss, v):
        idx = jnp.minimum(jnp.searchsorted(ss, v), ss.shape[-1] - 1)
        return (ss[idx] == v) & (v != SENT)

    if sorted_set.ndim == 1:
        return one(sorted_set, values)
    lead = sorted_set.shape[:-1]
    ssf = sorted_set.reshape((-1, sorted_set.shape[-1]))
    vf = jnp.broadcast_to(
        values, lead + values.shape[-1:]).reshape((-1, values.shape[-1]))
    return jax.vmap(one)(ssf, vf).reshape(lead + values.shape[-1:])


def union(a, b):
    out = jnp.concatenate([a, b], axis=-1)
    return canonicalize(out)


def intersection(a, b):
    keep = _member(b, a)
    return canonicalize(jnp.where(keep, a, SENT))


def difference(a, b):
    keep = ~_member(b, a) & (a != SENT)
    return canonicalize(jnp.where(keep, a, SENT))


def symmetric_difference(a, b):
    da = jnp.where(~_member(b, a) & (a != SENT), a, SENT)
    db = jnp.where(~_member(a, b) & (b != SENT), b, SENT)
    return canonicalize(jnp.concatenate([da, db], axis=-1))


def has_difference(a, b):
    """True if a has coverage not present in b (the fuzzer hot path,
    cover.go:104-117)."""
    return jnp.any(~_member(b, a) & (a != SENT), axis=-1)


def set_size(a):
    return jnp.sum(a != SENT, axis=-1)


# ---------------------------------------------------------------------- #
# Packed bitsets

DEFAULT_BITS = 1 << 26  # 64 Mbit = 8 MB per set


def make_bitset(nbits: int = DEFAULT_BITS):
    return jnp.zeros(nbits // 32, dtype=U32)


def _index(bitset, sigs):
    nbits = bitset.shape[-1] * 32
    h = jnp.asarray(sigs, U32) & U32(nbits - 1)
    return h >> 5, (h & U32(31)).astype(U32)


def bitset_test(bitset, sigs):
    """Gather: which signals are already present? (masked for SENT)"""
    word, bit = _index(bitset, sigs)
    hit = (bitset[word] >> bit) & U32(1)
    return (hit == 1) & (jnp.asarray(sigs, U32) != SENT)


def bitset_add(bitset, sigs):
    """Scatter-OR signals into the set (SENT lanes are no-ops)."""
    word, bit = _index(bitset, sigs)
    mask = jnp.where(jnp.asarray(sigs, U32) == SENT, U32(0),
                     U32(1) << bit)
    # scatter with bitwise-or accumulation over duplicate words
    return jnp.bitwise_or.at(bitset, word, mask, inplace=False)


def bitset_count(bitset):
    return jnp.sum(jax.lax.population_count(bitset))


def bitset_or(a, b):
    return a | b


def signal_new(max_signal_bits, sigs):
    """Per batch row: any signal not yet in the accumulated set?
    sigs: [..., S] u32 padded with SENT."""
    fresh = ~bitset_test(max_signal_bits, sigs) & \
        (jnp.asarray(sigs, U32) != SENT)
    return jnp.any(fresh, axis=-1)


def signal_diff_mask(max_signal_bits, sigs):
    """Boolean mask of the signals that are new."""
    return ~bitset_test(max_signal_bits, sigs) & \
        (jnp.asarray(sigs, U32) != SENT)


def signal_add(max_signal_bits, sigs):
    return bitset_add(max_signal_bits, jnp.asarray(sigs, U32).reshape(-1))


# ---------------------------------------------------------------------- #
# Corpus minimization: greedy set cover (cover.go:119-146), device version
# over per-program bitsets.


def minimize_corpus(program_bits, sizes=None):
    """program_bits: [N, L] u32 packed coverage per program.
    Returns keep mask [N] bool — the greedy cover: programs in decreasing
    coverage-size order, kept iff they add an uncovered bit.

    Dispatches to the pallas kernel (ops/pallas_cover.py) on TPU when the
    bitset fits VMEM; this function is the exact XLA-scan semantics both
    share.  Call _minimize_corpus_xla directly from inside jit (the pallas
    wrapper is eager).  The eager entry is span-timed (``cover.minimize``)
    — corpus minimization is a triage-ladder phase the manager graphs."""
    if not isinstance(program_bits, jax.core.Tracer):
        from . import pallas_cover
        from ..telemetry import get_tracer

        pb = jnp.asarray(program_bits, U32)
        # block inside the span (jax dispatch is async; an enqueue-only
        # timing reads near-zero regardless of corpus size) — but only
        # when spans are on: the barrier is the span's cost, not the
        # caller's
        tracer = get_tracer()
        with tracer.span("cover.minimize"):
            if pallas_cover._use_pallas(pb.shape[-1], pb.shape[0]):
                out = pallas_cover._minimize_pallas_entry(pb, sizes)
            else:
                out = _minimize_corpus_xla(program_bits, sizes)
            if tracer.enabled:
                jax.block_until_ready(out)
        return out
    return _minimize_corpus_xla(program_bits, sizes)


def _minimize_corpus_xla(program_bits, sizes=None):
    """Exact XLA implementation (safe under jit; pallas fallback)."""
    program_bits = jnp.asarray(program_bits)
    n = program_bits.shape[0]
    if sizes is None:
        sizes = jax.vmap(bitset_count)(program_bits)
    order = jnp.argsort(-sizes)

    def step(covered, i):
        bits = program_bits[i]
        newbits = bits & ~covered
        hit = jnp.any(newbits != 0)
        covered = jnp.where(hit, covered | bits, covered)
        return covered, hit

    covered0 = jnp.zeros_like(program_bits[0])
    _, hits = jax.lax.scan(step, covered0, order)
    keep = jnp.zeros(n, dtype=bool).at[order].set(hits)
    return keep


# ---------------------------------------------------------------------- #
# Host-side exact reference (used by parity tests and host corpus records)


def py_canonicalize(cov):
    return sorted(set(int(x) for x in cov))


def py_union(a, b):
    return sorted(set(a) | set(b))


def py_intersection(a, b):
    return sorted(set(a) & set(b))


def py_difference(a, b):
    return sorted(set(a) - set(b))


def py_symmetric_difference(a, b):
    return sorted(set(a) ^ set(b))


def py_has_difference(a, b):
    return bool(set(a) - set(b))


def py_minimize(corpus):
    """Greedy set cover over exact sets; returns kept indices
    (cover.go:119-146 semantics: larger covers first, keep if any new)."""
    order = sorted(range(len(corpus)), key=lambda i: -len(corpus[i]))
    covered: set = set()
    keep = []
    for i in order:
        cov = set(corpus[i])
        if cov - covered:
            keep.append(i)
            covered |= cov
    return sorted(keep)


def pad_set(values, size: int) -> np.ndarray:
    """Host helper: exact set -> padded sorted u32 array."""
    vals = sorted(set(int(v) & 0xFFFFFFFF for v in values))[:size]
    out = np.full(size, 0xFFFFFFFF, dtype=np.uint32)
    out[: len(vals)] = np.array(vals, dtype=np.uint32)
    return out
