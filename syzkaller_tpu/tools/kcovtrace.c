/* kcovtrace: strace-like coverage tracer.
 *
 * Capability parity with reference /root/reference/tools/kcovtrace
 * (kcovtrace.c): run a command with KCOV enabled and print every covered
 * kernel PC to stdout, one hex per line.  Original implementation against
 * the documented KCOV uapi (linux/kcov.h ioctls).
 *
 * Usage: kcovtrace command [args...]
 */

#include <fcntl.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/ioctl.h>
#include <sys/mman.h>
#include <sys/types.h>
#include <sys/wait.h>
#include <unistd.h>

#define KCOV_INIT_TRACE _IOR('c', 1, unsigned long)
#define KCOV_ENABLE _IO('c', 100)
#define KCOV_DISABLE _IO('c', 101)
#define COVER_SIZE (64 << 10)

int main(int argc, char **argv)
{
	int fd, status;
	uint64_t *cover, n, i;
	pid_t pid;

	if (argc < 2) {
		fprintf(stderr, "usage: %s command [args...]\n", argv[0]);
		return 1;
	}
	fd = open("/sys/kernel/debug/kcov", O_RDWR);
	if (fd == -1) {
		perror("open /sys/kernel/debug/kcov");
		return 1;
	}
	if (ioctl(fd, KCOV_INIT_TRACE, COVER_SIZE)) {
		perror("KCOV_INIT_TRACE");
		return 1;
	}
	cover = (uint64_t*)mmap(NULL, COVER_SIZE * sizeof(uint64_t),
				PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
	if (cover == MAP_FAILED) {
		perror("mmap");
		return 1;
	}
	pid = fork();
	if (pid < 0) {
		perror("fork");
		return 1;
	}
	if (pid == 0) {
		/* child: enable tracing for THIS task, then exec */
		if (ioctl(fd, KCOV_ENABLE, 0)) {
			perror("KCOV_ENABLE");
			_exit(1);
		}
		__atomic_store_n(&cover[0], 0, __ATOMIC_RELAXED);
		execvp(argv[1], argv + 1);
		perror("execvp");
		_exit(1);
	}
	waitpid(pid, &status, 0);
	n = __atomic_load_n(&cover[0], __ATOMIC_RELAXED);
	for (i = 0; i < n && i < COVER_SIZE - 1; i++)
		printf("0x%lx\n", (unsigned long)cover[i + 1]);
	if (ioctl(fd, KCOV_DISABLE, 0)) {
		/* the child held the enable; disable may legitimately fail */
	}
	close(fd);
	return WIFEXITED(status) ? WEXITSTATUS(status) : 1;
}
