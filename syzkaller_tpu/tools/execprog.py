"""Replay programs from a file or fuzzer log against the executor.

Capability parity with reference /root/reference/tools/syz-execprog:
reads programs (blank-line-separated text, or a fuzzer log with
`executing program` markers), executes them with configurable
threaded/collide/fault options in a repeat loop. The repro pipeline runs
this inside VM instances to test crash hypotheses.
"""

from __future__ import annotations

import argparse
import sys
from typing import List


def load_progs(target, data: str) -> List:
    """Programs from either a fuzzer log or plain serialized text."""
    from ..prog.parse import parse_log

    if "executing program" in data:
        return [e.p for e in parse_log(target, data)]
    from ..prog.encoding import deserialize

    progs = []
    for chunk in data.split("\n\n"):
        chunk = chunk.strip()
        if not chunk:
            continue
        try:
            progs.append(deserialize(target, chunk + "\n"))
        except Exception as e:
            print(f"skipping unparsable program: {e}", file=sys.stderr)
    return progs


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="syz-execprog")
    ap.add_argument("files", nargs="+", help="program files or fuzzer logs")
    ap.add_argument("-os", default="linux")
    ap.add_argument("-arch", default="amd64")
    ap.add_argument("-procs", type=int, default=1)
    ap.add_argument("-repeat", type=int, default=1,
                    help="0 = loop forever")
    ap.add_argument("-sandbox", default="none")
    ap.add_argument("-threaded", action="store_true")
    ap.add_argument("-collide", action="store_true")
    ap.add_argument("-cover", action="store_true")
    ap.add_argument("-fault-call", dest="fault_call", type=int, default=-1)
    ap.add_argument("-fault-nth", dest="fault_nth", type=int, default=0)
    ap.add_argument("-mock", action="store_true",
                    help="mock executor (no real syscalls)")
    ap.add_argument("-v", type=int, default=0)
    args = ap.parse_args(argv)

    from ..ipc import Env, EnvConfig, ExecOpts, MockEnv
    from ..prog import get_target

    target = get_target(args.os, args.arch)
    progs = []
    for path in args.files:
        with open(path, "r", errors="replace") as f:
            progs.extend(load_progs(target, f.read()))
    if not progs:
        print("no programs to execute", file=sys.stderr)
        return 1

    opts = ExecOpts(threaded=args.threaded, collide=args.collide,
                    collect_cover=args.cover,
                    fault_call=args.fault_call, fault_nth=args.fault_nth)
    if args.mock:
        envs = [MockEnv(target, pid=i) for i in range(args.procs)]
    else:
        ec = EnvConfig(sandbox=args.sandbox)
        envs = [Env(target, pid=i, config=ec) for i in range(args.procs)]
    try:
        n = 0
        rep = 0
        while True:
            for i, p in enumerate(progs):
                env = envs[i % len(envs)]
                _, infos, failed, hanged = env.exec(opts, p)
                n += 1
                if args.v > 0:
                    ok = sum(1 for x in infos if x.executed)
                    print(f"executed {n}: {len(p.calls)} calls, "
                          f"{ok} ran, failed={failed} hanged={hanged}",
                          flush=True)
            rep += 1
            if args.repeat and rep >= args.repeat:
                break
        print(f"executed {n} programs", flush=True)
        return 0
    finally:
        for e in envs:
            e.close()


if __name__ == "__main__":
    sys.exit(main())
