"""Render a serialized program as a standalone C reproducer
(reference /root/reference/tools/syz-prog2c/prog2c.go)."""

from __future__ import annotations

import argparse
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="syz-prog2c")
    ap.add_argument("file", nargs="?", help="program file (default stdin)")
    ap.add_argument("-os", default="linux")
    ap.add_argument("-arch", default="amd64")
    ap.add_argument("-threaded", action="store_true")
    ap.add_argument("-collide", action="store_true")
    ap.add_argument("-repeat", action="store_true")
    ap.add_argument("-procs", type=int, default=1)
    ap.add_argument("-sandbox", default="")
    ap.add_argument("-fault-call", dest="fault_call", type=int, default=-1)
    ap.add_argument("-fault-nth", dest="fault_nth", type=int, default=0)
    args = ap.parse_args(argv)

    from ..csource import Options, write
    from ..prog import get_target
    from ..prog.encoding import deserialize

    target = get_target(args.os, args.arch)
    data = (open(args.file).read() if args.file else sys.stdin.read())
    p = deserialize(target, data)
    opts = Options(threaded=args.threaded, collide=args.collide,
                   repeat=args.repeat, procs=args.procs,
                   sandbox=args.sandbox,
                   fault=args.fault_call >= 0,
                   fault_call=args.fault_call, fault_nth=args.fault_nth)
    sys.stdout.write(write(p, opts))
    return 0


if __name__ == "__main__":
    sys.exit(main())
