"""Mutate a single program and print the result (reference
/root/reference/tools/syz-mutate/mutate.go).  This is BASELINE config #1's
CPU measurement tool: `-loop N` times the host-CPU tree mutator;
`-device` runs the same workload through the batched TPU kernel so the
two paths can be compared on identical distributions.
"""

from __future__ import annotations

import argparse
import sys
import time


def _bench_device(target, n: int, B: int = 4096, C: int = 16) -> float:
    """Batched device mutation throughput over ~n programs total."""
    import jax

    from ..descriptions.tables import get_tables
    from ..ops import mutation as dmut
    from ..ops.dtables import build_device_tables
    from ..prog.tensor import TensorFormat

    tables = get_tables(target)
    fmt = TensorFormat.for_tables(tables, max_calls=C)
    dt = build_device_tables(tables, fmt)
    iters = max(1, n // B)

    key = jax.random.PRNGKey(0)
    cid, sval, data = dmut.generate_batch(key, dt, B=B, C=C)
    step = jax.jit(lambda k, c, s, d: dmut.mutate_batch(k, dt, c, s, d))
    out = step(key, cid, sval, data)            # compile
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for i in range(iters):
        out = step(jax.random.fold_in(key, i), *out)
    jax.block_until_ready(out)
    return B * iters / (time.perf_counter() - t0)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="syz-mutate")
    ap.add_argument("file", nargs="?", help="program file (default stdin)")
    ap.add_argument("-os", default="linux")
    ap.add_argument("-arch", default="amd64")
    ap.add_argument("-seed", type=int, default=None)
    ap.add_argument("-len", dest="ncalls", type=int, default=30,
                    help="max program length")
    ap.add_argument("-corpus", help="corpus.db to splice from")
    ap.add_argument("-loop", type=int, default=0,
                    help="benchmark: mutate N times, print progs/sec")
    ap.add_argument("-device", action="store_true",
                    help="benchmark on the TPU mutation kernel instead")
    args = ap.parse_args(argv)

    from ..prog import get_target
    from ..prog.encoding import deserialize, serialize
    from ..prog.generation import generate
    from ..prog.mutation import mutate

    target = get_target(args.os, args.arch)
    if args.file:
        with open(args.file) as f:
            p = deserialize(target, f.read())
    elif not sys.stdin.isatty():
        p = deserialize(target, sys.stdin.read())
    else:
        p = generate(target, args.seed or 0, args.ncalls)

    from . import load_corpus_db
    corpus = load_corpus_db(target, args.corpus) if args.corpus else []

    if args.loop:
        if args.device:
            rate = _bench_device(target, n=args.loop)
        else:
            t0 = time.perf_counter()
            for i in range(args.loop):
                q = p.clone()
                mutate(q, (args.seed or 0) * 1000003 + i, args.ncalls,
                       corpus=corpus or None)
            rate = args.loop / (time.perf_counter() - t0)
        print(f"{rate:.1f} progs/sec", file=sys.stderr)
        return 0

    mutate(p, args.seed, args.ncalls, corpus=corpus or None)
    sys.stdout.write(serialize(p))
    return 0


if __name__ == "__main__":
    sys.exit(main())
