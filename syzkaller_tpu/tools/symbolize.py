"""Symbolize a kernel crash report (reference
/root/reference/tools/syz-symbolize/symbolize.go): parses the report,
rewrites stack-trace PCs to file:line via the vmlinux symbol table +
addr2line, prints crash title and guilty file.
"""

from __future__ import annotations

import argparse
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="syz-symbolize")
    ap.add_argument("file", help="console log / crash report file")
    ap.add_argument("-vmlinux", help="kernel image with debug info")
    args = ap.parse_args(argv)

    from .. import report as rep

    with open(args.file, "r", errors="replace") as f:
        output = f.read()

    r = rep.parse(output)
    if r is None:
        print("no crash found in the log", file=sys.stderr)
        return 1
    print(f"TITLE: {r.title}")
    guilty = rep.extract_guilty_file(r.report)
    if guilty:
        print(f"GUILTY FILE: {guilty}")
    text = r.report
    if args.vmlinux:
        from ..report.symbolize import Symbolizer
        text = Symbolizer(args.vmlinux).symbolize_report(text)
    print()
    print(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
