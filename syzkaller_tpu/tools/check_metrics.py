"""Metric-namespace linter: keep registry metric names coherent.

Walks the package source for literal metric registrations —
``<registry>.counter("name", ...)`` / ``.gauge`` / ``.histogram`` — and
fails on:

  - non-snake_case names (the Prometheus exposition and the BENCH JSON
    schema both assume ``[a-z][a-z0-9_]*``);
  - undocumented names: a name every registration site leaves without a
    ``help=`` string never reaches ``# HELP`` on /metrics, so operators
    can't tell what it measures.  One documented site is enough — hot
    paths may re-bind the same metric without repeating the help text.

Dynamically built names (``"fleet_" + k``, the tracer's ``span_*``
histograms) are exempt by construction: only string-literal first
arguments are checked.  REQUIRED_METRICS additionally pins names that
must never lose their registration (the ``arena_*`` corpus-arena family,
the drain/device-health gauges) — dropping one breaks dashboards and
capacity tuning silently.  Invoked from the test suite
(tests/test_analytics.py) so the namespace stays coherent as it grows;
also runnable as ``python -m syzkaller_tpu.tools.check_metrics``
(``--require name1,name2`` overrides the pinned set; a trailing ``*``
matches a prefix family).
"""

from __future__ import annotations

import ast
import os
import re
import sys
from typing import Dict, List, NamedTuple

SNAKE_CASE = re.compile(r"^[a-z][a-z0-9_]*$")
METRIC_METHODS = ("counter", "gauge", "histogram")

# Metrics the package must keep registered (a refactor that silently drops
# one breaks dashboards and the BENCH JSON schema).  A trailing ``*``
# requires at least one registration with that prefix.  Applied when
# linting the package itself (default root); explicit roots — the unit
# tests' tmp trees — are exempt unless the caller passes ``required``.
REQUIRED_METRICS = (
    # device-resident corpus arena (ISSUE 3): occupancy / evictions /
    # resident bytes must stay visible for capacity tuning
    "arena_occupancy",
    "arena_evictions_total",
    "arena_resident_bytes",
    # device-side candidate admission + yield-weighted scheduling
    # (ISSUE 5): the dedup win and the Bloom decay policy must stay
    # auditable, and weighted eviction must stay distinguishable from
    # plain ring overwrite
    "candidates_deduped_total",
    "candidates_admitted_total",
    "admission_bloom_occupancy",
    "admission_bloom_resets_total",
    "arena_weighted_evictions_total",
    # parallel executor fan-out: env utilization of the batch drain
    "device_drain_env_occupancy",
    # prefix-memoized batch execution (ISSUE 6): the memo's win
    # (calls saved) and health (hit/miss) must stay auditable, the
    # executed-call denominator must stay countable, and silent row
    # loss + yield decay must stay visible
    "prefix_cache_hits_total",
    "prefix_cache_misses_total",
    "prefix_calls_saved_total",
    "calls_executed_total",
    "drain_rows_dropped_total",
    "arena_yield_decays_total",
    # device health family (ISSUE 2)
    "device_batch_occupancy",
    "device_live_buffer_bytes",
    # campaign supervision (ISSUE 4): checkpoint/resume, env supervisor,
    # RPC retry, degradation ladder, visible-error accounting
    "env_restarts_total",
    "env_quarantined",
    "env_watchdog_trips_total",
    "env_kill_escalations_total",
    "checkpoint_write_seconds",
    "checkpoint_age_seconds",
    "rpc_errors_total",
    "rpc_retries_total",
    "device_degraded_total",
    "errors_total",
    # fused signal path (ISSUE 8): silent host fallback off the pallas
    # cover kernels must stay visible, fused merges must stay
    # countable, and the batched-bisection round economy must stay
    # auditable next to the probe execs it carries
    "pallas_cover_fallback_total",
    "cover_merge_fused_total",
    "minimize_bisect_rounds_total",
    "minimize_batch_execs_total",
    # fleet observability (ISSUE 7): the durable campaign journal's
    # volume must stay visible (record/byte growth is the replay-cost
    # axis), and the fleet aggregator's scrape health must never go
    # silent — a fleet that can't see its engines isn't a fleet
    "journal_records_total",
    "journal_bytes_total",
    "fleet_scrape_errors_total",
    "fleet_engines_online",
    # compiler frontend (ISSUE 16): the hlo differential executor's
    # compile economy and findings-by-failure-mode must stay visible —
    # the /stats.json "frontend" block and the dashboard table read
    # these, and the bench hlo_e2e config derives its hit rate from them
    "frontend_compiles_total",
    "frontend_compile_cache_hits_total",
    "frontend_miscompares_total",
    "frontend_exceptions_total",
    "frontend_exec_timeouts_total",
    # async pipelined device step (ISSUE 18): ring occupancy is the
    # pipeline's health signal and stalls are its honest cost — the
    # depth sweep in bench.py reads both next to execs/sec
    "device_pipeline_inflight",
    "device_pipeline_stalls_total",
)


class Registration(NamedTuple):
    name: str
    file: str
    line: int
    has_help: bool


def _package_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def collect_registrations(root: str = "") -> List[Registration]:
    root = root or _package_root()
    regs: List[Registration] = []
    for dirpath, _dirs, files in os.walk(root):
        for fn in sorted(files):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            with open(path, "r", encoding="utf-8") as fh:
                try:
                    tree = ast.parse(fh.read(), filename=path)
                except SyntaxError:
                    continue  # not this tool's failure to report
            rel = os.path.relpath(path, root)
            for node in ast.walk(tree):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr in METRIC_METHODS
                        and node.args
                        and isinstance(node.args[0], ast.Constant)
                        and isinstance(node.args[0].value, str)):
                    continue
                has_help = any(
                    kw.arg == "help" and not (
                        isinstance(kw.value, ast.Constant)
                        and kw.value.value in ("", None))
                    for kw in node.keywords) or (
                    len(node.args) > 1
                    and isinstance(node.args[1], ast.Constant)
                    and bool(node.args[1].value))
                regs.append(Registration(
                    node.args[0].value, rel, node.lineno, has_help))
    return regs


def check(root: str = "", required=None) -> List[str]:
    """Lint the package's metric namespace; returns problem strings
    (empty list == clean).  ``required`` overrides the REQUIRED_METRICS
    presence check (defaulted for the package root, off for explicit
    roots so test fixtures lint standalone)."""
    return _lint(collect_registrations(root), root, required)


def _lint(regs: List[Registration], root: str, required) -> List[str]:
    """The one lint core (check() and main() share it — one walk, one
    required-defaulting rule)."""
    if required is None:
        required = REQUIRED_METRICS if not root else ()
    return _problems(regs) + _missing_required(regs, required)


def _missing_required(regs: List[Registration], required) -> List[str]:
    names = {r.name for r in regs}
    problems: List[str] = []
    for req in required:
        if req.endswith("*"):
            if not any(n.startswith(req[:-1]) for n in names):
                problems.append(
                    f"required metric family {req!r} has no literal "
                    f"registration anywhere in the package")
        elif req not in names:
            problems.append(
                f"required metric {req!r} is not registered anywhere "
                f"in the package")
    return problems


def _problems(regs: List[Registration]) -> List[str]:
    problems: List[str] = []
    documented: Dict[str, bool] = {}
    for r in regs:
        documented[r.name] = documented.get(r.name, False) or r.has_help
        if not SNAKE_CASE.match(r.name):
            problems.append(
                f"{r.file}:{r.line}: metric {r.name!r} is not snake_case")
    for name in sorted(n for n, ok in documented.items() if not ok):
        sites = ", ".join(f"{r.file}:{r.line}" for r in regs
                          if r.name == name)
        problems.append(
            f"metric {name!r} has no help= at any registration site "
            f"({sites})")
    return problems


def main(argv=None) -> int:
    args = list(argv) if argv is not None else sys.argv[1:]
    required = None
    if "--require" in args:
        i = args.index("--require")
        if i + 1 >= len(args):
            print("usage: check_metrics [root] [--require name1,name2]",
                  file=sys.stderr)
            return 2
        required = tuple(x for x in args[i + 1].split(",") if x)
        del args[i:i + 2]
    root = args[0] if args else ""
    regs = collect_registrations(root)
    problems = _lint(regs, root, required)
    for p in problems:
        print(p, file=sys.stderr)
    print(f"check_metrics: {len(regs)} literal registrations, "
          f"{len(problems)} problem(s)")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
