"""Metric-namespace linter: keep registry metric names coherent.

Walks the package source for literal metric registrations —
``<registry>.counter("name", ...)`` / ``.gauge`` / ``.histogram`` — and
fails on:

  - non-snake_case names (the Prometheus exposition and the BENCH JSON
    schema both assume ``[a-z][a-z0-9_]*``);
  - undocumented names: a name every registration site leaves without a
    ``help=`` string never reaches ``# HELP`` on /metrics, so operators
    can't tell what it measures.  One documented site is enough — hot
    paths may re-bind the same metric without repeating the help text.

Dynamically built names (``"fleet_" + k``, the tracer's ``span_*``
histograms) are exempt by construction: only string-literal first
arguments are checked.  Invoked from the test suite (tests/test_analytics
.py) so the namespace stays coherent as it grows; also runnable as
``python -m syzkaller_tpu.tools.check_metrics``.
"""

from __future__ import annotations

import ast
import os
import re
import sys
from typing import Dict, List, NamedTuple

SNAKE_CASE = re.compile(r"^[a-z][a-z0-9_]*$")
METRIC_METHODS = ("counter", "gauge", "histogram")


class Registration(NamedTuple):
    name: str
    file: str
    line: int
    has_help: bool


def _package_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def collect_registrations(root: str = "") -> List[Registration]:
    root = root or _package_root()
    regs: List[Registration] = []
    for dirpath, _dirs, files in os.walk(root):
        for fn in sorted(files):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            with open(path, "r", encoding="utf-8") as fh:
                try:
                    tree = ast.parse(fh.read(), filename=path)
                except SyntaxError:
                    continue  # not this tool's failure to report
            rel = os.path.relpath(path, root)
            for node in ast.walk(tree):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr in METRIC_METHODS
                        and node.args
                        and isinstance(node.args[0], ast.Constant)
                        and isinstance(node.args[0].value, str)):
                    continue
                has_help = any(
                    kw.arg == "help" and not (
                        isinstance(kw.value, ast.Constant)
                        and kw.value.value in ("", None))
                    for kw in node.keywords) or (
                    len(node.args) > 1
                    and isinstance(node.args[1], ast.Constant)
                    and bool(node.args[1].value))
                regs.append(Registration(
                    node.args[0].value, rel, node.lineno, has_help))
    return regs


def check(root: str = "") -> List[str]:
    """Lint the package's metric namespace; returns problem strings
    (empty list == clean)."""
    return _problems(collect_registrations(root))


def _problems(regs: List[Registration]) -> List[str]:
    problems: List[str] = []
    documented: Dict[str, bool] = {}
    for r in regs:
        documented[r.name] = documented.get(r.name, False) or r.has_help
        if not SNAKE_CASE.match(r.name):
            problems.append(
                f"{r.file}:{r.line}: metric {r.name!r} is not snake_case")
    for name in sorted(n for n, ok in documented.items() if not ok):
        sites = ", ".join(f"{r.file}:{r.line}" for r in regs
                          if r.name == name)
        problems.append(
            f"metric {name!r} has no help= at any registration site "
            f"({sites})")
    return problems


def main(argv=None) -> int:
    args = argv if argv is not None else sys.argv[1:]
    root = args[0] if args else ""
    regs = collect_registrations(root)
    problems = _problems(regs)
    for p in problems:
        print(p, file=sys.stderr)
    print(f"check_metrics: {len(regs)} literal registrations, "
          f"{len(problems)} problem(s)")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
