"""journalcat: decode, filter, and verify a campaign journal.

The offline half of the campaign journal (telemetry/journal.py): given a
workdir (or the journal file itself), decode the JSONL records across
rotated segments, verify the CRC/seq chain end-to-end, and print the
records that match the filters — the campaign-forensics tool that
answers "which operator/row/env produced each finding" without a live
process.

    python -m syzkaller_tpu.tools.journalcat <workdir>
    python -m syzkaller_tpu.tools.journalcat <workdir> --type corpus_add
    python -m syzkaller_tpu.tools.journalcat <workdir> --env 2
    python -m syzkaller_tpu.tools.journalcat <workdir> --phase mutate
    python -m syzkaller_tpu.tools.journalcat <workdir> --verify
    python -m syzkaller_tpu.tools.journalcat <workdir> --replay

Default mode prints matching records one JSON object per line (stdout)
and chain problems to stderr; ``--verify`` prints only the verification
verdict; ``--replay`` prints the replayed trajectory summary (the
``telemetry.journal.replay`` document).  Exit code 1 when the chain has
defects beyond the tolerated trailing truncation, 2 on usage errors.

Wired into the test suite (tests/test_tools.py) like check_metrics, so
the tool keeps decoding what the engine keeps writing.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List

from ..telemetry import journal as _journal


def _matches(rec: dict, types: List[str], env: int, phase: str) -> bool:
    if types and rec.get("ev") not in types:
        return False
    if env >= 0 and rec.get("env") != env:
        return False
    if phase and rec.get("phase") != phase:
        return False
    return True


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="journalcat")
    ap.add_argument("path",
                    help="campaign workdir or journal.jsonl path")
    ap.add_argument("--type", default="",
                    help="comma-separated event types to keep "
                         "(e.g. corpus_add,env_restart)")
    ap.add_argument("--env", type=int, default=-1,
                    help="keep only events of this executor env index")
    ap.add_argument("--phase", default="",
                    help="keep only events of this attribution phase")
    ap.add_argument("--verify", action="store_true",
                    help="verify the CRC/seq chain only (no record dump)")
    ap.add_argument("--replay", action="store_true",
                    help="print the replayed trajectory summary instead "
                         "of raw records")
    args = ap.parse_args(argv)

    segments = _journal.journal_segments(args.path)
    if not segments:
        print(f"journalcat: no journal at {args.path!r}", file=sys.stderr)
        return 2

    records, defects = _journal.read_records(args.path)
    # a truncated FINAL record is the journal's documented SIGKILL
    # artifact (the at-most-one-lost-record durability bound) — report
    # it, but don't fail the verification on it
    tolerated = [d for d in defects if d.startswith("tail: ")]
    problems = [d for d in defects if not d.startswith("tail: ")] \
        + _journal.verify_records(records)

    if args.replay:
        doc = _journal.replay(args.path)
        print(json.dumps(doc, sort_keys=True))
    elif args.verify:
        print(f"journalcat: {len(records)} record(s) across "
              f"{len(segments)} segment(s), {len(problems)} problem(s)")
    else:
        types = [t for t in args.type.split(",") if t]
        shown = 0
        for rec in records:
            if _matches(rec, types, args.env, args.phase):
                print(json.dumps(rec, sort_keys=True))
                shown += 1
        print(f"journalcat: {shown}/{len(records)} record(s) shown, "
              f"{len(problems)} chain problem(s)", file=sys.stderr)
    for p in tolerated:
        print(f"journalcat: tolerated crash artifact: {p}",
              file=sys.stderr)
    for p in problems:
        print(f"journalcat: {p}", file=sys.stderr)
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
