"""Corpus-driven stress loop without a manager (reference
/root/reference/tools/syz-stress/stress.go): each proc repeatedly executes
either a mutation of a random corpus program or a freshly generated one,
with no triage/feedback — pure load generation for soak-testing a kernel
or the executor itself.
"""

from __future__ import annotations

import argparse
import random
import sys
import threading
from typing import List


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="syz-stress")
    ap.add_argument("-os", default="linux")
    ap.add_argument("-arch", default="amd64")
    ap.add_argument("-corpus", help="corpus.db of seed programs")
    ap.add_argument("-procs", type=int, default=2)
    ap.add_argument("-len", dest="ncalls", type=int, default=30)
    ap.add_argument("-executed", type=int, default=0,
                    help="stop after N executions (0 = forever)")
    ap.add_argument("-sandbox", default="none")
    ap.add_argument("-threaded", action="store_true")
    ap.add_argument("-mock", action="store_true",
                    help="mock executor (no real syscalls)")
    ap.add_argument("-seed", type=int, default=0)
    args = ap.parse_args(argv)

    from ..ipc import Env, EnvConfig, ExecOpts, MockEnv
    from ..prog import get_target
    from ..prog.generation import generate
    from ..prog.mutation import mutate
    from ..prog.prio import build_choice_table

    from . import load_corpus_db

    target = get_target(args.os, args.arch)
    corpus = load_corpus_db(target, args.corpus) if args.corpus else []
    ct = build_choice_table(target)
    opts = ExecOpts(threaded=args.threaded)

    count = 0
    count_lock = threading.Lock()
    stop = threading.Event()
    errors: List[BaseException] = []

    def proc(pid: int) -> None:
        nonlocal count
        try:
            _proc(pid)
        except BaseException as e:  # a dead proc must stop the run
            errors.append(e)
            print(f"proc {pid} died: {e!r}", file=sys.stderr)
            stop.set()

    def _proc(pid: int) -> None:
        nonlocal count
        rng = random.Random(args.seed * 1000 + pid)
        env = (MockEnv(target, pid=pid) if args.mock
               else Env(target, pid=pid,
                        config=EnvConfig(sandbox=args.sandbox)))
        try:
            while not stop.is_set():
                # 4:1 mutate:generate when a corpus exists (stress.go)
                if corpus and rng.randrange(5) != 0:
                    p = rng.choice(corpus).clone()
                    mutate(p, rng.randrange(1 << 30), args.ncalls,
                           ct=ct, corpus=corpus)
                else:
                    p = generate(target, rng.randrange(1 << 30),
                                 args.ncalls, ct=ct)
                env.exec(opts, p)
                with count_lock:
                    count += 1
                    if args.executed and count >= args.executed:
                        stop.set()
        finally:
            env.close()

    threads = [threading.Thread(target=proc, args=(i,), daemon=True)
               for i in range(args.procs)]
    for t in threads:
        t.start()
    try:
        while not stop.is_set():
            if not stop.wait(10.0):
                print(f"executed {count}", flush=True)
    except KeyboardInterrupt:
        stop.set()
    for t in threads:
        t.join(timeout=30.0)
    print(f"executed {count} programs", flush=True)
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
