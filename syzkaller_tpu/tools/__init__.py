"""Command-line tools mirroring the reference's tools/ directory
(/root/reference/tools/): execprog (replay programs), stress (corpusless
stress loop), mutate (single-program mutation), prog2c (program -> C),
db (corpus database surgery), benchcmp (bench-series comparison HTML),
repro (crash reproduction from a log), symbolize (report symbolization),
fmt (description formatter). Each is `python -m syzkaller_tpu.tools.<name>`.
"""

from __future__ import annotations

import sys
from typing import List


def load_corpus_db(target, path: str) -> List:
    """Parse every program stored in a corpus.db, skipping (and
    reporting) entries that no longer deserialize."""
    from ..db import DB
    from ..prog.encoding import deserialize

    progs = []
    skipped = 0
    with DB.open(path) as db:
        for _, val in db.items():
            try:
                progs.append(deserialize(target,
                                         val.decode("utf-8", "replace")))
            except Exception:
                skipped += 1
    if skipped:
        print(f"corpus {path}: skipped {skipped} unparsable programs",
              file=sys.stderr)
    return progs
