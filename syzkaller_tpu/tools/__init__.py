"""Command-line tools mirroring the reference's tools/ directory
(/root/reference/tools/): execprog (replay programs), stress (corpusless
stress loop), mutate (single-program mutation), prog2c (program -> C),
db (corpus database surgery), benchcmp (bench-series comparison HTML),
repro (crash reproduction from a log), symbolize (report symbolization),
fmt (description formatter). Each is `python -m syzkaller_tpu.tools.<name>`.
"""
