"""Corpus database tool (reference /root/reference/tools/syz-db/syz-db.go:
pack a directory of programs into corpus.db, unpack a db into a directory,
merge several dbs).  Keys are the sha1 of the serialized program text, the
same keying the manager uses, so packed dbs drop straight into a workdir.
"""

from __future__ import annotations

import argparse
import os
import sys


def pack(target, srcdir: str, dbpath: str) -> int:
    from ..db import DB
    from ..prog.encoding import deserialize, serialize
    from ..utils.hash import hash_str

    keys = set()
    with DB.open(dbpath) as db:
        for name in sorted(os.listdir(srcdir)):
            path = os.path.join(srcdir, name)
            if not os.path.isfile(path):
                continue
            with open(path, "r", errors="replace") as f:
                text = f.read()
            if target is not None:
                try:
                    text = serialize(deserialize(target, text))
                except Exception as e:
                    print(f"skipping {name}: {e}", file=sys.stderr)
                    continue
            key = hash_str(text.encode()).encode()
            db.save(key, text.encode())
            keys.add(key)
        db.flush()
    return len(keys)


def unpack(dbpath: str, dstdir: str) -> int:
    from ..db import DB

    os.makedirs(dstdir, exist_ok=True)
    n = 0
    with DB.open(dbpath) as db:
        for key, val in db.items():
            with open(os.path.join(dstdir, key.decode()), "wb") as f:
                f.write(val)
            n += 1
    return n


def merge(dst: str, srcs) -> int:
    from ..db import DB

    n = 0
    with DB.open(dst) as out:
        for path in srcs:
            with DB.open(path) as src:
                for key, val in src.items():
                    if key not in out:
                        out.save(key, val)
                        n += 1
        out.flush()
        out.compact()
    return n


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="syz-db")
    ap.add_argument("-os", default="linux")
    ap.add_argument("-arch", default="amd64")
    ap.add_argument("-no-verify", dest="no_verify", action="store_true",
                    help="pack without parsing programs")
    sub = ap.add_subparsers(dest="cmd", required=True)
    p = sub.add_parser("pack");   p.add_argument("dir"); p.add_argument("db")
    p = sub.add_parser("unpack"); p.add_argument("db");  p.add_argument("dir")
    p = sub.add_parser("merge")
    p.add_argument("dst"); p.add_argument("srcs", nargs="+")
    p = sub.add_parser("list");   p.add_argument("db")
    args = ap.parse_args(argv)

    if args.cmd == "pack":
        target = None
        if not args.no_verify:
            from ..prog import get_target
            target = get_target(args.os, args.arch)
        print(f"packed {pack(target, args.dir, args.db)} programs")
    elif args.cmd == "unpack":
        print(f"unpacked {unpack(args.db, args.dir)} programs")
    elif args.cmd == "merge":
        print(f"merged {merge(args.dst, args.srcs)} new programs")
    elif args.cmd == "list":
        from ..db import DB
        with DB.open(args.db) as db:
            for key, val in db.items():
                print(key.decode(), len(val))
    return 0


if __name__ == "__main__":
    sys.exit(main())
