"""Format syscall description files in place (reference
/root/reference/tools/syz-fmt/fmt.go).  Note: like a code formatter run
through the AST, comments are not preserved — use -check to diff without
writing.
"""

from __future__ import annotations

import argparse
import glob
import os
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="syz-fmt")
    ap.add_argument("paths", nargs="+",
                    help=".txt files or directories of descriptions")
    ap.add_argument("-check", action="store_true",
                    help="print formatted text to stdout, don't write")
    args = ap.parse_args(argv)

    from ..descriptions.format import format_file

    files = []
    for p in args.paths:
        if os.path.isdir(p):
            files.extend(sorted(glob.glob(os.path.join(p, "*.txt"))))
        else:
            files.append(p)
    rc = 0
    for path in files:
        try:
            result = format_file(path, write=not args.check)
        except Exception as e:
            print(f"{path}: {e}", file=sys.stderr)
            rc = 1
            continue
        if args.check:
            sys.stdout.write(result)
        elif result:
            print(f"reformatted {path}")
    return rc


if __name__ == "__main__":
    sys.exit(main())
