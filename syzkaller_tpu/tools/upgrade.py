"""Upgrade a corpus directory of serialized programs to the current
description set.

Capability parity with reference /root/reference/tools/syz-upgrade
(upgrade.go): re-parse every program in non-strict mode (dropping calls
or args the current descriptions no longer accept) and write back the
normalized serialization; unparseable programs are deleted.
"""

from __future__ import annotations

import argparse
import os
import sys


def upgrade_dir(target, dir_: str) -> dict:
    from ..prog.encoding import deserialize, serialize

    stats = {"ok": 0, "fixed": 0, "dropped": 0}
    for name in sorted(os.listdir(dir_)):
        path = os.path.join(dir_, name)
        if not os.path.isfile(path):
            continue
        with open(path) as f:
            text = f.read()
        out = _reparse(target, text)
        if out is None:
            os.unlink(path)
            stats["dropped"] += 1
            continue
        if out != text:
            with open(path, "w") as f:
                f.write(out)
            stats["fixed"] += 1
        else:
            stats["ok"] += 1
    return stats


def _reparse(target, text: str):
    """Non-strict reparse: drop lines naming calls the current
    descriptions don't know, then retry; None when nothing survives."""
    from ..prog.encoding import deserialize, serialize

    lines = text.splitlines()
    for _ in range(len(lines) + 1):
        try:
            p = deserialize(target, "\n".join(lines) + "\n")
            return serialize(p) if p.calls else None
        except Exception as e:
            msg = str(e)
            if "unknown syscall" in msg:
                known = target.syscall_map
                kept = [ln for ln in lines
                        if not _names_unknown_call(ln, known)]
                if len(kept) == len(lines):
                    return None
                lines = kept
                continue
            return None
    return None


def _names_unknown_call(line: str, known) -> bool:
    import re

    m = re.match(r"\s*(?:r\d+\s*=\s*)?([a-zA-Z_][\w$]*)\(", line)
    return bool(m) and m.group(1) not in known


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="syz-upgrade")
    ap.add_argument("corpus_dir")
    ap.add_argument("--os", default="linux")
    ap.add_argument("--arch", default="amd64")
    args = ap.parse_args(argv)

    from ..prog import get_target

    target = get_target(args.os, args.arch)
    stats = upgrade_dir(target, args.corpus_dir)
    print(f"upgrade: {stats['ok']} ok, {stats['fixed']} rewritten, "
          f"{stats['dropped']} dropped")
    return 0


if __name__ == "__main__":
    sys.exit(main())
