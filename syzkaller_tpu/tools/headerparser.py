"""Generate description-language skeletons from C header files.

Capability parity with reference /root/reference/tools/syz-headerparser
(headerparser.py + headerlib): parse struct definitions out of kernel
headers and emit ready-to-edit description structs, flag-set stubs for
#define groups, and a report of fields needing human typing (lengths,
pointers).  Original implementation: a small tokenizer for the C subset
that appears in uapi headers (no preprocessor beyond #define collection).
"""

from __future__ import annotations

import argparse
import re
import sys
from typing import Dict, List, Optional, Tuple

_STRUCT_RE = re.compile(
    r"struct\s+(\w+)\s*\{(.*?)\}\s*(?:__attribute__\(\(packed\)\))?\s*;",
    re.S)
_DEFINE_RE = re.compile(
    r"^#define\s+([A-Z_][A-Z0-9_]*)\s+"
    r"(0x[0-9a-fA-F]+|\d+|\(?1\s*<<\s*\d+\)?)\s*$", re.M)
_FIELD_RE = re.compile(
    r"""(?P<type>(?:unsigned\s+|signed\s+|struct\s+|const\s+)*[\w]+)
        \s*(?P<ptr>\**)\s*
        (?P<name>\w+)
        \s*(?:\[(?P<arr>[^\]]*)\])?
        \s*(?::\s*(?P<bits>\d+))?\s*;""", re.X)

_C_TO_DESC = {
    "__u8": "int8", "u8": "int8", "uint8_t": "int8", "char": "int8",
    "__s8": "int8", "s8": "int8",
    "__u16": "int16", "u16": "int16", "uint16_t": "int16",
    "__s16": "int16", "s16": "int16", "short": "int16",
    "__be16": "int16be", "__le16": "int16",
    "__u32": "int32", "u32": "int32", "uint32_t": "int32",
    "__s32": "int32", "s32": "int32", "int": "int32",
    "__be32": "int32be", "__le32": "int32",
    "__u64": "int64", "u64": "int64", "uint64_t": "int64",
    "__s64": "int64", "s64": "int64",
    "__be64": "int64be", "__le64": "int64",
    "long": "intptr", "size_t": "intptr",
}


def _strip_comments(text: str) -> str:
    text = re.sub(r"/\*.*?\*/", " ", text, flags=re.S)
    return re.sub(r"//[^\n]*", " ", text)


def parse_structs(text: str) -> List[Tuple[str, List[Dict]]]:
    """[(struct_name, [field dicts])] from header text."""
    text = _strip_comments(text)
    out = []
    for m in _STRUCT_RE.finditer(text):
        name, body = m.group(1), m.group(2)
        fields = []
        for fm in _FIELD_RE.finditer(body):
            base = fm.group("type").strip()
            base = re.sub(r"\b(unsigned|signed|const)\s+", "", base).strip()
            fields.append({
                "name": fm.group("name"),
                "ctype": base,
                "ptr": bool(fm.group("ptr")),
                "array": fm.group("arr"),
                "bits": fm.group("bits"),
            })
        if fields:
            out.append((name, fields))
    return out


def parse_defines(text: str) -> Dict[str, str]:
    return {m.group(1): m.group(2)
            for m in _DEFINE_RE.finditer(_strip_comments(text))}


def field_to_desc(f: Dict) -> Tuple[str, bool]:
    """(description type, needs_human) for one parsed C field."""
    if f["ptr"]:
        return "ptr[in, TODO]", True
    base = _C_TO_DESC.get(f["ctype"])
    if base is None:
        base = f["ctype"]  # struct-by-value: keep the name
        needs = False
    else:
        needs = False
    if f["bits"]:
        return f"{base}:{f['bits']}", needs
    if f["array"] is not None:
        n = f["array"].strip()
        if n.isdigit():
            return f"array[{base}, {n}]", needs
        return f"array[{base}]", True  # macro-sized: human decides
    # heuristic: *len/*size fields likely belong in len[] types
    if re.search(r"(len|size|count)$", f["name"]):
        return base, True
    return base, needs


def emit_descriptions(text: str) -> str:
    """Description-language skeleton for all structs + defines found."""
    out: List[str] = []
    defines = parse_defines(text)
    if defines:
        groups: Dict[str, List[str]] = {}
        for name in defines:
            prefix = name.rsplit("_", 1)[0]
            groups.setdefault(prefix, []).append(name)
        for prefix, names in sorted(groups.items()):
            if len(names) >= 2:
                out.append(f"{prefix.lower()}_flags = " +
                           ", ".join(sorted(names)))
        out.append("")
    for name, fields in parse_structs(text):
        out.append(f"{name} {{")
        for f in fields:
            typ, needs = field_to_desc(f)
            todo = "\t# TODO: check" if needs else ""
            out.append(f"\t{f['name']}\t{typ}{todo}")
        out.append("}")
        out.append("")
    return "\n".join(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="syz-headerparser")
    ap.add_argument("headers", nargs="+")
    args = ap.parse_args(argv)
    for path in args.headers:
        with open(path) as f:
            text = f.read()
        sys.stdout.write(f"# from {path}\n")
        sys.stdout.write(emit_descriptions(text))
    return 0


if __name__ == "__main__":
    sys.exit(main())
