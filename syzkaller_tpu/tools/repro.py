"""Automated crash reproduction from a crash log (CLI).

Capability parity with reference /root/reference/tools/syz-repro: feed a
crash log + config, get a minimized reproducer program (and C source when
extraction succeeds).  Drives pkg-repro's pipeline (repro/__init__.py)
with a local in-process tester by default; pass --mock to exercise the
pipeline without a kernel (hermetic smoke mode).
"""

from __future__ import annotations

import argparse
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="syz-repro")
    ap.add_argument("log", help="crash log or program file")
    ap.add_argument("--os", default="linux")
    ap.add_argument("--arch", default="amd64")
    ap.add_argument("--out", default="repro.prog",
                    help="where to write the reproducer program")
    ap.add_argument("--cout", default="repro.c",
                    help="where to write the C reproducer (if extracted)")
    ap.add_argument("--vm-type", default="local",
                    help="VM backend to replay in (local/qemu/...)")
    ap.add_argument("--vm-count", type=int, default=1)
    ap.add_argument("--kernel", default="")
    ap.add_argument("--image", default="")
    ap.add_argument("--mock", action="store_true",
                    help="mock tester (always-crashing): pipeline check")
    args = ap.parse_args(argv)

    from ..prog import get_target
    from .. import repro as repro_mod

    target = get_target(args.os, args.arch)
    with open(args.log) as f:
        data = f.read()
    if "executing program" not in data:
        # plain program file(s): wrap into the log format the pipeline's
        # parser expects (blank-line-separated serialized programs)
        chunks = [c.strip() for c in data.split("\n\n") if c.strip()]
        data = "".join(f"executing program {i}:\n{c}\n\n"
                       for i, c in enumerate(chunks))

    if args.mock:
        tester = _MockTester()
    else:
        from ..vm import VMConfig, create

        pool = create(VMConfig(type=args.vm_type, count=args.vm_count,
                               kernel=args.kernel, image=args.image))
        tester = repro_mod.VMTester(pool)
    res = repro_mod.run(data, target, tester)
    if res is None or res.prog is None:
        print("repro: failed to reproduce the crash", file=sys.stderr)
        return 1
    from ..prog.encoding import serialize

    with open(args.out, "w") as f:
        f.write(serialize(res.prog))
    print(f"repro: wrote {args.out} "
          f"({len(res.prog.calls)} calls, opts={res.opts})")
    if res.c_src:
        with open(args.cout, "w") as f:
            f.write(res.c_src)
        print(f"repro: wrote {args.cout}")
    return 0


class _MockTester:
    """Reports a crash whenever any program is executed (pipeline test)."""

    def test_progs(self, progs, opts, duration):
        from ..report import Report

        if not any(p.calls for p in progs):
            return None
        return Report(title="mock crash", report="mock")

    def test_c_bin(self, bin_path, duration):
        return None


if __name__ == "__main__":
    sys.exit(main())
