"""Compare manager bench files (reference
/root/reference/tools/syz-benchcmp/benchcmp.go:44-52: graphs of coverage /
corpus / exec-total / crash-types over time for several runs).

Input: one or more JSON-lines files written by `Manager -bench`
(one object per minute: {"ts": ..., "signal": ..., "corpus": ...,
"exec_total": ..., "crash_types": ...}).  Output: a single standalone
HTML file with one inline-SVG line chart per metric, one polyline per
input file — no external plotting dependencies, same spirit as the
reference's self-contained HTML output.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Tuple

GRAPHS = ("signal", "corpus", "exec_total", "crash_types")
COLORS = ("#1f77b4", "#d62728", "#2ca02c", "#9467bd", "#ff7f0e",
          "#8c564b", "#e377c2", "#7f7f7f")


def load_series(path: str) -> Dict[str, List[Tuple[float, float]]]:
    """metric -> [(minutes since start, value)]."""
    out: Dict[str, List[Tuple[float, float]]] = {g: [] for g in GRAPHS}
    t0 = None
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            ts = float(rec.get("ts", 0))
            if t0 is None:
                t0 = ts
            for g in GRAPHS:
                if g in rec:
                    out[g].append(((ts - t0) / 60.0, float(rec[g])))
    return out


def _svg_chart(title: str, series: List[Tuple[str, List[Tuple[float, float]]]],
               w: int = 640, h: int = 320) -> str:
    pad = 48
    xs = [x for _, pts in series for x, _ in pts]
    ys = [y for _, pts in series for _, y in pts]
    xmax = max(xs, default=1.0) or 1.0
    ymax = max(ys, default=1.0) or 1.0
    parts = [f'<svg width="{w}" height="{h}" '
             f'style="border:1px solid #ccc;margin:8px">',
             f'<text x="{w // 2}" y="16" text-anchor="middle" '
             f'font-weight="bold">{title}</text>']
    # axes + ticks
    parts.append(f'<line x1="{pad}" y1="{h - pad}" x2="{w - 8}" '
                 f'y2="{h - pad}" stroke="#888"/>')
    parts.append(f'<line x1="{pad}" y1="{h - pad}" x2="{pad}" y2="24" '
                 f'stroke="#888"/>')
    for i in range(5):
        yv = ymax * i / 4
        yp = (h - pad) - (h - pad - 24) * i / 4
        parts.append(f'<text x="{pad - 4}" y="{yp + 4:.0f}" '
                     f'text-anchor="end" font-size="10">{yv:.0f}</text>')
        xv = xmax * i / 4
        xp = pad + (w - 8 - pad) * i / 4
        parts.append(f'<text x="{xp:.0f}" y="{h - pad + 14}" '
                     f'text-anchor="middle" font-size="10">{xv:.0f}m</text>')
    for i, (name, pts) in enumerate(series):
        color = COLORS[i % len(COLORS)]
        if not pts:
            continue
        coords = " ".join(
            f"{pad + (w - 8 - pad) * x / xmax:.1f},"
            f"{(h - pad) - (h - pad - 24) * y / ymax:.1f}" for x, y in pts)
        parts.append(f'<polyline points="{coords}" fill="none" '
                     f'stroke="{color}" stroke-width="1.5"/>')
    # legend
    for i, (name, _) in enumerate(series):
        color = COLORS[i % len(COLORS)]
        parts.append(f'<rect x="{pad + 8}" y="{28 + 14 * i}" width="10" '
                     f'height="10" fill="{color}"/>')
        parts.append(f'<text x="{pad + 22}" y="{37 + 14 * i}" '
                     f'font-size="11">{name}</text>')
    parts.append("</svg>")
    return "".join(parts)


def render(files: List[str]) -> str:
    data = [(os.path.basename(p), load_series(p)) for p in files]
    charts = [_svg_chart(g, [(name, d[g]) for name, d in data])
              for g in GRAPHS]
    return ("<!doctype html><html><head><meta charset='utf-8'>"
            "<title>syz-benchcmp</title></head><body>"
            + "\n".join(charts) + "</body></html>\n")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="syz-benchcmp")
    ap.add_argument("files", nargs="+", help="manager -bench JSON files")
    ap.add_argument("-o", "--out", default="bench.html")
    args = ap.parse_args(argv)
    html = render(args.files)
    with open(args.out, "w") as f:
        f.write(html)
    print(f"wrote {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
