"""Replay a crash log on N VMs in parallel, counting reproductions.

Capability parity with reference /root/reference/tools/syz-crush
(crush.go): intended for particularly elusive crashes — boot every
instance of the pool, replay the log's programs in a loop on each, and
report how many instances crashed and with what titles.
"""

from __future__ import annotations

import argparse
import concurrent.futures as cf
import sys
from collections import Counter


def crush(target, pool, data: str, instances: int, duration: float,
          repro_mod=None) -> Counter:
    from .. import repro as repro_mod_default
    from ..ipc import ExecOpts

    repro_mod = repro_mod or repro_mod_default
    from ..prog.parse import parse_log
    from ..prog.encoding import deserialize

    if "executing program" in data:
        progs = [e.p for e in parse_log(target, data)]
    else:
        progs = []
        for chunk in data.split("\n\n"):
            if chunk.strip():
                try:
                    progs.append(deserialize(target, chunk))
                except Exception:
                    pass
    if not progs:
        raise SystemExit("crush: no programs parsed from the log")

    titles: Counter = Counter()

    def one(idx: int):
        tester = repro_mod.VMTester(pool, instance_indexes=[idx])
        rep = tester.test_progs(progs, ExecOpts(threaded=True,
                                                collide=True), duration)
        return rep.title if rep is not None else None

    with cf.ThreadPoolExecutor(max_workers=instances) as ex:
        for title in ex.map(one, range(instances)):
            if title:
                titles[title] += 1
    return titles


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="syz-crush")
    ap.add_argument("log")
    ap.add_argument("--os", default="linux")
    ap.add_argument("--arch", default="amd64")
    ap.add_argument("--vm-type", default="local")
    ap.add_argument("--instances", type=int, default=4)
    ap.add_argument("--duration", type=float, default=60.0)
    ap.add_argument("--kernel", default="")
    ap.add_argument("--image", default="")
    args = ap.parse_args(argv)

    from ..prog import get_target
    from ..vm import VMConfig, create

    target = get_target(args.os, args.arch)
    pool = create(VMConfig(type=args.vm_type, count=args.instances,
                           kernel=args.kernel, image=args.image))
    with open(args.log) as f:
        data = f.read()
    titles = crush(target, pool, data, args.instances, args.duration)
    total = sum(titles.values())
    print(f"crush: {total}/{args.instances} instances crashed")
    for title, n in titles.most_common():
        print(f"  {n}x {title}")
    return 0 if total else 1


if __name__ == "__main__":
    sys.exit(main())
