"""Dump a serial console (USB/serial TTY) to stdout.

Capability parity with reference /root/reference/tools/syz-tty
(syz-tty.go + vmimpl.OpenConsole): configure the port raw at 115200 and
stream it — used to eyeball what a hardware device under test prints.
"""

from __future__ import annotations

import argparse
import os
import sys

try:
    import termios
except ImportError:  # non-unix: tool unsupported
    termios = None


def open_console(path: str, baud: int = 115200) -> int:
    """Open + configure the tty raw; returns the fd
    (vm/vmimpl/console.go equivalent)."""
    fd = os.open(path, os.O_RDONLY | os.O_NOCTTY | os.O_NONBLOCK)
    if termios is not None and os.isatty(fd):
        attrs = termios.tcgetattr(fd)
        speed = getattr(termios, f"B{baud}", termios.B115200)
        # raw 8N1, no flow control
        attrs[0] = termios.IGNPAR          # iflag
        attrs[1] = 0                       # oflag
        attrs[2] = (termios.CS8 | termios.CREAD | termios.CLOCAL)  # cflag
        attrs[3] = 0                       # lflag
        attrs[4] = speed                   # ispeed
        attrs[5] = speed                   # ospeed
        attrs[6][termios.VMIN] = 0
        attrs[6][termios.VTIME] = 1        # 100ms read timeout
        termios.tcsetattr(fd, termios.TCSANOW, attrs)
    return fd


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="syz-tty")
    ap.add_argument("device", help="/dev/ttyUSBx")
    ap.add_argument("--baud", type=int, default=115200)
    args = ap.parse_args(argv)
    fd = open_console(args.device, args.baud)
    import select

    try:
        while True:
            r, _, _ = select.select([fd], [], [], 1.0)
            if not r:
                continue
            data = os.read(fd, 4096)
            if data:
                sys.stdout.buffer.write(data)
                sys.stdout.buffer.flush()
    except KeyboardInterrupt:
        return 0
    finally:
        os.close(fd)


if __name__ == "__main__":
    sys.exit(main())
