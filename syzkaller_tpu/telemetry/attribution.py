"""Attribution ledger: which phase / mutation operator earns the corpus.

Coverage-guided fuzzers are judged on trajectories, and trajectories are
made of *credited* events: every triaged corpus addition started life in
some phase — generate / mutate / smash / hints / candidate — and, for
mutations, under some operator (the shared operator index space of
``ops/mutation.py``'s device mix and ``prog/mutation.py``'s host mix:
splice / insert / value / data / remove).  The ledger accumulates, per
phase and per operator:

  - ``execs``        — programs executed with that provenance (the cost);
  - ``new_signal``   — new max-signal PCs its triaged inputs contributed;
  - ``corpus_adds``  — inputs it landed in the corpus (the yield);

and ``snapshot()`` derives yield-per-exec from them.  This is the
per-operator effectiveness data that memoized mutation analysis
(arxiv 2102.11559) and coverage-guided tensor-compiler fuzzing
(arxiv 2202.09947) show turns "runs fast" into "finds more": the mix
weights can be audited against measured yield instead of folklore.

Multi-op provenance (a device lane mutated twice, a host mutate() loop
applying several ops) credits EVERY operator involved in full — the
per-operator rows answer "did executions involving op X pay off", so
their execs/adds columns each sum to >= the phase totals, not equal.
Phase totals are exact: one exec / one add is credited to exactly one
phase, and the tests pin sum(phase corpus_adds) == engine new_inputs —
plus the ``seed`` row, which counts connect-time corpus imports (no
exec paid, not new_inputs) so seed volume is auditable next to yield.

Like the metrics registry, one process-wide default ledger
(``get_ledger``) is shared by in-process fuzzers and the manager UI;
``record_exec`` is one lock + a few dict adds, cheap enough for the
per-exec hot path (the ISSUE 1 <5% overhead bound test includes it).
"""

from __future__ import annotations

import threading
from typing import Dict, Iterable, Sequence, Tuple

# Phase vocabulary: where a program's provenance starts.
PHASE_GENERATE = "generate"
PHASE_MUTATE = "mutate"
PHASE_SMASH = "smash"
PHASE_HINTS = "hints"
PHASE_CANDIDATE = "candidate"
PHASE_TRIAGE = "triage"  # re-runs/minimize: pure cost, never yields adds
PHASE_SEED = "seed"      # corpus loaded from the manager/db at connect

PHASES: Tuple[str, ...] = (
    PHASE_GENERATE, PHASE_MUTATE, PHASE_SMASH, PHASE_HINTS,
    PHASE_CANDIDATE, PHASE_TRIAGE, PHASE_SEED)

# Operator index space shared by the device mutator (ops/mutation._OP_MIX
# order) and the host mutator (prog/mutation.mutate's op arms).
OP_SPLICE, OP_INSERT, OP_VALUE, OP_DATA, OP_REMOVE = range(5)
OP_NAMES: Tuple[str, ...] = ("splice", "insert", "value", "data", "remove")


def ops_from_mask(mask: int) -> Tuple[int, ...]:
    """Decode a device-side op bitmask (bit i == operator i applied) into
    the operator-index tuple the ledger takes."""
    return tuple(i for i in range(len(OP_NAMES)) if (int(mask) >> i) & 1)


class _Cell:
    __slots__ = ("execs", "new_signal", "corpus_adds")

    def __init__(self):
        self.execs = 0
        self.new_signal = 0
        self.corpus_adds = 0

    def to_dict(self) -> Dict[str, float]:
        ypk = (1000.0 * self.corpus_adds / self.execs) if self.execs else 0.0
        spk = (1000.0 * self.new_signal / self.execs) if self.execs else 0.0
        return {
            "execs": self.execs,
            "new_signal": self.new_signal,
            "corpus_adds": self.corpus_adds,
            "adds_per_kexec": round(ypk, 4),
            "signal_per_kexec": round(spk, 4),
        }


class AttributionLedger:
    """Thread-safe per-phase / per-operator yield accounting."""

    def __init__(self):
        self._lock = threading.Lock()
        self._phases: Dict[str, _Cell] = {}
        self._ops: Dict[int, _Cell] = {}

    def _phase(self, phase: str) -> _Cell:
        c = self._phases.get(phase)
        if c is None:
            c = self._phases[phase] = _Cell()
        return c

    def _op(self, op: int) -> _Cell:
        c = self._ops.get(op)
        if c is None:
            c = self._ops[op] = _Cell()
        return c

    # ---- recording (engine hot path) ----

    def record_exec(self, phase: str, ops: Sequence[int] = (),
                    n: int = 1) -> None:
        with self._lock:
            self._phase(phase).execs += n
            for op in ops:
                self._op(op).execs += n

    def record_new_signal(self, phase: str, ops: Sequence[int],
                          n: int) -> None:
        if n <= 0:
            return
        with self._lock:
            self._phase(phase).new_signal += n
            for op in ops:
                self._op(op).new_signal += n

    def record_corpus_add(self, phase: str, ops: Sequence[int] = ()) -> None:
        with self._lock:
            self._phase(phase).corpus_adds += 1
            for op in ops:
                self._op(op).corpus_adds += 1

    # ---- reading ----

    def snapshot(self) -> Dict[str, Dict[str, Dict[str, float]]]:
        with self._lock:
            phases = {p: c.to_dict() for p, c in self._phases.items()}
            ops = {OP_NAMES[o]: c.to_dict()
                   for o, c in sorted(self._ops.items())
                   if 0 <= o < len(OP_NAMES)}
        return {"phases": phases, "operators": ops}

    def totals(self) -> Dict[str, int]:
        """Exact phase-summed totals (per-operator rows intentionally
        overlap and are excluded)."""
        with self._lock:
            return {
                "execs": sum(c.execs for c in self._phases.values()),
                "new_signal": sum(c.new_signal
                                  for c in self._phases.values()),
                "corpus_adds": sum(c.corpus_adds
                                   for c in self._phases.values()),
            }

    def reset(self) -> None:
        with self._lock:
            self._phases.clear()
            self._ops.clear()

    # ---- checkpoint round-trip (engine/checkpoint.py) ----

    def state(self) -> Dict[str, Dict]:
        """Raw cell counts for the engine checkpoint (snapshot() derives
        ratios and is lossy; this is the exact restorable form)."""
        with self._lock:
            return {
                "phases": {p: [c.execs, c.new_signal, c.corpus_adds]
                           for p, c in self._phases.items()},
                "ops": {int(o): [c.execs, c.new_signal, c.corpus_adds]
                        for o, c in self._ops.items()},
            }

    def load_state(self, st: Dict[str, Dict]) -> None:
        """Replace the ledger wholesale from a checkpointed ``state()``
        — the ``--resume`` path: a fresh process restoring its own
        trajectory (the persistent engine_id keeps it the SAME
        trajectory across the restart; the restored counts continue
        monotonically from the checkpoint).  For folding ledgers from
        several engines, use ``merge_state``."""
        with self._lock:
            self._phases.clear()
            self._ops.clear()
            for p, (e, ns, ca) in st.get("phases", {}).items():
                c = self._phase(p)
                c.execs, c.new_signal, c.corpus_adds = \
                    int(e), int(ns), int(ca)
            for o, (e, ns, ca) in st.get("ops", {}).items():
                c = self._op(int(o))
                c.execs, c.new_signal, c.corpus_adds = \
                    int(e), int(ns), int(ca)

    def merge_state(self, st: Dict[str, Dict]) -> None:
        """Fold another ledger's raw ``state()`` INTO this one (counts
        add cell-wise) — the cross-engine aggregation edge: ledgers
        from N engines merged into one fleet ledger are EXACT, because
        every cell is an integer event count credited by exactly one
        engine (merged phase totals == sum of the engines' phase
        totals; the tests pin merged corpus_adds-minus-seed == sum of
        engines' new_inputs).  Merging the same engine's state twice
        double-counts by construction — callers dedup by engine_id
        (manager/fleet.py) and keep only the latest absolute state per
        engine."""
        with self._lock:
            for p, (e, ns, ca) in (st.get("phases") or {}).items():
                c = self._phase(p)
                c.execs += int(e)
                c.new_signal += int(ns)
                c.corpus_adds += int(ca)
            for o, (e, ns, ca) in (st.get("ops") or {}).items():
                c = self._op(int(o))
                c.execs += int(e)
                c.new_signal += int(ns)
                c.corpus_adds += int(ca)


class Provenance:
    """One program's origin: phase + the operator indices that shaped it
    + (for device-arena candidates) the arena row it was sampled from.
    Carried on TriageItems so the eventual corpus add credits the source
    that produced the input, not the triage step that confirmed it — and
    so new signal can be credited BACK to the sampled arena row (the
    yield-weighted scheduler's feedback edge, ISSUE 5).  ``row`` is -1
    when the input did not come from the device arena; ``row_age`` is
    the arena's append-sequence stamp at sample time, so credit for a
    row that was evicted and rewritten in the meantime is dropped
    instead of misattributed (CorpusArena.credit)."""

    __slots__ = ("phase", "ops", "row", "row_age")

    def __init__(self, phase: str, ops: Iterable[int] = (),
                 row: int = -1, row_age: int = -1):
        self.phase = phase
        # dedupe, order-preserving: an exec is credited once per operator
        # *involved*, however many times the host mutate() loop drew it
        self.ops = tuple(dict.fromkeys(ops))
        self.row = int(row)
        self.row_age = int(row_age)

    def __repr__(self) -> str:
        names = [OP_NAMES[o] for o in self.ops if 0 <= o < len(OP_NAMES)]
        return f"Provenance({self.phase}{':' if names else ''}{'+'.join(names)})"


_default = AttributionLedger()


def get_ledger() -> AttributionLedger:
    """The process-wide default ledger (pairs with metrics.get_registry:
    in-process fuzzers write it, the manager UI serves it)."""
    return _default
