"""Nestable span timers with Chrome-trace JSON export.

``with span("device.fuzz_step"): ...`` records one complete event (wall
time, thread, nesting depth) into a bounded in-process ring buffer.  The
manager UI serves the buffer as Chrome trace-event JSON on ``/trace``
(load it in chrome://tracing or Perfetto); ``--telemetry-out`` dumps the
same document next to the metrics snapshot.

Spans are opt-out via the metrics registry flag (``spans_enabled``) — when
off, ``span()`` returns a shared no-op context manager, so the hot path
pays one attribute read.  Each finished ``span()`` feeds a latency
histogram named ``span_<name>_seconds`` (dots -> underscores) in the
registry, which is how per-phase breakdowns reach /metrics and BENCH.
Hot paths that own a canonical histogram use ``timed(name, hist)``
instead: one clock-read pair feeding the explicit histogram (always —
latency metrics are wire stats) plus a trace event when spans are on.

Device-kernel convention: the first invocation of a jitted step traces and
compiles inside the call, so the caller records it under
``<name>.compile`` and steady-state invocations under ``<name>.dispatch``
(see parallel/mesh.make_fuzz_step) — the Chrome trace then separates
first-call JIT time from dispatch without any XLA introspection.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from . import metrics as _metrics

MAX_EVENTS = 65536  # ring-bounded: a week-long run must not eat the heap


class _NullSpan:
    """Shared no-op context manager for the spans-disabled path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _HistTimer:
    """Times into an explicit histogram only — the spans-disabled arm of
    ``Tracer.timed``: latency metrics are wire stats and stay on."""

    __slots__ = ("hist", "_t0")

    def __init__(self, hist):
        self.hist = hist

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.hist.observe(time.perf_counter() - self._t0)
        return False


class _Span:
    __slots__ = ("tracer", "name", "hist", "_t0")

    def __init__(self, tracer: "Tracer", name: str, hist=None):
        self.tracer = tracer
        self.name = name
        self.hist = hist

    def __enter__(self):
        tls = self.tracer._tls
        stack = getattr(tls, "stack", None)
        if stack is None:
            stack = tls.stack = []
        stack.append(self)
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        t1 = time.perf_counter()
        tls_stack = self.tracer._tls.stack
        # tolerate exits out of order after an exception unwound the stack
        while tls_stack and tls_stack[-1] is not self:
            tls_stack.pop()
        depth = max(len(tls_stack) - 1, 0)
        if tls_stack:
            tls_stack.pop()
        self.tracer._record(self.name, self._t0, t1, depth, self.hist)
        return False


class Tracer:
    """Bounded buffer of finished spans + Chrome-trace export."""

    def __init__(self, registry: Optional[_metrics.Registry] = None,
                 max_events: int = MAX_EVENTS):
        self.registry = registry
        self._events: deque = deque(maxlen=max_events)
        self._lock = threading.Lock()
        self._tls = threading.local()
        self._epoch = time.perf_counter()
        self._hists: Dict[str, _metrics.Histogram] = {}
        self._hists_gen = -1  # registry generation the cache belongs to

    def _reg(self) -> _metrics.Registry:
        return self.registry or _metrics.get_registry()

    @property
    def enabled(self) -> bool:
        return self._reg().spans_enabled

    def span(self, name: str):
        if not self.enabled:
            return _NULL_SPAN
        return _Span(self, name)

    def record_span(self, name: str, t0: float, t1: float) -> None:
        """Record an already-measured interval (``time.perf_counter``
        endpoints) as a finished span.  This is how OVERLAPPING device
        work gets honest trace events: the pipelined engine stamps t0 at
        launch and t1 when the batch's transfer is consumed, so with k
        batches in flight the ``device.step`` spans overlap each other
        (their sum can exceed wall time — the point of the pipeline).
        A live ``span()`` context can't express that: it nests on one
        thread's stack."""
        if not self.enabled:
            return
        self._record(name, t0, t1, 0)

    def timed(self, name: str, hist: _metrics.Histogram):
        """One timing, two sinks: the explicit histogram always gets the
        observation (latency metrics are wire stats), and a trace event
        is recorded when spans are enabled.  The instrumented hot paths
        use this instead of a manual perf_counter pair around a span —
        one clock read pair and one observe per phase."""
        if not self.enabled:
            return _HistTimer(hist)
        return _Span(self, name, hist)

    def _record(self, name: str, t0: float, t1: float, depth: int,
                hist: Optional[_metrics.Histogram] = None) -> None:
        with self._lock:
            self._events.append(
                (name, t0 - self._epoch, t1 - t0, threading.get_ident(),
                 depth))
        if hist is not None:  # timed(): the caller owns the histogram
            hist.observe(t1 - t0)
            return
        reg = self._reg()
        with self._lock:
            if self._hists_gen != reg.generation:
                # registry was reset: cached histograms are orphans that
                # no longer reach /metrics — drop and re-create
                self._hists.clear()
                self._hists_gen = reg.generation
            h = self._hists.get(name)
        if h is None:
            h = reg.histogram(
                "span_" + name.replace(".", "_").replace("-", "_")
                + "_seconds",
                help=f"wall time of span {name}")
            with self._lock:
                self._hists[name] = h
        h.observe(t1 - t0)

    def events(self) -> List[tuple]:
        with self._lock:
            return list(self._events)

    def span_names(self) -> List[str]:
        return sorted({e[0] for e in self.events()})

    def reset(self) -> None:
        with self._lock:
            self._events.clear()
            self._hists.clear()
            self._epoch = time.perf_counter()

    def chrome_trace(self) -> dict:
        """Chrome trace-event document (complete 'X' events, microsecond
        timestamps; args carry the nesting depth)."""
        pid = os.getpid()
        events = [{
            "name": name,
            "ph": "X",
            "ts": round(ts * 1e6, 3),
            "dur": round(dur * 1e6, 3),
            "pid": pid,
            "tid": tid,
            "args": {"depth": depth},
        } for name, ts, dur, tid, depth in self.events()]
        return {"traceEvents": events, "displayTimeUnit": "ms"}


_default = Tracer()


def get_tracer() -> Tracer:
    """The process-wide default tracer (pairs with metrics.get_registry)."""
    return _default


def span(name: str):
    """``with span("phase"): ...`` on the default tracer — the one-liner
    the hot paths use."""
    return _default.span(name)


def timed(name: str, hist: _metrics.Histogram):
    """``with timed("phase", hist): ...`` on the default tracer — one
    timing feeding the explicit histogram (always) and the trace buffer
    (when spans are enabled)."""
    return _default.timed(name, hist)


def record_span(name: str, t0: float, t1: float) -> None:
    """Retroactively record a measured interval on the default tracer
    (see Tracer.record_span — overlapping in-flight device work)."""
    _default.record_span(name, t0, t1)
