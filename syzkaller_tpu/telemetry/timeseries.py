"""Fixed-capacity time series sampled from the metrics registry.

The campaign-analytics spine (ISSUE 2): point-in-time metrics answer
"how fast is it now", but the paper's claim — TPU-batched mutation finds
new signal *faster* — is a trajectory claim, so the manager samples the
registry snapshot on a fixed interval into bounded per-metric series and
serves them on ``/stats.json`` (and as the inline-SVG sparklines on the
``/dashboard`` page).

Bounding strategy: each ``Series`` holds at most ``capacity`` points.
When full it *downsamples in place* — every other point is dropped and
the effective sampling stride doubles — so a week-long campaign keeps
its whole trajectory at decreasing resolution instead of a sliding
window that forgets the start.  Invariants (asserted by the tests):

  - ``len(series) <= capacity`` always;
  - the first recorded point is never dropped (index 0 survives ``[::2]``),
    so growth curves keep their true origin;
  - timestamps stay strictly increasing;
  - ``stride`` is ``2**k`` times the base interval after k downsamples.

Values are stored exactly as sampled (cumulative counters stay
cumulative); rate views are computed by the consumer from consecutive
deltas — downsampling a cumulative series loses no area, whereas
downsampling a pre-computed rate would.

No jax/numpy imports: like the rest of telemetry this must stay cheap
and loadable on host-only deployments.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Tuple

from . import metrics as _metrics

DEFAULT_CAPACITY = 240
DEFAULT_INTERVAL = 5.0


class Series:
    """One metric's bounded (t, value) history with halving downsample.

    Writer (the sampler tick) and readers (/stats.json, the dashboard)
    are different threads, and the in-place downsample rebinds ts/vals —
    a per-series lock keeps every read an aligned (t, v) snapshot."""

    __slots__ = ("name", "capacity", "ts", "vals", "stride", "_lock")

    def __init__(self, name: str, capacity: int = DEFAULT_CAPACITY):
        if capacity < 4:
            raise ValueError(f"capacity must be >= 4, got {capacity}")
        self.name = name
        self.capacity = capacity
        self.ts: List[float] = []
        self.vals: List[float] = []
        self.stride = 1  # samples merged per kept point (2**downsamples)
        self._lock = threading.Lock()

    def __len__(self) -> int:
        return len(self.ts)

    def append(self, t: float, v: float) -> None:
        with self._lock:
            if self.ts and t <= self.ts[-1]:
                return  # clock went backwards / duplicate tick: monotonic
            if len(self.ts) >= self.capacity:
                # halve resolution, keeping index 0 (the campaign origin)
                # and every other point after it; stride doubles
                self.ts = self.ts[::2]
                self.vals = self.vals[::2]
                self.stride *= 2
            self.ts.append(t)
            self.vals.append(v)

    def points(self) -> List[Tuple[float, float]]:
        with self._lock:
            return list(zip(self.ts, self.vals))

    def to_dict(self) -> Dict[str, object]:
        with self._lock:
            return {"t": list(self.ts), "v": list(self.vals),
                    "stride": self.stride}


class TimeSeriesStore:
    """Name -> Series map; one ``record_snapshot`` call per sampling tick."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self.capacity = capacity
        self._lock = threading.Lock()
        self._series: Dict[str, Series] = {}

    def series(self, name: str) -> Series:
        with self._lock:
            s = self._series.get(name)
            if s is None:
                s = self._series[name] = Series(name, self.capacity)
            return s

    def record(self, name: str, t: float, v: float) -> None:
        self.series(name).append(t, v)

    def record_snapshot(self, t: float, snap: Dict[str, float]) -> None:
        for name, v in snap.items():
            self.series(name).append(t, v)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._series)

    def to_dict(self) -> Dict[str, object]:
        with self._lock:
            items = list(self._series.items())
        return {name: s.to_dict() for name, s in items}


class RegistrySampler:
    """Samples a registry snapshot (plus optional extra values) into a
    TimeSeriesStore on a fixed interval.

    ``sample()`` is callable directly — tests and short mock campaigns
    drive ticks by hand; ``start()`` runs the same tick from a daemon
    thread for live managers.  Histogram ``_sum``/``_count`` pairs ride
    along from ``snapshot()``, so per-phase latency trajectories (e.g.
    ``span_device_fuzz_step_dispatch_seconds_sum``) come for free.
    """

    def __init__(self, registry: Optional[_metrics.Registry] = None,
                 interval: float = DEFAULT_INTERVAL,
                 capacity: int = DEFAULT_CAPACITY,
                 extra: Optional[Callable[[], Dict[str, float]]] = None):
        self.registry = registry
        self.interval = float(interval)
        self.store = TimeSeriesStore(capacity)
        self.extra = extra
        self.samples_taken = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _reg(self) -> _metrics.Registry:
        return self.registry or _metrics.get_registry()

    def sample(self, now: Optional[float] = None) -> Dict[str, float]:
        import time

        t = time.time() if now is None else now
        snap = dict(self._reg().snapshot())
        if self.extra is not None:
            try:
                snap.update(self.extra())
            except Exception:
                pass  # a dying manager must not kill the sampler tick
        self.store.record_snapshot(t, snap)
        self.samples_taken += 1
        return snap

    def start(self) -> None:
        if self.interval <= 0:
            return  # manual-tick mode: a 0-interval loop would spin hot
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="telemetry-sampler", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=2.0)

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.sample()
            except Exception:
                pass  # sampling must never take the manager down


def rate_points(ts: List[float], vals: List[float]
                ) -> List[Tuple[float, float]]:
    """Per-second rate between consecutive samples of a cumulative
    series: [(t_i, (v_i - v_{i-1}) / (t_i - t_{i-1})), ...].  Negative
    deltas (a counter restarted) clamp to 0 rather than plotting a dip
    to a bogus negative rate."""
    out: List[Tuple[float, float]] = []
    for i in range(1, len(ts)):
        dt = ts[i] - ts[i - 1]
        if dt <= 0:
            continue
        out.append((ts[i], max(vals[i] - vals[i - 1], 0) / dt))
    return out
