"""Telemetry subsystem: metrics registry + span tracing.

One import surface for the rest of the tree:

    from ..telemetry import get_registry, span

    get_registry().counter("exec_total").inc()
    with span("device.fuzz_step"):
        ...

``metrics`` holds the thread-safe counter/gauge/histogram registry with
snapshot()/delta() and Prometheus text exposition; ``trace`` holds the
nestable span timers with Chrome-trace JSON export.  The manager serves
both on /metrics and /trace (manager/html.py); ``--telemetry-out`` on the
engine and bench.py dumps them as one JSON document.

No jax/numpy imports here: telemetry must load (and stay cheap) on
host-only deployments.
"""

from .attribution import (  # noqa: F401
    OP_NAMES,
    PHASES,
    AttributionLedger,
    Provenance,
    get_ledger,
    ops_from_mask,
)
from .journal import (  # noqa: F401
    PROC_TOKEN,
    CampaignJournal,
    get_journal,
    journal_emit,
    mint_engine_id,
)
from .metrics import (  # noqa: F401
    DEFAULT_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    Registry,
    get_registry,
)
from .timeseries import (  # noqa: F401
    RegistrySampler,
    Series,
    TimeSeriesStore,
    rate_points,
)
from .trace import Tracer, get_tracer, record_span, span, timed  # noqa: F401


def telemetry_dump() -> dict:
    """The --telemetry-out document: metrics snapshot + Chrome trace +
    the phase/operator attribution ledger."""
    return {
        "metrics": get_registry().snapshot(),
        "trace": get_tracer().chrome_trace(),
        "attribution": get_ledger().snapshot(),
    }


def telemetry_dump_to(path: str):
    """Write the --telemetry-out document to ``path``.  Returns an error
    string instead of raising — a bad dump path must not cost the caller
    (engine CLI, bench) the run's own outcome."""
    import json

    try:
        with open(path, "w") as fh:
            json.dump(telemetry_dump(), fh)
        return None
    except Exception as e:
        return f"{type(e).__name__}: {e}"


def set_spans_enabled(enabled: bool) -> None:
    """Global span opt-out (counters stay on — they are the wire stats)."""
    get_registry().spans_enabled = bool(enabled)


def count_error(site: str, exc=None) -> None:
    """Log + count a handled error: the replacement for bare
    ``except Exception: pass``.  Bumps the aggregate ``errors_total``
    plus a per-site ``errors_<site>_total`` counter (dynamic names —
    tools/check_metrics exempts non-literal registrations), and logs at
    verbosity 1 so failures are visible, never silent."""
    reg = get_registry()
    reg.counter(
        "errors_total",
        help="handled internal errors (per-site split: errors_<site>_total)"
    ).inc()
    reg.counter("errors_" + site + "_total").inc()
    if exc is not None:
        from ..utils.log import logf

        logf(1, "error at %s: %s: %s", site, type(exc).__name__, exc)
