"""Durable campaign journal: the workdir outlives the process.

The telemetry spine (registry, sampler series, attribution ledger) dies
with the engine; the checkpoint restores the *latest* state but not the
*trajectory*.  The journal closes that gap: an append-only, CRC-chained,
sequence-numbered JSONL event log in the workdir, written at every state
transition the registry only counts — checkpoint save/restore, env
restart/quarantine/unquarantine, device degradation-ladder steps,
admission Bloom resets + yield decays, RPC reconnects, and crash /
corpus-add / new-signal events stamped with full provenance (phase,
operator indices, arena row) — so a campaign's corpus/signal/yield
trajectory can be rebuilt from the workdir alone (``replay``), no live
process required.

Record format (one JSON object per line, key order canonicalized):

    {"seq": N, "t": <unix ts>, "ev": "<type>", "eng": "<engine id>",
     "pc": "<prev record's crc>", ...event fields..., "crc": "<crc32>"}

``crc`` is the CRC32 (hex) of the record's canonical JSON *without* the
crc field; ``pc`` chains it to the previous record, so a reader verifies
both per-record integrity and the end-to-end chain (``verify_records``).
Rotation keeps the log bounded: past ``max_bytes`` the current segment
shifts to ``<path>.1`` (older segments to ``.2``...), the oldest beyond
``segments`` is dropped, and ``seq``/``pc`` continue across the shift —
a rotated-away prefix breaks only the first surviving record's back
link, which the verifier reports as informational, not corruption.

Durability bound: every ``emit`` writes one complete line and flushes it
to the OS, so a SIGKILL'd engine loses at most the record being written
at the instant of the kill (a truncated final line, which readers
tolerate and count as a defect).  ``sync()`` additionally fsyncs — the
engine calls it on every checkpoint and on clean exit, where the
terminal ``campaign_end`` record is written.

Like the rest of telemetry: stdlib only, no jax/numpy — the journal
must load (and replay) on host-only deployments and in offline tooling
(tools/journalcat.py).
"""

from __future__ import annotations

import json
import os
import threading
import zlib
from typing import Dict, Iterable, List, Optional, Tuple

from .metrics import get_registry

JOURNAL_NAME = "journal.jsonl"
DEFAULT_MAX_BYTES = 4 << 20
DEFAULT_SEGMENTS = 4

# One random token per *process*, minted at import: lets a manager tell
# "this engine shares my process" (its ledger credit is already in the
# process-global ledger — merging its shipped state would double-count)
# from a genuinely remote engine.  Not persisted on purpose: a restart
# IS a new process.
PROC_TOKEN = os.urandom(8).hex()


def mint_engine_id(workdir: str = "") -> str:
    """The persistent engine identity: minted once per workdir (stored
    in ``<workdir>/engine_id`` so ``--resume`` and every later restart
    continue the same trajectory under the same id), ephemeral when no
    workdir is configured.  Stamped into wire stats, /stats.json,
    journal records, and checkpoints — the key fleet tooling dedups and
    attributes by."""
    fresh = "eng-" + os.urandom(8).hex()
    if not workdir:
        return fresh
    path = os.path.join(workdir, "engine_id")
    try:
        os.makedirs(workdir, exist_ok=True)
        with open(path, "r", encoding="utf-8") as fh:
            got = fh.read().strip()
        if got:
            return got
    except OSError:
        pass
    try:
        # atomic claim: two racing processes in one workdir both end up
        # reading the same winner
        tmp = path + f".tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as fh:
            fh.write(fresh + "\n")
            fh.flush()
            os.fsync(fh.fileno())
        if os.path.exists(path):
            os.remove(tmp)
            with open(path, "r", encoding="utf-8") as fh:
                return fh.read().strip() or fresh
        os.replace(tmp, path)
    except OSError:
        return fresh  # unwritable workdir: stay ephemeral, never raise
    return fresh


def _canon(rec: Dict) -> bytes:
    return json.dumps(rec, sort_keys=True, separators=(",", ":")).encode()


def _crc(rec: Dict) -> str:
    return "%08x" % zlib.crc32(_canon(rec))


class CampaignJournal:
    """Append-only rotating JSONL writer with a CRC/seq chain.

    Thread-safe: drain workers, the supervisor, and the scheduling
    thread all emit.  IO failures are counted (``errors_journal_write``)
    and swallowed — a full disk must not kill the campaign the journal
    exists to make auditable."""

    def __init__(self, path: str, engine_id: str = "",
                 max_bytes: int = DEFAULT_MAX_BYTES,
                 segments: int = DEFAULT_SEGMENTS):
        self.path = path
        self.engine_id = engine_id
        self.max_bytes = max(int(max_bytes), 1 << 12)
        self.segments = max(int(segments), 1)
        self._lock = threading.Lock()
        self._fh = None
        self._size = 0
        self.records_written = 0
        reg = get_registry()
        self._c_records = reg.counter(
            "journal_records_total",
            help="campaign journal records appended (event-sourced "
                 "state transitions: checkpoints, env supervision, "
                 "degradation, admission resets, corpus adds)")
        self._c_bytes = reg.counter(
            "journal_bytes_total",
            help="campaign journal bytes appended (pre-rotation; the "
                 "on-disk footprint is bounded by max_bytes * segments)")
        self._c_rotations = reg.counter(
            "journal_rotations_total",
            help="campaign journal segment rotations (oldest segment "
                 "beyond the retention bound is dropped)")
        # continue an existing journal's chain (resume in the same
        # workdir): the next record's seq/pc pick up where the last
        # durable record left off
        self.seq, self.prev_crc = self._recover_tail()

    # ---- writing ----

    def emit(self, ev: str, **fields) -> Optional[Dict]:
        """Append one event record; returns the record (or None when the
        write failed and was counted)."""
        import time

        rec = dict(fields)
        rec["ev"] = ev
        rec["t"] = round(time.time(), 3)
        if self.engine_id:
            rec["eng"] = self.engine_id
        with self._lock:
            rec["seq"] = self.seq
            rec["pc"] = self.prev_crc
            rec["crc"] = _crc(rec)
            line = json.dumps(rec, sort_keys=True,
                              separators=(",", ":")) + "\n"
            try:
                self._write_locked(line)
            except Exception as e:
                self._count_write_error(e)
                return None
            self.seq += 1
            self.prev_crc = rec["crc"]
            self.records_written += 1
        self._c_records.inc()
        self._c_bytes.inc(len(line))
        return rec

    def _write_locked(self, line: str) -> None:
        if self._fh is None:
            self._fh = open(self.path, "a", encoding="utf-8")
            self._size = self._fh.tell()
        self._fh.write(line)
        # flush to the OS per record: SIGKILL then loses at most the
        # line being written this very instant (the durability bound
        # the chaos test pins); fsync is reserved for sync()
        self._fh.flush()
        self._size += len(line)
        if self._size >= self.max_bytes:
            self._rotate_locked()

    def _rotate_locked(self) -> None:
        self._fh.close()
        self._fh = None
        oldest = f"{self.path}.{self.segments - 1}"
        if os.path.exists(oldest):
            os.remove(oldest)
        for k in range(self.segments - 2, 0, -1):
            src = f"{self.path}.{k}"
            if os.path.exists(src):
                os.replace(src, f"{self.path}.{k + 1}")
        if self.segments > 1:
            os.replace(self.path, f"{self.path}.1")
        else:
            os.remove(self.path)  # retention of one: truncate in place
        self._size = 0
        self._c_rotations.inc()

    def sync(self) -> None:
        """Flush + fsync the current segment (checkpoint / clean-exit
        durability; per-record emits only flush to the OS)."""
        with self._lock:
            if self._fh is None:
                return
            try:
                self._fh.flush()
                os.fsync(self._fh.fileno())
            except Exception as e:
                self._count_write_error(e)

    def close(self) -> None:
        with self._lock:
            fh, self._fh = self._fh, None
            if fh is None:
                return
            try:
                fh.flush()
                os.fsync(fh.fileno())
            except Exception as e:
                self._count_write_error(e)
            finally:
                fh.close()

    def _count_write_error(self, e: Exception) -> None:
        # local import: telemetry/__init__ imports this module
        from . import count_error

        count_error("journal_write", e)

    def _recover_tail(self) -> Tuple[int, str]:
        """Last durable (seq, crc) across existing segments, so a resumed
        engine continues the chain instead of restarting seq at 0.  A
        partial trailing record (the SIGKILL artifact) is truncated away
        first: appending onto it would fuse two records into one
        undecodable mid-file line — turning the tolerated ``tail:``
        defect into permanent corruption AND losing the first
        post-restart record."""
        try:
            self._heal_partial_tail()
            records, _defects = read_records(self.path)
        except OSError:
            return 0, ""
        if not records:
            return 0, ""
        last = records[-1]
        return int(last.get("seq", -1)) + 1, str(last.get("crc", ""))

    def _heal_partial_tail(self) -> None:
        """Drop undecodable trailing line(s) from the CURRENT segment (the
        only one ever appended to).  Every complete record ends with a
        newline and decodes as a JSON object; a crash mid-write leaves at
        most one trailing line violating that.  Earlier (non-trailing)
        corruption is evidence and is left untouched."""
        if not os.path.exists(self.path):
            return
        with open(self.path, "rb") as fh:
            data = fh.read()
        # line offsets: (start, end incl. newline, newline-terminated)
        lines = []
        pos = 0
        while pos < len(data):
            nl = data.find(b"\n", pos)
            if nl < 0:
                lines.append((pos, len(data), False))
                break
            lines.append((pos, nl + 1, True))
            pos = nl + 1
        keep = len(data)
        for start, end, terminated in reversed(lines):
            chunk = data[start:end].strip()
            good = False
            if terminated and chunk:
                try:
                    good = isinstance(json.loads(chunk), dict)
                except ValueError:
                    good = False
            if good:
                break  # durable tail found: keep everything up to here
            keep = start
        if keep < len(data):
            with open(self.path, "rb+") as fh:
                fh.truncate(keep)


# ---- process-global hook (far call sites: rpc reconnects, manager
# crash persistence — the engine owns and installs its journal) ----

_active: Optional[CampaignJournal] = None


def install(journal: Optional[CampaignJournal]) -> None:
    global _active
    _active = journal


def get_journal() -> Optional[CampaignJournal]:
    return _active


def journal_emit(ev: str, **fields) -> None:
    """Emit on the installed journal; no-op (one global read) when no
    campaign journal is active — production call sites outside the
    engine pay nothing in journal-less deployments."""
    j = _active
    if j is not None:
        j.emit(ev, **fields)


# ---- reading / verification ----


def journal_segments(path: str) -> List[str]:
    """Existing segment files oldest-first (``path.N`` ... ``path.1``,
    then ``path``).  ``path`` may be the journal file or a workdir
    containing ``journal.jsonl``."""
    if os.path.isdir(path):
        path = os.path.join(path, JOURNAL_NAME)
    out = []
    k = 1
    while os.path.exists(f"{path}.{k}"):
        k += 1
    for i in range(k - 1, 0, -1):
        out.append(f"{path}.{i}")
    if os.path.exists(path):
        out.append(path)
    return out


def read_records(path: str) -> Tuple[List[Dict], List[str]]:
    """Decode every record across segments oldest-first; returns
    (records, defects).  A truncated or corrupt line is a defect string,
    never an exception — the journal's contract is that a SIGKILL
    leaves at most one partial trailing record, and readers keep
    everything before it.  A defect that IS that tolerated case (the
    final line of the newest segment) is prefixed ``tail:`` so
    verifiers can report it as the expected crash artifact rather than
    corruption."""
    records: List[Dict] = []
    defects: List[str] = []
    segs = journal_segments(path)
    for si, seg in enumerate(segs):
        with open(seg, "rb") as fh:
            data = fh.read()
        lines = [(i, raw) for i, raw in enumerate(data.splitlines())
                 if raw.strip()]
        for li, (i, raw) in enumerate(lines):
            try:
                rec = json.loads(raw)
                if not isinstance(rec, dict):
                    raise ValueError("record is not an object")
            except ValueError as e:
                tail = (si == len(segs) - 1 and li == len(lines) - 1)
                defects.append(
                    f"{'tail: ' if tail else ''}"
                    f"{os.path.basename(seg)}:{i + 1}: "
                    f"undecodable record: {e}")
                continue
            records.append(rec)
    return records, defects


def verify_records(records: Iterable[Dict]) -> List[str]:
    """CRC + seq/chain verification over decoded records (assumed
    oldest-first).  Returns problem strings; empty == the chain holds
    end-to-end.  The first record's back link is only checkable when it
    is seq 0 (rotation may have dropped the true head)."""
    problems: List[str] = []
    prev_crc: Optional[str] = None
    prev_seq: Optional[int] = None
    for rec in records:
        seq = rec.get("seq")
        body = {k: v for k, v in rec.items() if k != "crc"}
        want = _crc(body)
        if rec.get("crc") != want:
            problems.append(f"seq {seq}: crc mismatch "
                            f"({rec.get('crc')!r} != {want})")
            # a corrupt record breaks the chain; re-anchor on it so one
            # flip reports once, not for every successor
        if prev_seq is not None and seq != prev_seq + 1:
            problems.append(f"seq {seq}: gap after {prev_seq}")
        if prev_crc is not None and rec.get("pc") != prev_crc:
            problems.append(f"seq {seq}: chain break (pc "
                            f"{rec.get('pc')!r} != prev crc {prev_crc!r})")
        elif prev_crc is None and seq == 0 and rec.get("pc") != "":
            problems.append("seq 0: nonempty back link on the first "
                            "record")
        prev_crc = rec.get("crc")
        prev_seq = seq if isinstance(seq, int) else prev_seq
    return problems


def verify(path: str) -> List[str]:
    """End-to-end journal verification: decode defects + chain problems
    in one list (what ``journalcat --verify`` prints)."""
    records, defects = read_records(path)
    return defects + verify_records(records)


# ---- replay: the trajectory from the workdir alone ----


def replay(path: str) -> Dict:
    """Rebuild the campaign's corpus/signal/yield trajectory from the
    journal alone — no live process, no registry.  Event-sourced
    counters are bit-exact (each ``corpus_add`` / ``signal`` record IS
    the increment); exec totals ride the periodic ``checkpoint_save``
    stats and are checkpoint-granular by design (per-exec journaling
    would blow the telemetry overhead bound).

    Returns::

        {"records": N, "defects": [...], "engines": [ids...],
         "events": {ev: count},
         "corpus_total": adds incl. seed,
         "new_inputs_total": adds excl. seed,
         "signal_total": new-signal PCs accepted,
         "series": {"corpus": [(t, v)], "new_inputs": [(t, v)],
                    "signal": [(t, v)], "execs": [(t, v)]},
         "attribution": {"phases": {p: {"corpus_adds", "new_signal"}},
                         "operators": {op: {...}}},
         "restores": checkpoint restores seen}
    """
    from .attribution import OP_NAMES

    records, defects = read_records(path)
    events: Dict[str, int] = {}
    engines: List[str] = []
    corpus = new_inputs = signal = restores = 0
    series: Dict[str, List[Tuple[float, float]]] = {
        "corpus": [], "new_inputs": [], "signal": [], "execs": []}
    phases: Dict[str, Dict[str, int]] = {}
    operators: Dict[str, Dict[str, int]] = {}

    def cell(table, key):
        c = table.get(key)
        if c is None:
            c = table[key] = {"corpus_adds": 0, "new_signal": 0}
        return c

    for rec in records:
        ev = rec.get("ev", "?")
        events[ev] = events.get(ev, 0) + 1
        eng = rec.get("eng")
        if eng and eng not in engines:
            engines.append(eng)
        t = float(rec.get("t", 0.0))
        if ev == "corpus_add":
            corpus += 1
            phase = rec.get("phase", "?")
            cell(phases, phase)["corpus_adds"] += 1
            for op in rec.get("ops", ()):
                if 0 <= int(op) < len(OP_NAMES):
                    cell(operators, OP_NAMES[int(op)])["corpus_adds"] += 1
            if phase != "seed":
                new_inputs += 1
                series["new_inputs"].append((t, new_inputs))
            series["corpus"].append((t, corpus))
        elif ev == "signal":
            n = int(rec.get("n", 0))
            signal += n
            cell(phases, rec.get("phase", "?"))["new_signal"] += n
            for op in rec.get("ops", ()):
                if 0 <= int(op) < len(OP_NAMES):
                    cell(operators, OP_NAMES[int(op)])["new_signal"] += n
            series["signal"].append((t, signal))
        elif ev in ("checkpoint_save", "campaign_end"):
            if "execs" in rec:
                series["execs"].append((t, int(rec["execs"])))
        elif ev == "checkpoint_restore":
            restores += 1
    return {
        "records": len(records),
        "defects": defects + verify_records(records),
        "engines": engines,
        "events": events,
        "corpus_total": corpus,
        "new_inputs_total": new_inputs,
        "signal_total": signal,
        "series": series,
        "attribution": {"phases": phases, "operators": operators},
        "restores": restores,
    }
