"""Thread-safe metrics registry: counters, gauges, fixed-bucket histograms.

The observability spine of the engine (role parity with the reference
syz-manager's Stat/uptime machinery plus its /metrics-style exposition):
every hot path — engine triage/smash/generate, the device candidate
pipeline, ipc exec, manager RPC, hub sync — bumps metrics here, and the
manager HTTP UI serves the registry as Prometheus text on ``/metrics``.

Design constraints (BASELINE: this rides the 100x-triage hot path):
  - counters are plain locked integer adds — no labels, no allocation;
  - histograms are fixed-bucket (bisect + locked add), latency-oriented;
  - gauges may be callback-backed (``set_fn``) so registry reads always
    see live state (corpus size etc.) without per-update bookkeeping;
  - ``snapshot()`` returns a flat name->number dict and ``delta()`` diffs
    two snapshots, so BENCH rounds can report per-phase rates;
  - the registry carries the ``spans_enabled`` flag that telemetry.trace
    consults — spans are opt-out with one attribute write.

Everything in-process shares the module-default registry (``get_registry``)
so a manager plus in-process fuzzers expose one merged view; tests build
private ``Registry()`` instances or ``reset()`` the default.
"""

from __future__ import annotations

import bisect
import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple, Union

Number = Union[int, float]

# Latency-oriented defaults: 100us .. 10s, roughly log-spaced.  Device
# dispatch lands in the low buckets, first-call JIT compiles in the top.
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


class Counter:
    """Monotonic counter; ``inc`` is a plain locked integer add."""

    __slots__ = ("name", "help", "_lock", "_value")

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._value = 0

    def inc(self, n: Number = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> Number:
        with self._lock:
            return self._value


class Gauge:
    """Point-in-time value; either set explicitly or backed by a callback
    (``set_fn``) that is evaluated on every read."""

    __slots__ = ("name", "help", "_lock", "_value", "_fn")

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._value: Number = 0
        self._fn: Optional[Callable[[], Number]] = None

    def set(self, v: Number) -> None:
        with self._lock:
            self._value = v
            self._fn = None

    def inc(self, n: Number = 1) -> None:
        with self._lock:
            self._value += n

    def dec(self, n: Number = 1) -> None:
        self.inc(-n)

    def set_fn(self, fn: Optional[Callable[[], Number]]) -> None:
        with self._lock:
            self._fn = fn

    def clear_fn(self, fn: Callable[[], Number]) -> None:
        """Detach ``fn`` iff it is still the bound callback — a newer
        instance may have re-bound the gauge, and its callback must not
        be clobbered by an older instance's close()."""
        with self._lock:
            if self._fn is fn:
                self._fn = None

    @property
    def value(self) -> Number:
        with self._lock:
            fn = self._fn
            if fn is None:
                return self._value
        try:
            return fn()
        except Exception:  # callback raced a teardown: last value stands
            return self._value


class Histogram:
    """Fixed-bucket histogram (cumulative on exposition, like Prometheus).

    ``observe`` is a bisect over a small static tuple plus one locked
    add — cheap enough for per-exec latencies."""

    __slots__ = ("name", "help", "buckets", "_lock", "_counts", "_sum",
                 "_count")

    def __init__(self, name: str, help: str = "",
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        self.name = name
        self.help = help
        self.buckets = tuple(sorted(buckets))
        self._lock = threading.Lock()
        self._counts = [0] * (len(self.buckets) + 1)  # last = +Inf
        self._sum = 0.0
        self._count = 0

    def observe(self, v: Number) -> None:
        i = bisect.bisect_left(self.buckets, v)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def cumulative(self) -> List[Tuple[float, int]]:
        """[(le, cumulative_count), ...] ending with (+inf, total)."""
        with self._lock:
            counts = list(self._counts)
        out: List[Tuple[float, int]] = []
        run = 0
        for le, c in zip(self.buckets, counts):
            run += c
            out.append((le, run))
        out.append((float("inf"), run + counts[-1]))
        return out


class Registry:
    """Name -> metric map with get-or-create accessors.

    ``spans_enabled`` is the opt-out flag telemetry.trace checks before
    recording span events (counters stay on: they are the wire stats)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, object] = {}
        self.spans_enabled = True
        # bumped on reset() so holders of bound metric objects (the
        # tracer's histogram cache) can detect staleness cheaply
        self.generation = 0

    def _get_or_create(self, name: str, cls, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, **kw)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, not {cls.__name__}")
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(name, Counter, help=help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(name, Gauge, help=help)

    def histogram(self, name: str, help: str = "",
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_create(name, Histogram, help=help,
                                   buckets=buckets)

    def get(self, name: str):
        with self._lock:
            return self._metrics.get(name)

    def metrics(self) -> List[object]:
        with self._lock:
            return [self._metrics[k] for k in sorted(self._metrics)]

    def reset(self) -> None:
        with self._lock:
            self._metrics.clear()
            self.spans_enabled = True
            self.generation += 1

    # ---- snapshots ----

    def snapshot(self) -> Dict[str, Number]:
        """Flat name->number view: counters and gauges by name, histograms
        as ``<name>_count`` / ``<name>_sum``."""
        out: Dict[str, Number] = {}
        for m in self.metrics():
            if isinstance(m, Counter):
                out[m.name] = m.value
            elif isinstance(m, Gauge):
                out[m.name] = m.value
            elif isinstance(m, Histogram):
                out[m.name + "_count"] = m.count
                out[m.name + "_sum"] = round(m.sum, 9)
        return out

    def delta(self, prev: Dict[str, Number]) -> Dict[str, Number]:
        """Difference of the current snapshot against an earlier one
        (names absent from ``prev`` diff against 0); gauge values pass
        through as-is since rates over gauges are meaningless."""
        cur = self.snapshot()
        gauges = {m.name for m in self.metrics() if isinstance(m, Gauge)}
        return {k: v if k in gauges else v - prev.get(k, 0)
                for k, v in cur.items()}

    # ---- Prometheus text exposition (format 0.0.4) ----

    def prometheus_text(self) -> str:
        lines: List[str] = []
        for m in self.metrics():
            if m.help:
                lines.append(f"# HELP {m.name} {m.help}")
            if isinstance(m, Counter):
                lines.append(f"# TYPE {m.name} counter")
                lines.append(f"{m.name} {_fmt(m.value)}")
            elif isinstance(m, Gauge):
                lines.append(f"# TYPE {m.name} gauge")
                lines.append(f"{m.name} {_fmt(m.value)}")
            elif isinstance(m, Histogram):
                lines.append(f"# TYPE {m.name} histogram")
                for le, c in m.cumulative():
                    le_s = "+Inf" if le == float("inf") else _fmt(le)
                    lines.append(
                        f'{m.name}_bucket{{le="{le_s}"}} {c}')
                lines.append(f"{m.name}_sum {_fmt(m.sum)}")
                lines.append(f"{m.name}_count {m.count}")
        return "\n".join(lines) + "\n"


def _fmt(v: Number) -> str:
    if isinstance(v, float):
        return repr(v) if v != int(v) else str(int(v))
    return str(v)


_default = Registry()


def get_registry() -> Registry:
    """The process-wide default registry (manager + in-process fuzzers
    share it so /metrics exposes one merged view)."""
    return _default
