"""syz-manager binary equivalent: `python -m syzkaller_tpu.manager`.

Role parity with reference /root/reference/syz-manager/manager.go:115-136
(main): load the strict-JSON config, start the manager (RPC + HTTP + hub +
bench series) and the VM fleet loop, run until interrupted.
"""

from __future__ import annotations

import argparse
import signal
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="syz-manager")
    ap.add_argument("-config", required=True, help="JSON config file")
    ap.add_argument("-bench", default="", help="append stats JSON here")
    args = ap.parse_args(argv)

    from ..utils import log
    from ..utils.config import load_file
    from . import Manager, ManagerConfig
    from .vmloop import VMLoop, VMLoopConfig

    cfg = load_file(ManagerConfig, args.config)
    if args.bench:
        cfg.bench_file = args.bench
    mgr = Manager(cfg)
    loop = VMLoop(mgr, VMLoopConfig(
        procs=cfg.procs, mock_fuzzer=cfg.mock_executor))
    loop.start()
    log.logf(0, "serving rpc on %s, http on %s",
             mgr.rpc.addr, mgr.http.addr if mgr.http else "-")

    import threading

    stop = threading.Event()  # Event.wait has no check-then-pause race
    signal.signal(signal.SIGINT, lambda *a: stop.set())
    signal.signal(signal.SIGTERM, lambda *a: stop.set())
    try:
        stop.wait()
    finally:
        loop.stop()
        loop.join()
        mgr.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
