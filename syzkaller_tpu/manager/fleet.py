"""Fleet aggregator: one pane of glass over N managers.

The reference system's production story is a *fleet* — many managers,
each with many fuzzers, syncing through a hub — and at that scale the
question stops being "how fast is this engine" and becomes "what is the
fleet doing, and which engine did what".  This module is the
observability half of fleet federation (ROADMAP): a poller that scrapes
N managers' ``/stats.json`` and serves

  - ``/fleet.json`` — restart-aware folded fleet counters (monotonic
    across engine restarts), summed fleet gauges, per-engine health
    (online / stale / unreachable — never silently dropped), bounded
    aggregate time series, and the EXACT merged attribution ledger
    (``AttributionLedger.merge_state`` over each manager's
    ``attribution_state``, deduped by process token for in-process
    ledgers and by engine id for remote engines, so an engine polled
    through two managers — or a restarted engine — is counted once);
  - ``/fleet`` — an HTML dashboard: aggregate exec/signal/crash
    sparklines, per-engine health + yield tables, merged per-operator
    attribution.

Counter folding reuses the ``rate_points`` clamp semantics: per engine
and per counter the aggregator adds ``max(v - prev, 0)`` — a counter
that went backwards means the engine restarted, and the clamp keeps the
fleet aggregate monotonic without double-counting the restart's replay
(the engine's ``--resume`` restores its counters from the checkpoint,
so the post-restart values catch back up to ``prev`` and folding
resumes exactly where it left off).

Scrape targets are ``host:port`` of a manager HTTP UI (or a full
``/stats.json`` URL).  A target that stops answering is marked
``unreachable`` but its last-known contribution stays in the aggregate
— a dead manager must dent the fleet's *rate*, not rewrite its
*history*.  Runnable standalone::

    python -m syzkaller_tpu.manager.fleet \
        --managers 127.0.0.1:56741,127.0.0.1:56743 --http 127.0.0.1:8050
"""

from __future__ import annotations

import json
import threading
import time
from typing import Callable, Dict, List, Optional

from ..telemetry import (
    AttributionLedger,
    TimeSeriesStore,
    get_registry,
    rate_points,
)

FLEET_SCHEMA_VERSION = 1

# snapshot keys that are point-in-time values: the fleet aggregate is
# the SUM OF LATEST over engines with data, not a delta fold (folding a
# gauge as a counter would ratchet it upward forever)
GAUGE_KEYS = frozenset({
    "uptime_s", "phase", "corpus", "signal", "candidates", "fuzzers",
    "crash_types",
})

STATUS_ONLINE = "online"
STATUS_STALE = "stale"
STATUS_UNREACHABLE = "unreachable"


class _Engine:
    """Scrape-side state for one managed target."""

    __slots__ = ("target", "url", "name", "engine_id", "doc", "last_ok",
                 "last_attempt", "last_error", "scrapes", "errors",
                 "prev")

    def __init__(self, target: str):
        self.target = target
        self.url = (target if "://" in target
                    else f"http://{target}/stats.json")
        self.name: str = ""
        self.engine_id: Optional[str] = None
        self.doc: Optional[dict] = None      # last good /stats.json
        self.last_ok = 0.0
        self.last_attempt = 0.0
        self.last_error: str = ""
        self.scrapes = 0
        self.errors = 0
        self.prev: Dict[str, float] = {}     # last absolute counter values

    def status(self, now: float, stale_after: float) -> str:
        """ONLINE while the last successful scrape is within the
        staleness window — one transient scrape error must not flap the
        fleet view.  Past the window: UNREACHABLE when the most recent
        attempt failed (or nothing ever answered), STALE when scraping
        itself went quiet (aggregator paused) with no error to show."""
        if self.doc is None:
            return STATUS_UNREACHABLE
        if now - self.last_ok <= stale_after:
            return STATUS_ONLINE
        return STATUS_UNREACHABLE if self.last_error else STATUS_STALE


def _http_fetch(url: str, timeout: float) -> dict:
    import urllib.request

    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read())


class FleetAggregator:
    """Scrapes N managers' /stats.json into one fleet view.

    ``poll_once()`` is callable directly (tests and short campaigns
    drive ticks by hand, like RegistrySampler); ``start()`` runs it from
    a daemon thread.  ``fetch`` is injectable for hermetic tests."""

    def __init__(self, targets: List[str], interval: float = 5.0,
                 capacity: int = 240, timeout: float = 5.0,
                 stale_after: float = 0.0,
                 fetch: Optional[Callable[[str], dict]] = None):
        self.interval = float(interval)
        self.timeout = float(timeout)
        # 3 missed scrape windows => stale (operator rule of thumb)
        self.stale_after = float(stale_after) or 3.0 * max(
            self.interval, 1.0)
        self.engines = [_Engine(t) for t in targets]
        self._fetch = fetch or (
            lambda target: _http_fetch(
                next(e.url for e in self.engines if e.target == target),
                self.timeout))
        self.store = TimeSeriesStore(capacity)
        self.samples_taken = 0
        self._lock = threading.Lock()
        self._counters: Dict[str, float] = {}  # folded, monotonic
        # merged-attribution sources, deduped: one local-process ledger
        # per proc token, one state per engine id (latest wins)
        self._local_ledgers: Dict[str, Dict] = {}
        self._engine_ledgers: Dict[str, Dict] = {}
        # proc token per engine-ledger key: one process has ONE global
        # ledger, so engine entries sharing a proc (two fuzzers in one
        # process, seen via one or two managers) collapse to one
        self._engine_ledger_procs: Dict[str, str] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        reg = get_registry()
        self._c_scrapes = reg.counter(
            "fleet_scrapes_total",
            help="manager /stats.json scrape attempts by the fleet "
                 "aggregator")
        self._c_scrape_errors = reg.counter(
            "fleet_scrape_errors_total",
            help="manager /stats.json scrapes that failed (the engine "
                 "is marked stale/unreachable, never dropped)")
        self._g_online = reg.gauge(
            "fleet_engines_online",
            help="scraped managers currently answering /stats.json "
                 "within the staleness window")

    # ---- polling ----

    def poll_once(self, now: Optional[float] = None) -> int:
        """Scrape every target once; returns how many answered.  Never
        raises — a dead manager is a status, not an exception."""
        now = time.time() if now is None else now
        ok = 0
        for eng in self.engines:
            eng.last_attempt = now
            self._c_scrapes.inc()
            try:
                doc = self._fetch(eng.target)
                if not isinstance(doc, dict):
                    raise ValueError("stats document is not an object")
            except Exception as e:  # noqa: BLE001 — status, not crash
                eng.errors += 1
                eng.last_error = f"{type(e).__name__}: {e}"
                self._c_scrape_errors.inc()
                continue
            ok += 1
            eng.scrapes += 1
            eng.doc = doc
            eng.last_ok = now
            eng.last_error = ""
            eng.name = str(doc.get("name") or eng.target)
            eng.engine_id = doc.get("engine_id") or eng.engine_id
            with self._lock:
                self._fold_counters_locked(eng)
                self._merge_attribution_locked(doc)
        with self._lock:
            online = sum(1 for e in self.engines
                         if e.status(now, self.stale_after)
                         == STATUS_ONLINE)
            self._g_online.set(online)
            point = dict(self._counters)
            point.update(self._gauge_sums_locked())
            point["fleet_engines_online"] = online
            self.store.record_snapshot(now, point)
            self.samples_taken += 1
        return ok

    def _fold_counters_locked(self, eng: _Engine) -> None:
        """Restart-aware delta fold of one engine's snapshot counters
        into the fleet aggregate (the rate_points clamp: negative
        deltas — a restarted engine whose --resume rewound its counters
        to the last checkpoint — contribute 0 until the engine catches
        back up past its previous high-water mark, keeping the fleet
        totals monotonic without double-counting)."""
        snap = (eng.doc or {}).get("snapshot") or {}
        for k, v in snap.items():
            if k in GAUGE_KEYS or not isinstance(v, (int, float)):
                continue
            prev = eng.prev.get(k, 0)
            dv = v - prev
            if dv > 0:
                self._counters[k] = self._counters.get(k, 0) + dv
            eng.prev[k] = max(v, prev)

    def _gauge_sums_locked(self) -> Dict[str, float]:
        """Sum-of-latest over every engine that ever answered: stale and
        unreachable engines keep contributing their last-known values —
        marked, not dropped."""
        out: Dict[str, float] = {}
        for eng in self.engines:
            snap = (eng.doc or {}).get("snapshot") or {}
            for k in GAUGE_KEYS:
                v = snap.get(k)
                if isinstance(v, (int, float)) and k != "phase":
                    out[k] = out.get(k, 0) + v
        return out

    def _merge_attribution_locked(self, doc: dict) -> None:
        st = doc.get("attribution_state")
        if not isinstance(st, dict):
            return
        proc = st.get("proc")
        if proc and isinstance(st.get("local"), dict):
            # one process-global ledger per process, however many
            # managers in that process serve it
            self._local_ledgers[str(proc)] = st["local"]
        for name, ent in (st.get("engines") or {}).items():
            if not isinstance(ent, dict) or not ent.get("state"):
                continue
            # dedup remote engines by persistent id when stamped, else
            # by manager-scoped name (pre-id engines can't be followed
            # across managers — documented limitation)
            key = str(ent.get("engine_id") or f"{doc.get('name')}:{name}")
            eproc = str(ent.get("proc") or "")
            if eproc:
                # one surviving entry per engine PROCESS (its ledger is
                # process-global): a second fuzzer of the same process,
                # or the same engine seen through two managers, would
                # otherwise double-count every cell
                for other, op in list(self._engine_ledger_procs.items()):
                    if op == eproc and other != key:
                        self._engine_ledgers.pop(other, None)
                        self._engine_ledger_procs.pop(other, None)
                self._engine_ledger_procs[key] = eproc
            self._engine_ledgers[key] = ent["state"]

    # ---- reading ----

    def merged_ledger(self) -> AttributionLedger:
        """The exact fleet attribution ledger: every deduped source
        merged once (merge_state is cell-wise integer addition, so the
        merged phase totals equal the sum of the sources')."""
        merged = AttributionLedger()
        with self._lock:
            # an engine entry whose proc also served a local ledger
            # (an engine sharing a manager's process, scraped through a
            # DIFFERENT manager) is the same ledger twice: local wins
            sources = list(self._local_ledgers.values()) + [
                st for key, st in self._engine_ledgers.items()
                if self._engine_ledger_procs.get(key)
                not in self._local_ledgers]
        for st in sources:
            merged.merge_state(st)
        return merged

    def engine_rows(self, now: Optional[float] = None
                    ) -> List[Dict[str, object]]:
        now = time.time() if now is None else now
        rows = []
        for eng in self.engines:
            snap = (eng.doc or {}).get("snapshot") or {}
            rows.append({
                "target": eng.target,
                "name": eng.name or eng.target,
                "engine_id": eng.engine_id,
                "status": eng.status(now, self.stale_after),
                "last_ok_age_s": (round(now - eng.last_ok, 1)
                                  if eng.last_ok else None),
                "scrapes": eng.scrapes,
                "errors": eng.errors,
                "last_error": eng.last_error,
                "engines": (eng.doc or {}).get("engines") or {},
                "snapshot": snap,
            })
        return rows

    def fleet_doc(self, now: Optional[float] = None) -> Dict[str, object]:
        """The /fleet.json payload."""
        now = time.time() if now is None else now
        merged = self.merged_ledger()
        with self._lock:
            counters = dict(self._counters)
            gauges = self._gauge_sums_locked()
            engine_ledgers = {k: dict(v)
                              for k, v in self._engine_ledgers.items()}
        rows = self.engine_rows(now)
        return {
            "schema_version": FLEET_SCHEMA_VERSION,
            "now": now,
            "interval": self.interval,
            "samples": self.samples_taken,
            "engines": rows,
            "engines_online": sum(1 for r in rows
                                  if r["status"] == STATUS_ONLINE),
            "counters": counters,
            "gauges": gauges,
            "series": self.store.to_dict(),
            "attribution": merged.snapshot(),
            "attribution_state": merged.state(),
            "engine_ledgers": engine_ledgers,
        }

    # ---- thread lifecycle (mirrors RegistrySampler) ----

    def start(self) -> None:
        if self.interval <= 0 or self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="fleet-aggregator", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=2.0)

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.poll_once()
            except Exception:
                pass  # poll_once already never raises; belt and braces


class FleetHttp:
    """Serves /fleet.json + the /fleet dashboard for a FleetAggregator
    (same shape as ManagerHttp: ephemeral-port friendly, daemon thread)."""

    def __init__(self, fleet: FleetAggregator, host: str = "127.0.0.1",
                 port: int = 0):
        import http.server
        import urllib.parse

        self.fleet = fleet
        ui = self

        class _Handler(http.server.BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self) -> None:
                try:
                    url = urllib.parse.urlparse(self.path)
                    route = {
                        "/": ui._dashboard,
                        "/fleet": ui._dashboard,
                        "/fleet.json": ui._fleet_json,
                        "/metrics": ui._metrics,
                    }.get(url.path)
                    if route is None:
                        self.send_error(404)
                        return
                    ctype, body = route()
                    self.send_response(200)
                    self.send_header("Content-Type", ctype)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                except BrokenPipeError:
                    pass
                except Exception as e:  # pragma: no cover - defensive
                    try:
                        self.send_error(500, str(e))
                    except Exception:
                        pass

        class _Server(http.server.ThreadingHTTPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._server = _Server((host, port), _Handler)
        self.addr = "%s:%d" % self._server.server_address
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True)

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()

    # ---- endpoints ----

    def _fleet_json(self) -> tuple:
        return ("application/json",
                json.dumps(self.fleet.fleet_doc(), sort_keys=True).encode())

    def _metrics(self) -> tuple:
        return ("text/plain; version=0.0.4",
                get_registry().prometheus_text().encode())

    def _dashboard(self) -> tuple:
        """The fleet pane: aggregate sparklines (exec/signal/crash),
        per-engine health + yield table, merged operator attribution —
        same rendering idioms as the manager dashboard (values live in
        text; strokes only say "this is the series")."""
        from .html import _fmt_num, _page, _spark_panel, _table

        doc = self.fleet.fleet_doc()
        stored = doc["series"]
        parts = ['<p><a href="/fleet.json">fleet.json</a></p>']

        def series(name):
            s = stored.get(name) or {"t": [], "v": []}
            return s["t"], s["v"]

        panels = []
        for title, name, as_rate in (
                ("fleet exec rate /s", "exec_total", True),
                ("fleet signal", "signal", False),
                ("fleet crash rate /s", "crashes", True),
                ("fleet corpus", "corpus", False),
                ("engines online", "fleet_engines_online", False)):
            ts, vals = series(name)
            if as_rate:
                pts = rate_points(ts, vals)
                ts = [t for t, _ in pts]
                vals = [v for _, v in pts]
            panels.append(_spark_panel(title, ts, vals))
        parts.append('<div class="sparks">' + "".join(panels) + "</div>")

        rows = []
        for r in doc["engines"]:
            snap = r["snapshot"]
            execs = snap.get("exec_total", 0)
            adds = snap.get("new_inputs", 0)
            rows.append([
                r["name"], r["target"], r["engine_id"] or "-",
                r["status"],
                "-" if r["last_ok_age_s"] is None
                else f'{r["last_ok_age_s"]}s',
                _fmt_num(snap.get("corpus", 0)),
                _fmt_num(snap.get("signal", 0)),
                _fmt_num(execs), _fmt_num(adds),
                _fmt_num(round(1000.0 * adds / execs, 3)) if execs
                else "n/a",
                _fmt_num(snap.get("crashes", 0)),
                r["errors"],
            ])
        parts.append(
            "<h2>engines</h2>" + _table(
                ["manager", "target", "engine id", "status", "last seen",
                 "corpus", "signal", "execs", "new inputs",
                 "yield/kexec", "crashes", "scrape errors"], rows))

        att = doc["attribution"]
        cols = ["execs", "corpus_adds", "new_signal", "adds_per_kexec",
                "signal_per_kexec"]
        ops = att.get("operators", {})
        if ops:
            orows = [[name] + [_fmt_num(c[k]) for k in cols]
                     for name, c in sorted(
                         ops.items(),
                         key=lambda kv: -kv[1]["adds_per_kexec"])]
            parts.append("<h2>merged per-operator yield</h2>"
                         + _table(["operator"] + cols, orows))
        phases = att.get("phases", {})
        if phases:
            prows = [[name] + [_fmt_num(c[k]) for k in cols]
                     for name, c in sorted(phases.items())]
            parts.append("<h2>merged per-phase yield</h2>"
                         + _table(["phase"] + cols, prows))
        fold = [[k, _fmt_num(v)]
                for k, v in sorted(doc["counters"].items())][:40]
        if fold:
            parts.append("<h2>folded fleet counters (monotonic)</h2>"
                         + _table(["counter", "value"], fold))
        return "text/html", _page(
            f"fleet ({doc['engines_online']}/{len(doc['engines'])} online)",
            "".join(parts))


def main(argv=None) -> int:
    """``python -m syzkaller_tpu.manager.fleet`` — standalone fleet
    aggregator over comma-separated manager HTTP addresses."""
    import argparse

    ap = argparse.ArgumentParser(prog="syz-fleet")
    ap.add_argument("--managers", required=True,
                    help="comma-separated manager HTTP addresses "
                         "(host:port of the manager UI)")
    ap.add_argument("--http", default="127.0.0.1:0",
                    help="address to serve /fleet + /fleet.json on")
    ap.add_argument("--interval", type=float, default=5.0)
    args = ap.parse_args(argv)
    targets = [t.strip() for t in args.managers.split(",") if t.strip()]
    fleet = FleetAggregator(targets, interval=args.interval)
    host, port = args.http.rsplit(":", 1)
    http = FleetHttp(fleet, host, int(port))
    http.start()
    fleet.start()
    print(f"fleet aggregator over {len(targets)} manager(s) "
          f"on http://{http.addr}/fleet")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        return 0
    finally:
        fleet.stop()
        http.stop()


if __name__ == "__main__":
    import sys

    sys.exit(main())
