"""Manager HTTP UI: summary, corpus, crashes, coverage, priorities.

Role parity with reference /root/reference/syz-manager/html.go:30-39
(endpoint set) and syz-manager/cover.go:52-110 (coverage report).  The
reference's report objdumps vmlinux for all coverable PCs; here the
report is built from the PCs the fleet actually covered — symbolized to
func/file:line when a kernel object is configured (report/symbolize.py
wraps addr2line/nm), raw PC tables otherwise.
"""

from __future__ import annotations

import html as _html
import http.server
import json
import os
import threading
import urllib.parse
from typing import Dict, List, Optional

from ..prog.encoding import call_set
from ..telemetry import get_registry, get_tracer

_STYLE = """
<style>
body { font-family: monospace; margin: 1em 2em; }
table { border-collapse: collapse; }
td, th { border: 1px solid #999; padding: 2px 8px; text-align: left; }
th { background: #eee; }
a { text-decoration: none; }
h1 { font-size: 1.3em; }
</style>
"""


def _page(title: str, body: str) -> bytes:
    return (f"<html><head><title>{_html.escape(title)}</title>{_STYLE}"
            f"</head><body><h1>{_html.escape(title)}</h1>{body}"
            f"</body></html>").encode()


def _table(headers: List[str], rows: List[List[str]],
           raw: bool = False) -> str:
    esc = (lambda s: s) if raw else (lambda s: _html.escape(str(s)))
    out = ["<table><tr>"]
    out += [f"<th>{_html.escape(h)}</th>" for h in headers]
    out.append("</tr>")
    for r in rows:
        out.append("<tr>" + "".join(f"<td>{esc(c)}</td>" for c in r)
                   + "</tr>")
    out.append("</table>")
    return "".join(out)


class ManagerHttp:
    """Serves the UI for a live Manager on cfg.http (ephemeral port ok)."""

    def __init__(self, mgr, host: str = "127.0.0.1", port: int = 0):
        self.mgr = mgr
        self._sym = None
        self._sym_lock = threading.Lock()
        ui = self

        class _Handler(http.server.BaseHTTPRequestHandler):
            def log_message(self, *a):  # silenced: the manager has own logs
                pass

            def do_GET(self) -> None:
                try:
                    url = urllib.parse.urlparse(self.path)
                    q = dict(urllib.parse.parse_qsl(url.query))
                    route = {
                        "/": ui._summary,
                        "/corpus": ui._corpus,
                        "/crash": ui._crash,
                        "/cover": ui._cover,
                        "/rawcover": ui._rawcover,
                        "/prio": ui._prio,
                        "/stats": ui._stats,
                        "/metrics": ui._metrics,
                        "/trace": ui._trace,
                    }.get(url.path)
                    if route is None:
                        self.send_error(404)
                        return
                    ctype, body = route(q)
                    self.send_response(200)
                    self.send_header("Content-Type", ctype)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                except BrokenPipeError:
                    pass
                except Exception as e:  # pragma: no cover - defensive
                    try:
                        self.send_error(500, str(e))
                    except Exception:
                        pass

        class _Server(http.server.ThreadingHTTPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._server = _Server((host, port), _Handler)
        self.addr = "%s:%d" % self._server.server_address
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True)

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()

    # ---- pages ----

    def _summary(self, q) -> tuple:
        m = self.mgr
        snap = m.snapshot()
        stats_rows = [[k, v] for k, v in sorted(snap.items())]
        with m._lock:
            crashes = sorted(m.crashes.values(),
                             key=lambda e: -e.count)
        crash_rows = [
            [f'<a href="/crash?title={urllib.parse.quote(e.title)}">'
             f'{_html.escape(e.title)}</a>', e.count] for e in crashes]
        body = (
            f'<p><a href="/corpus">corpus</a> | <a href="/cover">cover</a>'
            f' | <a href="/prio">prio</a> | <a href="/rawcover">rawcover</a>'
            f' | <a href="/stats">stats.json</a>'
            f' | <a href="/metrics">metrics</a>'
            f' | <a href="/trace">trace</a></p>'
            + "<h2>stats</h2>" + _table(["stat", "value"], stats_rows)
            + "<h2>crashes</h2>"
            + _table(["title", "count"], crash_rows, raw=True))
        return "text/html", _page(m.cfg.name, body)

    def _corpus(self, q) -> tuple:
        m = self.mgr
        sig = q.get("sig")
        if sig:
            with m._lock:
                text = m.corpus.get(sig, "")
            return "text/plain", text.encode()
        with m._lock:
            items = [(h, t, len(m.corpus_signal.get(h, ())))
                     for h, t in m.corpus.items()]
        rows = [[f'<a href="/corpus?sig={h}">{h[:16]}</a>',
                 _html.escape(",".join(call_set(t))[:80]), n]
                for h, t, n in sorted(items, key=lambda it: -it[2])]
        return "text/html", _page(
            f"corpus ({len(rows)})",
            _table(["prog", "calls", "signal"], rows, raw=True))

    def _crash(self, q) -> tuple:
        m = self.mgr
        title = q.get("title", "")
        from ..utils.hash import hash_str

        d = os.path.join(m.crashdir, hash_str(title.encode())[:16])
        if not os.path.isdir(d):
            return "text/html", _page("crash", "unknown crash")
        parts = [f"<h2>{_html.escape(title)}</h2>"]
        for fn in sorted(os.listdir(d)):
            p = os.path.join(d, fn)
            with open(p, "rb") as f:
                blob = f.read(1 << 16)
            parts.append(f"<h3>{_html.escape(fn)}</h3><pre>"
                         f"{_html.escape(blob.decode('utf-8', 'replace'))}"
                         f"</pre>")
        return "text/html", _page("crash", "".join(parts))

    def _cover_pcs(self) -> List[int]:
        m = self.mgr
        with m._lock:
            return sorted(getattr(m, "max_cover", ()))

    def _cover(self, q) -> tuple:
        pcs = self._cover_pcs()
        if not pcs:
            return "text/html", _page("cover", "no coverage data")
        vmlinux = getattr(self.mgr.cfg, "kernel_obj", "")
        if vmlinux and os.path.exists(vmlinux):
            from ..report.symbolize import Symbolizer

            # one symbolizer per UI instance: its PC cache makes repeated
            # /cover views incremental instead of re-running addr2line.
            # Guarded by a lock: handler threads race on first view, and
            # the cache itself isn't thread-safe.
            with self._sym_lock:
                if self._sym is None:
                    self._sym = Symbolizer(vmlinux)
                frames = self._sym._resolve(pcs)
            by_file: Dict[str, List[str]] = {}
            for fr in frames:
                file = fr.split(":")[0] if ":" in fr else "?"
                by_file.setdefault(file, []).append(fr)
            rows = [[f, len(v),
                     ", ".join(sorted(set(v))[:8])]
                    for f, v in sorted(by_file.items())]
            body = _table(["file", "covered PCs", "frames"], rows)
        else:
            # raw fallback: group PCs by 64K region
            by_region: Dict[int, int] = {}
            for pc in pcs:
                by_region[pc >> 16] = by_region.get(pc >> 16, 0) + 1
            rows = [[hex(r << 16), n] for r, n in sorted(by_region.items())]
            body = (f"<p>{len(pcs)} covered PCs "
                    f"(no kernel_obj configured; raw regions)</p>"
                    + _table(["region", "PCs"], rows))
        return "text/html", _page(f"cover ({len(pcs)} PCs)", body)

    def _rawcover(self, q) -> tuple:
        pcs = self._cover_pcs()
        return ("text/plain",
                "".join(f"0x{pc:x}\n" for pc in pcs).encode())

    def _prio(self, q) -> tuple:
        m = self.mgr
        from ..prog.prio import calculate_priorities
        from ..prog.encoding import deserialize

        with m._lock:
            corpus = list(m.corpus.values())[:256]
        progs = []
        for t in corpus:
            try:
                progs.append(deserialize(m.target, t))
            except Exception:
                pass
        prios = calculate_priorities(m.target, progs)
        names = [s.name for s in m.target.syscalls]
        # top-N strongest pairs, like reading the reference's /prio page
        pairs = []
        n = len(names)
        for i in range(n):
            row = prios[i]
            for j in range(n):
                if i != j and row[j] > 0.1:
                    pairs.append((float(row[j]), names[i], names[j]))
        pairs.sort(reverse=True)
        rows = [[f"{p:.3f}", a, b] for p, a, b in pairs[:200]]
        return "text/html", _page(
            "call-pair priorities (top 200)",
            _table(["prio", "call", "related"], rows))

    def _stats(self, q) -> tuple:
        return ("application/json",
                json.dumps(self.mgr.snapshot(), sort_keys=True).encode())

    # ---- telemetry (ISSUE 1: registry + tracer exposition) ----

    def _metrics(self, q) -> tuple:
        """Prometheus text exposition of the process-wide registry (the
        manager's counters plus any in-process fuzzers' latencies)."""
        return ("text/plain; version=0.0.4",
                get_registry().prometheus_text().encode())

    def _trace(self, q) -> tuple:
        """Chrome trace-event JSON of the span buffer — load the response
        in chrome://tracing or Perfetto to read per-phase wall time."""
        return ("application/json",
                json.dumps(get_tracer().chrome_trace()).encode())
