"""Manager HTTP UI: summary, corpus, crashes, coverage, priorities.

Role parity with reference /root/reference/syz-manager/html.go:30-39
(endpoint set) and syz-manager/cover.go:52-110 (coverage report).  The
reference's report objdumps vmlinux for all coverable PCs; here the
report is built from the PCs the fleet actually covered — symbolized to
func/file:line when a kernel object is configured (report/symbolize.py
wraps addr2line/nm), raw PC tables otherwise.
"""

from __future__ import annotations

import html as _html
import http.server
import json
import os
import threading
import urllib.parse
from typing import Dict, List, Optional

import time

from ..prog.encoding import call_set
from ..telemetry import get_ledger, get_registry, get_tracer, rate_points

# /stats.json wire-shape version: the fleet aggregator (manager/fleet.py)
# and any external scraper key off this — bump it whenever a top-level
# key is added/removed/retyped (tests/test_fleet.py pins the shape)
# v2: added the top-level "frontend" block (compiler-frontend counters)
STATS_SCHEMA_VERSION = 2

# compiler-frontend counters surfaced as the /stats.json "frontend"
# block and the /dashboard "compiler frontend" table; zero-defaulted so
# the block exists (all zeros) in syscall-frontend and manager-only
# processes — scrapers never need a presence check
FRONTEND_METRICS = (
    "frontend_compiles_total",
    "frontend_compile_cache_hits_total",
    "frontend_miscompares_total",
    "frontend_exceptions_total",
    "frontend_exec_timeouts_total",
)

_STYLE = """
<style>
body { font-family: monospace; margin: 1em 2em;
       color-scheme: light;
       background: var(--surface-1); color: var(--text-primary);
       --surface-1: #fcfcfb; --text-primary: #0b0b0b;
       --text-secondary: #52514e; --series-1: #2a78d6; }
@media (prefers-color-scheme: dark) {
  body { color-scheme: dark;
         --surface-1: #1a1a19; --text-primary: #ffffff;
         --text-secondary: #c3c2b7; --series-1: #3987e5; }
}
table { border-collapse: collapse; }
td, th { border: 1px solid #999; padding: 2px 8px; text-align: left; }
th { background: rgba(153,153,153,0.15); }
a { text-decoration: none; }
h1 { font-size: 1.3em; }
.sparks { display: flex; flex-wrap: wrap; gap: 16px; }
.spark { border: 1px solid #999; padding: 6px 10px; }
.spark .t { color: var(--text-secondary); }
.spark .v { font-weight: bold; }
.spark polyline { stroke: var(--series-1); stroke-width: 2;
                  fill: none; stroke-linejoin: round; }
.spark line.base { stroke: var(--text-secondary); stroke-width: 1;
                   opacity: 0.35; }
</style>
"""


def _page(title: str, body: str) -> bytes:
    return (f"<html><head><title>{_html.escape(title)}</title>{_STYLE}"
            f"</head><body><h1>{_html.escape(title)}</h1>{body}"
            f"</body></html>").encode()


def _table(headers: List[str], rows: List[List[str]],
           raw: bool = False) -> str:
    esc = (lambda s: s) if raw else (lambda s: _html.escape(str(s)))
    out = ["<table><tr>"]
    out += [f"<th>{_html.escape(h)}</th>" for h in headers]
    out.append("</tr>")
    for r in rows:
        out.append("<tr>" + "".join(f"<td>{esc(c)}</td>" for c in r)
                   + "</tr>")
    out.append("</table>")
    return "".join(out)


def _fmt_num(v) -> str:
    if isinstance(v, float) and v != int(v):
        return f"{v:.4g}"
    return str(int(v)) if isinstance(v, (int, float)) else str(v)


def _spark_panel(title: str, ts: List[float], vals: List[float],
                 w: int = 260, h: int = 48) -> str:
    """One single-series sparkline panel: inline SVG polyline (the stroke
    carries "series", the title carries identity — no legend needed for
    one series), latest value as text, native <title> tooltips on the
    per-point hover targets."""
    n = len(vals)
    head = (f'<div class="spark"><div class="t">{_html.escape(title)}'
            f'</div>')
    if n < 2:
        return head + '<div class="v">no data yet</div></div>'
    t0, t1 = ts[0], ts[-1]
    lo, hi = min(vals), max(vals)
    span_t = (t1 - t0) or 1.0
    span_v = (hi - lo) or 1.0
    pad = 3

    def x(t):
        return pad + (t - t0) / span_t * (w - 2 * pad)

    def y(v):
        return h - pad - (v - lo) / span_v * (h - 2 * pad)

    pts = " ".join(f"{x(t):.1f},{y(v):.1f}" for t, v in zip(ts, vals))
    hovers = "".join(
        f'<circle cx="{x(t):.1f}" cy="{y(v):.1f}" r="6" fill="transparent">'
        f"<title>{_fmt_num(v)} @ +{t - t0:.0f}s</title></circle>"
        for t, v in zip(ts, vals))
    svg = (f'<svg width="{w}" height="{h}" role="img" '
           f'aria-label="{_html.escape(title)}">'
           f'<line class="base" x1="{pad}" y1="{y(lo):.1f}"'
           f' x2="{w - pad}" y2="{y(lo):.1f}"/>'
           f'<polyline points="{pts}"/>{hovers}</svg>')
    return (head + svg +
            f'<div class="v">{_fmt_num(vals[-1])}'
            f'<span class="t"> (min {_fmt_num(lo)}, max {_fmt_num(hi)}, '
            f'{n} pts)</span></div></div>')


class ManagerHttp:
    """Serves the UI for a live Manager on cfg.http (ephemeral port ok)."""

    def __init__(self, mgr, host: str = "127.0.0.1", port: int = 0):
        self.mgr = mgr
        self._sym = None
        self._sym_lock = threading.Lock()
        ui = self

        class _Handler(http.server.BaseHTTPRequestHandler):
            def log_message(self, *a):  # silenced: the manager has own logs
                pass

            def do_GET(self) -> None:
                try:
                    url = urllib.parse.urlparse(self.path)
                    q = dict(urllib.parse.parse_qsl(url.query))
                    route = {
                        "/": ui._summary,
                        "/corpus": ui._corpus,
                        "/crash": ui._crash,
                        "/cover": ui._cover,
                        "/rawcover": ui._rawcover,
                        "/prio": ui._prio,
                        "/stats": ui._stats,
                        "/stats.json": ui._stats_json,
                        "/dashboard": ui._dashboard,
                        "/metrics": ui._metrics,
                        "/trace": ui._trace,
                    }.get(url.path)
                    if route is None:
                        self.send_error(404)
                        return
                    ctype, body = route(q)
                    self.send_response(200)
                    self.send_header("Content-Type", ctype)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                except BrokenPipeError:
                    pass
                except Exception as e:  # pragma: no cover - defensive
                    try:
                        self.send_error(500, str(e))
                    except Exception:
                        pass

        class _Server(http.server.ThreadingHTTPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._server = _Server((host, port), _Handler)
        self.addr = "%s:%d" % self._server.server_address
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True)

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()

    # ---- pages ----

    def _summary(self, q) -> tuple:
        m = self.mgr
        snap = m.snapshot()
        stats_rows = [[k, v] for k, v in sorted(snap.items())]
        with m._lock:
            crashes = sorted(m.crashes.values(),
                             key=lambda e: -e.count)
        crash_rows = [
            [f'<a href="/crash?title={urllib.parse.quote(e.title)}">'
             f'{_html.escape(e.title)}</a>', e.count] for e in crashes]
        body = (
            f'<p><a href="/corpus">corpus</a> | <a href="/cover">cover</a>'
            f' | <a href="/prio">prio</a> | <a href="/rawcover">rawcover</a>'
            f' | <a href="/dashboard">dashboard</a>'
            f' | <a href="/stats">stats</a>'
            f' | <a href="/stats.json">stats.json</a>'
            f' | <a href="/metrics">metrics</a>'
            f' | <a href="/trace">trace</a></p>'
            + "<h2>stats</h2>" + _table(["stat", "value"], stats_rows)
            + "<h2>crashes</h2>"
            + _table(["title", "count"], crash_rows, raw=True))
        return "text/html", _page(m.cfg.name, body)

    def _corpus(self, q) -> tuple:
        m = self.mgr
        sig = q.get("sig")
        if sig:
            with m._lock:
                text = m.corpus.get(sig, "")
            return "text/plain", text.encode()
        with m._lock:
            items = [(h, t, len(m.corpus_signal.get(h, ())))
                     for h, t in m.corpus.items()]
        rows = [[f'<a href="/corpus?sig={h}">{h[:16]}</a>',
                 _html.escape(",".join(call_set(t))[:80]), n]
                for h, t, n in sorted(items, key=lambda it: -it[2])]
        return "text/html", _page(
            f"corpus ({len(rows)})",
            _table(["prog", "calls", "signal"], rows, raw=True))

    def _crash(self, q) -> tuple:
        m = self.mgr
        title = q.get("title", "")
        from ..utils.hash import hash_str

        d = os.path.join(m.crashdir, hash_str(title.encode())[:16])
        if not os.path.isdir(d):
            return "text/html", _page("crash", "unknown crash")
        parts = [f"<h2>{_html.escape(title)}</h2>"]
        for fn in sorted(os.listdir(d)):
            p = os.path.join(d, fn)
            with open(p, "rb") as f:
                blob = f.read(1 << 16)
            parts.append(f"<h3>{_html.escape(fn)}</h3><pre>"
                         f"{_html.escape(blob.decode('utf-8', 'replace'))}"
                         f"</pre>")
        return "text/html", _page("crash", "".join(parts))

    def _cover_pcs(self) -> List[int]:
        m = self.mgr
        with m._lock:
            return sorted(getattr(m, "max_cover", ()))

    def _cover(self, q) -> tuple:
        pcs = self._cover_pcs()
        if not pcs:
            return "text/html", _page("cover", "no coverage data")
        vmlinux = getattr(self.mgr.cfg, "kernel_obj", "")
        if vmlinux and os.path.exists(vmlinux):
            from ..report.symbolize import Symbolizer

            # one symbolizer per UI instance: its PC cache makes repeated
            # /cover views incremental instead of re-running addr2line.
            # Guarded by a lock: handler threads race on first view, and
            # the cache itself isn't thread-safe.
            with self._sym_lock:
                if self._sym is None:
                    self._sym = Symbolizer(vmlinux)
                frames = self._sym._resolve(pcs)
            by_file: Dict[str, List[str]] = {}
            for fr in frames:
                file = fr.split(":")[0] if ":" in fr else "?"
                by_file.setdefault(file, []).append(fr)
            rows = [[f, len(v),
                     ", ".join(sorted(set(v))[:8])]
                    for f, v in sorted(by_file.items())]
            body = _table(["file", "covered PCs", "frames"], rows)
        else:
            # raw fallback: group PCs by 64K region
            by_region: Dict[int, int] = {}
            for pc in pcs:
                by_region[pc >> 16] = by_region.get(pc >> 16, 0) + 1
            rows = [[hex(r << 16), n] for r, n in sorted(by_region.items())]
            body = (f"<p>{len(pcs)} covered PCs "
                    f"(no kernel_obj configured; raw regions)</p>"
                    + _table(["region", "PCs"], rows))
        return "text/html", _page(f"cover ({len(pcs)} PCs)", body)

    def _rawcover(self, q) -> tuple:
        pcs = self._cover_pcs()
        return ("text/plain",
                "".join(f"0x{pc:x}\n" for pc in pcs).encode())

    def _prio(self, q) -> tuple:
        m = self.mgr
        from ..prog.prio import calculate_priorities
        from ..prog.encoding import deserialize

        with m._lock:
            corpus = list(m.corpus.values())[:256]
        progs = []
        for t in corpus:
            try:
                progs.append(deserialize(m.target, t))
            except Exception:
                pass
        prios = calculate_priorities(m.target, progs)
        names = [s.name for s in m.target.syscalls]
        # top-N strongest pairs, like reading the reference's /prio page
        pairs = []
        n = len(names)
        for i in range(n):
            row = prios[i]
            for j in range(n):
                if i != j and row[j] > 0.1:
                    pairs.append((float(row[j]), names[i], names[j]))
        pairs.sort(reverse=True)
        rows = [[f"{p:.3f}", a, b] for p, a, b in pairs[:200]]
        return "text/html", _page(
            "call-pair priorities (top 200)",
            _table(["prio", "call", "related"], rows))

    def _stats(self, q) -> tuple:
        return ("application/json",
                json.dumps(self.mgr.snapshot(), sort_keys=True).encode())

    # ---- campaign analytics (ISSUE 2: series + attribution) ----

    def _stats_json(self, q) -> tuple:
        """Ring-buffer time series (registry snapshot sampled on the
        manager's analytics interval) + the phase/operator attribution
        ledger + a point-in-time snapshot, as one JSON document.

        The shape is versioned (``schema_version``) and pinned by a
        regression test: the fleet aggregator and external scrapers
        depend on it.  ``attribution_state`` carries the EXACT raw
        ledger counts (local process once, remote engines latest-wins)
        that merge across managers without double-counting;
        ``attribution`` stays the derived human-facing snapshot."""
        sampler = getattr(self.mgr, "sampler", None)
        att_state = getattr(self.mgr, "attribution_state", None)
        engines = getattr(self.mgr, "engines_info", None)
        reg_snap = get_registry().snapshot()
        payload = {
            "frontend": {k: reg_snap.get(k, 0) for k in FRONTEND_METRICS},
            "schema_version": STATS_SCHEMA_VERSION,
            "engine_id": getattr(self.mgr, "engine_id", None),
            "name": self.mgr.cfg.name,
            "now": time.time(),
            "interval": sampler.interval if sampler else None,
            "samples": sampler.samples_taken if sampler else 0,
            "series": sampler.store.to_dict() if sampler else {},
            "attribution": get_ledger().snapshot(),
            "attribution_state": att_state() if att_state else None,
            "engines": engines() if engines else {},
            "snapshot": self.mgr.snapshot(),
        }
        return ("application/json",
                json.dumps(payload, sort_keys=True).encode())

    @staticmethod
    def _series(stored, *names):
        """First sampled series among ``names`` that has actually moved,
        as (ts, vals); ``stored`` is one store.to_dict() snapshot shared
        by all panels so they render a consistent tick.  All-zero series
        are passed over so the preference order works across topologies:
        the bare counters exist (at 0) in every manager process, but in
        the RPC deployment only the fleet_* counters move — a flat-zero
        first choice must not shadow the live fallback.  If nothing
        moved, the first existing series is returned (an honest flat 0)."""
        first = ([], [])
        for n in names:
            s = stored.get(n)
            if s and s["t"]:
                if any(s["v"]):
                    return s["t"], s["v"]
                if not first[0]:
                    first = (s["t"], s["v"])
        return first

    def _dashboard(self, q) -> tuple:
        """Campaign dashboard: sparklines for the trajectory claims
        (signal growth, exec rate, crash rate, corpus) plus device-health
        gauges and the per-operator yield table.  Values live in the
        tables/labels (text ink); the sparkline stroke only says "this is
        the series" — single-series panels, titles name them."""
        parts = ['<p><a href="/">back</a> | '
                 '<a href="/stats.json">stats.json</a></p>']
        sampler = getattr(self.mgr, "sampler", None)
        stored = sampler.store.to_dict() if sampler else {}
        panels = []
        for title, names, as_rate in (
                ("signal growth", ("manager_signal", "max_signal_size"),
                 False),
                ("exec rate /s", ("exec_total", "fleet_exec_total"), True),
                ("admission rate /s", ("candidates_admitted_total",
                                       "fleet_device_admitted"), True),
                ("crash rate /s", ("manager_crashes", "crashes"), True),
                ("corpus size", ("manager_corpus", "corpus_size"), False)):
            ts, vals = self._series(stored, *names)
            if as_rate:
                pts = rate_points(ts, vals)
                ts = [t for t, _ in pts]
                vals = [v for _, v in pts]
            panels.append(_spark_panel(title, ts, vals))
        parts.append('<div class="sparks">' + "".join(panels) + "</div>")

        snap = get_registry().snapshot()
        health = [[k, _fmt_num(snap[k])] for k in (
            "device_batch_occupancy", "device_jit_cache_entries",
            "device_jit_compiles_total", "device_live_buffer_bytes",
            "device_batches_total") if k in snap]
        if health:
            parts.append("<h2>device health</h2>"
                         + _table(["gauge", "value"], health))

        # candidate admission + yield efficiency (ISSUE 5): the number a
        # perf PR is judged on is execs-per-new-input, not raw execs/sec.
        # In the RPC deployment the engine-side counters never move in
        # this process — the fleet_* counters folded from polled wire
        # stats do, hence the fallbacks
        def first_moving(*names):
            return next((snap[n] for n in names if snap.get(n)), 0)

        adm = [[k, _fmt_num(snap[k])] for k in (
            "candidates_admitted_total", "fleet_device_admitted",
            "candidates_deduped_total", "fleet_device_deduped",
            "admission_bloom_occupancy", "admission_bloom_resets_total",
            "arena_occupancy", "arena_evictions_total",
            "arena_weighted_evictions_total") if k in snap]
        execs = first_moving("exec_total", "fleet_exec_total")
        adds = first_moving("new_inputs_total", "fleet_new_inputs")
        if execs:
            # "n/a" until the first input lands: execs/max(adds,1) would
            # fabricate a value indistinguishable from a real ratio
            adm.append(["execs_per_new_input",
                        _fmt_num(round(execs / adds, 2)) if adds
                        else "n/a (no inputs yet)"])
            adm.append(["yield_per_kexec",
                        _fmt_num(round(1000.0 * adds / execs, 4))])
        if adm:
            parts.append("<h2>admission &amp; yield</h2>"
                         + _table(["metric", "value"], adm))

        # prefix-memoized execution: memo health + the calls it saved.
        # fleet_* fallbacks carry the RPC deployment (remote engines
        # report prefix_hits/... in their wire stats)
        pfx = [[k, _fmt_num(snap[k])] for k in (
            "prefix_cache_hits_total", "fleet_prefix_hits",
            "prefix_cache_misses_total", "fleet_prefix_misses",
            "prefix_calls_saved_total", "fleet_prefix_calls_saved",
            "calls_executed_total") if k in snap]
        hits = first_moving("prefix_cache_hits_total",
                            "fleet_prefix_hits")
        misses = first_moving("prefix_cache_misses_total",
                              "fleet_prefix_misses")
        if hits or misses:
            pfx.append(["prefix_hit_rate",
                        _fmt_num(round(hits / (hits + misses), 3))])
        calls = first_moving("calls_executed_total")
        if calls and execs:
            pfx.append(["calls_executed_per_exec",
                        _fmt_num(round(calls / execs, 2))])
        if pfx:
            parts.append("<h2>prefix memoization</h2>"
                         + _table(["metric", "value"], pfx))

        # compiler frontend (ISSUE 16): the hlo differential executor's
        # compile economy (cache hit rate is the execs/sec lever) and
        # its findings by failure mode.  The counters only register when
        # an HloEnv exists, so syscall-only campaigns skip the section.
        fr = [[k, _fmt_num(snap[k])] for k in FRONTEND_METRICS
              if k in snap]
        fc = first_moving("frontend_compiles_total")
        fh = first_moving("frontend_compile_cache_hits_total")
        if fc or fh:
            fr.append(["compile_cache_hit_rate",
                       _fmt_num(round(fh / (fh + fc), 3))])
        if fr:
            parts.append("<h2>compiler frontend</h2>"
                         + _table(["metric", "value"], fr))

        # fused signal path (ISSUE 8): cover merges through the fused
        # merge+new entry vs silent host fallback off the pallas path,
        # and the batched-bisection triage round economy.  fleet_*
        # fallbacks carry the RPC deployment's remote engines
        sig_rows = [[k, _fmt_num(snap[k])] for k in (
            "cover_merge_fused_total", "pallas_cover_fallback_total",
            "minimize_bisect_rounds_total", "fleet_minimize_rounds",
            "minimize_batch_execs_total",
            "fleet_minimize_batch_execs") if k in snap]
        rounds = first_moving("minimize_bisect_rounds_total",
                              "fleet_minimize_rounds")
        bexecs = first_moving("minimize_batch_execs_total",
                              "fleet_minimize_batch_execs")
        if rounds:
            sig_rows.append(["probe_execs_per_round",
                             _fmt_num(round(bexecs / rounds, 2))])
        if sig_rows:
            parts.append("<h2>fused signal path</h2>"
                         + _table(["metric", "value"], sig_rows))

        # drain_rows_dropped_total: rows the supervised drain gave up
        # on — silent loss must be VISIBLE here and in /stats.json
        # (fleet_drain_rows_dropped is the remote engines' wire stat)
        sup = [[k, _fmt_num(snap[k])] for k in (
            "env_restarts_total", "env_quarantined",
            "env_watchdog_trips_total", "env_kill_escalations_total",
            "rpc_errors_total", "rpc_retries_total",
            "device_degraded_total", "drain_rows_dropped_total",
            "fleet_drain_rows_dropped",
            "checkpoint_age_seconds", "checkpoint_writes_total",
            "journal_records_total", "journal_bytes_total",
            "errors_total") if k in snap]
        if sup:
            parts.append("<h2>supervision</h2>"
                         + _table(["metric", "value"], sup))

        att = get_ledger().snapshot()
        cols = ["execs", "corpus_adds", "new_signal", "adds_per_kexec",
                "signal_per_kexec"]
        ops = att.get("operators", {})
        if ops:
            rows = [[name] + [_fmt_num(c[k]) for k in cols]
                    for name, c in sorted(
                        ops.items(),
                        key=lambda kv: -kv[1]["adds_per_kexec"])]
            parts.append("<h2>per-operator yield</h2>"
                         + _table(["operator"] + cols, rows))
        phases = att.get("phases", {})
        if phases:
            rows = [[name] + [_fmt_num(c[k]) for k in cols]
                    for name, c in sorted(phases.items())]
            parts.append("<h2>per-phase yield</h2>"
                         + _table(["phase"] + cols, rows))
        if not ops and not phases:
            parts.append("<p>no attribution data yet "
                         "(no triaged corpus additions)</p>")
        return "text/html", _page(
            f"{self.mgr.cfg.name} dashboard", "".join(parts))

    # ---- telemetry (ISSUE 1: registry + tracer exposition) ----

    def _metrics(self, q) -> tuple:
        """Prometheus text exposition of the process-wide registry (the
        manager's counters plus any in-process fuzzers' latencies)."""
        return ("text/plain; version=0.0.4",
                get_registry().prometheus_text().encode())

    def _trace(self, q) -> tuple:
        """Chrome trace-event JSON of the span buffer — load the response
        in chrome://tracing or Perfetto to read per-phase wall time."""
        return ("application/json",
                json.dumps(get_tracer().chrome_trace()).encode())
