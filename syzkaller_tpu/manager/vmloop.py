"""The manager's VM-fleet scheduler (vmLoop).

Role parity with reference /root/reference/syz-manager/manager.go:339-540:
every pool index runs an instance lifecycle — boot, forward the RPC port,
copy the fuzzer bits in, start the fuzzer, watch the console — restarting
on exit/crash forever.  Crashed titles that the manager wants reproduced
are queued; a repro worker reserves instances and drives the pkg-repro
pipeline, feeding results back to `Manager.save_repro`.

Against the `local` VM backend this runs the full distributed stack
hermetically on one host (subprocess "VMs" + real RPC + real executor) —
the hermetic multi-node test the reference never had (SURVEY.md §4).
"""

from __future__ import annotations

import os
import queue
import shlex
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import List, Optional

from ..utils import log
from ..vm import create as vm_create, monitor_execution


@dataclass
class VMLoopConfig:
    procs: int = 1
    mock_fuzzer: bool = False        # fuzzer uses the mock executor
    fuzzer_restart_s: float = 3600.0  # periodic fuzzer restart (ref: 1h)
    repro_instances: int = 2          # reserved per repro job (ref: 4)
    python: str = sys.executable
    extra_fuzzer_args: List[str] = field(default_factory=list)


@dataclass
class ReproJob:
    title: str
    log: bytes


class VMLoop:
    def __init__(self, mgr, cfg: Optional[VMLoopConfig] = None):
        self.mgr = mgr
        self.cfg = cfg or VMLoopConfig()
        self.pool = vm_create(mgr.cfg.vm)
        self.stop_ev = threading.Event()
        self.repro_queue: "queue.Queue[ReproJob]" = queue.Queue()
        self._repro_titles = set()
        self._lock = threading.Lock()
        # index reservation: fuzzing loops park their index while a repro
        # job borrows it (reference vmLoop's instances<->reproInstances
        # partitioning, manager.go:339-491)
        self._cond = threading.Condition(self._lock)
        self._reserved: set = set()
        self._running: set = set()
        self.threads: List[threading.Thread] = []
        self.crashes = 0
        self.instance_restarts = 0
        self.repros_done = 0
        self._package_zip = None

    # ---- lifecycle ----

    def start(self) -> None:
        n = self.pool.count
        # one index is borrowed by the repro worker when jobs arrive
        for idx in range(n):
            t = threading.Thread(target=self._instance_loop, args=(idx,),
                                 daemon=True, name=f"vmloop-{idx}")
            t.start()
            self.threads.append(t)
        rt = threading.Thread(target=self._repro_loop, daemon=True,
                              name="vmloop-repro")
        rt.start()
        self.threads.append(rt)

    def stop(self) -> None:
        self.stop_ev.set()

    def join(self, timeout: float = 10.0) -> None:
        deadline = time.time() + timeout
        for t in self.threads:
            t.join(max(0.1, deadline - time.time()))

    # ---- fuzzing instances (reference runInstance manager.go:493-540) ----

    def _fuzzer_cmd(self, manager_addr: str = "") -> str:
        args = [self.cfg.python, "-m", "syzkaller_tpu.engine",
                "-manager", manager_addr or self.mgr.rpc.addr,
                "-procs", str(self.cfg.procs),
                "-name", "{name}"]
        if self.cfg.mock_fuzzer:
            args += ["-mock"]
        args += self.cfg.extra_fuzzer_args
        return " ".join(shlex.quote(a) if "{" not in a else a
                        for a in args)

    def _instance_loop(self, idx: int) -> None:
        while not self.stop_ev.is_set():
            with self._cond:
                # park while a repro job has this index reserved
                while idx in self._reserved and not self.stop_ev.is_set():
                    self._cond.wait(timeout=1.0)
                if self.stop_ev.is_set():
                    return
                self._running.add(idx)
            try:
                self._run_instance(idx)
            except Exception as e:
                log.logf(0, "vmloop[%d]: instance failed: %s: %s",
                         idx, type(e).__name__, e)
                self.mgr._bump("vm_restarts")
            finally:
                with self._cond:
                    self._running.discard(idx)
                    self._cond.notify_all()
            self.instance_restarts += 1
            if self.stop_ev.wait(1.0):
                return

    def _reserve_for_repro(self, n: int) -> List[int]:
        """Take n pool indexes away from fuzzing (highest first)."""
        want = list(range(self.pool.count))[-n:]
        with self._cond:
            self._reserved.update(want)
            deadline = time.time() + 120.0
            while any(i in self._running for i in want) \
                    and time.time() < deadline \
                    and not self.stop_ev.is_set():
                self._cond.wait(timeout=1.0)
        return want

    def _release_repro(self, idxs: List[int]) -> None:
        with self._cond:
            self._reserved.difference_update(idxs)
            self._cond.notify_all()

    def _package_archive(self) -> str:
        """One-time zip of the syzkaller_tpu package + prebuilt executor
        for guests without a shared filesystem (Python imports straight
        from the zip on PYTHONPATH)."""
        if self._package_zip is None:
            import tempfile
            import zipfile

            from ..ipc.build import build_executor

            repo = os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__))))
            fd, path = tempfile.mkstemp(suffix=".zip",
                                        prefix="syzkaller-tpu-")
            os.close(fd)
            with zipfile.ZipFile(path, "w") as z:
                pkg = os.path.join(repo, "syzkaller_tpu")
                for root, dirs, files in os.walk(pkg):
                    dirs[:] = [d for d in dirs
                               if d not in ("build", "__pycache__")]
                    for f in files:
                        if f.endswith((".pyc",)):
                            continue
                        full = os.path.join(root, f)
                        z.write(full, os.path.relpath(full, repo))
            self._package_zip = path
            self._executor_bin = str(build_executor())
        return self._package_zip

    def _run_instance(self, idx: int) -> None:
        inst = self.pool.create(idx)
        try:
            name = f"vm-{idx}"
            rpc_port = int(self.mgr.rpc.addr.rsplit(":", 1)[1])
            # the backend decides how the guest reaches the manager
            # (loopback / ssh -R / routed IP)
            addr = inst.forward(rpc_port)
            cmd = self._fuzzer_cmd(addr).replace("{name}", name)
            repo = os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__))))
            if self.mgr.cfg.vm.type == "local":
                # shared filesystem: run straight from the repo
                cmd = f"PYTHONPATH={shlex.quote(repo)} {cmd}"
            else:
                guest_zip = inst.copy(self._package_archive())
                guest_exec = inst.copy(self._executor_bin)
                cmd = (f"PYTHONPATH={shlex.quote(guest_zip)} "
                       f"SYZ_TPU_EXECUTOR={shlex.quote(guest_exec)} {cmd}")
            merger, proc = inst.run(cmd,
                                    timeout=self.cfg.fuzzer_restart_s)
            res = monitor_execution(
                merger, proc, timeout=self.cfg.fuzzer_restart_s,
                no_output_timeout=180.0,
                ignores=self.mgr.cfg.ignores,
                stop=self.stop_ev)
            if res.report is not None:
                self.crashes += 1
                self.mgr.save_crash(res.report, res.output, idx)
                self._maybe_queue_repro(res.report.title, res.output)
            elif res.lost_connection:
                from ..report import Report

                self.mgr.save_crash(
                    Report(title="lost connection to test machine"),
                    res.output, idx)
            elif res.no_output:
                from ..report import Report

                self.mgr.save_crash(Report(title="no output from test "
                                           "machine"), res.output, idx)
        finally:
            inst.close()

    # ---- repro scheduling (reference vmLoop repro branch 416-419) ----

    def _maybe_queue_repro(self, title: str, output: bytes) -> None:
        with self._lock:
            if title in self._repro_titles:
                return
            if not self.mgr.need_repro(title):
                return
            self._repro_titles.add(title)
        self.repro_queue.put(ReproJob(title=title, log=output))
        self.mgr._bump("repro_queued")

    def _repro_loop(self) -> None:
        from .. import repro as repro_mod
        from ..prog.encoding import serialize

        while not self.stop_ev.is_set():
            try:
                job = self.repro_queue.get(timeout=1.0)
            except queue.Empty:
                continue
            idxs = self._reserve_for_repro(
                min(self.cfg.repro_instances, self.pool.count))
            try:
                tester = repro_mod.VMTester(
                    self.pool, instance_indexes=idxs,
                    ignores=self.mgr.cfg.ignores)
                res = repro_mod.run(job.log.decode("utf-8", "replace"),
                                    self.mgr.target, tester)
                if res is not None and res.prog is not None:
                    self.mgr.save_repro(job.title, serialize(res.prog),
                                        res.c_src or "")
                    self.repros_done += 1
                else:
                    self.mgr._bump("repro_failed")
                    if self.mgr._dash is not None:
                        try:
                            self.mgr._dash.report_failed_repro(
                                self.mgr.cfg.name, job.title)
                        except Exception:
                            pass
            except Exception as e:
                log.logf(0, "vmloop: repro of %r failed: %s", job.title, e)
                self.mgr._bump("repro_failed")
            finally:
                self._release_repro(idxs)
                with self._lock:
                    self._repro_titles.discard(job.title)
