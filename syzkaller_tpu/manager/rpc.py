"""Manager<->fuzzer RPC: length-prefixed JSON frames over TCP.

Role parity with reference /root/reference/pkg/rpctype (rpctype.go:8-102
wire types; rpc.go:20-90 gob net/rpc wrappers with keep-alive). JSON
replaces gob — the fuzzer side is Python, and the payloads (program text,
signal lists, stat counters) are JSON-shaped already.

Frame: u32 LE length + utf-8 JSON. Request {"method", "args"}; response
{"result"} or {"error"}.
"""

from __future__ import annotations

import json
import random
import socket
import socketserver
import struct
import threading
import time
from typing import Any, Dict, Optional

from ..telemetry import count_error, get_registry, journal_emit
from ..testing import faults as _faults
from ..testing.faults import InjectedFault

_LEN = struct.Struct("<I")
MAX_FRAME = 256 << 20


class RpcError(RuntimeError):
    pass


class RpcConnError(RpcError):
    """Transport-level failure (connection closed/reset) — retryable,
    unlike an application error the server replied with."""


def _send(sock: socket.socket, obj: Any) -> None:
    blob = json.dumps(obj).encode()
    sock.sendall(_LEN.pack(len(blob)) + blob)


def _recv(sock: socket.socket) -> Optional[Any]:
    hdr = _recv_exact(sock, _LEN.size)
    if hdr is None:
        return None
    (n,) = _LEN.unpack(hdr)
    if n > MAX_FRAME:
        raise RpcError(f"frame too large: {n}")
    blob = _recv_exact(sock, n)
    if blob is None:
        return None
    return json.loads(blob)


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


class RpcServer:
    """Exposes public methods of `handler` (names not starting with _).
    One thread per connection; connections are long-lived (the fuzzer
    keeps one open for its lifetime, like the reference's keep-alive)."""

    def __init__(self, handler, host: str = "127.0.0.1", port: int = 0):
        self.handler = handler
        outer = self

        class _Conn(socketserver.BaseRequestHandler):
            def handle(self) -> None:
                sock = self.request
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                while True:
                    try:
                        req = _recv(sock)
                    except (OSError, RpcError, json.JSONDecodeError):
                        return
                    if req is None:
                        return
                    method = req.get("method", "")
                    args = req.get("args") or {}
                    try:
                        if method.startswith("_"):
                            raise RpcError(f"bad method {method!r}")
                        fn = getattr(outer.handler, method, None)
                        if fn is None:
                            raise RpcError(f"unknown method {method!r}")
                        resp = {"result": fn(**args)}
                    except Exception as e:  # error -> reply, keep serving
                        resp = {"error": f"{type(e).__name__}: {e}"}
                    try:
                        _send(sock, resp)
                    except OSError:
                        return

        class _Server(socketserver.ThreadingTCPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._server = _Server((host, port), _Conn)
        self.addr = "%s:%d" % self._server.server_address
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True)

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()


class RpcClient:
    def __init__(self, addr: str, timeout: float = 60.0):
        host, port = addr.rsplit(":", 1)
        self._sock = socket.create_connection((host, int(port)),
                                              timeout=timeout)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._lock = threading.Lock()

    def call(self, method: str, **args) -> Any:
        with self._lock:
            _send(self._sock, {"method": method, "args": args})
            resp = _recv(self._sock)
        if resp is None:
            raise RpcConnError("connection closed")
        if "error" in resp:
            raise RpcError(resp["error"])
        return resp.get("result")

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


class RemoteManager:
    """engine.ManagerConn implementation over RpcClient — what a fuzzer
    process uses to talk to a manager on another machine/VM.

    Every call retries transport failures (connection closed/reset and
    injected faults) with jittered exponential backoff and a
    restart-aware reconnect: a fresh socket is dialed and — because a
    restarted manager has lost this fuzzer's registration — ``connect``
    is replayed before the failed method is retried.  Failures are
    counted (``rpc_errors_total`` / ``rpc_retries_total`` /
    ``rpc_reconnects_total``) and logged, never swallowed."""

    RETRYABLE = (OSError, RpcConnError, InjectedFault)

    def __init__(self, addr: str, name: str = "fuzzer",
                 max_retries: int = 5, base_backoff: float = 0.1,
                 max_backoff: float = 5.0, seed: int = 0):
        self.addr = addr
        self.name = name
        self.max_retries = max_retries
        self.base_backoff = base_backoff
        self.max_backoff = max_backoff
        self._rng = random.Random(seed)
        reg = get_registry()
        self._c_errors = reg.counter(
            "rpc_errors_total",
            help="manager RPC calls that failed (counted per attempt, "
                 "before any retry succeeds)")
        self._c_retries = reg.counter(
            "rpc_retries_total",
            help="manager RPC attempts retried after a transport failure")
        self._c_reconnects = reg.counter(
            "rpc_reconnects_total",
            help="manager RPC sockets re-dialed (restart-aware: connect "
                 "is replayed before the failed call)")
        self.client = RpcClient(addr)

    def _call(self, method: str, **args):
        delay = self.base_backoff
        for attempt in range(self.max_retries + 1):
            try:
                # distinct from the engine-level "rpc.poll" site: a plan
                # targeting the transport exercises THIS retry loop
                _faults.fire(f"rpc.transport.{method}")
                return self.client.call(method, **args)
            except self.RETRYABLE as e:
                self._c_errors.inc()
                # transport-scoped site: the engine-level "rpc_poll" /
                # "rpc_new_input" sites count logical failures exactly
                # once; these count per attempt
                count_error("rpc_transport_" + method, e)
                if attempt == self.max_retries:
                    raise
                self._c_retries.inc()
                time.sleep(delay * (0.5 + self._rng.random()))
                delay = min(delay * 2.0, self.max_backoff)
                self._reconnect(method)

    def _reconnect(self, method: str) -> None:
        """Dial a fresh socket; on success re-register with ``connect``
        (a restarted manager forgot us).  A failed redial keeps the old
        client — the next attempt fails fast and backs off again."""
        old = self.client
        try:
            client = RpcClient(self.addr)
        except OSError as e:
            count_error("rpc_reconnect", e)
            return
        try:
            old.close()
        except OSError:
            pass
        self.client = client
        self._c_reconnects.inc()
        # the campaign journal (when an engine in this process owns one)
        # records the reconnect: RPC topology changes are exactly the
        # cross-restart forensics the fleet story needs
        journal_emit("rpc_reconnect", method=method, addr=self.addr)
        if method != "connect":
            try:
                self.client.call("connect", name=self.name)
            except (OSError, RpcError) as e:
                count_error("rpc_reconnect", e)

    def connect(self):
        return self._call("connect", name=self.name)

    def new_input(self, prog_text: str, call_index: int, signal, cover):
        return self._call("new_input", name=self.name,
                          prog_text=prog_text, call_index=call_index,
                          signal=list(signal), cover=list(cover))

    def poll(self, stats, need_candidates: bool, new_signal=(),
             ledger=None):
        # the ledger kwarg is omitted when absent so poll handlers that
        # predate it keep accepting DIRECT RemoteManager.poll() callers
        # (test stubs, tooling); the engine itself always ships a
        # ledger, so a same-repo manager is required on that path
        kw = {"ledger": ledger} if ledger is not None else {}
        return self._call("poll", name=self.name, stats=stats,
                          need_candidates=need_candidates,
                          new_signal=list(new_signal), **kw)

    def close(self) -> None:
        self.client.close()
