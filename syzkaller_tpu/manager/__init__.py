"""Manager: the host orchestrator — corpus lifecycle, VM fleet, RPC hub,
crash persistence, stats/bench series.

Role parity with reference /root/reference/syz-manager/manager.go:
corpus.db load -> dup+shuffled candidates (178-229), phase ladder
init -> triaged_corpus -> ... (88-99), RPC methods Connect/NewInput/Poll
(799-971), vmLoop instance scheduler (339-491), crash persistence with
bounded per-bug logs (570-640), minimizeCorpus greedy cover (769-797),
-bench JSON series appender (267-301).
"""

from __future__ import annotations

import json
import os
import random
import threading
import time
import weakref
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set

from ..db import DB
from ..prog.encoding import deserialize, serialize
from ..prog.prio import calculate_priorities
from ..telemetry import get_registry, journal_emit, timed
from ..telemetry import journal as _journal
from ..utils.hash import hash_str
from ..vm import VMConfig
from .rpc import RpcServer

PHASE_INIT = 0
PHASE_LOADED_CORPUS = 1
PHASE_TRIAGED_CORPUS = 2
PHASE_QUERIED_HUB = 3
PHASE_TRIAGED_HUB = 4

MAX_CRASH_LOGS = 100  # per crash title (reference manager.go:608-638)


@dataclass
class ManagerConfig:
    name: str = "syzkaller-tpu"
    target_os: str = "linux"
    target_arch: str = "amd64"
    workdir: str = "workdir"
    http: str = "127.0.0.1:0"
    rpc: str = "127.0.0.1:0"
    procs: int = 1
    program_length: int = 16
    mock_executor: bool = False
    use_device: bool = False
    bench_file: str = ""
    hub_addr: str = ""
    hub_key: str = ""
    kernel_obj: str = ""  # vmlinux path for the /cover symbolized report
    # campaign analytics (ISSUE 2): registry sampling cadence/series bound
    # for /stats.json and the /dashboard sparklines; interval <= 0 keeps
    # the sampler constructed (tests drive ticks by hand) but unstarted
    analytics_interval: float = 5.0
    analytics_capacity: int = 240
    dashboard_addr: str = ""
    dashboard_client: str = ""
    dashboard_key: str = ""
    ignores: List[str] = field(default_factory=list)
    suppressions: List[str] = field(default_factory=list)
    vm: VMConfig = field(default_factory=VMConfig)


@dataclass
class CrashEntry:
    title: str
    count: int = 0
    first_seen: float = 0.0
    last_seen: float = 0.0


class Manager:
    def __init__(self, cfg: ManagerConfig, target=None):
        from ..prog import get_target

        self.cfg = cfg
        self.target = target or get_target(cfg.target_os, cfg.target_arch)
        os.makedirs(cfg.workdir, exist_ok=True)
        self.crashdir = os.path.join(cfg.workdir, "crashes")
        os.makedirs(self.crashdir, exist_ok=True)

        self._lock = threading.Lock()
        # separate lock for corpus.db: DB has no internal locking and RPC
        # handlers run on per-connection threads; also keeps file IO out
        # of the main-lock critical sections
        self._db_lock = threading.Lock()
        self.phase = PHASE_INIT
        self.start_time = time.time()
        # the manager's counters dual-write: _stats_local keeps the
        # historic per-manager RPC/snapshot dict shape (several managers
        # can share one process, e.g. the hub federation tests), and the
        # process-wide telemetry registry carries the same bumps for
        # /metrics exposition
        self.metrics = get_registry()
        self._stats_local: Dict[str, int] = {}
        self._counters: Dict[str, object] = {}  # bind-once, see _counter
        self._h_hub_sync = self.metrics.histogram(
            "hub_sync_seconds", help="wall time of one hub delta exchange")
        self.metrics.counter("exec_total", help="programs executed")
        self.metrics.histogram(
            "device_batch_latency_seconds",
            help="wall time to execute one device candidate batch")
        # gauges are weakref-bound and detached in close(): the registry
        # outlives manager instances (several share one process in the
        # hub federation tests) and must not pin a dead one's corpus
        ref = weakref.ref(self)

        def _live(attr):
            return lambda: (len(getattr(s, attr))
                            if (s := ref()) is not None else 0)

        self._gauge_fns = [
            (self.metrics.gauge("corpus_size",
                                help="programs in the manager corpus"),
             _live("corpus")),
            (self.metrics.gauge("max_signal_size",
                                help="accumulated max-signal PCs"),
             _live("max_signal")),
            (self.metrics.gauge("connected_fuzzers",
                                help="fuzzers connected over RPC"),
             _live("connected_fuzzers")),
            (self.metrics.gauge("pending_candidates",
                                help="candidates waiting for triage"),
             _live("candidates")),
        ]
        for g, fn in self._gauge_fns:
            g.set_fn(fn)
        # absolute per-fuzzer counter snapshots (summed for reporting);
        # a single shared dict would flip-flop between fuzzers' values
        self._fuzzer_stats: Dict[str, Dict[str, int]] = {}
        # cross-restart / cross-engine attribution (ISSUE 7): engines
        # stamp a persistent engine_id into their wire stats and ship
        # their attribution-ledger state on every poll; the manager
        # keeps the LATEST absolute state per engine (replace, never
        # accumulate — the state is already cumulative) so the merged
        # fleet ledger stays exact across engine restarts.  The
        # manager's own id is minted per workdir like an engine's.
        self.engine_id = _journal.mint_engine_id(cfg.workdir)
        self._engine_ids: Dict[str, str] = {}
        self._engine_ledgers: Dict[str, Dict] = {}
        # proc token per stored ledger: a PROCESS has one global ledger,
        # so two fuzzers sharing a process ship identical state — only
        # one copy may survive or the merge double-counts every cell
        self._engine_ledger_procs: Dict[str, str] = {}
        self.connected_fuzzers: Set[str] = set()
        self.crashes: Dict[str, CrashEntry] = {}
        self.max_signal: Set[int] = set()
        self.max_cover: Set[int] = set()  # union of per-call cover PCs
        # corpus: hash -> (prog text, signal)
        self.corpus: Dict[str, str] = {}
        self.corpus_signal: Dict[str, List[int]] = {}
        # per-fuzzer pending-input queues (NewInput fan-out, manager.go:897)
        self._pending: Dict[str, List[str]] = {}
        # append-only log of newly seen signal + per-fuzzer cursors, so
        # Poll returns each fuzzer exactly the max-signal delta it misses
        self._signal_log: List[int] = []
        self._signal_cursor: Dict[str, int] = {}
        self.candidates: List[str] = []

        self.db = DB.open(os.path.join(cfg.workdir, "corpus.db"))
        self._load_corpus()

        # campaign time-series: the registry snapshot plus this manager's
        # own trajectory values, sampled into bounded downsampling series
        # served on /stats.json and drawn by /dashboard.  The extra()
        # callback is weakref-bound like the gauges: the sampler thread
        # must not pin a closed manager alive.
        from ..telemetry import RegistrySampler

        def _extra():
            m = ref()
            if m is None:
                return {}
            snap = m.snapshot()
            return {
                "manager_corpus": snap["corpus"],
                "manager_signal": snap["signal"],
                "manager_crashes": snap["crashes"],
                "manager_candidates": snap["candidates"],
                "manager_fuzzers": snap["fuzzers"],
            }

        self.sampler = RegistrySampler(
            interval=cfg.analytics_interval,
            capacity=cfg.analytics_capacity, extra=_extra)

        self.rpc = RpcServer(_RpcHandler(self), *self._split(cfg.rpc))
        self.rpc.start()
        self.http = None
        if cfg.http:
            from .html import ManagerHttp

            self.http = ManagerHttp(self, *self._split(cfg.http))
            self.http.start()
        # started only once the servers are up: a failed __init__ (bound
        # port, bad workdir) must not leak a forever-ticking daemon thread
        if cfg.analytics_interval > 0:
            self.sampler.start()
        self._bench_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        if cfg.bench_file:
            self._bench_thread = threading.Thread(
                target=self._bench_loop, daemon=True)
            self._bench_thread.start()

        # dashboard reporting (reference saveCrash manager.go:570-607)
        self._dash = None
        if cfg.dashboard_addr:
            from ..dashboard import DashApi

            self._dash = DashApi(cfg.dashboard_addr, cfg.dashboard_client,
                                 cfg.dashboard_key)

        # hub federation (reference manager.go:303-310, 994-...)
        self._hub = None
        self._hub_connected = False
        self._hub_ever_connected = False
        self._hub_synced: Set[str] = set()  # sigs already mirrored to hub
        self._hub_thread: Optional[threading.Thread] = None
        if cfg.hub_addr:
            self._hub_thread = threading.Thread(
                target=self._hub_loop, daemon=True)
            self._hub_thread.start()

    @staticmethod
    def _split(addr: str):
        host, port = addr.rsplit(":", 1)
        return host, int(port)

    # ---- corpus lifecycle ----

    def _load_corpus(self) -> None:
        """Replay corpus.db as candidates, duplicated + shuffled for
        flake-tolerance (reference manager.go:218-229)."""
        cands: List[str] = []
        for key, val in list(self.db.items()):
            text = val.decode("utf-8", "replace")
            try:
                deserialize(self.target, text)
            except Exception:
                self.db.delete(key)  # disabled/unparseable: drop from db
                continue
            cands.append(text)
        cands = cands * 2
        random.Random(0).shuffle(cands)
        self.candidates = cands
        self.phase = PHASE_LOADED_CORPUS

    def _add_corpus(self, text: str, signal: Sequence[int]) -> bool:
        h = hash_str(text.encode())
        with self._lock:
            if h in self.corpus:
                # merge signal for minimization bookkeeping
                s = set(self.corpus_signal.get(h, ()))
                s.update(signal)
                self.corpus_signal[h] = sorted(s)
                return False
            self.corpus[h] = text
            self.corpus_signal[h] = sorted(signal)
            self._note_signal(signal)
        with self._db_lock:
            self.db.save(h.encode(), text.encode())
            self.db.flush()
        return True

    def _note_signal(self, signal: Sequence[int]) -> None:
        fresh = [s for s in signal if s not in self.max_signal]
        self.max_signal.update(fresh)
        self._signal_log.extend(fresh)

    def minimize_corpus(self) -> int:
        """Greedy set cover over corpus signal; drop programs adding no
        unique signal (reference manager.go:769-797 + pkg/cover Minimize).
        Returns number dropped."""
        with self._lock:
            items = sorted(self.corpus_signal.items(),
                           key=lambda kv: -len(kv[1]))
            covered: Set[int] = set()
            keep: Set[str] = set()
            for h, sig in items:
                if not sig or set(sig) - covered:
                    keep.add(h)
                    covered.update(sig)
            drop = [h for h in self.corpus if h not in keep]
            for h in drop:
                del self.corpus[h]
                del self.corpus_signal[h]
        with self._db_lock:
            for h in drop:
                self.db.delete(h.encode())
            if drop:
                self.db.flush()
        return len(drop)

    # ---- RPC methods (called by _RpcHandler) ----

    def on_connect(self, name: str):
        with self._lock:
            self.connected_fuzzers.add(name)
            self._pending.setdefault(name, [])
            self._signal_cursor[name] = len(self._signal_log)
            corpus = list(self.corpus.values())
            nc = len(self.candidates)
            take = self.candidates[:500]
            self.candidates = self.candidates[500:]
            if not self.candidates and nc and \
                    self.phase == PHASE_LOADED_CORPUS:
                self.phase = PHASE_TRIAGED_CORPUS
            max_signal = sorted(self.max_signal)
        prios = calculate_priorities(
            self.target, [deserialize(self.target, t) for t in
                          list(corpus)[:256]])
        return {
            "corpus": corpus,
            "prios": prios.tolist(),
            "max_signal": max_signal,
            "candidates": take,
            "enabled": None,
        }

    def on_new_input(self, name: str, prog_text: str, call_index: int,
                     signal: Sequence[int], cover: Sequence[int]):
        self._bump("manager_new_inputs")
        if cover:
            with self._lock:
                self.max_cover.update(cover)
        if self._add_corpus(prog_text, signal):
            with self._lock:
                # fan the input out to every other connected fuzzer
                for other, q in self._pending.items():
                    if other != name:
                        q.append(prog_text)
        return {}

    def on_poll(self, name: str, stats: Dict[str, int],
                need_candidates: bool, new_signal: Sequence[int],
                ledger=None):
        fleet_deltas: Dict[str, int] = {}
        with self._lock:
            if stats:
                stats = dict(stats)
                # the engine's persistent identity rides the wire stats
                # as a string — pop it before the numeric fold
                eid = stats.pop("engine_id", None)
                if eid:
                    self._engine_ids[name] = str(eid)
                snap = {k: int(v) for k, v in stats.items()}
                prev = self._fuzzer_stats.get(name, {})
                # fleet_-prefixed registry counters carry remote fuzzers'
                # absolute snapshots as deltas, so /metrics covers the
                # RPC topology too; the bare names stay reserved for
                # in-process fuzzers (which write the registry directly —
                # a shared name would double-count them).  v < prev means
                # the fuzzer restarted and its counters reset: the whole
                # post-restart value is the delta
                fleet_deltas = {}
                for k, v in snap.items():
                    dv = v - prev.get(k, 0) if v >= prev.get(k, 0) else v
                    if dv > 0:
                        fleet_deltas[k] = dv
                self._fuzzer_stats[name] = snap
            if isinstance(ledger, dict) and ledger.get("state") and \
                    ledger.get("proc") != _journal.PROC_TOKEN:
                # latest-wins absolute ledger state per REMOTE engine
                # PROCESS; an in-process fuzzer's credit already lives
                # in the shared process-global ledger (same proc
                # token), and two remote fuzzers sharing one process
                # ship the same process-global state under different
                # names — either duplicate would double-count in the
                # merged view, so one copy per proc token survives
                proc = str(ledger.get("proc") or "")
                if proc:
                    for other, op in list(
                            self._engine_ledger_procs.items()):
                        if op == proc and other != name:
                            self._engine_ledgers.pop(other, None)
                            self._engine_ledger_procs.pop(other, None)
                    self._engine_ledger_procs[name] = proc
                self._engine_ledgers[name] = ledger["state"]
                if ledger.get("engine_id"):
                    self._engine_ids[name] = str(ledger["engine_id"])
            self._note_signal(new_signal)
            cur = self._signal_cursor.get(name, 0)
            delta = self._signal_log[cur:]
            self._signal_cursor[name] = len(self._signal_log)
            inputs = self._pending.get(name, [])
            self._pending[name] = []
            cands = []
            if need_candidates or self.candidates:
                had = bool(self.candidates)
                cands = self.candidates[:100]
                self.candidates = self.candidates[100:]
                if had and not self.candidates and \
                        self.phase == PHASE_LOADED_CORPUS:
                    self.phase = PHASE_TRIAGED_CORPUS
        for k, dv in fleet_deltas.items():
            self._counter("fleet_" + k).inc(dv)
        return {
            "new_inputs": inputs,
            "candidates": cands,
            "max_signal": delta,
        }

    def _counter(self, name: str):
        """Bind-once counter cache: _bump and the fleet-delta path must
        pay one locked add per call, not a registry get-or-create."""
        c = self._counters.get(name)
        if c is None:
            c = self._counters[name] = self.metrics.counter(name)
        return c

    def _bump(self, stat: str, n: int = 1) -> None:
        with self._lock:
            self._stats_local[stat] = self._stats_local.get(stat, 0) + n
        self._counter(stat).inc(n)

    @property
    def stats(self) -> Dict[str, int]:
        """This manager's own counters in the historic dict shape
        (RPC/snapshot/tests consume this); the registry carries the
        process-wide totals for /metrics."""
        with self._lock:
            return dict(self._stats_local)

    # ---- cross-engine attribution (ISSUE 7) ----

    def engines_info(self) -> Dict[str, Dict[str, object]]:
        """Connected fuzzers with their persistent engine ids (None for
        engines that predate the id stamp) — the /stats.json `engines`
        map fleet tooling attributes by."""
        with self._lock:
            return {name: {"engine_id": self._engine_ids.get(name)}
                    for name in sorted(self.connected_fuzzers
                                       | set(self._engine_ids))}

    def attribution_state(self) -> Dict[str, object]:
        """The exact (raw-count) attribution picture this manager can
        vouch for, structured so a fleet aggregator can merge WITHOUT
        double-counting: the process-global ledger once per process
        (keyed by proc token — several managers can share one process)
        plus the latest absolute state each remote engine shipped
        (keyed by name, engine_id alongside for cross-manager dedup)."""
        from ..telemetry import get_ledger

        with self._lock:
            engines = {name: {"engine_id": self._engine_ids.get(name),
                              "proc": self._engine_ledger_procs.get(name),
                              "state": st}
                       for name, st in self._engine_ledgers.items()}
        return {"proc": _journal.PROC_TOKEN,
                "local": get_ledger().state(),
                "engines": engines}

    def merged_attribution_state(self) -> Dict[str, Dict]:
        """One exact merged ledger state over this manager's view: the
        process-local ledger + every remote engine's latest state
        (merged phase totals == local totals + sum of engines' totals;
        pinned by the fleet tests)."""
        from ..telemetry import AttributionLedger, get_ledger

        merged = AttributionLedger()
        merged.merge_state(get_ledger().state())
        with self._lock:
            states = list(self._engine_ledgers.values())
        for st in states:
            merged.merge_state(st)
        return merged.state()

    # ---- crash persistence (reference saveCrash manager.go:570-640) ----

    def save_crash(self, report, output: bytes, vm_index: int = -1) -> str:
        title = report.title if report else "lost connection"
        if self._dash is not None:
            try:
                self._dash.report_crash({
                    "namespace": self.cfg.name,
                    "manager": self.cfg.name,
                    "title": title,
                    "log": output.decode("utf-8", "replace"),
                    "report": report.report if report else "",
                    "maintainers": list(getattr(report, "maintainers", [])),
                })
            except Exception as e:
                from ..utils import log

                log.logf(0, "dashboard report failed: %s", e)
                self._bump("dashboard_errors")
        h = hash_str(title.encode())[:16]
        d = os.path.join(self.crashdir, h)
        os.makedirs(d, exist_ok=True)
        desc = os.path.join(d, "description")
        if not os.path.exists(desc):
            with open(desc, "w") as f:
                f.write(title + "\n")
        with self._lock:
            e = self.crashes.setdefault(title, CrashEntry(
                title=title, first_seen=time.time()))
            e.count += 1
            e.last_seen = time.time()
            seq = e.count % MAX_CRASH_LOGS  # ring: bound disk usage
        with open(os.path.join(d, f"log{seq}"), "wb") as f:
            f.write(output)
        if report and report.report:
            with open(os.path.join(d, f"report{seq}"), "w") as f:
                f.write(report.report)
        self._bump("crashes")
        # campaign-journal crash forensics (no-op without an installed
        # journal): which crash, when, attributed to which VM slot
        journal_emit("crash", title=title, vm=vm_index)
        return d

    def save_repro(self, title: str, prog_text: str,
                   c_src: str = "") -> str:
        """Persist a reproducer next to its crash logs (reference
        saveRepro manager.go:682-754: repro.prog / repro.cprog); also
        reported to the dashboard when configured.  need_repro keys off
        the repro.prog file this writes."""
        h = hash_str(title.encode())[:16]
        d = os.path.join(self.crashdir, h)
        os.makedirs(d, exist_ok=True)
        desc = os.path.join(d, "description")
        if not os.path.exists(desc):
            with open(desc, "w") as f:
                f.write(title + "\n")
        with open(os.path.join(d, "repro.prog"), "w") as f:
            f.write(prog_text)
        if c_src:
            with open(os.path.join(d, "repro.cprog"), "w") as f:
                f.write(c_src)
        if self._dash is not None:
            try:
                self._dash.report_crash({
                    "namespace": self.cfg.name,
                    "manager": self.cfg.name,
                    "title": title,
                    "repro_syz": prog_text,
                    "repro_c": c_src,
                })
            except Exception as e:
                from ..utils import log

                log.logf(0, "dashboard repro report failed: %s", e)
                self._bump("dashboard_errors")
        self._bump("repros")
        return d

    def need_repro(self, title: str) -> bool:
        """Whether a crash deserves a repro attempt: ask the dashboard
        when configured, else local heuristic — no repro on disk yet
        (reference needRepro manager.go:641-...)."""
        if self._dash is not None:
            try:
                return self._dash.need_repro(self.cfg.name, title)
            except Exception:
                return False
        h = hash_str(title.encode())[:16]
        return not os.path.exists(
            os.path.join(self.crashdir, h, "repro.prog"))

    # ---- stats / bench ----

    def snapshot(self) -> Dict[str, object]:
        stats = self.stats  # registry-backed; takes its own locks
        with self._lock:
            fleet: Dict[str, int] = {}
            for per in self._fuzzer_stats.values():
                for k, v in per.items():
                    fleet[k] = fleet.get(k, 0) + v
            return {
                "uptime_s": round(time.time() - self.start_time, 1),
                "phase": self.phase,
                "corpus": len(self.corpus),
                "signal": len(self.max_signal),
                "candidates": len(self.candidates),
                "fuzzers": len(self.connected_fuzzers),
                "crashes": sum(e.count for e in self.crashes.values()),
                "crash_types": len(self.crashes),
                **fleet,
                **stats,
            }

    # ---- hub sync (reference manager.go:994-...; syz-hub/hub.go) ----

    def hub_sync_once(self) -> int:
        """One corpus-delta exchange with the hub (draining `more` pages in
        the same call, like the reference's while-More loop); received
        programs are injected as candidates.  Returns number of programs
        received.  Runs from the hub thread; callable directly in tests."""
        with timed("manager.hub_sync", self._h_hub_sync):
            return self._hub_sync_once()

    def _hub_sync_once(self) -> int:
        from ..hub import HubClient

        if self._hub is None:
            self._hub = HubClient(self.cfg.hub_addr, self.cfg.name,
                                  self.cfg.hub_key)
        if not self._hub_connected:
            with self._lock:
                corpus = list(self.corpus.values())
                sigs = set(self.corpus)
            # fresh only on the first connect of this manager's lifetime:
            # reconnects after transient errors keep the hub-side cursor,
            # so the delta stream resumes instead of restarting
            self._hub.connect(
                fresh=not self._hub_ever_connected,
                calls=[s.name for s in self.target.syscalls],
                corpus=corpus)
            self._hub_connected = True
            self._hub_ever_connected = True
            self._hub_synced = sigs
            self._bump("hub_send", len(corpus))
            if self.phase == PHASE_TRIAGED_CORPUS:
                self.phase = PHASE_QUERIED_HUB
        with self._lock:
            cur = dict(self.corpus)
        add = [cur[h] for h in cur.keys() - self._hub_synced]
        del_ = sorted(self._hub_synced - cur.keys())
        accepted = 0
        more = 1
        while more:
            progs, more, _repros = self._hub.sync(add=add, del_=del_)
            self._bump("hub_send", len(add))
            self._hub_synced = set(cur)
            add, del_ = [], []  # later pages only drain pending deltas
            for text in progs:
                try:
                    deserialize(self.target, text)
                except Exception:
                    continue
                with self._lock:
                    self.candidates.append(text)
                accepted += 1
        self._bump("hub_recv", accepted)
        if accepted and self.phase == PHASE_QUERIED_HUB:
            self.phase = PHASE_TRIAGED_HUB
        return accepted

    def _hub_loop(self) -> None:
        """Every minute once the initial corpus is triaged (reference
        hubSync cadence, manager.go:303-310)."""
        from ..utils import log

        while not self._stop.wait(60.0):
            if self.phase < PHASE_TRIAGED_CORPUS:
                continue
            try:
                self.hub_sync_once()
            except Exception as e:
                # hub unreachable: drop the connection, retry next tick
                log.logf(0, "hub sync failed: %s: %s", type(e).__name__, e)
                self._bump("hub_errors")
                if self._hub is not None:
                    try:
                        self._hub.close()
                    except Exception:
                        pass
                self._hub = None
                self._hub_connected = False

    def _bench_loop(self) -> None:
        """Minute-resolution JSON lines (reference -bench manager.go:
        267-301; rendered by tools/benchcmp.py)."""
        while not self._stop.wait(60.0):
            line = json.dumps({"ts": int(time.time()), **self.snapshot()})
            with open(self.cfg.bench_file, "a") as f:
                f.write(line + "\n")

    def close(self) -> None:
        self._stop.set()
        if getattr(self, "sampler", None) is not None:
            self.sampler.stop()
        for g, fn in getattr(self, "_gauge_fns", ()):
            g.clear_fn(fn)
        self.rpc.stop()
        if self.http is not None:
            self.http.stop()
        if self._hub is not None:
            try:
                self._hub.close()
            except Exception:
                pass
        self.db.close()


class _RpcHandler:
    """Methods exposed over RPC (whitelist via explicit delegation)."""

    def __init__(self, mgr: Manager):
        self._mgr = mgr

    def connect(self, name: str):
        return self._mgr.on_connect(name)

    def new_input(self, name: str, prog_text: str, call_index: int,
                  signal, cover):
        return self._mgr.on_new_input(name, prog_text, call_index,
                                      signal, cover)

    def poll(self, name: str, stats, need_candidates: bool,
             new_signal=(), ledger=None):
        return self._mgr.on_poll(name, stats, need_candidates, new_signal,
                                 ledger=ledger)
