"""Kernel build + VM image creation helpers for the CI loop.

Role parity with reference /root/reference/pkg/kernel/kernel.go:27-45
(Build: .config -> olddefconfig -> bzImage; CreateImage: debootstrap-style
image script).  The image step runs a user-supplied script (the reference
embeds one specific debootstrap recipe; ours is injectable because image
recipes are site-specific), with the same contract: script gets
(kernel_dir, image_out, sshkey_out) and must produce both files.
"""

from __future__ import annotations

import os
import shutil
import subprocess
from typing import Optional


class KernelBuildError(RuntimeError):
    pass


def build(kernel_dir: str, config: str, compiler: str = "gcc",
          jobs: Optional[int] = None,
          config_timeout: float = 600.0,
          build_timeout: float = 3 * 3600.0) -> str:
    """Build bzImage from `kernel_dir` with `config`; returns the bzImage
    path.  Only bzImage — no modules, like the reference."""
    shutil.copyfile(config, os.path.join(kernel_dir, ".config"))
    _run(["make", "olddefconfig"], kernel_dir, config_timeout)
    jobs = jobs or os.cpu_count() or 1
    _run(["make", "bzImage", f"-j{jobs}", f"CC={compiler}"],
         kernel_dir, build_timeout)
    bz = os.path.join(kernel_dir, "arch", "x86", "boot", "bzImage")
    if not os.path.exists(bz):
        raise KernelBuildError("build completed but bzImage is missing")
    return bz


def vmlinux_path(kernel_dir: str) -> str:
    return os.path.join(kernel_dir, "vmlinux")


def create_image(script: str, kernel_dir: str, image_out: str,
                 sshkey_out: str, timeout: float = 3600.0) -> None:
    """Run an image-creation script: argv = [script, kernel_dir,
    image_out, sshkey_out]; both outputs must exist afterwards."""
    _run([script, kernel_dir, image_out, sshkey_out],
         os.path.dirname(os.path.abspath(image_out)) or ".", timeout)
    for f, what in ((image_out, "image"), (sshkey_out, "ssh key")):
        if not os.path.exists(f):
            raise KernelBuildError(f"image script produced no {what}: {f}")


def _run(argv, cwd: str, timeout: float) -> None:
    try:
        r = subprocess.run(argv, cwd=cwd, capture_output=True, text=True,
                           timeout=timeout)
    except subprocess.TimeoutExpired as e:
        raise KernelBuildError(f"{argv[0]} timed out after {timeout}s") \
            from e
    if r.returncode != 0:
        raise KernelBuildError(
            f"{' '.join(argv)} failed:\n{r.stdout[-2000:]}\n"
            f"{r.stderr[-4000:]}")
