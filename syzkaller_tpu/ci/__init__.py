"""Continuous fuzzing orchestration (syz-ci equivalent).

Role parity with reference /root/reference/syz-ci/syz-ci.go:10-48 and
manager.go:59-360: keep two builds per artifact — `latest` (last known
GOOD, preserved across restarts so fuzzing continues even when the
current source head is broken) and `current` (the one in use, a copy of a
latest) — identified by tag files; poll sources, rebuild, test, promote
to latest, restart the managed fuzzing process; never crash the CI
process on a bad build.

Build/poll/test steps are injectable commands so the unit is hermetic;
the default build step compiles this repo's C++ executor (the artifact
our managers actually ship into VMs).
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from ..utils import log


@dataclass
class BuildInfo:
    """Tag-file content identifying a build (syz-ci.go:44-48)."""

    tag: str
    time: float = 0.0

    def save(self, dir_: str) -> None:
        with open(os.path.join(dir_, "tag"), "w") as f:
            json.dump({"tag": self.tag, "time": self.time or time.time()},
                      f)

    @classmethod
    def load(cls, dir_: str) -> Optional["BuildInfo"]:
        try:
            d = json.loads(open(os.path.join(dir_, "tag")).read())
            return cls(tag=d["tag"], time=d.get("time", 0.0))
        except (OSError, ValueError, KeyError):
            return None


class Updater:
    """latest/current two-dir build state for one artifact
    (reference Manager.build/checkLatest manager.go:204-273).

    poll()  -> version tag at source head (e.g. git hash)
    build(tag, outdir) -> build artifacts into outdir; raise on failure
    test(dir) -> optional sanity check before promoting to latest
    """

    def __init__(self, root: str,
                 poll: Callable[[], str],
                 build: Callable[[str, str], None],
                 test: Optional[Callable[[str], None]] = None):
        self.root = root
        self.latest = os.path.join(root, "latest")
        self.current = os.path.join(root, "current")
        os.makedirs(self.latest, exist_ok=True)
        self._poll = poll
        self._build = build
        self._test = test
        self.build_failures = 0

    def poll_and_build(self) -> bool:
        """Rebuild `latest` if the source moved.  Returns True if a new
        good build was produced; a broken head leaves latest intact."""
        try:
            tag = self._poll()
        except Exception as e:
            log.logf(0, "ci: poll failed: %s", e)
            return False
        have = BuildInfo.load(self.latest)
        if have is not None and have.tag == tag:
            return False
        tmp = self.latest + ".tmp"
        shutil.rmtree(tmp, ignore_errors=True)
        os.makedirs(tmp)
        try:
            self._build(tag, tmp)
            if self._test is not None:
                self._test(tmp)
        except Exception as e:
            log.logf(0, "ci: build of %s failed: %s", tag, e)
            self.build_failures += 1
            shutil.rmtree(tmp, ignore_errors=True)
            return False
        BuildInfo(tag=tag).save(tmp)
        shutil.rmtree(self.latest, ignore_errors=True)
        os.replace(tmp, self.latest)
        return True

    def use_latest(self) -> Optional[BuildInfo]:
        """Copy latest -> current (the build the fuzzing process uses;
        reference restartManager manager.go:274-305)."""
        info = BuildInfo.load(self.latest)
        if info is None:
            return None
        cur = BuildInfo.load(self.current)
        if cur is not None and cur.tag == info.tag:
            return info
        tmp = self.current + ".tmp"
        shutil.rmtree(tmp, ignore_errors=True)
        shutil.copytree(self.latest, tmp)
        shutil.rmtree(self.current, ignore_errors=True)
        os.replace(tmp, self.current)
        return info


@dataclass
class CIManagerConfig:
    name: str
    # argv for the managed process; {current} expands to the current
    # build dir, {workdir} to the manager's persistent workdir
    argv: List[str] = field(default_factory=list)
    restart_backoff: float = 10.0


class CIManager:
    """One managed fuzzing process: restart-on-exit with backoff, using
    the updater's `current` build (reference Manager.loop
    manager.go:102-193)."""

    def __init__(self, root: str, cfg: CIManagerConfig, updater: Updater):
        self.cfg = cfg
        self.updater = updater
        self.workdir = os.path.join(root, "workdir")
        os.makedirs(self.workdir, exist_ok=True)
        self.proc: Optional[subprocess.Popen] = None
        self.restarts = 0

    def _argv(self) -> List[str]:
        subs = {"current": self.updater.current, "workdir": self.workdir}
        return [a.format(**subs) for a in self.cfg.argv]

    def ensure_running(self) -> bool:
        """(Re)start the process if it is not alive.  Returns True if a
        start happened."""
        if self.proc is not None and self.proc.poll() is None:
            return False
        if self.updater.use_latest() is None:
            return False  # nothing buildable yet: keep waiting
        if self.proc is not None:
            self.restarts += 1
        self.proc = subprocess.Popen(self._argv())
        return True

    def restart(self) -> None:
        self.stop()
        self.ensure_running()

    def stop(self) -> None:
        if self.proc is not None and self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait()


class CI:
    """The orchestrator: poll+build, restart managers on updates
    (reference syz-ci.go main loop)."""

    def __init__(self, updater: Updater, managers: List[CIManager],
                 poll_period: float = 60.0):
        self.updater = updater
        self.managers = managers
        self.poll_period = poll_period
        self._stop = threading.Event()

    def run_once(self) -> Dict[str, int]:
        updated = self.updater.poll_and_build()
        started = 0
        for m in self.managers:
            if updated:
                m.restart()
                started += 1
            else:
                started += m.ensure_running()
        return {"updated": int(updated), "started": started}

    def loop(self) -> None:
        while not self._stop.wait(self.poll_period):
            try:
                self.run_once()
            except Exception as e:  # a CI must never die (syz-ci.go:28-30)
                log.logf(0, "ci: cycle failed: %s", e)

    def stop(self) -> None:
        self._stop.set()
        for m in self.managers:
            m.stop()


def executor_build_steps(repo_root: str):
    """Default artifact: this repo's C++ executor.  poll = source mtime
    fingerprint, build = compile executor.cc into outdir, test = binary
    exists and runs --help-style probe."""
    src = os.path.join(repo_root, "syzkaller_tpu", "executor",
                       "executor.cc")

    def poll() -> str:
        st = os.stat(src)
        return f"{st.st_mtime_ns}-{st.st_size}"

    def build(tag: str, outdir: str) -> None:
        out = os.path.join(outdir, "syz-executor")
        subprocess.run(["g++", "-O2", "-o", out, src, "-lpthread"],
                       check=True, capture_output=True)

    def test(dir_: str) -> None:
        path = os.path.join(dir_, "syz-executor")
        if not (os.path.isfile(path) and os.access(path, os.X_OK)):
            raise RuntimeError("executor binary missing")

    return poll, build, test
