"""IPC layer: drive the native executor over shared memory + pipes.

Capability parity with reference /root/reference/pkg/ipc (MakeEnv/Exec,
ipc_linux.go:46-307; Config/ExecOpts flag sets ipc.go:14-61; Gate
concurrency limiter pkg/ipc/gate.go), redesigned around the description-
agnostic executor protocol (see executor/executor.cc header comment).

`Env.exec(opts, prog)` returns `(output, [CallInfo], failed, hanged)` like
the reference's `Env.Exec`. `MockEnv` fakes deterministic KCOV-style signal
without any subprocess so the full fuzzing loop has a hermetic test path
(SURVEY.md §4 notes the reference lacks one — gap deliberately not copied).
"""

from __future__ import annotations

import mmap
import os
import shutil
import struct
import subprocess
import tempfile
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

from ..prog.encodingexec import serialize_for_exec
from ..prog.prog import Prog
from ..telemetry import get_registry
from ..testing import faults as _faults
from . import protocol as P
from .build import build_executor


def _exec_histogram():
    return get_registry().histogram(
        "ipc_exec_latency_seconds",
        help="wall time of one executor round trip (exec_raw)")


def _env_respawns_counter():
    return get_registry().counter(
        "env_respawns_total",
        help="executor processes respawned after an unexpected death")


def _kill_escalations_counter():
    return get_registry().counter(
        "env_kill_escalations_total",
        help="executor shutdowns escalated to SIGKILL after the "
             "graceful quit timed out")


def _calls_executed_counter():
    return get_registry().counter(
        "calls_executed_total",
        help="syscalls actually executed across all exec paths (prefix "
             "continuation hits skip the memoized calls, so this runs "
             "below calls-per-program * exec_total when prefix "
             "scheduling wins)")


def _prefix_saved_counter():
    return get_registry().counter(
        "prefix_calls_saved_total",
        help="call executions skipped by splicing a memoized prefix "
             "(exec_suffix continuation hits + exec_prefix parent "
             "continuations)")


# prefix-continuation cache entries held per env (exec_prefix results
# keyed by prefix hash); LRU-bounded so a long campaign can't grow the
# memo without bound — an evicted entry only costs one full re-exec.
# Sized to hold several batches' worth of tree nodes: recurring
# arena-seed prefixes then cost ZERO warm-up in steady state (entries
# are a handful of CallInfos each — this is kilobytes, not megabytes)
PREFIX_CACHE_ENTRIES = 1024

_REQ = struct.Struct("<6Q")
_REPLY = struct.Struct("<3Q")


@dataclass
class ExecOpts:
    collect_signal: bool = True
    collect_cover: bool = False
    dedup_cover: bool = True
    threaded: bool = False
    collide: bool = False
    collect_comps: bool = False
    fault_call: int = -1  # call index to fault-inject, -1 = off
    fault_nth: int = 0
    timeout_ms: int = 5000

    def flags(self) -> int:
        f = 0
        if self.collect_signal:
            f |= P.EXEC_COLLECT_SIGNAL
        if self.collect_cover:
            f |= P.EXEC_COLLECT_COVER
        if self.dedup_cover:
            f |= P.EXEC_DEDUP_COVER
        if self.threaded:
            f |= P.EXEC_THREADED
        if self.collide:
            f |= P.EXEC_COLLIDE
        if self.collect_comps:
            f |= P.EXEC_COLLECT_COMPS
        if self.fault_call >= 0:
            f |= P.EXEC_INJECT_FAULT
            f |= (self.fault_call & 0xFFFF) << 32
            f |= (self.fault_nth & 0xFFFF) << 48
        return f


@dataclass
class EnvConfig:
    debug: bool = False
    use_kcov: bool = True          # harmless if absent; executor probes
    synthetic_cover: bool = True   # fallback signal when KCOV unavailable
    premap_arena: bool = True
    sandbox: str = "none"          # none | setuid | namespace

    def flags(self) -> int:
        f = 0
        if self.debug:
            f |= P.ENV_DEBUG
        if self.use_kcov:
            f |= P.ENV_USE_KCOV
        if self.synthetic_cover:
            f |= P.ENV_SYNTHETIC_COVER
        if self.premap_arena:
            f |= P.ENV_PREMAP_ARENA
        if self.sandbox == "setuid":
            f |= P.ENV_SANDBOX_SETUID
        elif self.sandbox == "namespace":
            f |= P.ENV_SANDBOX_NAMESPACE
        return f


@dataclass
class CallInfo:
    """Per-call execution result (reference pkg/ipc ipc_linux.go CallInfo)."""
    index: int
    num: int
    errno: int
    executed: bool
    fault_injected: bool
    signal: List[int] = field(default_factory=list)
    cover: List[int] = field(default_factory=list)
    comps: List[Tuple[int, int]] = field(default_factory=list)


class ExecError(RuntimeError):
    pass


def _copy_info(i: CallInfo) -> CallInfo:
    """Fresh CallInfo with copied lists: memoized prefix infos are
    spliced into many programs' results, and shared mutable lists across
    results would let one consumer's edit corrupt another's."""
    return CallInfo(index=i.index, num=i.num, errno=i.errno,
                    executed=i.executed, fault_injected=i.fault_injected,
                    signal=list(i.signal), cover=list(i.cover),
                    comps=list(i.comps))


class Env:
    """One executor process + its two shared-memory files.

    Lazily (re)spawns the executor like the reference (a crashed executor is
    respawned on the next exec, ipc_linux.go:128-160).
    """

    # The native executor has no fork/snapshot point (protocol.py
    # CMD_EXEC_PREFIX/SUFFIX are reserved for a fork-server executor),
    # so prefix jobs are never scheduled here and exec_suffix falls back
    # to a full execution; the engine still reuses the memoized prefix
    # SIGNAL for triage (the new-signal scan skips call indices the
    # prefix hash already covered).
    supports_continuation = False

    def __init__(self, target, pid: int = 0,
                 config: Optional[EnvConfig] = None,
                 executor_path: Optional[str] = None):
        self.target = target
        self.pid = pid
        self.config = config or EnvConfig()
        self.executor_path = executor_path or str(build_executor())
        self.workdir = tempfile.mkdtemp(prefix=f"syzenv-{pid}-")
        self._in_path = os.path.join(self.workdir, "in.shm")
        self._out_path = os.path.join(self.workdir, "out.shm")
        for path, size in ((self._in_path, P.IN_SHM_SIZE),
                           (self._out_path, P.OUT_SHM_SIZE)):
            with open(path, "wb") as f:
                f.truncate(size)
        # map both files once; the executor maps the same inodes (the
        # reference's 2MB-in/16MB-out shmem design, ipc_linux.go:46-104)
        self._in_f = open(self._in_path, "r+b")
        self._in_mm = mmap.mmap(self._in_f.fileno(), P.IN_SHM_SIZE)
        self._out_f = open(self._out_path, "r+b")
        self._out_mm = mmap.mmap(self._out_f.fileno(), P.OUT_SHM_SIZE)
        self._proc: Optional[subprocess.Popen] = None
        self.restarts = 0
        self._h_exec = _exec_histogram()
        self._c_calls = _calls_executed_counter()

    # ---- process lifecycle ----

    def _spawn(self) -> None:
        self._proc = subprocess.Popen(
            [self.executor_path, self._in_path, self._out_path],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=None if self.config.debug else subprocess.DEVNULL,
            cwd=self.workdir)
        self._handshake()

    def _handshake(self) -> None:
        t = self.target
        words = [len(t.syscalls), t.page_size, t.num_pages, t.data_offset]
        words += [c.nr for c in t.syscalls]
        self._write_in(struct.pack(f"<{len(words)}Q", *words))
        self._request(P.CMD_HANDSHAKE, flags=self.config.flags(),
                      pid=self.pid)

    def _ensure_proc(self) -> None:
        if self._proc is None or self._proc.poll() is not None:
            if self._proc is not None:
                self.restarts += 1
                _env_respawns_counter().inc()
                self._drain_proc()
            self._spawn()

    def interrupt(self) -> None:
        """Watchdog escalation (engine/supervisor.py): kill the executor
        mid-call so a wedged exec unblocks — the worker's pipe read fails
        and exec_raw reports the ordinary crash path; the next exec
        respawns a fresh process."""
        p = self._proc
        if p is not None and p.poll() is None:
            try:
                p.kill()
            except OSError:
                pass

    def _drain_proc(self) -> None:
        if self._proc is None:
            return
        for s in (self._proc.stdin, self._proc.stdout):
            try:
                if s:
                    s.close()
            except OSError:
                pass
        self._proc.wait()
        self._proc = None

    def close(self) -> None:
        if self._proc is not None and self._proc.poll() is None:
            try:
                self._proc.stdin.write(
                    _REQ.pack(P.REQ_MAGIC, P.CMD_QUIT, 0, 0, 0, 0))
                self._proc.stdin.flush()
            except OSError:
                pass
            try:
                self._proc.wait(timeout=2)
            except subprocess.TimeoutExpired:
                # wedged executor: escalate to SIGKILL and reap — without
                # the re-wait a zombie leaks and pins the shm files open
                self._proc.kill()
                _kill_escalations_counter().inc()
                try:
                    self._proc.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    pass  # unkillable (D-state): _drain_proc waits anyway
        self._drain_proc()
        for m in (self._in_mm, self._out_mm, self._in_f, self._out_f):
            try:
                m.close()
            except OSError:
                pass
        shutil.rmtree(self.workdir, ignore_errors=True)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # ---- wire helpers ----

    def _write_in(self, data: bytes) -> None:
        if len(data) > P.IN_SHM_SIZE:
            raise ExecError("program too large for in-shm")
        self._in_mm[: len(data)] = data

    def _request(self, cmd: int, flags: int = 0, pid: int = 0,
                 exec_flags: int = 0, timeout_ms: int = 0) -> Tuple[int, int]:
        p = self._proc
        p.stdin.write(_REQ.pack(P.REQ_MAGIC, cmd, flags, pid, exec_flags,
                                timeout_ms))
        p.stdin.flush()
        raw = p.stdout.read(_REPLY.size)
        if len(raw) != _REPLY.size:
            raise ExecError("executor died mid-request")
        magic, status, ns = _REPLY.unpack(raw)
        if magic != P.REPLY_MAGIC:
            raise ExecError(f"bad reply magic {magic:#x}")
        return status, ns

    # ---- the hot path ----

    def exec(self, opts: ExecOpts, p: Prog
             ) -> Tuple[bytes, List[CallInfo], bool, bool]:
        """Returns (output, call_infos, failed, hanged)."""
        data = serialize_for_exec(p, pid=self.pid)
        return self.exec_raw(opts, data, [c.meta.id for c in p.calls])

    def exec_raw(self, opts: ExecOpts, data: bytes, call_ids: List[int]
                 ) -> Tuple[bytes, List[CallInfo], bool, bool]:
        """Execute a pre-serialized exec stream (the device fast path:
        prog/execgen.py emits these without building Prog trees).
        `call_ids` lists the stream's syscall ids in order — used to pad
        unreached calls, exactly like exec() pads from p.calls."""
        if len(data) > P.IN_SHM_SIZE:
            # deterministic host-side rejection; the executor is healthy,
            # don't tear it down (distinct from the crash path below)
            return b"", [], True, False
        if _faults.should_fire(f"env.exec:{self.pid}"):
            # injected executor death (testing/faults.FaultPlan):
            # indistinguishable from a real crash — proc torn down,
            # call reports failed, next exec respawns
            if self._proc is not None and self._proc.poll() is None:
                try:
                    self._proc.kill()
                except OSError:
                    pass
            self._drain_proc()
            return b"", [], True, False
        failed = hanged = False
        t0 = time.perf_counter()
        try:
            self._ensure_proc()
            self._write_in(data)
            status, _ns = self._request(
                P.CMD_EXEC, exec_flags=opts.flags(),
                timeout_ms=opts.timeout_ms)
        except (ExecError, OSError):
            # executor crashed (possibly mid-pipe-write); report failure,
            # the next exec respawns it
            self._drain_proc()
            return b"", [], True, False
        finally:
            self._h_exec.observe(time.perf_counter() - t0)
        if status == P.STATUS_FAILED:
            failed = True
        elif status == P.STATUS_HANGED:
            hanged = True
        infos = self._parse_out()
        # A program call that forks (clone/clone3) can race a child into
        # the record stream before the executor's post-call pid check
        # reaps it: drop records for out-of-range indexes and keep one
        # record per call, preferring an executed record over a raced
        # non-executed one regardless of arrival order.
        by_index: dict = {}
        for info in infos:
            if info.index >= len(call_ids):
                continue
            prev = by_index.get(info.index)
            if prev is None or (info.executed and not prev.executed):
                by_index[info.index] = info
        infos = list(by_index.values())
        # Pad calls with no record (child died mid-program: seccomp strict,
        # exit(), hang kill) as not-executed, errno=-1 — one info per call,
        # like the reference's ipc (reference pkg/ipc/ipc_linux.go fills
        # len(p.Calls) infos and leaves unexecuted ones marked).
        for idx, num in enumerate(call_ids):
            if idx not in by_index:
                infos.append(CallInfo(
                    index=idx, num=num, errno=-1,
                    executed=False, fault_injected=False,
                    signal=[], cover=[], comps=[]))
        infos.sort(key=lambda i: i.index)
        self._c_calls.inc(sum(1 for i in infos if i.executed))
        return b"", infos, failed, hanged

    # ---- prefix continuation (prefix-memoized batch execution) ----

    def exec_prefix(self, opts: ExecOpts, data: bytes,
                    call_ids: List[int], n_calls: int, prefix_hash: int,
                    parent_hash: Optional[int] = None,
                    parent_calls: int = 0):
        """Unsupported without a fork point: returns None so the drain
        scheduler never pays a wasted round trip warming a cache this
        env cannot hold (see protocol.CMD_EXEC_PREFIX)."""
        return None

    def exec_suffix(self, opts: ExecOpts, data: bytes,
                    call_ids: List[int], n_prefix: int, prefix_hash: int
                    ) -> Tuple[bytes, List[CallInfo], bool, bool, bool]:
        """Continuation fallback: full execution, never a memo hit (the
        trailing bool).  The engine-side triage reuse of the memoized
        prefix signal is what this path still benefits from."""
        out, infos, failed, hanged = self.exec_raw(opts, data, call_ids)
        return out, infos, failed, hanged, False

    def _parse_out(self) -> List[CallInfo]:
        # The out region is executor-written and the child can die mid-write;
        # treat every count as untrusted and stop at the first inconsistency
        # (the header count is only bumped after a full record, so a clean
        # prefix survives).
        mem = self._out_mm
        end = len(mem)
        (ncalls,) = struct.unpack_from("<I", mem, 0)
        pos = 4
        infos: List[CallInfo] = []
        for _ in range(min(ncalls, 1 << 16)):
            if pos + 28 > end:
                break
            index, num, err, cflags, nsig, ncover, ncomps = struct.unpack_from(
                "<7I", mem, pos)
            pos += 28
            if pos + 4 * nsig + 4 * ncover + 16 * ncomps > end:
                break
            sig = list(struct.unpack_from(f"<{nsig}I", mem, pos))
            pos += 4 * nsig
            cov = list(struct.unpack_from(f"<{ncover}I", mem, pos))
            pos += 4 * ncover
            comps = []
            for _c in range(ncomps):
                a, b = struct.unpack_from("<2Q", mem, pos)
                pos += 16
                comps.append((a, b))
            infos.append(CallInfo(
                index=index, num=num, errno=err,
                executed=bool(cflags & P.CALL_EXECUTED),
                fault_injected=bool(cflags & P.CALL_FAULT_INJECTED),
                signal=sig, cover=cov, comps=comps))
        # exec() sorts after padding missing calls; no sort needed here
        return infos


class MockEnv:
    """Hermetic in-process stand-in for Env: deterministic synthetic signal
    keyed on (call id, arg fingerprint) with no subprocess. Used by unit
    tests and the engine's dry-run mode.

    Implements EXACT prefix continuation (exec_prefix/exec_suffix): the
    synthetic per-call signal is a pure function of the call instruction
    itself, so a memoized prefix spliced with a freshly executed suffix
    is bit-identical to the full execution — the property tier-1 pins so
    the scheduler's correctness contract is testable without a
    fork-server executor."""

    supports_continuation = True

    def __init__(self, target, pid: int = 0, signal_space: int = 1 << 20,
                 prefix_cache_entries: int = PREFIX_CACHE_ENTRIES):
        self.target = target
        self.pid = pid
        self.signal_space = signal_space
        self.restarts = 0
        self._h_exec = _exec_histogram()
        self._c_calls = _calls_executed_counter()
        self._c_saved = _prefix_saved_counter()
        # prefix memo: (prefix_hash, opts key) -> tuple of CallInfos for
        # call indices 1..n (the prelude mmap is never cached: its args
        # depend on the FULL program's page budget, so each program's
        # own execution recomputes it).  Bounded LRU.
        self.prefix_cache_entries = max(int(prefix_cache_entries), 1)
        self._prefix_cache: "OrderedDict[tuple, tuple]" = OrderedDict()

    def close(self) -> None:
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        pass

    @staticmethod
    def _mix(x: int) -> int:
        x &= 0xFFFFFFFF
        x ^= x >> 16
        x = (x * 0x85EBCA6B) & 0xFFFFFFFF
        x ^= x >> 13
        x = (x * 0xC2B2AE35) & 0xFFFFFFFF
        x ^= x >> 16
        return x

    def exec(self, opts: ExecOpts, p: Prog
             ) -> Tuple[bytes, List[CallInfo], bool, bool]:
        # Delegate through the wire format so tree-serialized programs and
        # device-emitted raw streams of the same program fingerprint
        # IDENTICALLY — a divergence would make raw-discovered signal
        # unreproducible by triage's tree re-execution and push the same
        # candidates forever.
        from ..prog.encodingexec import serialize_for_exec

        data = serialize_for_exec(p, pid=self.pid)
        return self.exec_raw(opts, data, [c.meta.id for c in p.calls])

    def exec_raw(self, opts: ExecOpts, data: bytes, call_ids: List[int]
                 ) -> Tuple[bytes, List[CallInfo], bool, bool]:
        """Synthesize deterministic signal from the decoded instruction
        stream (the one authority for both exec() and the raw path).
        Pointer-valued consts (>= data_offset) fingerprint as pointers."""
        if _faults.should_fire(f"env.exec:{self.pid}"):
            # injected env death: report failed like a crashed executor
            self.restarts += 1
            return b"", [], True, False

        t0 = time.perf_counter()
        infos = self._synth_range(opts, data)
        self._h_exec.observe(time.perf_counter() - t0)
        self._c_calls.inc(len(infos))
        return b"", infos, False, False

    def _synth_range(self, opts: ExecOpts, data: bytes, lo: int = 0,
                     hi: Optional[int] = None) -> List[CallInfo]:
        """The one signal authority: CallInfos for the stream's call
        instructions with index in [lo, hi) — the full stream when
        unbounded.  Each info is a pure function of its own instruction
        (id + args), which is what makes prefix memoization EXACT here:
        the executed range never changes any produced info."""
        from ..prog.encodingexec import decode_exec

        data_off = getattr(self.target, "data_offset", 512 << 20)
        infos: List[CallInfo] = []
        i = 0
        for ins in decode_exec(data):
            if ins["op"] != "call":
                continue
            if i < lo:
                i += 1
                continue
            if hi is not None and i >= hi:
                break
            cid = ins["id"]
            h = self._mix(cid * 2654435761)
            sig = [h % self.signal_space]
            comps = []
            for a in ins["args"]:
                if a["kind"] == "const":
                    if a["value"] >= data_off:
                        sig.append(self._mix(h ^ 0x9999) % self.signal_space)
                    else:
                        cls = min(int(a["value"]).bit_length(), 16)
                        sig.append(self._mix(h ^ (cls + 1))
                                   % self.signal_space)
                        if opts.collect_comps:
                            # a deterministic "kernel comparison" per const
                            # arg so the hermetic loop can exercise the
                            # full hints join+mutate pipeline
                            v = int(a["value"])
                            comps.append((v, (v ^ 0x2A) & ((1 << 64) - 1)))
                elif a["kind"] == "result":
                    sig.append(self._mix(h ^ 0x5555) % self.signal_space)
            infos.append(CallInfo(
                index=i, num=cid, errno=0, executed=True,
                fault_injected=False,
                signal=sig if opts.collect_signal else [],
                cover=sig if opts.collect_cover else [],
                comps=comps if opts.collect_comps else []))
            i += 1
        return infos

    # ---- prefix continuation (exact: see class docstring) ----

    @staticmethod
    def _memo_key(prefix_hash: int, opts: ExecOpts) -> tuple:
        # collection flags change the info payloads, so a memo taken
        # under different opts must not splice
        return (int(prefix_hash), opts.collect_signal, opts.collect_cover,
                opts.collect_comps)

    def _memo_get(self, key: tuple):
        entry = self._prefix_cache.get(key)
        if entry is not None:
            self._prefix_cache.move_to_end(key)
        return entry

    def _memo_put(self, key: tuple, infos: List[CallInfo]) -> None:
        self._prefix_cache[key] = tuple(_copy_info(x) for x in infos)
        self._prefix_cache.move_to_end(key)
        while len(self._prefix_cache) > self.prefix_cache_entries:
            self._prefix_cache.popitem(last=False)

    def exec_prefix(self, opts: ExecOpts, data: bytes,
                    call_ids: List[int], n_calls: int, prefix_hash: int,
                    parent_hash: Optional[int] = None,
                    parent_calls: int = 0):
        """Execute the carrier stream's first ``n_calls`` calls (call
        indices 1..n — NOT the prelude mmap: its page budget is a
        whole-program property, so every sibling's suffix execution
        must re-run its own prelude regardless, and executing the
        carrier's here would be pure warm-up waste) and memoize the
        per-call results under ``prefix_hash``.  With a memoized
        ``parent_hash`` (this node's tree parent), only the marginal
        ``n_calls - parent_calls`` calls execute — the
        nested-continuation edge of the prefix tree.  Returns
        ``(out, infos, failed, hanged, calls_saved)`` — the trailing
        int is how many call executions memoization skipped in THIS
        job (truthy == some memo was reused), so the engine's wire
        stats can mirror prefix_calls_saved_total exactly."""
        if _faults.should_fire(f"env.exec:{self.pid}"):
            self.restarts += 1
            return b"", [], True, False, 0
        t0 = time.perf_counter()
        # already warm (the memo persists ACROSS batches and arena-seed
        # prefixes recur batch after batch): execute nothing at all —
        # steady-state warm-up cost for a recurring prefix is zero
        own = self._memo_get(self._memo_key(prefix_hash, opts))
        if own is not None and len(own) == n_calls:
            saved = (n_calls - parent_calls if parent_hash is not None
                     else n_calls)
            self._c_saved.inc(saved)
            self._h_exec.observe(time.perf_counter() - t0)
            return (b"", [_copy_info(x) for x in own], False, False,
                    saved)
        parent = None
        if parent_hash is not None and 0 < parent_calls <= n_calls:
            parent = self._memo_get(self._memo_key(parent_hash, opts))
            if parent is not None and len(parent) != parent_calls:
                parent = None  # hash reuse at another depth: not ours
        if parent is not None:
            run = self._synth_range(opts, data, parent_calls + 1,
                                    n_calls + 1)
            infos = [_copy_info(x) for x in parent] + run
            self._c_saved.inc(parent_calls)
            saved = parent_calls
        else:
            infos = self._synth_range(opts, data, 1, n_calls + 1)
            run = infos
            saved = 0
        self._memo_put(self._memo_key(prefix_hash, opts), infos)
        self._h_exec.observe(time.perf_counter() - t0)
        self._c_calls.inc(len(run))
        return b"", infos, False, False, saved

    def exec_suffix(self, opts: ExecOpts, data: bytes,
                    call_ids: List[int], n_prefix: int, prefix_hash: int
                    ) -> Tuple[bytes, List[CallInfo], bool, bool, bool]:
        """Execute only the prelude + suffix of a full program stream,
        splicing the memoized prefix CallInfos — bit-identical to the
        full execution (tier-1 pins this).  On a cold memo (the env
        never ran the prefix job, e.g. after a quarantine re-plan) fall
        back to a full execution and SELF-HEAL the memo from it, so
        the group's remaining siblings hit again."""
        key = self._memo_key(prefix_hash, opts)
        entry = self._memo_get(key)
        if entry is None or len(entry) != n_prefix:
            out, infos, failed, hanged = self.exec_raw(
                opts, data, call_ids)
            if not failed and not hanged and len(infos) > n_prefix:
                self._memo_put(key, infos[1:n_prefix + 1])
            return out, infos, failed, hanged, False
        if _faults.should_fire(f"env.exec:{self.pid}"):
            self.restarts += 1
            return b"", [], True, False, False
        t0 = time.perf_counter()
        prelude = self._synth_range(opts, data, 0, 1)
        suffix = self._synth_range(opts, data, n_prefix + 1, None)
        infos = prelude + [_copy_info(x) for x in entry] + suffix
        self._c_saved.inc(n_prefix)
        self._h_exec.observe(time.perf_counter() - t0)
        self._c_calls.inc(len(prelude) + len(suffix))
        return b"", infos, False, False, True


class Gate:
    """Sliding-window concurrency limiter (reference pkg/ipc/gate.go):
    section i+size may not *start* until section i has *finished* (strictly
    ordered retirement, not just a counting semaphore). The optional hook
    (the reference uses it for kmemleak scans) runs each time a full window
    of `size` sections has retired, while new entries are held out."""

    def __init__(self, size: int, hook=None):
        self.size = size
        self.hook = hook
        self._cv = threading.Condition()
        self._seq = 0          # next ticket to hand out
        self._retired = 0      # every ticket < this has finished
        self._done = set()     # finished tickets awaiting in-order retirement
        self._in_hook = False

    def enter(self) -> int:
        with self._cv:
            while self._seq - self._retired >= self.size or self._in_hook:
                self._cv.wait()
            t = self._seq
            self._seq += 1
            return t

    def leave(self, ticket: int) -> None:
        run_hook = False
        with self._cv:
            self._done.add(ticket)
            while self._retired in self._done:
                self._done.remove(self._retired)
                self._retired += 1
                if self.hook is not None and self._retired % self.size == 0:
                    run_hook = True
            if run_hook:
                # hooks are exclusive: wait out a concurrently running one
                while self._in_hook:
                    self._cv.wait()
                self._in_hook = True
            else:
                self._cv.notify_all()
        if run_hook:
            try:
                self.hook()
            finally:
                with self._cv:
                    self._in_hook = False
                    self._cv.notify_all()

    def section(self):
        gate = self

        class _Section:
            def __enter__(self):
                self.idx = gate.enter()
                return self

            def __exit__(self, *exc):
                gate.leave(self.idx)

        return _Section()
