"""Build the C++ executor binary, cached by source hash.

The reference ships a Makefile target for executor/executor_linux.cc; here
the ipc layer builds on demand so tests and tools are self-contained.
"""

from __future__ import annotations

import hashlib
import os
import subprocess
from pathlib import Path

_SRC = Path(__file__).resolve().parent.parent / "executor" / "executor.cc"
_BUILD_DIR = Path(__file__).resolve().parent.parent / "executor" / "build"


class BuildError(RuntimeError):
    pass


def executor_source() -> Path:
    return _SRC


def build_executor(cxx: str = "g++", force: bool = False) -> Path:
    """Compile executor.cc -> build/syz-executor-<hash8>; returns the path.

    Hash-keyed caching: recompiles only when the source changes.
    SYZ_TPU_EXECUTOR overrides with a prebuilt binary (the vmLoop ships
    one into guests that have no toolchain).
    """
    override = os.environ.get("SYZ_TPU_EXECUTOR")
    if override and os.path.isfile(override) and not force:
        return Path(override)
    src = _SRC.read_bytes()
    h = hashlib.sha256(src).hexdigest()[:8]
    out = _BUILD_DIR / f"syz-executor-{h}"
    if out.exists() and not force:
        return out
    _BUILD_DIR.mkdir(parents=True, exist_ok=True)
    tmp = out.with_suffix(".tmp")
    cmd = [cxx, "-O2", "-std=c++17", "-Wall", "-Wno-unused-result",
           "-pthread", str(_SRC), "-o", str(tmp)]
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        raise BuildError(f"executor build failed:\n{proc.stderr}")
    os.replace(tmp, out)
    # drop stale binaries from previous source revisions
    for old in _BUILD_DIR.glob("syz-executor-*"):
        if old != out and not old.name.endswith(".tmp"):
            try:
                old.unlink()
            except OSError:
                pass
    return out
