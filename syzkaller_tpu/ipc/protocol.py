"""Wire constants shared with the C++ executor (executor/executor.cc).

Keep in sync by hand; tests/test_ipc.py round-trips real executions through
the compiled binary, which catches any skew.
"""

REQ_MAGIC = 0x73797A74707500AA
REPLY_MAGIC = 0x73797A74707500BB

CMD_HANDSHAKE = 1
CMD_EXEC = 2
CMD_QUIT = 3
# Prefix-continuation pair (prefix-memoized batch execution): execute
# only the first N calls of a stream and snapshot at the boundary
# (PREFIX), or resume a snapshotted prefix and execute the remainder
# (SUFFIX).  The current C++ executor has no fork/snapshot point, so
# the native `Env` never sends these — they are reserved for a
# fork-server executor; `MockEnv` implements the exact in-process
# equivalent (memoized per-call signal spliced with a freshly executed
# suffix) so the continuation contract is testable in tier-1.
CMD_EXEC_PREFIX = 4
CMD_EXEC_SUFFIX = 5

# env flags (handshake)
ENV_DEBUG = 1 << 0
ENV_USE_KCOV = 1 << 1
ENV_SANDBOX_SETUID = 1 << 2
ENV_SANDBOX_NAMESPACE = 1 << 3
ENV_SYNTHETIC_COVER = 1 << 4
ENV_PREMAP_ARENA = 1 << 5

# exec flags
EXEC_COLLECT_SIGNAL = 1 << 0
EXEC_COLLECT_COVER = 1 << 1
EXEC_DEDUP_COVER = 1 << 2
EXEC_THREADED = 1 << 3
EXEC_COLLIDE = 1 << 4
EXEC_COLLECT_COMPS = 1 << 5
EXEC_INJECT_FAULT = 1 << 6

STATUS_OK = 0
STATUS_FAILED = 1
STATUS_HANGED = 2

# call record flags
CALL_EXECUTED = 1 << 0
CALL_FAULT_INJECTED = 1 << 1

IN_SHM_SIZE = 2 << 20    # reference pkg/ipc/ipc.go:36 — 2MB in
OUT_SHM_SIZE = 16 << 20  # 16MB out
