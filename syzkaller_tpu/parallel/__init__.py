from ..utils.jaxcfg import ensure_x64

ensure_x64()
