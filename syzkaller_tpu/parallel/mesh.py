"""The fuzzing mesh: how one device-step is sharded across chips.

Parallelism axes (the TPU translation of the reference's axes, SURVEY §2.6):

  - ``fuzz``  — data parallelism over candidate programs.  Each chip
    mutates/generates its own shard of the candidate batch (the analogue
    of the reference's `procs` × VM-fleet parallelism,
    /root/reference/syz-fuzzer/fuzzer.go:248-328).
  - ``cover`` — the global signal bitset is *sharded by word range* across
    this axis (the analogue of sharding the manager's maxSignal map).
    Folding executed signals into the set and testing candidates for new
    signal are collectives: signals all_gather over ``fuzz`` to reach the
    owning shard, per-shard hit masks psum over ``cover``.

Within a slice these collectives ride ICI; the same program laid over a
multi-pod mesh rides DCN for the leading axis — no code change, only the
Mesh construction differs (hub-sync analogue).
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional, Tuple
import threading
import weakref

from . import ensure_x64  # noqa: F401

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import collective as pcol
from ..ops.dtables import DeviceTables
from ..ops import admission as dadm
from ..ops import cover as dcov
from ..ops import mutation as dmut
from ..ops import rng as drng
from ..telemetry import get_registry, get_tracer

# Device-health gauge: live jitted steps whose executable caches the
# ``device_jit_cache_entries`` gauge sums (weakrefs — a gauge must not
# pin dead pipelines' compiled programs in memory).  The gauge callback
# runs from any scraping thread (sampler tick, /metrics) concurrently
# with registrations, so the list is lock-guarded.
_jit_steps: List["weakref.ref"] = []
_jit_steps_lock = threading.Lock()


def _jit_cache_entries() -> int:
    total = 0
    with _jit_steps_lock:
        live = []
        for r in _jit_steps:
            f = r()
            if f is None:
                continue
            live.append(r)
            try:
                total += f._cache_size()
            except Exception:
                pass  # older jax without _cache_size: count as 0
        _jit_steps[:] = live
    return total


def _register_jit_step(jitted) -> None:
    with _jit_steps_lock:
        _jit_steps.append(weakref.ref(jitted))
    get_registry().gauge(
        "device_jit_cache_entries",
        help="compiled executables cached across live jitted device steps"
    ).set_fn(_jit_cache_entries)


def _timed_step(step, name: str):
    """Wrap a jitted step so telemetry separates first-call JIT compile
    from steady-state dispatch: the first invocation traces + compiles
    inside the call (blocked to completion so the span is honest), later
    invocations only measure the async dispatch enqueue.  Span names
    ``<name>.compile`` / ``<name>.dispatch`` land in the Chrome trace and
    as ``span_*_seconds`` histograms in the registry."""
    compiled = [False]
    _register_jit_step(step)
    compiles = get_registry().counter(
        "device_jit_compiles_total",
        help="first-call JIT compilations of device steps")

    def run(*args):
        if compiled[0]:
            with get_tracer().span(name + ".dispatch"):
                return step(*args)
        with get_tracer().span(name + ".compile"):
            out = step(*args)
            jax.block_until_ready(out)
        compiled[0] = True
        compiles.inc()
        return out

    return run

AXIS_FUZZ = "fuzz"
AXIS_COVER = "cover"

U32 = jnp.uint32
SENT = jnp.uint32(0xFFFFFFFF)


def shard_map(f, *, mesh, in_specs, out_specs):
    """Version-tolerant shard_map: newer jax exposes ``jax.shard_map``
    (replication checks disabled via ``check_vma``); older releases keep it
    in ``jax.experimental.shard_map`` (``check_rep``).  All mesh-mapped
    bodies in this package go through here."""
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        try:
            return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      check_vma=False)
        except TypeError:
            pass
        try:
            # transitional releases expose jax.shard_map with the older
            # check_rep keyword; the opt-out must not be dropped
            return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      check_rep=False)
        except TypeError:
            return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs)
    from jax.experimental.shard_map import shard_map as _sm

    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=False)


def make_mesh(n_devices: Optional[int] = None, n_cover: Optional[int] = None,
              devices=None) -> Mesh:
    """Build the 2-D (fuzz, cover) mesh over the available devices."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        if len(devices) < n_devices:
            raise ValueError(
                f"mesh wants {n_devices} devices, only {len(devices)} visible")
        devices = devices[:n_devices]
    n = len(devices)
    if n_cover is None:
        n_cover = 2 if n % 2 == 0 and n > 1 else 1
    assert n % n_cover == 0, (n, n_cover)
    arr = np.asarray(devices).reshape(n // n_cover, n_cover)
    return Mesh(arr, (AXIS_FUZZ, AXIS_COVER))


# ---------------------------------------------------------------------- #
# program fingerprints (proxy signal)


def call_fingerprints(cid, sval) -> jnp.ndarray:
    """Per-call u32 fingerprint of a program [C] — a splitmix-style hash of
    the call id, its slot values, and the running prefix hash (the same
    shape as the executor's edge signal ``pc ^ hash(prev)``,
    /root/reference/executor/executor.h:388-401).  Used for candidate
    dedup before execution and as the proxy signal in hermetic/dry runs;
    real coverage signal comes from the executor."""
    U64 = jnp.uint64

    def mix(h):
        h = (h ^ (h >> 30)) * U64(0xBF58476D1CE4E5B9)
        h = (h ^ (h >> 27)) * U64(0x94D049BB133111EB)
        return h ^ (h >> 31)

    hv = mix(jnp.asarray(sval, U64).sum(axis=-1) ^
             (jnp.asarray(cid, U64) + U64(0x9E3779B97F4A7C15)))

    def step(prev, h):
        out = mix(h ^ prev)
        return out, out
    _, sig = jax.lax.scan(step, U64(0), hv)
    live = jnp.asarray(cid) >= 0
    return jnp.where(live, (sig & U64(0xFFFFFFFF)).astype(U32), SENT)


# ---------------------------------------------------------------------- #
# sharded signal bitset ops (word-range sharded over AXIS_COVER)


def _shard_index(sig_shard, sigs, shard_idx, n_shards):
    """The single canonical bitset mapping (ops/cover.py:_index — low bits
    of the mixed signal, power-of-two table) applied to this device's word
    range [shard_idx*W, (shard_idx+1)*W). Returns (mine, local_word, bit)."""
    w = sig_shard.shape[0]
    nbits_total = w * n_shards * 32
    assert nbits_total & (nbits_total - 1) == 0, \
        f"sharded bitset must be power-of-two total bits, got {nbits_total}"
    h = jnp.asarray(sigs, U32)
    masked = h & U32(nbits_total - 1)
    word = masked >> 5
    lo = jnp.uint32(shard_idx * w)
    mine = (word >= lo) & (word < lo + jnp.uint32(w)) & (h != SENT)
    return mine, jnp.where(mine, word - lo, 0), (masked & U32(31))


def fold_signals(sig_shard, sigs, gate=None):
    """Inside shard_map: union executed signals (sharded over ``fuzz``,
    [b, K] u32 padded SENT) into the word-sharded global bitset; return
    (new sig_shard, fresh[b] bool = program produced signal not seen
    before anywhere).  Distributed SignalNew + SignalAdd
    (/root/reference/pkg/cover/cover.go:160-182).

    ``gate`` ([b] bool, optional) restricts the FOLD to gated rows while
    the freshness TEST still covers every row: the arena step passes its
    admission verdict, so a candidate rejected by a Bloom false positive
    does not permanently mark its fingerprints as seen — after the
    filter decays, an identical mutant re-tests fresh and executes."""
    j = jax.lax.axis_index(AXIS_COVER)
    n_shards = jax.lax.psum(1, AXIS_COVER)
    # --- test: per-shard hits, then combine over the cover axis (the
    # word-level test/scatter core is ops/cover's — the same one the
    # fused merge_and_new path uses, so the bitset semantics can never
    # fork between the sharded step and the host/XLA/pallas merges) ---
    mine, lw, bit = _shard_index(sig_shard, sigs, j, n_shards)
    hit = dcov.bitset_test_words(sig_shard, lw, bit)
    fresh_local = jnp.any(mine & ~hit, axis=-1)
    fresh = jax.lax.psum(fresh_local.astype(jnp.int32), AXIS_COVER) > 0
    # --- fold: gather every fuzz-shard's signals, scatter my range ---
    if gate is not None:
        sigs = jnp.where(gate[..., None], jnp.asarray(sigs, U32), SENT)
    allsigs = jax.lax.all_gather(sigs, AXIS_FUZZ).reshape(-1)
    mine_all, lw_all, bit_all = _shard_index(sig_shard, allsigs, j, n_shards)
    sig_shard = dcov.bitset_or_words(sig_shard, lw_all, bit_all, mine_all)
    return sig_shard, fresh


def fold_admission(bloom_shard, probes):
    """Inside shard_map: Bloom-filter membership + update over the
    word-range-sharded recent-hash bitset (the admission analogue of
    ``fold_signals``).  ``probes`` is [b, K] u32 — the K probe signals of
    each row's 64-bit hash (ops/admission.bloom_probes).  Returns
    (new bloom_shard, seen[b] bool = ALL K probes were already set
    somewhere across the cover shards).  Every row's probes are then
    folded in — a rejected duplicate must stay remembered."""
    j = jax.lax.axis_index(AXIS_COVER)
    n_shards = jax.lax.psum(1, AXIS_COVER)
    # --- test: any probe I own that is NOT set refutes membership ---
    mine, lw, bit = _shard_index(bloom_shard, probes, j, n_shards)
    hit = dcov.bitset_test_words(bloom_shard, lw, bit)
    missing_local = jnp.any(mine & ~hit, axis=-1)
    seen = jax.lax.psum(missing_local.astype(jnp.int32), AXIS_COVER) == 0
    # --- fold: gather every fuzz-shard's probes, scatter my range ---
    allp = jax.lax.all_gather(probes, AXIS_FUZZ).reshape(-1)
    mine_all, lw_all, bit_all = _shard_index(bloom_shard, allp, j, n_shards)
    bloom_shard = dcov.bitset_or_words(bloom_shard, lw_all, bit_all,
                                       mine_all)
    return bloom_shard, seen


# ---------------------------------------------------------------------- #
# global-view (explicit-sharding) bitset folds
#
# Same semantics as fold_signals / fold_admission, written over the FULL
# bitset instead of a per-device shard: the jitted step carries explicit
# NamedShardings (in_shardings/out_shardings), so the SPMD partitioner
# inserts the gather/scatter collectives that the shard_map bodies spell
# out by hand.  Bit-identity between the two implementations is pinned by
# the parity suite in tests/test_parallel.py.


def _global_index(nwords: int, sigs):
    """The canonical bitset mapping (ops/cover._index) over the full
    [nwords] table, plus the validity mask the shard path derives from
    per-shard ownership: across all shards, a lane is "owned somewhere"
    iff its signal is not SENT.  Power-of-two total bits required, like
    ``_shard_index``."""
    nbits = nwords * 32
    assert nbits & (nbits - 1) == 0, \
        f"bitset must be power-of-two total bits, got {nbits}"
    h = jnp.asarray(sigs, U32)
    masked = h & U32(nbits - 1)
    return masked >> 5, masked & U32(31), (h != SENT)


def fold_signals_global(sig, sigs, gate=None):
    """Global-view fold_signals: union executed signals ([b, K] u32
    padded SENT) into the full bitset; return (new sig, fresh[b]).
    ``gate`` restricts the FOLD while the freshness TEST still covers
    every row (see fold_signals)."""
    word, bit, valid = _global_index(sig.shape[0], sigs)
    hit = dcov.bitset_test_words(sig, word, bit)
    fresh = jnp.any(valid & ~hit, axis=-1)
    if gate is not None:
        sigs = jnp.where(gate[..., None], jnp.asarray(sigs, U32), SENT)
    word, bit, valid = _global_index(sig.shape[0],
                                     jnp.asarray(sigs, U32).reshape(-1))
    sig = dcov.bitset_or_words(sig, word, bit, valid)
    return sig, fresh


def fold_admission_global(bloom, probes):
    """Global-view fold_admission: Bloom membership + update over the
    full recent-hash bitset.  Returns (new bloom, seen[b] = ALL K probe
    bits already set).  Every row's probes are folded in — a rejected
    duplicate must stay remembered."""
    word, bit, valid = _global_index(bloom.shape[0], probes)
    hit = dcov.bitset_test_words(bloom, word, bit)
    seen = ~jnp.any(valid & ~hit, axis=-1)
    word, bit, valid = _global_index(bloom.shape[0], probes.reshape(-1))
    bloom = dcov.bitset_or_words(bloom, word, bit, valid)
    return bloom, seen


# ---------------------------------------------------------------------- #
# the sharded fuzz step


def _step_body(dt: DeviceTables, rounds: int, key, cid, sval, data,
               sig_shard):
    """Per-device body under shard_map: mutate my candidate shard, proxy-
    fingerprint it, fold+test against the sharded global signal set.

    The key is folded with the FUZZ index only: the batch outputs are
    declared replicated over ``cover`` (out_specs P(fuzz)), so every
    cover replica of a fuzz shard MUST compute the identical batch —
    folding the cover index in would make each replica mutate different
    programs while the word-sharded signal fold records each replica's
    own phantoms (check_rep=False would silence the divergence, and
    replica 0's data would silently win in the returned arrays)."""
    i = jax.lax.axis_index(AXIS_FUZZ)
    key = jax.random.fold_in(key, i)
    cid, sval, data, op_mask = dmut.mutate_rows_stratified_traced(
        key, dt, cid, sval, data, rounds)
    sigs = jax.vmap(call_fingerprints)(cid, sval)      # [b, C] u32
    sig_shard, fresh = fold_signals(sig_shard, sigs)
    return cid, sval, data, sig_shard, fresh, op_mask


def _step_body_explicit(dt: DeviceTables, rounds: int, n_fuzz: int, key,
                        cid, sval, data, sig):
    """Global-view body of the fuzz step for the explicit-sharding
    (pjit) compile path: the SAME per-shard computation as
    ``_step_body``, written over the full batch/bitset — per-shard PRNG
    streams come from ``collective.per_shard_keys`` (bit-identical to
    ``fold_in(key, axis_index)``) and each shard's rows are mutated
    under a vmap over the shard axis, so the lane-level arithmetic is
    identical to the shard_map implementation (parity-pinned)."""
    B = cid.shape[0]
    assert B % n_fuzz == 0, (B, n_fuzz)
    b = B // n_fuzz
    keys = pcol.per_shard_keys(key, n_fuzz)

    def mut(k, c, s, d):
        return dmut.mutate_rows_stratified_traced(k, dt, c, s, d, rounds)

    cid, sval, data, op_mask = (
        x.reshape((B,) + x.shape[2:]) for x in jax.vmap(mut)(
            keys,
            cid.reshape((n_fuzz, b) + cid.shape[1:]),
            sval.reshape((n_fuzz, b) + sval.shape[1:]),
            data.reshape((n_fuzz, b) + data.shape[1:])))
    sigs = jax.vmap(call_fingerprints)(cid, sval)      # [B, C] u32
    sig, fresh = fold_signals_global(sig, sigs)
    return cid, sval, data, sig, fresh, op_mask


def make_fuzz_step(mesh: Mesh, dt: DeviceTables, *, rounds: int = 2,
                   donate: bool = True, impl: str = "explicit"):
    """Compile the full sharded fuzz step over `mesh`.

    Returns (step, sharding) where
      step(key, cid, sval, data, sig_shard)
        -> (cid, sval, data, sig_shard, fresh, op_mask)
    cid/sval/data are batch-sharded over ``fuzz`` (batch must divide the
    fuzz axis), sig_shard is the full bitset sharded over ``cover`` (word
    count must divide the cover axis), key is replicated.  ``op_mask``
    [B] u32 carries per-lane mutation-operator provenance (bit i set iff
    operator i touched the lane) for the attribution ledger.

    ``impl`` selects the compile path:
      - ``"explicit"`` (default, the production path): a global-view
        body jitted with explicit NamedSharding in_shardings /
        out_shardings and per-argument donation — the SPMD partitioner
        inserts the collectives, the shardings survive a >1-host mesh,
        and dispatch is fully async (the depth-k pipeline rides this).
      - ``"shard_map"``: the per-device body under the version-tolerant
        shard_map wrapper (kept as the parity reference — both paths
        are pinned bit-identical in tests/test_parallel.py).

    With ``donate`` (the default) the batch tensors and the signal bitset
    are donated, so the pipelined engine loop updates its shards in
    place instead of allocating fresh [B, ...] + bitset buffers every
    round — the inputs are INVALID after the call; pass ``donate=False``
    when the caller must reuse them (parity tests)."""
    pspec_batch = P(AXIS_FUZZ)
    pspec_sig = P(AXIS_COVER)
    batch_s = NamedSharding(mesh, pspec_batch)
    sig_s = NamedSharding(mesh, pspec_sig)
    repl_s = NamedSharding(mesh, P())

    if impl == "explicit":
        n_fuzz = mesh.devices.shape[0]
        body = partial(_step_body_explicit, dt, rounds, n_fuzz)
        jitted = jax.jit(
            body,
            in_shardings=(repl_s, batch_s, batch_s, batch_s, sig_s),
            out_shardings=(batch_s, batch_s, batch_s, sig_s, batch_s,
                           batch_s),
            donate_argnums=(1, 2, 3, 4) if donate else ())
    elif impl == "shard_map":
        body = partial(_step_body, dt, rounds)
        mapped = shard_map(
            body, mesh=mesh,
            in_specs=(P(), pspec_batch, pspec_batch, pspec_batch,
                      pspec_sig),
            out_specs=(pspec_batch, pspec_batch, pspec_batch, pspec_sig,
                       pspec_batch, pspec_batch))
        jitted = jax.jit(mapped,
                         donate_argnums=(1, 2, 3, 4) if donate else ())
    else:
        raise ValueError(f"unknown fuzz-step impl {impl!r}")
    step = _timed_step(jitted, "device.fuzz_step")
    shardings = {
        "batch": batch_s,
        "signal": sig_s,
        "replicated": repl_s,
    }
    return step, shardings


def _arena_step_body(dt: DeviceTables, rounds: int, b_local: int,
                     k_probes: int, key, a_cid, a_sval, a_data, weights,
                     sig_shard, bloom_shard):
    """Per-device body for the arena-resident launch path: draw my
    candidate rows from the yield-weighted cumulative table ON DEVICE
    (ops/rng.choose_weighted_from over the replicated weight vector),
    gather them out of the resident arena with ``jnp.take``, mutate /
    fingerprint / fold like ``_step_body``, then ADMISSION-gate the
    mutants (ops/admission): in-batch dedup over the gathered hash
    vector plus the sharded recent-hash Bloom filter.  The host ships
    nothing per-row per launch — only the replicated PRNG key.

    FUZZ-index fold only (see ``_step_body``): the drawn rows, mutants,
    and admit verdicts are replicated over ``cover`` by construction, so
    each cover shard's signal/Bloom word range is folded with the SAME
    batch the host actually receives."""
    i = jax.lax.axis_index(AXIS_FUZZ)
    key = jax.random.fold_in(key, i)
    kidx, kmut = jax.random.split(key)
    # yield-weighted sampling: cumsum + binary search per lane.  The
    # cumsum runs on device — no host-side weight normalization (the
    # launch-path guard test pins that).
    cw = jnp.cumsum(weights.astype(jnp.uint64))
    words = jax.random.bits(kidx, (b_local,), dtype=jnp.uint64)
    idx = jnp.minimum(drng.choose_weighted_from(words, cw),
                      weights.shape[0] - 1)
    cid = jnp.take(a_cid, idx, axis=0)
    sval = jnp.take(a_sval, idx, axis=0)
    data = jnp.take(a_data, idx, axis=0)
    cid, sval, data, op_mask = dmut.mutate_rows_stratified_traced(
        kmut, dt, cid, sval, data, rounds)
    # --- admission FIRST: hash, in-batch dedup, Bloom test+fold ---
    h = jax.vmap(dadm.row_hash)(cid, sval, data)       # [b] u64
    allh = jax.lax.all_gather(h, AXIS_FUZZ).reshape(-1)
    first = jax.lax.dynamic_slice_in_dim(
        dadm.inbatch_first_mask(allh), i * b_local, b_local)
    bloom_shard, seen = fold_admission(
        bloom_shard, dadm.bloom_probes(h, k_probes))
    admit = first & ~seen
    pop = jax.lax.psum(
        jnp.sum(jax.lax.population_count(bloom_shard)), AXIS_COVER)
    # freshness is TESTED for every row (the stale/dedup accounting
    # needs both verdicts), but only admitted rows' fingerprints are
    # FOLDED into the persistent proxy set — see fold_signals(gate=...)
    sigs = jax.vmap(call_fingerprints)(cid, sval)      # [b, C] u32
    sig_shard, fresh = fold_signals(sig_shard, sigs, gate=admit)
    return (idx, cid, sval, data, sig_shard, bloom_shard, fresh, admit,
            op_mask, pop)


def _arena_step_body_explicit(dt: DeviceTables, rounds: int, n_fuzz: int,
                              b_local: int, k_probes: int, key, a_cid,
                              a_sval, a_data, weights, sig, bloom):
    """Global-view body of the arena launch path for the
    explicit-sharding compile path: the SAME computation as
    ``_arena_step_body`` over the full batch — per-shard key folds via
    ``collective.per_shard_keys``, the weighted draw and mutation
    vmapped over the shard axis so every lane's PRNG stream and
    arithmetic match the shard_map implementation bit-for-bit
    (parity-pinned), and the bitset/Bloom folds over the full tables
    (the partitioner turns them into the gather/scatter collectives the
    shard_map body spells out by hand)."""
    keys = pcol.per_shard_keys(key, n_fuzz)
    ks = jax.vmap(jax.random.split)(keys)
    kidx, kmut = ks[:, 0], ks[:, 1]
    B = n_fuzz * b_local
    # yield-weighted sampling: one global cumsum, per-shard draw words
    cw = jnp.cumsum(weights.astype(jnp.uint64))
    words = jax.vmap(
        lambda k: jax.random.bits(k, (b_local,), dtype=jnp.uint64))(kidx)
    idx = jnp.minimum(
        drng.choose_weighted_from(words.reshape(-1), cw),
        weights.shape[0] - 1)
    cid = jnp.take(a_cid, idx, axis=0)
    sval = jnp.take(a_sval, idx, axis=0)
    data = jnp.take(a_data, idx, axis=0)

    def mut(k, c, s, d):
        return dmut.mutate_rows_stratified_traced(k, dt, c, s, d, rounds)

    cid, sval, data, op_mask = (
        x.reshape((B,) + x.shape[2:]) for x in jax.vmap(mut)(
            kmut,
            cid.reshape((n_fuzz, b_local) + cid.shape[1:]),
            sval.reshape((n_fuzz, b_local) + sval.shape[1:]),
            data.reshape((n_fuzz, b_local) + data.shape[1:])))
    # --- admission FIRST: hash, in-batch dedup, Bloom test+fold ---
    h = jax.vmap(dadm.row_hash)(cid, sval, data)       # [B] u64
    first = dadm.inbatch_first_mask(h)
    bloom, seen = fold_admission_global(
        bloom, dadm.bloom_probes(h, k_probes))
    admit = first & ~seen
    pop = jnp.sum(jax.lax.population_count(bloom))
    sigs = jax.vmap(call_fingerprints)(cid, sval)      # [B, C] u32
    sig, fresh = fold_signals_global(sig, sigs, gate=admit)
    return (idx, cid, sval, data, sig, bloom, fresh, admit, op_mask, pop)


# compiled-step memo: every Fuzzer construction in a process asks for
# the same (mesh, tables, batch) step, and tracing + XLA-compiling the
# global-view body costs seconds each time.  Keyed on dt *identity*
# (build_device_tables memoizes, so equal inputs yield the same object)
# with dt pinned in the value so a recycled id can never alias a dead
# table set.  ``fresh=True`` bypasses AND refreshes the entry — the
# degradation ladder's recompile rung wants a genuinely new executable.
_ARENA_STEP_CACHE: dict = {}


def make_arena_fuzz_step(mesh: Mesh, dt: DeviceTables, *, batch: int,
                         rounds: int = 2,
                         k_probes: int = dadm.BLOOM_PROBES,
                         donate: bool = True, impl: str = "explicit",
                         shard_weights: bool = False,
                         fresh: bool = False):
    """Memoized front door for ``_build_arena_fuzz_step`` (the API
    contract lives on its docstring); ``fresh=True`` forces a rebuild."""
    key = (mesh, id(dt), batch, rounds, k_probes, donate, impl,
           shard_weights)
    if not fresh:
        hit = _ARENA_STEP_CACHE.get(key)
        if hit is not None and hit[0] is dt:
            return hit[1], hit[2]
    step, shardings = _build_arena_fuzz_step(
        mesh, dt, batch=batch, rounds=rounds, k_probes=k_probes,
        donate=donate, impl=impl, shard_weights=shard_weights)
    _ARENA_STEP_CACHE[key] = (dt, step, shardings)
    return step, shardings


def _build_arena_fuzz_step(mesh: Mesh, dt: DeviceTables, *, batch: int,
                           rounds: int = 2,
                           k_probes: int = dadm.BLOOM_PROBES,
                           donate: bool = True, impl: str = "explicit",
                           shard_weights: bool = False):
    """Compile the arena-sampling sharded fuzz step over `mesh`.

    Returns (step, sharding) where
      step(key, arena_cid, arena_sval, arena_data, weights, sig_shard,
           bloom)
        -> (idx, cid, sval, data, sig_shard, bloom, fresh, admit,
            op_mask, bloom_popcount)
    The arena tensors ([cap, ...], ops/arena.CorpusArena) are sampled on
    device inside the jitted step — the only per-launch host->device
    transfer is the replicated PRNG key.  ``idx`` [B] i32 reports which
    arena row each candidate was drawn from (provenance -> yield
    credit); ``admit`` [B] bool is the device-side admission verdict
    (in-batch-unique AND not recently seen); ``bloom_popcount`` is the
    set-bit count of the updated filter (drives the decay/reset policy
    without an extra device round-trip).  ``batch`` must divide the
    fuzz axis.  The signal bitset and the Bloom filter are donated
    (``donate``) so the steady-state loop reuses the buffers; the arena
    tensors and weights are NOT donated — they persist across launches
    by design.

    ``impl`` selects the compile path (see ``make_fuzz_step``):
    ``"explicit"`` (default) jits a global-view body with explicit
    NamedSharding in_shardings/out_shardings + per-argument donation so
    the 64-Mbit signal bitset, the Bloom filter, and — with
    ``shard_weights`` (requires capacity % fuzz-axis == 0) — the arena
    weight table carry real shardings that survive a >1-host mesh;
    ``"shard_map"`` keeps the per-device body under the
    version-tolerant wrapper as the bit-identical parity reference."""
    pspec_batch = P(AXIS_FUZZ)
    pspec_sig = P(AXIS_COVER)
    n_fuzz = mesh.devices.shape[0]
    assert batch % n_fuzz == 0, (batch, n_fuzz)
    batch_s = NamedSharding(mesh, pspec_batch)
    sig_s = NamedSharding(mesh, pspec_sig)
    repl_s = NamedSharding(mesh, P())
    # the [cap] u32 weight table can shard over ``fuzz`` (the global
    # cumsum is one small collective); the row tensors stay replicated —
    # the weighted gather needs arbitrary rows on every shard
    weights_s = batch_s if (impl == "explicit" and shard_weights) \
        else repl_s

    if impl == "explicit":
        body = partial(_arena_step_body_explicit, dt, rounds, n_fuzz,
                       batch // n_fuzz, k_probes)
        jitted = jax.jit(
            body,
            in_shardings=(repl_s, repl_s, repl_s, repl_s, weights_s,
                          sig_s, sig_s),
            out_shardings=(batch_s, batch_s, batch_s, batch_s, sig_s,
                           sig_s, batch_s, batch_s, batch_s, repl_s),
            donate_argnums=(5, 6) if donate else ())
    elif impl == "shard_map":
        body = partial(_arena_step_body, dt, rounds, batch // n_fuzz,
                       k_probes)
        mapped = shard_map(
            body, mesh=mesh,
            in_specs=(P(), P(), P(), P(), P(), pspec_sig, pspec_sig),
            out_specs=(pspec_batch, pspec_batch, pspec_batch, pspec_batch,
                       pspec_sig, pspec_sig, pspec_batch, pspec_batch,
                       pspec_batch, P()))
        jitted = jax.jit(mapped, donate_argnums=(5, 6) if donate else ())
    else:
        raise ValueError(f"unknown arena-fuzz-step impl {impl!r}")
    step = _timed_step(jitted, "device.fuzz_step")
    shardings = {
        "batch": batch_s,
        "signal": sig_s,
        "bloom": sig_s,
        "replicated": repl_s,
        "arena": repl_s,
        "weights": weights_s,
    }
    return step, shardings


def make_generate_step(mesh: Mesh, dt: DeviceTables, *, C: int):
    """Sharded batched generation: each fuzz-shard generates its own lanes
    (seed corpus bootstrap, reference fuzzer.go:315)."""

    def body(key, dummy):
        # fuzz-index fold only: outputs are replicated over ``cover``
        # (see _step_body), so cover replicas must generate identically
        i = jax.lax.axis_index(AXIS_FUZZ)
        key = jax.random.fold_in(key, i)
        return dmut.generate_rows(key, dt, B=dummy.shape[0], C=C)

    mapped = shard_map(
        body, mesh=mesh, in_specs=(P(), P(AXIS_FUZZ)),
        out_specs=(P(AXIS_FUZZ), P(AXIS_FUZZ), P(AXIS_FUZZ)))
    return _timed_step(jax.jit(mapped), "device.generate_step")
