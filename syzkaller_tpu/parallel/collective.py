"""Collective primitives for the fuzzing mesh.

The reference's "distributed backend" is net/rpc-over-TCP between managers
and fuzzers plus syz-hub delta sync (reference:
/root/reference/pkg/rpctype/rpc.go:20-90, syz-hub/hub.go:85-117).  The
TPU-native equivalent keeps RPC only at the host boundary; *signal-state*
merging between chips rides ICI as XLA collectives:

  - coverage/signal union  = bitwise-OR all-reduce over packed bitsets,
  - "any chip saw new signal" = boolean psum,
  - corpus/candidate exchange = all_gather of program tensors
    (the hub-sync analogue; across pods the same op rides DCN).

Two programming models consume these:

  - shard_map bodies call the named collectives below directly
    (``jax.lax.axis_index`` / ``psum`` / ``all_gather``);
  - the explicit-sharding (global-view) steps in ``parallel/mesh.py``
    express the same unions as plain array ops and let the SPMD
    partitioner insert the collectives — the only per-shard identity
    they still need is the deterministic per-shard PRNG fold, which
    ``per_shard_keys`` provides as the global-view analogue of
    ``fold_in(key, axis_index(...))``.
"""

from __future__ import annotations

from . import ensure_x64  # noqa: F401

import jax
import jax.numpy as jnp


def per_shard_keys(key, n_shards: int):
    """[n_shards, ...] PRNG keys: ``fold_in(key, i)`` for each shard
    index, bit-identical to what a shard_map body computes from
    ``fold_in(key, axis_index(axis))`` on shard i.  This is how the
    global-view (explicit-sharding) steps keep per-shard mutation
    streams identical to the shard_map implementation — the parity
    suite in tests/test_parallel.py pins it."""
    idx = jnp.arange(n_shards, dtype=jnp.uint32)
    return jax.vmap(lambda i: jax.random.fold_in(key, i))(idx)


def or_all_reduce(x, axis_name: str):
    """Bitwise-OR all-reduce along a mesh axis.

    XLA has no named OR collective for packed integer lanes, so this is an
    all_gather + local OR-reduce; on TPU the gather rides ICI and the
    reduce fuses into the consumer.  Used for cross-chip signal-bitset
    union (the pkg/cover SignalAdd merge, distributed)."""
    g = jax.lax.all_gather(x, axis_name)  # [n, ...]
    return jax.lax.reduce(g, jnp.zeros((), x.dtype),
                          jax.lax.bitwise_or, (0,))


def any_all_reduce(x, axis_name: str):
    """Boolean OR all-reduce (elementwise) along a mesh axis."""
    return jax.lax.psum(x.astype(jnp.int32), axis_name) > 0


def gather_programs(row, axis_name: str):
    """All-gather program-tensor shards along a mesh axis and flatten the
    device dimension into the batch dimension (candidate exchange; the
    syz-hub corpus sync analogue)."""
    def g(x):
        y = jax.lax.all_gather(x, axis_name)
        return y.reshape((-1,) + y.shape[2:])
    return jax.tree_util.tree_map(g, row)
